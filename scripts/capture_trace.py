"""Capture one S3 PutObject trace from a real forked server process and
render TRACE_SAMPLE.md (VERDICT r3 task 6 deliverable).

Usage: python scripts/capture_trace.py [size_bytes]
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("GARAGE_TPU_DEVICE", "off")
    size = int(sys.argv[1]) if len(sys.argv) > 1 else 4 << 20

    from s3util import S3Client
    from test_s3_api import Server

    tmp = tempfile.mkdtemp(prefix="gt_trace_")
    trace_path = os.path.join(tmp, "spans.jsonl")
    os.environ["GARAGE_TPU_TRACE"] = trace_path
    srv = Server(tmp)
    try:
        srv.start()
        srv.setup_layout_and_key()
        cli = S3Client("127.0.0.1", srv.s3_port, srv.key_id, srv.secret,
                       "garage")
        status, _, rbody = cli.request("PUT", "/trace-bucket")
        assert status in (200, 409), (status, rbody[:200])
        body = os.urandom(size)
        status, _, rbody = cli.request("PUT", "/trace-bucket/sample-object",
                                       body=body)
        assert status == 200, (status, rbody[:200])
    finally:
        srv.stop()

    spans = [json.loads(line) for line in open(trace_path)]
    # find the PUT object request trace
    roots = [s for s in spans
             if s["name"] == "http.request"
             and s.get("attrs", {}).get("path", "").endswith("sample-object")]
    assert roots, "no http.request span for the object PUT"
    root = roots[-1]
    tid = root["trace"]
    mine = sorted((s for s in spans if s["trace"] == tid),
                  key=lambda s: s["start_us"])

    by_parent: dict = {}
    for s in mine:
        by_parent.setdefault(s["parent"], []).append(s)

    lines = []

    def walk(sp, depth):
        attrs = sp.get("attrs", {})
        akeys = ("size", "endpoint", "node", "table", "offset", "width",
                 "method", "path")
        astr = " ".join(f"{k}={attrs[k]}" for k in akeys if k in attrs)
        lines.append(f"| {'&nbsp;&nbsp;' * depth}{sp['name']} "
                     f"| {sp['dur_us']:,} | {astr} |")
        for c in by_parent.get(sp["span"], []):
            walk(c, depth + 1)

    walk(root, 0)

    agg: dict[str, list[float]] = {}
    for s in mine:
        agg.setdefault(s["name"], []).append(s["dur_us"])

    with open(os.path.join(REPO, "TRACE_SAMPLE.md"), "w") as f:
        f.write(f"""# TRACE_SAMPLE — one S3 PutObject, end to end

Captured by `python scripts/capture_trace.py {size}` from a REAL forked
single-node server (tests/test_s3_api.py harness, sqlite metadata,
64 KiB blocks, replication_factor=1, host data plane), tracing enabled
via `GARAGE_TPU_TRACE`. Object size: {size:,} bytes
({size // 65536} blocks). Spans: garage_tpu/utils/tracing.py; the trace
id crosses the RPC wire (net/conn.py request header), so multi-node
traces correlate the same way.

Total request wall time: **{root['dur_us']:,} us**.

## Span tree (one PUT /trace-bucket/sample-object)

| span | dur_us | attrs |
|---|---:|---|
""")
        f.write("\n".join(lines))
        f.write("""

## Aggregates over this trace

| span name | count | total us | avg us |
|---|---:|---:|---:|
""")
        for name, durs in sorted(agg.items(),
                                 key=lambda kv: -sum(kv[1])):
            f.write(f"| {name} | {len(durs)} | {sum(durs):,.0f} "
                    f"| {sum(durs) / len(durs):,.0f} |\n")
        f.write("""
## Reading it

- `http.request` wraps SigV4 verification + routing + `save_stream`;
  the gap between it and the sum of child spans is framework overhead
  (header parsing, signature HMAC chain, response write).
- `s3.put.chunk_read` is the client-socket read of the next 64 KiB
  block — on loopback this is small; over WAN it dominates and the
  pipeline overlaps it with block writes.
- `s3.put.hash` is the BLAKE3 content address (feeder: native C inline
  or device batch).
- `s3.put.block` covers one block's fan-out: `block.put` ->
  `block.encode` (RS shard + crc, one fused native call) +
  `block.write_shards` -> per-node `rpc.call`s, overlapped up to the
  pipeline's parallelism limit; `table.insert` rows (version +
  block_ref) ride the same gather.
- remote nodes adopt the caller's trace id (`set_remote_context`), so
  in a multi-node cluster their server-side spans join this tree.
""")
    print(f"TRACE_SAMPLE.md written; {len(mine)} spans in trace {tid}")


if __name__ == "__main__":
    main()
