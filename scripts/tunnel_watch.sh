#!/usr/bin/env bash
# Tunnel watcher: probe the axon TPU tunnel every ~8 min; on the first
# probe that answers, run bench.py on a quiet box and save the capture
# as the next free BENCH_r05_tpu_captureN.json. Writes a lockfile while
# benching so interactive work can avoid contending (quiet-box rule).
cd "$(dirname "$0")/.." || exit 1
LOG=.tunnel_watch.log
while true; do
  if timeout 50 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u +%m-%d\ %H:%M) ALIVE" >> "$LOG"
    if pgrep -f "pytest|python bench.py" >/dev/null; then
      echo "$(date -u +%m-%d\ %H:%M) busy box, skipping capture" >> "$LOG"
      sleep 300
      continue
    fi
    touch /tmp/gt_bench.lock
    timeout 1500 python bench.py >/tmp/watch_bench_out.json \
        2>/tmp/watch_bench_err.log
    rc=$?
    rm -f /tmp/gt_bench.lock
    if [ $rc -eq 0 ] && grep -q '"platform": "tpu"' /tmp/watch_bench_out.json; then
      n=6
      while [ -e "BENCH_r05_tpu_capture$n.json" ]; do n=$((n+1)); done
      cp /tmp/watch_bench_out.json "BENCH_r05_tpu_capture$n.json"
      echo "$(date -u +%m-%d\ %H:%M) CAPTURED -> capture$n" >> "$LOG"
      sleep 3600  # one capture per window is enough; rest
    else
      echo "$(date -u +%m-%d\ %H:%M) bench rc=$rc (no tpu line)" >> "$LOG"
      sleep 600
    fi
  else
    echo "$(date -u +%m-%d\ %H:%M) timeout" >> "$LOG"
    sleep 480
  fi
done
