"""Profile rpc_put_block end-to-end on the in-process loopback cluster.

Usage: python scripts/profile_put.py [nblocks] [--cprofile] [--mode=off]

Imports bench.py's _build_cluster so the profile measures exactly what
the bench measures (VERDICT r3 task 1: find the gap between the encode
kernel and the end-to-end system number).
"""
from __future__ import annotations

import asyncio
import cProfile
import os
import pstats
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def run(nblocks: int, do_profile: bool, device_mode: str) -> None:
    import bench
    from garage_tpu.rpc import ReplicationMode
    from garage_tpu.utils.data import blake3sum

    tmp = tempfile.mkdtemp(prefix="gt_prof_",
                           dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    try:
        rm = ReplicationMode.parse(3, erasure="4,2")
        systems, managers, tasks = await bench._build_cluster(
            tmp, 6, rm, device_mode)
        block_len = 1 << 20
        rng = np.random.default_rng(2)
        blocks = [rng.integers(0, 256, block_len, dtype=np.uint8).tobytes()
                  for _ in range(nblocks)]
        hashes = [blake3sum(b) for b in blocks]
        for i in range(2):
            await managers[0].rpc_put_block(hashes[i], blocks[i])

        prof = cProfile.Profile() if do_profile else None
        if prof:
            prof.enable()
        t0c = time.process_time()
        dt = await bench._pump_blocks(managers[0], hashes, blocks, 2)
        dtc = time.process_time() - t0c
        if prof:
            prof.disable()
        gbps = (nblocks - 2) * block_len / dt / 1e9
        print(f"put: {nblocks-2} x 1MiB in {dt:.3f}s (cpu {dtc:.3f}s) "
              f"= {gbps:.3f} GB/s")
        print("feeder:", dict(managers[0].feeder.stats))
        print("perf:", managers[0].feeder.perf_summary())
        if prof:
            st = pstats.Stats(prof)
            st.sort_stats("cumulative").print_stats(35)
            st.sort_stats("tottime").print_stats(35)
        await bench._teardown(systems, managers, tasks)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    from garage_tpu.utils.runtime import tune

    tune()
    n = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() else 128
    mode = "off" if "--mode=off" in sys.argv else "auto"
    asyncio.run(run(n, "--cprofile" in sys.argv, mode))
    os._exit(0)
