#!/usr/bin/env bash
# Soak the randomized consistency/chaos suite across seeds: each seed
# re-runs the multi-writer convergence, partition/heal, layout-storm
# and shard-migration scenarios with fresh interleavings.
# Usage: scripts/soak_consistency.sh [first_seed] [n_seeds]
cd "$(dirname "$0")/.." || exit 1
first=${1:-1}
n=${2:-8}
fails=0
for ((s = first; s < first + n; s++)); do
  if GARAGE_TPU_CONSISTENCY_SEED=$s timeout 600 \
      python -m pytest tests/test_consistency.py -q -x >/tmp/soak_$s.log 2>&1
  then
    echo "seed $s: ok"
  else
    fails=$((fails + 1))
    echo "seed $s: FAIL (log: /tmp/soak_$s.log)"
  fi
done
echo "soak done: $n seeds, $fails failures"
exit $((fails > 0))
