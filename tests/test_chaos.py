"""Chaos harness + self-healing RPC tests.

The fault injector (garage_tpu/chaos/) is the proof apparatus for the
self-healing layer (rpc/rpc_helper.py + net/peering.py): these tests
drive quorum reads/writes and erasure decodes through injected hangs,
errors, disconnects and bit-rot, and assert the recovery machinery —
hedged reads, circuit breakers, adaptive timeouts, degraded decode —
actually engages (every assertion is backed by a chaos_*/rpc_* counter
so silent non-injection cannot pass).
"""

import asyncio
import os
import random
import time

import pytest

from garage_tpu.chaos import FaultSpec, arm, controller, disarm
from garage_tpu.utils.data import blake2sum
from garage_tpu.chaos import injector
from garage_tpu.net.peering import (
    BREAKER_COOLDOWN,
    BREAKER_FAILURES,
    PeerHealthTracker,
)
from garage_tpu.rpc import RequestStrategy, RpcHelper
from garage_tpu.utils.error import QuorumError

from test_block import make_block_cluster, run, stop_all
from test_rpc import apply_flat_layout, make_cluster

A, B = b"\xaa" * 32, b"\xbb" * 32


@pytest.fixture(autouse=True)
def _chaos_clean():
    """Chaos is process-global: every test must leave it disarmed."""
    disarm()
    yield
    disarm()


# ---- injector units ----------------------------------------------------


def test_disarmed_by_default_and_state_reports_it():
    assert injector.ACTIVE is None
    st = controller().state()
    assert st["enabled"] is False and st["faults"] == []


def test_scoping_budget_and_metrics():
    c = arm(seed=7)
    f = c.add(FaultSpec(kind="disk_read_error", node=A.hex()[:6],
                        hash_prefix="ab", count=2))
    # out of scope: wrong node, then wrong hash
    assert c.disk_read(B, bytes.fromhex("ab" * 32), b"x") == b"x"
    assert c.disk_read(A, bytes.fromhex("cd" * 32), b"x") == b"x"
    assert f.fired == 0
    # in scope: fires, twice, then the budget is spent
    for _ in range(2):
        with pytest.raises(OSError):
            c.disk_read(A, bytes.fromhex("ab" * 32), b"x")
    assert f.fired == 2 and f.exhausted()
    assert c.disk_read(A, bytes.fromhex("ab" * 32), b"x") == b"x"
    # all faults exhausted -> the seams auto-disarm back to no-op
    assert injector.ACTIVE is None
    assert c.total_fired == 2


def test_bitrot_flips_exactly_one_bit():
    c = arm(seed=3)
    c.add(FaultSpec(kind="disk_bitrot", count=1))
    raw = bytes(range(256))
    rotted = c.disk_read(A, b"h" * 32, raw)
    assert len(rotted) == len(raw)
    diff = [(x, y) for x, y in zip(raw, rotted) if x != y]
    assert len(diff) == 1
    x, y = diff[0]
    assert bin(x ^ y).count("1") == 1


def test_torn_write_halves_content():
    c = arm(seed=3)
    c.add(FaultSpec(kind="disk_torn_write", count=1))
    out = c.disk_write(A, b"h" * 32, b"0123456789")
    assert out == b"01234"


def test_fixed_seed_is_deterministic():
    def pattern():
        c = arm(seed=1234)
        c.add(FaultSpec(kind="disk_read_error", prob=0.5))
        hits = []
        for i in range(32):
            try:
                c.disk_read(A, b"h" * 32, b"x")
                hits.append(0)
            except OSError:
                hits.append(1)
        disarm()
        return hits

    p1, p2 = pattern(), pattern()
    assert p1 == p2
    assert 0 < sum(p1) < 32  # prob actually probabilistic


def test_unknown_kind_rejected():
    c = arm()
    with pytest.raises(ValueError):
        c.add(FaultSpec(kind="disk_meteor_strike"))


# ---- health tracker / breaker units ------------------------------------


def test_breaker_opens_after_failures_and_recovers_via_half_open():
    ht = PeerHealthTracker()
    for _ in range(BREAKER_FAILURES - 1):
        ht.record_failure(A)
    assert ht.breaker_state(A) == "closed"
    ht.record_failure(A)
    assert ht.breaker_state(A) == "open"
    assert ht.breaker_opens == 1
    # open peers rank behind everything
    assert ht.breaker_rank(A) == 3 and ht.breaker_rank(B) == 0
    # cooldown elapses -> half-open with a bounded probe budget
    now = ht.peers[A].opened_at + BREAKER_COOLDOWN + 0.01
    assert ht.breaker_state(A, now) == "half_open"
    assert ht.breaker_rank(A, now) == 1
    ht.note_launch(A)
    ht.note_launch(A)
    assert ht.breaker_rank(A, now) == 2  # probe budget exhausted
    # a probe success closes; a half-open failure would have re-opened
    ht.record_success(A, 0.01)
    assert ht.breaker_state(A) == "closed"
    assert ht.breaker_closes == 1


def test_breaker_half_open_failure_reopens():
    ht = PeerHealthTracker()
    for _ in range(BREAKER_FAILURES):
        ht.record_failure(A)
    now = ht.peers[A].opened_at + BREAKER_COOLDOWN + 0.01
    assert ht.breaker_state(A, now) == "half_open"
    ht.record_failure(A)
    assert ht.breaker_state(A) == "open"
    assert ht.breaker_opens == 2


def test_adaptive_timeout_clamps_and_preserves_flat_default():
    ht = PeerHealthTracker()
    # no samples: the flat default stays in force
    assert ht.call_timeout(A, 30.0) == 30.0
    for _ in range(16):
        ht.record_success(A, 0.02)
    t = ht.call_timeout(A, 30.0)
    assert t == 1.0  # clamp floor: p99*4 = 80ms < 1s
    for _ in range(16):
        ht.record_success(A, 2.0)
    assert 4.0 <= ht.call_timeout(A, 30.0) <= 8.0
    # the flat value is a ceiling, adaptation never grows past it
    assert ht.call_timeout(A, 3.0) == 3.0
    ht.adaptive_timeout_enabled = False
    assert ht.call_timeout(A, 30.0) == 30.0


def test_hedge_delay_and_rate_cap():
    ht = PeerHealthTracker()
    assert ht.hedge_delay([A]) == pytest.approx(0.25)  # no samples
    for _ in range(16):
        ht.record_success(A, 0.1)
    assert ht.hedge_delay([A]) == pytest.approx(0.15)  # p95 * 1.5
    # token bucket: burst drains, then refuses
    took = sum(1 for _ in range(50) if ht.try_take_hedge())
    assert took <= 17  # bucket cap (+1 for refill during the loop)
    assert not ht.try_take_hedge()


# ---- cluster: hung peer, hedged quorum read ----------------------------


def test_hung_peer_quorum_read_hedges_past_it(tmp_path):
    """A quorum-2 read with a hung peer in its initial send set must
    complete in ~the hedge delay, NOT the 30 s flat timeout."""

    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_flat_layout(systems)
            for s in systems:
                async def h(frm, payload, stream, s=s):
                    return {"node": s.id}
                s.netapp.endpoint("test/hedge").set_handler(h)
            helper = RpcHelper(systems[0])
            ep = systems[0].netapp.endpoint("test/hedge")
            nodes = [s.id for s in systems]
            # the victim is whoever ranks second (the initial quorum-2
            # send set is [self, victim]) — hang every call to it
            victim = helper.request_order(list(nodes))[1]
            c = arm(seed=5)
            c.add(FaultSpec(kind="rpc_hang", peer=victim.hex()[:8],
                            endpoint="test/hedge"))
            t0 = time.monotonic()
            resp = await helper.try_call_many(
                ep, nodes, {}, RequestStrategy(quorum=2, timeout=30.0))
            dt = time.monotonic() - t0
            assert len(resp) == 2
            # ~hedge delay (0.25 s default), far below the 30 s timeout
            assert dt < 5.0, f"hedge did not engage: {dt:.1f}s"
            assert c.total_fired >= 1, "hang was never injected"
            ht = systems[0].peering.health
            assert ht.hedges_launched >= 1
            assert ht.hedge_wins >= 1
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_hedging_off_waits_for_timeout(tmp_path):
    """Control for the test above: same hung peer, hedge=False — the
    read only completes once the hung call times out."""

    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_flat_layout(systems)
            for s in systems:
                async def h(frm, payload, stream, s=s):
                    return {"node": s.id}
                s.netapp.endpoint("test/hedge2").set_handler(h)
            helper = RpcHelper(systems[0])
            ep = systems[0].netapp.endpoint("test/hedge2")
            nodes = [s.id for s in systems]
            victim = helper.request_order(list(nodes))[1]
            c = arm(seed=5)
            c.add(FaultSpec(kind="rpc_hang", peer=victim.hex()[:8],
                            endpoint="test/hedge2"))
            t0 = time.monotonic()
            resp = await helper.try_call_many(
                ep, nodes, {},
                RequestStrategy(quorum=2, timeout=2.0, hedge=False))
            dt = time.monotonic() - t0
            assert len(resp) == 2
            assert dt >= 1.9, f"hedge fired despite hedge=False: {dt:.2f}s"
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- cluster: breaker end-to-end ---------------------------------------


def test_breaker_opens_under_injected_errors_and_recovers(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_flat_layout(systems)
            for s in systems:
                async def h(frm, payload, stream):
                    return {}
                s.netapp.endpoint("test/brk").set_handler(h)
            helper = RpcHelper(systems[0])
            ep = systems[0].netapp.endpoint("test/brk")
            victim = systems[1].id
            ht = systems[0].peering.health
            # budget is generous: a background ping success between
            # two injected failures resets the consecutive count, so
            # the loop keeps failing calls until the breaker trips
            c = arm(seed=9)
            c.add(FaultSpec(kind="rpc_error", peer=victim.hex()[:8],
                            endpoint="test/brk",
                            count=BREAKER_FAILURES * 4))
            for _ in range(BREAKER_FAILURES * 4):
                with pytest.raises(Exception):
                    await helper.call(ep, victim, {}, timeout=2.0)
                if ht.breaker_state(victim) == "open":
                    break
            assert ht.breaker_state(victim) == "open"
            # broken peers sort behind healthy ones (self still first)
            order = helper.request_order([s.id for s in systems])
            assert order[0] == systems[0].id and order[-1] == victim
            # after the cooldown: half-open, then a successful probe
            # closes it (a background ping may have probed it first —
            # same recovery path, record_ping_ok)
            disarm()  # budget may not be spent; make calls succeed
            ht.peers[victim].opened_at -= BREAKER_COOLDOWN + 1.0
            assert ht.breaker_state(victim) in ("half_open", "closed")
            await helper.call(ep, victim, {}, timeout=2.0)
            assert ht.breaker_state(victim) == "closed"
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- cluster: error naming ---------------------------------------------


def test_errors_name_peer_and_endpoint(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_flat_layout(systems)
            for s in systems:
                async def h(frm, payload, stream):
                    return {}
                s.netapp.endpoint("test/who").set_handler(h)
            helper = RpcHelper(systems[0])
            ep = systems[0].netapp.endpoint("test/who")
            victim = systems[2].id
            c = arm(seed=1)
            c.add(FaultSpec(kind="rpc_error", peer=victim.hex()[:8],
                            endpoint="test/who"))
            with pytest.raises(Exception) as ei:
                await helper.call(ep, victim, {}, timeout=2.0)
            msg = str(ei.value)
            assert victim.hex()[:8] in msg and "test/who" in msg
            # QuorumError entries carry the same naming
            with pytest.raises(QuorumError) as qe:
                await helper.try_call_many(
                    ep, [s.id for s in systems], {},
                    RequestStrategy(quorum=3, timeout=2.0))
            assert any(victim.hex()[:8] in e and "test/who" in e
                       for e in qe.value.errors)
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- cluster: block data path under chaos ------------------------------


def test_erasure_bitrot_degraded_read_and_scrub_flag(tmp_path):
    """Single-bit rot on a stored shard: the erasure GET must fall
    through to a degraded decode (parity) and still return correct
    bytes, while the rotten holder quarantines the shard and queues a
    resync — all deterministic under the fixed chaos seed."""

    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=3, rf=3, erasure=(2, 1))
        try:
            from garage_tpu.block.codec import shard_nodes_of

            data = b"chaos-bitrot-payload " * 3000
            h = await managers[0].hash_block(data)
            await managers[0].rpc_put_block(h, data, compress=False)
            # read path must hit the store, not node0's write-through
            # cache
            managers[0].cache.configure(max_bytes=0)
            placement = shard_nodes_of(
                systems[0].layout_helper.current(), h, 3)
            # rot a SYSTEMATIC shard's holder so the decode must lean
            # on parity (shard 0 unless node0 holds it — reading
            # through parity either way)
            victim_idx = 0 if placement[0] != systems[0].id else 1
            victim = placement[victim_idx]
            vmgr = managers[[s.id for s in systems].index(victim)]
            before = vmgr.metrics["corruptions"]
            c = arm(seed=42)
            c.add(FaultSpec(kind="disk_bitrot", node=victim.hex()[:8],
                            hash_prefix=h.hex()[:8], count=1))
            got = await managers[0].rpc_get_block(h, cacheable=False)
            assert got == data, "degraded decode returned wrong bytes"
            assert c.total_fired == 1, "bit-rot was never injected"
            # the holder flagged the rotten shard: quarantined + queued
            # for resync (the scrub/repair machinery's entry points)
            assert vmgr.metrics["corruptions"] == before + 1
            assert vmgr.resync.queue_len() >= 1
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_local_disk_eio_degrades_to_remote_read(tmp_path):
    """EIO on the local whole-block read: the replicate GET falls back
    to a remote holder instead of failing the request."""

    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=3, rf=3)
        try:
            data = b"chaos-eio-payload " * 4000
            h = await managers[0].hash_block(data)
            await managers[0].rpc_put_block(h, data, compress=False)
            managers[0].cache.configure(max_bytes=0)
            c = arm(seed=8)
            # every local read of this block on node0 returns EIO
            c.add(FaultSpec(kind="disk_read_error",
                            node=systems[0].id.hex()[:8],
                            hash_prefix=h.hex()[:8]))
            got = await managers[0].rpc_get_block(h, cacheable=False)
            assert got == data
            assert c.total_fired >= 1
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_put_quorum_survives_injected_disconnect(tmp_path):
    """net-level disconnect of one peer mid-write: the replicate PUT
    still reaches its 2/3 write quorum."""

    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=3, rf=3)
        try:
            victim = systems[1].id
            c = arm(seed=11)
            c.add(FaultSpec(kind="net_disconnect", peer=victim.hex()[:8],
                            count=1))
            data = b"chaos-disconnect-payload " * 3000
            h = await managers[0].hash_block(data)
            await managers[0].rpc_put_block(h, data, compress=False)
            assert c.total_fired == 1
            # quorum landed on the two healthy nodes
            stored = sum(1 for m in managers if m.has_local(h))
            assert stored >= 2
            got = await managers[0].rpc_get_block(h, cacheable=False)
            assert got == data
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_admin_chaos_roundtrip_and_metrics(tmp_path):
    """GET/POST /v1/chaos arm/disarm faults at runtime, and /metrics
    always carries the chaos_* and rpc_hedge_*/rpc_breaker_* planes."""

    async def main():
        import json as _json
        import socket
        import urllib.error
        import urllib.request

        from garage_tpu.admin.http import AdminHttpServer

        from test_model import make_garage_cluster
        from test_model import stop_all as stop_garages

        net, garages, tasks = await make_garage_cluster(tmp_path, n=1,
                                                        rf=1)
        g = garages[0]
        g.config.admin_token = "chaos-admin-token"
        srv = AdminHttpServer(g)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        await srv.start("127.0.0.1", port)
        loop = asyncio.get_running_loop()

        def req(method, path, body=None, raw=False):
            r = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", method=method,
                data=_json.dumps(body).encode() if body else None,
                headers={"authorization": "Bearer chaos-admin-token"})
            with urllib.request.urlopen(r, timeout=10) as resp:
                data = resp.read().decode()
                return data if raw else _json.loads(data)

        def in_pool(fn, *a):
            return loop.run_in_executor(None, fn, *a)

        try:
            st = await in_pool(req, "GET", "/v1/chaos")
            assert st["enabled"] is False and st["faults"] == []

            st = await in_pool(req, "POST", "/v1/chaos", {
                "seed": 99,
                "faults": [{"kind": "rpc_error",
                            "endpoint": "test/none", "count": 3}]})
            assert st["enabled"] is True  # arming faults enables
            assert st["seed"] == 99
            assert st["faults"][0]["kind"] == "rpc_error"
            assert st["faults"][0]["fired"] == 0

            # bad kind and bad fields are rejected with 400
            for bad in ({"faults": [{"kind": "meteor"}]},
                        {"faults": [{"kind": "rpc_error",
                                     "blast_radius": 5}]},
                        {"faults": [{"prob": 0.5}]}):
                try:
                    await in_pool(req, "POST", "/v1/chaos", bad)
                    raise AssertionError(f"{bad} was accepted")
                except urllib.error.HTTPError as e:
                    assert e.code == 400

            # /metrics: chaos + self-healing planes always present
            txt = await in_pool(
                lambda: req("GET", "/metrics", None, True))
            assert "chaos_enabled 1" in txt
            assert "chaos_faults_armed 1" in txt
            assert "rpc_hedge_launched_total" in txt
            assert "rpc_breaker_open_total" in txt
            assert "qos_governor_queue_depth" in txt \
                or "qos_governor" not in txt  # governor may be off

            st = await in_pool(req, "POST", "/v1/chaos",
                               {"enabled": False})
            assert st["enabled"] is False
            assert len(st["faults"]) == 1  # disable keeps the specs
            st = await in_pool(req, "POST", "/v1/chaos", {"clear": True})
            assert st["faults"] == []
            txt = await in_pool(
                lambda: req("GET", "/metrics", None, True))
            assert "chaos_enabled 0" in txt
        finally:
            await srv.stop()
            await stop_garages(garages, tasks)

    run(main())


def test_net_delay_slows_but_does_not_break(tmp_path):
    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=3, rf=3)
        try:
            victim = systems[2].id
            c = arm(seed=13)
            c.add(FaultSpec(kind="net_delay", peer=victim.hex()[:8],
                            delay_s=0.05, count=20))
            data = b"chaos-delay-payload " * 2000
            h = await managers[0].hash_block(data)
            await managers[0].rpc_put_block(h, data, compress=False)
            got = await managers[0].rpc_get_block(h, cacheable=False)
            assert got == data
            assert c.total_fired >= 1
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- randomized soak (script/chaos_soak.sh) ----------------------------
#
# One iteration of the nightly soak: a seeded-random fault mix over a
# real 3-node cluster, PUT/GET rounds that may fail while chaos is
# armed (quorum loss is legal) but must NEVER return wrong bytes, and
# a full read-back after disarm. The seed comes from CHAOS_SOAK_SEED
# and is printed on entry, so any failure replays deterministically:
#
#     CHAOS_SOAK_SEED=<seed> pytest tests/test_chaos.py -k soak -s


@pytest.mark.slow
@pytest.mark.skipif("CHAOS_SOAK_SEED" not in os.environ,
                    reason="soak iteration; driven by script/chaos_soak.sh")
def test_randomized_soak(tmp_path):
    seed = int(os.environ["CHAOS_SOAK_SEED"])
    print(f"\nchaos soak seed={seed}")
    rng = random.Random(seed)

    async def main():
        net, systems, managers, tasks = await make_block_cluster(tmp_path)
        try:
            victim = systems[rng.randrange(1, len(systems))].id
            c = arm(seed=seed)
            for _ in range(rng.randint(2, 4)):
                kind = rng.choice(["rpc_error", "disk_read_error",
                                   "disk_bitrot", "net_delay"])
                spec = {"kind": kind,
                        "prob": round(rng.uniform(0.05, 0.4), 3),
                        "count": rng.randint(1, 6)}
                if kind == "rpc_error":
                    spec["peer"] = victim.hex()[:8]
                if kind == "net_delay":
                    spec["peer"] = victim.hex()[:8]
                    spec["delay_s"] = 0.02
                c.add(FaultSpec(**spec))
            stored: list[tuple[bytes, bytes]] = []
            for i in range(12):
                data = bytes([rng.randrange(256)]) * rng.randint(
                    1 << 10, 64 << 10)
                h = blake2sum(data)
                try:
                    await asyncio.wait_for(
                        managers[0].rpc_put_block(h, data), 20.0)
                    stored.append((h, data))
                except Exception:
                    pass  # quorum loss under chaos is legal
                if stored and rng.random() < 0.7:
                    rh, rdata = stored[rng.randrange(len(stored))]
                    m = managers[rng.randrange(len(managers))]
                    try:
                        got = await asyncio.wait_for(
                            m.rpc_get_block(rh, cacheable=False), 20.0)
                    except Exception:
                        continue  # failure is legal; corruption is not
                    assert got == rdata, \
                        f"soak seed={seed}: corrupt read round {i}"
            disarm()
            # steady state: everything that was acknowledged must read
            # back byte-identical from an arbitrary node
            assert stored, f"soak seed={seed}: no PUT survived"
            for rh, rdata in stored:
                m = managers[rng.randrange(len(managers))]
                got = await asyncio.wait_for(
                    m.rpc_get_block(rh, cacheable=False), 30.0)
                assert got == rdata, \
                    f"soak seed={seed}: corrupt read after disarm"
        finally:
            await stop_all(systems, tasks)

    run(main(), timeout=240.0)
