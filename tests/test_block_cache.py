"""Hot-block read cache: SLRU unit behavior (byte budget, scan-resistant
admission, promotion/demotion) and BlockManager integration — a
cache-hit GET must perform ZERO block RPCs and ZERO RS decodes,
write-through on PUT, purge on decref/delete_local, SSE-C exclusion via
the cacheable flag."""

import asyncio
import os

from garage_tpu.block import BlockCache
from test_block import make_block_cluster, stop_all
from garage_tpu.utils.data import blake2sum


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def h(i: int) -> bytes:
    return i.to_bytes(32, "big")


# ---- unit: the SLRU itself ----------------------------------------------


def test_cache_byte_budget_evicts_lru_first():
    c = BlockCache(1000, probation_pct=50)
    for i in range(10):
        c.insert(h(i), bytes(100))  # exactly at budget
    assert c.bytes_used == 1000 and c.entries == 10
    c.insert(h(10), bytes(100))  # one over: oldest probation entry goes
    assert c.bytes_used == 1000
    assert c.get(h(0)) is None  # LRU evicted
    assert c.get(h(10)) is not None
    assert c.evictions == 1


def test_cache_hit_promotes_and_protected_is_capped():
    c = BlockCache(1000, probation_pct=50)  # protected cap 500
    for i in range(6):
        c.insert(h(i), bytes(100))
    for i in range(6):
        assert c.get(h(i)) is not None  # promote all 6 (600 B > cap)
    s = c.stats()
    # demotion keeps the protected segment within its cap; nothing lost
    assert s["protected_bytes"] <= 500
    assert c.entries == 6 and c.bytes_used == 600
    assert s["hits"] == 6 and s["misses"] == 0


def test_cache_scan_resistance_protects_hot_set():
    """A long one-touch scan (every hash seen once) must churn through
    probation without displacing the promoted hot set."""
    c = BlockCache(8000, probation_pct=20)  # protected cap 6400
    hot = {h(i): bytes([i]) * 600 for i in range(4)}
    for k, v in hot.items():
        c.insert(k, v)
    for k in hot:
        assert c.get(k) is not None  # second touch: promoted
    for j in range(100, 200):  # 100 one-touch fills, 50 KiB >> budget
        c.insert(h(j), bytes(500))
    for k, v in hot.items():
        assert c.get(k) == v  # hot set survived the scan
    assert c.bytes_used <= 8000
    assert c.evictions > 0


def test_cache_oversize_entry_rejected():
    c = BlockCache(800)  # max entry = 100
    c.insert(h(1), bytes(200))
    assert c.entries == 0 and c.stats()["rejected"] == 1
    c.insert(h(2), bytes(100))
    assert c.entries == 1


def test_cache_configure_shrink_evicts_and_zero_disables():
    c = BlockCache(1000, probation_pct=50)
    for i in range(8):
        c.insert(h(i), bytes(100))
    c.configure(max_bytes=300)
    assert c.bytes_used <= 300
    c.configure(max_bytes=0)
    assert c.bytes_used == 0
    hits0, misses0 = c.hits, c.misses
    c.insert(h(1), bytes(10))  # disabled: no-ops, no stat movement
    assert c.get(h(1)) is None
    assert c.entries == 0 and (c.hits, c.misses) == (hits0, misses0)


def test_cache_discard_both_segments():
    c = BlockCache(1000, probation_pct=50)
    c.insert(h(1), bytes(50))  # stays probationary
    c.insert(h(2), bytes(50))
    assert c.get(h(2)) is not None  # promoted
    c.discard(h(1))
    c.discard(h(2))
    assert c.entries == 0 and c.bytes_used == 0


def test_cache_memoryview_input_materialized():
    c = BlockCache(1000)
    c.insert(h(1), memoryview(b"x" * 64))
    got = c.get(h(1))
    assert isinstance(got, bytes) and got == b"x" * 64


# ---- integration: the BlockManager seam ---------------------------------


def test_erasure_cache_hit_zero_rpcs_zero_decodes(tmp_path):
    """The acceptance property: a cache-hit read performs no block RPC,
    no shard gather, and no RS decode — instrumented counters on the
    endpoint, the gather, and the codec all stay at zero."""
    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2)
        )
        try:
            data = os.urandom(200_000)
            hash32 = blake2sum(data)
            await managers[0].rpc_put_block(hash32, data)

            m = managers[1]  # a node whose cache the put did NOT fill
            calls = {"rpc": 0, "gather": 0, "decode": 0}
            orig_call = m.endpoint.call
            orig_gather = m._gather_parts
            orig_decode = m.codec.decode

            async def counting_call(*a, **kw):
                calls["rpc"] += 1
                return await orig_call(*a, **kw)

            async def counting_gather(*a, **kw):
                calls["gather"] += 1
                return await orig_gather(*a, **kw)

            def counting_decode(*a, **kw):
                calls["decode"] += 1
                return orig_decode(*a, **kw)

            m.endpoint.call = counting_call
            m._gather_parts = counting_gather
            m.codec.decode = counting_decode

            got = await m.rpc_get_block(hash32)  # miss: the real path
            assert got == data
            assert calls["gather"] == 1 and calls["decode"] >= 1
            assert m.cache.stats()["misses"] >= 1

            calls.update(rpc=0, gather=0, decode=0)
            got = await m.rpc_get_block(hash32)  # hit
            assert got == data
            assert calls == {"rpc": 0, "gather": 0, "decode": 0}
            assert m.cache.stats()["hits"] >= 1
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_put_write_through_serves_reads_without_store(tmp_path):
    async def main():
        net, systems, managers, tasks = await make_block_cluster(tmp_path)
        try:
            data = os.urandom(50_000)
            hash32 = blake2sum(data)
            m = managers[0]
            await m.rpc_put_block(hash32, data)
            # write-through put the decoded payload in probation
            reads0 = m.metrics["bytes_read"]
            calls = {"rpc": 0}
            orig_call = m.endpoint.call

            async def counting_call(*a, **kw):
                calls["rpc"] += 1
                return await orig_call(*a, **kw)

            m.endpoint.call = counting_call
            assert await m.rpc_get_block(hash32) == data
            assert calls["rpc"] == 0  # no RPC…
            assert m.metrics["bytes_read"] == reads0  # …and no disk read
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_cacheable_false_never_populates(tmp_path):
    """The SSE-C contract at the manager seam: neither a put nor a get
    with cacheable=False leaves the payload in RAM."""
    async def main():
        net, systems, managers, tasks = await make_block_cluster(tmp_path)
        try:
            data = os.urandom(40_000)
            hash32 = blake2sum(data)
            m = managers[0]
            await m.rpc_put_block(hash32, data, cacheable=False)
            assert m.cache.entries == 0
            assert await m.rpc_get_block(hash32, cacheable=False) == data
            assert m.cache.entries == 0
            # and a cacheable read of other content still works
            assert await m.rpc_get_block(hash32) == data
            assert m.cache.entries == 1
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_decref_to_zero_purges_cache(tmp_path):
    """A block whose refcount drops to zero must not keep a ghost
    pinned in cache RAM for the whole gc_delay."""
    async def main():
        net, systems, managers, tasks = await make_block_cluster(tmp_path)
        try:
            data = os.urandom(30_000)
            hash32 = blake2sum(data)
            m = managers[0]
            await m.rpc_put_block(hash32, data)
            assert m.cache.entries == 1
            m.db.transaction(lambda tx: m.block_incref(tx, hash32))
            m.db.transaction(lambda tx: m.block_incref(tx, hash32))
            m.db.transaction(lambda tx: m.block_decref(tx, hash32))
            assert m.cache.entries == 1  # still referenced: stays hot
            m.db.transaction(lambda tx: m.block_decref(tx, hash32))
            assert m.cache.entries == 0  # became deletable: purged
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_delete_local_purges_cache(tmp_path):
    async def main():
        net, systems, managers, tasks = await make_block_cluster(tmp_path)
        try:
            data = os.urandom(30_000)
            hash32 = blake2sum(data)
            m = managers[0]
            await m.rpc_put_block(hash32, data)
            assert m.cache.entries == 1
            m.delete_local(hash32)
            assert m.cache.entries == 0
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_qos_read_charge_symmetric_on_hit_and_miss(tmp_path):
    """Foreground reads charge the qos bytes budget identically whether
    served from the cache or the store — an asymmetric charge would
    throttle hot reads below cold ones (or let hot sets ride free).
    PUTs don't charge here (they're priced at admission)."""
    async def main():
        net, systems, managers, tasks = await make_block_cluster(tmp_path)
        try:
            data = os.urandom(20_000)
            hash32 = blake2sum(data)
            m = managers[0]
            charged: list[int] = []

            async def charge(n):
                charged.append(n)

            m.read_qos_charge = charge
            await m.rpc_put_block(hash32, data)
            assert charged == []  # write path never read-charges
            m.cache.clear()
            assert await m.rpc_get_block(hash32) == data  # miss
            assert charged == [len(data)]
            assert await m.rpc_get_block(hash32) == data  # hit
            assert charged == [len(data), len(data)]
        finally:
            await stop_all(systems, tasks)

    run(main())
