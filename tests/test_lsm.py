"""LSM engine specifics (garage_tpu/db/lsm.py): WAL crash-replay,
compaction under concurrent snapshot readers, snapshot iterator
isolation, tombstone collection, orphan-segment GC.

The generic KV/table contract is covered by tests/test_db.py and
tests/test_table.py parametrized over the `db_engine` fixture; this
file only tests what is unique to the log-structured engine.
"""

import os

import pytest

from garage_tpu.db import TxAbort, open_db
from garage_tpu.db.lsm import LsmEngine


def lsm_dir(tmp_path) -> str:
    return str(tmp_path / "meta")


def test_wal_crash_replay_no_committed_write_lost(tmp_path):
    """Simulated kill: the first instance is abandoned WITHOUT close()
    (no flush, no WAL truncation) — every committed write must be
    replayed from the WAL by the next open."""
    d = open_db(lsm_dir(tmp_path), engine="lsm")
    t = d.open_tree("obj")
    for i in range(500):
        t.insert(b"k%03d" % i, b"v%03d" % i)
    t.remove(b"k007")

    def body(tx):
        tx.insert(t, b"txa", b"1")
        tx.insert(t, b"txb", b"2")

    d.transaction(body)

    def aborted(tx):
        tx.insert(t, b"never", b"x")
        raise TxAbort()

    with pytest.raises(TxAbort):
        d.transaction(aborted)
    # crash: no close, no flush — reopen from WAL alone
    d2 = open_db(lsm_dir(tmp_path), engine="lsm")
    t2 = d2.open_tree("obj")
    assert len(t2) == 501  # 500 - 1 removed + 2 tx
    assert t2.get(b"k007") is None
    assert t2.get(b"k008") == b"v008"
    assert t2.get(b"txa") == b"1" and t2.get(b"txb") == b"2"
    assert t2.get(b"never") is None  # rolled back: never hit the WAL
    d2.close()


def test_wal_torn_tail_ignored(tmp_path):
    """A crash mid-append leaves a torn record at the WAL tail; replay
    must keep everything before it and ignore the garbage."""
    d = open_db(lsm_dir(tmp_path), engine="lsm")
    t = d.open_tree("x")
    t.insert(b"a", b"1")
    t.insert(b"b", b"2")
    wal = os.path.join(lsm_dir(tmp_path), "db.lsm", "wal.log")
    with open(wal, "ab") as f:
        f.write(b"\xde\xad\xbe\xef torn half-record")
    d2 = open_db(lsm_dir(tmp_path), engine="lsm")
    t2 = d2.open_tree("x")
    assert t2.get(b"a") == b"1" and t2.get(b"b") == b"2"
    assert len(t2) == 2
    d2.close()


def test_wal_torn_tail_truncated_so_later_commits_survive(tmp_path):
    """Recovery must TRUNCATE the torn tail: commits acknowledged after
    a recovery would otherwise append beyond the garbage and be
    unreachable to the next replay (silent loss on the second crash)."""
    d = open_db(lsm_dir(tmp_path), engine="lsm")
    d.open_tree("x").insert(b"a", b"1")
    wal = os.path.join(lsm_dir(tmp_path), "db.lsm", "wal.log")
    with open(wal, "ab") as f:
        f.write(b"\x00\xff garbage from a crash mid-append")
    d2 = open_db(lsm_dir(tmp_path), engine="lsm")
    t2 = d2.open_tree("x")
    assert t2.get(b"a") == b"1"
    t2.insert(b"b", b"2")  # acknowledged AFTER the recovery
    # crash again (no close): b must be replayed on the third open
    d3 = open_db(lsm_dir(tmp_path), engine="lsm")
    t3 = d3.open_tree("x")
    assert t3.get(b"a") == b"1" and t3.get(b"b") == b"2"
    d3.close()


def test_clear_with_segments_survives_reopen(tmp_path):
    """clear() drops on-disk segments: the manifest must be rewritten
    (before the unlink) or the next open points at deleted files."""
    d = open_db(lsm_dir(tmp_path), engine="lsm")
    t = d.open_tree("x")
    for i in range(50):
        t.insert(b"%03d" % i, b"v")
    d._engine.flush()
    assert d.engine_stats()["segments"] >= 1
    t.clear()
    t.insert(b"after", b"clear")
    # clean close/reopen
    d.close()
    d2 = open_db(lsm_dir(tmp_path), engine="lsm")
    t2 = d2.open_tree("x")
    assert len(t2) == 1 and t2.get(b"after") == b"clear"
    # crash (no close) right after another flushed clear
    d2._engine.flush()
    t2.clear()
    d3 = open_db(lsm_dir(tmp_path), engine="lsm")
    assert len(d3.open_tree("x")) == 0
    d3.close()


def test_flush_resets_wal_and_survives_reopen(tmp_path):
    d = open_db(lsm_dir(tmp_path), engine="lsm")
    t = d.open_tree("x")
    for i in range(100):
        t.insert(b"%04d" % i, b"v" * 32)
    eng = d._engine
    eng.flush()
    wal = os.path.join(lsm_dir(tmp_path), "db.lsm", "wal.log")
    assert os.path.getsize(wal) == 0  # all data now lives in segments
    assert eng.stats()["segments"] >= 1
    d2 = open_db(lsm_dir(tmp_path), engine="lsm")
    t2 = d2.open_tree("x")
    assert len(t2) == 100
    assert [k for k, _ in t2.iter(limit=3)] == [b"0000", b"0001", b"0002"]
    d2.close()


def test_orphan_segment_gc_on_open(tmp_path):
    """A segment file written by a flush that crashed before its
    manifest rename is invisible garbage and must be deleted on open."""
    d = open_db(lsm_dir(tmp_path), engine="lsm")
    t = d.open_tree("x")
    t.insert(b"a", b"1")
    d._engine.flush()
    orphan = os.path.join(lsm_dir(tmp_path), "db.lsm", "seg-9999.sst")
    with open(orphan, "wb") as f:
        f.write(b"junk from a crashed flush")
    d2 = open_db(lsm_dir(tmp_path), engine="lsm")
    assert not os.path.exists(orphan)
    assert d2.open_tree("x").get(b"a") == b"1"
    d2.close()


def _multi_segment_engine(tmp_path, rows=400):
    """An engine with several segments + a live memtable."""
    eng = LsmEngine(str(tmp_path / "e"), memtable_max_bytes=1 << 30)
    eng.ensure_tree("t")
    for lo in range(0, rows, 100):
        eng.begin()
        for i in range(lo, lo + 100):
            eng.put("t", b"%05d" % i, b"v%05d" % i)
        eng.commit()
        eng.flush()  # one segment per batch
    eng.begin()
    eng.put("t", b"zz-mem", b"memtable-row")
    eng.commit()
    return eng


def test_compaction_under_concurrent_snapshot_reader(tmp_path):
    """A snapshot iterator opened before a compaction keeps streaming
    the exact frozen view; victim segment files stay on disk until the
    reader releases them, then disappear."""
    eng = _multi_segment_engine(tmp_path)
    victims = [s.path for ts in eng._trees.values() for s in ts.segments]
    assert len(victims) >= 4
    it = eng.iter_snapshot("t")
    first = [next(it) for _ in range(10)]
    assert first[0] == (b"00000", b"v00000")
    eng.compact_full()  # merges everything under the reader
    assert eng.stats()["segments"] == 1
    # the reader's files are dead but must still be readable on disk
    assert all(os.path.exists(p) for p in victims)
    rest = list(it)
    got = first + rest
    assert len(got) == 401
    assert got[-1] == (b"zz-mem", b"memtable-row")
    assert got == sorted(got)
    # iterator exhausted -> refs released -> victims unlinked
    assert not any(os.path.exists(p) for p in victims)
    eng.close()


def test_snapshot_iterator_isolation(tmp_path):
    """Writes and deletes after iter_snapshot() are invisible to the
    iterator but visible to fresh reads."""
    eng = _multi_segment_engine(tmp_path)
    it = eng.iter_snapshot("t")
    eng.begin()
    eng.put("t", b"00000", b"OVERWRITTEN")
    eng.delete("t", b"00001")
    eng.put("t", b"00000a", b"NEW")
    eng.commit()
    got = dict(it)
    assert got[b"00000"] == b"v00000"  # pre-snapshot value
    assert b"00001" in got             # delete invisible
    assert b"00000a" not in got        # insert invisible
    # live reads see the new state
    assert eng.get("t", b"00000") == b"OVERWRITTEN"
    assert eng.get("t", b"00001") is None
    assert eng.get("t", b"00000a") == b"NEW"
    eng.close()


def test_tombstones_dropped_on_full_compaction(tmp_path):
    eng = LsmEngine(str(tmp_path / "e"))
    eng.ensure_tree("t")
    eng.begin()
    for i in range(100):
        eng.put("t", b"%03d" % i, b"v")
    eng.commit()
    eng.flush()
    eng.begin()
    for i in range(100):
        eng.delete("t", b"%03d" % i)
    eng.commit()
    eng.flush()
    assert eng.length("t") == 0
    eng.compact_full()
    # pure-tombstone trees compact down to nothing at all
    assert eng.stats()["segments"] == 0
    assert eng.range("t", None, None, False) == []
    eng.close()


def test_clear_rolls_back(tmp_path):
    eng = _multi_segment_engine(tmp_path)
    n = eng.length("t")
    eng.begin()
    eng.clear("t")
    assert eng.length("t") == 0
    eng.rollback()
    assert eng.length("t") == n
    assert eng.get("t", b"00000") == b"v00000"
    # the segments survived the rolled-back clear
    assert eng.get("t", b"00399") == b"v00399"
    eng.close()


def test_lsm_server_end_to_end_with_kill9_restart(tmp_path):
    """A real forked server on `[metadata] db_engine = "lsm"`: S3
    PUT/list/GET work, admin /v1/metadata and /metrics report the
    engine, and a SIGKILL + restart loses no committed write (the
    crash-replay acceptance criterion, against a live process)."""
    from s3util import S3Client, xml_find
    from test_s3_api import Server, _admin

    srv = Server(str(tmp_path), db_engine="lsm")
    srv.start()
    try:
        srv.setup_layout_and_key()
        c = S3Client("127.0.0.1", srv.s3_port, srv.key_id, srv.secret)
        st, _, _ = c.request("PUT", "/lsmbkt")
        assert st == 200
        for k in ("a/1", "a/2", "b/1", "top"):
            st, _, _ = c.request("PUT", f"/lsmbkt/{k}", body=b"payload")
            assert st == 200
        st, _, body = c.request(
            "GET", "/lsmbkt",
            query=[("list-type", "2"), ("delimiter", "/")])
        assert st == 200
        assert xml_find(body, "Key") == ["top"]
        st, _, body = c.request("GET", "/lsmbkt/a/1")
        assert st == 200 and body == b"payload"
        st, got = _admin(srv, "GET", "/v1/metadata")
        assert st == 200
        assert got["engine"]["engine"] == "lsm"
        assert "segments" in got["engine"]
        assert got["compaction"] is not None  # maintenance worker live
        # meta_* gauges exported
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", srv.admin_port,
                                          timeout=30)
        conn.request("GET", "/metrics")
        metrics = conn.getresponse().read().decode()
        conn.close()
        assert 'meta_rows{engine="lsm"}' in metrics

        # hard kill: no shutdown hooks, no flush — WAL replay must
        # restore every acknowledged write on restart
        srv.proc.kill()
        srv.proc.wait()
        srv.start()
        st, _, body = c.request("GET", "/lsmbkt/b/1")
        assert st == 200 and body == b"payload"
        st, _, body = c.request("GET", "/lsmbkt",
                                query=[("list-type", "2")])
        assert xml_find(body, "Key") == ["a/1", "a/2", "b/1", "top"]
    finally:
        srv.stop()


def test_engine_stats_shape(tmp_path):
    d = open_db(lsm_dir(tmp_path), engine="lsm")
    t = d.open_tree("x")
    t.insert(b"a", b"1")
    s = d.engine_stats()
    assert s["engine"] == "lsm"
    for k in ("segments", "compaction_backlog", "wal_bytes",
              "memtable_bytes", "rows"):
        assert k in s
    assert s["rows"] == 1
    assert s["wal_bytes"] > 0
    d.close()
