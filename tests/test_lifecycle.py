"""Lifecycle worker + metadata snapshots.

VERDICT round-2 item 6: lifecycle expiration across a simulated day
boundary, abort-incomplete-MPU, and snapshot keep-2 rotation.
"""

import asyncio
import os

from garage_tpu.model import Garage
from garage_tpu.model.s3 import (Object, ObjectVersion, ObjectVersionData,
                                 ObjectVersionMeta, ObjectVersionState)
from garage_tpu.model.s3.lifecycle_worker import LifecycleWorker, next_date
from garage_tpu.model.snapshot import snapshot_metadata, snapshots_dir
from garage_tpu.net import LocalNetwork
from garage_tpu.utils.background import WState
from garage_tpu.utils.config import Config, DataDir
from garage_tpu.utils.crdt import now_msec
from garage_tpu.utils.data import gen_uuid

from test_model import make_garage_cluster, stop_all, wait_until  # noqa: E402


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


DAY_MS = 86400 * 1000


async def _setup(tmp_path, lifecycle_rules):
    net, garages, tasks = await make_garage_cluster(tmp_path, n=1, rf=1)
    g = garages[0]
    from garage_tpu.model.helper import GarageHelper

    helper = GarageHelper(g)
    bucket = await helper.create_bucket("lc-bucket")
    await helper.update_bucket_config(bucket.id, "lifecycle_config",
                                      lifecycle_rules)
    return net, garages, tasks, g, bucket


def _complete_version(ts, size=100):
    meta = ObjectVersionMeta({}, size, "etag")
    return ObjectVersion(gen_uuid(), ts, ObjectVersionState.complete(
        ObjectVersionData.inline(meta, b"x" * size)))


def fresh_worker(g) -> LifecycleWorker:
    """The cluster's background lifecycle worker may already have
    completed today's (empty-table) pass before the test inserts its
    objects — reset the cursor so this worker runs a fresh pass."""
    w = LifecycleWorker(g)
    w._last_completed = None
    return w


async def _drain(worker, max_steps=50):
    for _ in range(max_steps):
        st = await worker.work()
        if st == WState.IDLE:
            return
    raise AssertionError("lifecycle worker did not finish")


def test_expiration_after_days(tmp_path):
    async def main():
        rules = [{"id": "exp", "enabled": True, "filter": {},
                  "abort_incomplete_mpu_days": None, "expiration": 3}]
        net, garages, tasks, g, bucket = await _setup(tmp_path, rules)
        try:
            old = _complete_version(now_msec() - 5 * DAY_MS)
            fresh = _complete_version(now_msec() - 1 * DAY_MS)
            await g.object_table.insert(
                Object(bucket.id, "old-obj", [old]))
            await g.object_table.insert(
                Object(bucket.id, "fresh-obj", [fresh]))
            w = fresh_worker(g)
            await _drain(w)
            gone = await g.object_table.get(bucket.id, b"old-obj")
            assert gone.last_data() is None  # expired -> delete marker
            kept = await g.object_table.get(bucket.id, b"fresh-obj")
            assert kept.last_data() is not None
            # second run same day: no-op (completed)
            assert await w.work() == WState.IDLE
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_expiration_at_date_and_size_filter(tmp_path):
    async def main():
        rules = [{"id": "d", "enabled": True,
                  "filter": {"size_gt": 150},
                  "abort_incomplete_mpu_days": None,
                  "expiration": "2001-01-01"}]
        net, garages, tasks, g, bucket = await _setup(tmp_path, rules)
        try:
            big = _complete_version(now_msec() - 2 * DAY_MS, size=200)
            small = _complete_version(now_msec() - 2 * DAY_MS, size=100)
            await g.object_table.insert(Object(bucket.id, "big", [big]))
            await g.object_table.insert(Object(bucket.id, "small", [small]))
            w = fresh_worker(g)
            await _drain(w)
            assert (await g.object_table.get(bucket.id,
                                             b"big")).last_data() is None
            assert (await g.object_table.get(
                bucket.id, b"small")).last_data() is not None
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_abort_incomplete_mpu(tmp_path):
    async def main():
        rules = [{"id": "mpu", "enabled": True, "filter": {},
                  "abort_incomplete_mpu_days": 2, "expiration": None}]
        net, garages, tasks, g, bucket = await _setup(tmp_path, rules)
        try:
            stale = ObjectVersion(
                gen_uuid(), now_msec() - 4 * DAY_MS,
                ObjectVersionState.uploading({}, multipart=True))
            await g.object_table.insert(
                Object(bucket.id, "stale-up", [stale]))
            w = fresh_worker(g)
            await _drain(w)
            obj = await g.object_table.get(bucket.id, b"stale-up")
            from garage_tpu.model.s3.object_table import ST_ABORTED

            assert all(v.state.kind == ST_ABORTED for v in obj.versions)
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_disabled_rules_skip_bucket(tmp_path):
    async def main():
        rules = [{"id": "off", "enabled": False, "filter": {},
                  "abort_incomplete_mpu_days": None, "expiration": 1}]
        net, garages, tasks, g, bucket = await _setup(tmp_path, rules)
        try:
            old = _complete_version(now_msec() - 9 * DAY_MS)
            await g.object_table.insert(Object(bucket.id, "keepme", [old]))
            w = fresh_worker(g)
            await _drain(w)
            assert (await g.object_table.get(
                bucket.id, b"keepme")).last_data() is not None
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_next_date_semantics():
    import datetime

    ts = int(datetime.datetime(2026, 7, 1, 23, 59,
                               tzinfo=datetime.timezone.utc
                               ).timestamp() * 1000)
    assert next_date(ts) == datetime.date(2026, 7, 2)


def test_snapshot_keep_two(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=1,
                                                        rf=1)
        g = garages[0]
        try:
            paths = []
            for _ in range(3):
                paths.append(await asyncio.to_thread(snapshot_metadata, g))
                # distinct second-resolution stamps; asyncio.sleep, not
                # time.sleep — the sanitizer flags on-loop sleeps
                await asyncio.sleep(1.1)
            base = snapshots_dir(g.config)
            left = sorted(os.listdir(base))
            assert len(left) == 2
            assert os.path.basename(paths[-1]) in left
            assert os.path.basename(paths[0]) not in left
        finally:
            await stop_all(garages, tasks)

    run(main())
