"""QoS admission control: token buckets, load shedding, governor.

Covers the garage_tpu/qos/ subsystem end to end: refill math against an
injected clock, 503 SlowDown + Retry-After under sustained overload (and
NOT under a burst within budget) through a real in-process S3 API
server, the governor throttling scrub when injected foreground latency
rises, and the admin /v1/qos endpoint round-tripping a limit change.
"""

import asyncio
import concurrent.futures
import json
import socket
import urllib.error
import urllib.request

import pytest

from garage_tpu.qos.limiter import (ConcurrencyLimiter, QosEngine,
                                    QosLimits, SlowDown, TokenBucket)
from garage_tpu.qos.governor import GovernorWorker
from garage_tpu.utils.background import Throttled

from s3util import S3Client  # noqa: E402
from test_model import make_garage_cluster, stop_all  # noqa: E402


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# client requests must NOT ride asyncio.to_thread: that shares the
# loop's default executor with the in-process server (whose table scans
# also use to_thread), and on a small box the blocked client threads
# starve the server into a deadlock broken only by client timeouts
_CLIENT_POOL = concurrent.futures.ThreadPoolExecutor(16)


def in_pool(fn, *args):
    return asyncio.get_running_loop().run_in_executor(_CLIENT_POOL, fn,
                                                      *args)


# ---- token bucket math ---------------------------------------------------


def test_token_bucket_refill_math():
    clk = [100.0]
    b = TokenBucket(rate=10.0, burst=20.0, clock=lambda: clk[0])
    # full burst available at start
    assert b.try_acquire(20.0)
    assert not b.try_acquire(0.001)
    # refill is rate * elapsed
    clk[0] += 0.5
    assert b.wait_for(5.0) == pytest.approx(0.0)
    assert b.try_acquire(5.0)
    assert not b.try_acquire(0.5)
    # wait_for quotes deficit / rate
    assert b.wait_for(10.0) == pytest.approx(1.0)
    # refill caps at burst, never beyond
    clk[0] += 1000.0
    assert b.wait_for(20.0) == pytest.approx(0.0)
    assert b.wait_for(20.001) > 0
    assert b.try_acquire(20.0)


def test_token_bucket_reconfigure_keeps_fill_fraction():
    clk = [0.0]
    b = TokenBucket(rate=10.0, burst=10.0, clock=lambda: clk[0])
    assert b.try_acquire(5.0)  # half full
    b.configure(rate=100.0, burst=100.0)
    assert b.tokens == pytest.approx(50.0)


def test_token_bucket_bounded_wait_and_shed():
    async def main():
        b = TokenBucket(rate=1000.0, burst=100.0)
        assert b.try_acquire(100.0)  # drain the burst
        # within the bounded wait: granted after a short sleep
        waited = await b.acquire(50.0, max_wait=0.5)
        assert 0.0 < waited <= 0.5
        # beyond the bounded wait: shed immediately with a usable hint
        with pytest.raises(SlowDown) as ei:
            await b.acquire(5000.0, max_wait=0.5)
        assert ei.value.retry_after > 0.5
        assert int(ei.value.header_value()) >= 1

    run(main())


def test_concurrency_limiter_bounded_queue():
    async def main():
        lim = ConcurrencyLimiter(limit=2, max_queue=1)
        await lim.acquire()
        await lim.acquire()
        assert lim.active == 2
        waiter = asyncio.create_task(lim.acquire())
        await asyncio.sleep(0)  # queued
        assert lim.queued == 1
        with pytest.raises(SlowDown):
            await lim.acquire()  # queue full -> shed
        lim.release(0.01)
        await asyncio.wait_for(waiter, 1.0)
        assert lim.active == 2
        lim.release(0.01)
        lim.release(0.01)
        assert lim.active == 0

    run(main())


def test_concurrency_limiter_raise_limit_wakes_waiters():
    async def main():
        lim = ConcurrencyLimiter(limit=1, max_queue=4)
        await lim.acquire()
        waiters = [asyncio.create_task(lim.acquire()) for _ in range(3)]
        await asyncio.sleep(0)
        assert lim.queued == 3
        lim.configure(limit=4, max_queue=4)  # runtime raise
        await asyncio.wait_for(asyncio.gather(*waiters), 1.0)
        assert lim.active == 4 and lim.queued == 0

    run(main())


def test_shed_refunds_earlier_stage_tokens():
    async def main():
        clk = [0.0]
        eng = QosEngine(QosLimits(global_rps=100.0, global_burst=100.0,
                                  global_bytes_per_s=1000.0,
                                  global_bytes_burst=1000.0,
                                  max_concurrent=1, max_queue=0,
                                  max_wait_s=0.0),
                        clock=lambda: clk[0])
        adm = eng.admit("s3", nbytes=10)
        await adm.__aenter__()  # holds the single concurrency slot
        # next request passes rps+bytes but sheds at concurrency:
        # both earlier debits must be refunded
        with pytest.raises(SlowDown):
            async with eng.admit("s3", nbytes=400):
                pass
        assert eng._req_bucket.tokens == pytest.approx(99.0)
        assert eng._bytes_bucket.tokens == pytest.approx(990.0)
        await adm.__aexit__(None, None, None)

    run(main())


def test_engine_unset_limits_are_free():
    async def main():
        eng = QosEngine(QosLimits())  # nothing configured
        for _ in range(1000):
            async with eng.admit("s3", nbytes=1 << 30):
                pass
        await eng.admit_scoped(key_id="k", bucket="b")
        assert eng.counters.shed == 0

    run(main())


def test_engine_per_key_isolation():
    async def main():
        clk = [0.0]
        eng = QosEngine(QosLimits(per_key_rps=2.0, max_wait_s=0.0),
                        clock=lambda: clk[0])
        # key A exhausts its own bucket ...
        await eng.admit_scoped(key_id="A")
        await eng.admit_scoped(key_id="A")
        with pytest.raises(SlowDown):
            await eng.admit_scoped(key_id="A")
        # ... key B is unaffected
        await eng.admit_scoped(key_id="B")
        assert eng.counters.shed_by_scope.get("key") == 1

    run(main())


def test_shed_visibility_per_key_and_bucket():
    """Operators need to see WHO is being shed (ROADMAP '503 retry
    ergonomics'): scoped sheds are attributed to the key and bucket
    they hit, surfaced top-N-sorted through state() -> GET /v1/qos."""
    async def main():
        clk = [0.0]
        eng = QosEngine(QosLimits(per_key_rps=1.0, max_wait_s=0.0),
                        clock=lambda: clk[0])
        for key, bucket, n in (("hot", "logs", 5), ("warm", "logs", 2),
                               ("cold", "media", 1)):
            await eng.admit_scoped(key_id=key, bucket=bucket)  # burst token
            for _ in range(n):
                with pytest.raises(SlowDown):
                    await eng.admit_scoped(key_id=key, bucket=bucket)
        c = eng.counters.to_dict()
        assert c["top_shed_keys"] == [["hot", 5], ["warm", 2], ["cold", 1]]
        assert c["top_shed_buckets"] == [["logs", 7], ["media", 1]]
        assert eng.state()["counters"]["top_shed_keys"][0] == ["hot", 5]

    run(main())


def test_request_rate_drr_bounded_share_between_keys():
    """ISSUE 15 satellite: the global REQUEST-RATE bucket drains
    through the same per-key deficit round-robin as the bytes bucket.
    Key A floods the admission queue first, key B arrives after; the
    grants must interleave (~1/K each) instead of draining A's backlog
    first — the bounded-share property, now for requests."""
    from garage_tpu.qos.limiter import CURRENT_QOS_KEY

    eng = QosEngine(QosLimits(global_rps=2000.0, global_burst=2000.0,
                              max_wait_s=5.0, fair_keys=True))
    assert eng._fair_req is not None
    order = []

    async def scenario():
        eng._req_bucket.tokens = 0.0  # force contention immediately

        async def one(key):
            tok = CURRENT_QOS_KEY.set(key)
            try:
                async with eng.admit("s3"):
                    order.append(key)
            finally:
                CURRENT_QOS_KEY.reset(tok)

        tasks = [asyncio.ensure_future(one("A")) for _ in range(10)]
        await asyncio.sleep(0)  # A's backlog queues first
        tasks += [asyncio.ensure_future(one("B")) for _ in range(10)]
        await asyncio.gather(*tasks)

    run(scenario())
    assert len(order) == 20
    first_half = order[:10]
    assert 3 <= first_half.count("B") <= 7, order
    assert eng.counters.admitted == 20 and eng.counters.shed == 0


def test_request_rate_drr_keeps_bounded_wait_shed_contract():
    """Fairness must not weaken shedding: an arrival whose estimated
    wait (bucket deficit + the fair queue ahead of it) exceeds
    max_wait_s sheds immediately with SlowDown, keyed or not."""
    from garage_tpu.qos.limiter import CURRENT_QOS_KEY

    eng = QosEngine(QosLimits(global_rps=10.0, global_burst=10.0,
                              max_wait_s=0.05, fair_keys=True))

    async def scenario():
        eng._req_bucket.tokens = 0.0  # ~0.1 s deficit > max_wait
        tok = CURRENT_QOS_KEY.set("A")
        try:
            with pytest.raises(SlowDown) as ei:
                async with eng.admit("s3"):
                    pass
            assert ei.value.scope == "global"
        finally:
            CURRENT_QOS_KEY.reset(tok)
        # anonymous requests (no key) keep the legacy debt path
        with pytest.raises(SlowDown):
            async with eng.admit("s3"):
                pass

    run(scenario())
    assert eng.counters.shed == 2 and eng.counters.admitted == 0


def test_request_rate_drr_flooding_key_cannot_shed_fresh_keys():
    """Review pin: the shed estimate prices what round-robin will make
    THIS arrival wait (own queue + one rotation), not the global
    backlog — key A's flood throttles A at the bound while fresh key B
    still admits."""
    from garage_tpu.qos.limiter import CURRENT_QOS_KEY

    eng = QosEngine(QosLimits(global_rps=200.0, global_burst=200.0,
                              max_wait_s=0.1, fair_keys=True))

    async def scenario():
        eng._req_bucket.tokens = 0.0
        results = {"A": [], "B": []}

        async def one(key):
            tok = CURRENT_QOS_KEY.set(key)
            try:
                async with eng.admit("s3"):
                    results[key].append("ok")
            except SlowDown:
                results[key].append("shed")
            finally:
                CURRENT_QOS_KEY.reset(tok)

        # A floods far past what 0.1 s of budget (20 reqs) can hold
        tasks = [asyncio.ensure_future(one("A")) for _ in range(60)]
        await asyncio.sleep(0)
        # B's first requests arrive while A's backlog is deep
        tasks += [asyncio.ensure_future(one("B")) for _ in range(3)]
        await asyncio.gather(*tasks)
        return results

    results = run(scenario())
    assert results["B"] == ["ok", "ok", "ok"], results["B"]
    assert "shed" in results["A"]  # the flooder pays its own bound

def test_claimed_key_id_parsed_without_crypto():
    from garage_tpu.api.signature import claimed_key_id

    class Req:
        def __init__(self, auth=None, query=None):
            self._auth = auth
            self.query = query or {}

        def header(self, name):
            return self._auth if name == "authorization" else None

    assert claimed_key_id(Req(
        "AWS4-HMAC-SHA256 Credential=GKkey1/20260804/garage/s3/"
        "aws4_request, SignedHeaders=host, Signature=deadbeef"
    )) == "GKkey1"
    assert claimed_key_id(Req(
        query={"X-Amz-Credential":
               "GKkey2%2F20260804%2Fgarage%2Fs3%2Faws4_request"}
    )) == "GKkey2"
    assert claimed_key_id(Req()) is None


def test_shed_entity_map_is_bounded():
    """An attacker spraying distinct key ids must not grow the shed
    attribution maps without bound: past the cap, new entities
    aggregate under '(other)'."""
    from garage_tpu.qos.limiter import SHED_ENTITY_MAX, QosCounters

    c = QosCounters()
    for i in range(SHED_ENTITY_MAX + 50):
        c.count_entity(c.shed_by_key, f"key{i}")
    assert len(c.shed_by_key) <= SHED_ENTITY_MAX + 1
    assert c.shed_by_key["(other)"] == 50
    assert sum(c.shed_by_key.values()) == SHED_ENTITY_MAX + 50


# ---- governor ------------------------------------------------------------


class _FakeScrubState:
    tranquility = 4.0


class _FakeScrubWorker:
    def __init__(self):
        self.state = _FakeScrubState()


class _FakeResync:
    tranquility = 0.0


class _FakeCacheTier:
    prefetch_tranquility = 0.0


class _FakeBlockManager:
    def __init__(self):
        self.resync = _FakeResync()
        self.scrub_worker = _FakeScrubWorker()
        self.cache_tier = _FakeCacheTier()


class _FakeGarage:
    def __init__(self):
        self.block_manager = _FakeBlockManager()


def test_governor_throttles_scrub_under_latency():
    g = _FakeGarage()
    samples = {"count": 0, "total": 0.0}
    gov = GovernorWorker(g, interval=0.01, target_latency=0.05,
                         scrub_range=(1.0, 30.0), resync_range=(0.0, 2.0),
                         sample_fn=lambda: (samples["count"],
                                            samples["total"]))
    gov.step()  # baseline snapshot
    # inject sustained HIGH foreground latency (10x target)
    for _ in range(12):
        samples["count"] += 20
        samples["total"] += 20 * 0.5
        gov.step()
    assert gov.pressure == pytest.approx(1.0)
    sw = g.block_manager.scrub_worker
    assert sw.state.tranquility == pytest.approx(30.0)  # scrub yields
    assert g.block_manager.resync.tranquility == pytest.approx(2.0)
    # cache-tier hint prefetch yields too (ISSUE 18)
    assert g.block_manager.cache_tier.prefetch_tranquility == \
        pytest.approx(GovernorWorker.PREFETCH_TRANQ_MAX)
    high_ewma = gov.ewma
    assert high_ewma > 0.05

    # latency falls well below target -> background sprints again
    for _ in range(60):
        samples["count"] += 20
        samples["total"] += 20 * 0.001
        gov.step()
    assert gov.pressure == pytest.approx(0.0)
    assert sw.state.tranquility == pytest.approx(1.0)
    assert g.block_manager.resync.tranquility == pytest.approx(0.0)
    assert g.block_manager.cache_tier.prefetch_tranquility == \
        pytest.approx(0.0)

    # foreground-idle: pressure decays instead of freezing
    gov.pressure = 0.6
    for _ in range(10):
        gov.step()
    assert gov.pressure == pytest.approx(0.0)


def test_governor_reacts_to_queue_depth_before_latency():
    """ROADMAP 'governor signal breadth': writers parked at the block
    byte-semaphore push pressure up even while the latency EWMA still
    looks healthy (the queue is the leading indicator)."""
    g = _FakeGarage()
    samples = {"count": 0, "total": 0.0}
    depth = {"n": 0}
    gov = GovernorWorker(g, target_latency=0.05,
                         sample_fn=lambda: (samples["count"],
                                            samples["total"]),
                         queue_depth_fn=lambda: depth["n"])
    gov.step()  # baseline
    # healthy latency, NO queue: pressure stays at zero
    for _ in range(5):
        samples["count"] += 10
        samples["total"] += 10 * 0.001
        gov.step()
    assert gov.pressure == pytest.approx(0.0)
    # healthy latency but writers piling up at the byte-semaphore
    depth["n"] = 8
    for _ in range(4):
        samples["count"] += 10
        samples["total"] += 10 * 0.001
        gov.step()
    assert gov.pressure > 0.5
    assert gov.last_queue_depth == 8
    assert gov.state()["queue_depth"] == 8
    # queue drains -> the healthy-latency bleed-off takes it back down
    depth["n"] = 0
    for _ in range(60):
        samples["count"] += 10
        samples["total"] += 10 * 0.001
        gov.step()
    assert gov.pressure == pytest.approx(0.0)


def test_byte_semaphore_queue_depth_surface():
    """The governor's queue signal reads _ByteSemaphore.queue_depth():
    parked waiters are visible, granted ones are not."""

    async def main():
        from garage_tpu.block.manager import _ByteSemaphore

        sem = _ByteSemaphore(100)
        await sem.acquire(80)
        assert sem.queue_depth() == 0
        t1 = asyncio.create_task(sem.acquire(50))
        t2 = asyncio.create_task(sem.acquire(60))
        await asyncio.sleep(0)
        assert sem.queue_depth() == 2
        assert sem.waiting_bytes() == 110
        sem.release(80)
        await asyncio.sleep(0)
        assert sem.queue_depth() == 1  # FIFO: 50 granted, 60 waits
        sem.release(50)
        await asyncio.sleep(0)
        assert sem.queue_depth() == 0
        await t1
        await t2
        sem.release(60)

    asyncio.run(asyncio.wait_for(main(), 10))


def test_governor_respects_manual_hold():
    g = _FakeGarage()
    g.block_manager.scrub_worker.state.tranquility_manual = True
    g.block_manager.resync.tranquility_manual = True
    g.block_manager.resync.tranquility = 7.5
    samples = {"count": 0, "total": 0.0}
    gov = GovernorWorker(g, target_latency=0.05,
                         sample_fn=lambda: (samples["count"],
                                            samples["total"]))
    gov.step()
    for _ in range(10):
        samples["count"] += 10
        samples["total"] += 10 * 0.5
        gov.step()
    assert gov.pressure > 0  # loop still runs ...
    # ... but operator-held knobs are untouched
    assert g.block_manager.scrub_worker.state.tranquility == 4.0
    assert g.block_manager.resync.tranquility == 7.5


def test_governor_worker_protocol():
    async def main():
        g = _FakeGarage()
        gov = GovernorWorker(g, interval=0.25,
                             sample_fn=lambda: (0, 0.0))
        st = await gov.work()
        assert isinstance(st, Throttled) and st.delay == 0.25
        gov.enabled = False
        await gov.work()  # disabled: no sampling, still throttles
        assert "disabled" in gov.info().progress

    run(main())


# ---- end-to-end: S3 API sheds with 503 SlowDown --------------------------


async def _one_node_s3(tmp_path):
    """In-process single node + S3 API server + an authorized key."""
    from garage_tpu.api.s3.api_server import S3ApiServer
    from garage_tpu.model.helper import GarageHelper, allow_all

    net, garages, tasks = await make_garage_cluster(tmp_path, n=1, rf=1)
    g = garages[0]
    helper = GarageHelper(g)
    key = await helper.create_key("qos-test")
    bucket = await helper.create_bucket("qos-bucket")
    await helper.set_bucket_key_permissions(bucket.id, key.key_id,
                                            allow_all())
    srv = S3ApiServer(g)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    await srv.start("127.0.0.1", port)
    cli = S3Client("127.0.0.1", port, key.key_id,
                   key.params.secret_key, region=g.config.s3_region)
    return net, garages, tasks, g, srv, cli


def test_overload_sheds_503_slowdown(tmp_path):
    async def main():
        net, garages, tasks, g, srv, cli = await _one_node_s3(tmp_path)
        try:
            # burst budget of 4, negligible refill, no waiting room:
            # sustained pressure MUST shed instead of queueing
            g.qos.set_limits(QosLimits(global_rps=0.001, global_burst=4,
                                       max_wait_s=0.0))

            def one(i):
                return cli.request("PUT", f"/qos-bucket/k{i}",
                                   body=b"x", timeout=30.0)

            results = await asyncio.gather(
                *[in_pool(one, i) for i in range(12)])
            codes = [st for st, _, _ in results]
            assert codes.count(200) == 4, codes
            shed = [(st, h, b) for st, h, b in results if st == 503]
            assert len(shed) == 8, codes
            for st, hdrs, body in shed:
                assert "retry-after" in hdrs, hdrs
                assert int(hdrs["retry-after"]) >= 1
                assert b"SlowDown" in body
                assert b"reduce your request rate" in body
            assert g.qos.counters.shed == 8
            assert g.qos.counters.admitted >= 4

            # a burst WITHIN budget never sheds
            g.qos.set_limits(QosLimits(global_rps=1000.0,
                                       global_burst=1000.0,
                                       max_wait_s=0.5))
            results = await asyncio.gather(
                *[in_pool(one, 100 + i) for i in range(10)])
            assert [st for st, _, _ in results] == [200] * 10
        finally:
            await srv.stop()
            await stop_all(garages, tasks)

    run(main())


def test_sustained_rate_with_bounded_wait_queues_not_sheds(tmp_path):
    async def main():
        net, garages, tasks, g, srv, cli = await _one_node_s3(tmp_path)
        try:
            # rate high enough that a short bounded wait absorbs the
            # burst: everything is admitted, some after queueing
            g.qos.set_limits(QosLimits(global_rps=50.0, global_burst=2,
                                       max_wait_s=2.0))

            def one(i):
                return cli.request("GET", "/qos-bucket",
                                   query=[("list-type", "2")],
                                   timeout=30.0)

            results = await asyncio.gather(
                *[in_pool(one, i) for i in range(8)])
            assert [st for st, _, _ in results] == [200] * 8
            assert g.qos.counters.queued_waits > 0
        finally:
            await srv.stop()
            await stop_all(garages, tasks)

    run(main())


# ---- admin endpoint round-trip -------------------------------------------


def test_admin_qos_roundtrip(tmp_path):
    async def main():
        from garage_tpu.admin.http import AdminHttpServer

        net, garages, tasks = await make_garage_cluster(tmp_path, n=1,
                                                        rf=1)
        g = garages[0]
        g.config.admin_token = "qos-admin-token"
        srv = AdminHttpServer(g)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        await srv.start("127.0.0.1", port)

        def req(method, path, body=None):
            r = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", method=method,
                data=json.dumps(body).encode() if body else None,
                headers={"authorization": "Bearer qos-admin-token"})
            with urllib.request.urlopen(r, timeout=10) as resp:
                return json.loads(resp.read().decode())

        try:
            before = await in_pool(req, "GET", "/v1/qos")
            assert before["limits"]["global_rps"] is None

            after = await in_pool(
                req, "POST", "/v1/qos",
                {"global_rps": 123.0, "max_concurrent": 7,
                 "per_key_rps": 9.0})
            assert after["limits"]["global_rps"] == 123.0
            assert after["limits"]["max_concurrent"] == 7

            got = await in_pool(req, "GET", "/v1/qos")
            assert got["limits"]["global_rps"] == 123.0
            assert got["limits"]["per_key_rps"] == 9.0
            assert got["limits"]["max_concurrent"] == 7
            # the engine actually enforces the new limit
            assert g.qos._req_bucket is not None
            assert g.qos._req_bucket.rate == 123.0
            assert g.qos._conc is not None and g.qos._conc.limit == 7

            # clearing a limit via null round-trips too
            got = await in_pool(req, "POST", "/v1/qos",
                                    {"max_concurrent": None})
            assert got["limits"]["max_concurrent"] is None
            assert g.qos._conc is None

            # unknown keys are rejected, state unchanged
            with pytest.raises(urllib.error.HTTPError) as ei:
                await in_pool(req, "POST", "/v1/qos",
                              {"bogus_limit": 1})
            assert ei.value.code == 400
            assert g.qos.limits.global_rps == 123.0
        finally:
            await srv.stop()
            await stop_all(garages, tasks)

    run(main())


def test_admin_cache_readout(tmp_path):
    """GET /v1/cache (ISSUE 18): one stop for the cold-herd machinery —
    both cache segments' stats, the node-local singleflight counters,
    and the cluster tier's lease/prefetch ledger."""
    async def main():
        from garage_tpu.admin.http import AdminHttpServer

        net, garages, tasks = await make_garage_cluster(tmp_path, n=1,
                                                        rf=1)
        g = garages[0]
        g.config.admin_token = "cache-admin-token"
        srv = AdminHttpServer(g)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        await srv.start("127.0.0.1", port)

        def req(path):
            r = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                headers={"authorization": "Bearer cache-admin-token"})
            with urllib.request.urlopen(r, timeout=10) as resp:
                return json.loads(resp.read().decode())

        try:
            got = await in_pool(req, "/v1/cache")
            assert got["enabled"] is True
            for seg in ("plain", "packed"):
                for key in ("entries", "bytes", "hits", "misses",
                            "inserts", "max_bytes"):
                    assert key in got[seg], (seg, key)
            assert got["singleflight"] == {"leaders": 0, "collapsed": 0,
                                           "in_flight": 0}
            tier = got["tier"]
            assert tier is not None  # [block] cache_tier defaults on
            for key in ("lease_wait_ms", "lease_depth", "lease_minted",
                        "lease_grants", "prefetch_queue", "prefetched",
                        "prefetch_inflight_max"):
                assert key in tier, key
        finally:
            await srv.stop()
            await stop_all(garages, tasks)

    run(main())
