"""System membership + quorum engine tests over the loopback network."""

import asyncio

import pytest

from garage_tpu.net import LocalNetwork, NetApp
from garage_tpu.net.message import PRIO_NORMAL
from garage_tpu.rpc import ReplicationMode, RpcHelper, RequestStrategy, System
from garage_tpu.rpc.layout import NodeRole
from garage_tpu.rpc.rpc_helper import QuorumSetResultTracker
from garage_tpu.rpc.system import ClusterHealthStatus
from garage_tpu.utils.error import QuorumError

NETID = b"rpc-test"


def run(coro, timeout=60.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def make_cluster(tmp_path, n, rf=3, connect=True):
    net = LocalNetwork()
    systems = []
    for i in range(n):
        app = NetApp(NETID)
        net.register(app)
        meta = str(tmp_path / f"node{i}")
        sys_ = System(
            app,
            ReplicationMode.parse(rf),
            meta,
            status_interval=0.2,
            ping_interval=0.2,
        )
        systems.append(sys_)
    tasks = [asyncio.create_task(s.run()) for s in systems]
    if connect:
        for s in systems[1:]:
            await s.netapp.try_connect(systems[0].netapp.public_addr, systems[0].id)
            s.peering.add_peer(systems[0].netapp.public_addr, systems[0].id)
        # let the mesh converge via peer exchange
        await _wait(lambda: all(len(s.netapp.conns) == n - 1 for s in systems), 15)
    return net, systems, tasks


async def _wait(cond, timeout):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return
        await asyncio.sleep(0.05)
    raise AssertionError("condition not reached")


async def stop_cluster(systems, tasks):
    for s in systems:
        await s.stop()
    for t in tasks:
        t.cancel()


def apply_flat_layout(systems, rf=3):
    """Stage all nodes with equal capacity on node 0 and apply."""
    lm = systems[0].layout_manager
    for s in systems:
        lm.history.stage_role(s.id, NodeRole(zone="z1", capacity=1 << 30))
    lm.apply_staged(None)


def test_layout_gossip_convergence(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_flat_layout(systems)
            await _wait(
                lambda: all(
                    s.layout_manager.history.current().version == 1 for s in systems
                ),
                10,
            )
            # ring identical everywhere
            rings = {s.layout_manager.history.current().ring_assignment_data for s in systems}
            assert len(rings) == 1
        finally:
            await stop_cluster(systems, tasks)

    run(main())


def test_cluster_health(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_flat_layout(systems)
            await _wait(
                lambda: all(
                    s.layout_manager.history.current().version == 1 for s in systems
                ),
                10,
            )
            h = systems[0].health()
            assert h.status == ClusterHealthStatus.HEALTHY
            assert h.storage_nodes == 3 and h.storage_nodes_up == 3
            # partition a node: health degrades (writes still have quorum 2/3)
            net.partition(systems[0].id, systems[2].id)
            net.partition(systems[1].id, systems[2].id)
            await _wait(lambda: not systems[0].is_up(systems[2].id), 15)
            h = systems[0].health()
            assert h.status == ClusterHealthStatus.DEGRADED
            assert h.storage_nodes_up == 2
        finally:
            await stop_cluster(systems, tasks)

    run(main())


def test_try_call_many_quorum(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_flat_layout(systems)
            calls = []
            for s in systems:
                def mk(s=s):
                    async def h(frm, payload, stream):
                        calls.append(s.id)
                        if payload.get("fail") == s.id:
                            raise ValueError("injected failure")
                        return {"node": s.id}
                    return h
                s.netapp.endpoint("test/q").set_handler(mk())
            helper = RpcHelper(systems[0])
            ep = systems[0].netapp.endpoint("test/q")
            nodes = [s.id for s in systems]

            # quorum 2 of 3, all healthy: adaptive send reaches quorum
            rs = RequestStrategy(quorum=2, timeout=5)
            resp = await helper.try_call_many(ep, nodes, {}, rs)
            assert len(resp) == 2

            # one node failing: replacement request still reaches quorum
            resp = await helper.try_call_many(ep, nodes, {"fail": systems[0].id}, rs)
            assert len(resp) == 2

            # quorum 3 with one failing: QuorumError
            rs3 = RequestStrategy(quorum=3, timeout=5)
            with pytest.raises(QuorumError):
                await helper.try_call_many(ep, nodes, {"fail": systems[1].id}, rs3)
        finally:
            await stop_cluster(systems, tasks)

    run(main())


def test_try_write_many_sets(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            seen = []
            for s in systems:
                def mk(s=s):
                    async def h(frm, payload, stream):
                        seen.append(s.id)
                        if payload.get("fail") == s.id:
                            raise ValueError("nope")
                        return {}
                    return h
                s.netapp.endpoint("test/w").set_handler(mk())
            helper = RpcHelper(systems[0])
            ep = systems[0].netapp.endpoint("test/w")
            ids = [s.id for s in systems]
            # two overlapping sets (layout transition shape)
            sets = [[ids[0], ids[1]], [ids[1], ids[2]]]
            rs = RequestStrategy(quorum=2, timeout=5)
            tracker = await helper.try_write_many_sets(ep, sets, {}, rs)
            assert tracker.all_quorums_ok()

            # failure of a node breaks only quorum-2 of both sets
            with pytest.raises(QuorumError):
                await helper.try_write_many_sets(ep, sets, {"fail": ids[1]}, rs)
        finally:
            await stop_cluster(systems, tasks)

    run(main())


def test_quorum_set_tracker_counts():
    a, b, c = b"a" * 32, b"b" * 32, b"c" * 32
    t = QuorumSetResultTracker([[a, b], [b, c]], 2)
    assert t.nodes == [a, b, c]
    t.success(a, {})
    t.success(b, {})
    assert not t.all_quorums_ok()
    t.failure(c, RuntimeError("x"))
    assert t.too_many_failures()
    err = t.quorum_error()
    assert err.quorum == 2 and err.ok == 2


def test_quorum_set_tracker_shared_node_failure_breaks_both_sets():
    """Overlapping sets: the ONE node both sets depend on fails — both
    quorums become unreachable after a single failure, and the error
    accounting must say so (not wait for more failures)."""
    a, b, c = b"a" * 32, b"b" * 32, b"c" * 32
    t = QuorumSetResultTracker([[a, b], [b, c]], 2)
    t.failure(b, RuntimeError("shared node down"))
    # each set is 2-wide with quorum 2: one failure > len - quorum = 0
    assert t.too_many_failures()
    assert not t.all_quorums_ok()
    assert t.set_counts() == [(0, 1), (0, 1)]
    # successes on the remaining nodes cannot rescue either set
    t.success(a, {})
    t.success(c, {})
    assert t.set_counts() == [(1, 1), (1, 1)]
    assert not t.all_quorums_ok() and t.too_many_failures()
    err = t.quorum_error()
    assert err.ok == 2 and err.total == 3 and len(err.errors) == 1


def test_quorum_set_tracker_disjoint_sets_isolated():
    """A failure confined to one set must not break the other."""
    a, b, c, d = b"a" * 32, b"b" * 32, b"c" * 32, b"d" * 32
    t = QuorumSetResultTracker([[a, b], [c, d]], 1)
    t.failure(a, RuntimeError("x"))
    t.success(b, {})
    t.success(c, {})
    assert t.set_counts() == [(1, 1), (1, 0)]
    assert t.all_quorums_ok()
    assert not t.too_many_failures()


def test_try_write_many_sets_cancellation_no_orphaned_tasks(tmp_path):
    """Cancelling a caller mid-write must cancel the per-node tasks and
    leave no 'exception was never retrieved' warnings behind."""

    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        unhandled = []
        loop = asyncio.get_running_loop()
        loop.set_exception_handler(
            lambda lp, ctx: unhandled.append(ctx.get("message", "")))
        try:
            release = asyncio.Event()
            for s in systems:
                async def h(frm, payload, stream):
                    await release.wait()
                    raise ValueError("late failure after caller left")
                s.netapp.endpoint("test/cancel").set_handler(h)
            helper = RpcHelper(systems[0])
            ep = systems[0].netapp.endpoint("test/cancel")
            ids = [s.id for s in systems]
            rs = RequestStrategy(quorum=2, timeout=10)
            writer = asyncio.create_task(helper.try_write_many_sets(
                ep, [[ids[0], ids[1]], [ids[1], ids[2]]], {}, rs))
            await asyncio.sleep(0.2)  # let the per-node tasks launch
            writer.cancel()
            with pytest.raises(asyncio.CancelledError):
                await writer
            release.set()  # handlers fail AFTER the caller is gone
            await asyncio.sleep(0.2)
            stray = [t for t in asyncio.all_tasks()
                     if "rpc_helper" in repr(t)
                     and ("try_write_many_sets" in repr(t)
                          or "one()" in repr(t))]
            assert not stray, f"orphaned write tasks: {stray}"
            import gc

            gc.collect()
            await asyncio.sleep(0.05)
            assert not any("never retrieved" in m for m in unhandled), \
                unhandled
        finally:
            loop.set_exception_handler(None)
            await stop_cluster(systems, tasks)

    run(main())


def test_try_call_many_hedges_around_slow_node(tmp_path):
    """No chaos needed: a merely-slow (not failing) node in the initial
    quorum set must not hold a read to its own pace — the hedge fires
    at the observed p95 and the next node answers."""

    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_flat_layout(systems)
            helper = RpcHelper(systems[0])
            nodes = [s.id for s in systems]
            # whoever ranks second sits in the initial quorum-2 send
            # set next to self — make THAT node the slow one
            slow = helper.request_order(list(nodes))[1]

            for s in systems:
                def mk(s=s):
                    async def h(frm, payload, stream):
                        if s.id == slow:
                            await asyncio.sleep(8.0)
                        return {"node": s.id}
                    return h
                s.netapp.endpoint("test/slow").set_handler(mk())
            ep = systems[0].netapp.endpoint("test/slow")
            t0 = asyncio.get_event_loop().time()
            resp = await helper.try_call_many(
                ep, nodes, {}, RequestStrategy(quorum=2, timeout=30.0))
            dt = asyncio.get_event_loop().time() - t0
            assert len(resp) == 2
            assert dt < 5.0, f"slow node dictated the read: {dt:.1f}s"
        finally:
            await stop_cluster(systems, tasks)

    run(main())


def test_peer_list_persisted_across_restart(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 2)
        try:
            await _wait(
                lambda: all(len(s.netapp.conns) == 1 for s in systems), 10
            )
            await systems[0]._advertise_status()
        finally:
            await stop_cluster(systems, tasks)
        # restart node 0 with no bootstrap: must reconnect from persisted list
        app = NetApp(NETID)
        net.register(app)
        meta = str(tmp_path / "node0")
        s0 = System(app, ReplicationMode.parse(3), meta, status_interval=0.2, ping_interval=0.2)
        assert any(p.addr is not None for p in s0.peering.peers.values() if p.id != s0.id)

    run(main())


def test_quorums_by_consistency_mode():
    # write quorum always derives from the CONSISTENT read quorum so that
    # degraded mode relaxes reads without inflating writes
    # (ref: src/rpc/replication_mode.rs:45-59)
    for n, r, w in [(1, 1, 1), (2, 2, 1), (3, 2, 2), (5, 3, 3)]:
        m = ReplicationMode.parse(n)
        assert (m.read_quorum, m.write_quorum) == (r, w)
        deg = ReplicationMode.parse(n, consistency_mode="degraded")
        assert (deg.read_quorum, deg.write_quorum) == (1, w)
        dang = ReplicationMode.parse(n, consistency_mode="dangerous")
        assert (dang.read_quorum, dang.write_quorum) == (1, 1)
