"""System membership + quorum engine tests over the loopback network."""

import asyncio

import pytest

from garage_tpu.net import LocalNetwork, NetApp
from garage_tpu.net.message import PRIO_NORMAL
from garage_tpu.rpc import ReplicationMode, RpcHelper, RequestStrategy, System
from garage_tpu.rpc.layout import NodeRole
from garage_tpu.rpc.rpc_helper import QuorumSetResultTracker
from garage_tpu.rpc.system import ClusterHealthStatus
from garage_tpu.utils.error import QuorumError

NETID = b"rpc-test"


def run(coro, timeout=60.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def make_cluster(tmp_path, n, rf=3, connect=True):
    net = LocalNetwork()
    systems = []
    for i in range(n):
        app = NetApp(NETID)
        net.register(app)
        meta = str(tmp_path / f"node{i}")
        sys_ = System(
            app,
            ReplicationMode.parse(rf),
            meta,
            status_interval=0.2,
            ping_interval=0.2,
        )
        systems.append(sys_)
    tasks = [asyncio.create_task(s.run()) for s in systems]
    if connect:
        for s in systems[1:]:
            await s.netapp.try_connect(systems[0].netapp.public_addr, systems[0].id)
            s.peering.add_peer(systems[0].netapp.public_addr, systems[0].id)
        # let the mesh converge via peer exchange
        await _wait(lambda: all(len(s.netapp.conns) == n - 1 for s in systems), 15)
    return net, systems, tasks


async def _wait(cond, timeout):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return
        await asyncio.sleep(0.05)
    raise AssertionError("condition not reached")


async def stop_cluster(systems, tasks):
    for s in systems:
        await s.stop()
    for t in tasks:
        t.cancel()


def apply_flat_layout(systems, rf=3):
    """Stage all nodes with equal capacity on node 0 and apply."""
    lm = systems[0].layout_manager
    for s in systems:
        lm.history.stage_role(s.id, NodeRole(zone="z1", capacity=1 << 30))
    lm.apply_staged(None)


def test_layout_gossip_convergence(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_flat_layout(systems)
            await _wait(
                lambda: all(
                    s.layout_manager.history.current().version == 1 for s in systems
                ),
                10,
            )
            # ring identical everywhere
            rings = {s.layout_manager.history.current().ring_assignment_data for s in systems}
            assert len(rings) == 1
        finally:
            await stop_cluster(systems, tasks)

    run(main())


def test_cluster_health(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_flat_layout(systems)
            await _wait(
                lambda: all(
                    s.layout_manager.history.current().version == 1 for s in systems
                ),
                10,
            )
            h = systems[0].health()
            assert h.status == ClusterHealthStatus.HEALTHY
            assert h.storage_nodes == 3 and h.storage_nodes_up == 3
            # partition a node: health degrades (writes still have quorum 2/3)
            net.partition(systems[0].id, systems[2].id)
            net.partition(systems[1].id, systems[2].id)
            await _wait(lambda: not systems[0].is_up(systems[2].id), 15)
            h = systems[0].health()
            assert h.status == ClusterHealthStatus.DEGRADED
            assert h.storage_nodes_up == 2
        finally:
            await stop_cluster(systems, tasks)

    run(main())


def test_try_call_many_quorum(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_flat_layout(systems)
            calls = []
            for s in systems:
                def mk(s=s):
                    async def h(frm, payload, stream):
                        calls.append(s.id)
                        if payload.get("fail") == s.id:
                            raise ValueError("injected failure")
                        return {"node": s.id}
                    return h
                s.netapp.endpoint("test/q").set_handler(mk())
            helper = RpcHelper(systems[0])
            ep = systems[0].netapp.endpoint("test/q")
            nodes = [s.id for s in systems]

            # quorum 2 of 3, all healthy: adaptive send reaches quorum
            rs = RequestStrategy(quorum=2, timeout=5)
            resp = await helper.try_call_many(ep, nodes, {}, rs)
            assert len(resp) == 2

            # one node failing: replacement request still reaches quorum
            resp = await helper.try_call_many(ep, nodes, {"fail": systems[0].id}, rs)
            assert len(resp) == 2

            # quorum 3 with one failing: QuorumError
            rs3 = RequestStrategy(quorum=3, timeout=5)
            with pytest.raises(QuorumError):
                await helper.try_call_many(ep, nodes, {"fail": systems[1].id}, rs3)
        finally:
            await stop_cluster(systems, tasks)

    run(main())


def test_try_write_many_sets(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            seen = []
            for s in systems:
                def mk(s=s):
                    async def h(frm, payload, stream):
                        seen.append(s.id)
                        if payload.get("fail") == s.id:
                            raise ValueError("nope")
                        return {}
                    return h
                s.netapp.endpoint("test/w").set_handler(mk())
            helper = RpcHelper(systems[0])
            ep = systems[0].netapp.endpoint("test/w")
            ids = [s.id for s in systems]
            # two overlapping sets (layout transition shape)
            sets = [[ids[0], ids[1]], [ids[1], ids[2]]]
            rs = RequestStrategy(quorum=2, timeout=5)
            tracker = await helper.try_write_many_sets(ep, sets, {}, rs)
            assert tracker.all_quorums_ok()

            # failure of a node breaks only quorum-2 of both sets
            with pytest.raises(QuorumError):
                await helper.try_write_many_sets(ep, sets, {"fail": ids[1]}, rs)
        finally:
            await stop_cluster(systems, tasks)

    run(main())


def test_quorum_set_tracker_counts():
    a, b, c = b"a" * 32, b"b" * 32, b"c" * 32
    t = QuorumSetResultTracker([[a, b], [b, c]], 2)
    assert t.nodes == [a, b, c]
    t.success(a, {})
    t.success(b, {})
    assert not t.all_quorums_ok()
    t.failure(c, RuntimeError("x"))
    assert t.too_many_failures()
    err = t.quorum_error()
    assert err.quorum == 2 and err.ok == 2


def test_peer_list_persisted_across_restart(tmp_path):
    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 2)
        try:
            await _wait(
                lambda: all(len(s.netapp.conns) == 1 for s in systems), 10
            )
            await systems[0]._advertise_status()
        finally:
            await stop_cluster(systems, tasks)
        # restart node 0 with no bootstrap: must reconnect from persisted list
        app = NetApp(NETID)
        net.register(app)
        meta = str(tmp_path / "node0")
        s0 = System(app, ReplicationMode.parse(3), meta, status_interval=0.2, ping_interval=0.2)
        assert any(p.addr is not None for p in s0.peering.peers.values() if p.id != s0.id)

    run(main())


def test_quorums_by_consistency_mode():
    # write quorum always derives from the CONSISTENT read quorum so that
    # degraded mode relaxes reads without inflating writes
    # (ref: src/rpc/replication_mode.rs:45-59)
    for n, r, w in [(1, 1, 1), (2, 2, 1), (3, 2, 2), (5, 3, 3)]:
        m = ReplicationMode.parse(n)
        assert (m.read_quorum, m.write_quorum) == (r, w)
        deg = ReplicationMode.parse(n, consistency_mode="degraded")
        assert (deg.read_quorum, deg.write_quorum) == (1, w)
        dang = ReplicationMode.parse(n, consistency_mode="dangerous")
        assert (dang.read_quorum, dang.write_quorum) == (1, 1)
