"""ISSUE 14: concurrency-soundness lint — GL12 await-interleaving
races, GL13 lock-order cycles, GL11v2 cross-function budget leaks,
engine-level @blocking_api annotations, GL10 generator-iteration
blindness — fire+suppress fixtures, the real-CLI exit-1 pins, summary
determinism over the new fields, and the SUMMARY_VERSION bump."""

import ast
import json
import os
import textwrap

from garage_tpu.analysis import (analyze_source, default_rules,
                                 summarize_tree, summary_json)
from garage_tpu.analysis.dataflow import SUMMARY_VERSION

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(src: str, rel_path: str = "garage_tpu/fake/mod.py"):
    ctx = analyze_source(textwrap.dedent(src), default_rules(),
                         rel_path=rel_path)
    return [v for v in ctx.violations if v.active]


def rules_of(violations):
    return sorted({v.rule for v in violations})


# ---- GL12 await-interleaving-atomicity ----------------------------------

def test_gl12_check_then_act_fires_with_both_lines():
    vs = run("""
        class F:
            async def start(self, h):
                if h not in self._inflight:
                    fut = await self._spawn(h)
                    self._inflight[h] = fut
                return self._inflight[h]
    """)
    assert rules_of(vs) == ["GL12"]
    assert "self._inflight" in vs[0].message
    assert "read at line 4" in vs[0].message
    assert "awaited at line 5" in vs[0].message


def test_gl12_write_in_awaited_callee_fires():
    vs = run("""
        class F:
            async def start(self, h):
                if h not in self._inflight:
                    await self._insert(h)
            async def _insert(self, h):
                self._inflight[h] = 1
    """)
    assert rules_of(vs) == ["GL12"]
    assert "F._insert" in vs[0].message


def test_gl12_write_in_sync_self_callee_after_await_fires():
    vs = run("""
        class F:
            async def start(self, h):
                if h not in self._inflight:
                    fut = await self.spawn(h)
                    self._store(h, fut)
            def _store(self, h, fut):
                self._inflight[h] = fut
    """)
    assert rules_of(vs) == ["GL12"]
    assert "F._store" in vs[0].message


def test_gl12_module_state_fires():
    vs = run("""
        _pending = {}
        async def start(h):
            if h not in _pending:
                fut = await spawn(h)
                _pending[h] = fut
    """)
    assert rules_of(vs) == ["GL12"]
    assert "_pending" in vs[0].message


def test_gl12_recheck_after_await_is_the_fix_idiom():
    vs = run("""
        class F:
            async def start(self, h):
                if h not in self._inflight:
                    fut = await self._spawn(h)
                    if h not in self._inflight:
                        self._inflight[h] = fut
    """)
    assert vs == []


def test_gl12_lock_across_await_suppresses():
    vs = run("""
        class F:
            async def start(self, h):
                async with self._lock:
                    if h not in self._inflight:
                        fut = await self._spawn(h)
                        self._inflight[h] = fut
    """)
    assert vs == []


def test_gl12_guard_loop_while_recheck_suppresses():
    # `while cond: await` re-evaluates its test before falling
    # through — the post-loop write acts on a re-validated read
    vs = run("""
        class F:
            async def admit(self, t):
                while len(self._tasks) >= self.cap:
                    await wait_any(self._tasks)
                self._tasks.add(t)
    """)
    assert vs == []


def test_gl12_accretive_mutation_suppresses():
    # extend/append act on LIVE state; a stale length check cannot
    # make them clobber another task's bytes
    vs = run("""
        class R:
            async def fill(self, n):
                while len(self._buf) < n:
                    c = await self.inner.read()
                    self._buf.extend(c)
    """)
    assert vs == []


def test_gl12_constant_flag_store_suppresses():
    vs = run("""
        class R:
            async def read(self):
                if self._eof:
                    return b""
                data = await self.inner.read()
                if not data:
                    self._eof = True
                return data
    """)
    assert vs == []


def test_gl12_return_barrier_suppresses_branch_write():
    # the await sits on a branch that RETURNS; the write path never
    # crossed it
    vs = run("""
        class W:
            async def work(self):
                if self._phase == 0:
                    await self.push_batch()
                    return "busy"
                self._phase = 1
    """)
    assert vs == []


def test_gl12_augassign_with_await_inside_value_fires():
    vs = run("""
        class C:
            async def bump(self):
                self.count += await self.compute()
    """)
    assert rules_of(vs) == ["GL12"]


def test_gl12_waivable_with_reason():
    vs = run("""
        class F:
            async def start(self, h):
                if h not in self._inflight:
                    fut = await self._spawn(h)
                    # lint: ignore[GL12] single dispatcher task owns this map
                    self._inflight[h] = fut
    """)
    assert vs == []


def test_gl12_skips_test_files():
    ctx = analyze_source(textwrap.dedent("""
        class F:
            async def start(self, h):
                if h not in self._inflight:
                    fut = await self._spawn(h)
                    self._inflight[h] = fut
    """), default_rules(), rel_path="tests/test_fake.py")
    assert [v for v in ctx.violations if v.active] == []


# ---- GL13 lock-order-inversion ------------------------------------------

GL13_ABBA = """
    class F:
        async def a(self):
            async with self._lock_a:
                async with self._lock_b:
                    pass
        async def b(self):
            async with self._lock_b:
                async with self._lock_a:
                    pass
"""


def test_gl13_abba_fires_with_both_chains():
    vs = run(GL13_ABBA)
    assert rules_of(vs) == ["GL13"]
    msg = vs[0].message
    assert "_lock_a -> " in msg and "_lock_b -> " in msg
    assert "F.a" in msg and "F.b" in msg


def test_gl13_consistent_order_is_quiet():
    vs = run("""
        class F:
            async def a(self):
                async with self._lock_a:
                    async with self._lock_b:
                        pass
            async def b(self):
                async with self._lock_a:
                    async with self._lock_b:
                        pass
    """)
    assert vs == []


def test_gl13_cycle_through_resolved_call():
    vs = run("""
        class F:
            async def a(self):
                async with self._lock_a:
                    await self._takeb()
            async def _takeb(self):
                async with self._lock_b:
                    pass
            async def b(self):
                async with self._lock_b:
                    async with self._lock_a:
                        pass
    """)
    assert rules_of(vs) == ["GL13"]
    assert "via F._takeb" in vs[0].message


def test_gl13_sync_with_and_acquire_count():
    vs = run("""
        class F:
            def a(self):
                with self._lock_a:
                    self._lock_b.acquire()
            def b(self):
                with self._lock_b:
                    with self._lock_a:
                        pass
    """)
    assert rules_of(vs) == ["GL13"]


def test_gl13_same_attr_in_different_classes_not_an_edge():
    # lock identity is CLASS-qualified: A._lock and B._lock are
    # different locks even with the same attribute name
    vs = run("""
        class A:
            async def f(self):
                async with self._lock:
                    async with self._other:
                        pass
        class B:
            async def g(self):
                async with self._other:
                    async with self._lock:
                        pass
    """)
    assert vs == []


# ---- GL11v2 cross-function leaks ----------------------------------------

def test_gl11v2_release_in_callee_from_finally_is_safe():
    vs = run("""
        class F:
            async def ok(self, n):
                tok = await self.bucket.acquire(n)
                try:
                    return await self.upstream(n)
                finally:
                    self._give_back(n)
            def _give_back(self, n):
                self.bucket.refund(n)
    """)
    assert vs == []


def test_gl11v2_release_in_callee_on_happy_path_fires():
    vs = run("""
        class F:
            async def bad(self, n):
                tok = await self.bucket.acquire(n)
                resp = await self.upstream(n)
                self._give_back(n)
                return resp
            def _give_back(self, n):
                self.bucket.refund(n)
    """)
    assert rules_of(vs) == ["GL11"]


def test_gl11v2_acquiring_helper_makes_caller_the_owner():
    vs = run("""
        class F:
            def _rent(self, n):
                lease = self.broker.acquire(n)
                return lease
            async def use(self, n):
                lease = self._rent(n)
                resp = await self.upstream(n)
                lease.release()
                return resp
    """)
    assert rules_of(vs) == ["GL11"]
    assert "_rent" in vs[0].message


def test_gl11v2_acquiring_helper_caller_with_finally_is_safe():
    vs = run("""
        class F:
            def _rent(self, n):
                lease = self.broker.acquire(n)
                return lease
            async def use(self, n):
                lease = self._rent(n)
                try:
                    return await self.upstream(n)
                finally:
                    lease.release()
    """)
    assert vs == []


def test_gl11v2_passing_resource_on_is_ownership_transfer():
    # the caller returns the lease itself: its own caller owns it
    vs = run("""
        class F:
            def _rent(self, n):
                lease = self.broker.acquire(n)
                return lease
            async def rent_for_caller(self, n):
                lease = self._rent(n)
                await self.audit(n)
                return lease
    """)
    assert vs == []


def test_gl11v2_release_via_param_passing_fires_and_finally_safe():
    vs = run("""
        def put_back(lease, n):
            lease.release()
        async def bad(self, n):
            lease = await self.broker.acquire(n)
            resp = await self.upstream(n)
            put_back(lease, n)
            return resp
    """)
    assert rules_of(vs) == ["GL11"]


# ---- engine-level blocking annotations (GL10) ---------------------------

def test_blocking_api_class_attribute_fires_direct_and_transitive():
    vs = run("""
        class Store:
            blocking_api = True
            def fetch_rows(self):
                return 1
        def helper(s):
            return s.fetch_rows()
        class Svc:
            async def handler(self, s):
                return helper(s)
    """)
    assert rules_of(vs) == ["GL10"]
    assert "fetch_rows" in vs[0].message


def test_blocking_api_decorator_fires():
    vs = run("""
        def blocking_api(fn):
            return fn
        @blocking_api
        def scan_all(path):
            return 1
        async def handler(path):
            return scan_all(path)
    """)
    assert rules_of(vs) == ["GL10"]


def test_annotation_beats_receiver_heuristic_when_resolved():
    # receiver named `store` + db-verb method, but the call RESOLVES
    # to an in-project, NON-annotated function: the annotation layer
    # is authoritative — quiet (the old name heuristic alone fired)
    vs = run("""
        class Store:
            def iter(self):
                return []
        class Svc:
            async def handler(self):
                return self.store.iter()
    """)
    assert vs == []


def test_heuristic_kept_for_unresolved_out_of_tree_receivers():
    vs = run("""
        async def handler(self, pk):
            return self.store.get(pk)
    """)
    assert rules_of(vs) == ["GL10"]


def test_blocking_api_to_thread_hop_is_quiet():
    vs = run("""
        import asyncio
        class Store:
            blocking_api = True
            def fetch_rows(self):
                return 1
        class Svc:
            async def handler(self, s):
                return await asyncio.to_thread(s.fetch_rows)
    """)
    assert vs == []


def test_db_facade_is_annotated_in_tree():
    src = open(os.path.join(REPO, "garage_tpu/db/db.py"),
               encoding="utf-8").read()
    s = summarize_tree(ast.parse(src), "garage_tpu/db/db.py")
    assert s["classes"]["Tree"]["blocking_api"]
    assert s["classes"]["Transaction"]["blocking_api"]
    assert s["classes"]["Db"]["blocking_api"]
    assert s["functions"]["open_db"]["blocking_api"]


# ---- GL10 generator-iteration blindness ---------------------------------

def test_generator_iteration_fires_at_iteration_site():
    vs = run("""
        import sqlite3
        def gen(path):
            yield sqlite3.connect(path)
        async def uses(path):
            for row in gen(path):
                pass
    """)
    assert rules_of(vs) == ["GL10"]
    assert "uses -> gen" in vs[0].message


def test_async_generator_iteration_fires():
    # the blocking atom sits in a sync helper INSIDE the async
    # generator's body — only iterating runs it on the caller's frame
    vs = run("""
        import sqlite3
        def scan(path):
            return sqlite3.connect(path)
        async def agen(path):
            yield scan(path)
        async def uses(path):
            async for row in agen(path):
                pass
    """)
    assert "GL10" in rules_of(vs)
    assert any("uses -> agen" in v.message for v in vs)


def test_plain_generator_call_stays_exempt():
    vs = run("""
        import sqlite3
        def gen(path):
            yield sqlite3.connect(path)
        async def plain(path):
            g = gen(path)
            return g
    """)
    assert vs == []


# ---- CLI pins (each bug shape exits 1 via the real CLI) -----------------

def _cli_rc_on(tmp_path, source: str, rel: str) -> int:
    from garage_tpu.analysis.__main__ import main

    target = tmp_path / rel
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent(source))
    return main(["--baseline", "none", str(target)])


def test_cli_gl12_seeded_fixture_exits_1(tmp_path, capsys):
    rc = _cli_rc_on(tmp_path, """
        class F:
            async def start(self, h):
                if h not in self._inflight:
                    fut = await self._spawn(h)
                    self._inflight[h] = fut
    """, "garage_tpu/block/fake_inflight.py")
    assert rc == 1
    assert "GL12" in capsys.readouterr().out


def test_cli_gl13_seeded_fixture_exits_1(tmp_path, capsys):
    rc = _cli_rc_on(tmp_path, GL13_ABBA,
                    "garage_tpu/gateway/fake_locks.py")
    assert rc == 1
    assert "GL13" in capsys.readouterr().out


def test_cli_gl11v2_seeded_fixture_exits_1(tmp_path, capsys):
    rc = _cli_rc_on(tmp_path, """
        class F:
            def _rent(self, n):
                lease = self.broker.acquire(n)
                return lease
            async def use(self, n):
                lease = self._rent(n)
                resp = await self.upstream(n)
                lease.release()
                return resp
    """, "garage_tpu/qos/fake_rent.py")
    assert rc == 1
    assert "GL11" in capsys.readouterr().out


def test_explain_covers_the_new_rules(capsys):
    from garage_tpu.analysis.__main__ import main

    for rule in ("GL12", "GL13", "GL11"):
        assert main(["--explain", rule]) == 0
        out = capsys.readouterr().out
        assert "fires on:" in out and "quiet on:" in out


# ---- summary schema: determinism + version bump -------------------------

CONCURRENCY_RICH = """
    _registry = {}

    class F:
        blocking_api = True

        async def start(self, h):
            if h not in self._inflight:
                async with self._lock:
                    with self._aux_lock:
                        fut = await self._spawn(h)
                self._inflight[h] = fut
            for x in self.gen():
                self.counts.update(x)

        def gen(self):
            yield 1

        async def leaky(self, n):
            tok = await self.bucket.acquire(n)
            try:
                return await self.up(n)
            finally:
                self.bucket.refund(n)
"""


def test_new_summary_fields_are_byte_deterministic():
    src = textwrap.dedent(CONCURRENCY_RICH)
    a = summary_json(summarize_tree(ast.parse(src), "garage_tpu/m.py"))
    b = summary_json(summarize_tree(ast.parse(src), "garage_tpu/m.py"))
    assert a == b
    payload = json.loads(a)
    fn = payload["functions"]["F.start"]
    # the ISSUE 14 fields exist and carry structure
    assert fn["accesses"] and fn["lock_acqs"]
    assert payload["classes"]["F"]["blocking_api"] is True
    assert any(ev["k"] == "a" and ev["locks"]
               for ev in fn["accesses"])


def test_summary_version_bumped_for_concurrency_fields():
    # stale-cache schema drift was a PR 9 review find: any cached
    # v<3 summary lacks accesses/lock_acqs/ctx and MUST be recomputed
    assert SUMMARY_VERSION >= 3
    src = "def f():\n    return 1\n"
    s = summarize_tree(ast.parse(src), "garage_tpu/m.py")
    fn = s["functions"]["f"]
    for field in ("accesses", "lock_acqs", "ret_names", "blocking_api"):
        assert field in fn


def test_gl11v2_partial_record_in_scope_does_not_crash():
    """Review regression: thread-hop/partial unwrapping synthesizes an
    extra call record — GL11's release-event scan must see its
    exit-path ctx like any other record (it used to KeyError and kill
    the whole lint run)."""
    vs = run("""
        from functools import partial
        class F:
            async def bad(self, n):
                cb = partial(self._cleanup)
                tok = await self.bucket.acquire(n)
                resp = await self.upstream(n)
                self.bucket.refund(n)
                return resp
            def _cleanup(self):
                self.bucket.release()
    """)
    assert "GL11" in rules_of(vs)


def test_gl13_multi_item_with_records_each_lock():
    """Review regression: `async with a, b:` acquires b while a is
    held — the most idiomatic multi-lock form must contribute the
    a -> b edge (only the last item used to be recorded)."""
    vs = run("""
        class F:
            async def a(self):
                async with self._lock_a, self._lock_b:
                    pass
            async def b(self):
                async with self._lock_b:
                    async with self._lock_a:
                        pass
    """)
    assert rules_of(vs) == ["GL13"]
