"""K2V: DVVS semantics, causality tokens, insert routing, poll.

Ref parity targets: src/model/k2v/causality.rs (token round-trip test
vector), item_table.rs (DVVS update/discard/merge), rpc.rs (routed
inserts keep vector clocks bounded; read-your-write via tokens).
"""

import asyncio

from garage_tpu.model.k2v import (CausalContext, DvvsEntry, K2VItem,
                                  make_node_id, partition_pk)
from garage_tpu.utils.data import gen_uuid

from test_model import make_garage_cluster, stop_all, wait_until  # noqa


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# ---- causality tokens ----------------------------------------------------


def test_causality_token_roundtrip():
    # the reference's own test vector (causality.rs tests)
    ct = CausalContext({4: 42, 1928131023: 76, 0xEFC0C1C47F9DE433: 2})
    assert CausalContext.parse(ct.serialize()) == ct
    assert CausalContext.parse("") is None
    assert CausalContext.parse("garbage!!") is None
    # checksum catches corruption
    tok = ct.serialize()
    bad = ("A" if tok[0] != "A" else "B") + tok[1:]
    assert CausalContext.parse(bad) != ct


def test_causality_newer_than():
    a = CausalContext({1: 5})
    b = CausalContext({1: 3, 2: 1})
    assert a.is_newer_than(b)
    assert b.is_newer_than(a)  # concurrent: each has something new
    c = CausalContext({1: 5, 2: 1})
    assert not a.is_newer_than(c)
    assert not b.is_newer_than(c)


# ---- DVVS semantics ------------------------------------------------------


def test_dvvs_update_and_discard():
    node_a, node_b = gen_uuid(), gen_uuid()
    item = K2VItem(gen_uuid(), "pk", "sk")
    item.update(node_a, None, b"v1", 0)
    assert item.live_values() == [b"v1"]
    # concurrent write on another node without context -> conflict
    item.update(node_b, None, b"v2", 0)
    assert sorted(item.live_values()) == [b"v1", b"v2"]
    # write WITH the merged context discards both
    ct = item.causal_context()
    item.update(node_a, ct, b"v3", 0)
    assert item.live_values() == [b"v3"]
    # delete with context -> tombstone
    item.update(node_b, item.causal_context(), None, 0)
    assert item.is_tombstone()


def test_dvvs_merge_commutative_idempotent():
    node_a, node_b = gen_uuid(), gen_uuid()
    base = K2VItem(gen_uuid(), "p", "s")
    base.update(node_a, None, b"x", 0)
    i1 = base.merge(K2VItem(base.bucket_id, "p", "s"))
    i2 = K2VItem(base.bucket_id, "p", "s")
    i2.update(node_b, None, b"y", 0)
    m12 = i1.merge(i2)
    m21 = i2.merge(i1)
    assert sorted(m12.live_values()) == sorted(m21.live_values()) \
        == [b"x", b"y"]
    assert m12.merge(i2).pack() == m12.pack()  # idempotent


def test_dvvs_entry_encoding_roundtrip():
    e = DvvsEntry(5, [(7, b"abc"), (9, None)])
    assert DvvsEntry.unpack(e.pack()).pack() == e.pack()
    item = K2VItem(gen_uuid(), "pk", "sk",
                   {make_node_id(gen_uuid()): e})
    from garage_tpu.utils import migrate

    assert migrate.decode(K2VItem, migrate.encode(item)).pack() \
        == item.pack()


# ---- cluster: routed inserts + read-your-write + poll --------------------


def test_k2v_cluster_insert_read_delete(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=3, rf=3)
        g0 = garages[0]
        try:
            bucket_id = gen_uuid()
            await g0.k2v_rpc.insert(bucket_id, "part", "key1", None,
                                    b"hello")
            item = await g0.k2v_item_table.get(
                partition_pk(bucket_id, "part"), b"key1")
            assert item is not None and item.live_values() == [b"hello"]
            # vector clock carries exactly ONE node id (the storage
            # node that applied it) — the point of insert routing
            assert len(item.causal_context().vector_clock) == 1

            # read-your-write from another node using the token
            item2 = await garages[1].k2v_item_table.get(
                partition_pk(bucket_id, "part"), b"key1")
            ct = item2.causal_context()
            await garages[1].k2v_rpc.insert(bucket_id, "part", "key1",
                                            ct, b"world")
            item3 = await garages[2].k2v_item_table.get(
                partition_pk(bucket_id, "part"), b"key1")
            assert item3.live_values() == [b"world"]

            # delete
            await g0.k2v_rpc.insert(bucket_id, "part", "key1",
                                    item3.causal_context(), None)
            item4 = await g0.k2v_item_table.get(
                partition_pk(bucket_id, "part"), b"key1")
            assert item4.is_tombstone()
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_k2v_conflicting_writes_coexist(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=3, rf=3)
        g0 = garages[0]
        try:
            bucket_id = gen_uuid()
            # two writes with NO causality token = concurrent
            await g0.k2v_rpc.insert(bucket_id, "p", "k", None, b"a")
            await garages[1].k2v_rpc.insert(bucket_id, "p", "k", None,
                                            b"b")
            item = await g0.k2v_item_table.get(
                partition_pk(bucket_id, "p"), b"k")
            assert sorted(item.live_values()) == [b"a", b"b"]
            # resolving write discards both
            await g0.k2v_rpc.insert(bucket_id, "p", "k",
                                    item.causal_context(), b"resolved")
            item2 = await g0.k2v_item_table.get(
                partition_pk(bucket_id, "p"), b"k")
            assert item2.live_values() == [b"resolved"]
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_k2v_insert_batch_and_counters(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=3, rf=3)
        g0 = garages[0]
        try:
            bucket_id = gen_uuid()
            await g0.k2v_rpc.insert_batch(bucket_id, [
                ("pa", "k1", None, b"1"),
                ("pa", "k2", None, b"22"),
                ("pb", "k1", None, b"333"),
            ])
            for pk, sk, want in (("pa", "k1", b"1"), ("pa", "k2", b"22"),
                                 ("pb", "k1", b"333")):
                item = await g0.k2v_item_table.get(
                    partition_pk(bucket_id, pk), sk.encode())
                assert item.live_values() == [want], (pk, sk)
            # index counters converge
            nodes = list(g0.system.layout_manager.history
                         .all_nongateway_nodes())
            vals = {}
            for _ in range(100):
                vals = await g0.k2v_counter.read(bucket_id, b"pa", nodes)
                if vals.get("entries") == 2:
                    break
                await asyncio.sleep(0.05)
            assert vals.get("entries") == 2
            assert vals.get("bytes") == 3  # len("1") + len("22")
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_k2v_poll_item_wakes_on_write(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=3, rf=3)
        g0 = garages[0]
        try:
            bucket_id = gen_uuid()
            await g0.k2v_rpc.insert(bucket_id, "p", "k", None, b"v1")
            item = await g0.k2v_item_table.get(
                partition_pk(bucket_id, "p"), b"k")
            ct = item.causal_context()

            async def poller():
                return await garages[1].k2v_rpc.poll_item(
                    bucket_id, "p", "k", ct, timeout=20.0)

            task = asyncio.create_task(poller())
            await asyncio.sleep(0.2)
            assert not task.done()
            await g0.k2v_rpc.insert(bucket_id, "p", "k", ct, b"v2")
            got = await asyncio.wait_for(task, 20.0)
            assert got is not None and b"v2" in got.live_values()

            # poll with up-to-date token times out -> None
            item2 = await g0.k2v_item_table.get(
                partition_pk(bucket_id, "p"), b"k")
            got2 = await garages[1].k2v_rpc.poll_item(
                bucket_id, "p", "k", item2.causal_context(), timeout=0.5)
            assert got2 is None
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_k2v_counters_track_overwrite_and_delete(tmp_path):
    """Regression: counter deltas must not alias old/new on the routed
    local-insert path (overwrite/delete previously left stale stats)."""
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=1, rf=1)
        g0 = garages[0]
        try:
            bucket_id = gen_uuid()
            nodes = list(g0.system.layout_manager.history
                         .all_nongateway_nodes())

            async def counters():
                for _ in range(100):
                    v = await g0.k2v_counter.read(bucket_id, b"p", nodes)
                    if v:
                        return v
                    await asyncio.sleep(0.02)
                return {}

            await g0.k2v_rpc.insert(bucket_id, "p", "k", None, b"xxxx")
            v = await counters()
            assert v.get("entries") == 1 and v.get("bytes") == 4
            item = await g0.k2v_item_table.get(
                partition_pk(bucket_id, "p"), b"k")
            # overwrite with a longer value: bytes must follow
            await g0.k2v_rpc.insert(bucket_id, "p", "k",
                                    item.causal_context(), b"y" * 10)
            for _ in range(100):
                v = await g0.k2v_counter.read(bucket_id, b"p", nodes)
                if v.get("bytes") == 10:
                    break
                await asyncio.sleep(0.02)
            assert v.get("bytes") == 10 and v.get("entries") == 1
            # delete: entries drops to 0
            item2 = await g0.k2v_item_table.get(
                partition_pk(bucket_id, "p"), b"k")
            await g0.k2v_rpc.insert(bucket_id, "p", "k",
                                    item2.causal_context(), None)
            for _ in range(100):
                v = await g0.k2v_counter.read(bucket_id, b"p", nodes)
                if v.get("entries") == 0:
                    break
                await asyncio.sleep(0.02)
            assert v.get("entries") == 0 and v.get("bytes") == 0
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_k2v_reverse_prefix_and_pagination(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=1, rf=1)
        g0 = garages[0]
        try:
            bucket_id = gen_uuid()
            await g0.k2v_rpc.insert_batch(bucket_id, [
                ("p", sk, None, b"v") for sk in
                ("a1", "a2", "a3", "b1", "b2")
            ])
            pk = partition_pk(bucket_id, "p")
            # reverse with prefix, no start: must return a3, a2, a1
            items = await g0.k2v_item_table.get_range(
                pk, None, flt={"type": "item"}, limit=10, reverse=True,
                prefix_sk=b"a")
            assert [i.sort_key_str for i in items] == ["a3", "a2", "a1"]
            # forward with exclusive end
            items = await g0.k2v_item_table.get_range(
                pk, None, flt={"type": "item"}, limit=10, end_sk=b"a3")
            assert [i.sort_key_str for i in items] == ["a1", "a2"]
            # reverse with exclusive end
            items = await g0.k2v_item_table.get_range(
                pk, None, flt={"type": "item"}, limit=10, reverse=True,
                end_sk=b"a2")
            assert [i.sort_key_str for i in items] == ["b2", "b1", "a3"]
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_k2v_poll_range_wakes_and_resumes(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=3, rf=3)
        g0 = garages[0]
        try:
            bucket_id = gen_uuid()
            await g0.k2v_rpc.insert(bucket_id, "p", "a1", None, b"v1")
            # first poll with empty marker returns existing items
            res = await g0.k2v_rpc.poll_range(
                bucket_id, "p", None, None, None, None, timeout=5.0)
            assert res is not None
            items, marker = res
            assert [i.sort_key_str for i in items] == ["a1"]

            # nothing new -> timeout
            res2 = await garages[1].k2v_rpc.poll_range(
                bucket_id, "p", None, None, None, marker, timeout=0.5)
            assert res2 is None

            # a write in range wakes the poller
            async def poller():
                return await garages[1].k2v_rpc.poll_range(
                    bucket_id, "p", None, None, None, marker,
                    timeout=20.0)

            task = asyncio.create_task(poller())
            await asyncio.sleep(0.2)
            assert not task.done()
            await g0.k2v_rpc.insert(bucket_id, "p", "a2", None, b"v2")
            got = await asyncio.wait_for(task, 20.0)
            assert got is not None
            items2, marker2 = got
            assert any(i.sort_key_str == "a2" for i in items2)

            # prefix filter excludes out-of-range writes
            res3_task = asyncio.create_task(garages[2].k2v_rpc.poll_range(
                bucket_id, "p", "a", None, None, marker2, timeout=1.0))
            await asyncio.sleep(0.1)
            await g0.k2v_rpc.insert(bucket_id, "p", "zzz", None, b"out")
            res3 = await asyncio.wait_for(res3_task, 10.0)
            assert res3 is None  # 'zzz' not under prefix 'a'
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_seen_marker_roundtrip():
    from garage_tpu.model.k2v.causality import CausalContext
    from garage_tpu.model.k2v.seen import RangeSeenMarker

    m = RangeSeenMarker()
    m.update("k1", CausalContext({5: 10}))
    m.update("k2", CausalContext({5: 3, 9: 1}))
    m2 = RangeSeenMarker.parse(m.serialize())
    assert m2.seen == m.seen
    assert not m2.is_new("k1", CausalContext({5: 10}))
    assert m2.is_new("k1", CausalContext({5: 11}))
    assert m2.is_new("k3", CausalContext({1: 1}))
    assert RangeSeenMarker.parse("!!bad!!") is None
    assert RangeSeenMarker.parse("").seen == {}


def test_k2v_poll_item_wakes_on_delete(tmp_path):
    """ref parity: poll.rs — a DELETE is a change like any other: a
    poller blocked on the pre-delete causality token must wake and see
    the tombstone (empty live values), not time out."""
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=3, rf=3)
        g0 = garages[0]
        try:
            bucket_id = gen_uuid()
            await g0.k2v_rpc.insert(bucket_id, "p", "k", None, b"v1")
            item = await g0.k2v_item_table.get(
                partition_pk(bucket_id, "p"), b"k")
            ct = item.causal_context()

            task = asyncio.create_task(garages[1].k2v_rpc.poll_item(
                bucket_id, "p", "k", ct, timeout=20.0))
            await asyncio.sleep(0.2)
            assert not task.done()
            await g0.k2v_rpc.insert(bucket_id, "p", "k", ct, None)  # delete
            got = await asyncio.wait_for(task, 20.0)
            assert got is not None and got.live_values() == []
        finally:
            await stop_all(garages, tasks)

    run(main())
