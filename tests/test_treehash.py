"""BLAKE3 tree hash tests (ops/treehash.py)."""

import numpy as np
import pytest

from garage_tpu.ops import treehash

# Published blake3 test vector: hash of the empty input.
EMPTY_B3 = "af1349b9f5f9a1a6a0404dea36dcc9499bcb25c9adc112b7cc9a93cae41f3262"


def vector_input(n: int) -> bytes:
    """The official blake3 test-vector input pattern: bytes i % 251."""
    return bytes(i % 251 for i in range(n))


class TestPythonReference:
    def test_empty_vector(self):
        assert treehash.blake3_py(b"").hex() == EMPTY_B3

    def test_deterministic_and_distinct(self):
        a = treehash.blake3_py(b"hello")
        assert a == treehash.blake3_py(b"hello")
        assert a != treehash.blake3_py(b"hellp")
        assert len(a) == 32

    def test_chunk_boundaries_distinct(self):
        # Different lengths straddling chunk/block boundaries all distinct
        seen = set()
        for n in (0, 1, 63, 64, 65, 1023, 1024, 1025, 2048, 3072):
            seen.add(treehash.blake3_py(vector_input(n)))
        assert len(seen) == 10


class TestJaxMatchesReference:
    @pytest.mark.parametrize(
        "n",
        [0, 1, 31, 64, 65, 128, 1023, 1024, 1025, 2047, 2048, 2049,
         3 * 1024, 5 * 1024 + 7, 8 * 1024, 16 * 1024 + 1],
    )
    def test_lengths(self, n):
        data = vector_input(n)
        got = treehash.blake3_many([data])[0]
        assert got.hex() == treehash.blake3_py(data).hex(), f"len={n}"

    def test_batch_mixed_lengths(self):
        blobs = [vector_input(n) for n in (0, 10, 1024, 1500, 1500, 4096, 100)]
        got = treehash.blake3_many(blobs)
        want = [treehash.blake3_py(b) for b in blobs]
        assert [g.hex() for g in got] == [w.hex() for w in want]

    def test_batch_same_chunkcount_shares_program(self):
        # 1500 and 2000 bytes are both 2 chunks — one device call
        before = treehash._hash_fn.cache_info().currsize
        treehash.blake3_many([vector_input(1500), vector_input(2000)])
        after = treehash._hash_fn.cache_info().currsize
        assert after <= before + 1

    def test_hash_batch_jax_shape(self):
        msgs = np.zeros((3, 2048), dtype=np.uint8)
        out = treehash.hash_batch_jax(msgs, np.array([1025, 1500, 2048]))
        assert out.shape == (3, 32)
        assert out[2].tobytes().hex() == treehash.blake3_py(bytes(2048)).hex()

    def test_hash_batch_jax_rejects_wrong_chunk_count(self):
        msgs = np.zeros((1, 2048), dtype=np.uint8)
        with pytest.raises(ValueError):
            treehash.hash_batch_jax(msgs, np.array([0]))
