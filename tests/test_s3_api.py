"""S3 conformance suite against a real forked server process.

Ref parity: src/garage/tests/common/garage.rs:20-247 (forked-server
harness) + src/garage/tests/s3/*. One single-node server process is
booted per module with replication_factor=1; requests are made with the
independent signer in tests/s3util.py (never the repo's own signature
code).
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import socket
import subprocess
import sys
import time
import xml.etree.ElementTree as ET

import pytest

from s3util import S3Client, xml_error_code, xml_find

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:
    import cryptography  # noqa: F401
    HAVE_CRYPTO = True
except ModuleNotFoundError:
    HAVE_CRYPTO = False

# SSE-C genuinely needs AES-GCM from the cryptography wheel; the server
# answers 501 NotImplemented without it (api/s3/encryption.py)
requires_crypto = pytest.mark.skipif(
    not HAVE_CRYPTO, reason="needs the cryptography wheel (SSE-C)")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class Server:
    def __init__(self, tmpdir: str, db_engine: str = "sqlite"):
        self.dir = tmpdir
        self.db_engine = db_engine
        self.rpc_port = free_port()
        self.s3_port = free_port()
        self.admin_port = free_port()
        self.web_port = free_port()
        self.k2v_port = free_port()
        self.config_path = os.path.join(tmpdir, "garage.toml")
        with open(self.config_path, "w") as f:
            f.write(f"""
metadata_dir = "{tmpdir}/meta"
data_dir = "{tmpdir}/data"
replication_factor = 1
block_size = 65536
rpc_bind_addr = "127.0.0.1:{self.rpc_port}"
rpc_public_addr = "127.0.0.1:{self.rpc_port}"

[s3_api]
api_bind_addr = "127.0.0.1:{self.s3_port}"
s3_region = "garage"
root_domain = ".s3.garage.test"

[k2v_api]
api_bind_addr = "127.0.0.1:{self.k2v_port}"

[admin]
api_bind_addr = "127.0.0.1:{self.admin_port}"
admin_token = "test-admin-token"

[web]
bind_addr = "127.0.0.1:{self.web_port}"
root_domain = ".web.garage.test"

[metadata]
db_engine = "{db_engine}"
""")
        self.proc: subprocess.Popen | None = None
        self.key_id = ""
        self.secret = ""

    def start(self) -> None:
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                   PYTHONUNBUFFERED="1")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "garage_tpu.cli.server",
             "--config", self.config_path, "--log-level", "warning"],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            line = self.proc.stdout.readline()
            if "ready" in line:
                return
            if self.proc.poll() is not None:
                raise RuntimeError(
                    "server died: " + (line + self.proc.stdout.read()))
        raise RuntimeError("server did not come up")

    def cli(self, *args: str) -> str:
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "garage_tpu.cli.main",
             "--config", self.config_path, *args],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=60)
        if r.returncode != 0:
            raise RuntimeError(f"cli {args} failed: {r.stdout}{r.stderr}")
        return r.stdout

    def setup_layout_and_key(self) -> None:
        out = self.cli("status")
        node_id = next(line.split()[-1] for line in out.splitlines()
                       if line.startswith("node id:"))
        self.cli("layout", "assign", node_id, "-z", "dc1", "-c", "1G")
        self.cli("layout", "apply")
        out = self.cli("key", "new", "--name", "test")
        for line in out.splitlines():
            if line.startswith("Key ID:"):
                self.key_id = line.split()[-1]
            if line.startswith("Secret key:"):
                self.secret = line.split()[-1]
        self.cli("key", "allow", self.key_id, "--create-bucket")

    def stop(self) -> None:
        if self.proc and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    srv = Server(str(tmp_path_factory.mktemp("s3srv")))
    srv.start()
    try:
        srv.setup_layout_and_key()
        yield srv
    finally:
        srv.stop()


@pytest.fixture(scope="module")
def client(server) -> S3Client:
    c = S3Client("127.0.0.1", server.s3_port, server.key_id, server.secret)
    status, _, body = c.request("PUT", "/conformance")
    assert status == 200, body
    return c


# ---- bucket ops ---------------------------------------------------------


def test_create_bucket_and_list(client):
    status, _, body = client.request("GET", "/")
    assert status == 200
    assert "conformance" in xml_find(body, "Name")


def test_create_bucket_requires_permission(server, client):
    # a fresh key without allow_create_bucket must get AccessDenied
    out = server.cli("key", "new", "--name", "nocreate")
    kid = sec = None
    for line in out.splitlines():
        if line.startswith("Key ID:"):
            kid = line.split()[-1]
        if line.startswith("Secret key:"):
            sec = line.split()[-1]
    c2 = S3Client("127.0.0.1", server.s3_port, kid, sec)
    status, _, body = c2.request("PUT", "/forbidden-bucket")
    assert status == 403
    assert xml_error_code(body) == "AccessDenied"


def test_bucket_location(client):
    status, _, body = client.request("GET", "/conformance",
                                     query=[("location", "")])
    assert status == 200
    assert b"LocationConstraint" in body


def test_delete_nonempty_bucket_fails(client):
    client.request("PUT", "/delme")
    client.request("PUT", "/delme/obj", body=b"x" * 10)
    status, _, body = client.request("DELETE", "/delme")
    assert status == 409
    client.request("DELETE", "/delme/obj")
    status, _, _ = client.request("DELETE", "/delme")
    assert status == 204


def test_bad_signature_rejected(server):
    bad = S3Client("127.0.0.1", server.s3_port, server.key_id,
                   "0" * 64)
    status, _, _ = bad.request("GET", "/")
    assert status == 403


def test_no_such_key_in_credential(server):
    ghost = S3Client("127.0.0.1", server.s3_port, "GK" + "0" * 24,
                     "0" * 64)
    status, _, _ = ghost.request("GET", "/")
    assert status == 403


# ---- object basics ------------------------------------------------------


def test_put_get_roundtrip_inline(client):
    body = b"tiny object"
    status, hdrs, _ = client.request("PUT", "/conformance/inline", body=body)
    assert status == 200
    etag = hdrs["etag"].strip('"')
    assert etag == hashlib.md5(body).hexdigest()
    status, hdrs, got = client.request("GET", "/conformance/inline")
    assert status == 200
    assert got == body
    assert hdrs["etag"].strip('"') == etag
    assert int(hdrs["content-length"]) == len(body)


def test_put_get_roundtrip_blocks(client):
    body = os.urandom(300_000)  # > block_size 64 KiB → multi-block
    status, _, _ = client.request("PUT", "/conformance/big", body=body)
    assert status == 200
    status, hdrs, got = client.request("GET", "/conformance/big")
    assert status == 200
    assert got == body
    assert int(hdrs["content-length"]) == len(body)


def test_head_object(client):
    client.request("PUT", "/conformance/headme", body=b"h" * 100)
    status, hdrs, body = client.request("HEAD", "/conformance/headme")
    assert status == 200
    assert int(hdrs["content-length"]) == 100
    assert body == b""


def test_get_missing_object_404(client):
    status, _, body = client.request("GET", "/conformance/nope")
    assert status == 404
    assert xml_error_code(body) == "NoSuchKey"


def test_get_missing_bucket_404(client):
    status, _, body = client.request("GET", "/nonexistent-bucket/key")
    assert status == 404
    assert xml_error_code(body) == "NoSuchBucket"


def test_delete_object(client):
    client.request("PUT", "/conformance/doomed", body=b"bye")
    status, _, _ = client.request("DELETE", "/conformance/doomed")
    assert status == 204
    status, _, _ = client.request("GET", "/conformance/doomed")
    assert status == 404


def test_put_overwrites(client):
    client.request("PUT", "/conformance/over", body=b"v1")
    client.request("PUT", "/conformance/over", body=b"v2-longer")
    status, _, got = client.request("GET", "/conformance/over")
    assert status == 200
    assert got == b"v2-longer"


def test_content_md5_enforced(client):
    import base64

    good = base64.b64encode(hashlib.md5(b"data").digest()).decode()
    status, _, _ = client.request("PUT", "/conformance/md5ok",
                                  headers={"content-md5": good},
                                  body=b"data")
    assert status == 200
    bad = base64.b64encode(hashlib.md5(b"other").digest()).decode()
    status, _, _ = client.request("PUT", "/conformance/md5bad",
                                  headers={"content-md5": bad},
                                  body=b"data")
    assert status == 400


def test_x_amz_checksum_header(client):
    import base64
    import zlib

    body = b"checksummed payload"
    crc = base64.b64encode(
        zlib.crc32(body).to_bytes(4, "big")).decode()
    status, _, _ = client.request(
        "PUT", "/conformance/ck", body=body,
        headers={"x-amz-checksum-crc32": crc})
    assert status == 200
    status, _, _ = client.request(
        "PUT", "/conformance/ckbad", body=body,
        headers={"x-amz-checksum-crc32": "AAAAAA=="})
    assert status == 400


def test_metadata_roundtrip(client):
    client.request("PUT", "/conformance/meta", body=b"m",
                   headers={"content-type": "application/x-custom",
                            "x-amz-meta-hello": "world"})
    status, hdrs, _ = client.request("GET", "/conformance/meta")
    assert status == 200
    assert hdrs["content-type"] == "application/x-custom"
    assert hdrs.get("x-amz-meta-hello") == "world"


# ---- range + conditional ------------------------------------------------


def test_range_get(client):
    body = os.urandom(200_000)
    client.request("PUT", "/conformance/range", body=body)
    status, hdrs, got = client.request(
        "GET", "/conformance/range", headers={"range": "bytes=1000-1999"})
    assert status == 206
    assert got == body[1000:2000]
    assert hdrs["content-range"] == f"bytes 1000-1999/{len(body)}"
    # suffix range
    status, _, got = client.request(
        "GET", "/conformance/range", headers={"range": "bytes=-500"})
    assert status == 206
    assert got == body[-500:]
    # unsatisfiable
    status, _, _ = client.request(
        "GET", "/conformance/range",
        headers={"range": f"bytes={len(body) + 10}-"})
    assert status == 416


def test_multi_range_rejected_416(client):
    """bytes=a-b,c-d: this server serves single ranges only; silently
    answering with just the first range hands the client a body it
    didn't ask for, so the whole spec is rejected."""
    body = os.urandom(50_000)
    client.request("PUT", "/conformance/mrange", body=body)
    status, hdrs, _ = client.request(
        "GET", "/conformance/mrange",
        headers={"range": "bytes=0-0,5-9"})
    assert status == 416
    assert hdrs["content-range"] == f"bytes */{len(body)}"
    # a single range with a trailing comma is still one range
    status, _, got = client.request(
        "GET", "/conformance/mrange", headers={"range": "bytes=0-4,"})
    assert status == 206 and got == body[:5]


def test_get_readahead_runtime_toggle(server, client):
    """Admin /v1/s3/tuning flips the GET readahead depth at runtime;
    multi-block reads must be byte-identical at every setting (the
    bench sweeps this knob the same way)."""
    body = os.urandom(300_000)  # ~5 blocks at the 64 KiB test block size
    client.request("PUT", "/conformance/rahead", body=body)
    st, got = _admin(server, "GET", "/v1/s3/tuning")
    assert st == 200
    assert got["get_readahead_blocks"] == 3  # config default
    assert got["put_blocks_max_parallel"] == 3
    try:
        for depth in (0, 1, 3):
            st, got = _admin(server, "POST", "/v1/s3/tuning",
                             body={"get_readahead_blocks": depth})
            assert st == 200 and got["get_readahead_blocks"] == depth
            st, _, data = client.request("GET", "/conformance/rahead")
            assert st == 200 and data == body
            st, _, data = client.request(
                "GET", "/conformance/rahead",
                headers={"range": "bytes=70000-250000"})
            assert st == 206 and data == body[70000:250001]
        st, _ = _admin(server, "POST", "/v1/s3/tuning",
                       body={"put_blocks_max_parallel": 0})
        assert st == 400
        st, _ = _admin(server, "POST", "/v1/s3/tuning",
                       body={"bogus_knob": 1})
        assert st == 400
        # atomic: a rejected update must not partially apply
        st, _ = _admin(server, "POST", "/v1/s3/tuning",
                       body={"get_readahead_blocks": 9,
                             "put_blocks_max_parallel": 0})
        assert st == 400
        st, got = _admin(server, "GET", "/v1/s3/tuning")
        assert got["get_readahead_blocks"] == 3  # untouched by the 400
    finally:
        _admin(server, "POST", "/v1/s3/tuning",
               body={"get_readahead_blocks": 3})


def test_read_cache_runtime_toggle(server, client):
    """Admin /v1/s3/tuning resizes/disables the hot-block read cache at
    runtime; GETs must stay byte-identical in every state, hits must
    move on warm reads, and a 0 budget must fully disable."""
    body = os.urandom(200_000)
    client.request("PUT", "/conformance/cached", body=body)
    st, got = _admin(server, "GET", "/v1/s3/tuning")
    assert st == 200
    default_max = got["read_cache_max_bytes"]
    assert default_max > 0  # sized off block_ram_buffer_max by default
    try:
        h0 = got["read_cache"]["hits"]
        st, _, data = client.request("GET", "/conformance/cached")
        assert st == 200 and data == body
        st, got = _admin(server, "GET", "/v1/s3/tuning")
        # PUT write-through made the first GET a cache hit
        assert got["read_cache"]["hits"] > h0
        # disable: reads still correct, counters frozen
        st, got = _admin(server, "POST", "/v1/s3/tuning",
                         body={"read_cache_max_bytes": 0})
        assert st == 200 and got["read_cache_max_bytes"] == 0
        assert got["read_cache"]["bytes"] == 0  # disabled = cleared
        frozen = got["read_cache"]["hits"]
        st, _, data = client.request("GET", "/conformance/cached")
        assert st == 200 and data == body
        st, got = _admin(server, "GET", "/v1/s3/tuning")
        assert got["read_cache"]["hits"] == frozen
        # admission knob bounds are validated
        st, _ = _admin(server, "POST", "/v1/s3/tuning",
                       body={"read_cache_probation_pct": 95})
        assert st == 400
        st, _ = _admin(server, "POST", "/v1/s3/tuning",
                       body={"read_cache_max_bytes": -1})
        assert st == 400
        # re-enable: a cold read fills, a warm read hits again
        st, _ = _admin(server, "POST", "/v1/s3/tuning",
                       body={"read_cache_max_bytes": default_max,
                             "read_cache_probation_pct": 20})
        assert st == 200
        client.request("GET", "/conformance/cached")
        st, got = _admin(server, "GET", "/v1/s3/tuning")
        h1 = got["read_cache"]["hits"]
        st, _, data = client.request("GET", "/conformance/cached")
        assert data == body
        st, got = _admin(server, "GET", "/v1/s3/tuning")
        assert got["read_cache"]["hits"] > h1
    finally:
        _admin(server, "POST", "/v1/s3/tuning",
               body={"read_cache_max_bytes": default_max})


@requires_crypto
def test_ssec_objects_never_enter_read_cache(server, client):
    """SSE-C payloads are excluded from the hot-block cache on both the
    PUT write-through and the GET miss-fill paths."""
    st, got = _admin(server, "GET", "/v1/s3/tuning")
    inserts0 = got["read_cache"]["inserts"]
    data = os.urandom(150_000)
    st, _, _ = client.request("PUT", "/conformance/uncachedsecret",
                              body=data, headers=_sse_headers())
    assert st == 200
    st, _, got_body = client.request("GET", "/conformance/uncachedsecret",
                                     headers=_sse_headers())
    assert st == 200 and got_body == data
    st, got = _admin(server, "GET", "/v1/s3/tuning")
    assert got["read_cache"]["inserts"] == inserts0


def test_conditional_get(client):
    client.request("PUT", "/conformance/cond", body=b"conditional")
    status, hdrs, _ = client.request("GET", "/conformance/cond")
    etag = hdrs["etag"]
    status, _, _ = client.request("GET", "/conformance/cond",
                                  headers={"if-none-match": etag})
    assert status == 304
    status, _, got = client.request("GET", "/conformance/cond",
                                    headers={"if-none-match": '"zzz"'})
    assert status == 200
    status, _, _ = client.request("GET", "/conformance/cond",
                                  headers={"if-match": '"zzz"'})
    assert status == 412
    status, _, _ = client.request("GET", "/conformance/cond",
                                  headers={"if-none-match": "*"})
    assert status == 304
    status, _, got = client.request("GET", "/conformance/cond",
                                    headers={"if-match": "*"})
    assert status == 200


# ---- listing ------------------------------------------------------------


@pytest.fixture(scope="module")
def listing_bucket(client):
    client.request("PUT", "/listing")
    for k in ("a/1", "a/2", "b/1", "b/2", "b/3", "c"):
        client.request("PUT", f"/listing/{k}", body=b"x")
    return "/listing"


def test_list_v2_all(client, listing_bucket):
    status, _, body = client.request("GET", listing_bucket,
                                     query=[("list-type", "2")])
    assert status == 200
    keys = xml_find(body, "Key")
    assert keys == ["a/1", "a/2", "b/1", "b/2", "b/3", "c"]


def test_list_v2_prefix_delimiter(client, listing_bucket):
    status, _, body = client.request(
        "GET", listing_bucket,
        query=[("list-type", "2"), ("delimiter", "/")])
    assert status == 200
    assert xml_find(body, "Key") == ["c"]
    root = ET.fromstring(body)
    common = [el.find("./{*}Prefix").text for el in root.iter()
              if el.tag.split("}")[-1] == "CommonPrefixes"]
    assert sorted(common) == ["a/", "b/"]
    status, _, body = client.request(
        "GET", listing_bucket,
        query=[("list-type", "2"), ("prefix", "b/")])
    assert xml_find(body, "Key") == ["b/1", "b/2", "b/3"]


def test_list_v2_pagination(client, listing_bucket):
    keys, token = [], None
    for _ in range(10):
        q = [("list-type", "2"), ("max-keys", "2")]
        if token:
            q.append(("continuation-token", token))
        status, _, body = client.request("GET", listing_bucket, query=q)
        assert status == 200
        keys += xml_find(body, "Key")
        truncated = xml_find(body, "IsTruncated")[0] == "true"
        if not truncated:
            break
        token = xml_find(body, "NextContinuationToken")[0]
    assert keys == ["a/1", "a/2", "b/1", "b/2", "b/3", "c"]


def test_list_v1_marker_pagination(client, listing_bucket):
    keys, marker = [], None
    for _ in range(10):
        q = [("max-keys", "2")]
        if marker:
            q.append(("marker", marker))
        status, _, body = client.request("GET", listing_bucket, query=q)
        assert status == 200
        page = xml_find(body, "Key")
        keys += page
        if xml_find(body, "IsTruncated")[0] != "true":
            break
        marker = page[-1]
    assert keys == ["a/1", "a/2", "b/1", "b/2", "b/3", "c"]


def test_list_start_after(client, listing_bucket):
    status, _, body = client.request(
        "GET", listing_bucket,
        query=[("list-type", "2"), ("start-after", "b/1")])
    assert xml_find(body, "Key") == ["b/2", "b/3", "c"]


def _common_prefixes(body) -> list:
    root = ET.fromstring(body)
    return sorted(el.find("./{*}Prefix").text for el in root.iter()
                  if el.tag.split("}")[-1] == "CommonPrefixes")


def test_list_v2_prefix_rollup_across_page_boundary(client,
                                                    listing_bucket):
    """max-keys=1 with a delimiter cuts the page right AFTER each
    folded common prefix; the continuation token must resume past the
    whole prefix (skip-scan), never re-emitting it or leaking a key
    from under it (ISSUE 7)."""
    got_keys, got_prefixes, token = [], [], None
    for _ in range(10):
        q = [("list-type", "2"), ("delimiter", "/"), ("max-keys", "1")]
        if token:
            q.append(("continuation-token", token))
        status, _, body = client.request("GET", listing_bucket, query=q)
        assert status == 200
        got_keys += xml_find(body, "Key")
        got_prefixes += _common_prefixes(body)
        if xml_find(body, "IsTruncated")[0] != "true":
            break
        token = xml_find(body, "NextContinuationToken")[0]
    assert got_keys == ["c"]
    assert got_prefixes == ["a/", "b/"]


def test_list_v2_continuation_token_overrides_start_after(
        client, listing_bucket):
    """AWS: when both are present, continuation-token wins and
    start-after is ignored (it only seeds the FIRST request)."""
    status, _, body = client.request(
        "GET", listing_bucket,
        query=[("list-type", "2"), ("max-keys", "2"),
               ("start-after", "a/1")])
    assert xml_find(body, "Key") == ["a/2", "b/1"]
    token = xml_find(body, "NextContinuationToken")[0]
    # a start-after far past the token's position must not matter
    status, _, body = client.request(
        "GET", listing_bucket,
        query=[("list-type", "2"), ("continuation-token", token),
               ("start-after", "zzz")])
    assert status == 200
    assert xml_find(body, "Key") == ["b/2", "b/3", "c"]


def test_list_v2_prefix_containing_delimiter(client):
    """prefix 'b/' itself contains the delimiter: folding must apply to
    the remainder AFTER the prefix only (b/sub/ folds, b/1 doesn't)."""
    client.request("PUT", "/edgelist")
    for k in ("b/1", "b/2", "b/sub/x", "b/sub/y", "b/zub/q"):
        client.request("PUT", f"/edgelist/{k}", body=b"x")
    status, _, body = client.request(
        "GET", "/edgelist",
        query=[("list-type", "2"), ("prefix", "b/"), ("delimiter", "/")])
    assert status == 200
    assert xml_find(body, "Key") == ["b/1", "b/2"]
    assert _common_prefixes(body) == ["b/sub/", "b/zub/"]
    for k in ("b/1", "b/2", "b/sub/x", "b/sub/y", "b/zub/q"):
        client.request("DELETE", f"/edgelist/{k}")
    client.request("DELETE", "/edgelist")


def test_admin_metadata_endpoint(server, client, listing_bucket):
    """GET /v1/metadata: per-engine internals + per-table depths +
    resize-phase readout in one operator call (ISSUE 7)."""
    st, got = _admin(server, "GET", "/v1/metadata")
    assert st == 200
    assert got["engine"]["engine"] == "sqlite"  # this server's config
    assert got["engine"]["rows"] > 0
    assert "object" in got["tables"]
    assert got["tables"]["object"]["rows"] >= 6  # the listing fixture
    assert "resize_phase_seconds" in got
    # auth required like every management route
    st, _ = _admin(server, "GET", "/v1/metadata", token=None)
    assert st == 403


def test_list_v2_max_keys_zero(client, listing_bucket):
    """AWS: max-keys=0 returns an empty, never-truncated page."""
    status, _, body = client.request(
        "GET", listing_bucket,
        query=[("list-type", "2"), ("max-keys", "0")])
    assert status == 200
    assert xml_find(body, "Key") == []
    assert xml_find(body, "KeyCount") == ["0"]
    assert xml_find(body, "IsTruncated") == ["false"]


def test_list_uploads_delimiter_page_boundary(client):
    """A multipart-uploads page that fills right at a folded common
    prefix resumes past the WHOLE prefix via the key-marker (the 'p'
    cursor: marker == the prefix, no upload-id-marker)."""
    made = []
    for k in ("updl/u/a", "updl/u/b", "updl/v"):
        _, _, body = client.request("POST", f"/conformance/{k}",
                                    query=[("uploads", "")])
        made.append((k, xml_find(body, "UploadId")[0]))
    q = [("uploads", ""), ("prefix", "updl/"), ("delimiter", "/"),
         ("max-uploads", "1")]
    status, _, body = client.request("GET", "/conformance", query=q)
    assert status == 200
    assert _common_prefixes(body) == ["updl/u/"]
    assert xml_find(body, "Key") == []
    assert xml_find(body, "IsTruncated") == ["true"]
    nk = xml_find(body, "NextKeyMarker")[0]
    assert nk == "updl/u/"
    assert not xml_find(body, "NextUploadIdMarker")
    status, _, body = client.request(
        "GET", "/conformance",
        query=[("uploads", ""), ("prefix", "updl/"), ("delimiter", "/"),
               ("key-marker", nk)])
    assert xml_find(body, "Key") == ["updl/v"]
    assert _common_prefixes(body) == []
    assert xml_find(body, "IsTruncated") == ["false"]
    for k, u in made:
        client.request("DELETE", f"/conformance/{k}",
                       query=[("uploadId", u)])


# ---- delete objects (batch) --------------------------------------------


def test_delete_objects_batch(client):
    client.request("PUT", "/conformance/bd1", body=b"1")
    client.request("PUT", "/conformance/bd2", body=b"2")
    payload = (b"<Delete><Object><Key>bd1</Key></Object>"
               b"<Object><Key>bd2</Key></Object>"
               b"<Object><Key>bd-missing</Key></Object></Delete>")
    status, _, body = client.request("POST", "/conformance",
                                     query=[("delete", "")], body=payload)
    assert status == 200
    deleted = xml_find(body, "Key")
    assert "bd1" in deleted and "bd2" in deleted
    status, _, _ = client.request("GET", "/conformance/bd1")
    assert status == 404


# ---- copy ---------------------------------------------------------------


def test_copy_object(client):
    body = os.urandom(150_000)
    client.request("PUT", "/conformance/src", body=body)
    status, _, rbody = client.request(
        "PUT", "/conformance/dst",
        headers={"x-amz-copy-source": "/conformance/src"})
    assert status == 200
    assert b"CopyObjectResult" in rbody
    status, _, got = client.request("GET", "/conformance/dst")
    assert got == body


def test_copy_source_preconditions(client):
    """x-amz-copy-source-if-* on CopyObject: every failing condition is
    a 412 (ref: copy.rs:50-60 + get.rs check_copy_source)."""
    body = os.urandom(20_000)
    client.request("PUT", "/conformance/precond-src", body=body)
    st, hdrs, _ = client.request("HEAD", "/conformance/precond-src")
    etag = hdrs["etag"]  # quoted
    lastmod = hdrs["last-modified"]
    past = "Mon, 01 Jan 2001 00:00:00 GMT"
    future = "Fri, 01 Jan 2100 00:00:00 GMT"
    src = {"x-amz-copy-source": "/conformance/precond-src"}

    def copy(extra):
        st, _, b = client.request("PUT", "/conformance/precond-dst",
                                  headers={**src, **extra})
        return st, b

    # if-match
    assert copy({"x-amz-copy-source-if-match": etag})[0] == 200
    assert copy({"x-amz-copy-source-if-match": "*"})[0] == 200
    st, b = copy({"x-amz-copy-source-if-match": '"beef"'})
    assert st == 412 and xml_error_code(b) == "PreconditionFailed"
    # if-none-match
    assert copy({"x-amz-copy-source-if-none-match": '"beef"'})[0] == 200
    assert copy({"x-amz-copy-source-if-none-match": etag})[0] == 412
    assert copy({"x-amz-copy-source-if-none-match": "*"})[0] == 412
    # if-modified-since (412 when NOT modified since — no 304 on copy)
    assert copy({"x-amz-copy-source-if-modified-since": past})[0] == 200
    assert copy({"x-amz-copy-source-if-modified-since": future})[0] == 412
    # if-unmodified-since
    assert copy({"x-amz-copy-source-if-unmodified-since": future})[0] == 200
    assert copy({"x-amz-copy-source-if-unmodified-since": lastmod})[0] == 200
    assert copy({"x-amz-copy-source-if-unmodified-since": past})[0] == 412
    # RFC 7232 order: a passing if-match shadows if-unmodified-since
    assert copy({"x-amz-copy-source-if-match": etag,
                 "x-amz-copy-source-if-unmodified-since": past})[0] == 200
    # a fresh dst write really happened on the 200s
    _, _, got = client.request("GET", "/conformance/precond-dst")
    assert got == body


def test_precondition_edge_cases(client):
    """Unquoted client ETags match (the reference strips quotes);
    malformed dates are a 400; page-size params validate."""
    client.request("PUT", "/conformance/precond-edge", body=b"edge")
    st, hdrs, _ = client.request("HEAD", "/conformance/precond-edge")
    bare_etag = hdrs["etag"].strip('"')
    src = {"x-amz-copy-source": "/conformance/precond-edge"}
    # unquoted if-match accepted
    st, _, _ = client.request(
        "PUT", "/conformance/precond-edge-dst",
        headers={**src, "x-amz-copy-source-if-match": bare_etag})
    assert st == 200
    # unquoted if-none-match still 412s on a match
    st, _, _ = client.request(
        "PUT", "/conformance/precond-edge-dst",
        headers={**src, "x-amz-copy-source-if-none-match": bare_etag})
    assert st == 412
    # malformed date -> 400 (ref get.rs PreconditionHeaders::parse)
    st, _, b = client.request(
        "PUT", "/conformance/precond-edge-dst",
        headers={**src, "x-amz-copy-source-if-modified-since": "nonsense"})
    assert st == 400 and xml_error_code(b) == "InvalidArgument"
    st, _, _ = client.request("GET", "/conformance/precond-edge",
                              headers={"if-modified-since": "nonsense"})
    assert st == 400
    # unquoted GET if-none-match
    st, _, _ = client.request("GET", "/conformance/precond-edge",
                              headers={"if-none-match": bare_etag})
    assert st == 304


def test_page_size_param_validation(client):
    # max-keys=0: legal, empty page, not truncated
    st, _, body = client.request("GET", "/conformance",
                                 query=[("list-type", "2"),
                                        ("max-keys", "0")])
    assert st == 200
    assert xml_find(body, "IsTruncated") == ["false"]
    assert not xml_find(body, "Contents")
    # max-uploads / max-parts < 1: 400, not an infinite-pagination trap
    st, _, b = client.request("GET", "/conformance",
                              query=[("uploads", ""), ("max-uploads", "0")])
    assert st == 400 and xml_error_code(b) == "InvalidArgument"
    _, _, b = client.request("POST", "/conformance/pgzero",
                             query=[("uploads", "")])
    upload_id = xml_find(b, "UploadId")[0]
    st, _, b = client.request(
        "GET", "/conformance/pgzero",
        query=[("uploadId", upload_id), ("max-parts", "0")])
    assert st == 400 and xml_error_code(b) == "InvalidArgument"
    st, _, b = client.request(
        "GET", "/conformance", query=[("uploads", ""),
                                      ("max-uploads", "junk")])
    assert st == 400
    client.request("DELETE", "/conformance/pgzero",
                   query=[("uploadId", upload_id)])


def test_upload_part_copy_preconditions(client):
    """Same headers gate UploadPartCopy (ref: copy.rs:347-363)."""
    body = os.urandom(12_000)
    client.request("PUT", "/conformance/precond-src2", body=body)
    st, hdrs, _ = client.request("HEAD", "/conformance/precond-src2")
    etag = hdrs["etag"]
    _, _, b = client.request("POST", "/conformance/precond-mp",
                             query=[("uploads", "")])
    upload_id = xml_find(b, "UploadId")[0]
    q = [("partNumber", "1"), ("uploadId", upload_id)]
    st, _, b = client.request(
        "PUT", "/conformance/precond-mp", query=q,
        headers={"x-amz-copy-source": "/conformance/precond-src2",
                 "x-amz-copy-source-if-match": '"beef"'})
    assert st == 412 and xml_error_code(b) == "PreconditionFailed"
    st, _, b = client.request(
        "PUT", "/conformance/precond-mp", query=q,
        headers={"x-amz-copy-source": "/conformance/precond-src2",
                 "x-amz-copy-source-if-match": etag})
    assert st == 200 and xml_find(b, "ETag")
    client.request("DELETE", "/conformance/precond-mp",
                   query=[("uploadId", upload_id)])


# ---- multipart ----------------------------------------------------------


def test_multipart_complete(client):
    status, _, body = client.request("POST", "/conformance/mp",
                                     query=[("uploads", "")])
    assert status == 200
    upload_id = xml_find(body, "UploadId")[0]
    parts = [os.urandom(120_000), os.urandom(90_000)]
    etags = []
    for i, p in enumerate(parts, start=1):
        status, hdrs, _ = client.request(
            "PUT", "/conformance/mp",
            query=[("partNumber", str(i)), ("uploadId", upload_id)],
            body=p)
        assert status == 200
        etags.append(hdrs["etag"].strip('"'))
    xml_parts = "".join(
        f"<Part><PartNumber>{i}</PartNumber><ETag>\"{e}\"</ETag></Part>"
        for i, e in enumerate(etags, start=1))
    status, _, body = client.request(
        "POST", "/conformance/mp", query=[("uploadId", upload_id)],
        body=f"<CompleteMultipartUpload>{xml_parts}</CompleteMultipartUpload>".encode())
    assert status == 200, body
    expect_etag = hashlib.md5(
        b"".join(bytes.fromhex(e) for e in etags)).hexdigest() + "-2"
    assert xml_find(body, "ETag")[0].strip('"') == expect_etag
    status, _, got = client.request("GET", "/conformance/mp")
    assert got == parts[0] + parts[1]


def test_multipart_list_parts_and_uploads(client):
    status, _, body = client.request("POST", "/conformance/mp2",
                                     query=[("uploads", "")])
    upload_id = xml_find(body, "UploadId")[0]
    client.request("PUT", "/conformance/mp2",
                   query=[("partNumber", "1"), ("uploadId", upload_id)],
                   body=b"p" * 70_000)
    status, _, body = client.request("GET", "/conformance",
                                     query=[("uploads", "")])
    assert status == 200
    assert upload_id in xml_find(body, "UploadId")
    status, _, body = client.request(
        "GET", "/conformance/mp2", query=[("uploadId", upload_id)])
    assert status == 200
    assert xml_find(body, "PartNumber") == ["1"]
    # abort
    status, _, _ = client.request(
        "DELETE", "/conformance/mp2", query=[("uploadId", upload_id)])
    assert status == 204
    status, _, body = client.request(
        "GET", "/conformance/mp2", query=[("uploadId", upload_id)])
    assert status == 404


def test_list_uploads_pagination_over_1000(client):
    """>1000 concurrent uploads page correctly through
    NextKeyMarker/NextUploadIdMarker (ref: list.rs:169-265)."""
    made = set()
    for i in range(1001):
        _, _, body = client.request("POST", f"/conformance/pgu/k{i:04d}",
                                    query=[("uploads", "")])
        made.add((f"pgu/k{i:04d}", xml_find(body, "UploadId")[0]))
    seen = set()
    q = [("uploads", ""), ("prefix", "pgu/")]
    pages = 0
    while True:
        status, _, body = client.request("GET", "/conformance", query=q)
        assert status == 200
        keys = xml_find(body, "Key")
        uids = xml_find(body, "UploadId")
        assert len(keys) == len(uids)
        for k, u in zip(keys, uids):
            assert (k, u) not in seen, "duplicate across pages"
            seen.add((k, u))
        pages += 1
        if xml_find(body, "IsTruncated")[0] != "true":
            break
        nk = xml_find(body, "NextKeyMarker")[0]
        q = [("uploads", ""), ("prefix", "pgu/"), ("key-marker", nk)]
        nu = xml_find(body, "NextUploadIdMarker")
        if nu:
            q.append(("upload-id-marker", nu[0]))
        assert pages < 10
    assert pages == 2  # 1000 + 1
    assert seen == made
    # cleanup so later listing tests aren't polluted
    for k, u in made:
        client.request("DELETE", f"/conformance/{k}",
                       query=[("uploadId", u)])


def test_list_uploads_same_key_marker_resume(client):
    """Several uploads on ONE key: a small page size forces the
    mid-key upload-id-marker cursor; delimiter folding pages too."""
    uids = set()
    for _ in range(5):
        _, _, body = client.request("POST", "/conformance/pgm/dup",
                                    query=[("uploads", "")])
        uids.add(xml_find(body, "UploadId")[0])
    got = []
    q = [("uploads", ""), ("prefix", "pgm/"), ("max-uploads", "2")]
    while True:
        status, _, body = client.request("GET", "/conformance", query=q)
        assert status == 200
        assert len(xml_find(body, "UploadId")) <= 2
        got += [u for u in xml_find(body, "UploadId")
                if u not in ("include",)]
        if xml_find(body, "IsTruncated")[0] != "true":
            break
        q = [("uploads", ""), ("prefix", "pgm/"), ("max-uploads", "2"),
             ("key-marker", xml_find(body, "NextKeyMarker")[0])]
        nu = xml_find(body, "NextUploadIdMarker")
        if nu:
            q.append(("upload-id-marker", nu[0]))
    assert len(got) == 5 and set(got) == uids
    assert got == sorted(got)  # same-key uploads in upload-id order

    # delimiter folding with paging: two folded prefixes + one upload
    for k in ("pgd/a/1", "pgd/a/2", "pgd/b/3"):
        client.request("POST", f"/conformance/{k}", query=[("uploads", "")])
    _, _, body = client.request("POST", "/conformance/pgd/c",
                                query=[("uploads", "")])
    c_uid = xml_find(body, "UploadId")[0]
    status, _, body = client.request(
        "GET", "/conformance",
        query=[("uploads", ""), ("prefix", "pgd/"), ("delimiter", "/"),
               ("max-uploads", "2")])
    assert xml_find(body, "Prefix") == ["pgd/", "pgd/a/", "pgd/b/"]
    assert xml_find(body, "IsTruncated")[0] == "true"
    status, _, body = client.request(
        "GET", "/conformance",
        query=[("uploads", ""), ("prefix", "pgd/"), ("delimiter", "/"),
               ("max-uploads", "2"),
               ("key-marker", xml_find(body, "NextKeyMarker")[0])])
    assert xml_find(body, "UploadId") == [c_uid]
    assert xml_find(body, "IsTruncated")[0] == "false"


def test_list_parts_pagination_over_1000(client):
    """1002 parts: default page returns 1000 + NextPartNumberMarker;
    the second page returns the rest (ref: list.rs fetch_part_info)."""
    _, _, body = client.request("POST", "/conformance/pgparts",
                                query=[("uploads", "")])
    upload_id = xml_find(body, "UploadId")[0]
    for pn in range(1, 1003):
        status, _, _ = client.request(
            "PUT", "/conformance/pgparts",
            query=[("partNumber", str(pn)), ("uploadId", upload_id)],
            body=b"x")
        assert status == 200
    status, _, body = client.request(
        "GET", "/conformance/pgparts", query=[("uploadId", upload_id)])
    assert status == 200
    pns = [int(p) for p in xml_find(body, "PartNumber")]
    assert pns == list(range(1, 1001))
    assert xml_find(body, "IsTruncated")[0] == "true"
    assert xml_find(body, "NextPartNumberMarker") == ["1000"]
    status, _, body = client.request(
        "GET", "/conformance/pgparts",
        query=[("uploadId", upload_id), ("part-number-marker", "1000")])
    pns2 = [int(p) for p in xml_find(body, "PartNumber")]
    assert pns2 == [1001, 1002]
    assert xml_find(body, "IsTruncated")[0] == "false"
    # small-page walk collects exactly the full set
    marker, walked = 0, []
    while True:
        status, _, body = client.request(
            "GET", "/conformance/pgparts",
            query=[("uploadId", upload_id), ("max-parts", "300"),
                   ("part-number-marker", str(marker))])
        walked += [int(p) for p in xml_find(body, "PartNumber")]
        if xml_find(body, "IsTruncated")[0] != "true":
            break
        marker = int(xml_find(body, "NextPartNumberMarker")[0])
    assert walked == list(range(1, 1003))
    client.request("DELETE", "/conformance/pgparts",
                   query=[("uploadId", upload_id)])


def test_multipart_complete_wrong_etag(client):
    status, _, body = client.request("POST", "/conformance/mp3",
                                     query=[("uploads", "")])
    upload_id = xml_find(body, "UploadId")[0]
    client.request("PUT", "/conformance/mp3",
                   query=[("partNumber", "1"), ("uploadId", upload_id)],
                   body=b"z" * 70_000)
    status, _, body = client.request(
        "POST", "/conformance/mp3", query=[("uploadId", upload_id)],
        body=(b"<CompleteMultipartUpload><Part><PartNumber>1</PartNumber>"
              b"<ETag>\"beef\"</ETag></Part></CompleteMultipartUpload>"))
    assert status == 400
    assert xml_error_code(body) == "InvalidPart"


def test_multipart_part_checksum(client):
    import base64

    status, _, body = client.request("POST", "/conformance/mpck",
                                     query=[("uploads", "")])
    upload_id = xml_find(body, "UploadId")[0]
    part = b"p" * 70_000
    digest = base64.b64encode(hashlib.sha256(part).digest()).decode()
    status, _, _ = client.request(
        "PUT", "/conformance/mpck",
        query=[("partNumber", "1"), ("uploadId", upload_id)],
        headers={"x-amz-checksum-sha256": digest}, body=part)
    assert status == 200
    status, _, _ = client.request(
        "PUT", "/conformance/mpck",
        query=[("partNumber", "2"), ("uploadId", upload_id)],
        headers={"x-amz-checksum-sha256": base64.b64encode(
            hashlib.sha256(b"wrong").digest()).decode()},
        body=part)
    assert status == 400


def test_multipart_unknown_upload(client):
    status, _, body = client.request(
        "PUT", "/conformance/mpx",
        query=[("partNumber", "1"), ("uploadId", "00" * 32)],
        body=b"x")
    assert status == 404
    assert xml_error_code(body) == "NoSuchUpload"


# ---- streaming signatures ----------------------------------------------


def test_chunked_signed_put(client):
    chunks = [os.urandom(70_000), os.urandom(30_000), b"tail"]
    status, _, body = client.put_chunked("/conformance/chunked", chunks)
    assert status == 200, body
    status, _, got = client.request("GET", "/conformance/chunked")
    assert got == b"".join(chunks)


def test_chunked_put_respects_block_size(server, client):
    """Client aws-chunks BIGGER than the server block size must still be
    re-chunked to block_size blocks (AwsChunkedReader returns whole
    decoded client chunks; the Chunker carries the overshoot)."""
    chunks = [os.urandom(200_000), os.urandom(150_000)]
    status, _, body = client.put_chunked("/conformance/bigchunk", chunks)
    assert status == 200, body
    status, _, got = client.request("GET", "/conformance/bigchunk")
    assert got == b"".join(chunks)
    # every stored block file obeys the configured 64 KiB block size
    too_big = []
    for root, _dirs, files in os.walk(os.path.join(server.dir, "data")):
        for fn in files:
            sz = os.path.getsize(os.path.join(root, fn))
            if sz > 65536 + 1024:  # header/compression slack
                too_big.append((fn, sz))
    assert not too_big, too_big


def test_chunked_bad_signature_rejected(client):
    status, _, _ = client.put_chunked(
        "/conformance/chunked-bad", [b"data" * 1000],
        corrupt_chunk_sig=True)
    assert status in (400, 403)
    status, _, _ = client.request("GET", "/conformance/chunked-bad")
    assert status == 404


def test_chunked_signed_trailer_put(client):
    import base64
    import zlib

    chunks = [os.urandom(80_000), b"end"]
    payload = b"".join(chunks)
    crc = base64.b64encode(zlib.crc32(payload).to_bytes(4, "big")).decode()
    status, _, body = client.put_chunked(
        "/conformance/trailer", chunks,
        trailer=("x-amz-checksum-crc32", crc))
    assert status == 200, body
    status, _, got = client.request("GET", "/conformance/trailer")
    assert got == payload


def test_chunked_trailer_bad_checksum(client):
    status, _, _ = client.put_chunked(
        "/conformance/trailer-bad", [b"payload" * 1000],
        trailer=("x-amz-checksum-crc32", "AAAAAA=="))
    assert status == 400


def test_unsigned_trailer_put(client):
    import base64
    import zlib

    payload = os.urandom(90_000)
    crc = base64.b64encode(zlib.crc32(payload).to_bytes(4, "big")).decode()
    status, _, body = client.put_unsigned_trailer(
        "/conformance/utrailer", [payload],
        trailer=("x-amz-checksum-crc32", crc))
    assert status == 200, body
    status, _, got = client.request("GET", "/conformance/utrailer")
    assert got == payload


# ---- presigned ----------------------------------------------------------


def test_presigned_get(client):
    client.request("PUT", "/conformance/presigned", body=b"presigned!")
    url = client.presign("GET", "/conformance/presigned")
    status, _, got = client.raw("GET", url)
    assert status == 200
    assert got == b"presigned!"


def test_presigned_put(client):
    url = client.presign("PUT", "/conformance/presput")
    status, _, _ = client.raw("PUT", url, body=b"via presigned url")
    assert status == 200
    status, _, got = client.request("GET", "/conformance/presput")
    assert got == b"via presigned url"


def test_presigned_bad_signature(client):
    url = client.presign("GET", "/conformance/presigned")
    url = url[:-4] + ("aaaa" if not url.endswith("aaaa") else "bbbb")
    status, _, _ = client.raw("GET", url)
    assert status == 403


def test_anonymous_rejected(client):
    status, _, _ = client.raw("GET", "/conformance/inline")
    assert status == 403


# ---- website / CORS -----------------------------------------------------

WEBSITE_XML = b"""<?xml version="1.0" encoding="UTF-8"?>
<WebsiteConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">
  <IndexDocument><Suffix>index.html</Suffix></IndexDocument>
  <ErrorDocument><Key>error.html</Key></ErrorDocument>
</WebsiteConfiguration>"""

CORS_XML = b"""<?xml version="1.0" encoding="UTF-8"?>
<CORSConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">
  <CORSRule>
    <AllowedOrigin>https://example.com</AllowedOrigin>
    <AllowedMethod>GET</AllowedMethod>
    <AllowedHeader>x-custom</AllowedHeader>
    <ExposeHeader>etag</ExposeHeader>
    <MaxAgeSeconds>3600</MaxAgeSeconds>
  </CORSRule>
</CORSConfiguration>"""


def _web_get(server, host, path, method="GET", headers=None):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", server.web_port,
                                      timeout=30)
    try:
        h = {"host": host}
        h.update(headers or {})
        conn.request(method, path, headers=h)
        r = conn.getresponse()
        return r.status, {k.lower(): v for k, v in r.getheaders()}, r.read()
    finally:
        conn.close()


@pytest.fixture(scope="module")
def website_bucket(server, client):
    status, _, body = client.request("PUT", "/wsite")
    assert status == 200, body
    status, _, body = client.request(
        "PUT", "/wsite", query=[("website", "")], body=WEBSITE_XML)
    assert status == 200, body
    for key, content in [("index.html", b"<h1>home</h1>"),
                         ("error.html", b"<h1>custom error</h1>"),
                         ("docs/index.html", b"<h1>docs</h1>"),
                         ("page.html", b"<h1>page</h1>")]:
        status, _, body = client.request("PUT", f"/wsite/{key}",
                                         body=content)
        assert status == 200, body
    return "wsite.web.garage.test"


def test_get_bucket_website_roundtrip(client, website_bucket):
    status, _, body = client.request("GET", "/wsite",
                                     query=[("website", "")])
    assert status == 200
    assert xml_find(body, "Suffix") == ["index.html"]
    assert xml_find(body, "Key") == ["error.html"]


def test_website_serves_index_and_keys(server, website_bucket):
    status, _, body = _web_get(server, website_bucket, "/")
    assert status == 200 and body == b"<h1>home</h1>"
    status, _, body = _web_get(server, website_bucket, "/page.html")
    assert status == 200 and body == b"<h1>page</h1>"
    status, _, body = _web_get(server, website_bucket, "/docs/")
    assert status == 200 and body == b"<h1>docs</h1>"


def test_website_implicit_redirect(server, website_bucket):
    status, headers, _ = _web_get(server, website_bucket, "/docs")
    assert status == 302
    assert headers["location"] == "/docs/"


def test_website_error_document(server, website_bucket):
    status, _, body = _web_get(server, website_bucket, "/missing.html")
    assert status == 404
    assert body == b"<h1>custom error</h1>"


def test_website_head(server, website_bucket):
    status, headers, body = _web_get(server, website_bucket, "/page.html",
                                     method="HEAD")
    assert status == 200 and body == b""
    assert headers["content-length"] == str(len(b"<h1>page</h1>"))


def test_website_not_configured(server, client):
    status, _, body = client.request("PUT", "/nosite")
    assert status == 200, body
    status, _, _ = _web_get(server, "nosite.web.garage.test", "/")
    assert status == 404


def test_website_delete_config(server, client, website_bucket):
    status, _, _ = client.request("PUT", "/wsite2")
    assert status == 200
    status, _, _ = client.request("PUT", "/wsite2",
                                  query=[("website", "")],
                                  body=WEBSITE_XML)
    assert status == 200
    status, _, _ = client.request("DELETE", "/wsite2",
                                  query=[("website", "")])
    assert status == 204
    status, _, body = client.request("GET", "/wsite2",
                                     query=[("website", "")])
    assert status == 404
    assert xml_error_code(body) == "NoSuchWebsiteConfiguration"


def test_cors_crud_and_preflight(server, client, website_bucket):
    status, _, body = client.request("PUT", "/wsite",
                                     query=[("cors", "")], body=CORS_XML)
    assert status == 200, body
    status, _, body = client.request("GET", "/wsite", query=[("cors", "")])
    assert status == 200
    assert xml_find(body, "AllowedOrigin") == ["https://example.com"]
    # preflight on the website endpoint
    status, headers, _ = _web_get(
        server, website_bucket, "/page.html", method="OPTIONS",
        headers={"origin": "https://example.com",
                 "access-control-request-method": "GET"})
    assert status == 200
    assert headers["access-control-allow-origin"] == "https://example.com"
    # denied origin
    status, _, _ = _web_get(
        server, website_bucket, "/page.html", method="OPTIONS",
        headers={"origin": "https://evil.example",
                 "access-control-request-method": "GET"})
    assert status == 403
    # actual response carries CORS headers
    status, headers, _ = _web_get(server, website_bucket, "/page.html",
                                  headers={"origin": "https://example.com"})
    assert status == 200
    assert headers.get("access-control-allow-origin") == "https://example.com"
    status, _, _ = client.request("DELETE", "/wsite", query=[("cors", "")])
    assert status == 204
    status, _, body = client.request("GET", "/wsite", query=[("cors", "")])
    assert status == 404


# ---- ops CLI (repair / block / meta / worker) ---------------------------


def test_cli_worker_get_set(server):
    out = server.cli("worker", "get")
    assert "resync-tranquility" in out
    out = server.cli("worker", "set", "resync-tranquility", "2.5")
    assert "2.5" in out
    out = server.cli("worker", "get", "resync-tranquility")
    assert "2.5" in out
    # erasure deep-scrub toggle (runtime-only)
    out = server.cli("worker", "get", "scrub-deep")
    assert "1" in out
    out = server.cli("worker", "set", "scrub-deep", "0")
    out = server.cli("worker", "get", "scrub-deep")
    assert "0" in out


def test_cli_repair_and_block_ops(server, client):
    out = server.cli("repair", "versions")
    assert "launched" in out
    out = server.cli("repair", "tables")
    assert "queued" in out
    out = server.cli("block", "list-errors")
    assert "hash" in out  # header prints even when empty
    # block info for a real stored block
    client.request("PUT", "/conformance/blockinfo",
                   body=os.urandom(100_000))
    # find its first block hash through stats-free path: list-errors empty,
    # so use repair scrub start/pause/resume as smoke instead
    out = server.cli("repair", "scrub", "pause")
    assert "scrub pause" in out
    out = server.cli("repair", "scrub", "resume")
    assert "scrub resume" in out


def test_cli_meta_snapshot(server):
    out = server.cli("meta", "snapshot")
    assert "snapshot written to" in out
    path = out.strip().split()[-1]
    assert os.path.basename(os.path.dirname(path)) == "snapshots"


# ---- K2V API (driven with the standalone k2v_client SDK) ----------------


@pytest.fixture(scope="module")
def k2v(server, client):
    from garage_tpu.k2v_client import K2vClient

    status, _, body = client.request("PUT", "/k2vbkt")
    assert status == 200, body
    return K2vClient("127.0.0.1", server.k2v_port, "k2vbkt",
                     server.key_id, server.secret)


def test_k2v_item_roundtrip(k2v):
    from garage_tpu.k2v_client import K2vError

    k2v.insert_item("users", "alice", b'{"age": 30}')
    val = k2v.read_item("users", "alice")
    assert val.value == b'{"age": 30}'
    # read-your-write via causality token
    k2v.insert_item("users", "alice", b'{"age": 31}',
                    causality=val.causality)
    val2 = k2v.read_item("users", "alice")
    assert val2.values == [b'{"age": 31}']
    # delete with token -> the tombstone stays readable as [null] so
    # its causality token can seed a re-insert (ref: item.rs
    # make_response serves DvvsValue::Deleted as JSON null / 204)
    k2v.delete_item("users", "alice", causality=val2.causality)
    val3 = k2v.read_item("users", "alice")
    assert val3.values == [None]
    assert val3.value is None
    # a never-written key is a true 404
    try:
        k2v.read_item("users", "ghost")
        raise AssertionError("expected NoSuchKey")
    except K2vError as e:
        assert e.status == 404


def test_k2v_conflict_surfaces_both_values(k2v):
    k2v.insert_item("conf", "k", b"one")      # no token
    k2v.insert_item("conf", "k", b"two")      # no token: concurrent
    val = k2v.read_item("conf", "k")
    assert sorted(v for v in val.values if v) == [b"one", b"two"]
    k2v.insert_item("conf", "k", b"merged", causality=val.causality)
    assert k2v.read_item("conf", "k").values == [b"merged"]


def test_k2v_batch_and_index(k2v):
    k2v.insert_batch([
        ("idx", "a", b"1", None),
        ("idx", "b", b"2", None),
        ("idx2", "a", b"3", None),
    ])
    res = k2v.read_batch([{"partitionKey": "idx"}])
    assert [i["sk"] for i in res[0]["items"]] == ["a", "b"]
    # counters propagate through the async insert queue
    parts = {}
    for _ in range(100):
        parts = {p.pk: p for p in k2v.read_index(prefix="idx")}
        if "idx" in parts and "idx2" in parts \
                and parts["idx"].entries == 2:
            break
        time.sleep(0.1)
    assert parts["idx"].entries == 2
    assert parts["idx2"].entries == 1
    assert parts["idx"].bytes == 2
    deleted = k2v.delete_batch([{"partitionKey": "idx"}])
    assert deleted[0]["deletedItems"] == 2
    res2 = k2v.read_batch([{"partitionKey": "idx"}])
    assert res2[0]["items"] == []


def test_k2v_poll_item(server, k2v):
    import threading

    k2v.insert_item("poll", "k", b"v1")
    val = k2v.read_item("poll", "k")
    got = {}

    def poller():
        got["val"] = k2v.poll_item("poll", "k", val.causality,
                                   timeout=20.0)

    t = threading.Thread(target=poller)
    t.start()
    time.sleep(0.5)
    k2v.insert_item("poll", "k", b"v2", causality=val.causality)
    t.join(timeout=25.0)
    assert not t.is_alive()
    assert got["val"] is not None and got["val"].values == [b"v2"]


def test_k2v_read_batch_pagination_no_duplicates(k2v):
    k2v.insert_batch([("pages", f"k{i:02d}", b"x", None)
                      for i in range(7)])
    res = k2v.read_batch([{"partitionKey": "pages", "limit": 3}])
    page1 = [i["sk"] for i in res[0]["items"]]
    assert page1 == ["k00", "k01", "k02"]
    assert res[0]["more"] is True
    res2 = k2v.read_batch([{"partitionKey": "pages", "limit": 3,
                            "start": res[0]["nextStart"]}])
    page2 = [i["sk"] for i in res2[0]["items"]]
    assert page2 == ["k03", "k04", "k05"]
    res3 = k2v.read_batch([{"partitionKey": "pages", "limit": 3,
                            "start": res2[0]["nextStart"]}])
    assert [i["sk"] for i in res3[0]["items"]] == ["k06"]
    assert res3[0]["more"] is False


# ---- admin REST API (ref: api/admin/api_server.rs + router_v1.rs) -------


def _admin(server, method, path, body=None, token="test-admin-token"):
    import http.client
    import json as _json

    conn = http.client.HTTPConnection("127.0.0.1", server.admin_port,
                                      timeout=30)
    try:
        headers = {}
        if token:
            headers["authorization"] = f"Bearer {token}"
        payload = _json.dumps(body).encode() if body is not None else b""
        conn.request(method, path, body=payload, headers=headers)
        r = conn.getresponse()
        raw = r.read()
        try:
            return r.status, _json.loads(raw.decode())
        except ValueError:
            return r.status, raw
    finally:
        conn.close()


def test_admin_requires_token(server):
    st, _ = _admin(server, "GET", "/v1/status", token=None)
    assert st == 403
    st, _ = _admin(server, "GET", "/v1/status", token="wrong")
    assert st == 403


def test_admin_status_and_health(server):
    st, body = _admin(server, "GET", "/v1/status")
    assert st == 200
    assert body["clusterHealth"]["status"] == "healthy"
    assert len(body["nodes"]) == 1
    assert body["nodes"][0]["role"]["zone"] == "dc1"
    st, h = _admin(server, "GET", "/v1/health")
    assert st == 200 and h["status"] == "healthy"
    assert h["partitionsQuorum"] == 256


def test_admin_layout_get(server):
    st, body = _admin(server, "GET", "/v1/layout")
    assert st == 200
    assert body["version"] == 1
    assert len(body["roles"]) == 1


def test_admin_key_lifecycle(server):
    st, k = _admin(server, "POST", "/v1/key", body={"name": "rest-key"})
    assert st == 200 and k["accessKeyId"].startswith("GK")
    kid = k["accessKeyId"]
    st, info = _admin(server, "GET",
                      f"/v1/key?id={kid}&showSecretKey=true")
    assert st == 200
    assert info["secretAccessKey"] == k["secretAccessKey"]
    assert info["permissions"]["createBucket"] is False
    st, info = _admin(server, "POST", f"/v1/key?id={kid}",
                      body={"allow": {"createBucket": True}})
    assert st == 200 and info["permissions"]["createBucket"] is True
    st, keys = _admin(server, "GET", "/v1/key")
    assert st == 200 and any(x["id"] == kid for x in keys)
    st, _ = _admin(server, "DELETE", f"/v1/key?id={kid}")
    assert st == 204
    st, _ = _admin(server, "GET", f"/v1/key?id={kid}")
    assert st == 404


def test_admin_bucket_lifecycle_and_aliases(server):
    st, b = _admin(server, "POST", "/v1/bucket",
                   body={"globalAlias": "rest-bucket"})
    assert st == 200
    bid = b["id"]
    st, info = _admin(server, "GET", f"/v1/bucket?id={bid}")
    assert st == 200 and "rest-bucket" in info["globalAliases"]
    # permission grant via REST
    st, k = _admin(server, "POST", "/v1/key", body={"name": "bkey"})
    st, _ = _admin(server, "POST", "/v1/bucket/allow", body={
        "bucketId": bid, "accessKeyId": k["accessKeyId"],
        "permissions": {"read": True, "write": True},
    })
    assert st == 200
    st, info = _admin(server, "GET", f"/v1/bucket?id={bid}")
    assert k["accessKeyId"] in info["keys"]
    # global alias add + remove
    st, _ = _admin(server, "PUT",
                   f"/v1/bucket/alias/global?id={bid}&alias=rest-alias")
    assert st == 200
    st, info = _admin(server, "GET", "/v1/bucket?globalAlias=rest-alias")
    assert st == 200 and info["id"] == bid
    st, _ = _admin(server, "DELETE",
                   f"/v1/bucket/alias/global?id={bid}&alias=rest-alias")
    assert st == 200
    # deleting the LAST alias must fail
    st, err = _admin(server, "DELETE",
                     f"/v1/bucket/alias/global?id={bid}&alias=rest-bucket")
    assert st == 400
    # empty bucket deletes
    st, _ = _admin(server, "DELETE", f"/v1/bucket?id={bid}")
    assert st == 204


def test_admin_check_domain(server, client, website_bucket):
    st, body = _admin(server, "GET", "/check?domain=wsite.web.garage.test")
    assert st == 200
    st, _ = _admin(server, "GET", "/check?domain=nosuch.web.garage.test")
    assert st == 400


def test_metrics_exposition(server, client):
    import http.client

    client.request("PUT", "/conformance/metricsobj", body=b"m" * 100)
    conn = http.client.HTTPConnection("127.0.0.1", server.admin_port,
                                      timeout=30)
    try:
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        text = r.read().decode()
        assert r.status == 200
    finally:
        conn.close()
    assert "cluster_healthy 1" in text
    assert "api_request_duration_seconds_count" in text
    assert "table_put_total_count" in text
    assert "rpc_request_duration_seconds_count" in text
    assert "feeder_batches" in text
    # breadth families (VERDICT r3 #9 / ref: block/metrics.rs:145,
    # table/metrics.rs:132, rpc/system_metrics.rs:302)
    assert "block_bytes_written" in text
    assert "block_bytes_read" in text
    assert "block_corruptions" in text
    assert "block_resync_queue_length" in text
    assert "block_resync_errored_blocks" in text
    assert "block_scrub_corruptions" in text
    assert "block_scrub_deep_stripes_checked" in text
    assert 'table_size_bytes{table="object"}' in text
    assert 'table_rows{table="object"}' in text
    assert "cluster_node_up" in text
    # the single node stores >0 bytes in the object table after a PUT
    import re as _re

    m = _re.search(r'table_size_bytes\{table="object"\} (\d+)', text)
    assert m and int(m.group(1)) > 0


# ---- SSE-C, UploadPartCopy, PostObject ----------------------------------

SSE_KEY = b"0123456789abcdef0123456789abcdef"


def _sse_headers(key=SSE_KEY, prefix=""):
    import base64
    import hashlib as _h

    return {
        f"x-amz-{prefix}server-side-encryption-customer-algorithm": "AES256",
        f"x-amz-{prefix}server-side-encryption-customer-key":
            base64.b64encode(key).decode(),
        f"x-amz-{prefix}server-side-encryption-customer-key-md5":
            base64.b64encode(_h.md5(key).digest()).decode(),
    }


@requires_crypto
def test_ssec_put_get_roundtrip(client, server):
    data = os.urandom(200_000)
    st, hdrs, _ = client.request("PUT", "/conformance/secret",
                                 body=data, headers=_sse_headers())
    assert st == 200
    assert hdrs.get(
        "x-amz-server-side-encryption-customer-algorithm") == "AES256"
    # read with the key
    st, hdrs, got = client.request("GET", "/conformance/secret",
                                   headers=_sse_headers())
    assert st == 200 and got == data
    # range read addresses plaintext offsets
    st, _, got = client.request("GET", "/conformance/secret",
                                headers={**_sse_headers(),
                                         "range": "bytes=1000-1999"})
    assert st == 206 and got == data[1000:2000]
    # read without the key -> 400
    st, _, body = client.request("GET", "/conformance/secret")
    assert st == 400
    # read with the wrong key -> 403
    st, _, _ = client.request("GET", "/conformance/secret",
                              headers=_sse_headers(b"x" * 32))
    assert st == 403
    # on-disk blocks must NOT contain plaintext
    found_plain = False
    for root, _, files in os.walk(os.path.join(server.dir, "data")):
        for fn in files:
            with open(os.path.join(root, fn), "rb") as f:
                if data[:64] in f.read():
                    found_plain = True
    assert not found_plain


@requires_crypto
def test_ssec_inline_object(client):
    st, _, _ = client.request("PUT", "/conformance/tinysecret",
                              body=b"small secret", headers=_sse_headers())
    assert st == 200
    st, _, got = client.request("GET", "/conformance/tinysecret",
                                headers=_sse_headers())
    assert st == 200 and got == b"small secret"
    st, _, _ = client.request("GET", "/conformance/tinysecret")
    assert st == 400


@requires_crypto
def test_ssec_etag_hides_plaintext_md5(client):
    """SSE-C ETags must not be the plaintext MD5 (a queryable plaintext
    digest would let readers dictionary-attack encrypted content)."""
    import hashlib

    small = b"guessable secret"          # inline path
    big = b"B" * 50_000                  # streamed path
    st, hdrs, _ = client.request("PUT", "/conformance/etag-sec-inline",
                                 body=small, headers=_sse_headers())
    assert st == 200
    assert hdrs["etag"].strip('"') != hashlib.md5(small).hexdigest()
    st, hdrs, _ = client.request("PUT", "/conformance/etag-sec-big",
                                 body=big, headers=_sse_headers())
    assert st == 200
    assert hdrs["etag"].strip('"') != hashlib.md5(big).hexdigest()
    # list must show the randomized etag too
    st, _, body = client.request("GET", "/conformance",
                                 query=[("list-type", "2"),
                                        ("prefix", "etag-sec-")])
    assert st == 200
    assert hashlib.md5(small).hexdigest().encode() not in body
    assert hashlib.md5(big).hexdigest().encode() not in body


@requires_crypto
def test_copy_ssec_source_requires_key(client):
    """Plain CopyObject of an SSE-C object (no SSE headers at all) must
    be rejected, not silently duplicate ciphertext."""
    assert client.request("PUT", "/conformance/enc-nokey-src",
                          body=b"s" * 9000,
                          headers=_sse_headers())[0] == 200
    st, _, body = client.request(
        "PUT", "/conformance/enc-nokey-dst",
        headers={"x-amz-copy-source": "/conformance/enc-nokey-src"})
    assert st == 400 and b"InvalidRequest" in body


def test_upload_part_copy(client):
    src = os.urandom(150_000)
    assert client.request("PUT", "/conformance/upc-src", body=src)[0] == 200
    st, _, body = client.request("POST", "/conformance/upc-dst",
                                 query=[("uploads", "")])
    assert st == 200
    upload_id = xml_find(body, "UploadId")[0]
    # part 1: copied byte range; part 2: copied full object
    st, _, body = client.request(
        "PUT", "/conformance/upc-dst",
        query=[("partNumber", "1"), ("uploadId", upload_id)],
        headers={"x-amz-copy-source": "/conformance/upc-src",
                 "x-amz-copy-source-range": "bytes=0-99999"})
    assert st == 200, body
    etag1 = xml_find(body, "ETag")[0].strip('"')
    st, _, body = client.request(
        "PUT", "/conformance/upc-dst",
        query=[("partNumber", "2"), ("uploadId", upload_id)],
        headers={"x-amz-copy-source": "/conformance/upc-src"})
    assert st == 200, body
    etag2 = xml_find(body, "ETag")[0].strip('"')
    complete = (
        '<CompleteMultipartUpload>'
        f'<Part><PartNumber>1</PartNumber><ETag>"{etag1}"</ETag></Part>'
        f'<Part><PartNumber>2</PartNumber><ETag>"{etag2}"</ETag></Part>'
        '</CompleteMultipartUpload>').encode()
    st, _, body = client.request("POST", "/conformance/upc-dst",
                                 query=[("uploadId", upload_id)],
                                 body=complete)
    assert st == 200, body
    st, _, got = client.request("GET", "/conformance/upc-dst")
    assert st == 200
    assert got == src[:100000] + src


@requires_crypto
def test_copy_reencrypt(client):
    data = os.urandom(50_000)
    assert client.request("PUT", "/conformance/plain-src",
                          body=data)[0] == 200
    # plaintext -> SSE-C copy
    st, _, _ = client.request(
        "PUT", "/conformance/enc-copy",
        headers={"x-amz-copy-source": "/conformance/plain-src",
                 **_sse_headers()})
    assert st == 200
    st, _, got = client.request("GET", "/conformance/enc-copy",
                                headers=_sse_headers())
    assert st == 200 and got == data
    # SSE-C -> plaintext copy (decrypting with copy-source headers)
    st, _, _ = client.request(
        "PUT", "/conformance/plain-again",
        headers={"x-amz-copy-source": "/conformance/enc-copy",
                 **_sse_headers(prefix="copy-source-")})
    assert st == 200
    st, _, got = client.request("GET", "/conformance/plain-again")
    assert st == 200 and got == data


def _post_policy_form(server, bucket, key_field, file_body,
                      extra_fields=None, extra_conditions=None,
                      filename="upload.bin"):
    import base64
    import datetime as dt
    import hashlib as _h
    import hmac as _hmac
    import json as _json

    exp = (dt.datetime.now(dt.timezone.utc)
           + dt.timedelta(minutes=5)).strftime("%Y-%m-%dT%H:%M:%SZ")
    date = dt.datetime.now(dt.timezone.utc).strftime("%Y%m%d")
    credential = f"{server.key_id}/{date}/garage/s3/aws4_request"
    conditions = [
        {"bucket": bucket},
        ["starts-with", "$key", key_field.split("${")[0]],
        {"x-amz-credential": credential},
    ] + (extra_conditions or [])
    policy = base64.b64encode(_json.dumps(
        {"expiration": exp, "conditions": conditions}).encode()).decode()
    k = b"AWS4" + server.secret.encode()
    for part in (date, "garage", "s3", "aws4_request"):
        k = _hmac.new(k, part.encode(), _h.sha256).digest()
    sig = _hmac.new(k, policy.encode(), _h.sha256).hexdigest()
    fields = {
        "key": key_field,
        "x-amz-credential": credential,
        "policy": policy,
        "x-amz-signature": sig,
        **(extra_fields or {}),
    }
    boundary = "testboundary123"
    parts = []
    for name, value in fields.items():
        parts.append(
            f'--{boundary}\r\nContent-Disposition: form-data; '
            f'name="{name}"\r\n\r\n{value}\r\n'.encode())
    parts.append(
        f'--{boundary}\r\nContent-Disposition: form-data; name="file"; '
        f'filename="{filename}"\r\n'
        f'Content-Type: application/octet-stream\r\n\r\n'.encode()
        + file_body + b"\r\n")
    parts.append(f"--{boundary}--\r\n".encode())
    body = b"".join(parts)
    return body, f"multipart/form-data; boundary={boundary}"


def test_post_object_upload(server, client):
    import http.client

    payload = os.urandom(80_000)
    body, ctype = _post_policy_form(server, "conformance",
                                    "posted/${filename}", payload,
                                    filename="hello.bin")
    conn = http.client.HTTPConnection("127.0.0.1", server.s3_port,
                                      timeout=30)
    try:
        conn.request("POST", "/conformance", body=body,
                     headers={"content-type": ctype,
                              "host": f"127.0.0.1:{server.s3_port}"})
        r = conn.getresponse()
        assert r.status == 204, r.read()
        r.read()
    finally:
        conn.close()
    st, _, got = client.request("GET", "/conformance/posted/hello.bin")
    assert st == 200 and got == payload


def test_post_object_bad_length_range_bounds(server):
    import http.client

    body, ctype = _post_policy_form(
        server, "conformance", "p3/x", b"data",
        extra_conditions=[["content-length-range", "zero", "many"]])
    conn = http.client.HTTPConnection("127.0.0.1", server.s3_port,
                                      timeout=30)
    try:
        conn.request("POST", "/conformance", body=body,
                     headers={"content-type": ctype})
        r = conn.getresponse()
        # must be a 400 InvalidPolicyDocument, not an uncaught 500
        assert r.status == 400, r.read()
        assert b"InvalidPolicyDocument" in r.read()
    finally:
        conn.close()


def test_post_object_bad_signature_and_policy(server):
    import http.client

    body, ctype = _post_policy_form(server, "conformance", "p2/x",
                                    b"data")
    # corrupt the signature
    body = body.replace(b'name="x-amz-signature"\r\n\r\n',
                        b'name="x-amz-signature"\r\n\r\n0')
    conn = http.client.HTTPConnection("127.0.0.1", server.s3_port,
                                      timeout=30)
    try:
        conn.request("POST", "/conformance", body=body,
                     headers={"content-type": ctype})
        r = conn.getresponse()
        assert r.status == 403
        r.read()
    finally:
        conn.close()
    # field not covered by policy -> denied
    body, ctype = _post_policy_form(server, "conformance", "p2/x",
                                    b"data",
                                    extra_fields={"x-amz-meta-evil": "1"})
    conn = http.client.HTTPConnection("127.0.0.1", server.s3_port,
                                      timeout=30)
    try:
        conn.request("POST", "/conformance", body=body,
                     headers={"content-type": ctype})
        r = conn.getresponse()
        assert r.status == 403
        r.read()
    finally:
        conn.close()


def test_post_object_content_length_range(server, client):
    import http.client

    body, ctype = _post_policy_form(
        server, "conformance", "small/obj", b"x" * 5000,
        extra_conditions=[["content-length-range", 1, 100]])
    conn = http.client.HTTPConnection("127.0.0.1", server.s3_port,
                                      timeout=30)
    try:
        conn.request("POST", "/conformance", body=body,
                     headers={"content-type": ctype})
        r = conn.getresponse()
        assert r.status == 400
        r.read()
    finally:
        conn.close()
    st, _, _ = client.request("GET", "/conformance/small/obj")
    assert st == 404  # nothing persisted


def test_post_object_too_small_preserves_existing(server, client):
    import http.client

    client.request("PUT", "/conformance/keepsafe", body=b"original")
    body, ctype = _post_policy_form(
        server, "conformance", "keepsafe", b"tiny",
        extra_conditions=[["content-length-range", 100, 1000]])
    conn = http.client.HTTPConnection("127.0.0.1", server.s3_port,
                                      timeout=30)
    try:
        conn.request("POST", "/conformance", body=body,
                     headers={"content-type": ctype})
        r = conn.getresponse()
        assert r.status == 400
        r.read()
    finally:
        conn.close()
    # the pre-existing object is untouched
    st, _, got = client.request("GET", "/conformance/keepsafe")
    assert st == 200 and got == b"original"


def test_get_part_number(client):
    part1 = os.urandom(70_000)
    part2 = os.urandom(80_000)
    st, _, body = client.request("POST", "/conformance/pnget",
                                 query=[("uploads", "")])
    upload_id = xml_find(body, "UploadId")[0]
    etags = []
    for i, part in enumerate((part1, part2), start=1):
        st, hdrs, _ = client.request(
            "PUT", "/conformance/pnget",
            query=[("partNumber", str(i)), ("uploadId", upload_id)],
            body=part)
        etags.append(hdrs["etag"].strip('"'))
    complete = ("<CompleteMultipartUpload>" + "".join(
        f'<Part><PartNumber>{i}</PartNumber><ETag>"{e}"</ETag></Part>'
        for i, e in enumerate(etags, start=1))
        + "</CompleteMultipartUpload>").encode()
    st, _, body = client.request("POST", "/conformance/pnget",
                                 query=[("uploadId", upload_id)],
                                 body=complete)
    assert st == 200, body
    st, hdrs, got = client.request("GET", "/conformance/pnget",
                                   query=[("partNumber", "2")])
    assert st == 206
    assert got == part2
    assert hdrs["x-amz-mp-parts-count"] == "2"
    st, _, _ = client.request("GET", "/conformance/pnget",
                              query=[("partNumber", "3")])
    assert st == 416


def test_checksum_stored_and_returned(client):
    import base64
    import zlib as _z

    payload = os.urandom(5000)
    crc = base64.b64encode(_z.crc32(payload).to_bytes(4, "big")).decode()
    st, _, _ = client.request("PUT", "/conformance/ckobj", body=payload,
                              headers={"x-amz-checksum-crc32": crc})
    assert st == 200
    # without checksum-mode: no checksum header
    st, hdrs, _ = client.request("HEAD", "/conformance/ckobj")
    assert "x-amz-checksum-crc32" not in hdrs
    st, hdrs, _ = client.request("HEAD", "/conformance/ckobj",
                                 headers={"x-amz-checksum-mode":
                                          "ENABLED"})
    assert hdrs.get("x-amz-checksum-crc32") == crc


def test_k2v_poll_range_api(server, k2v):
    import threading

    k2v.insert_item("pr", "x1", b"one")
    res = k2v.poll_range("pr", timeout=5.0)
    assert res is not None
    items, marker = res
    assert [i["sk"] for i in items] == ["x1"]
    got = {}

    def poller():
        got["res"] = k2v.poll_range("pr", seen_marker=marker,
                                    timeout=20.0)

    t = threading.Thread(target=poller)
    t.start()
    time.sleep(0.5)
    k2v.insert_item("pr", "x2", b"two")
    t.join(timeout=25.0)
    assert not t.is_alive()
    assert got["res"] is not None
    items2, _ = got["res"]
    assert any(i["sk"] == "x2" for i in items2)


def test_admin_update_bucket_quotas_and_website(server, client):
    """UpdateBucket (ref: api/admin/bucket.rs:405): set quotas + website
    flags via the admin API; quotas are then ENFORCED on PUT."""
    st, _, _ = client.request("PUT", "/quota-bucket")
    assert st == 200
    st, info = _admin(server, "GET", "/v1/bucket?globalAlias=quota-bucket")
    assert st == 200
    bid = info["id"]
    assert info["quotas"] == {"maxSize": None, "maxObjects": None}
    assert info["websiteAccess"] is False

    # set quotas + website config in one UpdateBucket call
    st, info = _admin(server, "PUT", f"/v1/bucket?id={bid}", body={
        "quotas": {"maxSize": 150000, "maxObjects": 2},
        "websiteAccess": {"enabled": True, "indexDocument": "index.html",
                          "errorDocument": "err.html"},
    })
    assert st == 200
    assert info["quotas"] == {"maxSize": 150000, "maxObjects": 2}
    assert info["websiteAccess"] is True
    assert info["websiteConfig"]["indexDocument"] == "index.html"

    # size quota: a PUT with content-length over maxSize is rejected 403
    st, _, body = client.request("PUT", "/quota-bucket/too-big",
                                 body=os.urandom(200000))
    assert st == 403, body
    assert xml_error_code(body) == "AccessDenied"

    # under the size quota: accepted (counts as object 1)
    st, _, body = client.request("PUT", "/quota-bucket/obj1",
                                 body=os.urandom(60000))
    assert st == 200, body
    # wait for counter propagation, then object 2 fills the count quota
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        st, info = _admin(server, "GET", f"/v1/bucket?id={bid}")
        if info["objects"] >= 1:
            break
        time.sleep(0.2)
    assert info["objects"] >= 1
    st, _, body = client.request("PUT", "/quota-bucket/obj2",
                                 body=os.urandom(10000))
    assert st == 200, body
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        st, info = _admin(server, "GET", f"/v1/bucket?id={bid}")
        if info["objects"] >= 2:
            break
        time.sleep(0.2)
    # object-count quota: a THIRD object is rejected...
    st, _, body = client.request("PUT", "/quota-bucket/obj3",
                                 body=os.urandom(1000))
    assert st == 403, body
    # ...but REPLACING an existing object is allowed (doesn't add one)
    st, _, body = client.request("PUT", "/quota-bucket/obj1",
                                 body=os.urandom(1000))
    assert st == 200, body

    # disable website + clear quotas
    st, info = _admin(server, "PUT", f"/v1/bucket?id={bid}", body={
        "quotas": {"maxSize": None, "maxObjects": None},
        "websiteAccess": {"enabled": False},
    })
    assert st == 200
    assert info["websiteAccess"] is False
    assert info["quotas"] == {"maxSize": None, "maxObjects": None}
    st, _, body = client.request("PUT", "/quota-bucket/obj3",
                                 body=os.urandom(1000))
    assert st == 200, body

    # invalid quota values are a 400 (and must not half-apply)
    st, _ = _admin(server, "PUT", f"/v1/bucket?id={bid}", body={
        "websiteAccess": {"enabled": True, "indexDocument": "i.html"},
        "quotas": {"maxSize": -5}})
    assert st == 400
    st, info = _admin(server, "GET", f"/v1/bucket?id={bid}")
    assert info["websiteAccess"] is False  # atomic: nothing applied
    # malformed shapes are 400, not 500
    st, _ = _admin(server, "PUT", f"/v1/bucket?id={bid}",
                   body={"websiteAccess": True})
    assert st == 400

    # multipart uploads are quota-checked at completion
    st, info = _admin(server, "PUT", f"/v1/bucket?id={bid}",
                      body={"quotas": {"maxSize": 100000}})
    assert st == 200
    st, _, body = client.request("POST", "/quota-bucket/mpu-big",
                                 query=[("uploads", "")])
    assert st == 200
    upload_id = xml_find(body, "UploadId")[0]
    st, hdrs, body = client.request(
        "PUT", "/quota-bucket/mpu-big",
        query=[("partNumber", "1"), ("uploadId", upload_id)],
        body=os.urandom(150000))
    assert st == 200, body
    part_etag = hdrs["etag"].strip('"')
    complete = (f'<CompleteMultipartUpload><Part><PartNumber>1'
                f'</PartNumber><ETag>"{part_etag}"</ETag></Part>'
                f'</CompleteMultipartUpload>').encode()
    st, _, body = client.request(
        "POST", "/quota-bucket/mpu-big",
        query=[("uploadId", upload_id)], body=complete)
    assert st == 403, body
    assert xml_error_code(body) == "AccessDenied"


# ---- operator CLI surface (ref: garage/cli/structs.rs:113-123) ----------


def test_cli_layout_config_and_revert(server):
    out = server.cli("layout", "config", "-r", "maximum")
    assert "zone_redundancy" in out and "maximum" in out
    out = server.cli("layout", "config", "-r", "1")
    assert "'zone_redundancy': 1" in out
    # stage a bogus assignment, then revert drops it
    out = server.cli("status")
    node_id = next(line.split()[-1] for line in out.splitlines()
                   if line.startswith("node id:"))
    server.cli("layout", "assign", node_id, "-z", "dc9", "-c", "2G")
    out = server.cli("layout", "show")
    assert "staged changes:" in out
    out = server.cli("layout", "revert")
    assert "reverted" in out
    out = server.cli("layout", "show")
    assert "staged changes:" not in out


def test_cli_layout_skip_dead_nodes(server):
    # single healthy node: nothing to skip
    out = server.cli("layout", "skip-dead-nodes", "--allow-missing-data")
    assert "no dead nodes" in out


def test_cli_repair_rebalance(server):
    out = server.cli("repair", "rebalance")
    assert "rebalance" in out


def test_k2v_cli_roundtrip(server):
    """k2v-cli binary (ref: k2v-client/bin/k2v-cli.rs) against the real
    forked server."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               AWS_ACCESS_KEY_ID=server.key_id,
               AWS_SECRET_ACCESS_KEY=server.secret)

    def k2vcli(*args, check=True):
        r = subprocess.run(
            [sys.executable, "-m", "garage_tpu.cli.k2v",
             "--port", str(server.k2v_port), "--bucket", "k2vcli-bucket",
             *args],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=60)
        if check and r.returncode != 0:
            raise AssertionError(f"k2v-cli {args}: {r.stdout}{r.stderr}")
        return r

    # bucket via S3 admin surface
    c = S3Client("127.0.0.1", server.s3_port, server.key_id, server.secret)
    st, _, _ = c.request("PUT", "/k2vcli-bucket")
    assert st == 200

    r = k2vcli("insert", "pk1", "sk1", "hello world")
    assert "ok" in r.stdout
    r = k2vcli("read", "pk1", "sk1")
    out = json.loads(r.stdout)
    assert out["values"] == [{"utf8": "hello world"}]
    causality = out["causality"]
    r = k2vcli("read-index")
    assert any(json.loads(line)["partitionKey"] == "pk1"
               for line in r.stdout.splitlines())
    r = k2vcli("read-range", "pk1")
    assert "sk1" in r.stdout
    # --causality=TOKEN: base64 tokens can start with '-' and would
    # otherwise be parsed as an option flag
    r = k2vcli("delete", "pk1", "sk1", "--causality=" + causality)
    assert "ok" in r.stdout
    # read-after-delete surfaces the causal tombstone
    r = k2vcli("read", "pk1", "sk1")
    assert json.loads(r.stdout)["values"] == [{"tombstone": True}]


def test_offline_convert_db_and_counter_repair(server, client):
    """convert-db copies every tree; repair-offline object-counters
    recomputes drifted counters (ref: cli/convert_db.rs,
    repair/offline.rs). Runs against a STOPPED server's metadata."""
    import shutil
    import tempfile

    # a fresh bucket with exactly two objects -> deterministic counters
    st, _, _ = client.request("PUT", "/offline-bkt")
    assert st == 200
    st, _, _ = client.request("PUT", "/offline-bkt/offline-1",
                              body=os.urandom(5000))
    assert st == 200
    st, _, _ = client.request("PUT", "/offline-bkt/offline-2",
                              body=os.urandom(80000))
    assert st == 200
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        st, info = _admin(server, "GET",
                          "/v1/bucket?globalAlias=offline-bkt")
        if st == 200 and info["objects"] == 2:
            break
        time.sleep(0.2)
    assert info["objects"] == 2 and info["bytes"] == 85000
    bid = info["id"]

    work = tempfile.mkdtemp(prefix="gt_offline_")
    try:
        server.stop()
        meta = os.path.join(server.dir, "meta")
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                   GARAGE_TPU_DEVICE="off")

        # convert-db round trip: sqlite -> sqlite copy has all trees
        dst = os.path.join(work, "copy")
        os.makedirs(dst)
        r = subprocess.run(
            [sys.executable, "-m", "garage_tpu.cli.main",
             "--config", server.config_path, "convert-db",
             "--src", os.path.join(meta, "db"), "--dst", dst],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "converted" in r.stdout
        import sqlite3

        src_c = sqlite3.connect(os.path.join(meta, "db", "db.sqlite"))
        dst_c = sqlite3.connect(os.path.join(dst, "db.sqlite"))
        q = ("select name from sqlite_master where type='table' "
             "order by name")
        assert [x[0] for x in src_c.execute(q)] == \
            [x[0] for x in dst_c.execute(q)]
        src_c.close(); dst_c.close()

        # CORRUPT the local object counter, then offline repair must
        # restore the true totals
        from garage_tpu.db import open_db as _open_db
        from garage_tpu.table.schema import tree_key as _tk

        import msgpack as _mp

        db = _open_db(os.path.join(meta, "db"), engine="sqlite")
        lc = db.open_tree("local_counter:bucket_object_counter")
        corrupted = 0

        def corrupt(tx):
            nonlocal corrupted
            for k, v in lc.iter():
                vals = _mp.unpackb(v)
                vals = [[n, ts, v0 * 7 + 3] for n, ts, v0 in vals]
                tx.insert(lc, k, _mp.packb(vals))
                corrupted += 1

        db.transaction(corrupt)
        db.close()
        assert corrupted > 0

        r = subprocess.run(
            [sys.executable, "-m", "garage_tpu.cli.main",
             "--config", server.config_path, "repair-offline",
             "object-counters"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "recomputed" in r.stdout

        # the LOCAL counter tree itself must hold the true totals again
        # (reading only the admin API after restart could be satisfied
        # by the untouched counter table)
        db = _open_db(os.path.join(meta, "db"), engine="sqlite")
        lc = db.open_tree("local_counter:bucket_object_counter")
        row = lc.get(_tk(bytes.fromhex(bid), b""))
        db.close()
        assert row is not None
        vals = {n: v0 for n, _ts, v0 in _mp.unpackb(row)}
        assert vals["objects"] == 2 and vals["bytes"] == 85000, vals
    finally:
        shutil.rmtree(work, ignore_errors=True)
        server.start()  # restart for any later tests in the module

    # after restart, the repaired counters are served again
    deadline = time.monotonic() + 15
    info = {}
    while time.monotonic() < deadline:
        st, info = _admin(server, "GET",
                          "/v1/bucket?globalAlias=offline-bkt")
        if st == 200 and info.get("objects") == 2:
            break
        time.sleep(0.3)
    assert info["objects"] == 2 and info["bytes"] == 85000, info


def test_secret_files_with_permission_checks(tmp_path):
    """Layered secrets (ref: src/garage/secrets.rs): *_file config keys
    read one-line files, refusing world-readable ones."""
    from garage_tpu.utils.config import config_from_dict

    sec = tmp_path / "rpc.secret"
    sec.write_text("aa" * 32 + "\n")
    os.chmod(sec, 0o600)
    cfg = config_from_dict({"metadata_dir": str(tmp_path),
                            "rpc_secret_file": str(sec)})
    assert cfg.rpc_secret == "aa" * 32

    os.chmod(sec, 0o644)
    with pytest.raises(ValueError, match="readable by other"):
        config_from_dict({"metadata_dir": str(tmp_path),
                          "rpc_secret_file": str(sec)})
    # escape hatch env
    os.environ["GARAGE_ALLOW_WORLD_READABLE_SECRETS"] = "1"
    try:
        cfg = config_from_dict({"metadata_dir": str(tmp_path),
                                "rpc_secret_file": str(sec)})
        assert cfg.rpc_secret == "aa" * 32
    finally:
        del os.environ["GARAGE_ALLOW_WORLD_READABLE_SECRETS"]
    # both inline and file -> error
    with pytest.raises(ValueError, match="pick one"):
        config_from_dict({"metadata_dir": str(tmp_path),
                          "rpc_secret": "bb" * 32,
                          "rpc_secret_file": str(sec)})
    # env var wins over file
    os.environ["GARAGE_ADMIN_TOKEN"] = "env-token"
    try:
        cfg = config_from_dict({"metadata_dir": str(tmp_path)})
        assert cfg.admin_token == "env-token"
    finally:
        del os.environ["GARAGE_ADMIN_TOKEN"]


def test_unix_socket_admin_bind(tmp_path_factory):
    """A path-valued bind addr makes the API server listen on a
    Unix-domain socket with the reference's 0o222 socket mode
    (ref: api/common/generic_server.rs:120-131,
    util/socket_address.rs)."""
    import http.client
    import socket
    import stat

    tmp = str(tmp_path_factory.mktemp("udssrv"))
    srv = Server(tmp)
    sock_path = os.path.join(tmp, "admin.sock")
    with open(srv.config_path) as f:
        cfg = f.read()
    cfg = cfg.replace(f'api_bind_addr = "127.0.0.1:{srv.admin_port}"',
                      f'api_bind_addr = "{sock_path}"', 1)
    # the [s3_api] section also matches api_bind_addr; replace only the
    # admin one (it appears after admin_token's section header)
    assert f'api_bind_addr = "{sock_path}"' in cfg
    with open(srv.config_path, "w") as f:
        f.write(cfg)
    srv.start()
    try:
        assert stat.S_IMODE(os.stat(sock_path).st_mode) == 0o222

        class UConn(http.client.HTTPConnection):
            def connect(self):
                self.sock = socket.socket(socket.AF_UNIX,
                                          socket.SOCK_STREAM)
                self.sock.connect(sock_path)

        c = UConn("localhost")
        c.request("GET", "/health")
        r = c.getresponse()
        assert r.status in (200, 503)
        assert r.read()  # health text body over the UDS transport
    finally:
        srv.stop()


def test_list_object_versions(client, listing_bucket):
    """GET ?versions: unversioned-bucket contract — one Version per
    key, VersionId null, IsLatest true; pagination + delimiter work."""
    st, _, body = client.request("GET", "/listing",
                                 query=[("versions", "")])
    assert st == 200
    assert b"<ListVersionsResult" in body
    keys = xml_find(body, "Key")
    assert keys == sorted(keys) and "c" in keys
    assert set(xml_find(body, "VersionId")) == {"null"}
    assert set(xml_find(body, "IsLatest")) == {"true"}
    # delimiter folding
    st, _, body = client.request(
        "GET", "/listing", query=[("versions", ""), ("delimiter", "/")])
    assert "a/" in xml_find(body, "Prefix")
    assert xml_find(body, "Key") == ["c"]
    # pagination via key-marker
    st, _, body = client.request(
        "GET", "/listing", query=[("versions", ""), ("max-keys", "2")])
    assert xml_find(body, "IsTruncated")[0] == "true"
    marker = xml_find(body, "NextKeyMarker")[0]
    got = xml_find(body, "Key")
    st, _, body = client.request(
        "GET", "/listing",
        query=[("versions", ""), ("key-marker", marker)])
    got += xml_find(body, "Key")
    assert got == sorted(set(got)) and len(got) == 6


def test_list_versions_prefix_rollup_across_page_boundary(
        client, listing_bucket):
    """?versions + delimiter with max-keys=1: a page ending on a folded
    common prefix sets NextKeyMarker to the prefix; the next page must
    resume PAST the whole prefix (("p",...) cursor, same convention as
    v1/uploads), never re-emitting it or leaking a key from under it."""
    got_keys, got_prefixes, marker = [], [], None
    for _ in range(10):
        q = [("versions", ""), ("delimiter", "/"), ("max-keys", "1")]
        if marker:
            q.append(("key-marker", marker))
        status, _, body = client.request("GET", listing_bucket, query=q)
        assert status == 200
        got_keys += xml_find(body, "Key")
        got_prefixes += _common_prefixes(body)
        if xml_find(body, "IsTruncated")[0] != "true":
            break
        marker = xml_find(body, "NextKeyMarker")[0]
    assert got_keys == ["c"]
    assert got_prefixes == ["a/", "b/"]


def test_list_marker_equal_to_prefix_not_folded(client, listing_bucket):
    """A marker that ends with the delimiter but does not strictly
    extend the request prefix (here: equal to it) is NOT a folded
    common prefix — folded prefixes are always prefix+<nonempty>+delim.
    Treating it as one seeks past the whole window and returns an
    empty page instead of the keys under the prefix."""
    st, _, body = client.request(
        "GET", listing_bucket,
        query=[("versions", ""), ("prefix", "a/"), ("delimiter", "/"),
               ("key-marker", "a/")])
    assert st == 200
    assert xml_find(body, "Key") == ["a/1", "a/2"]
    st, _, body = client.request(
        "GET", listing_bucket,
        query=[("prefix", "a/"), ("delimiter", "/"), ("marker", "a/")])
    assert st == 200
    assert xml_find(body, "Key") == ["a/1", "a/2"]


def test_unimplemented_subresources_501(client):
    """Recognized-but-unimplemented subresources answer NotImplemented
    like the reference (api_server.rs:66), never a misshaped fallback
    GetObject/ListObjects response."""
    client.request("PUT", "/conformance/subres", body=b"x")
    for path, query in (("/conformance", "tagging"),
                        ("/conformance", "policy"),
                        ("/conformance/subres", "tagging"),
                        ("/conformance/subres", "acl"),
                        ("/conformance/subres", "torrent")):
        st, _, body = client.request("GET", path, query=[(query, "")])
        assert st == 501, (path, query, st)
        assert xml_error_code(body) == "NotImplemented"
    st, _, _ = client.request("PUT", "/conformance/subres",
                              query=[("tagging", "")], body=b"<t/>")
    assert st == 501


def test_copy_metadata_directive(client):
    """x-amz-metadata-directive: REPLACE takes the request's metadata
    (the self-copy metadata-update idiom); default COPY carries the
    source's (ref: copy.rs:83-90)."""
    client.request("PUT", "/conformance/md-src", body=b"payload" * 100,
                   headers={"content-type": "text/plain",
                            "x-amz-meta-alpha": "one"})
    # default: metadata copied
    st, _, _ = client.request(
        "PUT", "/conformance/md-dst",
        headers={"x-amz-copy-source": "/conformance/md-src",
                 "x-amz-meta-alpha": "IGNORED"})
    assert st == 200
    st, hdrs, _ = client.request("HEAD", "/conformance/md-dst")
    h = dict(hdrs)
    assert h.get("x-amz-meta-alpha") == "one"
    assert h.get("content-type") == "text/plain"
    # REPLACE: request metadata wins; self-copy updates in place
    st, _, _ = client.request(
        "PUT", "/conformance/md-src",
        headers={"x-amz-copy-source": "/conformance/md-src",
                 "x-amz-metadata-directive": "REPLACE",
                 "content-type": "application/json",
                 "x-amz-meta-beta": "two"})
    assert st == 200
    st, hdrs, body = client.request("GET", "/conformance/md-src")
    h = dict(hdrs)
    assert body == b"payload" * 100
    assert h.get("content-type") == "application/json"
    assert h.get("x-amz-meta-beta") == "two"
    assert "x-amz-meta-alpha" not in h


def test_response_header_overrides(client):
    """response-content-* query params override the stored headers on
    GET (ref: get.rs:104-107), including via presigned URLs."""
    client.request("PUT", "/conformance/resp-ovr", body=b"ovr",
                   headers={"content-type": "text/plain"})
    st, hdrs, body = client.request(
        "GET", "/conformance/resp-ovr",
        query=[("response-content-type", "application/pdf"),
               ("response-content-disposition",
                'attachment; filename="x.pdf"'),
               ("response-cache-control", "no-store")])
    h = dict(hdrs)
    assert st == 200 and body == b"ovr"
    assert h["content-type"] == "application/pdf"
    assert h["content-disposition"] == 'attachment; filename="x.pdf"'
    assert h["cache-control"] == "no-store"
    # no override -> stored value
    st, hdrs, _ = client.request("GET", "/conformance/resp-ovr")
    assert dict(hdrs)["content-type"] == "text/plain"


def test_website_redirect_location(server, client, website_bucket):
    """x-amz-website-redirect-location: validated and stored on PUT,
    echoed on S3 GET, served as a 301 by the website endpoint
    (ref: put.rs:681-692, web_server.rs:302-309)."""
    st, _, body = client.request(
        "PUT", "/wsite/moved.html", body=b"",
        headers={"x-amz-website-redirect-location": "/page.html"})
    assert st == 200, body
    # invalid target -> 400
    st, _, _ = client.request(
        "PUT", "/wsite/bad.html", body=b"",
        headers={"x-amz-website-redirect-location": "elsewhere"})
    assert st == 400
    # S3 GET echoes the header with the object
    st, hdrs, _ = client.request("GET", "/wsite/moved.html")
    assert dict(hdrs)["x-amz-website-redirect-location"] == "/page.html"
    # website endpoint serves a 301
    status, headers, body = _web_get(server, website_bucket,
                                     "/moved.html")
    assert status == 301
    assert dict(headers)["location"] == "/page.html"
    assert body in (b"", None)


def test_create_bucket_location_constraint(client):
    """CreateBucketConfiguration: the configured region is accepted,
    any other is a 400 (ref: bucket.rs:127-138)."""
    ok = (b"<CreateBucketConfiguration><LocationConstraint>garage"
          b"</LocationConstraint></CreateBucketConfiguration>")
    st, _, body = client.request("PUT", "/locbkt", body=ok)
    assert st == 200, body
    bad = (b"<CreateBucketConfiguration><LocationConstraint>us-east-9"
           b"</LocationConstraint></CreateBucketConfiguration>")
    st, _, body = client.request("PUT", "/locbkt2", body=bad)
    assert st == 400
    assert xml_error_code(body) == "InvalidLocationConstraint"
    st, _, body = client.request("PUT", "/locbkt3", body=b"not-xml")
    assert st == 400
    client.request("DELETE", "/locbkt")


def test_list_encoding_type_url(client):
    """encoding-type=url percent-encodes keys/prefixes in listings
    (boto3 requests it by default; unencoded special-char keys would
    mis-parse client-side)."""
    client.request("PUT", "/enctest")
    raw_key = "dir with space/obj name.txt"
    from urllib.parse import quote

    st, _, _ = client.request("PUT", f"/enctest/{quote(raw_key)}",
                              body=b"e")
    assert st == 200
    st, _, body = client.request(
        "GET", "/enctest",
        query=[("list-type", "2"), ("encoding-type", "url")])
    assert st == 200
    assert xml_find(body, "EncodingType") == ["url"]
    keys = xml_find(body, "Key")
    assert keys == [quote(raw_key, safe="/")]
    # delimiter folding encodes CommonPrefixes too
    st, _, body = client.request(
        "GET", "/enctest",
        query=[("list-type", "2"), ("encoding-type", "url"),
               ("delimiter", "/")])
    assert xml_find(body, "Prefix")[-1] == quote("dir with space/")
    # versions + uploads honour it as well
    st, _, body = client.request(
        "GET", "/enctest", query=[("versions", ""),
                                  ("encoding-type", "url")])
    assert xml_find(body, "Key") == [quote(raw_key, safe="/")]
    # unknown encoding-type is a 400
    st, _, body = client.request(
        "GET", "/enctest", query=[("list-type", "2"),
                                  ("encoding-type", "base64")])
    assert st == 400
    client.request("DELETE", f"/enctest/{quote(raw_key)}")
    client.request("DELETE", "/enctest")


def test_otlp_trace_sink_from_forked_server(tmp_path_factory):
    """[admin] trace_sink wiring end to end: a real server process
    ships OTLP spans for a PUT to a local collector."""
    import http.server
    import json as _json
    import threading

    received = []

    class Sink(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append((self.path, _json.loads(body)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    col = http.server.HTTPServer(("127.0.0.1", 0), Sink)
    threading.Thread(target=col.serve_forever, daemon=True).start()
    tmp = str(tmp_path_factory.mktemp("otlpsrv"))
    srv = Server(tmp)
    with open(srv.config_path) as f:
        cfg = f.read()
    cfg = cfg.replace(
        'admin_token = "test-admin-token"',
        'admin_token = "test-admin-token"\n'
        f'trace_sink = "http://127.0.0.1:{col.server_port}"')
    with open(srv.config_path, "w") as f:
        f.write(cfg)
    try:
        srv.start()
        srv.setup_layout_and_key()
        cli = S3Client("127.0.0.1", srv.s3_port, srv.key_id, srv.secret)
        cli.request("PUT", "/otlpb")
        cli.request("PUT", "/otlpb/k", body=b"traced")
        deadline = time.monotonic() + 30  # exporter flushes every 3 s
        # (wide margin: this box runs co-tenant probes/benches)

        def all_spans():
            # scan EVERY batch received so far: under load the PUT's
            # span can land in the second flush, after a first batch
            # of boot-time spans
            out = []
            for path, payload in list(received):
                assert path == "/v1/traces"
                for rs in payload["resourceSpans"]:
                    for ss in rs["scopeSpans"]:
                        out.extend(ss["spans"])
            return out

        while time.monotonic() < deadline and not any(
                s["name"] == "http.request" for s in all_spans()):
            time.sleep(0.5)
        assert received, "no OTLP batch arrived from the server"
        assert any(s["name"] == "http.request" for s in all_spans())
    finally:
        srv.stop()
        col.shutdown()


def test_k2v_error_codes(k2v):
    """ref parity: src/garage/tests/k2v/errorcodes.rs — each malformed
    request answers 400; the happy-path insert answers 204."""
    import json as _json

    bkt = k2v.bucket

    def req(method, path, query=None, headers=None, body=b""):
        st, _, rbody = k2v._req(method, path, query=query,
                                headers=headers, body=body)
        return st, rbody

    # regular insert works (204)
    st, _ = req("PUT", f"/{bkt}/root", query=[("sort_key", "test1")],
                body=b"Hello, world!")
    assert st == 204

    # trash causality token on insert
    st, _ = req("PUT", f"/{bkt}/root", query=[("sort_key", "test1")],
                headers={"x-garage-causality-token": "tra$sh"},
                body=b"Hello, world!")
    assert st == 400

    # search without partitionKey
    st, _ = req("POST", f"/{bkt}", query=[("search", "")],
                body=b'[{}]')
    assert st == 400

    # search whose start does not lie in the prefix (range.rs:30-40)
    st, _ = req("POST", f"/{bkt}", query=[("search", "")],
                body=_json.dumps(
                    [{"partitionKey": "root", "prefix": "a",
                      "start": "bx"}]).encode())
    assert st == 400

    # search with invalid json
    st, _ = req("POST", f"/{bkt}", query=[("search", "")],
                body=b'[{"partitionKey": "root"')
    assert st == 400

    # batch insert with invalid causality token
    st, _ = req("POST", f"/{bkt}",
                body=b'[{"pk": "root", "sk": "a", "ct": "tra$h",'
                     b' "v": "aGVsbG8sIHdvcmxkCg=="}]')
    assert st == 400

    # batch insert with invalid base64 value (strict alphabet)
    st, _ = req("POST", f"/{bkt}",
                body=b'[{"pk": "root", "sk": "a", "ct": null,'
                     b' "v": "aGVsbG8sIHdvcmx$Cg=="}]')
    assert st == 400

    # poll with invalid causality token
    st, _ = req("GET", f"/{bkt}/root",
                query=[("sort_key", "test1"),
                       ("causality_token", "tra$h"),
                       ("timeout", "10")])
    assert st == 400

    # read-index start outside prefix
    st, _ = req("GET", f"/{bkt}",
                query=[("prefix", "a"), ("start", "bx")])
    assert st == 400

    # non-string query fields are a 400 (the reference rejects them at
    # deserialization), never a 500
    st, _ = req("POST", f"/{bkt}", query=[("search", "")],
                body=b'[{"partitionKey": "root", "start": 5}]')
    assert st == 400
    st, _ = req("POST", f"/{bkt}", query=[("search", "")],
                body=b'[{"partitionKey": 7}]')
    assert st == 400


def test_cli_stats(server, client):
    """`garage stats` over admin RPC: table and block-store counters."""
    import json as _json

    out = server.cli("stats")
    stats = _json.loads(out)
    assert "object" in stats["tables"]
    assert "bytes_written" in stats["block"]
    assert "resync_queue" in stats


def test_list_multichar_delimiter(client):
    """ref parity: list.rs test_multichar_delimiter (garage issue #692,
    reference results verified against Amazon): a multi-character
    delimiter folds at every occurrence of the WHOLE delimiter string
    after the prefix, and keys equal to a fold-point still list."""
    st, _, b = client.request("PUT", "/multichardelim")
    assert st == 200, b
    for k in ("a/", "a/b/", "a/b/c/", "a/b/c/d", "a/c/", "a/c/b/",
              "a/c/b/e"):
        st, _, b = client.request("PUT", f"/multichardelim/{k}")
        assert st == 200, b

    st, _, body = client.request(
        "GET", "/multichardelim",
        query=[("list-type", "2"), ("delimiter", "/")])
    assert st == 200
    assert xml_find(body, "Key") == []
    root = ET.fromstring(body)
    common = [el.find("./{*}Prefix").text for el in root.iter()
              if el.tag.split("}")[-1] == "CommonPrefixes"]
    assert common == ["a/"]

    st, _, body = client.request(
        "GET", "/multichardelim",
        query=[("list-type", "2"), ("delimiter", "b/")])
    assert st == 200
    assert xml_find(body, "Key") == ["a/", "a/c/"]
    root = ET.fromstring(body)
    common = [el.find("./{*}Prefix").text for el in root.iter()
              if el.tag.split("}")[-1] == "CommonPrefixes"]
    assert common == ["a/b/", "a/c/b/"]


def test_streaming_signature_on_config_endpoints(client):
    """ref parity: streaming_signature.rs test_create_bucket_streaming /
    test_put_website_streaming — aws-chunked signed bodies must work on
    EVERY endpoint, not just object PUT (the body decoder sits below
    the router)."""
    # CreateBucket with a chunked (empty) signed body
    st, _, b = client.put_chunked("/streamcfg", [])
    assert st == 200, b
    # PutBucketWebsite with a chunked XML body
    xml = (b"<WebsiteConfiguration><IndexDocument><Suffix>index.html"
           b"</Suffix></IndexDocument></WebsiteConfiguration>")
    st, _, b = client.put_chunked("/streamcfg", [xml],
                                  query=[("website", "")])
    assert st in (200, 204), b
    st, _, body = client.request("GET", "/streamcfg",
                                 query=[("website", "")])
    assert st == 200 and b"index.html" in body


def test_admin_v0_compat_paths(server):
    """ref parity: router_v0.rs — /v0/* routes serve the same handlers
    as /v1/*."""
    st, body = _admin(server, "GET", "/v0/status")
    assert st == 200
    assert "garageVersion" in body and "nodes" in body
    st, body = _admin(server, "GET", "/v0/health")
    assert st == 200
