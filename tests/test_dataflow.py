"""ISSUE 9: interprocedural dataflow engine tests — call-graph units
(self-method / import / partial / to_thread edges, cycle tolerance),
GL10/GL11 fire+suppress fixtures, upgraded GL02/GL03/GL06 fixtures,
summary determinism (same tree -> byte-identical JSON), and the three
acceptance regression pins (each re-introduced bug shape fails the CLI
with exit 1)."""

import ast
import os
import textwrap

from garage_tpu.analysis import (CallGraph, analyze_source,
                                 default_rules, summarize_tree,
                                 summary_json)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(src: str, rel_path: str = "garage_tpu/fake/mod.py"):
    ctx = analyze_source(textwrap.dedent(src), default_rules(),
                         rel_path=rel_path)
    return [v for v in ctx.violations if v.active]


def rules_of(violations):
    return sorted({v.rule for v in violations})


def graph_of(src: str, rel="garage_tpu/fake/mod.py") -> CallGraph:
    tree = ast.parse(textwrap.dedent(src))
    return CallGraph({rel: summarize_tree(tree, rel)})


# ---- call graph units ---------------------------------------------------

def test_callgraph_self_method_edge():
    g = graph_of("""
        class A:
            def helper(self):
                return 1
            def top(self):
                return self.helper()
    """)
    edges = g.edges_from("garage_tpu.fake.mod:A.top")
    assert [e[0] for e in edges] == ["garage_tpu.fake.mod:A.helper"]


def test_callgraph_to_thread_edge_is_via_thread():
    g = graph_of("""
        import asyncio
        def work():
            return 1
        async def top():
            return await asyncio.to_thread(work)
    """)
    edges = g.edges_from("garage_tpu.fake.mod:top")
    hits = [(c, r["via_thread"]) for c, r in edges
            if c.endswith(":work")]
    assert hits == [("garage_tpu.fake.mod:work", True)]


def test_callgraph_partial_unwrap_edge():
    g = graph_of("""
        from functools import partial
        def work(x):
            return x
        def top(x):
            f = partial(work, x)
            return f()
    """)
    edges = g.edges_from("garage_tpu.fake.mod:top")
    assert any(c.endswith(":work") and not r["via_thread"]
               for c, r in edges)


def test_callgraph_nested_def_resolves_before_module_level():
    g = graph_of("""
        def work():
            return "module"
        def top():
            def work():
                return "nested"
            return work()
    """)
    edges = g.edges_from("garage_tpu.fake.mod:top")
    assert [c for c, _ in edges] == ["garage_tpu.fake.mod:top.work"]


def test_callgraph_cycle_tolerance():
    g = graph_of("""
        import time
        def a(n):
            return b(n)
        def b(n):
            if n:
                return a(n - 1)
            time.sleep(1)
    """)
    # reachability over the a <-> b cycle terminates and still finds
    # the atom in b
    chains = list(g.blocking_chains("garage_tpu.fake.mod:a"))
    assert any(chain[-1]["target"] == "time.sleep" for chain in chains)


def test_callgraph_unique_method_cha():
    g = graph_of("""
        class Store:
            def read_rows(self):
                return []
        class User:
            def go(self, store):
                return store.read_rows()
    """)
    edges = g.edges_from("garage_tpu.fake.mod:User.go")
    assert [c for c, _ in edges] == ["garage_tpu.fake.mod:Store.read_rows"]


def test_callgraph_ambiguous_method_yields_no_edge():
    g = graph_of("""
        class A:
            def read_rows(self):
                return []
        class B:
            def read_rows(self):
                return []
        class User:
            def go(self, x):
                return x.read_rows()
    """)
    assert g.edges_from("garage_tpu.fake.mod:User.go") == []


def test_callgraph_base_class_method_edge():
    g = graph_of("""
        class Base:
            def helper(self):
                return 1
        class Child(Base):
            def top(self):
                return self.helper()
    """)
    edges = g.edges_from("garage_tpu.fake.mod:Child.top")
    assert [c for c, _ in edges] == ["garage_tpu.fake.mod:Base.helper"]


# ---- GL10 blocking-reachable-from-async ---------------------------------

def test_gl10_fires_two_frames_down_with_chain():
    vs = run("""
        import sqlite3
        def scan(path):
            return sqlite3.connect(path)
        def outer(path):
            return scan(path)
        async def handler(path):
            return outer(path)
    """)
    assert rules_of(vs) == ["GL10"]
    assert "handler -> outer -> scan" in vs[0].message
    assert "sqlite3.connect" in vs[0].message


def test_gl10_quiet_when_hopped_through_to_thread():
    vs = run("""
        import asyncio, sqlite3
        def scan(path):
            return sqlite3.connect(path)
        async def handler(path):
            return await asyncio.to_thread(scan, path)
    """)
    assert vs == []


def test_gl10_quiet_for_sync_only_callers_and_generators():
    vs = run("""
        import sqlite3
        def scan(path):
            return sqlite3.connect(path)
        def sync_caller(path):
            return scan(path)
        def gen(path):
            yield sqlite3.connect(path)
        async def uses_gen(path):
            return gen(path)          # calling a generator runs nothing
    """)
    assert vs == []


def test_gl10_direct_blocking_is_gl01_not_gl10():
    vs = run("""
        import time
        async def handler():
            time.sleep(1)
    """)
    assert rules_of(vs) == ["GL01"]


def test_gl10_db_seam_direct_in_async():
    vs = run("""
        async def handler(self, pk):
            return self.store.get(pk)
    """)
    assert rules_of(vs) == ["GL10"]
    assert "sync db call" in vs[0].message


def test_gl10_waivable_with_reason():
    vs = run("""
        import sqlite3
        def scan(path):
            return sqlite3.connect(path)
        async def handler(path):
            # lint: ignore[GL10] one-shot startup path, loop not serving yet
            return scan(path)
    """)
    assert vs == []


# ---- GL11 leaked-budget-on-exception ------------------------------------

def test_gl11_fires_on_happy_path_refund():
    vs = run("""
        async def handle(self, n):
            tok = await self.bucket.acquire(n)
            resp = await self.upstream(n)
            self.bucket.refund(n)
            return resp
    """)
    assert rules_of(vs) == ["GL11"]
    assert "happy" in vs[0].message


def test_gl11_quiet_on_safe_shapes():
    vs = run("""
        async def with_finally(self, n):
            await self.bucket.acquire(n)
            try:
                return await self.upstream(n)
            finally:
                self.bucket.refund(n)
        async def refund_on_failure(self, n):
            await self.bucket.acquire(n)
            try:
                return await self.upstream(n)
            except Exception:
                self.bucket.refund(n)
                raise
        async def plain_admission(self, n):
            await self.bucket.acquire(n)
            return await self.upstream(n)
        async def context_manager(self, n):
            async with self.sem.acquire():
                return await self.upstream(n)
    """)
    assert vs == []


def test_gl11_release_via_bound_value():
    vs = run("""
        async def handle(self, n):
            lease = await self.broker.acquire(n)
            resp = await self.upstream(n)
            lease.release()
            return resp
    """)
    assert rules_of(vs) == ["GL11"]


# ---- upgraded GL02: interprocedural strategies --------------------------

GL02_HELPER = """
    class H:
        async def _call_any(self, who, payload, strategy):
            await self.rpc.try_call_many(self.ep, who, payload, strategy)

        async def insert(self, who, payload):
            await self._call_any(who, payload, %s)
"""


def test_gl02_unpinned_strategy_through_helper_fires_at_caller():
    vs = run(GL02_HELPER % "RequestStrategy(quorum=1)")
    assert rules_of(vs) == ["GL02"]
    assert "hedge-sensitive" in vs[0].message
    assert vs[0].line == 7  # the CALLER's call site


def test_gl02_pinned_strategy_through_helper_is_quiet():
    assert run(GL02_HELPER % "RequestStrategy(quorum=1, hedge=False)") \
        == []


def test_gl02_read_context_caller_is_quiet():
    vs = run("""
        class H:
            async def _call_any(self, who, payload, strategy):
                await self.rpc.try_call_many(self.ep, who, payload,
                                             strategy)

            async def get_traced(self, who, payload):
                await self._call_any(who, payload,
                                     RequestStrategy(quorum=1))
    """)
    assert vs == []


def test_gl02_mutating_helper_fires_for_any_caller():
    vs = run("""
        class H:
            async def insert_rpc(self, who, payload, strategy):
                await self.rpc.try_call_many(self.ep, who, payload,
                                             strategy)

            async def kick(self, who, payload):
                await self.insert_rpc(who, payload,
                                      RequestStrategy(quorum=1))
    """)
    assert rules_of(vs) == ["GL02"]


def test_gl02_helper_with_strategy_param_no_longer_fires_at_helper():
    # PR 5's syntactic rule flagged the helper itself (unresolvable
    # strategy in mutation context); the dataflow engine blames callers
    vs = run("""
        class H:
            async def insert_rpc(self, who, payload, strategy):
                await self.rpc.try_call_many(self.ep, who, payload,
                                             strategy)
    """)
    assert vs == []


# ---- upgraded GL03: taint across helpers --------------------------------

S3 = "garage_tpu/api/s3/fake_get.py"


def test_gl03_taint_crosses_one_helper_hop():
    vs = run("""
        async def helper(mgr, h, key):
            return await mgr.rpc_get_block(h)

        async def stream(mgr, h, sse_key):
            return await helper(mgr, h, sse_key)
    """, rel_path=S3)
    assert rules_of(vs) == ["GL03"]
    assert "tainted via stream" in vs[0].message


def test_gl03_taint_crosses_two_hops():
    vs = run("""
        async def inner(mgr, h, k2):
            return await mgr.rpc_get_block(h)

        async def helper(mgr, h, k1):
            return await inner(mgr, h, k1)

        async def stream(mgr, h, sse_key):
            return await helper(mgr, h, sse_key)
    """, rel_path=S3)
    assert rules_of(vs) == ["GL03"]


def test_gl03_quiet_with_cacheable_at_helper_or_untainted():
    vs = run("""
        async def helper(mgr, h, key):
            return await mgr.rpc_get_block(h, cacheable=key is None)

        async def stream(mgr, h, sse_key):
            return await helper(mgr, h, sse_key)

        async def plain(mgr, h, color):
            return await helper2(mgr, h, color)

        async def helper2(mgr, h, key):
            return await mgr.rpc_get_block(h)
    """, rel_path=S3)
    assert vs == []


def test_gl03_decrypt_result_is_a_source():
    vs = run("""
        async def reseal(mgr, h, wrapped):
            plain = decrypt_block(wrapped)
            await mgr.rpc_put_block(h, plain)
    """, rel_path=S3)
    assert rules_of(vs) == ["GL03"]


def test_gl03_tainted_payload_into_cache_insert():
    vs = run("""
        def fill(cache, h, sse_payload):
            cache.insert(h, sse_payload)
    """, rel_path="garage_tpu/block/fake.py")
    assert rules_of(vs) == ["GL03"]
    assert "cache" in vs[0].message


def test_gl03_gateway_forwards_in_scope():
    vs = run("""
        async def forward(mgr, h, sse_key):
            return await mgr.rpc_get_block(h)
    """, rel_path="garage_tpu/gateway/fake.py")
    assert rules_of(vs) == ["GL03"]


# ---- upgraded GL06: sync with-lock --------------------------------------

def test_gl06_sync_with_lock_across_await_fires():
    vs = run("""
        async def refresh(self, payload):
            with self._lock:
                await self.rpc.try_call_many(self.ep, self.nodes,
                                             payload, st)
    """, rel_path="garage_tpu/block/fake.py")
    assert rules_of(vs) == ["GL06"]


def test_gl06_sync_lock_in_sync_fn_quiet():
    vs = run("""
        def compute(self):
            with self._lock:
                return self.table[0]
    """, rel_path="garage_tpu/block/fake.py")
    assert vs == []


def test_gl02_unpinned_strategy_through_non_self_receiver():
    """`await c.call_write(...)` (CHA-resolved dotted ref) must shift
    the bound self exactly like `self.call_write(...)` — positional
    args land on the right parameters."""
    vs = run("""
        class Caller:
            async def call_write(self, ep, who, payload, strategy):
                await self.rpc.try_call_many(ep, who, payload, strategy)

        class User:
            async def insert(self, c, ep, who, payload):
                await c.call_write(ep, who, payload,
                                   RequestStrategy(quorum=1))
    """)
    assert rules_of(vs) == ["GL02"]


def test_gl10_extra_io_atom_direct_in_async_frame():
    # os.replace is GL10's atom, not GL01's: typed directly in the
    # async frame it must STILL fire (inlining a flagged helper must
    # not make the finding disappear)
    vs = run("""
        import os
        async def commit(a, b):
            os.replace(a, b)
    """)
    assert rules_of(vs) == ["GL10"]
    assert "directly on the event loop" in vs[0].message


def test_shared_project_resettles_idempotently():
    """analyze_source with a shared ProjectState must not duplicate
    stale-waiver hygiene or finish_project findings, and later files
    must still be analyzed by the dataflow rules."""
    from garage_tpu.analysis import ProjectState

    p = ProjectState()
    rules = default_rules()
    ctx1 = analyze_source(textwrap.dedent("""
        def f():  # lint: ignore[GL05] nothing fires here
            return 1
    """), rules, rel_path="garage_tpu/a.py", project=p)
    ctx2 = analyze_source(textwrap.dedent("""
        import sqlite3
        def scan(path):
            return sqlite3.connect(path)
        async def handler(path):
            return scan(path)
    """), rules, rel_path="garage_tpu/b.py", project=p)
    stale = [v for v in ctx1.violations if "stale waiver" in v.message]
    assert len(stale) == 1  # not duplicated by the second settle
    assert [v.rule for v in ctx2.violations if v.active] == ["GL10"]


# ---- module / import resolution -----------------------------------------

def test_callgraph_relative_import_in_package_init():
    """`from .core import helper` inside pkg/__init__.py resolves
    against pkg itself, not pkg's parent (the __init__ component is
    already collapsed out of the module name)."""
    core_src = textwrap.dedent("""
        import sqlite3
        def helper(path):
            return sqlite3.connect(path)
    """)
    init_src = textwrap.dedent("""
        from .core import helper
        async def top(path):
            return helper(path)
    """)
    g = CallGraph({
        "garage_tpu/pkg/core.py": summarize_tree(
            ast.parse(core_src), "garage_tpu/pkg/core.py"),
        "garage_tpu/pkg/__init__.py": summarize_tree(
            ast.parse(init_src), "garage_tpu/pkg/__init__.py"),
    })
    edges = g.edges_from("garage_tpu.pkg:top")
    assert [c for c, _ in edges] == ["garage_tpu.pkg.core:helper"]
    chains = list(g.blocking_chains("garage_tpu.pkg:top"))
    assert any(c[-1]["target"] == "sqlite3.connect" for c in chains)


def test_summary_cache_rejects_other_engine_versions():
    from garage_tpu.analysis import DataflowState
    from garage_tpu.analysis.core import FileContext
    from garage_tpu.analysis.dataflow import SUMMARY_VERSION

    src = "def f():\n    return 1\n"
    ctx = FileContext("m.py", "garage_tpu/m.py", src, ast.parse(src))
    fresh = DataflowState([ctx])
    good = fresh.cache_payload()
    assert good["garage_tpu/m.py"]["v"] == SUMMARY_VERSION
    hit = DataflowState([ctx], summary_cache=good)
    assert hit.cache_hits == 1
    stale = {k: dict(v, v=SUMMARY_VERSION - 1) for k, v in good.items()}
    miss = DataflowState([ctx], summary_cache=stale)
    assert miss.cache_hits == 0
    assert miss.summaries == fresh.summaries  # recomputed, not trusted


# ---- summary determinism -------------------------------------------------

def test_summary_cache_determinism_same_tree_byte_identical():
    src = open(os.path.join(REPO, "garage_tpu/table/data.py"),
               encoding="utf-8").read()
    a = summary_json(summarize_tree(ast.parse(src),
                                    "garage_tpu/table/data.py"))
    b = summary_json(summarize_tree(ast.parse(src),
                                    "garage_tpu/table/data.py"))
    assert a == b
    assert a  # non-trivial


def test_summary_determinism_across_the_analysis_package():
    pkg = os.path.join(REPO, "garage_tpu", "analysis")
    for f in sorted(os.listdir(pkg)):
        if not f.endswith(".py"):
            continue
        src = open(os.path.join(pkg, f), encoding="utf-8").read()
        rel = f"garage_tpu/analysis/{f}"
        assert summary_json(summarize_tree(ast.parse(src), rel)) == \
            summary_json(summarize_tree(ast.parse(src), rel)), f


# ---- acceptance regression pins (ISSUE 9) -------------------------------

def _cli_rc_on(tmp_path, source: str, rel: str) -> int:
    from garage_tpu.analysis.__main__ import main

    target = tmp_path / rel
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent(source))
    return main(["--baseline", "none", str(target)])


def test_regression_a_ssec_through_helper_exits_1(tmp_path, capsys):
    rc = _cli_rc_on(tmp_path, """
        async def helper(mgr, h, key):
            return await mgr.rpc_get_block(h)

        async def stream(mgr, h, sse_key):
            return await helper(mgr, h, sse_key)
    """, "garage_tpu/api/s3/get2.py")
    assert rc == 1
    assert "GL03" in capsys.readouterr().out


def test_regression_b_sqlite_two_frames_below_async_exits_1(
        tmp_path, capsys):
    rc = _cli_rc_on(tmp_path, """
        import sqlite3

        def read_row(path, k):
            return sqlite3.connect(path).execute(
                "select v from t where k=?", (k,)).fetchone()

        def lookup(path, k):
            return read_row(path, k)

        async def handler(path, k):
            return lookup(path, k)
    """, "garage_tpu/table/fake_srv.py")
    assert rc == 1
    assert "GL10" in capsys.readouterr().out


def test_regression_c_happy_path_refund_exits_1(tmp_path, capsys):
    rc = _cli_rc_on(tmp_path, """
        async def admit(self, n):
            tok = await self.bucket.acquire(n)
            resp = await self.forward(n)
            self.bucket.refund(n)
            return resp
    """, "garage_tpu/qos/fake_admit.py")
    assert rc == 1
    assert "GL11" in capsys.readouterr().out
