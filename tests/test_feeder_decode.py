"""Device-routed read path (ISSUE 13): batched RS decode & repair
through the staged pipeline with pattern-as-data GF kernels.

Same deviceless discipline as test_feeder_pipeline.py: the jax
backend's "device" is the cpu platform (conftest pins JAX_PLATFORMS=cpu)
— the staging/padding/grouping and the pattern-as-data compile behavior
are under test, not the silicon — and the stub backend covers the
watchdog and live-gate semantics.
"""

from __future__ import annotations

import asyncio
import itertools
import os

import numpy as np
import pytest

from garage_tpu.block.codec import ErasureCodec
from garage_tpu.block.device_backend import StubDeviceBackend
from garage_tpu.block.feeder import DeviceFeeder, _Item
from garage_tpu.ops import rs


def run(coro):
    return asyncio.run(coro)


def _stripe(codec, block: bytes):
    return codec.encode(block)


# ---------------------------------------------------------------------------
# byte-parity: device decode/repair == decode_np across ALL erasure
# patterns and across shard-length buckets
# ---------------------------------------------------------------------------


def test_decode_byte_parity_all_patterns_and_buckets():
    """Every C(k+m, k) present-set, at two block sizes landing in
    different shard-length pad buckets, decoded through the staged jax
    route in ONE batch — results byte-identical to decode_np +
    join_stripe (pad rows and length padding sliced away)."""
    k, m = 4, 2
    codec = ErasureCodec(k, m, use_jax=False)
    f = DeviceFeeder(codec=codec, mode="require", max_batch=256)
    f._device_ok = True
    rng = np.random.default_rng(13)
    patterns = list(itertools.combinations(range(k + m), k))
    assert len(patterns) == 15

    async def go():
        items, want = [], []
        for blen in (3_000, 300_000):  # distinct bucket_len buckets
            block = rng.integers(0, 256, blen, dtype=np.uint8).tobytes()
            stripe = _stripe(codec, block)
            for present in patterns:
                shards = [stripe[i] for i in present]
                items.append((present, shards, blen))
                st = np.stack([np.frombuffer(s, dtype=np.uint8)
                               for s in shards])
                want.append(rs.join_stripe(
                    rs.decode_np(k, m, present, st), blen))
        batch = [_Item("decode", it, asyncio.get_running_loop()
                       .create_future()) for it in items]
        res = await f._run_batch_staged(batch)
        for got, exp, it in zip(res, want, items):
            assert not isinstance(got, BaseException), (it[0], got)
            assert got == exp, f"pattern {it[0]} len {it[2]}"
        assert f.stats["decode_device_items"] == len(items)
        assert f.stats["pad_waste_bytes"] > 0
        await f.stop()

    run(go())


def test_repair_byte_parity_mixed_missing_sizes():
    """Repair through the staged route rebuilds the exact missing
    shard bytes for 1- and 2-missing patterns in one batch (grouped by
    output row count internally) — vs the repair_np reference."""
    k, m = 4, 2
    codec = ErasureCodec(k, m, use_jax=False)
    f = DeviceFeeder(codec=codec, mode="require", max_batch=256)
    f._device_ok = True
    rng = np.random.default_rng(17)
    block = rng.integers(0, 256, 65_000, dtype=np.uint8).tobytes()
    stripe = _stripe(codec, block)
    full = np.stack([np.frombuffer(s, dtype=np.uint8) for s in stripe])

    items = []
    for missing in [(0,), (3,), (5,), (0, 1), (2, 5), (4, 5)]:
        present = tuple(i for i in range(k + m) if i not in missing)[:k]
        items.append((present, missing, [stripe[i] for i in present]))

    async def go():
        batch = [_Item("repair", it, asyncio.get_running_loop()
                       .create_future()) for it in items]
        res = await f._run_batch_staged(batch)
        for (present, missing, _s), got in zip(items, res):
            assert not isinstance(got, BaseException), (missing, got)
            assert sorted(got) == sorted(missing)
            for mi in missing:
                assert got[mi] == bytes(full[mi]), (present, missing, mi)
        await f.stop()

    run(go())


# ---------------------------------------------------------------------------
# recompile stability: the pattern is DATA, not a trace constant
# ---------------------------------------------------------------------------


def test_recompiles_flat_across_mixed_erasure_patterns():
    """>= 8 distinct erasure patterns through the staged decode route,
    one batch per pattern at identical shapes: feeder_recompiles moves
    once for the first shape and NEVER again — and the per-pattern
    constant-matrix jit cache (rs._jit_apply, the pre-ISSUE-13 leak)
    gains no entries at all."""
    k, m = 4, 2
    codec = ErasureCodec(k, m, use_jax=False)
    f = DeviceFeeder(codec=codec, mode="require", max_batch=16)
    f._device_ok = True
    rng = np.random.default_rng(23)
    block = rng.integers(0, 256, 40_000, dtype=np.uint8).tobytes()
    stripe = _stripe(codec, block)
    patterns = list(itertools.combinations(range(k + m), k))[:9]
    assert len(patterns) >= 8
    leak_cache_before = rs._jit_apply.cache_info().currsize

    async def go():
        rc_after_first = None
        for present in patterns:
            shards = [stripe[i] for i in present]
            batch = [_Item("decode", (present, shards, len(block)),
                           asyncio.get_running_loop().create_future())
                     for _ in range(4)]
            res = await f._run_batch_staged(batch)
            st = np.stack([np.frombuffer(s, dtype=np.uint8)
                           for s in shards])
            want = rs.join_stripe(rs.decode_np(k, m, present, st),
                                  len(block))
            assert all(r == want for r in res), present
            if rc_after_first is None:
                rc_after_first = f.stats["recompiles"]
        assert f.stats["recompiles"] == rc_after_first, \
            "a new erasure pattern caused a recompile"
        assert f.stats["decode_device_items"] == 4 * len(patterns)
        await f.stop()

    run(go())
    assert rs._jit_apply.cache_info().currsize == leak_cache_before, \
        "per-pattern constant-matrix jit entries leaked"


def test_rs_decode_repair_share_one_jit_across_patterns():
    """The ops-level decode/repair entry points themselves no longer
    grow a jit cache entry per pattern (the f"dec{k},{m},{present}"
    keys): every pattern rides the single pattern-as-data kernel."""
    k, m = 4, 2
    rng = np.random.default_rng(29)
    data = rng.integers(0, 256, (k, 64), dtype=np.uint8)
    stripe = np.concatenate([data, np.asarray(rs.encode(k, m, data))])
    before = rs._jit_apply.cache_info().currsize
    for present in itertools.combinations(range(k + m), k):
        got = np.asarray(rs.decode(k, m, present, stripe[list(present)]))
        assert np.array_equal(got, data)
    missing = (0, 5)
    present = (1, 2, 3, 4)
    got = np.asarray(rs.repair(k, m, present, missing,
                               stripe[list(present)]))
    assert np.array_equal(got, stripe[list(missing)])
    assert rs._jit_apply.cache_info().currsize == before


# ---------------------------------------------------------------------------
# watchdog: depth-2 decode hang -> host re-run, every future resolves
# ---------------------------------------------------------------------------


def test_decode_hang_reruns_host_every_future_resolves(monkeypatch):
    """Injected device hang with decode batches in flight at depth 2:
    every caller gets the CORRECT packed bytes via the host re-run, no
    future is lost, and the device path is disabled — the read-side
    edition of the pipeline hang test."""
    monkeypatch.delenv("GARAGE_TPU_DEVICE", raising=False)
    k, m = 4, 2
    codec = ErasureCodec(k, m, use_jax=False)
    stub = StubDeviceBackend(None, fixed_s=0.01)
    stub.hang_stage = "compute"
    f = DeviceFeeder(codec=codec, mode="require", max_batch=2,
                     backend=stub)
    f._device_ok = True
    f.batch_timeout = 1.0
    rng = np.random.default_rng(31)
    blocks = [rng.integers(0, 256, 20_000 + i, dtype=np.uint8).tobytes()
              for i in range(4)]
    present = (1, 2, 3, 4)  # degraded: shard 0 lost

    async def go():
        jobs = []
        for b in blocks:
            stripe = codec.encode(b)
            jobs.append(f.decode(present, [stripe[i] for i in present],
                                 len(b)))
        outs = await asyncio.gather(*jobs)
        dev_ok = f._device_ok
        await f.stop()
        return outs, dev_ok

    outs, dev_ok = run(go())
    for b, got in zip(blocks, outs):
        st = np.stack([np.frombuffer(s, dtype=np.uint8)
                       for s in codec.encode(b)])
        want = rs.join_stripe(
            rs.decode_np(k, m, present, st[list(present)]), len(b))
        assert got == want
    assert dev_ok is False
    assert f.stats["decode_device_items"] == 0


# ---------------------------------------------------------------------------
# stub live gate: degraded GETs through a real cluster engage the
# device decode route
# ---------------------------------------------------------------------------


def test_degraded_get_stub_live_gate(tmp_path, monkeypatch):
    """GARAGE_TPU_DEVICE=require + stub backend on a real 6-node
    erasure cluster: a degraded GET (one systematic shard destroyed)
    must route its decode through the device path —
    feeder_decode_device_items > 0, the CI shape of the read-side
    engagement gate."""
    from test_block import make_block_cluster, stop_all
    from garage_tpu.utils.data import blake2sum

    monkeypatch.setenv("GARAGE_TPU_DEVICE", "require")
    monkeypatch.setenv("GARAGE_TPU_DEVICE_BACKEND", "stub")

    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2))
        try:
            data = os.urandom(200_000)
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            for _ in range(100):
                held = sorted(i for mg in managers
                              for i in mg.local_parts(h))
                if held == [0, 1, 2, 3, 4, 5]:
                    break
                await asyncio.sleep(0.02)
            # destroy a systematic shard so the GET really decodes
            victim = next(mg for mg in managers
                          if 0 in mg.local_parts(h))
            victim.delete_local(h)
            reader = managers[1]
            reader.cache.clear()
            got = await reader.rpc_get_block(h, cacheable=False)
            assert got == data
            fs = reader.feeder.stats
            assert fs["decode_items"] >= 1
            assert fs["decode_device_items"] >= 1, fs
        finally:
            await stop_all(systems, tasks)

    run(asyncio.wait_for(main(), 120))


# ---------------------------------------------------------------------------
# deep-scrub gather fan-out is windowed
# ---------------------------------------------------------------------------


def test_deep_scrub_gather_window_bounded():
    """gather_bounded keeps at most `window` stripe gathers in flight
    (repair.py:258 used to fan out the whole leader set at once) and
    returns results in item order."""
    from garage_tpu.block.repair import gather_bounded

    live = 0
    peak = 0

    async def fake_gather(h, placement):
        nonlocal live, peak
        live += 1
        peak = max(peak, live)
        await asyncio.sleep(0.01)
        live -= 1
        return (h, placement)

    items = [(i, f"p{i}") for i in range(23)]

    async def go():
        return await gather_bounded(fake_gather, items, 4)

    out = run(go())
    assert out == items  # order preserved
    assert peak <= 4, f"window exceeded: {peak}"
    assert peak >= 2  # it did actually run concurrently


# ---------------------------------------------------------------------------
# knobs: [tpu] decode floors flow into the feeder + admin tuning
# ---------------------------------------------------------------------------


def test_decode_knobs_flow_into_feeder_and_tuning():
    from types import SimpleNamespace

    from garage_tpu.admin.http import apply_s3_tuning, s3_tuning_state
    from garage_tpu.block.cache import BlockCache
    from garage_tpu.block import feeder as fmod
    from garage_tpu.utils.config import Config, config_from_dict

    cfg = config_from_dict({
        "metadata_dir": "/tmp/x",
        "tpu": {"device_min_decode_bytes": 2048,
                "device_min_decode_items": 3},
    })
    f = DeviceFeeder(mode="off", tpu_cfg=cfg.tpu)
    assert f.device_min_decode_bytes == 2048
    assert f.device_min_decode_items == 3
    # None leaves the module defaults in force
    f2 = DeviceFeeder(mode="off")
    assert f2.device_min_decode_bytes == fmod._DEVICE_MIN_DECODE_BYTES
    assert f2.device_min_decode_items == fmod._DEVICE_MIN_DECODE_ITEMS

    feeder = DeviceFeeder(mode="off")
    garage = SimpleNamespace(
        config=Config(metadata_dir="/tmp/x"),
        block_manager=SimpleNamespace(cache=BlockCache(1 << 20),
                                      feeder=feeder))
    state = apply_s3_tuning(garage, {
        "feeder_device_min_decode_bytes": 1 << 21,
        "feeder_device_min_decode_items": 7})
    assert feeder.device_min_decode_bytes == 1 << 21
    assert feeder.device_min_decode_items == 7
    assert state["feeder_device_min_decode_items"] == 7
    assert s3_tuning_state(garage)["feeder_device_min_decode_bytes"] \
        == 1 << 21


def test_decode_routing_floor_keeps_lone_small_decode_on_host():
    """A single small decode below both [tpu] device_min_decode_*
    floors must not pay a device trip even when the device is healthy
    (auto mode, device winning on calibration data)."""
    k, m = 4, 2
    codec = ErasureCodec(k, m, use_jax=False)
    stub = StubDeviceBackend(None, fixed_s=0.0)
    f = DeviceFeeder(codec=codec, mode="auto", max_batch=8, backend=stub)
    f._device_ok = True
    f._record("decode", "device", 1 << 30, 1.0)  # device hugely winning
    f._record("decode", "host", 1 << 20, 1.0)
    backend, trial = f._pick_backend("decode", 4096, 1)
    assert backend == "host" and trial is False
    # a coalesced wave above the item floor goes device
    backend, _ = f._pick_backend(
        "decode", 4096 * f.device_min_decode_items,
        f.device_min_decode_items)
    assert backend == "device"


def test_malformed_decode_item_fails_its_caller_only():
    """Unequal shard lengths are rejected BEFORE the queue: the bad
    caller gets ValueError, batch-mates are unaffected (an in-batch
    failure would poison the whole op group)."""
    k, m = 4, 2
    codec = ErasureCodec(k, m, use_jax=False)
    f = DeviceFeeder(codec=codec, mode="off", max_batch=8)

    async def go():
        block = os.urandom(10_000)
        stripe = codec.encode(block)
        present = (1, 2, 3, 4)
        bad_shards = [stripe[1], stripe[2][:100], stripe[3], stripe[4]]
        with pytest.raises(ValueError):
            await f.decode(present, bad_shards, len(block))
        good = await f.decode(present,
                              [stripe[i] for i in present], len(block))
        st = np.stack([np.frombuffer(stripe[i], dtype=np.uint8)
                       for i in present])
        assert good == rs.join_stripe(
            rs.decode_np(k, m, present, st), len(block))
        await f.stop()

    run(go())
