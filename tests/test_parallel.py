"""Sharded data-plane steps (parallel/mesh.py) on the 8-device CPU mesh.

VERDICT r3 weak-item 6: make_put_step/make_scrub_step/make_repair_step
were exercised only by the driver's dryrun. These tests pin:
- sharded-vs-single-device equivalence for RS(4,2) and the flagship
  RS(10,4) across (dp, tp) in {(8,1), (4,2), (2,4)}
- the shard-S fallback when tp does not divide n = k+m
- corruption detection through the sharded scrub step
- the tp-does-not-divide-S error path
"""

from __future__ import annotations

import numpy as np
import pytest

from garage_tpu.ops import rs, treehash
from garage_tpu.parallel.mesh import (
    _layouts,
    data_plane_mesh,
    make_put_step,
    make_repair_step,
    make_scrub_step,
)

SHAPES = [(4, 2), (10, 4)]
GRIDS = [(8, 1), (4, 2), (2, 4)]
S = 2048


def _mesh(dp: int, tp: int):
    import jax

    assert len(jax.devices()) >= dp * tp, "conftest must provide 8 devices"
    return data_plane_mesh(dp * tp, tp=tp)


def _host_reference(data: np.ndarray, k: int, m: int):
    """Single-host numpy/py reference for the put step."""
    parity = np.stack([rs.encode_np(k, m, data[i])
                       for i in range(data.shape[0])])
    allsh = np.concatenate([data, parity], axis=1)
    hashes = np.stack([
        np.stack([np.frombuffer(treehash.blake3_py(allsh[i, j].tobytes()),
                                dtype=np.uint8)
                  for j in range(k + m)])
        for i in range(allsh.shape[0])
    ])
    return parity, allsh, hashes


@pytest.mark.parametrize("dp,tp", GRIDS)
@pytest.mark.parametrize("k,m", SHAPES)
def test_put_step_sharded_matches_host(k, m, dp, tp):
    mesh = _mesh(dp, tp)
    batch = dp * 2
    rng = np.random.default_rng(k * 100 + tp)
    data = rng.integers(0, 256, size=(batch, k, S), dtype=np.uint8)
    put = make_put_step(mesh, k, m, S)
    parity, hashes = put(data)
    ref_parity, _, ref_hashes = _host_reference(data, k, m)
    np.testing.assert_array_equal(np.asarray(parity), ref_parity)
    np.testing.assert_array_equal(np.asarray(hashes), ref_hashes)


@pytest.mark.parametrize("dp,tp", [(4, 2), (2, 4)])
@pytest.mark.parametrize("k,m", SHAPES)
def test_scrub_step_detects_injected_corruption(k, m, dp, tp):
    mesh = _mesh(dp, tp)
    batch = dp * 2
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=(batch, k, S), dtype=np.uint8)
    put = make_put_step(mesh, k, m, S)
    parity, hashes = put(data)
    shards = np.concatenate([data, np.asarray(parity)], axis=1)

    scrub = make_scrub_step(mesh, k, m, S)
    bad, count = scrub(shards, np.asarray(hashes))
    assert int(count) == 0
    assert not np.asarray(bad).any()

    # flip one byte in a data shard and one in a parity shard
    shards2 = shards.copy()
    shards2[1, 0, 100] ^= 0xFF
    shards2[2, k + 1, 5] ^= 0x01
    bad2, count2 = scrub(shards2, np.asarray(hashes))
    bad2 = np.asarray(bad2)
    assert bad2[1, 0] and bad2[2, k + 1]
    assert int(count2) == 2


@pytest.mark.parametrize("dp,tp", GRIDS)
def test_repair_step_rebuilds_missing(dp, tp):
    k, m = 10, 4
    mesh = _mesh(dp, tp)
    batch = dp * 2
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, size=(batch, k, S), dtype=np.uint8)
    parity = np.stack([rs.encode_np(k, m, data[i]) for i in range(batch)])
    shards = np.concatenate([data, parity], axis=1)
    present = (0, 1, 2, 3, 4, 6, 7, 8, 9, 12)
    missing = (5, 10, 13)
    repair = make_repair_step(mesh, k, m, present, missing, S)
    rebuilt, rhashes = repair(shards[:, list(present), :])
    np.testing.assert_array_equal(np.asarray(rebuilt),
                                  shards[:, list(missing), :])
    for j, mi in enumerate(missing):
        assert bytes(np.asarray(rhashes)[0, j]) == \
            treehash.blake3_py(shards[0, mi].tobytes())


def test_repair_step_shares_one_program_across_patterns():
    """ISSUE 20 / GL14 regression: make_repair_step was lru_cache'd per
    (present, missing) pattern — C(n,k) compiled programs. The repair
    matrix now rides as a tensor operand through a shape-keyed apply:
    same-size patterns must share ONE cache entry and stay correct."""
    from garage_tpu.parallel.mesh import _repair_apply_step

    k, m = 4, 2
    mesh = _mesh(4, 2)
    batch = 8
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=(batch, k, S), dtype=np.uint8)
    parity = np.stack([rs.encode_np(k, m, data[i]) for i in range(batch)])
    shards = np.concatenate([data, parity], axis=1)
    patterns = [((0, 1, 2, 4), (3,)), ((1, 2, 3, 5), (0,)),
                ((0, 2, 3, 4), (1,))]
    _repair_apply_step.cache_clear()
    for present, missing in patterns:
        repair = make_repair_step(mesh, k, m, present, missing, S)
        rebuilt, _ = repair(shards[:, list(present), :])
        np.testing.assert_array_equal(np.asarray(rebuilt),
                                      shards[:, list(missing), :])
    assert _repair_apply_step.cache_info().currsize == 1


def test_layout_fallback_when_tp_does_not_divide_n():
    mesh = _mesh(2, 4)
    # n = 14, tp = 4: whole-shard layout must fall back to sharding S
    _, shards_sh, n_sharded = _layouts(mesh, 14, S)
    assert not n_sharded
    # n = 6, tp = 2 on a fresh mesh: n axis sharded
    mesh2 = _mesh(4, 2)
    _, _, n_sharded2 = _layouts(mesh2, 6, S)
    assert n_sharded2


def test_tp_must_divide_shard_len():
    mesh = _mesh(2, 4)
    with pytest.raises(ValueError, match="divide shard_len"):
        _layouts(mesh, 6, 1023 * 3)  # 3069 % 4 != 0
    with pytest.raises(ValueError):
        data_plane_mesh(8, tp=3)  # 3 does not divide 8 devices
