"""Transport layer tests: loopback mesh, TCP handshake, streams, ordering.

Mirrors the reference's net test (src/net/test.rs:15-118 — 3-node mesh
convergence) plus deterministic in-process coverage the reference lacks.
"""

import asyncio

import pytest

from garage_tpu.net import LocalNetwork, NetApp, PeeringManager
from garage_tpu.net.message import PRIO_NORMAL
from garage_tpu.net.stream import ByteStream
from garage_tpu.net.peering import PeerConnState
from garage_tpu.utils.error import RpcError

NETID = b"test-cluster-secret"


def run(coro, timeout=30.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def make_local_node(net: LocalNetwork) -> NetApp:
    app = NetApp(NETID)
    net.register(app)
    return app


def test_loopback_call_roundtrip():
    async def main():
        net = LocalNetwork()
        a, b = make_local_node(net), make_local_node(net)

        async def handler(from_node, payload, stream):
            assert from_node == a.id
            return {"echo": payload["x"] * 2}

        b.endpoint("test/echo").set_handler(handler)
        await a.try_connect(b.public_addr, b.id)
        resp, _ = await a.endpoint("test/echo").call(b.id, {"x": 21}, PRIO_NORMAL, timeout=5)
        assert resp == {"echo": 42}

    run(main())


def test_self_call_shortcircuits():
    async def main():
        net = LocalNetwork()
        a = make_local_node(net)
        a.endpoint("test/self").set_handler(lambda f, p, s: _async({"me": True}))
        resp, _ = await a.endpoint("test/self").call(a.id, {}, PRIO_NORMAL)
        assert resp == {"me": True}

    run(main())


async def _async(v):
    return v


def test_stream_attach_and_reply():
    async def main():
        net = LocalNetwork()
        a, b = make_local_node(net), make_local_node(net)
        body = bytes(range(256)) * 1000  # 256 KB, multiple chunks

        async def handler(from_node, payload, stream):
            data = await stream.read_all()
            return {"len": len(data)}, ByteStream.from_bytes(data[::-1])

        b.endpoint("test/stream").set_handler(handler)
        await a.try_connect(b.public_addr, b.id)
        resp, reply_stream = await a.endpoint("test/stream").call(
            b.id, {}, PRIO_NORMAL, stream=ByteStream.from_bytes(body), timeout=10
        )
        assert resp == {"len": len(body)}
        back = await reply_stream.read_all()
        assert back == body[::-1]

    run(main())


def test_handler_error_propagates():
    async def main():
        net = LocalNetwork()
        a, b = make_local_node(net), make_local_node(net)

        async def handler(from_node, payload, stream):
            raise ValueError("boom")

        b.endpoint("test/err").set_handler(handler)
        await a.try_connect(b.public_addr, b.id)
        with pytest.raises(RpcError, match="boom"):
            await a.endpoint("test/err").call(b.id, {}, PRIO_NORMAL, timeout=5)

    run(main())


def test_call_timeout_and_cancel():
    async def main():
        net = LocalNetwork()
        a, b = make_local_node(net), make_local_node(net)
        started = asyncio.Event()
        cancelled = asyncio.Event()

        async def handler(from_node, payload, stream):
            started.set()
            try:
                await asyncio.sleep(60)
            except asyncio.CancelledError:
                cancelled.set()
                raise

        b.endpoint("test/slow").set_handler(handler)
        await a.try_connect(b.public_addr, b.id)
        with pytest.raises(asyncio.TimeoutError):
            await a.endpoint("test/slow").call(b.id, {}, PRIO_NORMAL, timeout=0.2)
        await asyncio.wait_for(started.wait(), 5)
        # CANCEL frame must abort the remote handler
        await asyncio.wait_for(cancelled.wait(), 5)

    run(main())


def test_ordered_dispatch():
    async def main():
        net = LocalNetwork()
        a, b = make_local_node(net), make_local_node(net)
        seen = []

        async def handler(from_node, payload, stream):
            seen.append(payload["seq"])
            return {}

        b.endpoint("test/ordered").set_handler(handler)
        await a.try_connect(b.public_addr, b.id)
        sid = 77
        # fire seq 2, 1, 0 concurrently — handlers must run 0, 1, 2
        await asyncio.gather(
            *(
                a.endpoint("test/ordered").call(
                    b.id, {"seq": s}, PRIO_NORMAL, order=(sid, s), timeout=5
                )
                for s in (2, 1, 0)
            )
        )
        assert seen == [0, 1, 2]

    run(main())


def test_three_node_mesh_convergence():
    async def main():
        net = LocalNetwork()
        nodes = [make_local_node(net) for _ in range(3)]
        # nodes 1 and 2 only know node 0's address
        pms = []
        for i, app in enumerate(nodes):
            bootstrap = [] if i == 0 else [(nodes[0].public_addr, nodes[0].id)]
            pm = PeeringManager(app, bootstrap, ping_interval=0.2, ping_timeout=1.0, retry_interval=0.2)
            pms.append(pm)
        tasks = [asyncio.create_task(pm.run()) for pm in pms]
        try:
            deadline = asyncio.get_event_loop().time() + 15
            while asyncio.get_event_loop().time() < deadline:
                if all(
                    sum(
                        1
                        for p in pm.get_peer_list()
                        if p.state == PeerConnState.CONNECTED
                    )
                    == 2
                    for pm in pms
                ):
                    break
                await asyncio.sleep(0.1)
            for pm in pms:
                connected = [p for p in pm.get_peer_list() if p.state == PeerConnState.CONNECTED]
                assert len(connected) == 2, f"mesh did not converge: {pm.get_peer_list()}"
        finally:
            for pm in pms:
                await pm.stop()
            for t in tasks:
                t.cancel()

    run(main(), timeout=40)


def test_failure_detection_and_reconnect():
    async def main():
        net = LocalNetwork()
        a, b = make_local_node(net), make_local_node(net)
        pma = PeeringManager(a, [(b.public_addr, b.id)], ping_interval=0.1, ping_timeout=0.3, retry_interval=0.3)
        pmb = PeeringManager(b, [], ping_interval=0.1, ping_timeout=0.3, retry_interval=0.3)
        tasks = [asyncio.create_task(pma.run()), asyncio.create_task(pmb.run())]
        try:
            await _wait_for(lambda: a.is_connected(b.id), 10)
            net.partition(a.id, b.id)
            await _wait_for(lambda: not a.is_connected(b.id), 10)
            net.heal(a.id, b.id)
            await _wait_for(lambda: a.is_connected(b.id), 15)
        finally:
            await pma.stop()
            await pmb.stop()
            for t in tasks:
                t.cancel()

    run(main(), timeout=45)


async def _wait_for(cond, timeout):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return
        await asyncio.sleep(0.05)
    raise AssertionError("condition not reached")


def test_tcp_transport_end_to_end():
    async def main():
        a = NetApp(NETID, bind_addr=("127.0.0.1", 0))
        b = NetApp(NETID, bind_addr=("127.0.0.1", 0))
        await a.listen()
        await b.listen()

        async def handler(from_node, payload, stream):
            extra = await stream.read_all() if stream else b""
            return {"sum": payload["x"] + payload["y"], "extra": len(extra)}

        b.endpoint("test/tcp").set_handler(handler)
        try:
            peer = await a.try_connect(b.bind_addr, b.id)
            assert peer == b.id
            resp, _ = await a.endpoint("test/tcp").call(
                b.id, {"x": 1, "y": 2}, PRIO_NORMAL,
                stream=ByteStream.from_bytes(b"z" * 100_000), timeout=10,
            )
            assert resp == {"sum": 3, "extra": 100_000}
        finally:
            await a.shutdown()
            await b.shutdown()

    run(main())


def test_tcp_wrong_netid_rejected():
    async def main():
        a = NetApp(b"cluster-one", bind_addr=("127.0.0.1", 0))
        b = NetApp(b"cluster-two", bind_addr=("127.0.0.1", 0))
        await b.listen()
        try:
            with pytest.raises(Exception):
                await a.try_connect(b.bind_addr, b.id)
        finally:
            await a.shutdown()
            await b.shutdown()

    run(main())


def test_stream_flow_control_bounds_buffering():
    """Receiver-side buffering must stay near STREAM_WINDOW even when the
    consumer is much slower than the producer (credit-based flow ctl)."""
    from garage_tpu.net.conn import STREAM_WINDOW

    async def main():
        net = LocalNetwork()
        a, b = make_local_node(net), make_local_node(net)
        high_water = 0
        done = asyncio.Event()

        async def handler(from_node, payload, stream):
            nonlocal high_water
            total = 0
            while True:
                await asyncio.sleep(0.001)  # slow consumer
                high_water = max(high_water, stream._size)
                chunk = await stream.read_chunk(1 << 16)
                if not chunk:
                    break
                total += len(chunk)
            done.set()
            return {"total": total}

        b.endpoint("test/flow").set_handler(handler)
        await a.try_connect(b.public_addr, b.id)

        async def producer():
            s = ByteStream()

            async def pump():
                for _ in range(24):  # 24 MiB total, 6x the window
                    await s.write(b"\x00" * (1 << 20))
                s.push_eof()

            asyncio.ensure_future(pump())
            return s

        src = await producer()
        resp, _ = await a.endpoint("test/flow").call(
            b.id, {}, PRIO_NORMAL, stream=src, timeout=60
        )
        assert resp == {"total": 24 << 20}
        assert high_water <= STREAM_WINDOW + (1 << 20), (
            f"receiver buffered {high_water} bytes, window is {STREAM_WINDOW}"
        )

    run(main(), timeout=90)


def test_ordered_cancel_does_not_stall_stream():
    """A cancelled seq must be tombstoned so later seqs still run."""

    async def main():
        net = LocalNetwork()
        a, b = make_local_node(net), make_local_node(net)
        release0 = asyncio.Event()
        ran = []

        async def handler(from_node, payload, stream):
            if payload["seq"] == 0:
                await release0.wait()
            ran.append(payload["seq"])
            return {}

        b.endpoint("test/ocancel").set_handler(handler)
        await a.try_connect(b.public_addr, b.id)
        sid = 99
        t0 = asyncio.ensure_future(
            a.endpoint("test/ocancel").call(b.id, {"seq": 0}, PRIO_NORMAL, order=(sid, 0), timeout=30)
        )
        await asyncio.sleep(0.05)
        # seq 1 times out while gated behind seq 0
        with pytest.raises(asyncio.TimeoutError):
            await a.endpoint("test/ocancel").call(b.id, {"seq": 1}, PRIO_NORMAL, order=(sid, 1), timeout=0.2)
        release0.set()
        await t0
        # seq 2 must still be dispatched despite the dead seq 1
        await a.endpoint("test/ocancel").call(b.id, {"seq": 2}, PRIO_NORMAL, order=(sid, 2), timeout=5)
        assert 0 in ran and 2 in ran

    run(main(), timeout=60)


def test_shutdown_closes_connections_registered_mid_shutdown():
    """GL12 regression (ISSUE 14): shutdown() used to close a SNAPSHOT
    of conns and then clear() the map — a connection _register()ed
    while an earlier close() awaited survived the snapshot and was
    dropped from the map WITHOUT being closed (leaked socket, the peer
    kept a half-open channel). The pop-then-close loop drains late
    registrations too."""
    async def main():
        net = LocalNetwork()
        a = make_local_node(net)

        class FakeConn:
            def __init__(self):
                self.closed_flag = False

            async def close(self):
                self.closed_flag = True

        late = FakeConn()

        class SlowConn(FakeConn):
            async def close(self):
                # while this close() awaits, a peer's connect lands
                await asyncio.sleep(0)
                a.conns[b"late-peer"] = late
                self.closed_flag = True

        slow = SlowConn()
        a.conns[b"slow-peer"] = slow
        await a.shutdown()
        assert slow.closed_flag
        assert late.closed_flag, "late-registered conn leaked by shutdown"
        assert not a.conns

    run(main())
