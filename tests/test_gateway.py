"""Multi-process gateway tests (ISSUE 8).

Unit layer: the BudgetLeaseBroker conservation invariant (Σ leases ≤
node budget at ALL times, fuzzed across renew/revoke/expiry and budget
changes), demand rebalance + starvation recovery, 503 correctness when
the node budget is exhausted across worker engines, deficit-round-robin
bounded share, rendezvous ring stability, the BlockManager cache-router
seam, worker config derivation and the /metrics relabel merge.

Integration layer: a REAL forked supervisor + 2 SO_REUSEPORT workers —
S3 traffic through the shared port, aggregated worker-labeled /metrics,
tuning fan-out, worker-sharded cache counters, and the kill-a-worker
drill (zero failed retried ops on the survivor, lease drained and
conserved, rate-limited respawn).
"""

import asyncio
import json
import os
import random
import signal
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tests"))

from garage_tpu.gateway.lease import BudgetLeaseBroker  # noqa: E402
from garage_tpu.gateway.ring import CacheRing  # noqa: E402


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


# ---- BudgetLeaseBroker -------------------------------------------------


def test_lease_conservation_invariant_fuzzed():
    """Σ granted ≤ budget after EVERY operation, through a random storm
    of renews (skewed demands), revokes, TTL expiries and runtime
    budget changes — the acceptance-criteria invariant."""
    rng = random.Random(8)
    clk = FakeClock()
    b = BudgetLeaseBroker(1000.0, 8e6, min_share=0.05, ttl_s=3.0,
                          expected_workers=4, clock=clk)
    workers = [f"w{i}" for i in range(4)]
    for step in range(600):
        op = rng.random()
        w = rng.choice(workers)
        if op < 0.70:
            b.renew(w, demand_rps=rng.uniform(0, 5000),
                    demand_bytes_per_s=rng.uniform(0, 5e7))
        elif op < 0.85:
            b.revoke(w)
        elif op < 0.95:
            clk.t += rng.uniform(0, 4.0)  # may expire someone
            b.expire()
        else:
            # budget changes: grow instantly safe; shrink converges
            # shrink-first, but against the ORIGINAL totals the fuzz
            # asserts only after regrowing
            b.set_totals(rps=1000.0, bytes_per_s=8e6)
        assert b.conservation_ok, f"violated at step {step}"
        clk.t += rng.uniform(0, 0.3)


def test_lease_rebalance_follows_demand_and_recovers_starvation():
    clk = FakeClock()
    b = BudgetLeaseBroker(1000.0, min_share=0.05, ttl_s=5.0,
                          expected_workers=2, clock=clk)
    # join: equal shares
    l0 = b.renew("w0")
    l1 = b.renew("w1")
    assert l0.rps == pytest.approx(500.0)
    assert l1.rps == pytest.approx(500.0)
    # w0 runs hot, w1 idle: a few renew rounds move the budget to w0
    # (shrink the idle worker first, hand the freed pool to the hot one)
    for _ in range(8):
        clk.t += 1.0
        b.renew("w1", demand_rps=0.0)
        l0 = b.renew("w0", demand_rps=5000.0)
        assert b.conservation_ok
    assert l0.rps > 850.0
    floor = 0.05 * 500.0
    assert b.granted("w1")[0] >= floor * 0.99  # never starved below
    # starvation recovery: w1's demand spikes; within a few rounds it
    # is back to ~half (the floor lease admitted the discovery burst)
    for _ in range(10):
        clk.t += 1.0
        b.renew("w0", demand_rps=5000.0)
        l1 = b.renew("w1", demand_rps=5000.0)
        assert b.conservation_ok
    assert l1.rps > 400.0


def test_lease_revoke_and_ttl_expiry_drain_to_pool():
    clk = FakeClock()
    b = BudgetLeaseBroker(100.0, min_share=0.05, ttl_s=2.0,
                          expected_workers=2, clock=clk)
    b.renew("w0", demand_rps=100)
    b.renew("w1", demand_rps=100)
    # kill w0: its grant returns to the pool at revoke, and w1 can
    # absorb it on the very next renew
    b.revoke("w0")
    assert b.granted("w0") == (None, None)
    clk.t += 1.0
    l1 = b.renew("w1", demand_rps=100)
    assert l1.rps > 90.0
    assert b.conservation_ok
    # silent worker: no renew past ttl -> expired at the next sweep
    b2 = BudgetLeaseBroker(100.0, ttl_s=2.0, expected_workers=2,
                           clock=clk)
    b2.renew("wA", demand_rps=10)
    b2.renew("wB", demand_rps=10)
    clk.t += 10.0
    assert set(b2.expire()) == {"wA", "wB"}
    assert b2.granted("wA") == (None, None)
    assert b2.conservation_ok


def test_lease_budget_shrink_converges_within_one_round():
    clk = FakeClock()
    b = BudgetLeaseBroker(1000.0, expected_workers=2, clock=clk)
    b.renew("w0", demand_rps=500)
    b.renew("w1", demand_rps=500)
    b.set_totals(rps=100.0)
    clk.t += 1.0
    b.renew("w0", demand_rps=500)
    b.renew("w1", demand_rps=500)
    assert b.conservation_ok  # Σ ≤ 100 once both renewed


def test_lease_unlimited_dimension_stays_none():
    b = BudgetLeaseBroker(None, None, clock=FakeClock())
    lease = b.renew("w0", demand_rps=100, demand_bytes_per_s=100)
    assert lease.rps is None and lease.bytes_per_s is None
    assert b.conservation_ok


def test_node_budget_exhausted_sheds_503_across_workers():
    """Two worker QosEngines holding leases that sum to the node
    budget: together they admit at most the budget, and the overflow
    sheds as SlowDown (-> 503) with a sane Retry-After — N workers
    cannot admit N× the configured rate."""
    from garage_tpu.qos.limiter import QosEngine, QosLimits, SlowDown

    clk = FakeClock()
    broker = BudgetLeaseBroker(10.0, expected_workers=2, clock=clk)
    engines = {}
    for w in ("w0", "w1"):
        lease = broker.renew(w, demand_rps=100)
        engines[w] = QosEngine(QosLimits(
            global_rps=lease.rps, global_burst=lease.rps,
            max_wait_s=0.0), clock=clk)

    async def drive():
        admitted = shed = 0
        retry_after = None
        for i in range(30):
            eng = engines["w0"] if i % 2 == 0 else engines["w1"]
            try:
                async with eng.admit("s3"):
                    admitted += 1
            except SlowDown as e:
                shed += 1
                retry_after = e.retry_after
        return admitted, shed, retry_after

    admitted, shed, retry_after = run(drive())
    # Σ(leases) ≤ 10 rps: the node admits at most its budget (whole
    # tokens of the two fractional grants), never the 30 offered
    assert 8 <= admitted <= 10
    assert shed == 30 - admitted
    assert retry_after is not None and retry_after > 0


# ---- deficit round-robin (per-key fairness) ----------------------------


def test_drr_bounded_share_between_keys():
    """Key A floods the queue first; key B arrives after. DRR grants
    alternate instead of draining A's backlog first — each backlogged
    key gets ~1/K of the byte budget (the bounded-share property)."""
    from garage_tpu.qos.limiter import DeficitRoundRobin, TokenBucket

    clk = FakeClock()
    bucket = TokenBucket(1000.0, 2000.0, clock=clk)
    bucket.tokens = 0.0  # force contention from the first submit

    order = []

    async def scenario():
        async def fake_sleep(dt):
            clk.t += dt  # the pump self-advances simulated time
            await asyncio.sleep(0)

        drr = DeficitRoundRobin(bucket, quantum=100.0, sleep=fake_sleep)

        async def one(key):
            await drr.submit(key, 100.0)
            order.append(key)

        tasks = [asyncio.ensure_future(one("A")) for _ in range(10)]
        await asyncio.sleep(0)  # A's backlog queues first
        tasks += [asyncio.ensure_future(one("B")) for _ in range(10)]
        await asyncio.gather(*tasks)
        return drr

    drr = run(scenario())
    assert len(order) == 20
    # strict FCFS would be AAAAAAAAAA BBBB...; DRR interleaves
    first_half = order[:10]
    assert 3 <= first_half.count("B") <= 7, order
    assert drr.queued == 0


def test_drr_fast_path_and_cancellation():
    from garage_tpu.qos.limiter import DeficitRoundRobin, TokenBucket

    clk = FakeClock()
    bucket = TokenBucket(1000.0, 1000.0, clock=clk)

    async def scenario():
        async def fake_sleep(dt):
            clk.t += dt
            await asyncio.sleep(0)

        drr = DeficitRoundRobin(bucket, quantum=100.0, sleep=fake_sleep)
        # fast path: tokens available, nothing queued -> no pump task
        await drr.submit("A", 500.0)
        assert drr._pump_task is None
        bucket.tokens = 0.0
        t1 = asyncio.ensure_future(drr.submit("A", 100.0))
        t2 = asyncio.ensure_future(drr.submit("A", 100.0))
        await asyncio.sleep(0)
        t1.cancel()
        await asyncio.gather(t1, return_exceptions=True)
        await t2  # survivor still granted, cancelled bytes never drawn
        return drr

    drr = run(scenario())
    assert drr.granted == 1  # only t2 drew tokens through the pump


def test_shape_bytes_uses_request_key_contextvar():
    from garage_tpu.qos.limiter import (CURRENT_QOS_KEY, QosEngine,
                                        QosLimits)

    clk = FakeClock()
    eng = QosEngine(QosLimits(global_bytes_per_s=1e6,
                              global_bytes_burst=1e6, fair_keys=True),
                    clock=clk)
    assert eng._fair is not None

    async def charge():
        CURRENT_QOS_KEY.set("key-a")
        await eng.shape_bytes(1234)

    run(charge())
    assert eng.counters.shaped_bytes == 1234
    assert eng.counters.offered_bytes == 1234
    # fair_keys=False keeps the legacy negative-debt path
    eng2 = QosEngine(QosLimits(global_bytes_per_s=1e6,
                               fair_keys=False), clock=clk)
    assert eng2._fair is None


# ---- rendezvous ring ---------------------------------------------------


def test_ring_ownership_stable_and_minimally_disruptive():
    ids = [bytes([i]) * 32 for i in range(4)]
    ring = CacheRing(ids[0])
    ring.set_members(ids)
    hashes = [os.urandom(32) for _ in range(300)]
    owners = {h: ring.owner(h) for h in hashes}
    # every member owns a non-trivial share
    counts = {m: sum(1 for o in owners.values() if o == m) for m in ids}
    assert all(c > 20 for c in counts.values()), counts
    # removing one member remaps ONLY its keys
    ring.set_members(ids[:3])
    for h in hashes:
        if owners[h] != ids[3]:
            assert ring.owner(h) == owners[h]
    # self-exclusion semantics
    assert ring.owner_of(hashes[0]) != ring.self_id
    single = CacheRing(ids[0])
    single.set_members([ids[0]])
    assert single.owner_of(hashes[0]) is None  # <2 members: no routing
    assert single.owns(hashes[0])
    outsider = CacheRing(b"z" * 32)
    outsider.set_members(ids[:2])  # not in roster yet: serve locally
    assert outsider.owner_of(hashes[0]) is None


# ---- BlockManager cache-router seam ------------------------------------


def test_block_manager_routes_through_cache_owner(tmp_path):
    from test_block import make_block_cluster, stop_all

    async def scenario():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=1, rf=1)
        m = managers[0]
        data = b"gateway sharded cache payload " * 100
        from garage_tpu.utils.data import blake2sum

        h = blake2sum(data)
        await m.rpc_put_block(h, data, compress=False)
        m.cache.clear()

        class Router:
            def __init__(self):
                self.forwards = []
                self.answer = b"forwarded-bytes"

            def owner_of(self, hash32):
                return b"o" * 32  # some other worker

            def owns(self, hash32):
                return False

            async def forward(self, owner, hash32):
                self.forwards.append((owner, hash32))
                return self.answer

        charges = []

        async def charge(n):
            charges.append(n)

        router = Router()
        m.cache_router = router
        m.read_qos_charge = charge
        # routed read: served by the owner, charged locally, no fill
        got = await m.rpc_get_block(h)
        assert got == b"forwarded-bytes"
        assert charges == [len(b"forwarded-bytes")]
        assert m.cache.entries == 0
        # owner down -> direct store read, STILL no local fill
        router.answer = None
        got = await m.rpc_get_block(h)
        assert got == data
        assert m.cache.entries == 0
        # SSE-C (cacheable=False) never consults the router
        n_fw = len(router.forwards)
        got = await m.rpc_get_block(h, cacheable=False)
        assert got == data and len(router.forwards) == n_fw
        # route=False (the owner-side serve) is local and uncharged
        charges.clear()
        got = await m.rpc_get_block(h, route=False, charge=False)
        assert got == data and charges == []
        assert m.cache.entries == 1  # the owner-side serve DOES fill
        # write-through respects ownership: non-owner PUT skips insert
        m.cache.clear()
        data2 = os.urandom(1024)
        await m.rpc_put_block(blake2sum(data2), data2, compress=False)
        assert m.cache.entries == 0
        await stop_all(systems, tasks)

    run(scenario())


# ---- worker config derivation ------------------------------------------


def test_derive_worker_config_strips_state_and_divides_ram():
    from garage_tpu.gateway.worker import derive_worker_config
    from garage_tpu.utils.config import Config, DataDir

    cfg = Config(metadata_dir="/tmp/gtw-meta",
                 data_dir=[DataDir("/tmp/gtw-data", capacity=1 << 30)],
                 db_engine="lsm",
                 rpc_bind_addr="127.0.0.1:3901",
                 s3_api_bind_addr="127.0.0.1:3900",
                 admin_api_bind_addr="127.0.0.1:3903",
                 block_ram_buffer_max=256 << 20)
    cfg.qos.global_rps = 1000.0
    cfg.qos.governor = True
    w = derive_worker_config(cfg, 2, 4, "ab" * 32 + "@127.0.0.1:3901")
    assert w.metadata_dir.endswith("gateway/worker2")
    assert w.data_dir == [] and w.db_engine == "memory"
    assert w.rpc_bind_addr.endswith(":0")
    assert w.admin_api_bind_addr is None
    assert w.qos.governor is False
    assert w.qos.global_rps is None  # leased, not configured
    assert w.block_ram_buffer_max == (256 << 20) // 4
    assert w.block_read_cache_max_bytes == (256 << 20) // 4 // 4
    # the original config is untouched (supervisor keeps using it)
    assert cfg.db_engine == "lsm" and cfg.qos.global_rps == 1000.0


def test_relabel_metrics_adds_worker_label():
    from garage_tpu.admin.http import relabel_metrics

    text = ("# HELP api_foo help\n"
            "# TYPE api_foo counter\n"
            'api_foo{api="s3",method="GET"} 12\n'
            "cache_hits 3\n")
    out = relabel_metrics(text, "1")
    assert out == [
        'api_foo{api="s3",method="GET",worker="1"} 12',
        'cache_hits{worker="1"} 3',
    ]


# ---- integration: real forked supervisor + workers ---------------------


class GatewayServer:
    """Forked store+supervisor with N SO_REUSEPORT workers (wraps the
    conformance harness's Server)."""

    def __init__(self, tmpdir, workers=2, extra=""):
        from test_s3_api import Server

        self.srv = Server(str(tmpdir))
        with open(self.srv.config_path, "a") as f:
            f.write(f"""
[gateway]
workers = {workers}
lease_interval_s = 0.2
lease_ttl_s = 1.5
respawn_backoff_s = 0.5
{extra}
""")

    def __getattr__(self, name):
        return getattr(self.srv, name)

    def admin(self, path, method="GET", body=None):
        rq = urllib.request.Request(
            f"http://127.0.0.1:{self.srv.admin_port}{path}",
            data=(json.dumps(body).encode() if body is not None
                  else None),
            method=method,
            headers={"authorization": "Bearer test-admin-token"})
        with urllib.request.urlopen(rq, timeout=30) as r:
            return r.read().decode()

    def metrics(self):
        return self.admin("/metrics")

    def gateway_state(self, detail=False):
        return json.loads(self.admin(
            "/v1/gateway" + ("?detail=1" if detail else "")))


def _req_retry(c, method, path, tries=6, **kw):
    """Request with SDK-style retries: on a loaded CI box a worker's
    first metadata RPCs can time out (503/500) before the store's loop
    gets scheduled — transient, and exactly what real SDK backoff
    absorbs."""
    st, b = None, b""
    for attempt in range(tries):
        try:
            st, hdrs, b = c.request(method, path, **kw)
            if st == 200:
                return st, hdrs, b
        except OSError:
            pass
        time.sleep(0.3 * (attempt + 1))
    raise AssertionError(f"{method} {path}: {st} {b[:200]}")


def test_gateway_two_workers_end_to_end(tmp_path):
    """S3 through the shared SO_REUSEPORT port, aggregated /metrics
    with per-worker labels, tuning fan-out to every worker, leases
    summing within the node budget, one cache copy per block."""
    from s3util import S3Client

    gw = GatewayServer(tmp_path, workers=2,
                       extra="\n[qos]\nglobal_rps = 500\n")
    gw.start()
    try:
        gw.setup_layout_and_key()
        c = S3Client("127.0.0.1", gw.s3_port, gw.key_id, gw.secret)
        _req_retry(c, "PUT", "/gwbkt")
        data = os.urandom(200_000)  # ~4 blocks at the 64 KiB test size
        _req_retry(c, "PUT", "/gwbkt/obj", body=data,
                   unsigned_payload=True)
        time.sleep(1.0)  # sibling mesh forms after the first renews
        for _ in range(8):  # fresh conns spread across both workers
            st, _, got = c.request("GET", "/gwbkt/obj")
            assert st == 200 and got == data

        state = gw.gateway_state()
        assert state["workers_configured"] == 2
        assert state["workers_alive"] == 2
        assert state["broker"]["conservation_ok"]
        leases = [w["lease"]["rps"] for w in state["workers"]]
        assert all(v is not None for v in leases)
        assert sum(leases) <= 500.0 * 1.001

        m = gw.metrics()
        for w in ("0", "1"):
            assert f'worker="{w}"' in m  # per-worker series merged
        assert "gateway_lease_conservation_ok 1" in m
        assert "gateway_workers_alive 2" in m
        # worker-sharded cache: ONE decoded copy per block node-wide
        inserts = [int(ln.split()[1]) for ln in m.splitlines()
                   if ln.startswith("cache_inserts{")]
        n_blocks = (len(data) + 65535) // 65536
        assert sum(inserts) <= n_blocks + 1  # +1: inline/meta slack

        # tuning fan-out: every worker applies the POST
        out = json.loads(gw.admin("/v1/s3/tuning", "POST",
                                  {"get_readahead_blocks": 9}))
        assert set(out["workers"]) == {0, 1} or \
            set(out["workers"]) == {"0", "1"}
        det = gw.gateway_state(detail=True)
        got_vals = [v.get("get_readahead_blocks")
                    for v in det["worker_tuning"].values()]
        assert got_vals == [9, 9]
        # qos fan-out: per-worker knobs travel; node budgets hit the
        # broker (leases shrink within a renew interval)
        json.loads(gw.admin("/v1/qos", "POST", {"global_rps": 100}))
        deadline = time.time() + 5
        while time.time() < deadline:
            st2 = gw.gateway_state()
            leases = [w["lease"]["rps"] or 0.0
                      for w in st2["workers"]]
            if sum(leases) <= 100.0 * 1.001:
                break
            time.sleep(0.1)
        assert sum(leases) <= 100.0 * 1.001
        assert st2["broker"]["conservation_ok"]
    finally:
        gw.stop()


def test_gateway_worker_kill_respawn_and_lease_conservation(tmp_path):
    """SIGKILL one worker mid-traffic: retried ops all succeed on the
    survivor, the dead worker's lease drains back (conservation holds
    throughout), and the supervisor respawns it rate-limited."""
    from s3util import S3Client

    gw = GatewayServer(tmp_path, workers=2,
                       extra="\n[qos]\nglobal_rps = 400\n")
    gw.start()
    try:
        gw.setup_layout_and_key()
        c = S3Client("127.0.0.1", gw.s3_port, gw.key_id, gw.secret)
        _req_retry(c, "PUT", "/kbkt")
        data = os.urandom(100_000)
        _req_retry(c, "PUT", "/kbkt/obj", body=data,
                   unsigned_payload=True)

        # a runtime knob posted BEFORE the crash must survive into the
        # respawned worker (supervisor replays fanned-out knobs on
        # hello — without it the new process silently reverts to the
        # on-disk config while its siblings keep the posted value)
        json.loads(gw.admin("/v1/s3/tuning", "POST",
                            {"get_readahead_blocks": 11}))

        state = gw.gateway_state()
        pid0 = next(w["pid"] for w in state["workers"]
                    if w["index"] == 0)
        os.kill(pid0, signal.SIGKILL)

        failed_after_retry = 0
        for _ in range(25):
            for attempt in range(4):
                try:
                    st, _, got = c.request("GET", "/kbkt/obj")
                    assert st == 200 and got == data
                    break
                except (AssertionError, OSError):
                    if attempt == 3:
                        failed_after_retry += 1
                    time.sleep(0.05)
        assert failed_after_retry == 0

        deadline = time.time() + 20
        while time.time() < deadline:
            state = gw.gateway_state()
            if state["workers_alive"] == 2 \
                    and all(w["ready"] for w in state["workers"]):
                break
            time.sleep(0.2)
        assert state["workers_alive"] == 2
        assert state["restarts_total"] >= 1
        assert state["broker"]["conservation_ok"]
        leases = [w["lease"]["rps"] or 0.0 for w in state["workers"]]
        assert sum(leases) <= 400.0 * 1.001
        m = gw.metrics()
        assert "gateway_lease_conservation_ok 1" in m
        assert "gateway_worker_restarts_total" in m
        # knob replay: the respawned worker carries the pre-crash value
        deadline = time.time() + 10
        vals = []
        while time.time() < deadline:
            det = gw.gateway_state(detail=True)
            vals = [v.get("get_readahead_blocks")
                    for v in det["worker_tuning"].values()]
            if vals == [11, 11]:
                break
            time.sleep(0.3)
        assert vals == [11, 11], vals
    finally:
        gw.stop()
