"""Model-layer tests: schema CRDT/encoding round-trips, and the
end-to-end trigger chain object -> version -> block_ref -> block rc ->
resync deletion, on a multi-node loopback cluster (the VERDICT round-1
done-criterion for the model layer)."""

import asyncio
import os

import pytest

from garage_tpu.model import (
    Bucket,
    BucketAlias,
    BucketKeyPerm,
    Garage,
    Key,
    is_valid_bucket_name,
)
from garage_tpu.model.s3 import (
    BlockRef,
    MultipartUpload,
    Object,
    ObjectVersion,
    ObjectVersionData,
    ObjectVersionMeta,
    ObjectVersionState,
    Version,
    object_upload_version,
)
from garage_tpu.net import LocalNetwork
from garage_tpu.utils import migrate
from garage_tpu.utils.config import Config, DataDir
from garage_tpu.utils.data import blake2sum, gen_uuid


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def wait_until(cond, timeout=20.0, interval=0.05):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return cond()


async def make_garage_cluster(tmp_path, n=3, rf=3, erasure=None,
                              storage=None):
    """`storage`: node indices that get a storage role in layout v1
    (default all) — the rest join as gateways, so tests can stage
    add/remove transitions later."""
    net = LocalNetwork()
    garages = []
    for i in range(n):
        cfg = Config(
            metadata_dir=str(tmp_path / f"node{i}" / "meta"),
            data_dir=[DataDir(path=str(tmp_path / f"node{i}" / "data"))],
            db_engine="memory",
            replication_factor=rf,
            erasure_coding="%d,%d" % erasure if erasure else None,
        )
        garages.append(Garage(cfg, local_net=net,
                              status_interval=0.2, ping_interval=0.2))
    tasks = [asyncio.create_task(g.run()) for g in garages]
    for g in garages[1:]:
        await g.netapp.try_connect(garages[0].netapp.public_addr,
                                   garages[0].system.id)
        g.system.peering.add_peer(garages[0].netapp.public_addr,
                                  garages[0].system.id)
    assert await wait_until(
        lambda: all(len(g.netapp.conns) == n - 1 for g in garages)
    )
    lm = garages[0].system.layout_manager
    from garage_tpu.rpc.layout import NodeRole

    for i, g in enumerate(garages):
        if storage is None or i in storage:
            lm.history.stage_role(g.system.id,
                                  NodeRole(zone="z1", capacity=1 << 30))
    lm.apply_staged(None)
    assert await wait_until(
        lambda: all(
            g.system.layout_manager.history.current().version == 1
            for g in garages
        )
    )
    return net, garages, tasks


async def stop_all(garages, tasks):
    for g in garages:
        await g.stop()
    for t in tasks:
        t.cancel()


async def put_object_like_api(g: Garage, bucket_id: bytes, key: str,
                              data: bytes):
    """Mimic the PUT path (api/s3/put.rs:122-300) for one-block objects:
    object Uploading -> version -> block_ref + block -> object Complete."""
    uuid = gen_uuid()
    h = blake2sum(data)
    up = object_upload_version(bucket_id, key, uuid,
                               {"content-type": "application/octet-stream"})
    await g.object_table.insert(up)
    version = Version.new(uuid, ("object", bucket_id, key))
    await g.version_table.insert(version)
    await g.block_ref_table.insert(BlockRef.new(h, uuid))
    await g.block_manager.rpc_put_block(h, data)
    await g.version_table.insert(version.with_block(0, 0, h, len(data)))
    meta = ObjectVersionMeta({"content-type": "application/octet-stream"},
                             len(data), '"%s"' % blake2sum(data).hex())
    ts = up.versions[0].timestamp
    done = Object(bucket_id, key, [ObjectVersion(
        uuid, ts,
        ObjectVersionState.complete(ObjectVersionData.first_block(meta, h)),
    )])
    await g.object_table.insert(done)
    return uuid, h


async def delete_object_like_api(g: Garage, bucket_id: bytes, key: str):
    """A DeleteMarker version supersedes all prior versions
    (api/s3/delete.rs)."""
    uuid = gen_uuid()
    obj = Object(bucket_id, key, [ObjectVersion(
        uuid, __import__("garage_tpu.utils.crdt", fromlist=["now_msec"]).now_msec(),
        ObjectVersionState.complete(ObjectVersionData.delete_marker()),
    )])
    await g.object_table.insert(obj)
    return uuid


# ---- pure schema tests --------------------------------------------------


def test_object_schema_roundtrip_and_merge():
    bid, uid = gen_uuid(), gen_uuid()
    meta = ObjectVersionMeta({"content-type": "text/plain"}, 11, '"abc"')
    v_up = ObjectVersion(uid, 100, ObjectVersionState.uploading({}, False))
    v_done = ObjectVersion(
        uid, 100,
        ObjectVersionState.complete(ObjectVersionData.inline(meta, b"hello world")),
    )
    o1 = Object(bid, "k", [v_up])
    o2 = Object(bid, "k", [v_done])
    m = o1.merge(o2)
    assert len(m.versions) == 1 and m.versions[0].is_data
    # commutative
    m2 = o2.merge(o1)
    assert migrate.encode(m) == migrate.encode(m2)
    # roundtrip
    rt = migrate.decode(Object, migrate.encode(m))
    assert rt.key == "k" and rt.versions[0].state.data.blob == b"hello world"
    assert rt.versions[0].state.data.meta.size == 11
    # aborted wins
    o3 = Object(bid, "k", [ObjectVersion(uid, 100, ObjectVersionState.aborted())])
    assert o2.merge(o3).versions[0].state.kind == "aborted"
    # newer complete version drops older ones
    uid2 = gen_uuid()
    v2 = ObjectVersion(
        uid2, 200,
        ObjectVersionState.complete(ObjectVersionData.delete_marker()),
    )
    m3 = m.merge(Object(bid, "k", [v2]))
    assert [v.timestamp for v in m3.versions] == [200]
    assert m3.counts() == [("objects", 0), ("unfinished_uploads", 0), ("bytes", 0)]


def test_version_and_blockref_roundtrip():
    uid = gen_uuid()
    v = Version.new(uid, ("object", gen_uuid(), "some/key"))
    v = v.with_block(1, 0, blake2sum(b"a"), 100)
    v = v.with_block(1, 100, blake2sum(b"b"), 50)
    rt = migrate.decode(Version, migrate.encode(v))
    assert rt.total_size() == 150 and rt.n_parts() == 1
    assert rt.has_part_number(1) and not rt.has_part_number(2)
    # deleted clears blocks
    d = rt.merge(Version(uid, __import__("garage_tpu.utils.crdt",
                                         fromlist=["Bool"]).Bool(True),
                         rt.blocks.clear(), rt.backlink))
    assert d.is_tombstone() and len(d.blocks) == 0
    br = BlockRef.new(blake2sum(b"a"), uid)
    rt2 = migrate.decode(BlockRef, migrate.encode(br))
    assert rt2.block == br.block and not rt2.is_tombstone()


def test_bucket_key_schema():
    assert is_valid_bucket_name("my-bucket.data")
    assert not is_valid_bucket_name("My_Bucket")
    assert not is_valid_bucket_name("ab")
    assert not is_valid_bucket_name("192.168.1.1")

    b = Bucket.new()
    params = b.params
    params.authorized_keys = params.authorized_keys.put(
        "GK" + "0" * 24, BucketKeyPerm(1, True, True, False))
    b = b.with_params(params)
    rt = migrate.decode(Bucket, migrate.encode(b))
    assert rt.authorized("GK" + "0" * 24).allow_write
    assert not rt.authorized("GK" + "1" * 24).allow_read

    k = Key.new("test-key")
    assert k.key_id.startswith("GK") and len(k.key_id) == 26
    rt = migrate.decode(Key, migrate.encode(k))
    assert rt.params.name.value == "test-key"
    assert not rt.allow_read(b.id)
    # permission tie-break: most restricted
    p1 = BucketKeyPerm(5, True, True, True)
    p2 = BucketKeyPerm(5, True, False, True)
    assert p1.merge(p2) == BucketKeyPerm(5, True, False, True)

    a = BucketAlias.new("my-bucket", b.id)
    rt = migrate.decode(BucketAlias, migrate.encode(a))
    assert rt.bucket_id == b.id and not rt.is_deleted
    assert BucketAlias.new("Bad_Name", b.id) is None


def test_mpu_schema():
    up = MultipartUpload.new(gen_uuid(), 123, gen_uuid(), "key")
    ts = up.next_timestamp(1)
    from garage_tpu.model.s3 import MpuPart

    up.parts = up.parts.put((1, ts), MpuPart(gen_uuid(), '"e1"', 500))
    rt = migrate.decode(MultipartUpload, migrate.encode(up))
    assert rt.counts() == [("uploads", 1), ("parts", 1), ("bytes", 500)]
    # deletion clears parts
    tomb = MultipartUpload.new(up.upload_id, 123, up.bucket_id, "key",
                               deleted=True)
    m = rt.merge(tomb)
    assert m.is_tombstone() and len(m.parts) == 0


# ---- cluster tests ------------------------------------------------------


def test_object_lifecycle_end_to_end(tmp_path):
    """Insert an object -> block refs + rc appear on all holders;
    delete it -> versions/block_refs tombstone, rc hits deletable,
    resync removes the data files (VERDICT item 1 done-criterion)."""

    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path)
        try:
            for g in garages:
                g.block_manager.rc.gc_delay = 0.0
            bucket_id = gen_uuid()
            data = os.urandom(100_000)
            uuid, h = await put_object_like_api(garages[0], bucket_id,
                                                "hello.bin", data)
            # all 3 nodes hold the block and a present rc
            assert await wait_until(lambda: all(
                g.block_manager.has_local(h) for g in garages))
            assert await wait_until(lambda: all(
                g.block_manager.rc.get(h)[0] == "present" for g in garages))
            # object readable from any node
            got = await garages[2].object_table.get(bucket_id, b"hello.bin")
            assert got is not None and got.last_data() is not None
            assert got.last_data().state.data.blob == h
            blk = await garages[1].block_manager.rpc_get_block(h)
            assert blk == data

            # delete: marker supersedes -> triggers cascade
            await delete_object_like_api(garages[0], bucket_id, "hello.bin")
            assert await wait_until(lambda: all(
                g.block_manager.rc.get(h)[0] != "present" for g in garages))
            # resync workers offload+delete the now-unneeded files
            assert await wait_until(lambda: not any(
                g.block_manager.has_local(h) for g in garages), timeout=30)
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_object_counter_counts(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path)
        try:
            bucket_id = gen_uuid()
            await put_object_like_api(garages[0], bucket_id, "a", b"x" * 1000)
            await put_object_like_api(garages[0], bucket_id, "b", b"y" * 500)
            nodes = [g.system.id for g in garages]
            counter = garages[0].object_counter

            async def totals():
                return await counter.read(bucket_id, b"", nodes)

            async def check():
                t = await totals()
                return t.get("objects") == 2 and t.get("bytes") == 1500

            deadline = asyncio.get_event_loop().time() + 20
            ok = False
            while asyncio.get_event_loop().time() < deadline and not ok:
                ok = await check()
                if not ok:
                    await asyncio.sleep(0.1)
            assert ok, await totals()
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_erasure_block_ref_reaches_all_shard_holders(tmp_path):
    """ADVICE round-1 medium: with erasure(k,m) where k+m > rf, block_ref
    rows (and therefore rc state) must reach all k+m shard holders so
    each holder manages its shard lifecycle."""

    async def main():
        net, garages, tasks = await make_garage_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2))
        try:
            bucket_id = gen_uuid()
            data = os.urandom(64_000)
            uuid, h = await put_object_like_api(garages[0], bucket_id,
                                               "wide.bin", data)
            # every node holds exactly one shard, and every holder's rc
            # is present (block_ref replicated to the full width)
            assert await wait_until(lambda: sorted(
                i for g in garages for i in g.block_manager.local_parts(h)
            ) == [0, 1, 2, 3, 4, 5], timeout=30)
            assert await wait_until(lambda: all(
                g.block_manager.rc.get(h)[0] == "present" for g in garages),
                timeout=30)
            # destroy one shard; its holder heals itself via resync
            victim = next(g for g in garages
                          if 2 in g.block_manager.local_parts(h))
            victim.block_manager.delete_local(h)
            victim.block_manager.resync.push_now(h)
            assert await wait_until(
                lambda: victim.block_manager.local_parts(h) == [2],
                timeout=30)
            got = await garages[5].block_manager.rpc_get_block(h)
            assert got == data
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_bucket_key_tables_fullcopy(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path)
        try:
            b = Bucket.new()
            await garages[0].bucket_table.insert(b)
            k = Key.new("app")
            await garages[0].key_table.insert(k)
            a = BucketAlias.new("my-bucket", b.id)
            await garages[0].bucket_alias_table.insert(a)

            # full-copy: local read on any node sees them (after sync)
            async def visible():
                got_b = await garages[2].bucket_table.get(b.id, b"")
                got_k = await garages[1].key_table.get(
                    b"", k.key_id.encode())
                got_a = await garages[2].bucket_alias_table.get(
                    b"", b"my-bucket")
                return (got_b is not None and got_k is not None
                        and got_a is not None
                        and got_a.bucket_id == b.id)

            deadline = asyncio.get_event_loop().time() + 20
            ok = False
            while asyncio.get_event_loop().time() < deadline and not ok:
                ok = await visible()
                if not ok:
                    await asyncio.sleep(0.1)
            assert ok
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_interrupted_upload_releases_block_refs(tmp_path):
    """A PUT dropped mid-stream must not leak refcounts: the per-block
    version/block_ref rows ride the local insert queue (put.py), and the
    abort path flushes them BEFORE the aborted-object tombstone — else
    the tombstone CRDT-merges into the queued version row, wipes its
    block map, and the already-queued live BlockRefs pin the blocks
    forever (r4 review finding)."""
    import pytest

    from garage_tpu.api.s3.put import save_stream

    class FailingBody:
        """Streams two blocks, lets the pipeline store them, then dies
        like a dropped connection (the leak needs put_one to have
        QUEUED its metadata rows before the failure)."""

        def __init__(self, block_size):
            self.left = [os.urandom(block_size), os.urandom(block_size)]

        async def read(self, n: int = 65536) -> bytes:
            if self.left:
                return self.left.pop(0)
            await asyncio.sleep(0.3)  # in-flight put_one tasks complete
            raise ConnectionError("client went away")

    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=1, rf=1)
        g = garages[0]
        try:
            # stop background workers: the InsertQueueWorker's fast
            # drain usually hides the race window this test pins down
            # (abort landing while rows are still queued)
            await g.runner.shutdown()
            bucket_id = gen_uuid()
            block_size = g.config.block_size
            with pytest.raises(ConnectionError):
                await save_stream(g, bucket_id, "interrupted", {},
                                  FailingBody(block_size))
            # the aborted tombstone is recorded
            obj = await g.object_table.get(bucket_id, b"interrupted")
            assert obj is not None
            assert obj.versions[-1].state.kind == "aborted"
            # drive queue propagation + triggers to quiescence
            for _ in range(5):
                await g.version_table.flush_insert_queue()
                await g.block_ref_table.flush_insert_queue()
            # every stored block's refcount must be released: the
            # version rows reached the table WITH their block maps, so
            # the deletion transition emitted BlockRef tombstones
            held = [h for h, _ in g.block_manager.iter_local_blocks()
                    if g.block_manager.rc.is_needed(h)]
            assert held == [], [h.hex()[:12] for h in held]
        finally:
            await stop_all(garages, tasks)

    run(main())
