"""Layout tests: assignment optimality, movement, CRDT convergence.

Mirrors the reference's layout tests (src/rpc/layout/test.rs:120
check_against_naive + staged-update merge convergence).
"""

import os

from garage_tpu.rpc.layout import (
    LayoutHistory,
    LayoutVersion,
    N_PARTITIONS,
    NodeRole,
)
from garage_tpu.rpc.layout.assign import compute_assignment
from garage_tpu.utils import crdt, migrate


def nid(i: int) -> bytes:
    return bytes([i]) * 32


def mkroles(spec):
    """spec: {node_id: (zone, capacity)}"""
    m = crdt.LwwMap()
    for node, (zone, cap) in spec.items():
        m = m.insert(node, NodeRole(zone=zone, capacity=cap))
    return m


def naive_partition_size(spec, rf):
    """Greedy baseline: repeatedly give the next replica slot to the
    storage node with the most remaining per-slot capacity, ignoring
    zones. Returns min over nodes of capacity/slots — what an unoptimized
    assignment would achieve."""
    caps = {n: c for n, (z, c) in spec.items() if c is not None}
    slots = {n: 0 for n in caps}
    for _ in range(N_PARTITIONS * rf):
        best = max(caps, key=lambda n: caps[n] / (slots[n] + 1))
        slots[best] += 1
    return min(caps[n] // slots[n] for n in caps if slots[n] > 0)


def check_optimal(spec, rf, zone_redundancy="maximum"):
    roles = list(mkroles(spec).items())
    node_id_vec, ring, size = compute_assignment(roles, rf, zone_redundancy)
    # structural invariants
    assert len(ring) == N_PARTITIONS * rf
    lv = LayoutVersion(1, rf, zone_redundancy, mkroles(spec), node_id_vec, ring, size)
    zones = {n: z for n, (z, c) in spec.items()}
    n_zones = len({z for z, c in spec.values() if c is not None})
    zr = min(rf, n_zones) if zone_redundancy == "maximum" else zone_redundancy
    for p in range(N_PARTITIONS):
        nodes = lv.nodes_of(p)
        assert len(set(nodes)) == rf, f"partition {p} has dup nodes"
        assert len({zones[n] for n in nodes}) >= zr, f"partition {p} zone redundancy"
    # load respects capacity at the claimed partition size
    counts = {}
    for b in ring:
        counts[node_id_vec[b]] = counts.get(node_id_vec[b], 0) + 1
    for n, cnt in counts.items():
        assert cnt * size <= spec[n][1], f"node overloaded: {cnt} x {size}"
    return size


def test_assignment_optimal_beats_naive_uniform():
    spec = {nid(i): ("z1", 1 << 30) for i in range(4)}
    size = check_optimal(spec, 3)
    assert size >= naive_partition_size(spec, 3)


def test_assignment_optimal_beats_naive_heterogeneous():
    spec = {
        nid(1): ("z1", 4 << 30),
        nid(2): ("z1", 2 << 30),
        nid(3): ("z2", 1 << 30),
        nid(4): ("z2", 4 << 30),
        nid(5): ("z3", 2 << 30),
    }
    size = check_optimal(spec, 3)
    # naive ignores zones, so the comparison is only meaningful as a
    # lower bound sanity check when zones don't bind; still assert we're
    # within a sane range of total/target
    assert size > 0


def test_assignment_three_zones_redundancy():
    spec = {
        nid(1): ("dc1", 1 << 30),
        nid(2): ("dc1", 1 << 30),
        nid(3): ("dc2", 1 << 30),
        nid(4): ("dc2", 1 << 30),
        nid(5): ("dc3", 1 << 30),
        nid(6): ("dc3", 1 << 30),
    }
    check_optimal(spec, 3, zone_redundancy=3)


def test_assignment_single_node_rf1():
    spec = {nid(1): ("dc1", 1 << 30)}
    size = check_optimal(spec, 1)
    assert size >= (1 << 30) // N_PARTITIONS


def test_movement_minimization():
    spec3 = {nid(i): ("z1", 1 << 30) for i in (1, 2, 3)}
    roles3 = mkroles(spec3)
    vec3, ring3, size3 = compute_assignment(list(roles3.items()), 3, "maximum")
    prev = LayoutVersion(1, 3, "maximum", roles3, vec3, ring3, size3)

    spec4 = dict(spec3)
    spec4[nid(4)] = ("z1", 1 << 30)
    roles4 = mkroles(spec4)
    vec4, ring4, size4 = compute_assignment(list(roles4.items()), 3, "maximum", prev=prev)
    new = LayoutVersion(2, 3, "maximum", roles4, vec4, ring4, size4)

    retained = sum(
        len(set(prev.nodes_of(p)) & set(new.nodes_of(p))) for p in range(N_PARTITIONS)
    )
    total = N_PARTITIONS * 3
    # optimal move: new node takes 1/4 of slots -> 75% retained; allow slack
    assert retained / total >= 0.70, f"only {retained}/{total} replica slots kept"
    # and the new node must actually carry ~1/4 of the data
    cnt4 = sum(1 for b in ring4 if vec4[b] == nid(4))
    assert cnt4 >= total // 8


def test_history_staging_and_apply(tmp_path):
    h = LayoutHistory.new(3)
    for i in (1, 2, 3):
        h.stage_role(nid(i), NodeRole(zone=f"z{i}", capacity=1 << 30))
    h.apply_staged_changes()
    assert h.current().version == 1
    assert len(h.current().ring_assignment_data) == N_PARTITIONS * 3
    # round-trip through the versioned encoding
    data = migrate.encode(h)
    h2 = migrate.decode(LayoutHistory, data)
    assert h2.current().version == 1
    assert h2.current().nodes_of(0) == h.current().nodes_of(0)


def test_history_crdt_merge_convergence():
    """Two operators stage different roles concurrently; both merge to the
    same state regardless of order (ref: layout/test.rs CRDT checks)."""
    base = LayoutHistory.new(3)
    for i in (1, 2, 3):
        base.stage_role(nid(i), NodeRole(zone="z", capacity=1 << 30))
    base.apply_staged_changes()
    raw = migrate.encode(base)

    a = migrate.decode(LayoutHistory, raw)
    b = migrate.decode(LayoutHistory, raw)
    a.stage_role(nid(4), NodeRole(zone="z", capacity=2 << 30))
    b.stage_role(nid(5), NodeRole(zone="z", capacity=3 << 30))

    ab = migrate.decode(LayoutHistory, migrate.encode(a))
    ab.merge(b)
    ba = migrate.decode(LayoutHistory, migrate.encode(b))
    ba.merge(a)
    assert migrate.encode(ab) == migrate.encode(ba)
    # apply on the merged state sees both staged roles
    ab.apply_staged_changes()
    assert nid(4) in ab.current().storage_nodes()
    assert nid(5) in ab.current().storage_nodes()


def test_tracker_gc_of_old_versions():
    h = LayoutHistory.new(1)
    h.stage_role(nid(1), NodeRole(zone="z", capacity=1 << 30))
    h.apply_staged_changes()
    h.stage_role(nid(2), NodeRole(zone="z", capacity=1 << 30))
    h.apply_staged_changes()
    # the empty bootstrap v0 is pruned as soon as a valid version exists
    # (ref: history.rs:81-89); v1 stays until sync-acked by all
    assert [v.version for v in h.versions] == [1, 2]
    for n in (nid(1), nid(2)):
        h.update_trackers.set_max("ack", n, 2)
        h.update_trackers.set_max("sync", n, 2)
        h.update_trackers.set_max("sync_ack", n, 2)
    h.cleanup_old_versions()
    assert h.min_stored() == 2
    # v0 was discarded (invalid/empty); v1 is archived for block lookup
    assert [v.version for v in h.old_versions] == [1]


def test_skip_dead_nodes_unblocks_tracker_convergence():
    """A permanently dead node wedges tracker GC forever; the
    layout_skip_dead_nodes admin op advances its trackers so the old
    version can be archived (ref: cli/layout.rs
    cmd_layout_skip_dead_nodes, cli/structs.rs:182)."""
    import asyncio
    import types

    from garage_tpu.admin.rpc import AdminRpcHandler

    h = LayoutHistory.new(2)
    for i in (1, 2, 3):
        h.stage_role(nid(i), NodeRole(zone="z", capacity=1 << 30))
    h.apply_staged_changes()
    h.stage_role(nid(4), NodeRole(zone="z", capacity=1 << 30))
    h.apply_staged_changes()
    assert [v.version for v in h.versions] == [1, 2]
    # live nodes fully ack v2; node 3 died before acking anything
    for n in (nid(1), nid(2), nid(4)):
        for which in ("ack", "sync", "sync_ack"):
            h.update_trackers.set_max(which, n, 2)
    h.cleanup_old_versions()
    assert h.min_stored() == 1  # wedged by the dead node

    class FakeLm:
        history = h

        @staticmethod
        def save():
            pass

        @staticmethod
        async def broadcast():
            pass

    system = types.SimpleNamespace(
        layout_manager=FakeLm,
        is_up=lambda node: node != nid(3),
    )
    handler = AdminRpcHandler.__new__(AdminRpcHandler)
    handler.garage = types.SimpleNamespace(system=system)

    r = asyncio.run(handler.op_layout_skip_dead_nodes(
        {"allow_missing_data": True}))
    assert r["updated"] == [nid(3).hex()]
    assert h.min_stored() == 2  # convergence unblocked
    # idempotent: second call finds nothing stale
    r = asyncio.run(handler.op_layout_skip_dead_nodes(
        {"allow_missing_data": True}))
    assert r["updated"] == []
