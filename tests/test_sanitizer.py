"""ISSUE 14: runtime asyncio sanitizer self-tests.

The seeded-stall test is the acceptance proof: a `time.sleep` typed
onto the loop produces a report naming the offending frame WHILE it
blocks. The other tests pin teardown leak detection (tasks, locks),
budget-conservation tracking, and that a clean run stays silent.

These tests install the sanitizer's patches themselves (they are
idempotent and observation-only), then drain every report they
generate so the conftest's autouse check never sees test-induced
noise.
"""

import asyncio
import time

import pytest

from garage_tpu.utils import sanitizer


@pytest.fixture(autouse=True, scope="module")
def _scoped_sanitizer():
    """These tests install() (and thereby activate) the sanitizer even
    in unarmed pytest sessions; on module exit, reporting reverts to
    the armed() state so later tests don't accumulate reports nobody
    drains."""
    yield
    sanitizer.set_active(sanitizer.armed())
    sanitizer.drain_reports()


@pytest.fixture
def fast_stall():
    """Temporarily lower the stall threshold; always restore."""
    sanitizer.install()
    prev = sanitizer.stall_threshold()
    sanitizer.configure(0.25)
    yield 0.25
    sanitizer.configure(prev)
    sanitizer.drain_reports()


def test_seeded_stall_reports_the_blocking_frame(fast_stall):
    async def _seeded_stall():
        time.sleep(0.7)  # deliberately pins the loop

    asyncio.run(_seeded_stall())
    time.sleep(0.1)  # let the monitor thread flush its sample
    reports = sanitizer.drain_reports()
    stalls = [r for r in reports if r["kind"] == "loop_stall"]
    assert stalls, f"no stall report in {reports}"
    # the report names the live frame, not a post-hoc summary
    assert "_seeded_stall" in stalls[0]["detail"]
    assert "time.sleep" in stalls[0]["detail"] \
        or "test_sanitizer" in stalls[0]["detail"]


def test_one_report_per_stall_episode(fast_stall):
    async def _stall_once():
        time.sleep(0.7)
        await asyncio.sleep(0.3)  # beats resume: episode over

    asyncio.run(_stall_once())
    time.sleep(0.1)
    stalls = [r for r in sanitizer.drain_reports()
              if r["kind"] == "loop_stall"]
    assert len(stalls) == 1


def test_sub_200ms_stall_detected_at_low_threshold():
    """ISSUE 15 satellite: the sampler used to run at threshold/5
    only, so a threshold below 200 ms could sandwich a whole stall
    between two samples AND between two heartbeats. The 20 ms cadence
    floor plus the heartbeat's retroactive late-arrival check make a
    seeded sub-200 ms stall deterministic to catch."""
    sanitizer.install()
    prev = sanitizer.stall_threshold()
    sanitizer.configure(0.08)
    try:
        assert sanitizer._sample_period() <= 0.02

        async def _short_stall():
            await asyncio.sleep(0.05)  # let the beat chain settle
            time.sleep(0.15)           # 150 ms pin, over the 80 ms bar
            await asyncio.sleep(0.05)  # beats resume -> retro check

        asyncio.run(_short_stall())
        time.sleep(0.1)
        stalls = [r for r in sanitizer.drain_reports()
                  if r["kind"] == "loop_stall"]
        assert stalls, "sub-200ms stall went unseen"
    finally:
        sanitizer.configure(prev)
        sanitizer.drain_reports()


def test_no_stall_report_below_threshold(fast_stall):
    async def _quick():
        time.sleep(0.05)
        await asyncio.sleep(0.05)

    asyncio.run(_quick())
    time.sleep(0.1)
    assert [r for r in sanitizer.drain_reports()
            if r["kind"] == "loop_stall"] == []


def test_leaked_task_reported_background_task_not():
    sanitizer.install()
    sanitizer.drain_reports()

    async def main():
        async def forever():
            await asyncio.sleep(3600)

        leaked = asyncio.ensure_future(forever())
        leaked.set_name("leaked-task")
        marked = asyncio.ensure_future(forever())
        marked._garage_background = True
        await asyncio.sleep(0.01)

    asyncio.run(main())
    leaks = [r for r in sanitizer.drain_reports()
             if r["kind"] == "task_leak"]
    assert len(leaks) == 1
    assert "leaked-task" in leaks[0]["detail"]


def test_utils_background_spawn_is_marked():
    from garage_tpu.utils.background import spawn

    sanitizer.install()
    sanitizer.drain_reports()

    async def main():
        async def forever():
            await asyncio.sleep(3600)

        spawn(forever(), "deliberate-background")
        await asyncio.sleep(0.01)

    asyncio.run(main())
    assert [r for r in sanitizer.drain_reports()
            if r["kind"] == "task_leak"] == []


def test_lock_held_at_teardown_reported():
    sanitizer.install()
    sanitizer.drain_reports()

    async def main():
        lock = asyncio.Lock()
        await lock.acquire()  # never released; survives cancel-all

    asyncio.run(main())
    locks = [r for r in sanitizer.drain_reports()
             if r["kind"] == "lock_leak"]
    assert locks, "held lock not reported at loop close"


def test_conservation_violation_reported():
    sanitizer.install()
    sanitizer.drain_reports()

    class Broken:
        conservation_ok = False

    obj = Broken()
    # track() is env-gated; reach past it the way lease.py would when
    # armed — the teardown check walks the registry either way
    sanitizer._conserved.append(__import__("weakref").ref(obj))

    async def main():
        await asyncio.sleep(0.01)

    asyncio.run(main())
    cons = [r for r in sanitizer.drain_reports()
            if r["kind"] == "budget_conservation"]
    assert cons and "Broken" in cons[0]["detail"]


def test_clean_run_produces_no_reports():
    sanitizer.install()
    sanitizer.drain_reports()

    async def main():
        lock = asyncio.Lock()
        async with lock:
            await asyncio.sleep(0.01)

    asyncio.run(main())
    assert sanitizer.drain_reports() == []


def test_broker_and_bucket_register_only_when_armed(monkeypatch):
    # disarmed: constructing runtime objects must not grow the registry
    monkeypatch.delenv(sanitizer.ENV_FLAG, raising=False)
    from garage_tpu.gateway.lease import BudgetLeaseBroker
    from garage_tpu.qos.limiter import TokenBucket

    before = len(sanitizer._conserved)
    BudgetLeaseBroker(100.0, 1000.0)
    TokenBucket(10.0)
    assert len(sanitizer._conserved) == before

    monkeypatch.setenv(sanitizer.ENV_FLAG, "1")
    b = BudgetLeaseBroker(100.0, 1000.0)
    t = TokenBucket(10.0)
    assert len(sanitizer._conserved) == before + 2
    assert b.conservation_ok and t.conservation_ok
    # drop our registrations so later teardown checks skip them
    sanitizer._conserved[:] = sanitizer._conserved[:before]


def test_background_mark_inherited_by_child_tasks():
    """gather fan-outs inside supervised service loops are themselves
    supervised — the mark propagates to tasks a background task
    creates."""
    sanitizer.install()
    sanitizer.drain_reports()

    async def main():
        async def child():
            await asyncio.sleep(3600)

        async def service_loop():
            asyncio.ensure_future(child())  # would leak if unmarked
            await asyncio.sleep(3600)

        svc = asyncio.ensure_future(service_loop())
        svc._garage_background = True
        await asyncio.sleep(0.02)

    asyncio.run(main())
    assert [r for r in sanitizer.drain_reports()
            if r["kind"] == "task_leak"] == []


def test_lock_leak_entry_purged_after_report():
    """Review regression: a reported leaked lock must not stay in the
    registry — id() reuse by a later loop would re-attribute it and
    fail an innocent test."""
    sanitizer.install()
    sanitizer.drain_reports()

    async def leaky():
        await asyncio.Lock().acquire()

    asyncio.run(leaky())
    assert [r for r in sanitizer.drain_reports()
            if r["kind"] == "lock_leak"]
    with sanitizer._lock:
        assert not sanitizer._held_locks  # purged with the report

    async def clean():
        await asyncio.sleep(0.01)

    asyncio.run(clean())
    assert sanitizer.drain_reports() == []
