"""KV engine tests — the same suite over every engine (sqlite, memory,
lsm), mirroring src/db/test.rs:3-150. The `db_engine` fixture lives in
conftest.py so the table suite parametrizes over the same axis."""

import pytest

from garage_tpu.db import TxAbort, open_db


@pytest.fixture
def db(db_engine, tmp_path):
    d = open_db(str(tmp_path / "meta"), engine=db_engine)
    yield d
    d.close()


def test_basic_ops(db):
    t = db.open_tree("test")
    assert t.get(b"k") is None
    assert t.insert(b"k", b"v1") is None
    assert t.get(b"k") == b"v1"
    assert t.insert(b"k", b"v2") == b"v1"
    assert len(t) == 1
    assert t.remove(b"k") == b"v2"
    assert t.get(b"k") is None
    assert len(t) == 0


def test_ordering_and_range(db):
    t = db.open_tree("rng")
    for k in [b"b", b"a", b"d", b"c"]:
        t.insert(k, k.upper())
    assert [k for k, _ in t.iter()] == [b"a", b"b", b"c", b"d"]
    assert [k for k, _ in t.iter(start=b"b", end=b"d")] == [b"b", b"c"]
    assert [k for k, _ in t.iter(reverse=True)] == [b"d", b"c", b"b", b"a"]
    assert t.first() == (b"a", b"A")
    assert t.get_gt(b"b") == (b"c", b"C")
    assert t.get_gt(b"d") is None


def test_transaction_commit_and_abort(db):
    t1 = db.open_tree("t1")
    t2 = db.open_tree("t2")

    def body(tx):
        tx.insert(t1, b"x", b"1")
        tx.insert(t2, b"y", b"2")
        return "ok"

    assert db.transaction(body) == "ok"
    assert t1.get(b"x") == b"1"
    assert t2.get(b"y") == b"2"

    def aborting(tx):
        tx.insert(t1, b"x", b"999")
        tx.remove(t2, b"y")
        raise TxAbort("rolled back")

    with pytest.raises(TxAbort):
        db.transaction(aborting)
    assert t1.get(b"x") == b"1"
    assert t2.get(b"y") == b"2"


def test_tx_sees_own_writes(db):
    t = db.open_tree("own")

    def body(tx):
        tx.insert(t, b"a", b"1")
        assert tx.get(t, b"a") == b"1"
        tx.remove(t, b"a")
        assert tx.get(t, b"a") is None
        tx.insert(t, b"a", b"2")
        return tx.get(t, b"a")

    assert db.transaction(body) == b"2"
    assert t.get(b"a") == b"2"


def test_on_commit_hooks(db):
    t = db.open_tree("hooks")
    fired = []

    def body(tx):
        tx.insert(t, b"k", b"v")
        tx.on_commit(lambda: fired.append(1))

    db.transaction(body)
    assert fired == [1]

    def aborting(tx):
        tx.on_commit(lambda: fired.append(2))
        raise TxAbort()

    with pytest.raises(TxAbort):
        db.transaction(aborting)
    assert fired == [1]


def test_clear_and_list_trees(db):
    t = db.open_tree("clearme")
    t.insert(b"a", b"1")
    t.clear()
    assert len(t) == 0
    assert "clearme" in db.list_trees()


def test_sqlite_snapshot(tmp_path):
    d = open_db(str(tmp_path / "meta"), engine="sqlite")
    t = d.open_tree("snap")
    t.insert(b"k", b"v")
    d.snapshot(str(tmp_path / "snapdir"))
    d.close()
    d2 = open_db(str(tmp_path / "snapdir" / "db.sqlite"), engine="sqlite")
    assert d2.open_tree("snap").get(b"k") == b"v"
    d2.close()
