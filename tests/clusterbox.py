"""Cluster-in-a-box: N full Garage nodes in one event loop, built for
layout-transition experiments (ISSUE 6 / ROADMAP "cluster-in-a-box
simulation harness").

Every node is a REAL Garage composition root — tables, merkle trees,
syncers, resync workers, the lot — on the loopback transport
(net/local.py), so add-node / drain-node / kill-and-restart transitions
exercise exactly the code a TCP cluster runs: table anti-entropy moves
block_ref rows, ref triggers drive the block rebalance, the resync
backlog drains, and the gossiped ack/sync trackers converge. Used by
tests/test_resize.py and bench.py's bench_resize segment; scales to a
few dozen nodes in-process.

The harness adds only what a test needs on top of Garage itself:
node lifecycle (add / crash / restart with persisted state), a
foreground workload driver that records per-op latency and failures
(the "zero failed quorum ops" assertion), and convergence waits.
"""

from __future__ import annotations

import asyncio
import os
import random
import time
from typing import Optional

from garage_tpu.model.garage import Garage
from garage_tpu.net import LocalNetwork
from garage_tpu.rpc.layout import NodeRole, ResizeOrchestrator
from garage_tpu.utils.config import Config, DataDir, QosConfig
from garage_tpu.utils.data import gen_uuid


class BoxNode:
    """One node's handle: survives crash/restart cycles (the Garage
    object is replaced, the meta/data dirs persist)."""

    def __init__(self, index: int, root: str):
        self.index = index
        self.root = root
        self.garage: Optional[Garage] = None
        self.task: Optional[asyncio.Task] = None
        self.alive = False

    @property
    def id(self) -> bytes:
        return self.garage.system.id

    @property
    def system(self):
        return self.garage.system

    @property
    def manager(self):
        return self.garage.block_manager


class ClusterBox:
    def __init__(self, tmp_path, n: int = 4, rf: int = 3,
                 erasure: Optional[tuple[int, int]] = None,
                 storage: Optional[set[int]] = None,
                 db_engine: str = "memory",
                 governor: bool = False,
                 status_interval: float = 0.1,
                 ping_interval: float = 0.3,
                 resync_retry_delay: float = 0.25,
                 zones: Optional[list[str]] = None,
                 zone_redundancy=None):
        self.tmp = str(tmp_path)
        self.n = n
        self.rf = rf
        self.erasure = erasure
        self.storage = set(range(n)) if storage is None else set(storage)
        # zone topology (ISSUE 16): one zone name per node index, e.g.
        # ["z1","z1","z2","z2","z3","z3"]. Default: everyone in "z1",
        # which keeps every pre-zone test byte-identical in behavior.
        # zone_redundancy (int or "maximum") is staged with the first
        # layout when given; None leaves the layout default intact.
        if zones is not None and len(zones) != n:
            raise ValueError(f"zones has {len(zones)} entries for {n} nodes")
        self.zones = zones if zones is not None else ["z1"] * n
        self.zone_redundancy = zone_redundancy
        self.db_engine = db_engine
        self.governor = governor
        self.status_interval = status_interval
        self.ping_interval = ping_interval
        self.resync_retry_delay = resync_retry_delay
        self.net = LocalNetwork()
        self.nodes: list[BoxNode] = []

    # ---- config / node construction ------------------------------------

    def _config(self, root: str) -> Config:
        return Config(
            metadata_dir=os.path.join(root, "meta"),
            data_dir=[DataDir(path=os.path.join(root, "data"))],
            db_engine=self.db_engine,
            replication_factor=self.rf,
            erasure_coding=("%d,%d" % self.erasure
                            if self.erasure else None),
            qos=QosConfig(governor=self.governor,
                          governor_interval=0.5,
                          # resize experiments: let resync sprint when
                          # foreground is quiet, yield hard when not
                          resync_tranquility_max=0.5),
        )

    def _boot(self, node: BoxNode) -> None:
        g = Garage(self._config(node.root), local_net=self.net,
                   status_interval=self.status_interval,
                   ping_interval=self.ping_interval)
        # chaos-friendly retry cadence: a fault-failed resync entry
        # must come back within the harness window, not in a minute
        g.block_manager.resync.retry_delay = self.resync_retry_delay
        node.garage = g
        node.task = asyncio.create_task(g.run())
        node.alive = True

    async def _join(self, node: BoxNode, seed: BoxNode) -> None:
        await node.garage.netapp.try_connect(
            seed.garage.netapp.public_addr, seed.id)
        node.system.peering.add_peer(
            seed.garage.netapp.public_addr, seed.id)

    # ---- lifecycle ------------------------------------------------------

    async def start(self) -> "ClusterBox":
        for i in range(self.n):
            node = BoxNode(i, os.path.join(self.tmp, f"node{i}"))
            os.makedirs(node.root, exist_ok=True)
            self.nodes.append(node)
            self._boot(node)
        for node in self.nodes[1:]:
            await self._join(node, self.nodes[0])
        await self.wait(lambda: all(
            len(nd.garage.netapp.conns) == self.n - 1
            for nd in self.nodes), 20, "initial mesh")
        lm = self.nodes[0].system.layout_manager
        for i, nd in enumerate(self.nodes):
            if i in self.storage:
                # default topology is one zone for everyone: with
                # zone_redundancy "maximum" a per-node-zone spread
                # forces every partition onto the single-node zones and
                # a newly added node in a full zone would get ZERO
                # partitions — resize experiments want capacity-driven
                # movement, not zone pinning. Zone drills pass zones=
                # (+ usually an explicit zone_redundancy) instead.
                lm.history.stage_role(
                    nd.id, NodeRole(zone=self.zones[i],
                                    capacity=1 << 30))
        if self.zone_redundancy is not None:
            lm.history.stage_parameters(self.zone_redundancy)
        lm.apply_staged(None)
        await self.wait(lambda: all(
            nd.system.layout_manager.history.current().version == 1
            for nd in self.nodes), 20, "layout v1")
        return self

    async def add_node(self) -> BoxNode:
        """A new empty node joins the mesh (no storage role yet — stage
        + apply is the caller's transition to drive)."""
        i = len(self.nodes)
        node = BoxNode(i, os.path.join(self.tmp, f"node{i}"))
        os.makedirs(node.root, exist_ok=True)
        self.nodes.append(node)
        self._boot(node)
        await self._join(node, self.live()[0])
        await self.wait(lambda: len(node.garage.netapp.conns) >= 1,
                        15, "new node joined")
        return node

    async def stop_node(self, node: BoxNode) -> None:
        """Crash: the process goes away (unregistered from the loopback
        net so RPCs to it fail like a dead TCP peer), persisted state
        stays on disk.

        Order matters: the transport dies FIRST. Garage.stop() closes
        the db before System.run's own teardown gets to the netapp, and
        cancelling the run task outright can skip netapp.shutdown()
        entirely — leaving a zombie node serving RPCs against a closed
        database while peers never see the links drop."""
        node.alive = False
        self.net.nodes.pop(node.id, None)
        await node.garage.netapp.shutdown()
        await node.garage.stop()
        if node.task is not None:
            try:
                await asyncio.wait_for(node.task, 10.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                node.task.cancel()
                await asyncio.gather(node.task, return_exceptions=True)

    async def restart_node(self, node: BoxNode) -> None:
        """Reboot from persisted state (node key, layout history with
        its ack/sync trackers, sqlite resync queue, block files)."""
        assert not node.alive
        self._boot(node)
        await self._join(node, self.live()[0])

    def live(self) -> list[BoxNode]:
        return [nd for nd in self.nodes if nd.alive]

    async def stop(self) -> None:
        # transports first, all nodes: stopping garages one by one
        # leaves the earlier ones' closed dbs serving RPCs from the
        # later ones (a flood of ProgrammingError teardown noise)
        for nd in self.live():
            await nd.garage.netapp.shutdown()
        for nd in self.live():
            await nd.garage.stop()
        for nd in self.nodes:
            if nd.task is not None:
                nd.task.cancel()
        await asyncio.gather(
            *(nd.task for nd in self.nodes if nd.task is not None),
            return_exceptions=True)

    # ---- transitions ----------------------------------------------------

    def orchestrator(self, node: Optional[BoxNode] = None) -> ResizeOrchestrator:
        return ResizeOrchestrator((node or self.nodes[0]).system)

    def resync_backlog(self) -> int:
        return sum(nd.manager.resync.queue_len() +
                   nd.manager.resync.errors_len()
                   for nd in self.live())

    # ---- waits ----------------------------------------------------------

    async def wait(self, cond, timeout: float, what: str = "condition"):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return
            await asyncio.sleep(0.05)
        if not cond():
            raise AssertionError(f"timeout waiting for {what}")


class Workload:
    """Sustained foreground PUT/GET traffic against the coordinator
    node, with per-op latency capture and a hard failure ledger — the
    instrument behind 'zero failed quorum reads/writes mid-resize'."""

    def __init__(self, box: ClusterBox, obj_kib: int = 64,
                 period: float = 0.03, op_timeout: float = 30.0,
                 zipf: Optional[float] = None, zipf_seed: int = 1234):
        self.box = box
        self.obj_kib = obj_kib
        self.period = period
        self.op_timeout = op_timeout
        # Zipf-like GET skew (ISSUE 16 zone drill): with exponent s,
        # read index = floor(len * u**s) for u ~ U(0,1) — s=0/None is
        # the old round-robin, s>=3 concentrates reads on the oldest
        # few objects (the "hot set" the cache tier should own)
        self.zipf = zipf
        self._zrng = random.Random(zipf_seed)
        self.bucket_id = gen_uuid()
        self.stored: list[tuple[bytes, bytes]] = []  # (hash, data)
        self.put_lat: list[float] = []
        self.get_lat: list[float] = []
        self.failures: list[str] = []
        self.corrupt = 0
        self._stop = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._n = 0

    def start(self) -> "Workload":
        self._task = asyncio.create_task(self._run())
        return self

    async def _run(self) -> None:
        from test_model import put_object_like_api

        g0 = self.box.nodes[0].garage
        rng_payload = os.urandom(self.obj_kib << 10)
        while not self._stop.is_set():
            self._n += 1
            do_put = self._n % 2 == 1 or not self.stored
            t0 = time.perf_counter()
            try:
                if do_put:
                    # unique payload per object: content-addressed
                    # stores dedupe identical blocks, which would turn
                    # the workload into a no-op
                    data = (self._n.to_bytes(8, "big")
                            + rng_payload[8:])
                    _uuid, h = await asyncio.wait_for(
                        put_object_like_api(
                            g0, self.bucket_id, f"o{self._n}", data),
                        self.op_timeout)
                    self.stored.append((h, data))
                    self.put_lat.append(time.perf_counter() - t0)
                else:
                    if self.zipf:
                        idx = int(len(self.stored)
                                  * (self._zrng.random() ** self.zipf))
                        idx = min(idx, len(self.stored) - 1)
                    else:
                        idx = self._n % len(self.stored)
                    h, data = self.stored[idx]
                    got = await asyncio.wait_for(
                        g0.block_manager.rpc_get_block(
                            h, cacheable=False),
                        self.op_timeout)
                    self.get_lat.append(time.perf_counter() - t0)
                    if got != data:
                        self.corrupt += 1
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.failures.append(
                    f"{'put' if do_put else 'get'} #{self._n}: "
                    f"{type(e).__name__}: {e}")
            await asyncio.sleep(self.period)

    async def stop(self) -> dict:
        self._stop.set()
        if self._task is not None:
            await self._task
        return self.stats()

    async def wait_ops(self, puts: int, gets: int,
                       timeout: float = 60.0) -> None:
        """Block until the driver has completed at least `puts`/`gets`
        ops. The driver is strictly sequential, so under a loaded
        full-suite run a transition window alone may not fit a fixed op
        count — callers that need an exercise floor wait for it instead
        of asserting it post-hoc."""
        deadline = time.monotonic() + timeout
        while (len(self.put_lat) < puts or len(self.get_lat) < gets):
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"workload op floor not reached in {timeout}s: "
                    f"{self.stats()}")
            await asyncio.sleep(0.1)

    @staticmethod
    def _pctl(xs: list[float], q: float) -> Optional[float]:
        if not xs:
            return None
        s = sorted(xs)
        return s[min(len(s) - 1, int(q * len(s)))]

    def stats(self) -> dict:
        return {
            "puts": len(self.put_lat),
            "gets": len(self.get_lat),
            "failures": list(self.failures),
            "corrupt": self.corrupt,
            "put_p50_ms": _ms(self._pctl(self.put_lat, 0.5)),
            "put_p99_ms": _ms(self._pctl(self.put_lat, 0.99)),
            "get_p50_ms": _ms(self._pctl(self.get_lat, 0.5)),
            "get_p99_ms": _ms(self._pctl(self.get_lat, 0.99)),
        }


def _ms(v: Optional[float]) -> Optional[float]:
    return round(v * 1e3, 2) if v is not None else None
