"""ISSUE 20: summary-v4 engine upgrades + device-path rules.

Covers: GL12 loop-carried races (back-edge unroll) with the re-read
suppressor, GL13 allocation-site lock identity (two instances fire,
aliases don't), GL11 path-sensitivity over the new CFG (dead except
handlers stop firing), import-aware receiver typing (the bucket.py
ET.Element.iter mis-resolution class), GL14/GL15/GL16 fire+suppress
fixtures, the real-CLI exit-1 pins, SARIF output, multi-rule
--fix-waivers, and byte-determinism + cache round-trip over the new
cfg/alloc_sites/var_types summary fields under SUMMARY_VERSION 4.
"""

import ast
import json
import textwrap

from garage_tpu.analysis import (analyze_source, default_rules,
                                 summarize_tree, summary_json)
from garage_tpu.analysis.dataflow import SUMMARY_VERSION, build_cfg


def run(src: str, rel_path: str = "garage_tpu/fake/mod.py"):
    ctx = analyze_source(textwrap.dedent(src), default_rules(),
                         rel_path=rel_path)
    return [v for v in ctx.violations if v.active]


def rules_of(violations):
    return sorted({v.rule for v in violations})


def _cli_rc_on(tmp_path, source: str, rel: str) -> int:
    from garage_tpu.analysis.__main__ import main

    target = tmp_path / rel
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent(source))
    return main(["--baseline", "none", str(target)])


# ---- GL12 loop-carried (back-edge unroll) -------------------------------

GL12_LOOP_CARRIED = """
    class P:
        async def pump(self):
            while self._more:
                await self.flush()
                self._cur = self.take()
                last = self._cur
"""


def test_gl12_loop_carried_race_fires():
    # read late in iteration i (line 7), write after the await in
    # iteration i+1 (line 6) — invisible to a linear event stream,
    # caught by the one-round unroll
    vs = run(GL12_LOOP_CARRIED)
    assert rules_of(vs) == ["GL12"]
    assert "self._cur" in vs[0].message
    assert "awaited" in vs[0].message


def test_gl12_loop_carried_reread_suppresses():
    # the fix idiom survives the unroll: iteration i+1 re-reads the
    # lvalue between its await and its write
    vs = run("""
        class P:
            async def pump(self):
                while self._more:
                    await self.flush()
                    cur = self._cur
                    self._cur = self.advance(cur)
    """)
    assert vs == []


def test_gl12_awaitless_loop_not_unrolled():
    # no await in the body -> no preemption point inside the loop ->
    # nothing to unroll, stays quiet
    vs = run("""
        class P:
            def drain(self):
                while self._more:
                    self._cur = self.take()
                    last = self._cur
    """)
    assert vs == []


def test_cli_gl12_loop_carried_exits_1(tmp_path, capsys):
    rc = _cli_rc_on(tmp_path, GL12_LOOP_CARRIED,
                    "garage_tpu/block/fake_pump.py")
    assert rc == 1
    assert "GL12" in capsys.readouterr().out


# ---- GL13 allocation-site lock identity ---------------------------------

GL13_TWO_INSTANCES = """
    class Guard:
        pass

    def crisscross():
        lock_a = Guard()
        lock_b = Guard()
        with lock_a:
            with lock_b:
                pass
        with lock_b:
            with lock_a:
                pass
"""


def test_gl13_two_instances_of_one_class_fire():
    # two Guard() instances ARE two locks: opposite orders cycle
    vs = run(GL13_TWO_INSTANCES)
    assert rules_of(vs) == ["GL13"]
    assert "<Guard@" in vs[0].message


def test_gl13_aliased_lock_is_one_identity_no_cycle():
    # lock_b aliases lock_a: both with-items resolve to the SAME
    # allocation site, so there is no a->b edge and no false ABBA
    # (name-level identity used to manufacture one)
    vs = run("""
        class Guard:
            pass

        def fwd():
            lock_a = Guard()
            lock_b = lock_a
            with lock_a:
                with lock_b:
                    pass

        def rev():
            lock_a = Guard()
            lock_b = lock_a
            with lock_b:
                with lock_a:
                    pass
    """)
    assert vs == []


def test_gl13_rebound_name_drops_its_site():
    # rebinding to a non-constructor value forgets the site: identity
    # falls back to the name, and consistent order stays quiet
    vs = run("""
        class Guard:
            pass

        def f(pool):
            lock_a = Guard()
            lock_a = pool.pick()
            lock_b = Guard()
            with lock_a:
                with lock_b:
                    pass
            with lock_a:
                with lock_b:
                    pass
    """)
    assert vs == []


def test_cli_gl13_two_instances_exits_1(tmp_path, capsys):
    rc = _cli_rc_on(tmp_path, GL13_TWO_INSTANCES,
                    "garage_tpu/gateway/fake_guards.py")
    assert rc == 1
    assert "GL13" in capsys.readouterr().out


# ---- GL11 path-sensitivity over the CFG ---------------------------------

def test_gl11_risky_call_in_dead_handler_is_off_path():
    # the await sits in an except handler no try-body statement can
    # raise into: it is CFG-unreachable between acquire and release,
    # so the release is NOT at risk (textual betweenness used to fire)
    vs = run("""
        class F:
            async def ok(self, n):
                tok = await self.bucket.acquire(n)
                try:
                    size = n + 1
                except ValueError:
                    await self.audit(n)
                self.bucket.refund(n)
                return size
    """)
    assert vs == []


def test_gl11_risky_call_on_the_real_path_still_fires():
    vs = run("""
        class F:
            async def bad(self, n):
                tok = await self.bucket.acquire(n)
                await self.audit(n)
                self.bucket.refund(n)
    """)
    assert rules_of(vs) == ["GL11"]


def test_cfg_dead_handler_has_no_incoming_edge():
    # the structural fact GL11 relies on, pinned at the CFG level
    src = textwrap.dedent("""
        def f(n):
            try:
                size = n + 1
            except ValueError:
                cleanup()
            return size
    """)
    fn = ast.parse(src).body[0]
    cfg = build_cfg(fn)
    handler = [b for b in cfg["blocks"] if 5 in b["lines"]]
    assert handler, "handler block exists"
    hid = handler[0]["id"]
    assert all(hid not in b["succ"] for b in cfg["blocks"])


def test_cfg_loop_back_edges_are_marked():
    src = textwrap.dedent("""
        def f(xs):
            total = 0
            for x in xs:
                total += x
            return total
    """)
    fn = ast.parse(src).body[0]
    cfg = build_cfg(fn)
    assert any(b["back"] for b in cfg["blocks"])


# ---- import-aware receiver typing (the bucket.py class) -----------------

BUCKET_SHAPE = """
    import xml.etree.ElementTree as ET

    class Tree:
        blocking_api = True

        def iter(self):
            return []

    async def parse(body):
        root = ET.fromstring(body.decode())
        for c in root.iter():
            pass
"""


def test_external_typed_receiver_beats_unique_method_cha():
    # `root` is constructor-typed from an out-of-project import:
    # root.iter() must NOT resolve to the project-unique (and
    # blocking) Tree.iter — the exact api/s3/bucket.py mis-resolution
    # whose waiver this PR deletes
    vs = run(BUCKET_SHAPE, rel_path="garage_tpu/api/s3/fake_bucket.py")
    assert vs == []


def test_reintroduced_bucket_waiver_goes_stale():
    # the retired waiver must not come back silently: with typed
    # receivers the finding is gone, so the waiver suppresses nothing
    # and GL00 flags it
    vs = run("""
        import xml.etree.ElementTree as ET

        class Tree:
            blocking_api = True

            def iter(self):
                return []

        async def parse(body):
            root = ET.fromstring(body.decode())
            # lint: ignore[GL10] ET walk, not db.Tree.iter
            for c in root.iter():
                pass
    """, rel_path="garage_tpu/api/s3/fake_bucket.py")
    assert rules_of(vs) == ["GL00"]
    assert "stale waiver for GL10" in vs[0].message


def test_constructor_typed_receiver_resolves_in_project():
    # the same mechanism, positive direction: a receiver typed by an
    # in-project constructor resolves to that class's method
    vs = run("""
        class Tree:
            blocking_api = True

            def iter(self):
                return []

        async def scan():
            t = Tree()
            for r in t.iter():
                pass
    """)
    assert rules_of(vs) == ["GL10"]
    assert "iter" in vs[0].message


def test_annotation_typed_receiver_resolves_in_project():
    vs = run("""
        class Tree:
            blocking_api = True

            def iter(self):
                return []

        async def scan(t: Tree):
            for r in t.iter():
                pass
    """)
    assert rules_of(vs) == ["GL10"]


def test_isinstance_guard_types_a_receiver():
    vs = run("""
        class Tree:
            blocking_api = True

            def iter(self):
                return []

        async def scan(t):
            if isinstance(t, Tree):
                for r in t.iter():
                    pass
    """)
    assert rules_of(vs) == ["GL10"]


# ---- GL14 jit-cache-key-leak --------------------------------------------

GL14_CACHED_BUILDER = """
    import functools

    @functools.lru_cache(maxsize=None)
    def make_step(mesh, k, m, present, missing):
        import jax

        def step(x):
            return x

        return jax.jit(step)
"""


def test_gl14_pattern_keyed_cached_builder_fires():
    vs = run(GL14_CACHED_BUILDER,
             rel_path="garage_tpu/parallel/fake_make.py")
    assert rules_of(vs) == ["GL14"]
    assert "present" in vs[0].message and "missing" in vs[0].message


def test_gl14_shape_keyed_builder_is_quiet():
    vs = run("""
        import functools

        @functools.lru_cache(maxsize=None)
        def make_step(mesh, k, m, shard_len):
            import jax

            def step(x):
                return x

            return jax.jit(step)
    """, rel_path="garage_tpu/parallel/fake_make.py")
    assert vs == []


def test_gl14_pattern_params_without_jit_are_quiet():
    # host-side matrix caches key on the pattern on purpose (tiny
    # numpy inverses) — no jit in the body, no leak
    vs = run("""
        import functools

        @functools.lru_cache(maxsize=None)
        def repair_matrix(k, m, present, missing):
            return invert(k, m, present, missing)
    """, rel_path="garage_tpu/ops/fake_rs.py")
    assert vs == []


def test_gl14_subscript_key_embedding_pattern_fires():
    vs = run("""
        class D:
            def get(self, k, present):
                key = (k, present)
                return self._jit_cache[key]
    """, rel_path="garage_tpu/ops/fake_rs.py")
    assert rules_of(vs) == ["GL14"]


def test_gl14_len_of_pattern_key_is_a_count_quiet():
    vs = run("""
        class D:
            def get(self, k, present):
                key = (k, len(present))
                return self._jit_cache[key]
    """, rel_path="garage_tpu/ops/fake_rs.py")
    assert vs == []


def test_gl14_outside_device_path_is_quiet():
    vs = run(GL14_CACHED_BUILDER, rel_path="garage_tpu/api/fake.py")
    assert vs == []


def test_cli_gl14_seeded_fixture_exits_1(tmp_path, capsys):
    rc = _cli_rc_on(tmp_path, GL14_CACHED_BUILDER,
                    "garage_tpu/parallel/fake_make.py")
    assert rc == 1
    assert "GL14" in capsys.readouterr().out


# ---- GL15 unpadded-device-launch ----------------------------------------

def test_gl15_raw_sized_operand_fires():
    vs = run("""
        import numpy as np

        def launch(blobs):
            buf = np.zeros((len(blobs), 256), dtype=np.uint8)
            return device_put(buf)
    """, rel_path="garage_tpu/block/fake_launch.py")
    assert rules_of(vs) == ["GL15"]
    assert "buf" in vs[0].message


def test_gl15_bucketed_operand_is_quiet():
    vs = run("""
        import numpy as np

        def launch(blobs, buckets):
            n, padded = bucket_items(len(blobs), buckets)
            buf = np.zeros((n, padded), dtype=np.uint8)
            return device_put(buf)
    """, rel_path="garage_tpu/block/fake_launch.py")
    assert vs == []


def test_gl15_taint_flows_through_assignment():
    vs = run("""
        import numpy as np

        def launch(blobs):
            raw = np.empty((len(blobs), 64), dtype=np.uint8)
            staged = raw
            return gf_apply_batched(staged)
    """, rel_path="garage_tpu/ops/fake_launch.py")
    assert rules_of(vs) == ["GL15"]


# ---- GL16 loop-touch-from-stage-thread ----------------------------------

def test_gl16_stage_method_touching_loop_fires():
    vs = run("""
        class FakeDeviceBackend:
            def readback(self, fut, out):
                self.loop.call_soon(fut.set_result, out)
    """, rel_path="garage_tpu/block/fake_backend.py")
    assert rules_of(vs) == ["GL16"]
    assert "call_soon" in vs[0].message


def test_gl16_threadsafe_crossing_is_sanctioned():
    vs = run("""
        class FakeDeviceBackend:
            def readback(self, fut, out):
                self.loop.call_soon_threadsafe(self._done, fut, out)
    """, rel_path="garage_tpu/block/fake_backend.py")
    assert vs == []


def test_gl16_reaches_through_sync_helpers():
    vs = run("""
        class FakeDeviceBackend:
            def compute(self, op):
                self._deliver(op)

            def _deliver(self, op):
                self.loop.call_soon(self._done, op)
    """, rel_path="garage_tpu/block/fake_backend.py")
    assert rules_of(vs) == ["GL16"]


def test_gl16_same_code_off_device_path_is_quiet():
    vs = run("""
        class FakeDeviceBackend:
            def readback(self, fut, out):
                self.loop.call_soon(fut.set_result, out)
    """, rel_path="garage_tpu/gateway/fake_backend.py")
    assert vs == []


# ---- CLI surfaces: SARIF, --explain, --fix-waivers ----------------------

def test_sarif_output_on_seeded_violation(tmp_path, capsys):
    from garage_tpu.analysis.__main__ import main

    target = tmp_path / "garage_tpu" / "parallel" / "fake_make.py"
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent(GL14_CACHED_BUILDER))
    rc = main(["--baseline", "none", "--format", "sarif", str(target)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    driver = doc["runs"][0]["tool"]["driver"]
    assert driver["name"] == "garage-lint"
    assert {"GL14", "GL15", "GL16"} <= {r["id"] for r in driver["rules"]}
    res = doc["runs"][0]["results"]
    assert res and res[0]["ruleId"] == "GL14"
    loc = res[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("fake_make.py")
    assert isinstance(loc["region"]["startLine"], int)


def test_explain_covers_device_rules(capsys):
    from garage_tpu.analysis.__main__ import main

    for rule in ("GL14", "GL15", "GL16"):
        assert main(["--explain", rule]) == 0
        out = capsys.readouterr().out
        assert "fires on:" in out and "quiet on:" in out


def test_fix_waivers_keeps_surviving_rules(tmp_path, capsys):
    from garage_tpu.analysis.__main__ import main

    target = tmp_path / "garage_tpu" / "block" / "fake_fix.py"
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent("""\
        async def teardown(sock):
            try:
                await sock.close()
            except Exception:
                pass  # lint: ignore[GL05, GL12] close is best-effort
    """))
    rc = main(["--fix-waivers", "--write", str(target)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "keep GL05" in out
    text = target.read_text()
    # GL12 (stale) stripped, GL05 (still suppressing) + reason kept
    assert "# lint: ignore[GL05] close is best-effort" in text
    assert "GL12" not in text
    assert main(["--baseline", "none", str(target)]) == 0
    capsys.readouterr()


def test_fix_waivers_still_drops_fully_stale_comment(tmp_path, capsys):
    from garage_tpu.analysis.__main__ import main

    target = tmp_path / "garage_tpu" / "block" / "fake_fix2.py"
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent("""\
        def f():
            # lint: ignore[GL05] nothing here anymore
            return 1
    """))
    rc = main(["--fix-waivers", "--write", str(target)])
    assert rc == 0
    assert "ignore[" not in target.read_text()
    assert main(["--baseline", "none", str(target)]) == 0
    capsys.readouterr()


# ---- summary v4: determinism + cache round-trip -------------------------

V4_RICH = """
    class Guard:
        pass

    class P:
        async def pump(self, items: list):
            while self._more:
                await self.flush()
                self._cur = self.take()
                last = self._cur

        def swap(self):
            lock_a = Guard()
            lock_b = lock_a
            with lock_a:
                with lock_b:
                    pass

        def route(self, t: "Guard"):
            try:
                g = Guard()
            except ValueError:
                g = None
            return g
"""


def test_v4_fields_exist_and_are_byte_deterministic():
    src = textwrap.dedent(V4_RICH)
    a = summary_json(summarize_tree(ast.parse(src), "garage_tpu/m.py"))
    b = summary_json(summarize_tree(ast.parse(src), "garage_tpu/m.py"))
    assert a == b
    payload = json.loads(a)
    pump = payload["functions"]["P.pump"]
    assert pump["cfg"]["blocks"], "explicit CFG serialized"
    assert any(blk["back"] for blk in pump["cfg"]["blocks"])
    assert pump["var_types"]["items"] == {"k": "ann", "t": "list"}
    swap = payload["functions"]["P.swap"]
    assert set(swap["alloc_sites"]) == {"lock_a", "lock_b"}
    assert swap["alloc_sites"]["lock_a"] == \
        swap["alloc_sites"]["lock_b"]  # alias shares the site
    route = payload["functions"]["P.route"]
    assert route["var_types"]["t"] == {"k": "ann", "t": "Guard"}


def test_summary_version_is_4():
    # cached v3 summaries lack cfg/alloc_sites/var_types and MUST be
    # recomputed — the version bump is what invalidates them
    assert SUMMARY_VERSION >= 4


def test_v4_summary_cache_round_trip(tmp_path, capsys):
    from garage_tpu.analysis.__main__ import main

    target = tmp_path / "garage_tpu" / "block" / "fake_clean.py"
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent("""\
        class Guard:
            pass

        def quiet(x: int):
            g = Guard()
            return (g, x)
    """))
    cache = tmp_path / "summaries.json"
    args = ["--baseline", "none", "--format", "json",
            "--summary-cache", str(cache), str(target)]
    assert main(args) == 0
    cold = json.loads(capsys.readouterr().out)
    assert cold["summary_cache_hits"] == 0
    raw = cache.read_text()
    for field in ('"cfg"', '"alloc_sites"', '"var_types"'):
        assert field in raw, f"{field} not persisted in the cache"
    assert main(args) == 0
    warm = json.loads(capsys.readouterr().out)
    assert warm["summary_cache_hits"] >= 1
    assert warm["violations"] == cold["violations"] == []
