"""Metadata-at-scale properties (ISSUE 7): the delimiter skip-scan's
complexity claim as an assertion, and the full-scale bench smoke
(slow tier).

Correctness of listing/engines is covered by tests/test_s3_api.py and
the engine-parametrized db/table suites; this file pins the SCALING
behavior so a regression back to O(keys-under-prefix) fails loudly.
"""

import asyncio
import bisect

import pytest

from garage_tpu.api.s3 import list as s3list


class _FakeObj:
    __slots__ = ("key",)

    def __init__(self, key: str):
        self.key = key

    def last_data(self):
        return self


class _FakeCtx:
    """In-memory object table speaking the get_range slice the
    collector uses; counts fetches so tests can assert scan cost."""

    bucket_id = b"b"

    def __init__(self, keys):
        self.garage = self
        self.object_table = self
        self._keys = sorted(keys)
        self._enc = [k.encode() for k in self._keys]
        self.fetches = 0
        self.rows_served = 0

    async def get_range(self, pk, start_sk=None, flt=None, limit=1000,
                        prefix_sk=None, **kw):
        self.fetches += 1
        i = 0 if start_sk is None else bisect.bisect_left(self._enc,
                                                          start_sk)
        out = [_FakeObj(k) for k in self._keys[i:i + limit]]
        self.rows_served += len(out)
        return out


def _keyset(prefixes: int, per_prefix: int) -> list:
    return [f"d{p:04d}/o{i:06d}" for p in range(prefixes)
            for i in range(per_prefix)]


def _delim_page(keys, max_keys=1000):
    ctx = _FakeCtx(keys)
    contents, cps, tok, trunc = asyncio.run(
        s3list._collect_objects(ctx, "", None, "/", max_keys))
    return ctx, contents, cps


def test_delimiter_cost_scales_with_prefixes_not_keys():
    """The acceptance claim: a delimiter page over P common prefixes
    costs O(P) range fetches and O(P) rows served, INDEPENDENT of how
    many keys sit under each prefix."""
    ctx_small, _, cps_small = _delim_page(_keyset(50, 100))
    ctx_big, _, cps_big = _delim_page(_keyset(50, 4000))  # 40x the keys
    assert len(cps_small) == len(cps_big) == 50
    assert ctx_big.fetches == ctx_small.fetches
    assert ctx_big.rows_served == ctx_small.rows_served
    # and the absolute cost is ~one probe per distinct prefix
    assert ctx_big.fetches <= 50 + 2
    assert ctx_big.rows_served <= 50 * s3list.DELIM_PROBE + s3list.PAGE


def test_delimiter_mixed_keys_and_prefixes():
    """Un-folded keys between prefixes keep full-page fetching; folded
    runs skip. Both shapes in one listing stay correct AND cheap."""
    keys = _keyset(10, 1000) + [f"top{i:03d}" for i in range(100)]
    ctx, contents, cps = _delim_page(keys, max_keys=1000)
    assert len(cps) == 10
    assert [k for k, _ in contents] == sorted(f"top{i:03d}"
                                              for i in range(100))
    # 10 folded prefixes (one probe each) + the tail of plain keys;
    # nothing close to the 10_100 total rows
    assert ctx.rows_served < 1500


def test_plain_listing_unchanged_by_probe_logic():
    keys = _keyset(5, 30)
    ctx = _FakeCtx(keys)
    contents, cps, tok, trunc = asyncio.run(
        s3list._collect_objects(ctx, "", None, "", 1000))
    assert [k for k, _ in contents] == sorted(keys)
    assert cps == [] and not trunc


@pytest.mark.slow
def test_bench_metadata_10m_lsm():
    """The 10M-key segment (slow tier; the nightly soak runs the 1M
    variant via bench.py bench_metadata)."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from bench import bench_metadata

    out = bench_metadata(keys=10_000_000, engines=("lsm",),
                         list_reps=8, sync_missing=1000)
    assert out.get("meta_lsm_sync_healed") is True
    assert out["meta_lsm_insert_per_s"] > 0
    assert out["meta_lsm_delim_fetches_per_page"] < 1000
