"""Tests for data types, config, persister, tranquilizer, background runner."""

import asyncio

import pytest

from garage_tpu.utils import background, config, data, migrate
from garage_tpu.utils.persister import Persister, PersisterShared


def test_hashes():
    assert len(data.sha256sum(b"hello")) == 32
    assert len(data.blake2sum(b"hello")) == 32
    assert data.blake2sum(b"a") != data.blake2sum(b"b")
    assert isinstance(data.fasthash(b"x"), int)
    u = data.gen_uuid()
    assert len(u) == 32
    assert data.hash_of_hex(data.hex_of(u)) == u


def test_config_parse(tmp_path):
    p = tmp_path / "garage.toml"
    p.write_text("""
metadata_dir = "/tmp/meta"
data_dir = "/tmp/data"
replication_factor = 3
block_size = "1M"
db_engine = "sqlite"
rpc_bind_addr = "127.0.0.1:3901"
bootstrap_peers = ["127.0.0.1:3902"]

[s3_api]
api_bind_addr = "127.0.0.1:3900"
s3_region = "garage"

[tpu]
batch_blocks = 8
""")
    cfg = config.read_config(str(p))
    assert cfg.metadata_dir == "/tmp/meta"
    assert cfg.data_dir[0].path == "/tmp/data"
    assert cfg.replication_factor == 3
    assert cfg.block_size == 10**6
    assert cfg.s3_api_bind_addr == "127.0.0.1:3900"
    assert cfg.bootstrap_peers == ["127.0.0.1:3902"]
    assert cfg.tpu.batch_blocks == 8
    assert cfg.erasure_params is None


def test_config_multi_hdd_and_erasure(tmp_path):
    p = tmp_path / "g.toml"
    p.write_text("""
metadata_dir = "/tmp/meta"
erasure_coding = "4,2"
data_dir = [
  { path = "/mnt/hdd1", capacity = "1T" },
  { path = "/mnt/hdd2", capacity = "500G", read_only = false },
]
""")
    cfg = config.read_config(str(p))
    assert cfg.erasure_params == (4, 2)
    assert cfg.data_dir[0].capacity == 10**12
    assert cfg.data_dir[1].capacity == 5 * 10**11


class PVal(migrate.Migratable):
    VERSION_MARKER = b"GTpv1"

    def __init__(self, n):
        self.n = n

    def pack(self):
        return self.n

    @classmethod
    def unpack(cls, raw):
        return cls(raw)


def test_persister(tmp_path):
    p = Persister(str(tmp_path), "val", PVal)
    assert p.load() is None
    p.save(PVal(42))
    assert p.load().n == 42
    # PersisterShared: persists default, then updates
    ps = PersisterShared(str(tmp_path), "shared", PVal, PVal(1))
    assert ps.get().n == 1
    ps.update(lambda v: PVal(v.n + 1))
    ps2 = PersisterShared(str(tmp_path), "shared", PVal, PVal(99))
    assert ps2.get().n == 2  # loaded, not default


def test_background_runner_lifecycle():
    async def main():
        runner = background.BackgroundRunner()
        done = []

        class W(background.Worker):
            name = "test-worker"

            def __init__(self):
                self.steps = 0

            async def work(self):
                self.steps += 1
                done.append(self.steps)
                if self.steps >= 3:
                    return background.WState.DONE
                return background.WState.BUSY

        runner.spawn_worker(W())
        await asyncio.sleep(0.1)
        infos = runner.worker_info()
        assert len(infos) == 1
        await runner.shutdown()
        assert done == [1, 2, 3]

    asyncio.run(main())


def test_background_worker_error_backoff():
    async def main():
        runner = background.BackgroundRunner()

        class Bad(background.Worker):
            name = "bad"

            async def work(self):
                raise RuntimeError("boom")

        runner.spawn_worker(Bad())
        await asyncio.sleep(0.15)
        info = list(runner.worker_info().values())[0]
        assert info.errors >= 1
        assert "boom" in info.last_error
        await runner.shutdown()

    asyncio.run(main())


def test_lockfile_exclusive(tmp_path):
    """Server-vs-offline-maintenance exclusion: second acquire fails
    while held (in a child process: flock is per-open-file, so a
    same-process re-acquire through a fresh fd would succeed), then
    succeeds after release."""
    import subprocess
    import sys

    from garage_tpu.utils import lockfile

    d = str(tmp_path / "meta")
    fd = lockfile.acquire(d, "server")
    child = (
        "import sys; from garage_tpu.utils import lockfile\n"
        f"d = {d!r}\n"
        "try:\n"
        "    lockfile.acquire(d, 'repair-offline')\n"
        "except lockfile.AlreadyLocked as e:\n"
        "    assert 'server' in str(e); sys.exit(42)\n"
        "sys.exit(0)\n"
    )
    r = subprocess.run([sys.executable, "-c", child])
    assert r.returncode == 42  # refused while the 'server' holds it
    lockfile.release(fd)
    r2 = subprocess.run([sys.executable, "-c", child])
    assert r2.returncode == 0  # free after release
