"""Zero-downtime cluster resize under load (ISSUE 6).

Layout transitions (stage -> apply -> ack -> sync -> commit) driven by
the ResizeOrchestrator against live traffic on the cluster-in-a-box
harness (clusterbox.py — full Garage nodes on the loopback transport),
with the PR 4 chaos injector armed: add-node, drain-node and
kill-and-restart must each complete mid-workload with ZERO failed
quorum reads/writes, the rebalance backlog must drain to zero, and a
crashed node must resume from its persisted ack/sync position.

Pure-layout units extend test_layout's fixtures (nid) rather than
duplicating them; the randomized soak iteration at the bottom is
driven by script/chaos_soak.sh exactly like test_chaos's.
"""

import asyncio
import os
import random
import time

import pytest

from garage_tpu.chaos import FaultSpec, arm, disarm
from garage_tpu.net import LocalNetwork, NetApp
from garage_tpu.net.peering import BREAKER_FAILURES, PeerHealthTracker
from garage_tpu.qos.governor import GovernorWorker
from garage_tpu.rpc import ReplicationMode
from garage_tpu.rpc.layout import (
    LayoutManager,
    NodeRole,
    ResizeOrchestrator,
)

from clusterbox import ClusterBox, Workload
from test_block import make_block_cluster, stop_all
from test_layout import nid  # noqa: F401  (fixture reuse, see soak)


def run(coro, timeout=240.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


@pytest.fixture(autouse=True)
def _chaos_clean():
    disarm()
    yield
    disarm()


# ---- units: sync sources, governor signal, breaker-aware placement ----


def test_sync_tracker_gated_on_all_sources(tmp_path):
    """The node's layout sync tracker advances at the MINIMUM across
    registered sources — one table finishing its round must no longer
    GC a version whose other layers are still migrating."""

    async def main():
        net = LocalNetwork()
        app = NetApp(b"resize-test")
        net.register(app)
        lm = LayoutManager(app, str(tmp_path), ReplicationMode.parse(1))
        lm.history.stage_role(app.id, NodeRole(zone="z", capacity=1 << 30))
        lm.apply_staged(None)
        assert lm.history.current().version == 1

        lm.register_sync_source("table:a")
        lm.register_sync_source("blocks")
        sync = lm.history.update_trackers.sync
        lm.sync_until_from("table:a", 1)
        assert sync.get(app.id, 0) == 0, "advanced past the slow source"
        lm.sync_until_from("blocks", 1)
        assert sync.get(app.id, 0) == 1
        # un-sourced legacy reports still work for single-layer callers
        lm.history.stage_role(nid(2), NodeRole(zone="z", capacity=1 << 30))
        lm.apply_staged(None)
        lm.sync_table_until(2)
        assert sync.get(app.id, 0) == 2
        await asyncio.sleep(0)  # let spawned broadcasts settle

    run(main())


def test_blocks_report_held_until_tables_synced(tmp_path):
    """Regression (ISSUE 16 residual): the block layer's sync report is
    PESSIMISTIC. block_ref rows land — and enqueue their block fetches
    via the ref trigger — strictly before their table source reports a
    version, so a drained resync backlog proves nothing while a table
    round is still running: the rows that would refill the queue may
    simply not have arrived. maybe_report_synced must hold the "blocks"
    report until every other registered source is through."""
    import types

    from garage_tpu.block.resync import BlockResyncManager
    from garage_tpu.db import open_db

    async def main():
        net = LocalNetwork()
        app = NetApp(b"resize-test")
        net.register(app)
        lm = LayoutManager(app, str(tmp_path), ReplicationMode.parse(1))
        lm.history.stage_role(app.id, NodeRole(zone="z", capacity=1 << 30))
        lm.apply_staged(None)
        lm.register_sync_source("table:a")
        lm.register_sync_source("blocks")

        db = open_db(str(tmp_path / "resync"), engine="memory")
        system = types.SimpleNamespace(layout_manager=lm,
                                       layout_helper=lm.helper)
        rsm = BlockResyncManager(
            types.SimpleNamespace(system=system), db)
        # enumeration for v1 completed, backlog fully drained — the
        # exact state that used to report prematurely
        rsm._enumerated_version = 1
        assert rsm.queue_len() == 0 and rsm.errors_len() == 0

        sync = lm.history.update_trackers.sync
        assert not rsm.maybe_report_synced(), \
            "blocks reported while table:a was still syncing"
        assert lm._sync_done["blocks"] == 0
        assert sync.get(app.id, 0) == 0

        lm.sync_until_from("table:a", 1)
        assert rsm.maybe_report_synced()
        assert lm._sync_done["blocks"] == 1
        assert sync.get(app.id, 0) == 1
        # idempotent re-report stays true once through
        assert rsm.maybe_report_synced()
        await asyncio.sleep(0)  # let spawned broadcasts settle

    run(main())


def test_governor_resync_backlog_signal():
    """A deep rebalance backlog pushes pressure UP while foreground
    traffic is active (rebalance yields to p99) and is ignored when
    the cluster is foreground-idle (rebalance sprints)."""
    samples = {"count": 0, "total": 0.0}
    backlog = {"n": 0}
    gov = GovernorWorker(
        object(), target_latency=0.05,
        sample_fn=lambda: (samples["count"], samples["total"]),
        queue_depth_fn=lambda: 0,
        resync_backlog_fn=lambda: backlog["n"])
    gov.step()  # prime the sample delta
    samples["count"] += 10
    samples["total"] += 10 * 0.05  # exactly on target: latency err ~0
    backlog["n"] = 10_000
    gov.step()
    assert gov.pressure > 0.2, \
        f"backlog did not push pressure: {gov.pressure}"
    assert gov.last_resync_backlog == 10_000
    p = gov.pressure
    gov.step()  # no new foreground samples: idle decay wins
    assert gov.pressure < p, "idle cluster must let rebalance sprint"


def test_resync_placement_skips_open_breaker(tmp_path):
    """Rebalance traffic never re-queues at a known-open peer: the
    placement order drops open-breaker nodes and ranks shaky ones
    last."""
    import types

    from garage_tpu.block.resync import BlockResyncManager
    from garage_tpu.db import open_db

    a, b, c = b"\xaa" * 32, b"\xbb" * 32, b"\xcc" * 32
    ht = PeerHealthTracker()
    for _ in range(BREAKER_FAILURES):
        ht.record_failure(b)
    assert ht.breaker_state(b) == "open"
    db = open_db(str(tmp_path / "db"), engine="memory")
    mgr = types.SimpleNamespace(
        rpc=types.SimpleNamespace(health=lambda: ht))
    res = BlockResyncManager(mgr, db)
    keep, skipped = res._placement_order([a, b, c])
    assert b not in keep and skipped == 1
    assert set(keep) == {a, c}
    # the knob restores blind placement
    res.breaker_aware = False
    keep, skipped = res._placement_order([a, b, c])
    assert keep == [a, b, c] and skipped == 0


def test_hedged_write_unsticks_hung_shard_holder(tmp_path):
    """Erasure(2,1) write quorum is all 3 placements: a hung holder
    used to stall the PUT for its whole timeout. With write hedging
    the same put is re-issued after the observed p95 and the PUT
    completes in well under a second."""

    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=3, rf=3, erasure=(2, 1))
        try:
            data = os.urandom(200_000)
            h = await managers[0].hash_block(data)
            victim = [s.id for s in systems if s.id != systems[0].id][0]
            ht = systems[0].peering.health

            # control: hedge_writes off -> the hung holder pins the PUT
            ht.write_hedging_enabled = False
            c = arm(seed=21)
            c.add(FaultSpec(kind="rpc_hang", peer=victim.hex()[:8],
                            endpoint="garage_tpu/block", count=1))
            with pytest.raises(asyncio.TimeoutError):
                await asyncio.wait_for(
                    managers[0].rpc_put_block(h, data, compress=False),
                    3.0)
            assert c.total_fired == 1, "hang was never injected"
            disarm()

            ht.write_hedging_enabled = True
            before = ht.hedges_launched
            c = arm(seed=22)
            c.add(FaultSpec(kind="rpc_hang", peer=victim.hex()[:8],
                            endpoint="garage_tpu/block", count=1))
            t0 = time.monotonic()
            await asyncio.wait_for(
                managers[0].rpc_put_block(h, data, compress=False), 10.0)
            dt = time.monotonic() - t0
            assert c.total_fired >= 1, "hang was never injected"
            assert dt < 5.0, f"write hedge did not engage: {dt:.1f}s"
            assert ht.hedges_launched > before
        finally:
            disarm()
            await stop_all(systems, tasks)

    run(main())


# ---- cluster: the three transitions, mid-workload, chaos armed ---------


def test_admin_resize_readout(tmp_path):
    """ISSUE 15 satellite (PR 6 follow-on): GET /v1/resize builds an
    operator progress readout from the existing resize_phase_seconds
    series and the gossiped ack/sync trackers — phases with timings,
    per-node lag, and the rebalance backlog."""

    async def main():
        import json as _json

        from garage_tpu.admin.http import AdminHttpServer

        box = ClusterBox(tmp_path, n=3, rf=3)
        await box.start()
        try:
            node = await box.add_node()
            orch = box.orchestrator()
            orch.stage_add(node.id, "z1", 1 << 30)
            await orch.run(timeout=120.0)

            class _Req:
                method = "GET"
                path = "/v1/resize"
                query = {}

                @staticmethod
                def header(name):
                    return None

            adm = AdminHttpServer(box.nodes[0].garage)
            resp = await adm._route_v1(_Req())
            body = _json.loads(bytes(resp.body))
            assert body["layout_version"] == 2
            assert body["transitions_completed"] >= 1
            # all four phases recorded with timings
            assert set(body["phases"]) >= {"apply", "ack", "sync",
                                           "commit"}
            for ph in body["phases"].values():
                assert ph["count"] >= 1 and ph["total_s"] >= 0
            # converged: nothing lagging, not resizing
            assert body["resizing"] is False
            assert all(n["lagging"] == [] for n in body["nodes"])
            assert len(body["nodes"]) == 4
            assert body["rebalance_backlog"] == 0
        finally:
            await box.stop()

    run(main())


def test_add_node_under_load_with_chaos(tmp_path):
    """Scale-up: a new node joins mid-workload with net faults armed.
    The transition completes, zero quorum ops fail, the rebalance
    backlog drains to zero, and the new node actually holds data for
    its assigned hashes."""

    async def main():
        box = await ClusterBox(tmp_path, n=4, rf=3).start()
        w = Workload(box, obj_kib=32, period=0.02)
        try:
            w.start()
            await asyncio.sleep(1.0)  # objects land pre-transition
            victim = box.nodes[1].id
            c = arm(seed=61)
            c.add(FaultSpec(kind="net_delay", peer=victim.hex()[:8],
                            prob=0.3, count=60, delay_s=0.02))
            c.add(FaultSpec(kind="rpc_error", peer=victim.hex()[:8],
                            endpoint="garage_tpu/block",
                            prob=0.2, count=12))
            newbie = await box.add_node()
            orch = box.orchestrator()
            orch.stage_add(newbie.id, "z1", 1 << 30)
            report = await orch.run(timeout=120.0)
            assert report.completed and report.version == 2
            # exercise floor, not a perf claim: with chaos still armed
            # and the rebalance backlog draining, keep traffic flowing
            # until both paths have demonstrably run — a loaded
            # full-suite box may fit < 3 sequential ops inside the
            # transition window itself
            await w.wait_ops(3, 3, timeout=60.0)
            stats = await w.stop()
            assert stats["failures"] == [], stats["failures"][:3]
            assert stats["corrupt"] == 0
            disarm()
            await box.wait(lambda: box.resync_backlog() == 0, 90,
                           "rebalance backlog drain")
            helper = box.nodes[0].system.layout_helper
            assert helper.read_version().version == 2
            assigned = [h for h, _ in w.stored
                        if newbie.id
                        in helper.current_storage_nodes_of(h)]
            assert assigned, "new node was assigned no stored hash?"
            await box.wait(
                lambda: sum(1 for h in assigned
                            if newbie.manager.has_local(h))
                >= max(1, len(assigned) // 2),
                90, "data landing on the new node")
            for h, data in w.stored:
                got = await box.nodes[0].manager.rpc_get_block(
                    h, cacheable=False)
                assert got == data
        finally:
            await w.stop()
            disarm()
            await box.stop()

    run(main())


def test_drain_node_zero_lost_blocks_under_faults(tmp_path):
    """Scale-down: a storage node is drained mid-workload with seeded
    net faults armed. The transition completes, zero quorum ops fail,
    and after the backlog drains EVERY stored block has a full
    replica set on the surviving nodes — proven by stopping the
    drained node outright and reading everything back."""

    async def main():
        box = await ClusterBox(tmp_path, n=5, rf=3).start()
        w = Workload(box, obj_kib=32, period=0.02)
        try:
            w.start()
            await asyncio.sleep(1.5)
            c = arm(seed=62)
            c.add(FaultSpec(kind="rpc_error",
                            peer=box.nodes[2].id.hex()[:8],
                            endpoint="garage_tpu/block",
                            prob=0.15, count=10))
            c.add(FaultSpec(kind="net_delay",
                            peer=box.nodes[1].id.hex()[:8],
                            prob=0.2, count=40, delay_s=0.02))
            victim = box.nodes[4]
            orch = box.orchestrator()
            orch.stage_remove(victim.id)
            report = await orch.run(timeout=120.0)
            assert report.completed and report.version == 2
            stats = await w.stop()
            assert stats["failures"] == [], stats["failures"][:3]
            assert stats["corrupt"] == 0
            disarm()
            current = box.nodes[0].system.layout_helper.current()
            assert victim.id not in current.storage_nodes()
            await box.wait(lambda: box.resync_backlog() == 0, 90,
                           "rebalance backlog drain")
            # every block must now have a full replica set WITHOUT the
            # drained node: wait for the survivors to hold rf copies,
            # then stop the drained node outright and read all back
            live_holders = lambda h: sum(  # noqa: E731
                1 for nd in box.nodes[:4] if nd.manager.has_local(h))
            await box.wait(
                lambda: all(live_holders(h) >= 3
                            for h, _ in w.stored),
                90, "full replica sets on survivors")
            await box.stop_node(victim)
            for h, data in w.stored:
                got = await box.nodes[0].manager.rpc_get_block(
                    h, cacheable=False)
                assert got == data, "block lost in drain"
        finally:
            await w.stop()
            disarm()
            await box.stop()

    run(main())


def test_kill_and_restart_resumes_persisted_position(tmp_path):
    """Crash-restart mid-transition (sqlite persistence): the cluster
    keeps serving, the transition completes once the node returns,
    and the restarted node resumes from its persisted ack/sync
    trackers (they only ever move forward across the crash)."""

    async def main():
        box = await ClusterBox(tmp_path, n=4, rf=3,
                               db_engine="sqlite").start()
        w = Workload(box, obj_kib=32, period=0.03, op_timeout=45.0)
        try:
            w.start()
            await asyncio.sleep(1.5)
            newbie = await box.add_node()
            orch = box.orchestrator()
            orch.stage_add(newbie.id, "z1", 1 << 30)
            run_task = asyncio.create_task(orch.run(timeout=150.0))
            await asyncio.sleep(0.5)  # transition underway
            victim = box.nodes[2]
            trk = victim.system.layout_manager.history.update_trackers
            pre_ack = dict(trk.ack)
            pre_sync = dict(trk.sync)
            await box.stop_node(victim)
            await asyncio.sleep(2.0)  # cluster serves degraded
            await box.restart_node(victim)
            report = await run_task
            assert report.completed and report.version == 2
            stats = await w.stop()
            assert stats["failures"] == [], stats["failures"][:3]
            assert stats["corrupt"] == 0
            # persisted ack/sync position: monotone across the crash
            post = victim.system.layout_manager.history.update_trackers
            for n, v in pre_ack.items():
                assert post.ack.get(n, 0) >= v, "ack tracker regressed"
            for n, v in pre_sync.items():
                assert post.sync.get(n, 0) >= v, \
                    "sync tracker regressed"
            await box.wait(lambda: box.resync_backlog() == 0, 90,
                           "rebalance backlog drain")
        finally:
            await w.stop()
            await box.stop()

    run(main())


# ---- randomized soak (script/chaos_soak.sh resize scenario) ------------


@pytest.mark.slow
@pytest.mark.skipif("CHAOS_SOAK_SEED" not in os.environ,
                    reason="soak iteration; driven by "
                           "script/chaos_soak.sh")
def test_resize_soak(tmp_path):
    """One nightly-soak iteration: add-node, drain-node and
    kill-and-restart back to back under randomized budgeted chaos with
    a workload running. Failures under chaos would be legal; corrupt
    reads and a stuck backlog are not. Replay:

        CHAOS_SOAK_SEED=<seed> pytest tests/test_resize.py -k resize_soak -s
    """
    seed = int(os.environ["CHAOS_SOAK_SEED"])
    print(f"\nresize soak seed={seed}")
    rng = random.Random(seed)

    async def main():
        box = await ClusterBox(tmp_path, n=5, rf=3,
                               db_engine="sqlite").start()
        w = Workload(box, obj_kib=32, period=0.03)
        try:
            w.start()
            await asyncio.sleep(1.0)
            c = arm(seed=seed)
            victim = box.nodes[rng.randrange(1, 5)].id
            for _ in range(rng.randint(1, 3)):
                kind = rng.choice(["rpc_error", "net_delay",
                                   "disk_read_error"])
                spec = {"kind": kind,
                        "prob": round(rng.uniform(0.05, 0.25), 3),
                        "count": rng.randint(2, 8)}
                if kind in ("rpc_error", "net_delay"):
                    spec["peer"] = victim.hex()[:8]
                if kind == "rpc_error":
                    spec["endpoint"] = "garage_tpu/block"
                if kind == "net_delay":
                    spec["delay_s"] = 0.02
                if kind == "disk_read_error":
                    spec["node"] = victim.hex()[:8]
                c.add(FaultSpec(**spec))
            newbie = await box.add_node()
            orch = box.orchestrator()
            orch.stage_add(newbie.id, "z1", 1 << 30)
            r1 = await orch.run(timeout=180.0)
            assert r1.completed, f"seed={seed}: add-node stuck"
            drain = box.nodes[rng.choice([1, 2])]
            orch.stage_remove(drain.id)
            r2 = await orch.run(timeout=180.0)
            assert r2.completed, f"seed={seed}: drain stuck"
            kr = box.nodes[3]
            await box.stop_node(kr)
            await asyncio.sleep(rng.uniform(0.5, 2.0))
            await box.restart_node(kr)
            stats = await w.stop()
            assert stats["corrupt"] == 0, f"seed={seed}: corrupt read"
            disarm()
            await box.wait(lambda: box.resync_backlog() == 0, 120,
                           f"seed={seed}: backlog drain")
            # steady state: everything the workload stored reads back
            # byte-identical after disarm
            for h, data in w.stored[-20:]:
                got = await box.nodes[0].manager.rpc_get_block(
                    h, cacheable=False)
                assert got == data, f"seed={seed}: corrupt after disarm"
        finally:
            await w.stop()
            disarm()
            await box.stop()

    run(main(), timeout=540)
