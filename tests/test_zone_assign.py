"""Zone-redundant assignment edge cases (ISSUE 16 satellite).

The max-flow solver (rpc/layout/assign.py) carries three promises the
zone subsystem leans on: every partition spans >= zone_redundancy
zones, replica spread is MAXIMIZED beyond that floor (a whole-zone
partition costs at most one replica per partition when zones >= rf),
and infeasible topologies fail loudly instead of silently shrinking
the span. These tests pin the edges: more zones than rf, a zone with
no usable capacity, "maximum" vs an explicit integer, and a node
changing zones across a layout version bump.
"""

import pytest

from garage_tpu.rpc.layout import LayoutHistory, N_PARTITIONS, NodeRole
from garage_tpu.rpc.layout.assign import LayoutError, compute_assignment


def nid(i: int) -> bytes:
    return bytes([i]) * 32


def roles_of(spec):
    """spec: {node_id: (zone, capacity)} -> (node, role) pairs."""
    return [(n, NodeRole(zone=z, capacity=c)) for n, (z, c) in spec.items()]


def spans(spec, vec, ring, rf=3):
    """Per-partition count of distinct zones."""
    zone = {n: z for n, (z, _) in spec.items()}
    return [len({zone[vec[ring[p * rf + i]]] for i in range(rf)})
            for p in range(N_PARTITIONS)]


def test_more_zones_than_rf_spans_rf_zones():
    """5 single-node zones, rf=3, "maximum": the effective requirement
    caps at rf and EVERY partition spans exactly 3 distinct zones."""
    spec = {nid(i): (f"z{i}", 1 << 30) for i in range(1, 6)}
    vec, ring, size = compute_assignment(roles_of(spec), 3, "maximum")
    assert min(spans(spec, vec, ring)) == 3
    assert size > 0


def test_zone_with_zero_capacity_is_skipped():
    """A zone whose only member has capacity 0 contributes nothing: the
    solver assigns it zero partitions and satisfies zone_redundancy=2
    from the remaining zones instead of wedging."""
    spec = {
        nid(1): ("z1", 1 << 30),
        nid(2): ("z2", 1 << 30),
        nid(3): ("z3", 0),
        nid(4): ("z1", 1 << 30),
    }
    vec, ring, _size = compute_assignment(roles_of(spec), 3, 2)
    counts = {}
    for b in ring:
        counts[vec[b]] = counts.get(vec[b], 0) + 1
    assert nid(3) not in counts
    assert min(spans(spec, vec, ring)) >= 2


def test_infeasible_zone_redundancy_fails_loudly():
    """Strict zone_redundancy=3 when only two zones have capacity must
    raise, not silently produce a 2-zone layout."""
    spec = {
        nid(1): ("z1", 1 << 30),
        nid(2): ("z2", 1 << 30),
        nid(3): ("z3", 0),
        nid(4): ("z1", 1 << 30),
    }
    with pytest.raises(LayoutError):
        compute_assignment(roles_of(spec), 3, 3)


def test_maximum_equals_explicit_int():
    """With 3 zones and rf=3, "maximum" resolves to 3 and the solver is
    deterministic: identical output to the explicit integer."""
    spec = {nid(i): (f"z{(i - 1) // 2 + 1}", 1 << 30)
            for i in range(1, 7)}
    assert compute_assignment(roles_of(spec), 3, "maximum") \
        == compute_assignment(roles_of(spec), 3, 3)


def test_spread_maximization_one_replica_per_zone():
    """zone_redundancy=2 is a FLOOR: with 3 equal zones the spread-
    maximizing cost layer still puts one replica in every zone for all
    256 partitions — the property that makes losing a whole zone cost
    exactly one replica (the drill's quorum math)."""
    spec = {nid(i): (f"z{(i - 1) // 2 + 1}", 1 << 30)
            for i in range(1, 7)}
    vec, ring, _size = compute_assignment(roles_of(spec), 3, 2)
    assert min(spans(spec, vec, ring)) == 3
    # and the load is still balanced: 256*3/6 slots each
    counts = {}
    for b in ring:
        counts[vec[b]] = counts.get(vec[b], 0) + 1
    assert set(counts.values()) == {N_PARTITIONS * 3 // 6}


def test_node_moving_zones_across_version_bump():
    """A node restaged into a different zone: the new version keeps the
    zone invariants, the mover keeps its SLOT COUNT (capacity unchanged
    — moving zones is not draining), and untouched replicas stay put
    within what the new zone constraint allows."""
    h = LayoutHistory.new(3)
    spec1 = {nid(i): (f"z{(i - 1) // 2 + 1}", 1 << 30)
             for i in range(1, 7)}
    for n, (z, c) in spec1.items():
        h.stage_role(n, NodeRole(zone=z, capacity=c))
    h.stage_parameters(2)
    h.apply_staged_changes()
    v1 = h.current()
    assert v1.version == 1

    # node 6 moves z3 -> z1 (now 3/2/1 nodes in z1/z2/z3)
    h.stage_role(nid(6), NodeRole(zone="z1", capacity=1 << 30))
    h.apply_staged_changes()
    v2 = h.current()
    assert v2.version == 2
    assert v2.node_role(nid(6)).zone == "z1"

    spec2 = dict(spec1)
    spec2[nid(6)] = ("z1", 1 << 30)
    zone2 = {n: z for n, (z, _) in spec2.items()}
    for p in range(N_PARTITIONS):
        nodes = v2.nodes_of(p)
        assert len(set(nodes)) == 3
        # zr=2 floor holds; spread max still yields 3 where feasible
        assert len({zone2[n] for n in nodes}) >= 2
    # z3 lost a node: its survivor must now hold a z3 replica for every
    # partition that keeps 3-zone spread — it gains load, it never
    # disappears
    counts = {}
    for b in v2.ring_assignment_data:
        counts[v2.node_id_vec[b]] = counts.get(v2.node_id_vec[b], 0) + 1
    assert counts.get(nid(5), 0) > 0
    assert counts.get(nid(6), 0) > 0  # the mover still carries data
    # movement is bounded: most replica slots survive the rezone
    retained = sum(
        len(set(v1.nodes_of(p)) & set(v2.nodes_of(p)))
        for p in range(N_PARTITIONS))
    assert retained / (N_PARTITIONS * 3) >= 0.5, \
        f"rezone moved too much: kept {retained}/{N_PARTITIONS * 3}"


def test_zone_redundancy_survives_crdt_roundtrip():
    """stage_parameters rides the layout CRDT like roles do: an
    explicit integer survives encode/decode and lands on the applied
    version (the value _verify_zone_span derives the write requirement
    from)."""
    from garage_tpu.utils import migrate

    h = LayoutHistory.new(3)
    for i in range(1, 7):
        h.stage_role(nid(i), NodeRole(zone=f"z{(i - 1) // 2 + 1}",
                                      capacity=1 << 30))
    h.stage_parameters(2)
    h.apply_staged_changes()
    h2 = migrate.decode(LayoutHistory, migrate.encode(h))
    assert h2.current().zone_redundancy == 2
    assert h2.current().nodes_of(0) == h.current().nodes_of(0)
