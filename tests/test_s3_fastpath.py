"""S3 data-plane fast path: GET readahead pipeline, overlapped SigV4
hashing, zero-copy chunker carry, and single-range enforcement.

These are unit-level tests against fakes (no forked server): the
readahead pipeline's ordering/cancellation contract is about task
scheduling, which a conformance GET can't observe.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import types

import pytest

from garage_tpu.api.s3.get import _plan_blocks, _stream_blocks, parse_range


def run(coro):
    return asyncio.run(coro)


# ---- fakes ---------------------------------------------------------------


class FakeBlockManager:
    """rpc_get_block with per-hash delay/failure injection and
    concurrency accounting."""

    def __init__(self, store: dict, delays: dict | None = None,
                 fail: set | None = None):
        self.store = store
        self.delays = delays or {}
        self.fail = fail or set()
        self.inflight = 0
        self.max_inflight = 0
        self.started: list[bytes] = []
        self.cacheable_flags: list[bool] = []
        self.cancelled = 0

    async def rpc_get_block(self, h: bytes, cacheable: bool = True) -> bytes:
        self.started.append(h)
        self.cacheable_flags.append(cacheable)
        self.inflight += 1
        self.max_inflight = max(self.max_inflight, self.inflight)
        try:
            await asyncio.sleep(self.delays.get(h, 0.001))
            if h in self.fail:
                raise RuntimeError("all holders failed")
            return self.store[h]
        except asyncio.CancelledError:
            self.cancelled += 1
            raise
        finally:
            self.inflight -= 1


def make_garage(bm: FakeBlockManager, readahead: int = 3):
    return types.SimpleNamespace(
        config=types.SimpleNamespace(s3_get_readahead_blocks=readahead),
        block_manager=bm)


def make_blocks(n: int, size: int = 100):
    store = {bytes([i]) * 4: bytes([i]) * size for i in range(n)}
    blocks = [((1, i * size), (bytes([i]) * 4, size)) for i in range(n)]
    return store, blocks


async def collect(gen) -> bytes:
    return b"".join([bytes(c) async for c in gen])


# ---- readahead pipeline --------------------------------------------------


def test_readahead_preserves_order_under_skewed_latency():
    """A slow FIRST block must not let faster later blocks jump the
    queue, and later blocks must actually overlap it."""
    async def main():
        store, blocks = make_blocks(8)
        bm = FakeBlockManager(store, delays={b"\x00" * 4: 0.1})
        out = await collect(_stream_blocks(make_garage(bm), blocks, 0, 800))
        assert out == b"".join(store[bytes([i]) * 4] for i in range(8))
        assert bm.max_inflight > 1  # genuine readahead happened
        # window never exceeds current + readahead depth
        assert bm.max_inflight <= 4

    run(main())


def test_readahead_zero_is_strictly_sequential():
    async def main():
        store, blocks = make_blocks(6)
        bm = FakeBlockManager(store)
        out = await collect(
            _stream_blocks(make_garage(bm, readahead=0), blocks, 0, 600))
        assert out == b"".join(store[bytes([i]) * 4] for i in range(6))
        assert bm.max_inflight == 1

    run(main())


def test_readahead_failed_block_fails_stream_and_leaks_nothing():
    async def main():
        store, blocks = make_blocks(8)
        bm = FakeBlockManager(store, fail={b"\x03" * 4})
        got = []
        with pytest.raises(RuntimeError):
            async for c in _stream_blocks(make_garage(bm), blocks, 0, 800):
                got.append(bytes(c))
        # blocks before the failure arrived, in order
        assert got == [store[bytes([i]) * 4] for i in range(3)]
        await asyncio.sleep(0.05)
        assert bm.inflight == 0  # prefetches past the failure cancelled

    run(main())


def test_readahead_client_disconnect_cancels_prefetches():
    """aclose (what http.write_response does when the client goes away)
    must cancel every in-flight prefetch promptly — no orphaned tasks
    keeping block fetches alive after the connection died."""
    async def main():
        store, blocks = make_blocks(8)
        delays = {h: 5.0 for h in store}
        delays[b"\x00" * 4] = 0.0
        bm = FakeBlockManager(store, delays=delays)
        gen = _stream_blocks(make_garage(bm), blocks, 0, 800)
        first = await gen.__anext__()
        assert bytes(first) == store[b"\x00" * 4]
        assert bm.inflight == 3  # readahead window in flight
        await gen.aclose()
        assert bm.inflight == 0
        assert bm.cancelled == 3
        # nothing else still running on the loop for this stream
        assert not [t for t in asyncio.all_tasks()
                    if t is not asyncio.current_task()]

    run(main())


def test_readahead_consumer_task_cancel_cancels_current_fetch():
    """Cancelling the consuming TASK mid-await (connection task torn
    down) must also cancel the block fetch being awaited — it is popped
    from the window only after it completes, so the generator's finally
    can still reach it."""
    async def main():
        store, blocks = make_blocks(8)
        bm = FakeBlockManager(store, delays={h: 5.0 for h in store})

        async def consume():
            async for _ in _stream_blocks(make_garage(bm), blocks, 0, 800):
                pass

        t = asyncio.create_task(consume())
        await asyncio.sleep(0.05)
        assert bm.inflight == 4
        t.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t
        assert bm.inflight == 0
        assert bm.cancelled == 4

    run(main())


def test_readahead_range_starting_mid_block():
    async def main():
        store, blocks = make_blocks(8)
        whole = b"".join(store[bytes([i]) * 4] for i in range(8))
        bm = FakeBlockManager(store)
        out = await collect(_stream_blocks(make_garage(bm), blocks,
                                           150, 420))
        assert out == whole[150:420]
        assert len(bm.started) == 4  # blocks 1..4 only — no over-fetch

    run(main())


def test_readahead_ssec_decrypt_ordering():
    """With SSE-C, decrypt happens inside prefetch tasks that finish out
    of order; the plaintext must still stream in block order."""
    class XorKey:
        def decrypt_block(self, data):
            return bytes(b ^ 0x5A for b in data)

    async def main():
        key = XorKey()
        plain, blocks = make_blocks(6)
        store = {h: key.decrypt_block(v) for h, v in plain.items()}  # "cipher"
        delays = {bytes([i]) * 4: 0.05 - i * 0.008 for i in range(6)}
        bm = FakeBlockManager(store, delays=delays)
        out = await collect(_stream_blocks(make_garage(bm), blocks,
                                           0, 600, sse_key=key))
        assert out == b"".join(plain[bytes([i]) * 4] for i in range(6))
        # SSE-C blocks must never enter the hot-block read cache: every
        # fetch opted out
        assert bm.cacheable_flags == [False] * 6

    run(main())


def test_stream_blocks_cache_opt_in_matches_encryption():
    """Plaintext GETs read (and fill) the hot-block cache; SSE-C GETs
    bypass it — on both the readahead and the sequential (depth 0)
    paths."""
    class XorKey:
        def decrypt_block(self, data):
            return bytes(b ^ 0x5A for b in data)

    async def main():
        for depth in (3, 0):
            store, blocks = make_blocks(4)
            bm = FakeBlockManager(store)
            await collect(_stream_blocks(make_garage(bm, readahead=depth),
                                         blocks, 0, 400))
            assert bm.cacheable_flags == [True] * 4

            key = XorKey()
            cipher = {h: key.decrypt_block(v) for h, v in store.items()}
            bm2 = FakeBlockManager(cipher)
            await collect(_stream_blocks(make_garage(bm2, readahead=depth),
                                         blocks, 0, 400, sse_key=key))
            assert bm2.cacheable_flags == [False] * 4

    run(main())


def test_plan_blocks_slices():
    _, blocks = make_blocks(3, size=10)
    assert _plan_blocks(blocks, 0, 30) == [
        (b"\x00" * 4, 0, 10), (b"\x01" * 4, 0, 10), (b"\x02" * 4, 0, 10)]
    assert _plan_blocks(blocks, 12, 18) == [(b"\x01" * 4, 2, 8)]
    assert _plan_blocks(blocks, 5, 25) == [
        (b"\x00" * 4, 5, 10), (b"\x01" * 4, 0, 10), (b"\x02" * 4, 0, 5)]
    assert _plan_blocks(blocks, 30, 30) == []


# ---- parse_range single-range enforcement --------------------------------


def test_parse_range_single_ranges_still_work():
    assert parse_range("bytes=0-99", 1000) == (0, 100)
    assert parse_range("bytes=500-", 1000) == (500, 1000)
    assert parse_range("bytes=-200", 1000) == (800, 1000)
    assert parse_range("bytes=0-4,", 1000) == (0, 5)  # trailing comma


def test_parse_range_multi_range_rejected():
    """bytes=0-0,5-9 used to silently serve only the first range — a
    multipart/byteranges consumer would misparse the body. Reject the
    whole spec (-> 416 upstream) instead."""
    assert parse_range("bytes=0-0,5-9", 1000) is None
    assert parse_range("bytes=0-4,10-14,20-24", 1000) is None
    assert parse_range("bytes=-5,0-1", 1000) is None


# ---- overlapped SigV4 hashing --------------------------------------------


class ListBody:
    """BodyReader stand-in yielding preset chunks."""

    def __init__(self, chunks):
        self.chunks = list(chunks)

    async def read(self, n: int = 65536) -> bytes:
        if not self.chunks:
            return b""
        return self.chunks.pop(0)

    async def drain(self):
        self.chunks = []


def test_signed_payload_reader_offloaded_hash_verifies():
    from garage_tpu.api.signature import SignedPayloadReader

    async def main():
        import os

        # chunks above AND below the offload threshold, interleaved
        chunks = [os.urandom(200_000), b"small", os.urandom(70_000),
                  b"x" * 10]
        body = b"".join(chunks)
        r = SignedPayloadReader(ListBody(chunks),
                               hashlib.sha256(body).hexdigest())
        got = await r.read_all()
        assert got == body

    run(main())


def test_signed_payload_reader_rejects_bad_hash():
    from garage_tpu.api.http import HttpError
    from garage_tpu.api.signature import SignedPayloadReader

    async def main():
        import os

        chunks = [os.urandom(200_000), os.urandom(100_000)]
        r = SignedPayloadReader(ListBody(chunks), "0" * 64)
        with pytest.raises(HttpError) as ei:
            await r.read_all()
        assert ei.value.status == 400

    run(main())


def _chunked_wire(chunks, secret, region="garage", amz_date="20260803T000000Z",
                  scope_date="20260803", corrupt_at=None):
    """Build a signed aws-chunked body + the VerifiedRequest seed sig,
    mirroring tests/s3util.py's independent signer."""
    from garage_tpu.api.signature import VerifiedRequest, signing_key

    sk = signing_key(secret, scope_date, region)
    seed = "0" * 64
    scope = f"{scope_date}/{region}/s3/aws4_request"
    prev = seed
    wire = b""
    empty = hashlib.sha256(b"").hexdigest()
    for i, c in enumerate(list(chunks) + [b""]):
        sts = "\n".join(["AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev,
                         empty, hashlib.sha256(c).hexdigest()])
        sig = hmac.new(sk, sts.encode(), hashlib.sha256).hexdigest()
        prev = sig
        if corrupt_at is not None and i == corrupt_at:
            sig = "f" * 64
        wire += b"%x;chunk-signature=%s\r\n" % (len(c), sig.encode())
        wire += c + b"\r\n" if c else b"\r\n"
    v = VerifiedRequest("key", "STREAMING-AWS4-HMAC-SHA256-PAYLOAD",
                        seed, scope_date, sk, False)
    return wire, v, amz_date


def test_aws_chunked_reader_pipelined_verification_accepts():
    from garage_tpu.api.signature import AwsChunkedReader

    async def main():
        import os

        chunks = [os.urandom(150_000), os.urandom(80_000), b"tail"]
        wire, v, amz_date = _chunked_wire(chunks, "secret")
        r = AwsChunkedReader(ListBody([wire]), v, "garage", amz_date,
                             signed=True)
        assert await r.read_all() == b"".join(chunks)

    run(main())


def test_aws_chunked_reader_forged_chunk_still_403s():
    """Verification is deferred one read for overlap — but a forged
    chunk MUST still fail the request before the body completes."""
    from garage_tpu.api.http import HttpError
    from garage_tpu.api.signature import AwsChunkedReader

    async def main():
        import os

        for corrupt_at in (0, 1, 2):
            chunks = [os.urandom(150_000), os.urandom(80_000), b"tail"]
            wire, v, amz_date = _chunked_wire(chunks, "secret",
                                              corrupt_at=corrupt_at)
            r = AwsChunkedReader(ListBody([wire]), v, "garage", amz_date,
                                 signed=True)
            with pytest.raises(HttpError) as ei:
                await r.read_all()
            assert ei.value.status == 403

    run(main())


# ---- Chunker carry path --------------------------------------------------


def test_chunker_memoryview_carry_reassembles():
    """An oversize upstream chunk (aws-chunked clients pick their own
    chunk size) is carried as a memoryview; every emitted block must be
    real bytes of exactly block_size."""
    from garage_tpu.api.s3.put import Chunker

    async def main():
        import os

        big = os.urandom(1_000_000)  # ~3.8 blocks of 256 KiB
        ch = Chunker(ListBody([big, b"xy"]), 256 * 1024)
        out = []
        while True:
            b = await ch.next()
            if b is None:
                break
            assert isinstance(b, bytes)
            assert len(b) <= 256 * 1024
            out.append(b)
        assert b"".join(out) == big + b"xy"
        assert all(len(b) == 256 * 1024 for b in out[:-1])

    run(main())


# ---- zero-copy HTTP writer -----------------------------------------------


class FakeWriter:
    def __init__(self):
        self.writes: list[bytes] = []
        self.drains = 0

    def write(self, data):
        self.writes.append(bytes(data))

    async def drain(self):
        self.drains += 1


def test_write_response_coalesces_head_and_small_body():
    from garage_tpu.api.http import Response, write_response

    async def main():
        w = FakeWriter()
        await write_response(w, None, Response(200, [], b"hello"), True)
        assert len(w.writes) == 1  # ONE transport write for the response
        assert w.writes[0].endswith(b"\r\n\r\nhello")

    run(main())


def test_write_response_streams_memoryviews_with_bounded_drains():
    from garage_tpu.api.http import Response, write_response

    async def main():
        blocks = [memoryview(bytes([i]) * 65536) for i in range(16)]

        async def gen():
            for b in blocks:
                yield b

        total = sum(len(b) for b in blocks)
        resp = Response(200, [("content-length", str(total))], gen())
        w = FakeWriter()
        await write_response(w, None, resp, True)
        body = b"".join(w.writes)
        assert body.endswith(b"".join(bytes(b) for b in blocks))
        # high-water draining: far fewer drains than chunks
        assert w.drains < len(blocks)

    run(main())


def test_write_response_chunked_framing_intact():
    from garage_tpu.api.http import Response, write_response

    async def main():
        async def gen():
            yield b"abc"
            yield memoryview(b"defg")

        w = FakeWriter()
        await write_response(w, None, Response(200, [], gen()), True)
        raw = b"".join(w.writes)
        head, _, body = raw.partition(b"\r\n\r\n")
        assert b"transfer-encoding: chunked" in head
        assert body == b"3\r\nabc\r\n4\r\ndefg\r\n0\r\n\r\n"

    run(main())


def test_write_response_closes_generator_on_write_failure():
    """A client disconnect mid-stream must aclose the body generator
    (which is what cancels the readahead pipeline)."""
    from garage_tpu.api.http import Response, write_response

    class ExplodingWriter(FakeWriter):
        def __init__(self):
            super().__init__()
            self.n = 0

        def write(self, data):
            self.n += 1
            if self.n > 1:
                raise ConnectionError("peer reset")
            super().write(data)

    closed = {"v": False}

    async def gen():
        try:
            for i in range(10):
                yield b"x" * 70000
        finally:
            closed["v"] = True

    async def main():
        resp = Response(200, [("content-length", str(700000))], gen())
        with pytest.raises(ConnectionError):
            await write_response(ExplodingWriter(), None, resp, True)
        assert closed["v"]

    run(main())
