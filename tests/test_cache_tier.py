"""ISSUE 15: cluster-wide read cache tier.

Covers the tentpole end to end: rendezvous owner routing with breaker
filtering (a degraded owner drops OUT of the ring), the single-hop
`rpc_cache_probe` (hit = zero decodes anywhere; miss = local fallback
+ write-through at the owner), SSE-C never probed or pushed cross-node,
hot-hash hint gossip over peering pings, hint-gated resync fetches, the
clusterbox kill-the-owner drill (zero failed GETs, ring remaps, decode
count bounded), the shm forward ring's safety protocol, and the GL03
fixtures for the new cross-node seam.
"""

import asyncio
import os
import textwrap
import time

import pytest

from garage_tpu.utils.data import blake3sum
from test_block import make_block_cluster, stop_all


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def tier_cluster(tmp_path, n=4, rf=3, erasure=(2, 1)):
    net, systems, managers, tasks = await make_block_cluster(
        tmp_path, n=n, rf=rf, erasure=erasure, cache_tier=True)
    return net, systems, managers, tasks


def by_id(systems, managers):
    return {s.id: m for s, m in zip(systems, managers)}


async def wait_for(cond, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        await asyncio.sleep(0.05)
    assert cond(), f"timeout waiting for {what}"


# ---- ring / routing ------------------------------------------------------


def test_rendezvous_owner_shared_by_both_layers():
    from garage_tpu.gateway.ring import CacheRing, rendezvous_owner

    ids = [bytes([i]) * 32 for i in range(5)]
    ring = CacheRing(ids[0])
    ring.set_members(ids)
    for _ in range(100):
        h = os.urandom(32)
        assert ring.owner(h) == rendezvous_owner(ids, h)
    assert rendezvous_owner([], os.urandom(32)) is None


def test_tier_owner_routing_and_breaker_filtering(tmp_path):
    """An open-breaker owner drops OUT of the ring: its share remaps to
    the next-highest weight instead of blackholing probes."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            m = managers[0]
            tier = m.cache_tier
            assert tier is not None
            members = tier.members()
            assert sorted(members) == sorted(s.id for s in systems)
            # find a hash owned by a REMOTE node
            h = os.urandom(32)
            while tier.owner_of(h) is None:
                h = os.urandom(32)
            owner = tier.owner_of(h)
            health = m.rpc.health()
            for _ in range(5):  # BREAKER_FAILURES
                health.record_failure(owner)
            assert health.breaker_state(owner) == "open"
            assert owner not in tier.members()
            remapped = tier.owner_of(h)
            assert remapped != owner  # remapped or became ours (None)
            # un-owned hashes of OTHER owners kept their owner
            h2 = os.urandom(32)
            while tier.owner_of(h2) in (None, owner):
                h2 = os.urandom(32)
            health.record_success(owner)
            assert owner in tier.members()
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_tier_disabled_by_knob_and_by_cache_off(tmp_path):
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            tier = managers[0].cache_tier
            h = os.urandom(32)
            while tier.owner_of(h) is None:
                h = os.urandom(32)
            tier.enabled = False
            assert tier.owner_of(h) is None and tier.owns(h)
            tier.enabled = True
            managers[0].cache.configure(max_bytes=0)
            assert tier.owner_of(h) is None and tier.owns(h)
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- probe hit / miss-warms-owner ---------------------------------------


def test_probe_hit_serves_without_any_decode(tmp_path):
    """The acceptance property: once the owner holds the decoded
    payload, a read from ANY other node performs zero shard gathers and
    zero decodes anywhere — cluster-wide store reads stay flat."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(150_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False)
            owners = by_id(systems, managers)
            owner_id = (managers[0].cache_tier.owner_of(h)
                        or systems[0].id)
            owner = owners[owner_id]
            # PUT write-through pushes to the owner in the background
            await wait_for(lambda: owner.cache.get(h) is not None,
                           what="owner warmed by put write-through")
            readers = [m for m in managers
                       if m.system.id != owner_id]
            r0 = sum(m.metrics["store_reads"] for m in managers)
            for m in readers:
                assert await m.rpc_get_block(h) == data
            assert sum(m.metrics["store_reads"]
                       for m in managers) == r0  # zero decodes anywhere
            probes = sum(m.cache_tier.probe_hits for m in readers)
            assert probes == len(readers)
            # readers did NOT fill their local cache: one copy per
            # cluster, at the owner
            for m in readers:
                assert m.cache.get(h) is None
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_probe_miss_warms_owner_one_decode_cluster_wide(tmp_path):
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(120_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False,
                                            cacheable=False)  # cold
            owners = by_id(systems, managers)
            owner_id = managers[0].cache_tier.owner_of(h)
            reader = managers[0] if owner_id is not None \
                else managers[1]
            owner_id = reader.cache_tier.owner_of(h)
            assert owner_id is not None
            owner = owners[owner_id]
            assert owner.cache.get(h) is None
            # first read: probe misses, local decode, owner warmed
            assert await reader.rpc_get_block(h) == data
            assert reader.cache_tier.probe_misses >= 1
            await wait_for(lambda: owner.cache.get(h) is not None,
                           what="owner warmed after miss")
            # second read from a THIRD node: probe hit, no new decode
            third = next(m for m in managers
                         if m.system.id not in (owner_id,
                                                reader.system.id))
            r0 = sum(m.metrics["store_reads"] for m in managers)
            assert await third.rpc_get_block(h) == data
            assert sum(m.metrics["store_reads"] for m in managers) == r0
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_probe_rejects_corrupt_payload(tmp_path):
    """A cache owner answering with bytes that don't hash to the key
    must not be served: the prober verifies and falls back."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(80_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False)
            owners = by_id(systems, managers)
            tier = next(m for m in managers
                        if m.cache_tier.owner_of(h) is not None
                        ).cache_tier
            reader = tier.manager
            owner_id = tier.owner_of(h)
            owner = owners[owner_id]
            await wait_for(lambda: owner.cache.get(h) is not None)
            # poison the owner's cache entry behind the hash
            owner.cache.discard(h)
            owner.cache._prob[h] = b"x" * 80_000
            owner.cache._prob_bytes += 80_000
            got = await reader.rpc_get_block(h)
            assert got == data  # served by the store path instead
            assert tier.probe_corrupt == 1
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- SSE-C conformance ---------------------------------------------------


def test_ssec_never_probed_or_pushed_cross_node(tmp_path):
    """cacheable=False must suppress the cross-node lanes end to end:
    no probe RPC, no insert push, nothing in any cache."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(90_000)
            h = blake3sum(data)
            probed = []
            for m in managers:
                orig = m.cache_tier.probe

                async def spy(owner, h2, cacheable=True, _o=orig):
                    probed.append(h2)
                    return await _o(owner, h2, cacheable=cacheable)

                m.cache_tier.probe = spy
            await managers[0].rpc_put_block(h, data, compress=False,
                                            cacheable=False)
            for m in managers:
                assert await m.rpc_get_block(h, cacheable=False) == data
            assert probed == []
            for m in managers:
                assert m.cache.get(h) is None
                assert m.cache_tier.probes == 0
                assert m.cache_tier.inserts_pushed == 0
            # and the tier-level guard itself: probe(cacheable=False)
            # is a no-op even when called directly
            tier = managers[0].cache_tier
            owner = tier.owner_of(h) or systems[1].id
            assert await tier.probe(owner, h, cacheable=False) is None
            assert tier.probes == 0
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- hint gossip + hint-gated resync ------------------------------------


def test_hot_hash_hints_gossip_over_pings(tmp_path):
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(50_000)
            h = blake3sum(data)
            m0 = managers[0]
            m0.cache.insert(h, data)
            assert m0.cache.get(h) == data  # a HIT makes it hot
            assert h in m0.cache.top_keys(16)
            # pings run every ~0.2 s in this harness; hints ride both
            # directions of each ping
            await wait_for(
                lambda: all(m.cache_tier.is_hot(h)
                            for m in managers[1:]),
                timeout=20.0, what="hints to converge")
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_resync_fetch_routes_through_tier_when_hinted(tmp_path):
    """A hinted-hot replicate fetch is served by one probe instead of a
    remote packed read — and a COLD block never probes."""
    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=4, rf=3, cache_tier=True)  # replicate mode
        try:
            data = os.urandom(70_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False)
            owners = by_id(systems, managers)
            fetcher = next(m for m in managers
                           if m.cache_tier.owner_of(h) is not None)
            owner = owners[fetcher.cache_tier.owner_of(h)]
            await wait_for(lambda: owner.cache.get(h) is not None)
            fetcher.delete_local(h)
            assert not fetcher.has_local(h)

            async def boom(*a, **kw):
                raise AssertionError("remote store read used")

            # cold: no hint -> the tier lane must not even be tried
            assert not fetcher.cache_tier.is_hot(h)
            assert not await fetcher.resync._fetch_via_tier(h)
            # hot: hint it, then the fetch lands via one probe with the
            # remote store path forbidden
            fetcher.cache_tier.note_hints(owner.system.id, [h])
            fetcher._get_replicate = boom
            await fetcher.resync._fetch(h)
            assert fetcher.has_local(h)
            got = await asyncio.to_thread(fetcher.read_local, h)
            assert got is not None
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- clusterbox: kill the owner mid-hot-workload -------------------------


@pytest.mark.slow
def test_kill_owner_mid_hot_workload_zero_failed_gets(tmp_path):
    """The acceptance drill on a >= 4-node cluster with a Zipf-hot
    working set: cluster-wide decode count for the hot set stays ~1 per
    block; killing the cache owner of the hottest blocks mid-workload
    yields ZERO failed GETs (probes fail fast, reads fall back local)
    and the ring remaps within one breaker window."""
    run(_kill_owner_drill(tmp_path), timeout=300.0)


async def _kill_owner_drill(tmp_path):
    from clusterbox import ClusterBox

    box = ClusterBox(tmp_path, n=4, rf=3, erasure=(2, 1))
    await box.start()
    try:
        rng_blocks = [os.urandom(100_000) for _ in range(6)]
        hashes = [blake3sum(b) for b in rng_blocks]
        m0 = box.nodes[0].manager
        for h, b in zip(hashes, rng_blocks):
            await m0.rpc_put_block(h, b, compress=False)
        # warm: every node reads every block once (owners fill)
        for nd in box.nodes:
            for h, b in zip(hashes, rng_blocks):
                assert await nd.manager.rpc_get_block(h) == b
        managers = [nd.manager for nd in box.nodes]
        decodes_warm = sum(m.metrics["store_reads"] for m in managers)

        # Zipf-hot: hammer the first two blocks from every node
        hot = list(zip(hashes[:2], rng_blocks[:2]))
        failures = []

        async def hammer(nd, rounds=40):
            for i in range(rounds):
                h, b = hot[i % len(hot)]
                try:
                    got = await nd.manager.rpc_get_block(h)
                    if got != b:
                        failures.append(f"corrupt read on {nd.index}")
                except Exception as e:  # noqa: BLE001 - ledger test
                    failures.append(f"get on node {nd.index}: {e!r}")
                await asyncio.sleep(0.01)

        # kill the owner of the hottest block mid-hammer
        owner_id = None
        for nd in box.nodes:
            o = nd.manager.cache_tier.owner_of(hot[0][0])
            if o is not None:
                owner_id = o
                break
        assert owner_id is not None
        victim = next(nd for nd in box.nodes if nd.id == owner_id)
        survivors = [nd for nd in box.nodes if nd is not victim]

        tasks = [asyncio.ensure_future(hammer(nd)) for nd in survivors]
        await asyncio.sleep(0.15)
        await box.stop_node(victim)
        await asyncio.gather(*tasks)
        assert failures == [], failures[:5]
        # ring remapped off the dead owner on every survivor
        for nd in survivors:
            o = nd.manager.cache_tier.owner_of(hot[0][0])
            assert o != owner_id
        # decode work stayed bounded: the hot hammer (240 GETs) must
        # not have re-decoded per GET — only the fallback window while
        # the breaker opened pays decodes
        live = [nd.manager for nd in survivors]
        decodes_now = sum(m.metrics["store_reads"] for m in live)
        hammered = sum(1 for _ in survivors) * 40
        assert decodes_now - decodes_warm < hammered / 2, (
            decodes_now, decodes_warm)
    finally:
        await box.stop()


# ---- shm forward ring ----------------------------------------------------


def test_shm_ring_roundtrip_reuse_and_validation(tmp_path):
    from garage_tpu.gateway.shm import ShmReader, ShmRing, ring_path

    p = ring_path(str(tmp_path), 0)
    ring = ShmRing(p, 1 << 20, lease_s=30.0)
    payload = os.urandom(200_000)
    h = b"\x01" * 32
    ref = ring.publish(h, payload)
    assert ref is not None
    rd = ShmReader()
    mv = rd.get(ref, h)
    assert isinstance(mv, memoryview) and bytes(mv) == payload
    # a hot hash is written once per lease, not once per forward
    assert ring.publish(h, payload) == ref and ring.reused == 1
    # wrong hash / stale seq / truncated refs all refuse
    assert rd.get(ref, b"\x02" * 32) is None
    assert rd.get({**ref, "seq": ref["seq"] + 1}, h) is None
    assert rd.get({**ref, "off": ring.size * 2}, h) is None
    assert rd.get({"path": p}, h) is None


def test_shm_ring_lease_blocks_overwrite_then_expires(tmp_path):
    from garage_tpu.gateway.shm import ShmReader, ShmRing, ring_path

    p = ring_path(str(tmp_path), 1)
    ring = ShmRing(p, 1 << 19, lease_s=0.2)  # 512 KiB
    rd = ShmReader()
    refs = [(os.urandom(32), os.urandom(100_000)) for _ in range(8)]
    out = [ring.publish(h, b) for h, b in refs]
    # the ring cannot host 800 KB of leased slots in 512 KiB: some
    # publishes fall back instead of overwriting a leased slot
    assert any(r is None for r in out)
    assert ring.fallbacks > 0
    # every reference that WAS handed out still validates
    for (h, b), r in zip(refs, out):
        if r is not None:
            assert bytes(rd.get(r, h)) == b
    time.sleep(0.25)  # leases expire -> space frees
    assert ring.publish(b"\x07" * 32, os.urandom(100_000)) is not None


def test_shm_oversize_payload_falls_back(tmp_path):
    from garage_tpu.gateway.shm import ShmRing, ring_path

    ring = ShmRing(ring_path(str(tmp_path), 2), 1 << 16, lease_s=1.0)
    assert ring.publish(b"\x01" * 32, os.urandom(1 << 17)) is None


def test_shm_crash_respawn_preserves_leased_slots(tmp_path):
    """A CRASH-respawned owner (no clean close) reopens the same inode
    WITHOUT zeroing it — a sibling still streaming a leased slot out
    of its mapping must keep seeing the published bytes — and
    references minted by the previous incarnation fail the seq-epoch
    check instead of serving whatever now occupies the slot."""
    from garage_tpu.gateway.shm import ShmReader, ShmRing, ring_path

    p = ring_path(str(tmp_path), 3)
    ring1 = ShmRing(p, 1 << 18, lease_s=30.0)
    h1 = b"\x01" * 32
    data1 = os.urandom(70_000)
    old_ref = ring1.publish(h1, data1)
    rd = ShmReader()
    mv_in_flight = rd.get(old_ref, h1)  # a slow client mid-stream
    assert mv_in_flight is not None
    # crash: NO close() — the inode (and its contents) survive
    ring2 = ShmRing(p, 1 << 18, lease_s=30.0)  # the respawn
    # the in-flight view still reads the original bytes (no memset)
    assert bytes(mv_in_flight) == data1
    h2 = b"\x02" * 32
    data2 = os.urandom(70_000)
    new_ref = ring2.publish(h2, data2)
    # same inode: the reader's EXISTING mapping serves the new slot
    assert bytes(rd.get(new_ref, h2)) == data2
    # the old incarnation's reference refuses (fresh seq epoch)
    assert rd.get(old_ref, h1) is None


def test_shm_clean_close_unlinks_and_reader_remaps(tmp_path):
    """Clean shutdown unlinks the ring (ephemeral clusters must not
    accumulate resident tmpfs files); a reader still holding the OLD
    inode's mapping detects the recreate and remaps on its next
    validation failure."""
    from garage_tpu.gateway.shm import ShmReader, ShmRing, ring_path

    p = ring_path(str(tmp_path), 4)
    ring1 = ShmRing(p, 1 << 18, lease_s=30.0)
    h1 = b"\x01" * 32
    ref1 = ring1.publish(h1, os.urandom(60_000))
    rd = ShmReader()
    assert rd.get(ref1, h1) is not None  # reader mapped inode #1
    ring1.close()
    assert not os.path.exists(p)  # unlinked on clean close
    ring2 = ShmRing(p, 1 << 18, lease_s=30.0)  # fresh inode
    h2 = b"\x02" * 32
    data2 = os.urandom(60_000)
    ref2 = ring2.publish(h2, data2)
    # the cached old-inode map fails validation -> remap -> serve
    assert bytes(rd.get(ref2, h2)) == data2
    ring2.close()


# ---- GL03: the new cross-node seam --------------------------------------


def _lint(src: str, rel_path: str):
    from garage_tpu.analysis import analyze_source, default_rules

    ctx = analyze_source(textwrap.dedent(src), default_rules(),
                         rel_path=rel_path)
    return sorted({v.rule for v in ctx.violations if v.active})


def test_gl03_fires_on_tier_probe_in_ssec_scope():
    assert _lint("""
        async def stream(mgr, h, sse_key):
            tier = mgr.cache_tier
            return await tier.probe(owner_of(h), h)
    """, "garage_tpu/api/s3/fake_tier.py") == ["GL03"]


def test_gl03_quiet_with_cacheable_on_tier_probe():
    assert _lint("""
        async def stream(mgr, h, sse_key):
            tier = mgr.cache_tier
            return await tier.probe(owner_of(h), h,
                                    cacheable=sse_key is None)
    """, "garage_tpu/api/s3/fake_tier.py") == []


def test_gl03_fires_on_tainted_payload_into_tier_insert():
    assert _lint("""
        def warm(mgr, owner, h, sse_payload):
            mgr.cache_tier.insert_at(owner, h, sse_payload)
    """, "garage_tpu/block/fake_tier.py") == ["GL03"]


def test_gl03_quiet_on_untainted_tier_insert():
    assert _lint("""
        def warm(mgr, owner, h, payload):
            mgr.cache_tier.insert_at(owner, h, payload)
    """, "garage_tpu/block/fake_tier.py") == []
