"""ISSUE 15 + ISSUE 18: cluster-wide read cache tier.

Covers the ISSUE 15 tentpole end to end: rendezvous owner routing with
breaker filtering (a degraded owner drops OUT of the ring), the
single-hop `rpc_cache_probe` (hit = zero decodes anywhere; miss = local
fallback + write-through at the owner), SSE-C never probed or pushed
cross-node, hot-hash hint gossip over peering pings, hint-gated resync
fetches, the clusterbox kill-the-owner drill (zero failed GETs, ring
remaps, decode count bounded), the shm forward ring's safety protocol,
and the GL03 fixtures for the cross-node seam.

ISSUE 18 (cold-herd engineering) adds: the owner-side probe
singleflight lease ledger (conservation under holder death and waiter
cancellation), the wait-inside-the-flat-probe-budget contract (unit
clamp + a chaos rpc_hang pin + a dead-holder fallback), the cold-herd
and flash-crowd decode-amplification bounds (O(blocks), not
O(blocks x nodes) — including a slow kill-the-lease-holder soak under
randomized chaos), the node-local `_read_store` singleflight, the
packed-bytes segment (byte-identity vs the on-disk shard files,
zero-gather warm rebuilds, scrub repair riding the cache), and
hint-driven owner prefetch.
"""

import asyncio
import os
import textwrap
import time

import pytest

from garage_tpu.block.manager import pack_shard, unpack_shard
from garage_tpu.utils.data import blake3sum
from test_block import make_block_cluster, stop_all


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def tier_cluster(tmp_path, n=4, rf=3, erasure=(2, 1)):
    net, systems, managers, tasks = await make_block_cluster(
        tmp_path, n=n, rf=rf, erasure=erasure, cache_tier=True)
    return net, systems, managers, tasks


def by_id(systems, managers):
    return {s.id: m for s, m in zip(systems, managers)}


async def wait_for(cond, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        await asyncio.sleep(0.05)
    assert cond(), f"timeout waiting for {what}"


# ---- ring / routing ------------------------------------------------------


def test_rendezvous_owner_shared_by_both_layers():
    from garage_tpu.gateway.ring import CacheRing, rendezvous_owner

    ids = [bytes([i]) * 32 for i in range(5)]
    ring = CacheRing(ids[0])
    ring.set_members(ids)
    for _ in range(100):
        h = os.urandom(32)
        assert ring.owner(h) == rendezvous_owner(ids, h)
    assert rendezvous_owner([], os.urandom(32)) is None


def test_tier_owner_routing_and_breaker_filtering(tmp_path):
    """An open-breaker owner drops OUT of the ring: its share remaps to
    the next-highest weight instead of blackholing probes."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            m = managers[0]
            tier = m.cache_tier
            assert tier is not None
            members = tier.members()
            assert sorted(members) == sorted(s.id for s in systems)
            # find a hash owned by a REMOTE node
            h = os.urandom(32)
            while tier.owner_of(h) is None:
                h = os.urandom(32)
            owner = tier.owner_of(h)
            health = m.rpc.health()
            for _ in range(5):  # BREAKER_FAILURES
                health.record_failure(owner)
            assert health.breaker_state(owner) == "open"
            assert owner not in tier.members()
            remapped = tier.owner_of(h)
            assert remapped != owner  # remapped or became ours (None)
            # un-owned hashes of OTHER owners kept their owner
            h2 = os.urandom(32)
            while tier.owner_of(h2) in (None, owner):
                h2 = os.urandom(32)
            health.record_success(owner)
            assert owner in tier.members()
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_tier_disabled_by_knob_and_by_cache_off(tmp_path):
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            tier = managers[0].cache_tier
            h = os.urandom(32)
            while tier.owner_of(h) is None:
                h = os.urandom(32)
            tier.enabled = False
            assert tier.owner_of(h) is None and tier.owns(h)
            tier.enabled = True
            managers[0].cache.configure(max_bytes=0)
            assert tier.owner_of(h) is None and tier.owns(h)
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- probe hit / miss-warms-owner ---------------------------------------


def test_probe_hit_serves_without_any_decode(tmp_path):
    """The acceptance property: once the owner holds the decoded
    payload, a read from ANY other node performs zero shard gathers and
    zero decodes anywhere — cluster-wide store reads stay flat."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(150_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False)
            owners = by_id(systems, managers)
            owner_id = (managers[0].cache_tier.owner_of(h)
                        or systems[0].id)
            owner = owners[owner_id]
            # PUT write-through pushes to the owner in the background
            await wait_for(lambda: owner.cache.get(h) is not None,
                           what="owner warmed by put write-through")
            readers = [m for m in managers
                       if m.system.id != owner_id]
            r0 = sum(m.metrics["store_reads"] for m in managers)
            for m in readers:
                assert await m.rpc_get_block(h) == data
            assert sum(m.metrics["store_reads"]
                       for m in managers) == r0  # zero decodes anywhere
            probes = sum(m.cache_tier.probe_hits for m in readers)
            assert probes == len(readers)
            # readers did NOT fill their local cache: one copy per
            # cluster, at the owner
            for m in readers:
                assert m.cache.get(h) is None
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_probe_miss_warms_owner_one_decode_cluster_wide(tmp_path):
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(120_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False,
                                            cacheable=False)  # cold
            owners = by_id(systems, managers)
            owner_id = managers[0].cache_tier.owner_of(h)
            reader = managers[0] if owner_id is not None \
                else managers[1]
            owner_id = reader.cache_tier.owner_of(h)
            assert owner_id is not None
            owner = owners[owner_id]
            assert owner.cache.get(h) is None
            # first read: probe misses, local decode, owner warmed
            assert await reader.rpc_get_block(h) == data
            assert reader.cache_tier.probe_misses >= 1
            await wait_for(lambda: owner.cache.get(h) is not None,
                           what="owner warmed after miss")
            # second read from a THIRD node: probe hit, no new decode
            third = next(m for m in managers
                         if m.system.id not in (owner_id,
                                                reader.system.id))
            r0 = sum(m.metrics["store_reads"] for m in managers)
            assert await third.rpc_get_block(h) == data
            assert sum(m.metrics["store_reads"] for m in managers) == r0
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_probe_rejects_corrupt_payload(tmp_path):
    """A cache owner answering with bytes that don't hash to the key
    must not be served: the prober verifies and falls back."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(80_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False)
            owners = by_id(systems, managers)
            tier = next(m for m in managers
                        if m.cache_tier.owner_of(h) is not None
                        ).cache_tier
            reader = tier.manager
            owner_id = tier.owner_of(h)
            owner = owners[owner_id]
            await wait_for(lambda: owner.cache.get(h) is not None)
            # poison the owner's cache entry behind the hash
            owner.cache.discard(h)
            owner.cache._prob[h] = b"x" * 80_000
            owner.cache._prob_bytes += 80_000
            got = await reader.rpc_get_block(h)
            assert got == data  # served by the store path instead
            assert tier.probe_corrupt == 1
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- SSE-C conformance ---------------------------------------------------


def test_ssec_never_probed_or_pushed_cross_node(tmp_path):
    """cacheable=False must suppress the cross-node lanes end to end:
    no probe RPC, no insert push, nothing in any cache."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(90_000)
            h = blake3sum(data)
            probed = []
            for m in managers:
                orig = m.cache_tier.probe

                async def spy(owner, h2, cacheable=True, _o=orig):
                    probed.append(h2)
                    return await _o(owner, h2, cacheable=cacheable)

                m.cache_tier.probe = spy
            await managers[0].rpc_put_block(h, data, compress=False,
                                            cacheable=False)
            for m in managers:
                assert await m.rpc_get_block(h, cacheable=False) == data
            assert probed == []
            for m in managers:
                assert m.cache.get(h) is None
                assert m.cache_tier.probes == 0
                assert m.cache_tier.inserts_pushed == 0
                # ISSUE 18: nor does SSE-C enter the packed segment or
                # the lease ledger on any node
                assert m.packed_cache.get(h) is None
                assert m.cache_tier.leases.minted == 0
            # and the tier-level guard itself: probe(cacheable=False)
            # is a no-op even when called directly
            tier = managers[0].cache_tier
            owner = tier.owner_of(h) or systems[1].id
            assert await tier.probe(owner, h, cacheable=False) is None
            assert tier.probes == 0
            # probe_full honors the same guard: no probe, no lease
            res = await tier.probe_full(owner, h, cacheable=False,
                                        kinds=("plain", "packed"))
            assert res.plain is None and res.packed is None
            assert not res.lease and not res.timed_out
            assert tier.probes == 0
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- hint gossip + hint-gated resync ------------------------------------


def test_hot_hash_hints_gossip_over_pings(tmp_path):
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(50_000)
            h = blake3sum(data)
            m0 = managers[0]
            m0.cache.insert(h, data)
            assert m0.cache.get(h) == data  # a HIT makes it hot
            assert h in m0.cache.top_keys(16)
            # pings run every ~0.2 s in this harness; hints ride both
            # directions of each ping
            await wait_for(
                lambda: all(m.cache_tier.is_hot(h)
                            for m in managers[1:]),
                timeout=20.0, what="hints to converge")
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_resync_fetch_routes_through_tier_when_hinted(tmp_path):
    """A hinted-hot replicate fetch is served by one probe instead of a
    remote packed read — and a COLD block never probes."""
    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=4, rf=3, cache_tier=True)  # replicate mode
        try:
            data = os.urandom(70_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False)
            owners = by_id(systems, managers)
            fetcher = next(m for m in managers
                           if m.cache_tier.owner_of(h) is not None)
            owner = owners[fetcher.cache_tier.owner_of(h)]
            await wait_for(lambda: owner.cache.get(h) is not None)
            fetcher.delete_local(h)
            assert not fetcher.has_local(h)

            async def boom(*a, **kw):
                raise AssertionError("remote store read used")

            # cold: no hint -> the tier lane must not even be tried
            assert not fetcher.cache_tier.is_hot(h)
            assert not await fetcher.resync._fetch_via_tier(h)
            # hot: hint it, then the fetch lands via one probe with the
            # remote store path forbidden
            fetcher.cache_tier.note_hints(owner.system.id, [h])
            fetcher._get_replicate = boom
            await fetcher.resync._fetch(h)
            assert fetcher.has_local(h)
            got = await asyncio.to_thread(fetcher.read_local, h)
            assert got is not None
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- clusterbox: kill the owner mid-hot-workload -------------------------


@pytest.mark.slow
def test_kill_owner_mid_hot_workload_zero_failed_gets(tmp_path):
    """The acceptance drill on a >= 4-node cluster with a Zipf-hot
    working set: cluster-wide decode count for the hot set stays ~1 per
    block; killing the cache owner of the hottest blocks mid-workload
    yields ZERO failed GETs (probes fail fast, reads fall back local)
    and the ring remaps within one breaker window."""
    run(_kill_owner_drill(tmp_path), timeout=300.0)


async def _kill_owner_drill(tmp_path):
    from clusterbox import ClusterBox

    box = ClusterBox(tmp_path, n=4, rf=3, erasure=(2, 1))
    await box.start()
    try:
        rng_blocks = [os.urandom(100_000) for _ in range(6)]
        hashes = [blake3sum(b) for b in rng_blocks]
        m0 = box.nodes[0].manager
        for h, b in zip(hashes, rng_blocks):
            await m0.rpc_put_block(h, b, compress=False)
        # warm: every node reads every block once (owners fill)
        for nd in box.nodes:
            for h, b in zip(hashes, rng_blocks):
                assert await nd.manager.rpc_get_block(h) == b
        managers = [nd.manager for nd in box.nodes]
        decodes_warm = sum(m.metrics["store_reads"] for m in managers)

        # Zipf-hot: hammer the first two blocks from every node
        hot = list(zip(hashes[:2], rng_blocks[:2]))
        failures = []

        async def hammer(nd, rounds=40):
            for i in range(rounds):
                h, b = hot[i % len(hot)]
                try:
                    got = await nd.manager.rpc_get_block(h)
                    if got != b:
                        failures.append(f"corrupt read on {nd.index}")
                except Exception as e:  # noqa: BLE001 - ledger test
                    failures.append(f"get on node {nd.index}: {e!r}")
                await asyncio.sleep(0.01)

        # kill the owner of the hottest block mid-hammer
        owner_id = None
        for nd in box.nodes:
            o = nd.manager.cache_tier.owner_of(hot[0][0])
            if o is not None:
                owner_id = o
                break
        assert owner_id is not None
        victim = next(nd for nd in box.nodes if nd.id == owner_id)
        survivors = [nd for nd in box.nodes if nd is not victim]

        tasks = [asyncio.ensure_future(hammer(nd)) for nd in survivors]
        await asyncio.sleep(0.15)
        await box.stop_node(victim)
        await asyncio.gather(*tasks)
        assert failures == [], failures[:5]
        # ring remapped off the dead owner on every survivor
        for nd in survivors:
            o = nd.manager.cache_tier.owner_of(hot[0][0])
            assert o != owner_id
        # decode work stayed bounded: the hot hammer (240 GETs) must
        # not have re-decoded per GET — only the fallback window while
        # the breaker opened pays decodes
        live = [nd.manager for nd in survivors]
        decodes_now = sum(m.metrics["store_reads"] for m in live)
        hammered = sum(1 for _ in survivors) * 40
        assert decodes_now - decodes_warm < hammered / 2, (
            decodes_now, decodes_warm)
    finally:
        await box.stop()


@pytest.mark.slow
def test_flash_crowd_kill_lease_holder_soak(tmp_path):
    """ISSUE 18 acceptance soak: a 6-node Zipf flash crowd over a COLD
    working set, with randomized absorbable chaos armed (net_delay
    everywhere, rpc_error scoped to the victim) and the lease-holding
    ring owner of the hottest block SIGKILLed mid-drill. Survivor GETs
    must all succeed, the lease machinery must have engaged, and the
    cluster decode count must stay far below one-per-GET. Seed comes
    from CHAOS_SOAK_SEED so a nightly failure replays exactly."""
    run(_flash_crowd_soak(tmp_path), timeout=300.0)


async def _flash_crowd_soak(tmp_path):
    import random

    from clusterbox import ClusterBox
    from garage_tpu.chaos import FaultSpec, arm, disarm

    seed = int(os.environ.get("CHAOS_SOAK_SEED", "1807"))
    box = ClusterBox(tmp_path, n=6, rf=3, erasure=(2, 1))
    await box.start()
    try:
        blocks = [os.urandom(100_000) for _ in range(8)]
        hashes = [blake3sum(b) for b in blocks]
        payload = dict(zip(hashes, blocks))
        m0 = box.nodes[0].manager
        for h, b in payload.items():
            await m0.rpc_put_block(h, b, compress=False,
                                   cacheable=False)  # fully cold
        for nd in box.nodes:
            nd.manager.cache_tier.lease_wait_ms = 1000.0

        failures = []

        async def hammer(nd, rounds=40):
            # Zipf-weighted: rank r drawn with weight 1/r, per-node
            # deterministic stream so a seeded run replays exactly
            rng = random.Random(seed ^ (nd.index * 7919))
            weights = [1.0 / (i + 1) for i in range(len(hashes))]
            seq = rng.choices(hashes, weights=weights, k=rounds)
            for h in seq:
                try:
                    got = await nd.manager.rpc_get_block(h)
                    if got != payload[h]:
                        failures.append(f"node {nd.index}: corrupt read")
                except Exception as e:  # noqa: BLE001 - ledger test
                    failures.append(f"node {nd.index}: {e!r}")
                await asyncio.sleep(0.005)

        c = arm(seed=seed)
        # absorbable background chaos: jitter every block RPC a little
        c.add(FaultSpec(kind="net_delay", prob=0.1, delay_s=0.008,
                        endpoint="garage_tpu/block"))
        tasks = [asyncio.ensure_future(hammer(nd)) for nd in box.nodes]
        await asyncio.sleep(0.08)
        # the victim: the ring owner of the hottest block — under the
        # cold herd it is holding (or just resolved) the decode lease
        owner_id = None
        for nd in box.nodes:
            o = nd.manager.cache_tier.owner_of(hashes[0])
            if o is not None:
                owner_id = o
                break
        victim = next((nd for nd in box.nodes if nd.id == owner_id),
                      box.nodes[-1])
        # its remaining RPCs error out non-deterministically too
        c.add(FaultSpec(kind="rpc_error", prob=0.3,
                        peer=victim.id.hex()[:8],
                        endpoint="garage_tpu/block"))
        vt = tasks[box.nodes.index(victim)]
        vt.cancel()
        await asyncio.gather(vt, return_exceptions=True)
        await box.stop_node(victim)
        survivors = [nd for nd in box.nodes if nd is not victim]
        await asyncio.gather(*[tasks[box.nodes.index(nd)]
                               for nd in survivors])
        disarm()
        # the victim's own in-flight GETs may legitimately have died
        # with it — only survivor reads are the ledger
        vtag = f"node {victim.index}:"
        survivor_failures = [f for f in failures
                             if not f.startswith(vtag)]
        assert survivor_failures == [], survivor_failures[:5]
        live = [nd.manager for nd in survivors]
        minted = sum(m.cache_tier.leases.minted for m in live)
        assert minted >= 1, "lease machinery never engaged"
        hammered = len(survivors) * 40
        decodes = sum(m.metrics["store_reads"] for m in live)
        assert decodes < hammered / 2, (decodes, hammered)
    finally:
        disarm()
        await box.stop()


# ---- ISSUE 18: probe singleflight leases ---------------------------------


def test_lease_table_conservation_holder_death_and_cancel():
    """Unit contract of the owner-side ledger: single-holder election,
    waiter accounting survives a cancellation mid-park, a holder that
    dies unresolved is reaped at TTL so the next prober can re-mint,
    and the conservation invariant (minted == resolved + expired +
    live; zero parked waiters) holds through all of it — the same
    predicate GARAGE_SANITIZE checks at loop teardown."""
    from garage_tpu.block.cache_tier import ProbeLeaseTable

    async def main():
        lt = ProbeLeaseTable(wait_ms=80.0)
        h = b"\x01" * 32
        assert lt.mint(h, b"a" * 32)
        assert not lt.mint(h, b"b" * 32)  # one holder per hash
        w_timeout = asyncio.create_task(lt.wait(h, 0.08))
        w_cancel = asyncio.create_task(lt.wait(h, 5.0))
        await asyncio.sleep(0.01)
        w_cancel.cancel()  # a waiter's client disconnects mid-park
        with pytest.raises(asyncio.CancelledError):
            await w_cancel
        assert lt._waiters == 1  # the cancel was accounted immediately
        assert await w_timeout is False  # holder died: timeout, no wake
        assert lt.wait_timeouts == 1
        # the corpse expires at TTL; the NEXT prober mints afresh
        await asyncio.sleep(lt.ttl_s + 0.05)
        assert not lt.live(h)
        assert lt.expired == 1
        assert lt.mint(h, b"c" * 32)
        waiter = asyncio.create_task(lt.wait(h, 5.0))
        await asyncio.sleep(0.01)
        lt.resolve(h)  # the insert lands: parked probers wake
        assert await waiter is True
        assert lt.wait_hits == 1
        assert lt.minted == 2 and lt.resolved == 1 and lt.expired == 1
        assert lt.conservation_ok and lt._waiters == 0

    run(main())


def test_probe_wait_clamped_inside_flat_probe_timeout():
    """Satellite contract: the lease wait a prober may request (and an
    owner may grant — the handler re-clamps with the same function)
    always fits INSIDE the flat 2 s probe budget with the transfer
    margin spared, no matter how the knob is configured — the wait can
    never stack on top of the RPC timeout. wait_ms=0 is the leases-off
    switch: no wait, and mint refuses."""
    from garage_tpu.block.cache_tier import (PROBE_TIMEOUT_S,
                                             PROBE_WAIT_MARGIN_S,
                                             ClusterCacheTier)

    tier = ClusterCacheTier(manager=None)
    budget_ms = (PROBE_TIMEOUT_S - PROBE_WAIT_MARGIN_S) * 1000.0
    tier.lease_wait_ms = 10_000.0  # operator asks for more than the budget
    assert tier.probe_wait_ms() == budget_ms
    tier.lease_wait_ms = 100.0
    assert tier.probe_wait_ms() == 100.0
    tier.lease_wait_ms = 0.0
    assert tier.probe_wait_ms() == 0.0
    assert not tier.leases.mint(b"\x01" * 32, b"a" * 32)


def test_cold_herd_collapses_to_one_decode(tmp_path):
    """The tentpole property at its sharpest: a fully cold 4-node herd
    on ONE block — every node GETs concurrently, the ring owner
    included — performs exactly one gather+decode cluster-wide.
    Whoever reaches the owner's lease table first (the owner's own
    self-lease, or the first remote prober's grant) pays it; everyone
    else parks and is woken by the write-through insert."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            for m in managers:
                m.cache_tier.lease_wait_ms = 1000.0
            data = os.urandom(150_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False,
                                            cacheable=False)  # cold
            owner_id = next(o for o in (m.cache_tier.owner_of(h)
                                        for m in managers)
                            if o is not None)
            owner = by_id(systems, managers)[owner_id]
            d0 = sum(m.metrics["store_reads"] for m in managers)
            got = await asyncio.gather(*[m.rpc_get_block(h)
                                         for m in managers])
            assert all(g == data for g in got)
            assert sum(m.metrics["store_reads"]
                       for m in managers) - d0 == 1
            lt = owner.cache_tier.leases
            assert lt.minted >= 1
            # the rest of the herd parked and was woken, not re-decoded
            waits = lt.wait_hits + sum(m.cache_tier.lease_wait_hits
                                       for m in managers)
            assert waits >= 1
            await wait_for(lambda: lt.conservation_ok,
                           what="lease conservation")
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_flash_crowd_decode_amplification_bounded(tmp_path):
    """The acceptance bound on a herd over a SET of cold blocks: 6
    nodes x 6 blocks x 3 synchronized rounds of GETs must stay within
    1.5 decodes per distinct block cluster-wide — O(blocks), not
    O(blocks x nodes)."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path, n=6)
        try:
            for m in managers:
                m.cache_tier.lease_wait_ms = 1000.0
            blocks = [os.urandom(120_000) for _ in range(6)]
            hashes = [blake3sum(b) for b in blocks]
            for h, b in zip(hashes, blocks):
                await managers[0].rpc_put_block(h, b, compress=False,
                                                cacheable=False)
            d0 = sum(m.metrics["store_reads"] for m in managers)

            async def herd(m):
                for _ in range(3):
                    for h, b in zip(hashes, blocks):
                        assert await m.rpc_get_block(h) == b

            await asyncio.gather(*[herd(m) for m in managers])
            decodes = sum(m.metrics["store_reads"]
                          for m in managers) - d0
            assert decodes <= 1.5 * len(blocks), decodes
            assert sum(m.cache_tier.leases.minted for m in managers) >= 1
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_node_local_read_singleflight_collapse(tmp_path):
    """With the cross-node tier off, concurrent same-hash readers ON
    ONE NODE still collapse onto a single leader's decode via the
    `_read_store` singleflight map; the hash is released on completion
    and SSE-C reads never transit the shared future."""
    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=3, rf=3, cache_tier=True)
        try:
            data = os.urandom(100_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False,
                                            cacheable=False)
            m = managers[0]
            m.cache_tier.enabled = False  # isolate the node-local lane
            d0 = m.metrics["store_reads"]
            got = await asyncio.gather(*[m.rpc_get_block(h)
                                         for _ in range(8)])
            assert all(g == data for g in got)
            assert m.metrics["store_reads"] - d0 == 1
            assert m.sf_leaders == 1 and m.sf_collapsed == 7
            assert len(m._sf) == 0  # released on completion
            # SSE-C reads go straight to the store, never the future
            d1 = m.metrics["store_reads"]
            await asyncio.gather(*[m.rpc_get_block(h, cacheable=False)
                                   for _ in range(3)])
            assert m.metrics["store_reads"] - d1 == 3
            assert m.sf_leaders == 1 and m.sf_collapsed == 7
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_hung_owner_wait_rides_inside_flat_probe_timeout(tmp_path):
    """Chaos pin of the wait-bound contract: the owner is blackholed
    (rpc_hang sleeps out the caller's whole budget), the lease wait is
    configured absurdly high — and the GET still completes in about
    PROBE_TIMEOUT_S plus one local decode, because the wait is
    budgeted INSIDE the flat probe timeout, never stacked on top."""
    async def main():
        from garage_tpu.block.cache_tier import PROBE_TIMEOUT_S
        from garage_tpu.chaos import FaultSpec, arm, disarm

        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(100_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False,
                                            cacheable=False)
            reader = next(m for m in managers
                          if m.cache_tier.owner_of(h) is not None)
            owner_id = reader.cache_tier.owner_of(h)
            reader.cache_tier.lease_wait_ms = 10_000.0
            c = arm(seed=18)
            c.add(FaultSpec(kind="rpc_hang", peer=owner_id.hex()[:8],
                            endpoint="garage_tpu/block", count=1))
            t0 = time.monotonic()
            assert await reader.rpc_get_block(h) == data
            dt = time.monotonic() - t0
            assert c.total_fired == 1, "hang was never injected"
            assert dt < PROBE_TIMEOUT_S + 1.5, (
                f"wait stacked on top of the probe budget: {dt:.1f}s")
            assert reader.cache_tier.probe_fails == 1
        finally:
            disarm()
            await stop_all(systems, tasks)

    run(main())


def test_dead_lease_holder_waiters_fall_back_within_budget(tmp_path):
    """A lease whose holder dies silently costs its waiters only the
    OWNER's configured wait (the server-side clamp outranks the
    prober's request): the parked probe answers a waited miss, the GET
    falls back to the local store path, the fallback does NOT push
    write-through (the holder's insert is presumed in flight), and the
    corpse is reaped at TTL with conservation intact."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data = os.urandom(90_000)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data, compress=False,
                                            cacheable=False)
            reader = next(m for m in managers
                          if m.cache_tier.owner_of(h) is not None)
            owner_id = reader.cache_tier.owner_of(h)
            owner = by_id(systems, managers)[owner_id]
            reader.cache_tier.lease_wait_ms = 1400.0  # prober asks big
            owner.cache_tier.lease_wait_ms = 120.0    # owner grants less
            # a holder that will never resolve (SIGKILLed mid-decode)
            assert owner.cache_tier.leases.mint(h, b"\xdd" * 32)
            t0 = time.monotonic()
            assert await reader.rpc_get_block(h) == data
            dt = time.monotonic() - t0
            # parked ~the OWNER's 120 ms clamp (not the 1400 asked),
            # then one store read — nowhere near the 2 s probe budget
            assert 0.1 <= dt < 1.0, f"owner did not clamp the wait: {dt:.2f}s"
            assert reader.cache_tier.lease_wait_timeouts == 1
            assert owner.cache_tier.leases.wait_timeouts == 1
            # the fallback suppressed its write-through push
            assert reader.cache_tier.inserts_pushed == 0
            await asyncio.sleep(0.2)
            assert owner.cache.get(h) is None
            await wait_for(lambda: not owner.cache_tier.leases.live(h),
                           what="lease corpse reaped at TTL")
            assert owner.cache_tier.leases.expired == 1
            assert owner.cache_tier.leases.conservation_ok
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- ISSUE 18: packed-bytes tier -----------------------------------------


def _find_owned_placed(systems, managers, need_leader=False):
    """(data, h, manager, placement) where the ring owner of h also
    holds one of its erasure shards (and is the stripe's scrub leader
    when need_leader) — the geometry the packed-tier tests need."""
    from garage_tpu.block.codec import shard_nodes_of

    layout = systems[0].layout_helper.current()
    width = managers[0].codec.width
    while True:
        data = os.urandom(150_000)
        h = blake3sum(data)
        placement = shard_nodes_of(layout, h, width)
        for m in managers:
            if not m.cache_tier.local_owner(h):
                continue
            if m.system.id not in placement:
                continue
            if need_leader and placement[0] != m.system.id:
                continue
            return data, h, m, placement


async def _wait_shards_placed(systems, managers, h, placement):
    ms = by_id(systems, managers)
    await wait_for(
        lambda: all(idx in ms[node].local_parts(h)
                    for idx, node in enumerate(placement)),
        what="shards placed")


def test_packed_tier_byte_identity_and_warm_rebuild_zero_gather(tmp_path):
    """The packed segment holds the EXACT bytes the erasure decode
    reassembled: re-encoding them through feeder.encode_put reproduces
    every on-disk shard file byte-for-byte, and a warm _rebuild_shard
    serves from the tier with the gather path forbidden — the
    acceptance's 'warm rebuild RPC fetch count == 0'."""
    async def main():
        from garage_tpu.block import DataBlock

        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data, h, m, placement = _find_owned_placed(systems, managers)
            await managers[0].rpc_put_block(h, data, compress=False,
                                            cacheable=False)
            await _wait_shards_placed(systems, managers, h, placement)
            # cold cacheable read on the ring owner: the decode admits
            # the reassembled packed bytes into the LOCAL packed segment
            assert await m.rpc_get_block(h) == data
            packed = m.packed_cache.get(h)
            assert packed is not None
            assert DataBlock.unpack(bytes(packed)).plain_bytes() == data
            # byte identity: the deterministic re-encode == disk files
            ms = by_id(systems, managers)
            framed = await m.feeder.encode_put(bytes(packed))
            for idx, node in enumerate(placement):
                assert bytes(framed[idx]) == \
                    ms[node].read_local_shard(h, idx)
            # warm rebuild: zero gather RPCs, byte-identical shard
            idx = placement.index(m.system.id)
            orig = m.read_local_shard(h, idx)
            real_gather = m._gather_parts

            async def no_gather(*a, **kw):
                raise AssertionError("gather used on a warm rebuild")

            m._gather_parts = no_gather
            try:
                rebuilt = await m.resync._rebuild_shard(h, idx)
            finally:
                m._gather_parts = real_gather
            assert rebuilt == orig
            assert m.resync.rebuild_tier_hits == 1
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_scrub_repair_rides_packed_tier(tmp_path):
    """A stripe repair whose packed bytes sit in the tier localizes
    from the CACHE — scrub_cache_hits == 1 — re-verifies them, and
    still pushes a byte-correct shard back to the forged holder; a
    re-scrub is clean."""
    async def main():
        from garage_tpu.block import ScrubWorker

        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data, h, leader, placement = _find_owned_placed(
                systems, managers, need_leader=True)
            await managers[0].rpc_put_block(h, data, compress=False,
                                            cacheable=False)
            await _wait_shards_placed(systems, managers, h, placement)
            assert await leader.rpc_get_block(h) == data  # packed warm
            assert leader.packed_cache.get(h) is not None
            # forge data shard 1: valid framing, wrong bytes
            victim = by_id(systems, managers)[placement[1]]
            raw = victim.read_local_shard(h, 1)
            payload, packed_len = unpack_shard(raw)
            forged = bytes(b ^ 0xFF for b in payload[:64]) + payload[64:]
            victim.write_local_shard(h, 1, pack_shard(forged, packed_len))
            sw = ScrubWorker(leader)
            assert await sw.scrub_batch([h]) == 1
            assert sw.scrub_cache_lookups == 1
            assert sw.scrub_cache_hits == 1
            fixed, _ = unpack_shard(victim.read_local_shard(h, 1))
            assert fixed == payload
            assert await sw.scrub_batch([h]) == 0
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- ISSUE 18: hint-driven prefetch --------------------------------------


def test_owner_prefetches_on_hint(tmp_path):
    """An inbound same-zone hint for an OWNED, uncached block queues a
    governor-paced background decode at the owner, so the first herd
    probe-hits instead of minting a lease. A re-hint of a held block
    is a cheap skip, non-owners never act, and prefetch_inflight=0
    turns the lane off entirely."""
    async def main():
        net, systems, managers, tasks = await tier_cluster(tmp_path)
        try:
            data, h, owner_m, placement = _find_owned_placed(
                systems, managers)
            await managers[0].rpc_put_block(h, data, compress=False,
                                            cacheable=False)
            await _wait_shards_placed(systems, managers, h, placement)
            owner_m.cache_tier.prefetch_tranquility = 0.02  # paced lane
            peer = next(s.id for s in systems
                        if s.id != owner_m.system.id)
            assert owner_m.cache.get(h) is None
            owner_m.cache_tier.note_hints(peer, [h])
            await wait_for(lambda: owner_m.cache.get(h) is not None,
                           what="hint-driven prefetch fill")
            assert owner_m.cache_tier.prefetched == 1
            # the herd now probe-hits: zero additional decodes anywhere
            reader = next(m for m in managers
                          if m.cache_tier.owner_of(h) is not None)
            d0 = sum(m.metrics["store_reads"] for m in managers)
            assert await reader.rpc_get_block(h) == data
            assert sum(m.metrics["store_reads"] for m in managers) == d0
            # a re-hint of a held block is a skip, not a decode
            owner_m.cache_tier.note_hints(peer, [h])
            assert owner_m.cache_tier.prefetch_skips >= 1
            # non-owners never act on the same hint
            other = next(m for m in managers
                         if not m.cache_tier.local_owner(h))
            other.cache_tier.note_hints(peer, [h])
            assert len(other.cache_tier._prefetch_q) == 0
            # and the knob turns the lane off entirely
            owner_m.cache_tier.prefetch_inflight = 0
            owner_m.cache.discard(h)
            owner_m.cache_tier.note_hints(peer, [h])
            await asyncio.sleep(0.1)
            assert owner_m.cache.get(h) is None
        finally:
            await stop_all(systems, tasks)

    run(main())


# ---- shm forward ring ----------------------------------------------------


def test_shm_ring_roundtrip_reuse_and_validation(tmp_path):
    from garage_tpu.gateway.shm import ShmReader, ShmRing, ring_path

    p = ring_path(str(tmp_path), 0)
    ring = ShmRing(p, 1 << 20, lease_s=30.0)
    payload = os.urandom(200_000)
    h = b"\x01" * 32
    ref = ring.publish(h, payload)
    assert ref is not None
    rd = ShmReader()
    mv = rd.get(ref, h)
    assert isinstance(mv, memoryview) and bytes(mv) == payload
    # a hot hash is written once per lease, not once per forward
    assert ring.publish(h, payload) == ref and ring.reused == 1
    # wrong hash / stale seq / truncated refs all refuse
    assert rd.get(ref, b"\x02" * 32) is None
    assert rd.get({**ref, "seq": ref["seq"] + 1}, h) is None
    assert rd.get({**ref, "off": ring.size * 2}, h) is None
    assert rd.get({"path": p}, h) is None


def test_shm_ring_lease_blocks_overwrite_then_expires(tmp_path):
    from garage_tpu.gateway.shm import ShmReader, ShmRing, ring_path

    p = ring_path(str(tmp_path), 1)
    ring = ShmRing(p, 1 << 19, lease_s=0.2)  # 512 KiB
    rd = ShmReader()
    refs = [(os.urandom(32), os.urandom(100_000)) for _ in range(8)]
    out = [ring.publish(h, b) for h, b in refs]
    # the ring cannot host 800 KB of leased slots in 512 KiB: some
    # publishes fall back instead of overwriting a leased slot
    assert any(r is None for r in out)
    assert ring.fallbacks > 0
    # every reference that WAS handed out still validates
    for (h, b), r in zip(refs, out):
        if r is not None:
            assert bytes(rd.get(r, h)) == b
    time.sleep(0.25)  # leases expire -> space frees
    assert ring.publish(b"\x07" * 32, os.urandom(100_000)) is not None


def test_shm_oversize_payload_falls_back(tmp_path):
    from garage_tpu.gateway.shm import ShmRing, ring_path

    ring = ShmRing(ring_path(str(tmp_path), 2), 1 << 16, lease_s=1.0)
    assert ring.publish(b"\x01" * 32, os.urandom(1 << 17)) is None


def test_shm_crash_respawn_preserves_leased_slots(tmp_path):
    """A CRASH-respawned owner (no clean close) reopens the same inode
    WITHOUT zeroing it — a sibling still streaming a leased slot out
    of its mapping must keep seeing the published bytes — and
    references minted by the previous incarnation fail the seq-epoch
    check instead of serving whatever now occupies the slot."""
    from garage_tpu.gateway.shm import ShmReader, ShmRing, ring_path

    p = ring_path(str(tmp_path), 3)
    ring1 = ShmRing(p, 1 << 18, lease_s=30.0)
    h1 = b"\x01" * 32
    data1 = os.urandom(70_000)
    old_ref = ring1.publish(h1, data1)
    rd = ShmReader()
    mv_in_flight = rd.get(old_ref, h1)  # a slow client mid-stream
    assert mv_in_flight is not None
    # crash: NO close() — the inode (and its contents) survive
    ring2 = ShmRing(p, 1 << 18, lease_s=30.0)  # the respawn
    # the in-flight view still reads the original bytes (no memset)
    assert bytes(mv_in_flight) == data1
    h2 = b"\x02" * 32
    data2 = os.urandom(70_000)
    new_ref = ring2.publish(h2, data2)
    # same inode: the reader's EXISTING mapping serves the new slot
    assert bytes(rd.get(new_ref, h2)) == data2
    # the old incarnation's reference refuses (fresh seq epoch)
    assert rd.get(old_ref, h1) is None


def test_shm_clean_close_unlinks_and_reader_remaps(tmp_path):
    """Clean shutdown unlinks the ring (ephemeral clusters must not
    accumulate resident tmpfs files); a reader still holding the OLD
    inode's mapping detects the recreate and remaps on its next
    validation failure."""
    from garage_tpu.gateway.shm import ShmReader, ShmRing, ring_path

    p = ring_path(str(tmp_path), 4)
    ring1 = ShmRing(p, 1 << 18, lease_s=30.0)
    h1 = b"\x01" * 32
    ref1 = ring1.publish(h1, os.urandom(60_000))
    rd = ShmReader()
    assert rd.get(ref1, h1) is not None  # reader mapped inode #1
    ring1.close()
    assert not os.path.exists(p)  # unlinked on clean close
    ring2 = ShmRing(p, 1 << 18, lease_s=30.0)  # fresh inode
    h2 = b"\x02" * 32
    data2 = os.urandom(60_000)
    ref2 = ring2.publish(h2, data2)
    # the cached old-inode map fails validation -> remap -> serve
    assert bytes(rd.get(ref2, h2)) == data2
    ring2.close()


# ---- GL03: the new cross-node seam --------------------------------------


def _lint(src: str, rel_path: str):
    from garage_tpu.analysis import analyze_source, default_rules

    ctx = analyze_source(textwrap.dedent(src), default_rules(),
                         rel_path=rel_path)
    return sorted({v.rule for v in ctx.violations if v.active})


def test_gl03_fires_on_tier_probe_in_ssec_scope():
    assert _lint("""
        async def stream(mgr, h, sse_key):
            tier = mgr.cache_tier
            return await tier.probe(owner_of(h), h)
    """, "garage_tpu/api/s3/fake_tier.py") == ["GL03"]


def test_gl03_quiet_with_cacheable_on_tier_probe():
    assert _lint("""
        async def stream(mgr, h, sse_key):
            tier = mgr.cache_tier
            return await tier.probe(owner_of(h), h,
                                    cacheable=sse_key is None)
    """, "garage_tpu/api/s3/fake_tier.py") == []


def test_gl03_fires_on_tainted_payload_into_tier_insert():
    assert _lint("""
        def warm(mgr, owner, h, sse_payload):
            mgr.cache_tier.insert_at(owner, h, sse_payload)
    """, "garage_tpu/block/fake_tier.py") == ["GL03"]


def test_gl03_quiet_on_untainted_tier_insert():
    assert _lint("""
        def warm(mgr, owner, h, payload):
            mgr.cache_tier.insert_at(owner, h, payload)
    """, "garage_tpu/block/fake_tier.py") == []


def test_gl03_fires_on_probe_full_and_probe_packed_in_ssec_scope():
    """ISSUE 18 extends the seam: the dual-segment probe and the
    packed-segment probe are sinks in SSE-tainted scope too."""
    assert _lint("""
        async def stream(mgr, h, sse_key):
            tier = mgr.cache_tier
            return await tier.probe_full(owner_of(h), h)
    """, "garage_tpu/api/s3/fake_tier.py") == ["GL03"]
    # probe_packed has NO cacheable escape hatch on purpose: the
    # packed segment must be structurally unreachable from SSE scope
    assert _lint("""
        async def rebuild(mgr, h, sse_key):
            tier = mgr.cache_tier
            return await tier.probe_packed(owner_of(h), h)
    """, "garage_tpu/block/fake_tier.py") == ["GL03"]


def test_gl03_quiet_with_cacheable_on_probe_full():
    assert _lint("""
        async def stream(mgr, h, sse_key):
            tier = mgr.cache_tier
            res = await tier.probe_full(owner_of(h), h,
                                        cacheable=sse_key is None)
            return res.plain
    """, "garage_tpu/api/s3/fake_tier.py") == []
