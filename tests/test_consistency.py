"""Consistency harness: property-based CRDT checks + randomized cluster
convergence (SURVEY.md §5.2 — the in-process stand-in for the
reference's external Jepsen rig, script/jepsen.garage/).

Three layers:
  1. randomized algebraic laws (merge commutative / associative /
     idempotent) over generated CRDT values, including the K2V DVVS;
  2. a randomized multi-writer cluster run: concurrent writers hit
     random nodes, partitions heal via anti-entropy, and every node's
     table stores must converge byte-for-byte;
  3. a no-lost-acknowledged-write check: after quiescence every acked
     object PUT is visible at every node or superseded by a later
     version of the same key.
"""

import asyncio
import os
import random


def _seed(default: int) -> int:
    """Fixed seeds for CI determinism; GARAGE_TPU_CONSISTENCY_SEED
    overrides them all so a soak loop (scripts/soak_consistency.sh) can
    sweep the randomized cluster scenarios across many interleavings."""
    return int(os.environ.get("GARAGE_TPU_CONSISTENCY_SEED", default))

from garage_tpu.model.k2v import DvvsEntry, K2VItem
from garage_tpu.model.s3 import (Object, ObjectVersion, ObjectVersionData,
                                 ObjectVersionMeta, ObjectVersionState)
from garage_tpu.utils.crdt import Bool, CrdtMap, Deletable, Lww, LwwMap
from garage_tpu.utils.data import gen_uuid

from test_model import make_garage_cluster, stop_all  # noqa: F401


def run(coro, timeout=180.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# ---------------------------------------------------------------------------
# 1. algebraic laws over generated values
# ---------------------------------------------------------------------------


def _gen_lww(rng):
    return Lww(rng.randrange(0, 1000), rng.randrange(0, 100))


def _gen_lwwmap(rng):
    items = {}
    for _ in range(rng.randrange(0, 5)):
        items[f"k{rng.randrange(0, 4)}"] = _gen_lww(rng)
    return LwwMap(items)


def _gen_bool(rng):
    return Bool(rng.random() < 0.5)


def _gen_deletable(rng):
    if rng.random() < 0.3:
        return Deletable.deleted()
    return Deletable.present(_gen_lww(rng))


def _dvvs_history(rng):
    """A node's writer history: strictly increasing timestamps. DVVS
    merge is only commutative over PROTOCOL-REACHABLE states — replicas
    of one node's entry are views (discard cut + suffix) of the same
    single-writer history, never arbitrary value sets."""
    ts, t = [], 0
    for _ in range(rng.randrange(1, 5)):
        t += rng.randrange(1, 10)
        ts.append((t, bytes([rng.randrange(0, 256)])
                   if rng.random() < 0.8 else None))
    return ts


def _dvvs_view(rng, history):
    """A replica's view: everything up to a seen-point, with a discard
    cut at or below it."""
    seen = rng.randrange(0, len(history) + 1)
    cut = rng.choice([0] + [t for t, _ in history[:seen]])
    e = DvvsEntry(cut, [(t, v) for t, v in history[:seen] if t > cut])
    return e


_K2V_HISTORIES = {}


def _gen_dvvs(rng):
    hist = _K2V_HISTORIES.setdefault("solo", [])
    if not hist:
        hist.extend(_dvvs_history(random.Random(5)))
    return _dvvs_view(rng, hist)


def _gen_k2v(rng):
    item = K2VItem(b"\x00" * 32, "p", "s")
    for node in range(rng.randrange(1, 4)):
        hist = _K2V_HISTORIES.setdefault(node, [])
        if not hist:
            hist.extend(_dvvs_history(random.Random(100 + node)))
        item.items[node] = _dvvs_view(rng, hist)
    return item


def _canon(v):
    """Canonical comparable form for merge results."""
    if isinstance(v, K2VItem) or isinstance(v, DvvsEntry):
        return v.pack()
    if hasattr(v, "pack"):
        return v.pack()
    return v


def test_crdt_merge_laws_random():
    gens = [_gen_lww, _gen_lwwmap, _gen_bool, _gen_deletable, _gen_dvvs,
            _gen_k2v]
    rng = random.Random(_seed(1234))
    for trial in range(300):
        gen = gens[trial % len(gens)]
        a, b, c = gen(rng), gen(rng), gen(rng)
        ab = a.merge(b)
        ba = b.merge(a)
        assert _canon(ab) == _canon(ba), (gen.__name__, trial)
        abc1 = a.merge(b).merge(c)
        abc2 = a.merge(b.merge(c))
        assert _canon(abc1) == _canon(abc2), (gen.__name__, trial)
        assert _canon(ab.merge(b)) == _canon(ab), (gen.__name__, trial)
        assert _canon(a.merge(a)) == _canon(a), (gen.__name__, trial)


def test_crdt_map_merge_laws_random():
    rng = random.Random(_seed(99))
    for trial in range(100):
        def gen():
            m = CrdtMap()
            for _ in range(rng.randrange(0, 4)):
                m = m.put(rng.randrange(0, 3), _gen_bool(rng))
            return m

        def dump(m):
            return [(k, _canon(v)) for k, v in m.items()]

        a, b, c = gen(), gen(), gen()
        assert dump(a.merge(b)) == dump(b.merge(a))
        assert dump(a.merge(b).merge(c)) == dump(a.merge(b.merge(c)))


# ---------------------------------------------------------------------------
# 2+3. randomized multi-writer cluster convergence
# ---------------------------------------------------------------------------


def _store_dump(table):
    return sorted(table.data.store.iter())


def test_cluster_random_writes_converge(tmp_path):
    async def main():
        rng = random.Random(_seed(4242))
        net, garages, tasks = await make_garage_cluster(tmp_path, n=3, rf=3)
        try:
            bucket_id = gen_uuid()
            keys = [f"obj-{i}" for i in range(8)]
            acked = []  # (key, uuid, timestamp)

            async def writer(wid):
                for _ in range(12):
                    g = garages[rng.randrange(3)]
                    key = keys[rng.randrange(len(keys))]
                    uuid = gen_uuid()
                    ts = rng.randrange(1, 1 << 40)
                    meta = ObjectVersionMeta({}, 3, f"w{wid}")
                    ov = ObjectVersion(
                        uuid, ts, ObjectVersionState.complete(
                            ObjectVersionData.inline(meta, b"xyz")))
                    await g.object_table.insert(
                        Object(bucket_id, key, [ov]))
                    acked.append((key, uuid, ts))
                    await asyncio.sleep(0)

            await asyncio.gather(*[writer(i) for i in range(4)])

            # quiesce: force anti-entropy on every node until stores
            # are byte-identical
            for _ in range(20):
                await asyncio.sleep(0.2)  # let merkle workers drain
                for g in garages:
                    await g.object_table.syncer.sync_all_partitions()
                dumps = [_store_dump(g.object_table) for g in garages]
                if dumps[0] == dumps[1] == dumps[2]:
                    break
            assert dumps[0] == dumps[1] == dumps[2]

            # no lost acknowledged write: older completed versions are
            # legitimately dropped once a newer complete one merges in
            # (ref object merge semantics), so the invariant is that on
            # EVERY node each key's surviving winner is the maximal
            # acked write for that key by (timestamp, uuid) order
            expect = {}
            for key, uuid, ts in acked:
                cur = expect.get(key)
                if cur is None or (ts, uuid) > cur:
                    expect[key] = (ts, uuid)
            for g in garages:
                for key, (ts, uuid) in expect.items():
                    obj = await g.object_table.get(bucket_id, key.encode())
                    assert obj is not None, key
                    win = max(((v.timestamp, v.uuid)
                               for v in obj.versions))
                    assert win == (ts, uuid), (key, win, ts)
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_k2v_random_causal_histories_converge(tmp_path):
    """Random interleaved K2V writers: some read-then-write with the
    causality token (those must supersede what they saw), some blind.
    After routing + table convergence, all nodes agree and every blind
    write is either visible or discarded by a write whose context
    covered it."""
    async def main():
        from garage_tpu.model.k2v import partition_pk

        rng = random.Random(_seed(777))
        net, garages, tasks = await make_garage_cluster(tmp_path, n=3, rf=3)
        try:
            bucket_id = gen_uuid()

            async def actor(aid):
                for i in range(10):
                    g = garages[rng.randrange(3)]
                    if rng.random() < 0.5:
                        item = await g.k2v_item_table.get(
                            partition_pk(bucket_id, "p"), b"k")
                        ct = (item.causal_context()
                              if item is not None else None)
                        await g.k2v_rpc.insert(
                            bucket_id, "p", "k", ct,
                            f"a{aid}i{i}".encode())
                    else:
                        await g.k2v_rpc.insert(
                            bucket_id, "p", "k", None,
                            f"blind{aid}i{i}".encode())
                    await asyncio.sleep(0)

            await asyncio.gather(*[actor(i) for i in range(3)])
            for _ in range(20):
                await asyncio.sleep(0.2)  # let merkle workers drain
                for g in garages:
                    await g.k2v_item_table.syncer.sync_all_partitions()
                dumps = [_store_dump(g.k2v_item_table) for g in garages]
                if dumps[0] == dumps[1] == dumps[2]:
                    break
            assert dumps[0] == dumps[1] == dumps[2]
            item = await garages[0].k2v_item_table.get(
                partition_pk(bucket_id, "p"), b"k")
            assert item is not None
            # the DVVS must hold at least one live value and no more
            # writers' values than actors could have raced
            vals = item.live_values()
            assert 1 <= len(vals) <= 30
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_erasure_cluster_partition_heal_degraded_reads(tmp_path):
    """Erasure(4,2) mode under churn: concurrent PUTs while random
    links are cut, then heal + resync; every acked block must be
    readable from EVERY node, including with two nodes stopped
    (degraded gather-any-k reads). Extends the §5.2 harness to the
    codec the reference lacks."""
    async def main():
        from garage_tpu.utils.data import blake3sum

        rng = random.Random(_seed(77))
        net, garages, tasks = await make_garage_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2))
        try:
            ids = [g.system.id for g in garages]
            blocks = {}

            async def writer(wid):
                for i in range(6):
                    data = bytes([wid]) * (4096 + 257 * i)
                    h = blake3sum(data)
                    g = garages[rng.randrange(6)]
                    try:
                        await g.block_manager.rpc_put_block(h, data)
                        # register a block ref like the real PUT path
                        # does — resync only repairs rc-needed blocks
                        from garage_tpu.model.s3 import BlockRef

                        await g.block_ref_table.insert(
                            BlockRef.new(h, gen_uuid()))
                        blocks[h] = data  # acked
                    except Exception:
                        pass  # quorum failure under partition: not acked
                    await asyncio.sleep(0)

            async def nemesis():
                for _ in range(6):
                    a, b = rng.sample(ids, 2)
                    net.partition(a, b)
                    await asyncio.sleep(0.05)
                    net.heal(a, b)
                    await asyncio.sleep(0.02)

            await asyncio.gather(*[writer(w) for w in range(3)], nemesis())
            assert blocks, "no write achieved quorum"

            # a connect attempted DURING a partition backs off ~60 s
            # (peering retry policy, tested elsewhere); reconnect
            # directly so this test measures repair, not backoff
            for g in garages:
                for o in garages:
                    if o.system.id != g.system.id \
                            and o.system.id not in g.netapp.conns:
                        try:
                            await g.netapp.try_connect(
                                o.netapp.public_addr, o.system.id)
                        except Exception:
                            pass

            # resync until FULL health: every node holds its assigned
            # shard (reads succeeding is weaker — any 4 shards satisfy
            # a read while a quorum-5 write's missing 6th shard would
            # still sink the 2-nodes-down phase below)
            full = False
            for _ in range(40):
                # block_ref rows ack at write-quorum 2 of the 6-wide
                # placement; anti-entropy must spread them before rc
                # marks the remaining shard holders as "needed".
                # Targeted: sync only the partitions our blocks live in
                # (a full 256-partition round is ~8k RPCs on this box)
                from garage_tpu.rpc.layout.version import partition_of

                parts = {partition_of(h) for h in blocks}
                for g in garages:
                    for p in parts:
                        for other in garages:
                            if other.system.id == g.system.id:
                                continue
                            try:
                                await g.block_ref_table.syncer \
                                    .sync_partition_with(p, other.system.id)
                            except Exception:
                                pass
                for g in garages:
                    for h in blocks:
                        try:
                            await g.block_manager.resync.resync_block(h)
                        except Exception:
                            pass
                full = all(
                    not g.block_manager.is_shard_needed(h)
                    for g in garages for h in blocks)
                if full:
                    break
                await asyncio.sleep(0.1)
            assert full, "shard placement incomplete after heal+resync"
            for g in garages:
                for h, data in blocks.items():
                    assert await g.block_manager.rpc_get_block(h) == data

            # degraded: stop two nodes AND cut their links (Garage.stop
            # alone leaves them in LocalNetwork, and survivors would
            # reconnect and fetch shards from the "dead" nodes);
            # any k=4 of 6 shards must reconstruct
            for g in garages[4:]:
                await g.stop()
                for other in garages[:4]:
                    net.partition(g.system.id, other.system.id)
            for g in garages[:4]:
                for h, data in blocks.items():
                    got = await g.block_manager.rpc_get_block(h)
                    assert got == data
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_layout_transition_write_storm(tmp_path):
    """Layout-transition chaos (the subtlest machinery in the system —
    multi-write-sets + ack lock + tracker GC, semantics of
    ref src/rpc/layout/manager.rs:338-381): four writers storm the
    object table while node 3 is ADDED to and node 1 REMOVED from the
    layout, applied mid-storm. Invariants after quiescence:
      * no acked write lost — every key's winner on every v2 storage
        node is the maximal acked (timestamp, uuid) for that key;
      * the three v2 storage nodes' stores are byte-identical;
      * the superseded layout v1 is GC'd out of `versions` (archived
        to old_versions) once every current node sync-acks v2."""
    async def main():
        from test_model import wait_until

        rng = random.Random(_seed(90210))
        net, garages, tasks = await make_garage_cluster(
            tmp_path, n=4, rf=3, storage=[0, 1, 2])
        try:
            bucket_id = gen_uuid()
            keys = [f"obj-{i}" for i in range(10)]
            acked = []
            stop = asyncio.Event()

            async def writer(wid):
                while not stop.is_set():
                    g = garages[rng.randrange(4)]
                    key = keys[rng.randrange(len(keys))]
                    uuid = gen_uuid()
                    ts = rng.randrange(1, 1 << 40)
                    meta = ObjectVersionMeta({}, 3, f"w{wid}")
                    ov = ObjectVersion(
                        uuid, ts, ObjectVersionState.complete(
                            ObjectVersionData.inline(meta, b"xyz")))
                    await g.object_table.insert(
                        Object(bucket_id, key, [ov]))
                    acked.append((key, uuid, ts))
                    await asyncio.sleep(rng.random() * 0.01)

            async def storm_until(n: int, deadline_s: float = 30.0):
                # condition-based: a fixed window on a loaded box can
                # ack too few writes to exercise the invariants below
                deadline = asyncio.get_event_loop().time() + deadline_s
                while len(acked) < n \
                        and asyncio.get_event_loop().time() < deadline:
                    await asyncio.sleep(0.05)

            wtasks = [asyncio.create_task(writer(i)) for i in range(4)]
            await storm_until(25)  # storm against layout v1 first

            # mid-storm transition: + node3, - node1, applied on node 0
            from garage_tpu.rpc.layout import NodeRole

            lm = garages[0].system.layout_manager
            lm.history.stage_role(garages[3].system.id,
                                  NodeRole(zone="z1", capacity=1 << 30))
            lm.history.stage_role(garages[1].system.id, None)
            lm.apply_staged(None)
            # keep storming THROUGH the transition while gossip spreads
            await storm_until(60)
            stop.set()
            await asyncio.gather(*wtasks)
            assert len(acked) > 50

            assert await wait_until(lambda: all(
                g.system.layout_manager.history.current().version == 2
                for g in garages))

            # quiesce: sync rounds everywhere (removed node 1 offloads
            # its partitions) until the v2 storage nodes are identical
            cur = [garages[i] for i in (0, 2, 3)]
            for _ in range(30):
                await asyncio.sleep(0.2)
                for g in garages:
                    await g.object_table.syncer.sync_all_partitions()
                dumps = [_store_dump(g.object_table) for g in cur]
                if dumps[0] == dumps[1] == dumps[2]:
                    break
            assert dumps[0] == dumps[1] == dumps[2]

            expect = {}
            for key, uuid, ts in acked:
                if key not in expect or (ts, uuid) > expect[key]:
                    expect[key] = (ts, uuid)
            for g in cur:
                for key, (ts, uuid) in expect.items():
                    obj = await g.object_table.get(bucket_id, key.encode())
                    assert obj is not None, key
                    win = max((v.timestamp, v.uuid) for v in obj.versions)
                    assert win == (ts, uuid), (key, win, ts)

            # tracker GC: keep running sync rounds (they advance
            # sync/sync_ack; gossip merges spread them) until v1 is out
            # of every node's live `versions`
            async def gc_done():
                for g in garages:
                    await g.object_table.syncer.sync_all_partitions()
                return all(
                    [v.version
                     for v in g.system.layout_manager.history.versions]
                    == [2] for g in garages)

            ok = False
            for _ in range(40):
                if await gc_done():
                    ok = True
                    break
                await asyncio.sleep(0.3)
            assert ok, [
                [v.version for v in g.system.layout_manager.history.versions]
                for g in garages]
            assert any(
                v.version == 1
                for v in garages[0].system.layout_manager.history.old_versions)
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_erasure_layout_transition_shard_migration(tmp_path):
    """Erasure(4,2) + layout transition under write load: 7 nodes, six
    storage + one gateway; mid-PUT-storm the gateway is ADDED to the
    layout and a storage node REMOVED (one apply — the write path must
    satisfy a shard-placement quorum under EVERY live layout version,
    manager._write_shard_sets). After heal/resync, every acked block
    is fully placed on the v2 assignment and readable from every
    current node — including with the removed node stopped AND
    partitioned off (gather-any-k against the new placement only)."""
    async def main():
        from garage_tpu.model.s3 import BlockRef
        from garage_tpu.rpc.layout import NodeRole
        from garage_tpu.rpc.layout.version import partition_of
        from garage_tpu.utils.data import blake3sum

        rng = random.Random(_seed(4242))
        net, garages, tasks = await make_garage_cluster(
            tmp_path, n=7, rf=3, erasure=(4, 2), storage=list(range(6)))
        try:
            blocks = {}
            stop_w = asyncio.Event()

            async def writer(wid):
                i = 0
                while not stop_w.is_set():
                    data = bytes([wid, i & 0xFF]) * (3000 + 131 * (i % 7))
                    h = blake3sum(data)
                    g = garages[rng.randrange(7)]
                    try:
                        await g.block_manager.rpc_put_block(h, data)
                        await g.block_ref_table.insert(
                            BlockRef.new(h, gen_uuid()))
                        blocks[h] = data  # acked
                    except Exception:
                        pass  # transition window quorum miss: not acked
                    i += 1
                    await asyncio.sleep(rng.random() * 0.01)

            async def storm_until(n: int, deadline_s: float = 30.0):
                # condition-based, not time-based: on a loaded co-tenant
                # box a fixed window can ack arbitrarily few writes
                # (soak seeds 135/136 landed 3 in 1.2 s), which starves
                # the assertions below of material rather than proving
                # anything about the product
                deadline = asyncio.get_event_loop().time() + deadline_s
                while len(blocks) < n \
                        and asyncio.get_event_loop().time() < deadline:
                    await asyncio.sleep(0.05)

            wtasks = [asyncio.create_task(writer(w)) for w in range(3)]
            await storm_until(6)  # storm against layout v1

            lm = garages[0].system.layout_manager
            lm.history.stage_role(garages[6].system.id,
                                  NodeRole(zone="z1", capacity=1 << 30))
            lm.history.stage_role(garages[1].system.id, None)
            lm.apply_staged(None)
            await storm_until(12)  # storm THROUGH the transition
            stop_w.set()
            await asyncio.gather(*wtasks)
            assert len(blocks) > 10

            from test_model import wait_until

            assert await wait_until(lambda: all(
                g.system.layout_manager.history.current().version == 2
                for g in garages))

            # spread block_ref rows (targeted partitions), then resync
            # until every CURRENT node holds its v2-assigned shard
            cur = [g for i, g in enumerate(garages) if i != 1]
            parts = {partition_of(h) for h in blocks}
            full = False
            for _ in range(40):
                for g in garages:
                    for p in parts:
                        for other in garages:
                            if other.system.id == g.system.id:
                                continue
                            try:
                                await g.block_ref_table.syncer \
                                    .sync_partition_with(p, other.system.id)
                            except Exception:
                                pass
                for g in cur:
                    for h in blocks:
                        try:
                            await g.block_manager.resync.resync_block(h)
                        except Exception:
                            pass
                full = all(
                    not g.block_manager.is_shard_needed(h)
                    for g in cur for h in blocks)
                if full:
                    break
                await asyncio.sleep(0.1)
            assert full, "v2 shard placement incomplete after transition"

            # the removed node goes away entirely; reads must survive on
            # the new placement alone
            await garages[1].stop()
            for g in cur:
                net.partition(garages[1].system.id, g.system.id)
            for g in cur:
                for h, data in blocks.items():
                    assert await g.block_manager.rpc_get_block(h) == data
        finally:
            await stop_all(garages, tasks)

    run(main())
