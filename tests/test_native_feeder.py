"""Native C kernels + DeviceFeeder batching + RAM semaphore.

The C BLAKE3 (garage_tpu/native) is validated against the vendored
official empty-input vector and cross-checked against the two other
independent implementations (pure-Python spec tree in ops/treehash.py,
lane-vectorized JAX) over the official test-vector input pattern
(byte i = i % 251) at every tree-shape edge case.
"""

from __future__ import annotations

import asyncio
import os

import numpy as np
import pytest

from garage_tpu import native
from garage_tpu.block.feeder import DeviceFeeder
from garage_tpu.block.manager import _ByteSemaphore
from garage_tpu.ops import gf256, rs, treehash

EMPTY_B3 = "af1349b9f5f9a1a6a0404dea36dcc9499bcb25c9adc112b7cc9a93cae41f3262"

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C toolchain for native kernels"
)

# every tree-shape class: sub-block, block edges, chunk edges, power-of-2
# chunk counts, odd tails, deep-carry counts
VECTOR_LENGTHS = (0, 1, 2, 63, 64, 65, 127, 128, 1023, 1024, 1025,
                  2048, 2049, 3072, 3073, 4096, 4097, 5120, 6144, 7168,
                  31744, 102400)


def official_input(n: int) -> bytes:
    return bytes(i % 251 for i in range(n))


def test_blake3_empty_vector():
    assert native.blake3(b"").hex() == EMPTY_B3
    assert treehash.blake3_py(b"").hex() == EMPTY_B3


def test_blake3_c_vs_python_vs_jax():
    msgs = [official_input(n) for n in VECTOR_LENGTHS]
    c_digs = [native.blake3(m) for m in msgs]
    py_digs = [treehash.blake3_py(m) for m in msgs]
    assert c_digs == py_digs
    jax_digs = treehash.blake3_many(msgs)
    assert c_digs == jax_digs


def test_blake3_many_matches_single():
    blobs = [os.urandom(n) for n in (0, 5, 1024, 4096, 70000)]
    assert native.blake3_many(blobs) == [native.blake3(b) for b in blobs]


def test_crc_native_matches_python():
    from garage_tpu.api.checksum import _crc32c_py, _crc64nvme_py

    for blob in (b"", b"a", b"123456789", os.urandom(7),
                 os.urandom(4096), os.urandom(100001)):
        assert native.crc32c(blob) == _crc32c_py(blob)
        assert native.crc64nvme(blob) == _crc64nvme_py(blob)
    # incremental == one-shot
    a, b = os.urandom(1000), os.urandom(777)
    assert native.crc32c(b, native.crc32c(a)) == native.crc32c(a + b)
    assert native.crc64nvme(b, native.crc64nvme(a)) == native.crc64nvme(a + b)
    # known-answer: CRC-32C("123456789") = 0xE3069283
    assert native.crc32c(b"123456789") == 0xE3069283


def test_gf_matmul_matches_numpy():
    rng = np.random.default_rng(3)
    mat = rng.integers(0, 256, (4, 10), dtype=np.uint8)
    x = rng.integers(0, 256, (10, 1000), dtype=np.uint8)
    assert np.array_equal(native.gf_matmul(mat, x), gf256.gf_matmul(mat, x))


def test_native_rs_roundtrip():
    """Native encode -> numpy decode from a mixed shard subset."""
    k, m = 4, 2
    data = os.urandom(4096 + 33)
    shards = rs.split_stripe(data, k)
    parity = native.gf_matmul(rs.parity_matrix(k, m), shards)
    full = np.concatenate([shards, parity])
    present = (0, 2, 4, 5)
    dec = rs.decode_np(k, m, present, full[list(present)])
    assert rs.join_stripe(dec, len(data)) == data


# ---------------------------------------------------------------------------
# DeviceFeeder
# ---------------------------------------------------------------------------


def run(coro):
    return asyncio.run(coro)


def test_feeder_hash_coalesces_and_matches():
    from garage_tpu.utils.data import blake3sum

    async def go():
        f = DeviceFeeder(mode="off")
        blobs = [os.urandom(n) for n in (10, 1024, 5000, 1 << 16)]
        digs = await asyncio.gather(*[f.hash(b) for b in blobs])
        assert list(digs) == [blake3sum(b) for b in blobs]
        # mode="off" + native loaded takes the inline fast path; without
        # native the items flow through the batch queue
        assert (f.stats["items"] + f.stats["inline_items"]) == len(blobs)
        await f.stop()

    run(go())


def test_feeder_encode_matches_codec():
    from garage_tpu.block.codec import ErasureCodec

    async def go():
        codec = ErasureCodec(4, 2, use_jax=False)
        f = DeviceFeeder(codec=codec, mode="off")
        blocks = [os.urandom(n) for n in (100, 4096, 10000)]
        outs = await asyncio.gather(*[f.encode(b) for b in blocks])
        for blk, parts in zip(blocks, outs):
            assert parts == codec.encode(blk)
        await f.stop()

    run(go())


def test_feeder_verify_blocks():
    from garage_tpu.utils.data import blake2sum, blake3sum

    async def go():
        f = DeviceFeeder(mode="off")
        good = os.urandom(2048)
        legacy = os.urandom(100)
        res = await f.verify_blocks([
            (blake3sum(good), good),
            (blake2sum(legacy), legacy),  # legacy-algo store stays valid
            (b"\x00" * 32, good),
        ])
        assert res == [True, True, False]
        await f.stop()

    run(go())


def test_feeder_error_propagates():
    async def go():
        from garage_tpu.block.codec import ErasureCodec

        f = DeviceFeeder(codec=ErasureCodec(4, 2, use_jax=False), mode="off")
        with pytest.raises(Exception):
            await f.encode(None)  # type: ignore[arg-type]
        # feeder survives the bad item
        assert (await f.hash(b"x")) == (await f.hash(b"x"))
        await f.stop()

    run(go())


# ---------------------------------------------------------------------------
# _ByteSemaphore
# ---------------------------------------------------------------------------


def test_byte_semaphore_limits_and_fifo():
    async def go():
        sem = _ByteSemaphore(100)
        order = []

        async def worker(name, n, hold):
            await sem.acquire(n)
            order.append(("in", name))
            await asyncio.sleep(hold)
            sem.release(n)
            order.append(("out", name))

        await asyncio.gather(
            worker("a", 60, 0.02), worker("b", 60, 0.01), worker("c", 50, 0.0)
        )
        assert sem.in_use == 0
        # b and c could not fit alongside a; FIFO: b enters before c
        assert order.index(("in", "a")) < order.index(("in", "b"))
        assert order.index(("in", "b")) < order.index(("in", "c"))

    run(go())


def test_byte_semaphore_oversize_alone():
    async def go():
        sem = _ByteSemaphore(10)
        await sem.acquire(50)  # oversize allowed when alone
        assert sem.in_use == 50
        blocked = asyncio.create_task(sem.acquire(1))
        await asyncio.sleep(0.01)
        assert not blocked.done()
        sem.release(50)
        await blocked
        sem.release(1)
        assert sem.in_use == 0

    run(go())


def test_byte_semaphore_cancel_waiter():
    async def go():
        sem = _ByteSemaphore(10)
        await sem.acquire(10)
        t = asyncio.create_task(sem.acquire(5))
        await asyncio.sleep(0.01)
        t.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t
        sem.release(10)
        assert sem.in_use == 0
        await sem.acquire(10)  # capacity fully recovered
        sem.release(10)

    run(go())


# ---------------------------------------------------------------------------
# rs_encode_packed: the fused PUT hot-path kernel (split+parity+crc+headers)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k,m", [(4, 2), (10, 4), (3, 1)])
@pytest.mark.parametrize("dlen", [0, 1, 7, 4096, (1 << 20) - 3, 1 << 20])
def test_rs_encode_packed_matches_reference(k, m, dlen):
    """The one-call C kernel must agree byte-for-byte with the composed
    reference path: split_stripe + encode_np + pack_shard."""
    from garage_tpu.block.manager import pack_shard, unpack_shard

    rng = np.random.default_rng(dlen % 97)
    prefix = b"\x00"
    data = rng.integers(0, 256, dlen, dtype=np.uint8).tobytes()
    block = prefix + data
    payloads = native.rs_encode_packed(data, k, m, rs.parity_matrix(k, m),
                                       prefix=prefix)
    shards = rs.split_stripe(block, k)
    parity = rs.encode_np(k, m, shards)
    assert len(payloads) == k + m
    for i, p in enumerate(payloads):
        got, plen = unpack_shard(bytes(p))
        assert plen == len(block)
        ref = shards[i] if i < k else parity[i - k]
        assert bytes(got) == ref.tobytes(), f"shard {i}"
        # and the composed python path produces the identical payload
        assert bytes(p) == pack_shard(ref.tobytes(), len(block))


def test_encode_put_backends_agree():
    """_do_encode_put host-native, host-numpy and device paths must emit
    interchangeable payloads (same shard bytes after unpack)."""
    from garage_tpu.block.codec import ErasureCodec
    from garage_tpu.block.manager import unpack_shard

    codec = ErasureCodec(4, 2, use_jax=False)
    f = DeviceFeeder(codec=codec, mode="off")
    rng = np.random.default_rng(3)
    items = [(b"\x00", rng.integers(0, 256, n, dtype=np.uint8).tobytes())
             for n in (100, 65536, (1 << 20) + 5)]
    a = f._do_encode_put(items, "host")   # native (or numpy fallback)
    b = f._do_encode_put(items, "device")  # codec.encode_batch path
    for pa, pb in zip(a, b):
        for sa, sb in zip(pa, pb):
            da, la = unpack_shard(bytes(sa))
            db, lb = unpack_shard(bytes(sb))
            assert la == lb and bytes(da) == bytes(db)


def test_put_get_roundtrip_native_erasure():
    """rpc_put_block -> rpc_get_block through the native encode fast
    path on a loopback cluster returns the original bytes."""
    import shutil
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    async def go():
        from garage_tpu.rpc import ReplicationMode
        from garage_tpu.utils.data import blake3sum

        tmp = tempfile.mkdtemp(prefix="gt_rt_")
        try:
            rm = ReplicationMode.parse(3, erasure="4,2")
            systems, managers, tasks = await bench._build_cluster(
                tmp, 6, rm, "off")
            data = os.urandom((1 << 20) + 17)
            h = blake3sum(data)
            await managers[0].rpc_put_block(h, data)
            assert managers[0].feeder.stats["inline_items"] >= 1 \
                or managers[0].feeder.stats["items"] >= 1
            back = await managers[1].rpc_get_block(h)
            assert back == data
            await bench._teardown(systems, managers, tasks)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    run(go())


def test_native_md5_fused():
    """Md5 accumulator: hashlib parity across chained fused/plain
    updates, and the fused call returns the block's blake3."""
    import hashlib

    import numpy as np

    from garage_tpu import native
    from garage_tpu.utils.data import blake3sum

    if not native.available():
        import pytest

        pytest.skip("no native toolchain")
    rng = np.random.default_rng(5)
    m = native.Md5()
    ref = hashlib.md5()
    assert m.fused
    for i, n in enumerate((0, 1, 63, 64, 65, 1024, 1025, 70_000,
                           (1 << 20) + 3)):
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        if i % 2:
            assert m.update_with_blake3(data) == blake3sum(data)
        else:
            m.update(data)
        ref.update(data)
        assert m.hexdigest() == ref.hexdigest(), n  # mid-stream digests


def test_native_md5_multilane_batch():
    """gt_md5_update_many / gt_b3_md5_many: hashlib parity for the
    8-way AVX2 multi-buffer path across lane counts 1..9, mixed
    lengths (lockstep + per-lane remainder), pre-seeded states, and a
    buffered (unaligned) state that must take the scalar fallback."""
    import hashlib

    import numpy as np
    import pytest

    from garage_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    rng = np.random.default_rng(11)
    lengths = [1 << 20, 300_000, 64, 63, 1_000_001, 128, 7, 65536, 4096]
    for nlanes in range(1, 10):
        items, refs = [], []
        for i in range(nlanes):
            d = rng.integers(0, 256, lengths[i], dtype=np.uint8).tobytes()
            m = native.Md5()
            r = hashlib.md5()
            if i % 3 == 0:  # pre-seeded state; i%3==1 leaves it fresh
                m.update(b"seed%d" % i)
                r.update(b"seed%d" % i)
            elif i % 3 == 2:  # unaligned buffered state -> scalar path
                m.update(b"x" * 7)
                r.update(b"x" * 7)
            items.append((m, d))
            refs.append((r, d))
        outs = native.b3_md5_many(items)
        for (m, d), (r, rd), o in zip(items, refs, outs):
            r.update(rd)
            assert m.hexdigest() == r.hexdigest(), (nlanes, len(d))
            assert o == native.blake3(d)
    # plain md5_update_many (no blake3) chains correctly across calls
    ms = [native.Md5() for _ in range(4)]
    rs = [hashlib.md5() for _ in range(4)]
    for _round in range(3):
        ds = [rng.integers(0, 256, 1 << 18, dtype=np.uint8).tobytes()
              for _ in range(4)]
        native.md5_update_many(list(zip(ms, ds)))
        for r, d in zip(rs, ds):
            r.update(d)
    assert [m.hexdigest() for m in ms] == [r.hexdigest() for r in rs]


def test_feeder_hash_md5_batches_and_device_route():
    """hash_with_md5: queued cross-request batching produces correct
    blake3 digests AND the right ETag-MD5 chains; mode="require"
    forces the device route (jax backend — cpu-pinned in tests), which
    batch-advances MD5 host-side while the content hash rides the
    device path and device_items counts it (the live-S3 proof metric)."""
    import hashlib

    from garage_tpu import native
    from garage_tpu.utils.data import blake3sum

    if not native.available():
        import pytest

        pytest.skip("no native toolchain")

    async def drive(mode):
        f = DeviceFeeder(mode=mode)
        if mode == "require":
            # bypass the real-device probe: the "device" backend in the
            # test env is the cpu-pinned jax path, which is exactly the
            # routing (not the silicon) this test covers
            f._device_ok = True
        f.active_streams = 4  # several "requests": engage lane gather
        accs = [native.Md5() for _ in range(4)]
        refs = [hashlib.md5() for _ in range(4)]
        blobs = [os.urandom(n) for n in (2048, 4096, 1024, 3000)]
        digs = await asyncio.gather(*[
            f.hash_with_md5(b, a) for b, a in zip(blobs, accs)])
        for r, b in zip(refs, blobs):
            r.update(b)
        assert list(digs) == [blake3sum(b) for b in blobs]
        assert [a.hexdigest() for a in accs] == \
            [r.hexdigest() for r in refs]
        stats = dict(f.stats)
        await f.stop()
        return stats

    stats = run(drive("off"))  # host route (queued when streams > 1)
    assert stats["items"] >= 1  # rode the queue, not the inline path
    stats = run(drive("require"))  # device route, cpu jax backend
    assert stats["device_items"] >= 4


def test_feeder_stop_mid_gather_window_resolves_waiters():
    """Cancelling the dispatcher while it sits in the hash_md5
    lane-gather wait must fail the already-dequeued items' futures
    (r5 review finding: they were stranded and PUT streams hung)."""
    from garage_tpu import native

    if not native.available():
        import pytest

        pytest.skip("no native toolchain")

    async def go():
        f = DeviceFeeder(mode="off")
        f.active_streams = 4  # force the gather window on first item
        acc = native.Md5()
        task = asyncio.create_task(
            f.hash_with_md5(os.urandom(2048), acc))
        # let the dispatcher dequeue the item and enter the window
        await asyncio.sleep(0.002)
        await f.stop()
        try:
            await asyncio.wait_for(task, 2.0)
        except RuntimeError as e:
            assert "feeder stopped" in str(e)
        except asyncio.TimeoutError:
            raise AssertionError("hash_with_md5 waiter stranded")

    run(go())


def test_feeder_hash_md5_device_failure_fallback_etag_correct():
    """A failing device hash must NOT have advanced the MD5 states
    before the host retry re-runs the op — the retry would otherwise
    double-count every byte into the ETag chain (r5 audit bug)."""
    import hashlib

    from garage_tpu import native
    from garage_tpu.utils.data import blake3sum

    if not native.available():
        import pytest

        pytest.skip("no native toolchain")

    async def go():
        calls = {"n": 0}

        class _BrokenBackend:
            """Staged device backend whose transfer stage always
            raises — the dead-tunnel shape, at the seam the pipelined
            device route actually goes through."""

            name = "jax"

            def stage(self, op, blobs):
                calls["n"] += 1
                raise RuntimeError("tunnel died")

        f = DeviceFeeder(mode="require", backend=_BrokenBackend())
        f._device_ok = True  # skip real probe; fake device above
        f.active_streams = 2
        accs = [native.Md5(), native.Md5()]
        refs = [hashlib.md5(), hashlib.md5()]
        blobs = [os.urandom(2048), os.urandom(4096)]
        digs = await asyncio.gather(*[
            f.hash_with_md5(b, a) for b, a in zip(blobs, accs)])
        for r, b in zip(refs, blobs):
            r.update(b)
        assert calls["n"] >= 1  # the device leg really ran and failed
        assert list(digs) == [blake3sum(b) for b in blobs]
        # the load-bearing assert: ETag chains advanced exactly once
        assert [a.hexdigest() for a in accs] == \
            [r.hexdigest() for r in refs]
        await f.stop()

    run(go())


def test_feeder_explore_trial_capped_and_adaptive():
    """Exploration of the losing backend is (a) capped to
    _TRIAL_MAX_ITEMS per trial — over a crawling tunnel a full
    production batch costs seconds, one timing sample doesn't — and
    (b) scheduled on an interval that widens with the measured rate
    gap, so a 500x-slower device is probed ~hourly, not every minute."""
    import time as _time

    from garage_tpu.block import feeder as fmod
    from garage_tpu.block.codec import ErasureCodec

    f = DeviceFeeder(codec=ErasureCodec(4, 2, use_jax=False), mode="auto")
    f._device_ok = True
    # seed calibration: host hugely winning (tunnel-shaped gap)
    f._record("encode", "host", 1 << 30, 1.0)     # 1 GB/s
    f._record("encode", "device", 1 << 21, 1.0)   # 2 MB/s
    f._last_explore["encode"] = _time.monotonic()

    # (b) adaptive interval: a 512x gap stretches the 60 s base cadence
    # to its 64x cap, so one base interval later no trial fires
    f._last_explore["encode"] = _time.monotonic() - 2 * fmod._EXPLORE_SECS
    assert f._explore_due("encode") is False
    # far past the stretched interval the trial fires
    f._last_explore["encode"] = (
        _time.monotonic() - 65 * fmod._EXPLORE_SECS)
    backend, trial = f._pick_backend("encode", 8 << 20, 8)
    assert (backend, trial) == ("device", True)

    # (a) the trial slice is capped: run a batch through _run_batch
    # with the device leg stubbed, and count what each backend saw
    seen = {"device": 0, "host": 0}
    real = f._do_op

    def spy(op, blobs, backend):
        seen[backend] += len(blobs)
        return real(op, blobs, "host")  # no real device in unit tests

    f._do_op = spy
    blk = os.urandom(1 << 20)  # 1 MiB items: the byte-aware cut engages

    class It:
        def __init__(self):
            self.op = "encode_put"
            self.data = (b"", blk)
            self.future = asyncio.get_event_loop().create_future()

    async def go():
        f._last_explore["encode"] = (
            _time.monotonic() - 65 * fmod._EXPLORE_SECS)
        items = [It() for _ in range(8)]
        f._run_batch(items)

    run(go())
    # trial grows past _TRIAL_MAX_ITEMS until _TRIAL_MAX_BYTES: 4x1 MiB
    want = fmod._TRIAL_MAX_BYTES >> 20
    assert seen["device"] == want
    assert seen["host"] == 8 - want

    # a DEAD device (0.0 recorded rate) is the widest gap: the adaptive
    # interval jumps straight to the 64x cap, not the 60 s base
    f._record("encode", "device", 0, 60.0)
    f._perf[("encode", "device")] = [0.0, 60.0]
    f._last_explore["encode"] = _time.monotonic() - 2 * fmod._EXPLORE_SECS
    assert f._explore_due("encode") is False


def test_probe_cache_poison_and_require_override():
    """A device that answers the probe but hangs on work poisons the
    shared probe cache with the `hung` marker (co-located feeders skip
    it for the TTL instead of each paying the watchdog timeout). A
    forced re-probe — mode="require"'s escape hatch — gets its own
    fresh result, but a probe-only success must NOT clear the hung
    marker: answering a probe is exactly what a hung-on-work device
    still does."""
    import json as _json

    from garage_tpu.block import feeder as fmod

    cache_path = fmod._probe_cache_path()
    old_result = fmod._probe_result
    old_disk = None
    try:
        with open(cache_path, "rb") as f:
            old_disk = f.read()
    except OSError:
        pass
    old_run = fmod.subprocess.run
    try:
        fmod.poison_probe_cache("calibration stuck >300s (test)")
        res = fmod.probe_device()
        assert res["ok"] is False
        assert "stuck" in res["error"]
        with open(cache_path) as f:
            on_disk = _json.load(f)
        assert on_disk["ok"] is False and on_disk["hung"] is True

        # forced re-probe that SUCCEEDS (stubbed subprocess: the device
        # answers): caller gets the positive result...
        class _R:
            returncode = 0
            stdout = "axon\n"
            stderr = ""

        fmod.subprocess.run = lambda *a, **k: _R()
        forced = fmod.probe_device(force=True)
        assert forced["ok"] is True and forced["platform"] == "axon"
        # ...but the shared verdict stays poisoned for auto feeders
        assert fmod.probe_device()["ok"] is False
        with open(cache_path) as f:
            assert _json.load(f)["hung"] is True
    finally:
        fmod.subprocess.run = old_run
        fmod._probe_result = old_result
        try:
            if old_disk is None:
                os.unlink(cache_path)
            else:
                with open(cache_path, "wb") as f:
                    f.write(old_disk)
        except OSError:
            pass


def test_parity_check_backends_agree_and_detect():
    """_do_parity_check host (native/numpy) and device (padded jax
    batch) agree, and both flag a stripe with one corrupted shard —
    mixed shard lengths in one batch exercise the zero-padding rule
    (linear code: zero rows encode to zero parity)."""
    from garage_tpu.block.codec import ErasureCodec

    codec = ErasureCodec(4, 2, use_jax=False)
    f = DeviceFeeder(codec=codec, mode="off")
    rng = np.random.default_rng(5)
    stripes = []
    for n in (1024, 65536, 100_000):
        block = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        stripes.append(codec.encode(block))
    s = list(stripes[1])
    s[2] = bytes(b ^ 1 for b in s[2])
    stripes[1] = s
    want = [True, False, True]
    assert f._do_parity_check(stripes, "host") == want
    assert f._do_parity_check(stripes, "device") == want

    async def go():
        assert await f.parity_check(stripes) == want
        await f.stop()

    run(go())
