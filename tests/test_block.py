"""Block store tests: local file store, replicate + erasure cluster
paths, refcounts, resync healing, scrub corruption detection."""

import asyncio
import os

from garage_tpu.block import (
    BlockManager,
    DataBlock,
    DataLayout,
    ErasureCodec,
    ReplicateCodec,
)
from garage_tpu.block.codec import shard_nodes_of
from garage_tpu.block.manager import pack_shard, unpack_shard
from garage_tpu.db import open_db
from garage_tpu.net import LocalNetwork, NetApp
from garage_tpu.rpc import ReplicationMode, System
from garage_tpu.rpc.layout import NodeRole
from garage_tpu.utils.data import blake2sum

try:
    import zstandard  # noqa: F401
    HAVE_ZSTD = True
except ModuleNotFoundError:
    HAVE_ZSTD = False  # block.py falls back to the zlib scheme

NETID = b"block-test"


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def make_block_cluster(tmp_path, n=3, rf=3, erasure=None,
                             cache_tier=False):
    # cache_tier=False by default: these suites pin the NODE-LOCAL
    # cache semantics (PR 3); the cluster tier's own routing semantics
    # live in tests/test_cache_tier.py
    net = LocalNetwork()
    systems, managers = [], []
    rm = (ReplicationMode.parse(rf, erasure="%d,%d" % erasure)
          if erasure else ReplicationMode.parse(rf))
    for i in range(n):
        app = NetApp(NETID)
        net.register(app)
        meta = str(tmp_path / f"node{i}")
        s = System(app, rm, meta, status_interval=0.2, ping_interval=0.2)
        systems.append(s)
    tasks = [asyncio.create_task(s.run()) for s in systems]
    for s in systems[1:]:
        await s.netapp.try_connect(systems[0].netapp.public_addr, systems[0].id)
        s.peering.add_peer(systems[0].netapp.public_addr, systems[0].id)
    deadline = asyncio.get_event_loop().time() + 15
    while asyncio.get_event_loop().time() < deadline:
        if all(len(s.netapp.conns) == n - 1 for s in systems):
            break
        await asyncio.sleep(0.05)
    lm = systems[0].layout_manager
    for s in systems:
        lm.history.stage_role(s.id, NodeRole(zone="z1", capacity=1 << 30))
    lm.apply_staged(None)
    while asyncio.get_event_loop().time() < deadline:
        if all(s.layout_manager.history.current().version == 1 for s in systems):
            break
        await asyncio.sleep(0.05)
    for i, s in enumerate(systems):
        db = open_db(str(tmp_path / f"node{i}" / "db"), engine="memory")
        lay = DataLayout.single(str(tmp_path / f"node{i}" / "data"))
        managers.append(BlockManager(s, db, lay, cache_tier=cache_tier))
    return net, systems, managers, tasks


async def stop_all(systems, tasks):
    for s in systems:
        await s.stop()
    for t in tasks:
        t.cancel()


# ---- pure local tests --------------------------------------------------


def test_datablock_roundtrip():
    data = b"hello world " * 100
    h = blake2sum(data)
    blk = DataBlock.compress(data)
    # compressible -> zstd (ref default); zlib scheme when the wheel
    # is absent (block.py fallback)
    assert blk.compression == (2 if HAVE_ZSTD else 1)
    blk.verify(h)
    assert blk.plain_bytes() == data
    rt = DataBlock.unpack(blk.pack())
    rt.verify(h)
    rnd = os.urandom(4096)
    blk2 = DataBlock.compress(rnd)
    assert blk2.compression == 0  # incompressible stays plain


def test_datablock_legacy_zlib_decodes():
    """Blocks written by pre-zstd builds (scheme byte 1) still decode."""
    import zlib

    data = b"legacy block payload " * 64
    h = blake2sum(data)
    legacy = DataBlock(1, zlib.compress(data, 1))
    legacy.verify(h)
    assert legacy.plain_bytes() == data
    assert legacy.file_suffix() == ".zlib"
    rt = DataBlock.unpack(legacy.pack())
    assert rt.plain_bytes() == data


def test_shard_file_roundtrip():
    raw = pack_shard(b"shard-bytes", 12345)
    data, plen = unpack_shard(raw)
    assert data == b"shard-bytes" and plen == 12345


def test_erasure_codec_roundtrip():
    codec = ErasureCodec(4, 2, use_jax=False)
    data = os.urandom(100_000)
    parts = codec.encode(data)
    assert len(parts) == 6
    # any 4 parts reconstruct
    for keep in [(0, 1, 2, 3), (1, 2, 4, 5), (0, 3, 4, 5), (2, 3, 4, 5)]:
        sub = {i: parts[i] for i in keep}
        assert codec.decode(sub, len(data)) == data
    # repair rebuilds exactly the lost shards
    lost = codec.repair_parts({i: parts[i] for i in (0, 2, 3, 5)}, (1, 4))
    assert lost[1] == parts[1] and lost[4] == parts[4]
    assert codec.parity_ok({i: parts[i] for i in range(6)}, blake2sum(data))


def test_erasure_codec_batch():
    codec = ErasureCodec(4, 2, use_jax=False)
    blocks = [os.urandom(n) for n in (1000, 5000, 3333)]
    outs = codec.encode_batch(blocks)
    for b, parts in zip(blocks, outs):
        assert parts == codec.encode(b)


def test_local_store_and_corruption(tmp_path):
    class _Sys:
        id = b"x" * 32
        meta_dir = str(tmp_path)
        replication = ReplicationMode.parse(1)

        class netapp:
            id = b"x" * 32

            @staticmethod
            def endpoint(path):
                class E:
                    def set_handler(self, h):
                        return self

                return E()

    db = open_db(str(tmp_path / "db"), engine="memory")
    lay = DataLayout.single(str(tmp_path / "data"))
    m = BlockManager.__new__(BlockManager)
    m.system = _Sys()
    m.db = db
    m.data_layout = lay
    m.compression = True
    m.fsync = False
    from garage_tpu.block.rc import BlockRc
    from garage_tpu.block.resync import BlockResyncManager

    m.rc = BlockRc(db)
    m.codec = ReplicateCodec(1)
    m.metrics = {"bytes_read": 0, "bytes_written": 0, "corruptions": 0,
                 "resync_sent": 0, "resync_recv": 0}
    m.resync = BlockResyncManager(m, db)

    data = b"some block content" * 50
    h = blake2sum(data)
    m.write_local(h, DataBlock.compress(data).pack())
    assert m.has_local(h)
    out = DataBlock.unpack(m.read_local(h))
    assert out.plain_bytes() == data

    # a pre-zstd .zlib file on disk still reads; a fresh write_local
    # replaces it with the zstd variant
    import zlib as _zlib
    from garage_tpu.block.block import BLOCK_SUFFIXES

    old = b"older zlib-era block" * 40
    h_old = blake2sum(old)
    os.makedirs(os.path.dirname(lay.block_path(h_old, ".zlib")), exist_ok=True)
    with open(lay.block_path(h_old, ".zlib"), "wb") as f:
        f.write(_zlib.compress(old, 1))
    assert DataBlock.unpack(m.read_local(h_old)).plain_bytes() == old
    m.write_local(h_old, DataBlock.compress(old).pack())
    if HAVE_ZSTD:
        assert m._find(h_old, [".zlib"]) is None  # old variant dropped
        assert m._find(h_old, [".zst"]) is not None
    else:
        # zlib fallback: the rewrite lands on the same-suffix path
        assert m._find(h_old, [".zlib"]) is not None

    # corrupt the file on disk: read detects, quarantines, queues resync
    path = m._find(h, BLOCK_SUFFIXES)
    with open(path, "r+b") as f:
        f.seek(5)
        f.write(b"\xff\xff\xff\xff")
    assert m.read_local(h) is None
    assert m.metrics["corruptions"] == 1
    assert os.path.exists(path + ".corrupted")
    assert m.resync.queue_len() == 1


def test_rc_lifecycle(tmp_path):
    from garage_tpu.block.rc import BlockRc

    db = open_db(str(tmp_path), engine="memory")
    rc = BlockRc(db, gc_delay=0.0)
    h = blake2sum(b"b")
    newly = []
    db.transaction(lambda tx: newly.append(rc.block_incref(tx, h)))
    assert newly == [True] and rc.is_needed(h)
    db.transaction(lambda tx: newly.append(rc.block_incref(tx, h)))
    assert rc.get(h) == ("present", 2)
    db.transaction(lambda tx: rc.block_decref(tx, h))
    assert rc.is_needed(h)
    dele = []
    db.transaction(lambda tx: dele.append(rc.block_decref(tx, h)))
    assert dele == [True] and rc.is_deletable_now(h)
    # recalculate from callbacks
    rc.register_calculator(lambda hh: 3 if hh == h else 0)
    assert rc.recalculate(h) == 3
    assert rc.get(h) == ("present", 3)


def test_shard_placement_distinct_and_stable():
    from garage_tpu.rpc.layout import LayoutHistory

    h = LayoutHistory.new(3)
    import hashlib

    nodes = [hashlib.sha256(b"n%d" % i).digest() for i in range(8)]
    for i, n in enumerate(nodes):
        h.stage_role(n, NodeRole(zone="z%d" % (i % 4), capacity=1 << 30))
    h.apply_staged_changes()
    v = h.current()
    bh = blake2sum(b"someblock")
    p = shard_nodes_of(v, bh, 6)
    assert len(p) == len(set(p)) == 6
    assert p == shard_nodes_of(v, bh, 6)  # deterministic
    assert p[:3] == v.nodes_of_hash(bh)  # prefix = the ring nodes


# ---- cluster tests -----------------------------------------------------


def test_replicate_put_get(tmp_path):
    async def main():
        net, systems, managers, tasks = await make_block_cluster(tmp_path)
        try:
            data = os.urandom(200_000)
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            # put returns at write quorum (2/3); the third write keeps
            # running in background by design — await convergence
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                if sum(1 for m in managers if m.has_local(h)) == 3:
                    break
                await asyncio.sleep(0.02)
            assert sum(1 for m in managers if m.has_local(h)) == 3
            got = await managers[2].rpc_get_block(h)
            assert got == data
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_replicate_get_survives_two_down(tmp_path):
    async def main():
        net, systems, managers, tasks = await make_block_cluster(tmp_path)
        try:
            data = b"important" * 1000
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            await systems[1].netapp.shutdown()
            await systems[2].netapp.shutdown()
            got = await managers[0].rpc_get_block(h)  # local read
            assert got == data
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_erasure_put_get_and_degraded_read(tmp_path):
    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2)
        )
        try:
            data = os.urandom(300_000)
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            # every node holds exactly one shard; the put acks at write
            # quorum (5/6) and the last shard lands in background
            held: list[int] = []
            for _ in range(100):
                parts = [m.local_parts(h) for m in managers]
                held = sorted(i for ps in parts for i in ps)
                if held == [0, 1, 2, 3, 4, 5]:
                    break
                await asyncio.sleep(0.02)
            assert held == [0, 1, 2, 3, 4, 5]
            got = await managers[3].rpc_get_block(h)
            assert got == data
            # kill two nodes -> still decodable from any 4 shards
            await systems[4].netapp.shutdown()
            await systems[5].netapp.shutdown()
            got = await managers[0].rpc_get_block(h)
            assert got == data
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_erasure_read_survives_forged_len_whose_decode_raises(tmp_path):
    """_get_erasure's packed_len fallthrough, exception-class coverage:
    a forged length can make the DECODE ITSELF blow up (packed_len=0 →
    join_stripe yields b"" → DataBlock.unpack raises IndexError), not
    just fail the content check. Forge the header on a MAJORITY of the
    gathered shards so the bad candidate is genuinely tried first (the
    length field sits outside the shard checksum, so local validation
    still passes) — the read must fall through to the minority
    candidate and recover the block."""
    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2)
        )
        try:
            data = os.urandom(150_000)
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            for _ in range(100):
                held = sorted(i for m in managers for i in m.local_parts(h))
                if held == [0, 1, 2, 3, 4, 5]:
                    break
                await asyncio.sleep(0.02)
            assert held == [0, 1, 2, 3, 4, 5]

            # the gather fetches systematic shards 0..3 first: forging
            # 0, 1 and 2 makes packed_len=0 the 3-vote majority against
            # shard 3's lone true header
            for idx in (0, 1, 2):
                victim = next(m for m in managers
                              if idx in m.local_parts(h))
                payload, _plen = unpack_shard(
                    victim.read_local_shard(h, idx))
                victim.write_local_shard(h, idx, pack_shard(payload, 0))
                # forged header still passes local validation
                assert victim.read_local_shard(h, idx) is not None

            reader = managers[1]
            reader.cache.clear()  # force the real gather+decode path
            decodes: list[int] = []
            orig_decode = reader.codec.decode

            def counting_decode(parts, plain_len):
                decodes.append(plain_len)
                return orig_decode(parts, plain_len)

            reader.codec.decode = counting_decode
            got = await reader.rpc_get_block(h)
            assert got == data
            # the majority (forged) candidate really was tried first
            assert decodes[0] == 0 and len(decodes) >= 2
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_erasure_resync_rebuilds_lost_shard(tmp_path):
    async def main():
        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2)
        )
        try:
            data = os.urandom(123_456)
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            # find the manager holding shard 2 and destroy its file
            victim = next(m for m in managers if 2 in m.local_parts(h))
            victim.delete_local(h)
            assert not victim.has_local(h)
            # mark needed + resync: shard is rebuilt from the other 5
            victim.db.transaction(lambda tx: victim.rc.block_incref(tx, h))
            await victim.resync.resync_block(h)
            assert victim.local_parts(h) == [2]
            # and the rebuilt shard is byte-identical: full read works
            got = await victim.rpc_get_block(h)
            assert got == data
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_replicate_resync_fetches_missing(tmp_path):
    async def main():
        net, systems, managers, tasks = await make_block_cluster(tmp_path)
        try:
            data = b"resync me" * 500
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            managers[1].delete_local(h)
            managers[1].db.transaction(
                lambda tx: managers[1].rc.block_incref(tx, h)
            )
            await managers[1].resync.resync_block(h)
            assert managers[1].has_local(h)
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_offload_unneeded_block(tmp_path):
    async def main():
        net, systems, managers, tasks = await make_block_cluster(tmp_path)
        try:
            data = b"temp" * 100
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            m0 = managers[0]
            m0.rc.gc_delay = 0.0
            # never incref'd -> absent rc; make it deletable-now via
            # incref+decref cycle
            m0.db.transaction(lambda tx: m0.rc.block_incref(tx, h))
            m0.db.transaction(lambda tx: m0.rc.block_decref(tx, h))
            assert m0.rc.is_deletable_now(h)
            await m0.resync.resync_block(h)
            assert not m0.has_local(h)
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_deep_scrub_detects_and_repairs_forged_shard(tmp_path):
    """Cross-shard deep scrub (ref parity: src/block/repair.rs:169-528
    whole-block rehash — the erasure-mode equivalent): a shard that is
    internally consistent (valid pack_shard checksum) but holds the
    WRONG bytes passes every local check; the stripe's scrub leader
    gathers all shards, the parity detect flags the stripe, and
    localization + repair push the corrected shard back to its
    holder."""
    async def main():
        from garage_tpu.block import ScrubWorker

        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2)
        )
        try:
            data = os.urandom(200_000)
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            for _ in range(100):
                held = sorted(i for m in managers for i in m.local_parts(h))
                if held == [0, 1, 2, 3, 4, 5]:
                    break
                await asyncio.sleep(0.02)
            assert held == [0, 1, 2, 3, 4, 5]

            layout = systems[0].layout_helper.current()
            placement = shard_nodes_of(layout, h, 6)
            leader = next(m for m in managers
                          if m.system.id == placement[0])

            # forge shard 1 on its holder: same length, valid framing,
            # wrong bytes — local checksum scrub CANNOT see this
            victim = next(m for m in managers if 1 in m.local_parts(h))
            raw = victim.read_local_shard(h, 1)
            payload, packed_len = unpack_shard(raw)
            forged = bytes(b ^ 0xFF for b in payload[:64]) + payload[64:]
            assert forged != payload
            victim.write_local_shard(h, 1, pack_shard(forged, packed_len))
            assert victim.read_local_shard(h, 1) is not None  # passes local

            sw = ScrubWorker(leader)
            bad = await sw.scrub_batch([h])
            assert bad == 1  # deep pass flagged the stripe

            # repair pushed the corrected shard to the holder
            fixed, _ = unpack_shard(victim.read_local_shard(h, 1))
            assert fixed == payload
            # stripe is consistent again: a re-scrub is clean and a
            # full read returns the original bytes
            assert await sw.scrub_batch([h]) == 0
            assert await managers[2].rpc_get_block(h) == data

            # non-leader nodes skip the deep pass (exactly one gather
            # per stripe per scrub round)
            non_leader = next(m for m in managers
                              if m.system.id != placement[0])
            assert await ScrubWorker(non_leader).scrub_batch([h]) == 0
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_deep_scrub_repairs_wrong_length_shard(tmp_path):
    """The misplaced-file class: a shard file holding a valid-framed
    shard of a DIFFERENT block (different length, different packed_len
    header) passes local validation; deep scrub must flag it WITHOUT
    crashing the batch (unequal lengths can't stack into the parity
    kernel), repair it, and the majority packed_len rule must keep the
    corrupt header from poisoning the localization decode."""
    async def main():
        from garage_tpu.block import ScrubWorker

        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2)
        )
        try:
            data = os.urandom(150_000)
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            for _ in range(100):
                held = sorted(i for m in managers for i in m.local_parts(h))
                if held == [0, 1, 2, 3, 4, 5]:
                    break
                await asyncio.sleep(0.02)
            assert held == [0, 1, 2, 3, 4, 5]

            layout = systems[0].layout_helper.current()
            placement = shard_nodes_of(layout, h, 6)
            leader = next(m for m in managers
                          if m.system.id == placement[0])

            victim = next(m for m in managers if 3 in m.local_parts(h))
            true_raw = victim.read_local_shard(h, 3)
            true_payload, _ = unpack_shard(true_raw)
            # a stray shard: wrong length AND wrong packed_len header
            stray = pack_shard(os.urandom(len(true_payload) + 512),
                               999_999)
            victim.write_local_shard(h, 3, stray)

            sw = ScrubWorker(leader)
            bad = await sw.scrub_batch([h])
            assert bad == 1
            fixed, _ = unpack_shard(victim.read_local_shard(h, 3))
            assert fixed == true_payload
            assert await sw.scrub_batch([h]) == 0
            assert await managers[1].rpc_get_block(h) == data
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_deep_scrub_repairs_rotted_header(tmp_path):
    """Header rot (ADVICE r5): the shard header's packed_len sits
    OUTSIDE the shard checksum, so a rotted header passes local
    validation AND the cross-shard parity check (parity covers payload
    bytes only) — invisible to every scrub pass before this one. Deep
    scrub must compare each shard's header against the stripe majority
    and push a rewritten shard (same payload, corrected header) to the
    disagreeing holder."""
    async def main():
        from garage_tpu.block import ScrubWorker

        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2)
        )
        try:
            data = os.urandom(180_000)
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            for _ in range(100):
                held = sorted(i for m in managers for i in m.local_parts(h))
                if held == [0, 1, 2, 3, 4, 5]:
                    break
                await asyncio.sleep(0.02)
            assert held == [0, 1, 2, 3, 4, 5]

            layout = systems[0].layout_helper.current()
            placement = shard_nodes_of(layout, h, 6)
            leader = next(m for m in managers
                          if m.system.id == placement[0])

            # rot shard 2's header: SAME payload, forged packed_len —
            # local checksum scrub and the parity kernel both pass
            victim = next(m for m in managers if 2 in m.local_parts(h))
            raw = victim.read_local_shard(h, 2)
            payload, true_len = unpack_shard(raw)
            victim.write_local_shard(h, 2, pack_shard(payload, 999_999))
            assert victim.read_local_shard(h, 2) is not None  # passes local

            sw = ScrubWorker(leader)
            bad = await sw.scrub_batch([h])
            # payload is intact, so this is NOT a content corruption...
            assert bad == 0
            # ...but the header was rewritten back to the majority value
            assert sw.header_repaired == 1
            fixed_payload, fixed_len = unpack_shard(
                victim.read_local_shard(h, 2))
            assert fixed_payload == payload
            assert fixed_len == true_len
            # clean second pass: nothing left to repair
            assert await sw.scrub_batch([h]) == 0
            assert sw.header_repaired == 1
            assert await managers[1].rpc_get_block(h) == data
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_deep_scrub_repairs_data_plus_parity_double_corruption(tmp_path):
    """RS(4,2) tolerates two losses; deep scrub localizes a double
    corruption of one DATA and one PARITY shard: the data exclusion
    must substitute the *healthy* parity shard (trying each in turn),
    then the re-encode fixes both."""
    async def main():
        from garage_tpu.block import ScrubWorker

        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2)
        )
        try:
            data = os.urandom(180_000)
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            for _ in range(100):
                held = sorted(i for m in managers for i in m.local_parts(h))
                if held == [0, 1, 2, 3, 4, 5]:
                    break
                await asyncio.sleep(0.02)
            assert held == [0, 1, 2, 3, 4, 5]

            layout = systems[0].layout_helper.current()
            placement = shard_nodes_of(layout, h, 6)
            leader = next(m for m in managers
                          if m.system.id == placement[0])

            originals = {}
            for part in (2, 4):  # data shard 2, parity shard 4
                holder = next(m for m in managers
                              if part in m.local_parts(h))
                payload, plen = unpack_shard(
                    holder.read_local_shard(h, part))
                originals[part] = (holder, payload)
                forged = bytes(b ^ 0xA5 for b in payload[:128]) \
                    + payload[128:]
                holder.write_local_shard(h, part, pack_shard(forged, plen))

            sw = ScrubWorker(leader)
            assert await sw.scrub_batch([h]) == 1
            for part, (holder, payload) in originals.items():
                fixed, _ = unpack_shard(holder.read_local_shard(h, part))
                assert fixed == payload, f"shard {part} not repaired"
            assert await sw.scrub_batch([h]) == 0
            assert await managers[0].rpc_get_block(h) == data
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_deep_scrub_skips_unreachable_stripes(tmp_path):
    """A down shard holder must not wedge or fail the deep pass: the
    gather comes back short, the stripe is skipped (absence is
    resync/repair's job), and the batch completes with 0 corruptions."""
    async def main():
        from garage_tpu.block import ScrubWorker

        net, systems, managers, tasks = await make_block_cluster(
            tmp_path, n=6, rf=3, erasure=(4, 2)
        )
        try:
            data = os.urandom(100_000)
            h = blake2sum(data)
            await managers[0].rpc_put_block(h, data)
            for _ in range(100):
                held = sorted(i for m in managers for i in m.local_parts(h))
                if held == [0, 1, 2, 3, 4, 5]:
                    break
                await asyncio.sleep(0.02)

            layout = systems[0].layout_helper.current()
            placement = shard_nodes_of(layout, h, 6)
            leader = next(m for m in managers
                          if m.system.id == placement[0])
            # kill a NON-leader holder
            downed = next(s for s in systems
                          if s.id == placement[3])
            await downed.netapp.shutdown()

            sw = ScrubWorker(leader)
            assert await asyncio.wait_for(sw.scrub_batch([h]), 30) == 0
            assert sw.deep_checked == 0  # skipped, not silently passed
        finally:
            await stop_all(systems, tasks)

    run(main())
