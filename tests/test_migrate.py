"""Versioned encoding migration chain tests (ref: src/util/migrate.rs:77-157)."""

import pytest

from garage_tpu.utils import migrate


class V1(migrate.Migratable):
    VERSION_MARKER = b"GT01x"
    PREVIOUS = None

    def __init__(self, a):
        self.a = a

    def pack(self):
        return {"a": self.a}

    @classmethod
    def unpack(cls, raw):
        return cls(raw["a"])

    def migrate(self):
        return V2(self.a, b=0)


class V2(migrate.Migratable):
    VERSION_MARKER = b"GT02x"
    PREVIOUS = V1

    def __init__(self, a, b):
        self.a, self.b = a, b

    def pack(self):
        return {"a": self.a, "b": self.b}

    @classmethod
    def unpack(cls, raw):
        return cls(raw["a"], raw["b"])


def test_roundtrip_current():
    v = V2(a=7, b=9)
    out = migrate.decode(V2, migrate.encode(v))
    assert (out.a, out.b) == (7, 9)


def test_migrates_old_version():
    old = migrate.encode(V1(a=5))
    out = migrate.decode(V2, old)
    assert isinstance(out, V2)
    assert (out.a, out.b) == (5, 0)


def test_unknown_marker_raises():
    with pytest.raises(ValueError):
        migrate.decode(V2, b"NOPEnope")
