"""garage-lint self-tests: per-rule firing + suppression fixtures,
waiver hygiene, baseline round-trip, and the tier-1 enforcement hook
(the full analyzer over garage_tpu/ must be clean).

Fixture snippets are analyzed in memory via analyze_source with a
rel_path chosen to satisfy each rule's directory scoping.
"""

import json
import os
import textwrap

import pytest

from garage_tpu.analysis import (META_RULE, analyze_paths, analyze_source,
                                 apply_baseline, default_rules,
                                 load_baseline, save_baseline)
from garage_tpu.analysis.baseline import DEFAULT_BASELINE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(src: str, rel_path: str = "garage_tpu/fake/mod.py"):
    """-> list of ACTIVE violations for one in-memory module."""
    ctx = analyze_source(textwrap.dedent(src), default_rules(),
                         rel_path=rel_path)
    return [v for v in ctx.violations if v.active]


def rules_of(violations):
    return sorted({v.rule for v in violations})


# ---- GL01 blocking-call-in-async ---------------------------------------

def test_gl01_fires_on_blocking_call_in_async():
    vs = run("""
        import time
        async def handler(req):
            time.sleep(0.1)
    """)
    assert rules_of(vs) == ["GL01"]
    assert "time.sleep" in vs[0].message


def test_gl01_fires_on_open_and_digest_of_data():
    vs = run("""
        import hashlib
        async def read_block(path, data):
            f = open(path, "rb")
            h = hashlib.sha256(data)
    """)
    assert [v.rule for v in vs] == ["GL01", "GL01"]


def test_gl01_exempts_to_thread_wrapped_and_constant_digest():
    vs = run("""
        import asyncio, hashlib, time
        async def handler(path, data):
            def work():
                time.sleep(0.1)
                return open(path, "rb").read()
            raw = await asyncio.to_thread(work)
            empty = hashlib.sha256()           # no data: instantaneous
            also = await asyncio.to_thread(hashlib.sha256, data)
        def sync_path(path):
            return open(path).read()           # not async: fine
    """)
    assert vs == []


# ---- GL02 hedge-on-mutation --------------------------------------------

def test_gl02_fires_on_explicit_hedge_true():
    # the PR 4 acceptance scenario: flipping the k2v pin to hedge=True
    vs = run("""
        async def _call_any(self, who, payload):
            await self.item_table.rpc.try_call_many(
                self.endpoint, who, payload,
                RequestStrategy(quorum=1, hedge=True))
    """)
    assert "GL02" in rules_of(vs)


def test_gl02_fires_on_hedge_defaulting_mutation():
    by_name = run("""
        async def insert_rpc(self, who, payload):
            await self.rpc.try_call_many(
                self.ep, who, payload, RequestStrategy(quorum=1))
    """)
    assert rules_of(by_name) == ["GL02"]
    by_op = run("""
        async def _fanout(self, who, raws):
            await self.rpc.try_call_many(
                self.ep, who, {"op": "insert_many", "entries": raws},
                RequestStrategy(quorum=2))
    """)
    assert rules_of(by_op) == ["GL02"]


def test_gl02_quiet_on_pinned_or_read_calls():
    vs = run("""
        async def insert_rpc(self, who, payload):
            await self.rpc.try_call_many(
                self.ep, who, payload,
                RequestStrategy(quorum=1, hedge=False))
        async def _get_traced(self, pk):
            return await self.rpc.try_call_many(
                self.ep, self.nodes, {"op": "get", "pk": pk},
                RequestStrategy(quorum=1))
    """)
    assert vs == []


def test_gl02_resolves_local_strategy_binding():
    vs = run("""
        async def delete_rpc(self, who, payload):
            st = RequestStrategy(quorum=1)
            await self.rpc.try_call_many(self.ep, who, payload, st)
    """)
    assert rules_of(vs) == ["GL02"]


# ---- GL03 ssec-cache-leak ----------------------------------------------

S3_PATH = "garage_tpu/api/s3/fake_get.py"


def test_gl03_fires_without_explicit_cacheable():
    vs = run("""
        async def stream(mgr, h, sse_key):
            return await mgr.rpc_get_block(h)
    """, rel_path=S3_PATH)
    assert rules_of(vs) == ["GL03"]


def test_gl03_quiet_with_cacheable_or_outside_sse_scope():
    vs = run("""
        async def stream(mgr, h, sse_key):
            return await mgr.rpc_get_block(
                h, cacheable=sse_key is None)
        async def plain(mgr, h):
            return await mgr.rpc_get_block(h)
    """, rel_path=S3_PATH)
    assert vs == []


def test_gl03_scoped_to_s3_and_block_dirs():
    vs = run("""
        async def stream(mgr, h, sse_key):
            return await mgr.rpc_get_block(h)
    """, rel_path="garage_tpu/web/server.py")
    assert vs == []


# ---- GL04 orphan-task --------------------------------------------------

def test_gl04_fires_on_dropped_task():
    vs = run("""
        import asyncio
        def kick(coro):
            asyncio.create_task(coro())
            asyncio.ensure_future(coro())
    """)
    assert [v.rule for v in vs] == ["GL04", "GL04"]


def test_gl04_quiet_when_retained_or_awaited():
    vs = run("""
        import asyncio
        from garage_tpu.utils.background import spawn
        async def kick(self, coro):
            t = asyncio.create_task(coro())
            self._tasks.add(t)
            await asyncio.create_task(coro())
            spawn(coro())
    """)
    assert vs == []


# ---- GL05 swallowed-exception ------------------------------------------

def test_gl05_fires_on_silent_swallow():
    for body in ("pass", "return None", "return"):
        vs = run(f"""
            def f(x):
                try:
                    g()
                except Exception:
                    {body}
        """)
        assert rules_of(vs) == ["GL05"], body
    vs = run("""
        def f(xs):
            for x in xs:
                try:
                    g(x)
                except Exception:
                    continue
    """)
    assert rules_of(vs) == ["GL05"]


def test_gl05_quiet_on_logged_narrow_or_test_code():
    vs = run("""
        def f():
            try:
                g()
            except Exception as e:
                log.debug("g failed: %s", e)
            try:
                g()
            except KeyError:
                pass
            try:
                g()
            except Exception:
                return False
    """)
    assert vs == []
    in_test = run("""
        def f():
            try:
                g()
            except Exception:
                pass
    """, rel_path="tests/test_fake.py")
    assert in_test == []


# ---- GL06 await-holding-lock -------------------------------------------

BLOCK_PATH = "garage_tpu/block/fake.py"


def test_gl06_fires_on_rpc_await_under_async_lock():
    vs = run("""
        async def refresh(self, payload):
            async with self._lock:
                await self.rpc.try_call_many(self.ep, self.nodes,
                                             payload, st)
    """, rel_path=BLOCK_PATH)
    assert rules_of(vs) == ["GL06"]


def test_gl06_quiet_outside_lock_or_non_rpc_awaits():
    vs = run("""
        async def refresh(self, payload):
            async with self._lock:
                await asyncio.sleep(0)
                data = await asyncio.to_thread(self.read_local, h)
            await self.rpc.try_call_many(self.ep, self.nodes,
                                         payload, st)
            async with self._sem:   # not a lock by name
                await self.rpc.call(self.ep, n, payload, 0)
    """, rel_path=BLOCK_PATH)
    assert vs == []


def test_gl06_scoped_to_table_and_block():
    vs = run("""
        async def push(self, payload):
            async with self._lock:
                await self.rpc.call(self.ep, n, payload, 0)
    """, rel_path="garage_tpu/api/s3/fake.py")
    assert vs == []


# ---- GL07 unregistered-metric ------------------------------------------

def test_gl07_fires_on_dynamic_and_off_scheme_names():
    vs = run("""
        from garage_tpu.utils.metrics import registry
        def f(key):
            registry().inc(f"qos_{key}_total")
            registry().inc("frontend_requests")
    """)
    assert [v.rule for v in vs] == ["GL07", "GL07"]
    assert "dynamically" in vs[0].message


def test_gl07_quiet_on_scheme_conforming_literals():
    vs = run("""
        from garage_tpu.utils.metrics import registry
        def f(n):
            registry().inc("qos_shed_requests", scope="global")
            registry().observe("rpc_request_duration_seconds", n)
            with registry().timer("s3_get_seconds"):
                pass
    """)
    assert vs == []


def test_gl07_runtime_agrees_with_static_rule(monkeypatch):
    # the satellite fix: utils/metrics.py rejects off-scheme names at
    # registration time in debug mode — same regex as the static rule
    import garage_tpu.utils.metrics as m
    monkeypatch.setattr(m, "STRICT_METRIC_NAMES", True)
    reg = m.MetricsRegistry()
    reg.inc("qos_ok_total")
    with pytest.raises(ValueError, match="naming scheme"):
        reg.inc("qos_Bad-Name")
    with pytest.raises(ValueError, match="naming scheme"):
        reg.inc("frontend_requests")
    monkeypatch.setattr(m, "STRICT_METRIC_NAMES", False)
    reg2 = m.MetricsRegistry()
    reg2.inc("frontend_requests")  # production: never raises


# ---- GL08 config-knob-drift --------------------------------------------

def _mini_tree(tmp_path, config_body, app_body):
    pkg = tmp_path / "garage_tpu"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "utils" / "config.py").write_text(textwrap.dedent(config_body))
    (pkg / "app.py").write_text(textwrap.dedent(app_body))
    return str(pkg)


def test_gl08_fires_on_unknown_key_and_dead_knob(tmp_path):
    pkg = _mini_tree(tmp_path, """
        from dataclasses import dataclass
        @dataclass
        class Config:
            block_size: int = 5
            dead_knob: int = 1
    """, """
        def f(cfg):
            return cfg.block_sizze + cfg.block_size
    """)
    vs, _ = analyze_paths([pkg], default_rules(), root=str(tmp_path))
    got = {(v.rule, v.message.split("`")[1]) for v in vs if v.active}
    assert ("GL08", "block_sizze") in got       # read, not a field
    assert ("GL08", "dead_knob") in got         # field, never read


def test_gl08_readme_mention_and_section_alias_count_as_use(tmp_path):
    pkg = _mini_tree(tmp_path, """
        from dataclasses import dataclass, field
        @dataclass
        class QosConfig:
            global_rps: float = 1.0
        @dataclass
        class Config:
            block_size: int = 5
            documented_knob: int = 1
            qos: QosConfig = field(default_factory=QosConfig)
    """, """
        def f(cfg):
            qc = cfg.qos
            return cfg.block_size + qc.global_rps
    """)
    vs, _ = analyze_paths([pkg], default_rules(), root=str(tmp_path),
                          data={"readme_text": "set `documented_knob`"})
    assert [v for v in vs if v.active] == []


# ---- waivers ------------------------------------------------------------

def test_waiver_suppresses_with_reason():
    vs = analyze_source(textwrap.dedent("""
        def f():
            try:
                g()
            except Exception:
                pass  # lint: ignore[GL05] g is best-effort telemetry
    """), default_rules(), rel_path="garage_tpu/fake.py").violations
    assert [v.rule for v in vs] == ["GL05"]
    assert vs[0].waived and not vs[0].active


def test_waiver_without_reason_is_an_error():
    vs = run("""
        def f():
            try:
                g()
            except Exception:
                pass  # lint: ignore[GL05]
    """)
    # the GL05 stays active AND the reasonless waiver is a GL00
    assert rules_of(vs) == [META_RULE, "GL05"]


def test_stale_waiver_is_an_error():
    vs = run("""
        def f():  # lint: ignore[GL05] nothing here actually fires
            return 1
    """)
    assert rules_of(vs) == [META_RULE]
    assert "stale waiver" in vs[0].message


def test_rules_subset_does_not_rot_other_rules_waivers(tmp_path):
    """--rules GL10 must not call a (live) GL05 waiver stale — its rule
    never ran; a FULL run still checks every waiver (ISSUE 9)."""
    target = tmp_path / "garage_tpu" / "mod.py"
    target.parent.mkdir(parents=True)
    target.write_text(textwrap.dedent("""
        def f():
            try:
                g()
            except Exception:
                pass  # lint: ignore[GL05] best-effort telemetry
    """))
    subset = [r for r in default_rules() if r.id == "GL10"]
    vs, _ = analyze_paths([str(target)], subset, root=str(tmp_path),
                          restricted=True)
    assert [v for v in vs if v.active] == []
    full, _ = analyze_paths([str(target)], default_rules(),
                            root=str(tmp_path))
    assert [v for v in full if v.active] == []  # waiver used, not stale


def test_waiver_in_docstring_is_prose_not_suppression():
    vs = run('''
        def f():
            """Example: x()  # lint: ignore[GL05] reason."""
            return 1
    ''')
    assert vs == []  # no stale-waiver error from the docstring


# ---- baseline -----------------------------------------------------------

FIRING = """
    def f():
        try:
            g()
        except Exception:
            pass
"""


def test_baseline_round_trip(tmp_path):
    bl = str(tmp_path / "baseline.json")
    first = analyze_source(textwrap.dedent(FIRING), default_rules(),
                           rel_path="garage_tpu/fake.py").violations
    assert save_baseline(bl, first) == 1
    second = analyze_source(textwrap.dedent(FIRING), default_rules(),
                            rel_path="garage_tpu/fake.py").violations
    stale = apply_baseline(second, load_baseline(bl))
    assert stale == []
    assert all(v.baselined for v in second if v.rule == "GL05")
    assert [v for v in second if v.active] == []


def test_stale_baseline_entry_is_an_error(tmp_path):
    bl = str(tmp_path / "baseline.json")
    first = analyze_source(textwrap.dedent(FIRING), default_rules(),
                           rel_path="garage_tpu/fake.py").violations
    save_baseline(bl, first)
    clean = analyze_source("def f():\n    return 1\n", default_rules(),
                           rel_path="garage_tpu/fake.py").violations
    stale = apply_baseline(clean, load_baseline(bl))
    assert len(stale) == 1 and stale[0].rule == META_RULE
    assert "stale baseline" in stale[0].message


def test_missing_baseline_file_is_empty(tmp_path):
    assert load_baseline(str(tmp_path / "nope.json")) == []


# ---- GL00 framework ------------------------------------------------------

def test_unparseable_source_is_gl00():
    vs = run("def broken(:\n")
    assert rules_of(vs) == [META_RULE]


# ---- tier-1 enforcement hook --------------------------------------------

def _tree_violations():
    rules = default_rules()
    data = {}
    readme = os.path.join(REPO, "README.md")
    if os.path.exists(readme):
        with open(readme, encoding="utf-8") as f:
            data["readme_text"] = f.read()
    # same path set as the CLI default: the package + harness files
    # (scoped to GL04/GL05/GL07 by the walker)
    paths = [os.path.join(REPO, "garage_tpu")] + [
        p for p in (os.path.join(REPO, h) for h in
                    ("tests/clusterbox.py", "tests/conftest.py",
                     "bench.py"))
        if os.path.exists(p)]
    violations, project = analyze_paths(paths, rules, root=REPO,
                                        data=data)
    violations += apply_baseline(
        violations, load_baseline(os.path.join(REPO, DEFAULT_BASELINE)))
    return violations, project


def test_tree_has_zero_non_baselined_violations():
    """THE enforcement hook: any new violation in garage_tpu/ (or the
    harness files) fails tier-1 until fixed, waived with a reason, or
    (exceptionally) baselined. Also pins the ISSUE 9 wall-time budget:
    the two-pass dataflow engine must keep the full-tree scan (cold,
    no summary cache) under 30 s."""
    import time as _time

    t0 = _time.monotonic()
    violations, project = _tree_violations()
    elapsed = _time.monotonic() - t0
    active = [v for v in violations if v.active]
    assert len(project.files) > 100  # the scan actually saw the tree
    assert active == [], "\n" + "\n".join(v.render() for v in active)
    assert elapsed < 30.0, f"lint took {elapsed:.1f}s (budget 30s)"


def test_cli_runs_clean_json(capsys):
    from garage_tpu.analysis.__main__ import main
    rc = main(["--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["violations"] == []
    assert out["files"] > 100


def test_every_rule_has_an_id_and_fixture_coverage():
    ids = {r.id for r in default_rules()}
    assert ids == {f"GL0{i}" for i in range(1, 10)} | {"GL10", "GL11",
                                                       "GL12", "GL13",
                                                       "GL14", "GL15",
                                                       "GL16"}


def test_every_rule_has_explain_material():
    # --explain RULE needs rationale + fire/suppress examples
    for r in default_rules():
        assert getattr(r, "rationale", ""), r.id
        assert getattr(r, "example_fire", ""), r.id
        assert getattr(r, "example_ok", ""), r.id


# ---- GL09 cross-worker-state -------------------------------------------


def test_gl09_fires_on_module_state_mutated_in_handler():
    vs = run("""
        PENDING = {}

        async def handle(req):
            PENDING[req.id] = req
    """, rel_path="garage_tpu/api/s3/foo.py")
    assert [v.rule for v in vs] == ["GL09"]


def test_gl09_fires_on_mutating_method_and_global_decl():
    vs = run("""
        SEEN = set()
        COUNT = dict()

        def note(x):
            SEEN.add(x)

        def bump():
            global COUNT
            COUNT["x"] = 1
    """, rel_path="garage_tpu/gateway/foo.py")
    assert sorted(v.rule for v in vs) == ["GL09", "GL09"]


def test_gl09_quiet_on_readonly_tables_and_locals():
    vs = run("""
        STATUS = {200: "OK"}  # read-only lookup table: fine

        def reason(code):
            local = {}
            local["x"] = 1  # local shadow, not module state
            return STATUS.get(code)
    """, rel_path="garage_tpu/api/http2.py")
    assert vs == []


def test_gl09_scoped_to_request_plane_packages():
    src = """
        PENDING = {}

        async def handle(req):
            PENDING[req.id] = req
    """
    assert run(src, rel_path="garage_tpu/block/foo.py") == []
    assert [v.rule for v in
            run(src, rel_path="garage_tpu/qos/foo.py")] == ["GL09"]
    assert [v.rule for v in
            run(src, rel_path="garage_tpu/web/foo.py")] == ["GL09"]


def test_gl09_nested_def_does_not_shadow_outer_mutation():
    # a nested def assigning the name locally must not hide the OUTER
    # function's mutation of module state...
    vs = run("""
        CACHE = {}

        def handler(x):
            def reset():
                CACHE = {}
                return CACHE
            CACHE[x] = 1
    """, rel_path="garage_tpu/api/foo.py")
    assert [v.rule for v in vs] == ["GL09"]
    # ...and a nested function's mutation of its OWN local must not
    # flag the enclosing scope
    vs = run("""
        CACHE = {}

        def handler(x):
            def build():
                CACHE = {}
                CACHE["x"] = 1
                return CACHE
            return build()
    """, rel_path="garage_tpu/api/foo.py")
    assert vs == []


def test_gl09_waivable_with_reason():
    vs = run("""
        SEEN = set()  # lint: ignore[GL09] merged by the supervisor scrape

        def note(x):
            SEEN.add(x)
    """, rel_path="garage_tpu/gateway/foo.py")
    assert vs == []
