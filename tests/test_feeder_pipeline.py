"""Staged device pipeline: overlap, padded launches, mesh sharding,
watchdog hang-fallback and lifecycle (ISSUE 12).

Everything here runs on this deviceless box: the stub backend
(block/device_backend.py StubDeviceBackend) emulates transfer/compute
latency deterministically over the host kernels, and the jax backend's
"device" is the cpu platform (conftest pins JAX_PLATFORMS=cpu with 8
virtual devices), which exercises the real staging/padding/mesh code
paths — the routing and pipelining, not the silicon, are under test.
"""

from __future__ import annotations

import asyncio
import json as _json
import os
import time

import pytest

from garage_tpu.block import feeder as fmod
from garage_tpu.block.codec import ErasureCodec
from garage_tpu.block.device_backend import (StubDeviceBackend,
                                             bucket_items, bucket_len)
from garage_tpu.block.feeder import DeviceFeeder, _Item
from garage_tpu.utils.data import blake3sum


def run(coro):
    return asyncio.run(coro)


@pytest.fixture
def probe_cache_guard():
    """Snapshot/restore the shared /tmp probe cache around tests that
    poison it (same discipline as test_native_feeder's poison test)."""
    cache_path = fmod._probe_cache_path()
    old_result = fmod._probe_result
    old_disk = None
    try:
        with open(cache_path, "rb") as f:
            old_disk = f.read()
    except OSError:
        pass
    yield cache_path
    fmod._probe_result = old_result
    try:
        if old_disk is None:
            os.unlink(cache_path)
        else:
            with open(cache_path, "wb") as f:
                f.write(old_disk)
    except OSError:
        pass


# ---------------------------------------------------------------------------
# overlap proof (acceptance criterion): wall < serial sum of stage sleeps
# ---------------------------------------------------------------------------


def test_pipeline_overlap_beats_serial_sum():
    """With depth-2 in-flight batches and per-stage latencies of
    `fixed_s` each, N batches must complete in measurably less wall
    time than the serial sum N * (h2d + compute + d2h) — the pinned
    proof that transfer overlaps compute instead of the old one
    blocking hop per batch."""
    fixed = 0.04
    nbatches = 4
    stub = StubDeviceBackend(None, h2d_gbps=1e6, compute_gbps=1e6,
                             d2h_gbps=1e6, fixed_s=fixed)
    # max_batch=1: every submission is its own batch, so the queue
    # can't coalesce the four items into one launch
    f = DeviceFeeder(mode="require", max_batch=1, backend=stub)
    f._device_ok = True
    blobs = [os.urandom(1024) for _ in range(nbatches)]
    serial_sum = nbatches * 3 * fixed

    async def go():
        t0 = time.perf_counter()
        digs = await asyncio.gather(*[f.hash(b) for b in blobs])
        wall = time.perf_counter() - t0
        assert list(digs) == [blake3sum(b) for b in blobs]
        stats = dict(f.stats)
        ps = f.pipeline_stats()
        await f.stop()
        return wall, stats, ps

    wall, stats, ps = run(go())
    assert stats["device_items"] == nbatches
    assert stats["device_batches"] == nbatches
    # pipelined ideal here is ~(N+2)*fixed = 0.24s vs serial 0.48s;
    # the 0.85 margin absorbs CI scheduling noise while still failing
    # hard if the pipeline ever degrades to one-batch-at-a-time
    assert wall < serial_sum * 0.85, (wall, serial_sum)
    # busy/wall > 1 is only possible when stages of different batches
    # genuinely ran concurrently
    assert ps["overlap_efficiency"] > 1.0, ps
    assert ps["wall_s"] > 0


# ---------------------------------------------------------------------------
# watchdog: mid-pipeline hang with depth-2 in flight
# ---------------------------------------------------------------------------


def test_pipeline_hang_reruns_all_inflight_host_side(probe_cache_guard,
                                                     monkeypatch):
    """Injected device hang with two batches in flight: BOTH re-run
    host-side, every caller future resolves with a correct digest, the
    device path is disabled and the probe cache is poisoned with the
    `hung` marker (extends the old single-batch watchdog semantics to
    every in-flight pipeline stage)."""
    # conftest exports GARAGE_TPU_DEVICE=off (never probe the real
    # tunnel in tests), which would downgrade mode="auto" to "off";
    # the stub backend needs no probe, so auto is safe here
    monkeypatch.delenv("GARAGE_TPU_DEVICE", raising=False)
    stub = StubDeviceBackend(None, fixed_s=0.01)
    stub.hang_stage = "compute"  # next batch entering compute wedges
    f = DeviceFeeder(mode="auto", max_batch=4, backend=stub)
    f._device_ok = True
    f.batch_timeout = 1.0  # shrink the 300 s watchdog for the test
    # calibration seed: device hugely winning, so auto-routing sends
    # these batches to the (about to hang) device path
    f._record("hash", "device", 1 << 30, 1.0)
    f._record("hash", "host", 1 << 20, 1.0)
    blobs = [os.urandom(65536) for _ in range(8)]

    async def go():
        t0 = time.perf_counter()
        digs = await asyncio.gather(*[f.hash(b) for b in blobs])
        wall = time.perf_counter() - t0
        dev_ok = f._device_ok
        await f.stop()
        return digs, wall, dev_ok

    digs, wall, dev_ok = run(go())
    # no caller future lost, results correct via the host re-run
    assert list(digs) == [blake3sum(b) for b in blobs]
    # the sibling batch must NOT have waited out its own full watchdog
    # on top of the first one's: the abort event fails it over at once
    assert wall < 2 * f.batch_timeout + 1.0
    assert dev_ok is False  # device path disabled
    assert f.stats["device_items"] == 0  # nothing credited to the device
    # probe cache poisoned with the hung marker for co-located feeders
    with open(probe_cache_guard) as fh:
        cached = _json.load(fh)
    assert cached["ok"] is False and cached.get("hung") is True
    assert "stuck" in cached["error"]


def test_stage_executor_never_runs_cancelled_queued_jobs():
    """A job cancelled while still QUEUED behind a slow sibling must
    never execute — stage fns carry side effects (the d2h MD5 lane
    advance), and running one after its batch already failed over to
    the host path would apply them twice (review finding: silent ETag
    corruption)."""
    from garage_tpu.block.device_backend import StageExecutor

    async def go():
        loop = asyncio.get_running_loop()
        ex = StageExecutor("d2h", {"d2h": 0.0})
        ran = []
        slow = ex.submit(loop, lambda: time.sleep(0.15))
        victim = ex.submit(loop, lambda: ran.append("side-effect"))
        victim.fut.cancel()  # abandoned while queued
        await asyncio.wait({slow.fut})
        assert slow.claimed and slow.busy >= 0.1
        await asyncio.sleep(0.1)  # give the worker time to (not) run it
        assert ran == [], "cancelled queued job executed its side effect"
        assert victim.claimed is False

    run(go())


def test_hash_md5_hang_fallback_advances_etag_exactly_once():
    """Depth-2 hash_md5 batches, device hang mid-pipeline: both re-run
    host-side and every serial MD5 ETag chain advances EXACTLY once
    (hashlib parity) — the side-effecting edition of the hang test."""
    import hashlib

    from garage_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    stub = StubDeviceBackend(None, fixed_s=0.01)
    stub.hang_stage = "compute"
    f = DeviceFeeder(mode="require", max_batch=2, backend=stub)
    f._device_ok = True
    f.batch_timeout = 1.0
    f.active_streams = 4
    blobs = [os.urandom(4096) for _ in range(4)]
    accs = [native.Md5() for _ in blobs]
    refs = [hashlib.md5() for _ in blobs]

    async def go():
        digs = await asyncio.gather(*[
            f.hash_with_md5(b, a) for b, a in zip(blobs, accs)])
        await f.stop()
        return digs

    digs = run(go())
    for r, b in zip(refs, blobs):
        r.update(b)
    assert list(digs) == [blake3sum(b) for b in blobs]
    assert [a.hexdigest() for a in accs] == [r.hexdigest() for r in refs]
    assert f._device_ok is False


def test_stop_with_inflight_batches_resolves_every_future():
    """stop() while depth-2 batches sit mid-stage: every waiter gets
    RuntimeError("feeder stopped") (or its result), nothing hangs."""
    stub = StubDeviceBackend(None, fixed_s=0.2)
    f = DeviceFeeder(mode="require", max_batch=1, backend=stub)
    f._device_ok = True

    async def go():
        tasks = [asyncio.create_task(f.hash(os.urandom(2048)))
                 for _ in range(3)]
        await asyncio.sleep(0.05)  # let two enter the pipeline
        await f.stop()
        outcomes = []
        for t in tasks:
            try:
                outcomes.append(await asyncio.wait_for(t, 2.0))
            except RuntimeError as e:
                assert "feeder stopped" in str(e)
                outcomes.append(None)
            except asyncio.TimeoutError:
                raise AssertionError("caller future stranded by stop()")
        return outcomes

    outcomes = run(go())
    assert len(outcomes) == 3


# ---------------------------------------------------------------------------
# fixed-shape padded launches (jax backend on the cpu "device")
# ---------------------------------------------------------------------------


def test_bucket_helpers():
    assert bucket_items(3, (1, 2, 4, 8)) == 4
    assert bucket_items(8, (1, 2, 4, 8)) == 8
    assert bucket_items(9, (1, 2, 4, 8)) == 9  # above the ladder: as-is
    assert bucket_len(1) == 1024
    assert bucket_len(1024) == 1024
    assert bucket_len(1025) == 2048
    assert bucket_len(262144) == 262144


def mk_batch(op, datas):
    loop = asyncio.get_event_loop_policy().new_event_loop()
    try:
        return [_Item(op, d, loop.create_future()) for d in datas]
    finally:
        loop.close()


def test_padded_launches_correct_and_shape_stable():
    """The staged jax route pads items to bucket shapes: results stay
    byte-identical to the host path, pad waste is accounted, and a
    second batch with the same bucket shape compiles NOTHING new
    (feeder_recompiles unchanged — the whole point of bucketing)."""
    import numpy as np

    codec = ErasureCodec(4, 2, use_jax=False)
    f = DeviceFeeder(codec=codec, mode="require", max_batch=8)
    f._device_ok = True
    rng = np.random.default_rng(7)

    def items(n, base):
        return [(b"\x00", rng.integers(0, 256, base + i, dtype=np.uint8)
                 .tobytes()) for i in range(n)]

    async def go():
        from garage_tpu.block.manager import unpack_shard

        # wave 1: 5 encode_put items -> bucket 8, padded shard len
        batch = [_Item("encode_put", it, asyncio.get_running_loop()
                       .create_future()) for it in items(5, 65536)]
        res = await f._run_batch_staged(batch)
        host = f._do_encode_put([it.data for it in batch], "host")
        for pa, pb in zip(res, host):
            for sa, sb in zip(pa, pb):
                da, la = unpack_shard(bytes(sa))
                db, lb = unpack_shard(bytes(sb))
                assert la == lb and bytes(da) == bytes(db)
        waste1 = f.stats["pad_waste_bytes"]
        rc1 = f.stats["recompiles"]
        assert waste1 > 0  # 5 -> 8 items plus shard-len rounding
        assert rc1 >= 1
        # wave 2: 6 items, same sizes -> same bucket -> zero recompiles
        batch2 = [_Item("encode_put", it, asyncio.get_running_loop()
                        .create_future()) for it in items(6, 65536)]
        res2 = await f._run_batch_staged(batch2)
        host2 = f._do_encode_put([it.data for it in batch2], "host")
        for pa, pb in zip(res2, host2):
            for sa, sb in zip(pa, pb):
                da, la = unpack_shard(bytes(sa))
                db, lb = unpack_shard(bytes(sb))
                assert la == lb and bytes(da) == bytes(db)
        assert f.stats["recompiles"] == rc1, "bucket shape recompiled"
        assert f.stats["pad_waste_bytes"] > waste1
        await f.stop()

    run(go())


def test_padded_hash_and_verify_and_parity_staged():
    """Hash digests from padded-item-count launches match blake3sum
    (pad rows sliced away); verify and parity_check verdicts survive
    the staged route including a corrupted stripe."""
    import numpy as np

    codec = ErasureCodec(4, 2, use_jax=False)
    f = DeviceFeeder(codec=codec, mode="require", max_batch=8)
    f._device_ok = True
    rng = np.random.default_rng(9)
    blobs = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
             for n in (1024, 5000, 65536)]

    async def go():
        batch = [_Item("hash", b, asyncio.get_running_loop()
                       .create_future()) for b in blobs]
        digs = await f._run_batch_staged(batch)
        assert digs == [blake3sum(b) for b in blobs]

        items = [(blake3sum(blobs[0]), blobs[0]),
                 (b"\x00" * 32, blobs[1])]
        vb = [_Item("verify", it, asyncio.get_running_loop()
                    .create_future()) for it in items]
        assert await f._run_batch_staged(vb) == [True, False]

        stripes = [codec.encode(b) for b in blobs]
        s = list(stripes[1])
        s[2] = bytes(x ^ 1 for x in s[2])
        stripes[1] = s
        pb = [_Item("parity_check", st, asyncio.get_running_loop()
                    .create_future()) for st in stripes]
        assert await f._run_batch_staged(pb) == [True, False, True]
        await f.stop()

    run(go())


# ---------------------------------------------------------------------------
# multi-chip mesh sharding (8 virtual cpu devices from conftest)
# ---------------------------------------------------------------------------


def test_mesh_sharded_encode_matches_host():
    import jax
    import numpy as np

    if len(jax.devices()) < 2:
        pytest.skip("single-device jax runtime")
    codec = ErasureCodec(4, 2, use_jax=False)
    f = DeviceFeeder(codec=codec, mode="require", max_batch=16)
    f._device_ok = True
    f.mesh_min_items = 4  # engage the mesh at this test's batch size
    rng = np.random.default_rng(11)
    blocks = [rng.integers(0, 256, 262144 + i, dtype=np.uint8).tobytes()
              for i in range(8)]

    async def go():
        batch = [_Item("encode", b, asyncio.get_running_loop()
                       .create_future()) for b in blocks]
        res = await f._run_batch_staged(batch)
        host = f._do_encode(blocks, "host")
        for a, b in zip(res, host):
            assert [bytes(x) for x in a] == [bytes(x) for x in b]
        assert f.stats["mesh_batches"] >= 1

        # parity_check rides the mesh too, and still detects corruption
        stripes = [codec.encode(b) for b in blocks]
        bad = list(stripes[3])
        bad[5] = bytes(x ^ 0xFF for x in bad[5])
        stripes[3] = bad
        pb = [_Item("parity_check", st, asyncio.get_running_loop()
                    .create_future()) for st in stripes]
        verdicts = await f._run_batch_staged(pb)
        assert verdicts == [i != 3 for i in range(8)]
        assert f.stats["mesh_batches"] >= 2
        await f.stop()

    run(go())


# ---------------------------------------------------------------------------
# stub backend selection + the require live gate, config + tuning knobs
# ---------------------------------------------------------------------------


def test_stub_backend_require_live_gate(monkeypatch):
    """GARAGE_TPU_DEVICE=require with the stub backend: no probe, no
    tunnel — device_items > 0 straight away. This is the CI shape of
    the live S3-path gate (bench's DeviceServer runs the same mode
    against real hardware when present)."""
    monkeypatch.setenv("GARAGE_TPU_DEVICE_BACKEND", "stub")
    f = DeviceFeeder(mode="require")

    async def go():
        blob = os.urandom(4096)
        dig = await f.hash(blob)
        assert dig == blake3sum(blob)
        assert f.stats["device_items"] >= 1
        assert f._get_backend().name == "stub"
        await f.stop()

    run(go())


def test_tpu_config_knobs_flow_into_feeder():
    from garage_tpu.utils.config import config_from_dict

    cfg = config_from_dict({
        "metadata_dir": "/tmp/x",
        "tpu": {"inflight_batches": 3, "device_min_bytes": 1024,
                "device_min_items": 2, "pad_buckets": [2, 4],
                "mesh_min_items": 5, "device_backend": "stub",
                "trial_max_items": 1, "trial_items_cap": 4,
                "trial_max_bytes": 123, "batch_timeout_s": 7.5},
    })
    f = DeviceFeeder(mode="off", tpu_cfg=cfg.tpu)
    assert f.inflight_batches == 3
    assert f.device_min_bytes == 1024
    assert f.device_min_items == 2
    assert f.pad_buckets == (2, 4)
    assert f.mesh_min_items == 5
    assert f.trial_max_items == 1
    assert f.trial_items_cap == 4
    assert f.trial_max_bytes == 123
    assert f.batch_timeout == 7.5
    assert f._backend_is_stub()
    # None fields leave the feeder defaults in force
    f2 = DeviceFeeder(mode="off")
    assert f2.device_min_bytes == fmod._DEVICE_MIN_BYTES
    assert f2.batch_timeout == fmod._BATCH_TIMEOUT


def test_s3_tuning_feeder_knobs():
    """The admin /v1/s3/tuning surface tunes the live feeder: depth and
    routing floors apply, the state echoes them, bad values 400."""
    from types import SimpleNamespace

    from garage_tpu.admin.http import apply_s3_tuning, s3_tuning_state
    from garage_tpu.block.cache import BlockCache
    from garage_tpu.utils.config import Config
    from garage_tpu.utils.error import BadRequest

    feeder = DeviceFeeder(mode="off")
    garage = SimpleNamespace(
        config=Config(metadata_dir="/tmp/x"),
        block_manager=SimpleNamespace(cache=BlockCache(1 << 20),
                                      feeder=feeder))
    state = apply_s3_tuning(garage, {"feeder_inflight_batches": 4,
                                     "feeder_device_min_bytes": 1 << 20,
                                     "feeder_device_min_items": 7})
    assert feeder.inflight_batches == 4
    assert feeder.device_min_bytes == 1 << 20
    assert feeder.device_min_items == 7
    assert state["feeder_inflight_batches"] == 4
    assert "feeder_pipeline" in state
    assert s3_tuning_state(garage)["feeder_device_min_items"] == 7
    with pytest.raises(BadRequest):
        apply_s3_tuning(garage, {"feeder_inflight_batches": 0})
    with pytest.raises(BadRequest):
        apply_s3_tuning(garage, {"feeder_bogus": 1})
    # a rejected spec must not have half-applied
    assert feeder.inflight_batches == 4


def test_stop_concurrent_restart_keeps_new_dispatcher():
    """GL12 regression (ISSUE 14): stop() yields while the cancelled
    dispatcher unwinds; a concurrent _submit's _ensure_started() can
    respawn a NEW dispatcher in that window. The old `self._task =
    None` after the await nulled the live dispatcher's handle — the
    feeder then thought it was stopped while an orphan kept consuming
    a queue nothing referenced, and the next restart spawned a second
    one. stop() now snapshots-and-clears BEFORE awaiting."""
    f = DeviceFeeder(mode="off")

    async def go():
        unwound = asyncio.Event()

        async def slow_dispatcher():
            try:
                await asyncio.sleep(3600)
            finally:
                unwound.set()
                # cancellation takes a few loop ticks — the window a
                # real dispatcher's cleanup occupies
                try:
                    await asyncio.shield(asyncio.sleep(0.05))
                except asyncio.CancelledError:
                    pass

        f._task = asyncio.create_task(slow_dispatcher())
        old = f._task
        await asyncio.sleep(0)  # let the dispatcher enter its try block

        async def restart_mid_stop():
            await unwound.wait()       # inside stop()'s await window
            f._ensure_started()        # a concurrent submitter respawns
            return f._task

        rt = asyncio.create_task(restart_mid_stop())
        await f.stop()
        new = await rt
        assert new is not old
        # the respawned dispatcher's handle must survive stop()
        assert f._task is new
        assert not new.done()
        await f.stop()  # cleanup (also exercises the fixed path again)
        assert f._task is None

    run(go())


def test_stop_drains_only_its_own_queue_not_the_respawns():
    """Review regression: stop() snapshots the queue BEFORE awaiting —
    an item submitted to a dispatcher respawned mid-stop must not get
    a spurious "feeder stopped" from stop()'s drain."""
    f = DeviceFeeder(mode="off")

    async def go():
        unwound = asyncio.Event()

        async def slow_dispatcher():
            try:
                await asyncio.sleep(3600)
            finally:
                unwound.set()
                try:
                    await asyncio.shield(asyncio.sleep(0.05))
                except asyncio.CancelledError:
                    pass

        f._ensure_started()          # real queue to snapshot
        f._task.cancel()             # replace with the slow stand-in
        f._task = asyncio.create_task(slow_dispatcher())
        await asyncio.sleep(0)

        async def submit_mid_stop():
            await unwound.wait()
            f._ensure_started()      # respawn: NEW queue
            fut = asyncio.get_event_loop().create_future()
            f._q.put_nowait(_Item("hash", b"x", fut, None))
            return fut

        st = asyncio.create_task(submit_mid_stop())
        await f.stop()
        fut = await st
        # the respawned dispatcher owns that item now: it must be
        # served normally (host-path digest), NEVER failed with
        # stop()'s "feeder stopped" drain
        for _ in range(100):
            if fut.done():
                break
            await asyncio.sleep(0.01)
        assert fut.done() and fut.exception() is None, \
            "stop() drained the respawned queue"
        assert fut.result() == blake3sum(b"x")
        await f.stop()               # clean shutdown of the respawn

    run(go())
