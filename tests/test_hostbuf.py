"""Zero-copy PUT ingest (ISSUE 17): pinned host-buffer pool semantics,
stripe-layout byte parity, the batched SHA-256 lanes, and the
aws-chunked reader's zero-copy (readinto1) decode path.

Unit-level against fakes — the end-to-end copy/efficiency claims live
in bench_put_path and script/device_smoke.py.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import os
import types

import numpy as np
import pytest

from garage_tpu.block.hostbuf import BlockLease, HostBufPool


def run(coro):
    return asyncio.run(coro)


# ---- pool semantics ------------------------------------------------------


def test_pool_exhaustion_parks_and_fifo_handoff():
    pool = HostBufPool(k=4, block_size=1024, count=2)

    async def main():
        a = await pool.acquire()
        b = await pool.acquire()
        assert pool.outstanding() == 2 and not pool._free
        order: list[str] = []

        async def waiter(tag: str):
            lease = await pool.acquire()
            order.append(tag)
            return lease

        w1 = asyncio.create_task(waiter("first"))
        w2 = asyncio.create_task(waiter("second"))
        await asyncio.sleep(0)  # both park: the pool is dry
        assert not w1.done() and not w2.done()
        assert pool.stats()["waiters"] == 2
        a.release()  # hands a's buffer to w1 directly
        b.release()
        l1, l2 = await w1, await w2
        assert order == ["first", "second"]  # FIFO, no barging
        # handoff never touched the free list
        assert pool.outstanding() == 2
        l1.release()
        l2.release()
        assert pool.outstanding() == 0 and len(pool._free) == 2

    run(main())


def test_pool_release_is_idempotent_and_conserves():
    pool = HostBufPool(k=2, block_size=64, count=1)

    async def main():
        lease = await pool.acquire()
        lease.release()
        lease.release()  # abort paths double-release without electing an owner
        lease.release()
        assert pool.outstanding() == 0
        assert len(pool._free) == 1  # buffer returned exactly once
        again = await pool.acquire()
        assert pool.outstanding() == 1
        again.release()

    run(main())


def test_pool_cancelled_waiter_skipped_no_leak():
    pool = HostBufPool(k=2, block_size=64, count=1)

    async def main():
        held = await pool.acquire()
        w1 = asyncio.create_task(pool.acquire())
        w2 = asyncio.create_task(pool.acquire())
        await asyncio.sleep(0)
        w1.cancel()
        await asyncio.gather(w1, return_exceptions=True)
        held.release()  # must skip the dead waiter, wake w2
        lease = await asyncio.wait_for(w2, 1.0)
        assert pool.outstanding() == 1
        lease.release()
        assert pool.outstanding() == 0

    run(main())


# ---- stripe layout parity ------------------------------------------------


def fill_lease(lease: BlockLease, body: bytes, scheme: int) -> None:
    mv = lease.body_mv()
    mv[:len(body)] = body
    lease.length = len(body)
    lease.set_scheme(scheme)


def test_stripe_view_matches_split_stripe():
    from garage_tpu.ops import rs

    k, block_size = 4, 1000
    pool = HostBufPool(k=k, block_size=block_size, count=1)
    lease = pool.try_acquire()
    body = os.urandom(block_size)
    fill_lease(lease, body, scheme=0x01)
    assert lease.full and lease.total_len == 1 + block_size
    want = np.asarray(rs.split_stripe(b"\x01" + body, k))
    got = lease.stripe()
    assert got.shape == want.shape
    assert bytes(got.tobytes()) == bytes(want.tobytes())
    # view() is exactly the body, without the scheme byte
    assert bytes(lease.view()) == body
    lease.release()


def test_stripe_tail_stays_zero_across_reuse():
    """stripe() relies on the reshape tail (< k bytes past the scheme +
    cap region) staying zero for the pool's LIFETIME — a short body on
    reuse must not inherit stale bytes in the padded region it never
    wrote (view/total_len bound what later stages read)."""
    k, block_size = 4, 1001
    pool = HostBufPool(k=k, block_size=block_size, count=1)
    tail = pool.slen * k - (1 + block_size)
    lease = pool.try_acquire()
    fill_lease(lease, b"\xff" * block_size, scheme=0xAA)
    if tail:
        assert not lease.buf[1 + block_size:].any()
    lease.release()
    again = pool.try_acquire()
    fill_lease(again, b"\x00" * 10, scheme=0x00)
    assert again.total_len == 11
    # the unwritten body region may hold stale 0xff — but the consumers
    # of a PARTIAL block (view/total_len) never read past length
    assert bytes(again.view()) == b"\x00" * 10
    again.release()


# ---- batched SHA-256 (ops/sha256) ----------------------------------------


def test_sha256_kernel_matches_hashlib_boundaries():
    from garage_tpu.ops import sha256 as sha

    cases = [b"", b"a", b"x" * 55, b"y" * 56, b"z" * 63, b"w" * 64,
             os.urandom(65), os.urandom(1000), os.urandom(64 * 1024 + 7)]
    got = sha.sha256_hex_many(cases)
    want = [hashlib.sha256(c).hexdigest() for c in cases]
    assert got == want


def test_sha256_span_lists_hash_as_one_message():
    from garage_tpu.ops import sha256 as sha

    blob = os.urandom(200_000)
    spans = [memoryview(blob)[0:70_000], memoryview(blob)[70_000:70_001],
             memoryview(blob)[70_001:200_000]]
    assert sha.part_len(spans) == len(blob)
    assert sha.sha256_hex_py(spans) == hashlib.sha256(blob).hexdigest()
    got = sha.sha256_hex_many([spans, blob, [b"ab", b"", b"cd"]])
    assert got == [hashlib.sha256(blob).hexdigest(),
                   hashlib.sha256(blob).hexdigest(),
                   hashlib.sha256(b"abcd").hexdigest()]


# ---- feeder sha256 lane --------------------------------------------------


def _stub_feeder(max_batch: int = 8):
    from garage_tpu.block.device_backend import StubDeviceBackend
    from garage_tpu.block.feeder import DeviceFeeder

    stub = StubDeviceBackend(None, h2d_gbps=1e6, compute_gbps=1e6,
                             d2h_gbps=1e6)
    f = DeviceFeeder(mode="require", max_batch=max_batch, backend=stub)
    f._device_ok = True
    return f


def test_feeder_sha256_host_floor_when_alone():
    f = _stub_feeder()
    blob = os.urandom(100_000)

    async def main():
        assert f.active_streams == 0  # lone caller: host floor
        out = await f.sha256_hex(blob)
        assert out == hashlib.sha256(blob).hexdigest()
        assert f.stats["device_items"] == 0
        assert ("sha256", "host") in f._perf

    run(main())


def test_feeder_sha256_concurrent_streams_batch_on_device():
    f = _stub_feeder()
    blobs = [os.urandom(80_000 + i) for i in range(4)]

    async def main():
        f.active_streams = 4
        try:
            outs = await asyncio.gather(*[f.sha256_hex(b) for b in blobs])
        finally:
            await f.stop()
        assert outs == [hashlib.sha256(b).hexdigest() for b in blobs]
        assert f.stats["device_items"] == 4
        # the linger window coalesced the four lanes into one launch
        assert f.stats["device_batches"] <= 2

    run(main())


def test_feeder_sha256_accepts_span_lists():
    f = _stub_feeder()
    blob = os.urandom(150_000)
    spans = [memoryview(blob)[:50_000], memoryview(blob)[50_000:]]

    async def main():
        f.active_streams = 2
        try:
            out = await f.sha256_hex(spans)
        finally:
            await f.stop()
        assert out == hashlib.sha256(blob).hexdigest()
        assert f.stats["device_items"] == 1

    run(main())


def test_batch_linger_knob_plumbed_from_config():
    from garage_tpu.block.feeder import DeviceFeeder

    assert DeviceFeeder(mode="off").batch_linger == pytest.approx(0.006)
    cfg = types.SimpleNamespace(batch_linger_ms=25)
    assert DeviceFeeder(
        mode="off", tpu_cfg=cfg).batch_linger == pytest.approx(0.025)
    off = types.SimpleNamespace(batch_linger_ms=0)
    assert DeviceFeeder(mode="off", tpu_cfg=off).batch_linger == 0.0


# ---- aws-chunked zero-copy decode (readinto1) ----------------------------


class ListBody:
    """BodyReader stand-in: read() yields preset chunks; readinto1
    lands at most `max_span` bytes per call (short socket reads)."""

    def __init__(self, chunks, max_span: int = 1 << 30):
        self.buf = bytearray(b"".join(chunks))
        self.max_span = max_span

    async def read(self, n: int = 65536) -> bytes:
        out = bytes(self.buf[:n])
        del self.buf[:n]
        return out

    async def readinto1(self, mv: memoryview) -> int:
        n = min(len(mv), len(self.buf), self.max_span)
        mv[:n] = self.buf[:n]
        del self.buf[:n]
        return n

    async def drain(self):
        self.buf = bytearray()


def _chunked_wire(chunks, secret="secret", region="garage",
                  amz_date="20260806T000000Z", scope_date="20260806",
                  corrupt_at=None):
    from garage_tpu.api.signature import VerifiedRequest, signing_key

    sk = signing_key(secret, scope_date, region)
    seed = "0" * 64
    scope = f"{scope_date}/{region}/s3/aws4_request"
    prev = seed
    wire = b""
    empty = hashlib.sha256(b"").hexdigest()
    for i, c in enumerate(list(chunks) + [b""]):
        sts = "\n".join(["AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev,
                         empty, hashlib.sha256(c).hexdigest()])
        sig = hmac.new(sk, sts.encode(), hashlib.sha256).hexdigest()
        prev = sig
        if corrupt_at is not None and i == corrupt_at:
            sig = "f" * 64
        wire += b"%x;chunk-signature=%s\r\n" % (len(c), sig.encode())
        wire += c + b"\r\n" if c else b"\r\n"
    v = VerifiedRequest("key", "STREAMING-AWS4-HMAC-SHA256-PAYLOAD",
                        seed, scope_date, sk, False)
    return wire, v, amz_date


async def _drain_readinto1(reader, window: int) -> bytes:
    """Pull the whole decoded body through readinto1 using successive
    `window`-sized destination buffers — the Chunker's access pattern
    (each buffer a leased block)."""
    out = bytearray()
    buf = bytearray(window)
    off = 0
    while True:
        n = await reader.readinto1(memoryview(buf)[off:])
        if n == 0:
            out.extend(buf[:off])
            return bytes(out)
        off += n
        if off == window:
            out.extend(buf)  # "hand off the lease"
            buf = bytearray(window)
            off = 0


def test_readinto1_parity_with_read_path():
    from garage_tpu.api.signature import AwsChunkedReader

    chunks = [os.urandom(150_000), os.urandom(80_000), b"tail"]
    body = b"".join(chunks)

    async def main():
        for window in (256 * 1024, 100_000, 7_777):
            wire, v, amz = _chunked_wire(chunks)
            r = AwsChunkedReader(ListBody([wire], max_span=61_440), v,
                                 "garage", amz, signed=True)
            assert await _drain_readinto1(r, window) == body

    run(main())


def test_readinto1_chunk_crossing_lease_boundary_folds_and_verifies():
    """A chunk that outlives its destination buffer folds its spans
    into a host hasher at the handoff — the signature still verifies
    even though the landed bytes are recycled before chunk end."""
    from garage_tpu.api.signature import AwsChunkedReader

    chunks = [os.urandom(190_000)]  # crosses a 128 KiB window

    async def main():
        wire, v, amz = _chunked_wire(chunks)
        r = AwsChunkedReader(ListBody([wire], max_span=50_000), v,
                             "garage", amz, signed=True)
        got = await _drain_readinto1(r, 128 * 1024)
        assert got == chunks[0]
        assert r._chunk_hasher is None and not r._chunk_spans

    run(main())


def test_readinto1_forged_chunk_403s_before_body_completes():
    from garage_tpu.api.http import HttpError
    from garage_tpu.api.signature import AwsChunkedReader

    async def main():
        for corrupt_at in (0, 1):
            chunks = [os.urandom(90_000), os.urandom(40_000)]
            wire, v, amz = _chunked_wire(chunks, corrupt_at=corrupt_at)
            r = AwsChunkedReader(ListBody([wire], max_span=30_000), v,
                                 "garage", amz, signed=True)
            with pytest.raises(HttpError) as ei:
                await _drain_readinto1(r, 256 * 1024)
            assert ei.value.status == 403

    run(main())


def test_readinto1_whole_chunk_rides_feeder_sha_lane():
    """A chunk wholly resident in the live lease hands its span list to
    the feeder (batched device sha256); a boundary-crossing chunk does
    not (its bytes are folded host-side at the handoff)."""
    from garage_tpu.api.signature import AwsChunkedReader

    calls: list[int] = []

    class FakeFeeder:
        async def sha256_hex(self, data):
            from garage_tpu.ops import sha256 as sha

            calls.append(sha.part_len(data))
            return sha.sha256_hex_py(data)

    async def main():
        chunks = [os.urandom(100_000), os.urandom(100_000)]
        wire, v, amz = _chunked_wire(chunks)
        r = AwsChunkedReader(ListBody([wire], max_span=61_440), v,
                             "garage", amz, signed=True,
                             feeder=FakeFeeder())
        # window holds each whole chunk: both hashes ride the feeder
        got = await _drain_readinto1(r, 100_000)
        assert got == b"".join(chunks)
        assert calls == [100_000, 100_000]
        calls.clear()
        wire, v, amz = _chunked_wire(chunks)
        r = AwsChunkedReader(ListBody([wire], max_span=61_440), v,
                             "garage", amz, signed=True,
                             feeder=FakeFeeder())
        # 150 KiB windows split chunk 2 across leases: only chunk 1
        # rides the feeder, chunk 2 folds host-side — still verifies
        got = await _drain_readinto1(r, 150_000)
        assert got == b"".join(chunks)
        assert calls == [100_000]

    run(main())


# ---- cache tier local-owner shortcut -------------------------------------


def _tier(me: bytes, members: list[bytes], max_bytes: int = 1 << 20):
    from garage_tpu.block.cache_tier import ClusterCacheTier

    manager = types.SimpleNamespace(
        cache=types.SimpleNamespace(max_bytes=max_bytes),
        system=types.SimpleNamespace(id=me))
    tier = ClusterCacheTier.__new__(ClusterCacheTier)
    tier.manager = manager
    tier.enabled = True
    tier.members = lambda: members
    return tier


def test_local_owner_true_only_on_real_multinode_ownership():
    from garage_tpu.gateway.ring import rendezvous_owner

    nodes = [bytes([i]) * 32 for i in range(4)]
    h_mine = None
    h_other = None
    for i in range(256):
        h = hashlib.sha256(bytes([i])).digest()
        if rendezvous_owner(nodes, h) == nodes[0]:
            h_mine = h_mine or h
        else:
            h_other = h_other or h
    tier = _tier(nodes[0], nodes)
    assert tier.local_owner(h_mine) is True
    assert tier.local_owner(h_other) is False
    # moot routing is False here (distinct from owns())
    assert _tier(nodes[0], [nodes[0]]).local_owner(h_mine) is False
    assert _tier(nodes[0], nodes, max_bytes=0).local_owner(h_mine) is False
    off = _tier(nodes[0], nodes)
    off.enabled = False
    assert off.local_owner(h_mine) is False


# ---- resync rebalance scoping (satellite: moved-partition diff) ----------


@pytest.mark.slow
def test_moved_partitions_scopes_rebalance_to_the_diff(tmp_path):
    """A +1-node resize moves a strict subset of the 256 partitions;
    _moved_partitions returns exactly the rows whose placement tuples
    changed, and falls back to None (full scan) whenever the diff
    cannot be computed soundly."""
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from test_model import make_garage_cluster, stop_all, wait_until

    async def main():
        net, garages, tasks = await make_garage_cluster(
            tmp_path, n=4, rf=3, storage=[0, 1, 2])
        try:
            from garage_tpu.rpc.layout import NodeRole

            lm = garages[0].system.layout_manager
            lm.history.stage_role(garages[3].system.id,
                                  NodeRole(zone="z1", capacity=1 << 30))
            lm.apply_staged(None)
            assert await wait_until(
                lambda: lm.history.current().version == 2)

            rsync = garages[0].block_manager.resync
            moved = rsync._moved_partitions(2, 1)
            assert moved is not None
            assert 0 < len(moved) < 256  # a resize, not a rebuild
            old = lm.history.get_version(1)
            new = lm.history.get_version(2)
            for p in range(256):
                changed = tuple(old.nodes_of(p)) != tuple(new.nodes_of(p))
                assert (p in moved) == changed

            # unsound diffs degrade to full scans, never to skipping
            assert rsync._moved_partitions(2, None) is None
            assert rsync._moved_partitions(2, 2) is None
            assert rsync._moved_partitions(2, 99) is None  # GC'd/unknown
        finally:
            await stop_all(garages, tasks)

    asyncio.run(asyncio.wait_for(main(), 120))
