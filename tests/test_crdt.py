"""CRDT property tests: merge must be commutative, associative, idempotent.

Mirrors the reference's reliance on CRDT semantics (src/util/crdt/) and the
survey's recommendation of property-based merge tests (SURVEY.md §5.2).
"""

import random

from garage_tpu.utils.crdt import Bool, CrdtMap, Deletable, Lww, LwwMap


def random_lww(rng):
    return Lww(rng.randint(0, 5), rng.randint(0, 100))


def random_lwwmap(rng):
    m = LwwMap()
    for _ in range(rng.randint(0, 6)):
        k = rng.choice("abcd")
        m = LwwMap({k: random_lww(rng)}).merge(m)
    return m


def random_crdtmap(rng):
    m = CrdtMap()
    for _ in range(rng.randint(0, 6)):
        m = m.put(rng.choice("abcd"), random_lww(rng))
    return m


GENS = [random_lww, random_lwwmap, random_crdtmap,
        lambda rng: Bool(rng.random() < 0.5),
        lambda rng: Deletable(None if rng.random() < 0.3 else random_lww(rng))]


def test_merge_laws():
    rng = random.Random(1234)
    for gen in GENS:
        for _ in range(200):
            a, b, c = gen(rng), gen(rng), gen(rng)
            assert a.merge(b) == b.merge(a), f"commutativity: {gen.__name__}"
            assert a.merge(b).merge(c) == a.merge(b.merge(c)), "associativity"
            assert a.merge(a) == a, "idempotence"


def test_lww_update_monotonic():
    a = Lww.new("x", ts=1000)
    b = a.update("y")
    assert b.ts > a.ts
    assert a.merge(b).value == "y"
    assert b.merge(a).value == "y"


def test_lwwmap_insert_wins():
    m = LwwMap().insert("k", 1)
    m2 = m.insert("k", 2)
    assert m.merge(m2).get("k") == 2
    assert m2.merge(m).get("k") == 2


def test_bool_true_wins():
    assert Bool(False).merge(Bool(True)).value is True


def test_deletable_delete_wins():
    d = Deletable.present(Lww(1, "v")).merge(Deletable.deleted())
    assert d.is_deleted
