"""Span system: nesting, context propagation, RPC trace-id on the wire.

Ref parity: the reference's OTLP span topology
(src/rpc/rpc_helper.rs:172-190, src/api/s3/put.rs:395-452); here spans
land in tracer.ring / a JSONL file instead of a collector.
"""

from __future__ import annotations

import asyncio
import os
import shutil
import sys
import tempfile

import pytest

from garage_tpu.utils import tracing
from garage_tpu.utils.tracing import span, tracer

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture()
def ring_tracer():
    tracer.enabled = True
    tracer.ring.clear()
    yield tracer
    tracer.enabled = False
    tracer.ring.clear()


def test_span_nesting_and_ids(ring_tracer):
    with span("outer", foo=1):
        with span("inner"):
            pass
    recs = list(tracer.ring)
    assert [r["name"] for r in recs] == ["inner", "outer"]
    inner, outer = recs
    assert inner["trace"] == outer["trace"]
    assert inner["parent"] == outer["span"]
    assert outer["parent"] is None
    assert outer["attrs"] == {"foo": 1}
    assert outer["dur_us"] >= inner["dur_us"]


def test_span_disabled_is_noop():
    tracer.enabled = False
    tracer.ring.clear()
    with span("nope"):
        pass
    assert not tracer.ring


def test_span_async_context_flows_across_tasks(ring_tracer):
    async def go():
        async with span("root"):
            async def child():
                with span("child"):
                    pass
            await asyncio.gather(child(), child())

    asyncio.run(go())
    recs = {r["name"]: r for r in tracer.ring}
    root = [r for r in tracer.ring if r["name"] == "root"][0]
    childs = [r for r in tracer.ring if r["name"] == "child"]
    assert len(childs) == 2
    assert all(c["trace"] == root["trace"] for c in childs)
    assert all(c["parent"] == root["span"] for c in childs)


def test_trace_id_propagates_over_rpc(ring_tracer):
    """A block put on a loopback cluster produces remote-side spans
    carrying the same trace id as the caller's root span."""
    import bench
    from garage_tpu.rpc import ReplicationMode
    from garage_tpu.utils.data import blake3sum

    async def go():
        tmp = tempfile.mkdtemp(prefix="gt_trace_")
        try:
            rm = ReplicationMode.parse(3, erasure="4,2")
            systems, managers, tasks = await bench._build_cluster(
                tmp, 6, rm, "off")
            data = os.urandom(1 << 18)
            h = blake3sum(data)
            async with span("test.root"):
                await managers[0].rpc_put_block(h, data)
            await bench._teardown(systems, managers, tasks)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    asyncio.run(go())
    recs = list(tracer.ring)
    root = [r for r in recs if r["name"] == "test.root"][0]
    same_trace = [r for r in recs if r["trace"] == root["trace"]]
    names = {r["name"] for r in same_trace}
    # caller side
    assert {"block.put", "block.encode", "block.write_shards",
            "rpc.call"} <= names
    # remote handler side: block.remote spans? the server-side write has
    # no span of its own, but the rpc.call spans from the caller and the
    # remote-context adoption are visible via at least k+m rpc.call spans
    assert sum(1 for r in same_trace if r["name"] == "rpc.call") >= 5


def test_jsonl_export(tmp_path, ring_tracer):
    path = str(tmp_path / "spans.jsonl")
    tracer.enable(path)
    with span("exported"):
        pass
    tracer.disable()
    tracer.enabled = True  # restore for fixture teardown symmetry
    import json

    lines = [json.loads(line) for line in open(path)]
    assert any(r["name"] == "exported" for r in lines)


def test_otlp_export_to_local_collector(tmp_path):
    """Spans ship to an OTLP/HTTP collector as valid OTLP JSON with
    wire-width ids (ref: garage/tracing_setup.rs init_tracing)."""
    import http.server
    import json
    import threading

    from garage_tpu.utils import otlp as otlp_mod
    from garage_tpu.utils.otlp import OtlpExporter
    from garage_tpu.utils.tracing import span, tracer

    received = []

    class Sink(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append((self.path, json.loads(body)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Sink)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        exp = OtlpExporter(f"http://127.0.0.1:{srv.server_port}",
                           "0011223344556677").start()
        was_enabled = tracer.enabled
        tracer.sinks.append(exp.sink)
        tracer.enabled = True
        try:
            with span("otlp.parent", table="objtest"):
                with span("otlp.child", size=123):
                    pass
                with span("otlp.bad"):
                    try:
                        raise ValueError("boom")
                    except ValueError:
                        pass
        finally:
            tracer.enabled = was_enabled
            tracer.sinks.remove(exp.sink)
        exp.stop()
        assert exp.sent_spans == 3 and exp.failed_posts == 0
        path, payload = received[0]
        assert path == "/v1/traces"
        rs = payload["resourceSpans"][0]
        res_attrs = {a["key"]: a["value"] for a in
                     rs["resource"]["attributes"]}
        assert res_attrs["service.name"]["stringValue"] == "garage"
        assert res_attrs["service.instance.id"]["stringValue"] \
            == "0011223344556677"
        spans = rs["scopeSpans"][0]["spans"]
        by_name = {s["name"]: s for s in spans}
        assert set(by_name) == {"otlp.parent", "otlp.child", "otlp.bad"}
        parent = by_name["otlp.parent"]
        child = by_name["otlp.child"]
        assert len(parent["traceId"]) == 32 and len(parent["spanId"]) == 16
        assert child["traceId"] == parent["traceId"]
        assert child["parentSpanId"] == parent["spanId"]
        assert int(child["endTimeUnixNano"]) >= int(
            child["startTimeUnixNano"])
        attrs = {a["key"]: a["value"] for a in child["attributes"]}
        assert attrs["size"]["intValue"] == "123"
    finally:
        srv.shutdown()


def test_otlp_collector_down_never_blocks(tmp_path):
    """A dead collector drops spans; emit() and stop() stay cheap."""
    from garage_tpu.utils.otlp import OtlpExporter

    exp = OtlpExporter("http://127.0.0.1:9", "00").start()  # discard port
    for i in range(10):
        exp.sink({"trace": "ab", "span": "cd", "parent": None,
                  "name": f"s{i}", "start_us": 1, "dur_us": 1})
    exp.stop()
    assert exp.sent_spans == 0 and exp.failed_posts >= 1
