"""Online repair procedures (ref: src/garage/repair/online.rs).

Inject dangling versions / block refs / multipart uploads into a live
single-node cluster, run the repair workers, verify cleanup.
"""

import asyncio

from garage_tpu.model.repair import (BlockRcRepair, RepairBlockRefs,
                                     RepairMpu, RepairVersions)
from garage_tpu.model.s3 import (BlockRef, MultipartUpload, Object,
                                 ObjectVersion, ObjectVersionState, Version,
                                 object_upload_version)
from garage_tpu.model.s3.version_table import BACKLINK_MPU, BACKLINK_OBJECT
from garage_tpu.utils.background import WState
from garage_tpu.utils.crdt import now_msec
from garage_tpu.utils.data import blake2sum, gen_uuid

from test_model import make_garage_cluster, stop_all, wait_until  # noqa


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def drain(worker, max_steps=200):
    for _ in range(max_steps):
        if await worker.work() == WState.DONE:
            return
    raise AssertionError(f"{worker.name} did not finish")


def test_repair_versions_tombstones_orphan(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=1, rf=1)
        g = garages[0]
        try:
            bucket_id = gen_uuid()
            # live version whose object row does not exist -> orphan
            orphan = Version.new(gen_uuid(),
                                 (BACKLINK_OBJECT, bucket_id, "ghost"))
            await g.version_table.insert(orphan)
            # version properly referenced by an uploading object -> kept
            ok_uuid = gen_uuid()
            up = object_upload_version(bucket_id, "live", ok_uuid, {})
            await g.object_table.insert(up)
            held = Version.new(ok_uuid, (BACKLINK_OBJECT, bucket_id, "live"))
            await g.version_table.insert(held)

            await drain(RepairVersions(g))
            v1 = await g.version_table.get(orphan.uuid, b"")
            assert v1.deleted.value
            v2 = await g.version_table.get(ok_uuid, b"")
            assert not v2.deleted.value
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_repair_block_refs_and_rc(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=1, rf=1)
        g = garages[0]
        try:
            h = blake2sum(b"data")
            # ref to a version that never existed
            await g.block_ref_table.insert(BlockRef.new(h, gen_uuid()))
            assert g.block_manager.rc.is_needed(h)

            await drain(RepairBlockRefs(g))
            refs = [g.block_ref_table.data.decode_stored(raw)
                    for raw in g.block_ref_table.data.read_range(
                        h, None, None, 10)]
            assert refs and all(r.deleted.value for r in refs)

            # rc repair: corrupt the refcount, recalculation heals it
            def corrupt(tx):
                tx.insert(g.block_manager.rc.tree, h,
                          g.block_manager.rc._pack_count(42))

            g.db.transaction(corrupt)
            assert g.block_manager.rc.is_needed(h)
            await drain(BlockRcRepair(g))
            assert not g.block_manager.rc.is_needed(h)
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_repair_mpu_tombstones_orphan(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=1, rf=1)
        g = garages[0]
        try:
            bucket_id = gen_uuid()
            upload_id = gen_uuid()
            mpu = MultipartUpload.new(upload_id, now_msec(), bucket_id,
                                      "gone-key")
            await g.mpu_table.insert(mpu)
            await drain(RepairMpu(g))
            got = await g.mpu_table.get(upload_id, b"")
            assert got.deleted.value
        finally:
            await stop_all(garages, tasks)

    run(main())
