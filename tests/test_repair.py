"""Online repair procedures (ref: src/garage/repair/online.rs).

Inject dangling versions / block refs / multipart uploads into a live
single-node cluster, run the repair workers, verify cleanup.
"""

import asyncio

from garage_tpu.model.repair import (BlockRcRepair, RepairBlockRefs,
                                     RepairMpu, RepairVersions)
from garage_tpu.model.s3 import (BlockRef, MultipartUpload, Object,
                                 ObjectVersion, ObjectVersionState, Version,
                                 object_upload_version)
from garage_tpu.model.s3.version_table import BACKLINK_MPU, BACKLINK_OBJECT
from garage_tpu.utils.background import WState
from garage_tpu.utils.crdt import now_msec
from garage_tpu.utils.data import blake2sum, gen_uuid

from test_model import make_garage_cluster, stop_all, wait_until  # noqa


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def drain(worker, max_steps=200):
    for _ in range(max_steps):
        if await worker.work() == WState.DONE:
            return
    raise AssertionError(f"{worker.name} did not finish")


def test_repair_versions_tombstones_orphan(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=1, rf=1)
        g = garages[0]
        try:
            bucket_id = gen_uuid()
            # live version whose object row does not exist -> orphan
            orphan = Version.new(gen_uuid(),
                                 (BACKLINK_OBJECT, bucket_id, "ghost"))
            await g.version_table.insert(orphan)
            # version properly referenced by an uploading object -> kept
            ok_uuid = gen_uuid()
            up = object_upload_version(bucket_id, "live", ok_uuid, {})
            await g.object_table.insert(up)
            held = Version.new(ok_uuid, (BACKLINK_OBJECT, bucket_id, "live"))
            await g.version_table.insert(held)

            await drain(RepairVersions(g))
            v1 = await g.version_table.get(orphan.uuid, b"")
            assert v1.deleted.value
            v2 = await g.version_table.get(ok_uuid, b"")
            assert not v2.deleted.value
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_repair_block_refs_and_rc(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=1, rf=1)
        g = garages[0]
        try:
            h = blake2sum(b"data")
            # ref to a version that never existed
            await g.block_ref_table.insert(BlockRef.new(h, gen_uuid()))
            assert g.block_manager.rc.is_needed(h)

            await drain(RepairBlockRefs(g))
            refs = [g.block_ref_table.data.decode_stored(raw)
                    for raw in g.block_ref_table.data.read_range(
                        h, None, None, 10)]
            assert refs and all(r.deleted.value for r in refs)

            # rc repair: corrupt the refcount, recalculation heals it
            def corrupt(tx):
                tx.insert(g.block_manager.rc.tree, h,
                          g.block_manager.rc._pack_count(42))

            g.db.transaction(corrupt)
            assert g.block_manager.rc.is_needed(h)
            await drain(BlockRcRepair(g))
            assert not g.block_manager.rc.is_needed(h)
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_repair_mpu_tombstones_orphan(tmp_path):
    async def main():
        net, garages, tasks = await make_garage_cluster(tmp_path, n=1, rf=1)
        g = garages[0]
        try:
            bucket_id = gen_uuid()
            upload_id = gen_uuid()
            mpu = MultipartUpload.new(upload_id, now_msec(), bucket_id,
                                      "gone-key")
            await g.mpu_table.insert(mpu)
            await drain(RepairMpu(g))
            got = await g.mpu_table.get(upload_id, b"")
            assert got.deleted.value
        finally:
            await stop_all(garages, tasks)

    run(main())


def test_rebalance_worker_moves_blocks_to_new_primary(tmp_path):
    """Multi-HDD layout change: RebalanceWorker moves stored files to
    their new primary dir and drops strays (ref: repair.rs:531-640)."""
    import asyncio
    import os

    from garage_tpu.block import BlockManager, DataLayout
    from garage_tpu.block.block import DataBlock
    from garage_tpu.block.layout import DataDir
    from garage_tpu.block.rc import BlockRc
    from garage_tpu.block.repair import RebalanceWorker
    from garage_tpu.block.resync import BlockResyncManager
    from garage_tpu.db import open_db
    from garage_tpu.utils.background import WState
    from garage_tpu.utils.data import blake3sum

    class _Sys:
        id = b"\x01" * 32
        meta_dir = str(tmp_path)

        class netapp:
            @staticmethod
            def endpoint(path):
                class E:
                    def set_handler(self, h):
                        return self

                return E()

    d1, d2 = str(tmp_path / "hdd1"), str(tmp_path / "hdd2")
    db = open_db(str(tmp_path / "db"), engine="memory")
    m = BlockManager.__new__(BlockManager)
    m.system = _Sys()
    m.db = db
    m.data_layout = DataLayout.initialize([DataDir(d1, 100)])
    m.compression = False
    m.fsync = False
    m.rc = BlockRc(db)
    from garage_tpu.block.codec import ReplicateCodec

    m.codec = ReplicateCodec(1)
    m.metrics = {"bytes_read": 0, "bytes_written": 0, "corruptions": 0,
                 "resync_sent": 0, "resync_recv": 0}
    m.resync = BlockResyncManager(m, db)

    blobs = [os.urandom(5000) for _ in range(24)]
    hashes = [blake3sum(b) for b in blobs]
    for h, b in zip(hashes, blobs):
        m.write_local(h, DataBlock.plain(b).pack())

    # add a second drive with most of the capacity: many primaries move
    m.data_layout = m.data_layout.update_dirs(
        [DataDir(d1, 100), DataDir(d2, 900)])
    moved_expected = [h for h in hashes
                      if not m.data_layout.block_path(h).startswith(d1)]
    assert moved_expected, "layout change should move some primaries"
    # reads still work through the secondary dirs before rebalance
    for h, b in zip(hashes, blobs):
        assert DataBlock.unpack(m.read_local(h)).plain_bytes() == b

    async def run_worker():
        w = RebalanceWorker(m)
        while await w.work() is not WState.DONE:
            pass
        return w

    w = asyncio.run(run_worker())
    assert w.moved == len(moved_expected)
    for h, b in zip(hashes, blobs):
        primary = m.data_layout.block_path(h)
        assert os.path.exists(primary), h.hex()
        assert DataBlock.unpack(m.read_local(h)).plain_bytes() == b
    # second pass is a no-op
    w2 = asyncio.run(run_worker())
    assert w2.moved == 0
