"""Table engine tests: local CRDT storage, Merkle trie, quorum ops over a
3-node loopback cluster, anti-entropy sync, tombstone GC.

Mirrors the reference strategy (SURVEY.md §4): real multi-node semantics
in one process via the deterministic in-process transport.
"""

import asyncio

from garage_tpu.db import open_db
from garage_tpu.net import LocalNetwork, NetApp
from garage_tpu.rpc import ReplicationMode, RpcHelper, System
from garage_tpu.rpc.layout import NodeRole
from garage_tpu.table import (
    Entry,
    Table,
    TableFullReplication,
    TableSchema,
    TableShardedReplication,
)
from garage_tpu.table.data import TableData
from garage_tpu.table.merkle import MerkleUpdater
from garage_tpu.table.schema import tree_key
from garage_tpu.utils import migrate
from garage_tpu.utils.background import BackgroundRunner
from garage_tpu.utils.crdt import Lww

NETID = b"table-test"


def run(coro, timeout=60.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# ---- a tiny test schema: last-writer-wins kv with tombstones -----------


class KvEntry(Entry):
    VERSION_MARKER = b"TKv1"

    def __init__(self, pk: bytes, sk: bytes, value: Lww):
        self.pk, self.sk, self.value = pk, sk, value

    @classmethod
    def new(cls, pk, sk, value, ts=None):
        return cls(pk, sk, Lww.new(value, ts))

    def partition_key(self):
        return self.pk

    def sort_key(self):
        return self.sk

    def merge(self, other):
        return KvEntry(self.pk, self.sk, self.value.merge(other.value))

    def is_tombstone(self):
        return self.value.value is None

    def pack(self):
        return [self.pk, self.sk, self.value.pack()]

    @classmethod
    def unpack(cls, raw):
        return cls(raw[0], raw[1], Lww.unpack(raw[2]))


class KvSchema(TableSchema):
    TABLE_NAME = "kv"
    ENTRY = KvEntry

    def __init__(self):
        self.trigger_log = []

    def updated(self, tx, old, new):
        self.trigger_log.append((old, new))


# ---- local-only tests --------------------------------------------------


class _FakeRepl:
    def partition_of(self, h):
        return h[0]

    def storage_nodes(self, h):
        return [b"me"]


def make_data(tmp_path, name="kv", engine="memory"):
    db = open_db(str(tmp_path / name), engine=engine)
    return TableData(db, KvSchema(), _FakeRepl(), b"me")


def test_local_merge_on_write(tmp_path, db_engine):
    data = make_data(tmp_path, engine=db_engine)
    e1 = KvEntry.new(b"p", b"a", "v1", ts=100)
    e2 = KvEntry.new(b"p", b"a", "v2", ts=200)
    assert data.update_entry_decoded(e1) is not None
    assert data.update_entry_decoded(e2) is not None
    # stale write is a no-op (CRDT merge keeps newest)
    assert data.update_entry_decoded(e1) is None
    stored = data.decode_stored(data.read_entry(b"p", b"a"))
    assert stored.value.value == "v2"
    # triggers saw both effective changes
    assert len(data.schema.trigger_log) == 2


def test_read_range_and_limits(tmp_path, db_engine):
    data = make_data(tmp_path, engine=db_engine)
    for i in range(20):
        data.update_entry_decoded(KvEntry.new(b"p", b"k%02d" % i, i))
    data.update_entry_decoded(KvEntry.new(b"other", b"x", 99))
    rows = data.read_range(b"p", None, None, 5)
    got = [data.decode_stored(r).sk for r in rows]
    assert got == [b"k00", b"k01", b"k02", b"k03", b"k04"]
    rows = data.read_range(b"p", b"k17", None, 10)
    got = [data.decode_stored(r).sk for r in rows]
    assert got == [b"k17", b"k18", b"k19"]
    rows = data.read_range(b"p", None, None, 100, reverse=True)
    assert data.decode_stored(rows[0]).sk == b"k19"


def test_read_range_raw_cursor_pages_without_decode(tmp_path, db_engine):
    """ISSUE 9: the raw-cursor variant pages a partition with sort keys
    sliced off the engine key — no per-row decode — and agrees with the
    decoded read_range. k2v poll_range pages through this."""
    data = make_data(tmp_path, engine=db_engine)
    for i in range(20):
        data.update_entry_decoded(KvEntry.new(b"p", b"k%02d" % i, i))
    data.update_entry_decoded(KvEntry.new(b"other", b"x", 99))

    rows, cur = data.read_range_raw(b"p", None, 5)
    assert [sk for sk, _ in rows] == [b"k00", b"k01", b"k02", b"k03",
                                      b"k04"]
    assert cur == b"k04\x00"
    # resume from the returned cursor; raw values decode identically
    rows2, cur2 = data.read_range_raw(b"p", cur, 100)
    assert [sk for sk, _ in rows2] == [b"k%02d" % i for i in range(5, 20)]
    assert cur2 is None  # range exhausted
    assert [data.decode_stored(v).sk for _, v in rows2] == \
        [sk for sk, _ in rows2]
    # prefix / end bounds match read_range semantics
    rows3, _ = data.read_range_raw(b"p", None, 100, prefix_sk=b"k1",
                                   end_sk=b"k15")
    assert [sk for sk, _ in rows3] == [b"k10", b"k11", b"k12", b"k13",
                                       b"k14"]
    # the sibling partition never bleeds in
    assert all(not sk.startswith(b"x") for sk, _ in rows + rows2)


def test_merkle_root_order_independent(tmp_path, db_engine):
    d1 = make_data(tmp_path, "a", engine=db_engine)
    d2 = make_data(tmp_path, "b", engine=db_engine)
    items = [KvEntry.new(b"p%d" % (i % 3), b"s%d" % i, i, ts=1) for i in range(40)]
    for e in items:
        d1.update_entry_decoded(e)
    for e in reversed(items):
        d2.update_entry_decoded(e)
    m1, m2 = MerkleUpdater(d1), MerkleUpdater(d2)
    for k, v in list(d1.merkle_todo.iter()):
        m1.update_item(k, v)
    for k, v in list(d2.merkle_todo.iter()):
        m2.update_item(k, v)
    assert len(d1.merkle_todo) == 0
    roots1 = {p: m1.root_hash(p) for p in range(256)}
    roots2 = {p: m2.root_hash(p) for p in range(256)}
    assert roots1 == roots2
    assert any(h != b"\x00" * 32 for h in roots1.values())
    # deleting one item changes exactly that partition's root
    e = items[0]
    k = tree_key(e.pk, e.sk)
    p = d1.replication.partition_of(k[:32])
    d1.delete_if_equal_hash(k, __import__("garage_tpu.utils.data", fromlist=["blake2sum"]).blake2sum(d1.read_entry(e.pk, e.sk)))
    for kk, vv in list(d1.merkle_todo.iter()):
        m1.update_item(kk, vv)
    assert m1.root_hash(p) != roots1[p]
    assert all(m1.root_hash(q) == roots1[q] for q in range(256) if q != p)


def test_merkle_update_batch_equals_sequential(tmp_path):
    """The batched trie fold (ISSUE 7) must produce a byte-identical
    merkle tree to one-row-at-a-time update_item: same node set, same
    packed encodings, same roots — the trie shape stays a pure function
    of the key set, whatever the apply order or batching."""
    d_seq = make_data(tmp_path, "seq")
    d_bat = make_data(tmp_path, "bat")
    # inserts, overwrites and deletes across a few partitions
    items = [KvEntry.new(b"p%d" % (i % 5), b"s%04d" % (i % 97), i, ts=i)
             for i in range(300)]
    for e in items:
        d_seq.update_entry_decoded(e)
        d_bat.update_entry_decoded(e)
    # delete a slice so the batch path also exercises tombstone folds
    from garage_tpu.utils.data import blake2sum

    for e in items[:40]:
        raw = d_seq.read_entry(e.pk, e.sk)
        if raw is None:
            continue
        k = tree_key(e.pk, e.sk)
        d_seq.delete_if_equal_hash(k, blake2sum(raw))
        d_bat.delete_if_equal_hash(k, blake2sum(raw))
    m_seq, m_bat = MerkleUpdater(d_seq), MerkleUpdater(d_bat)
    for k, v in list(d_seq.merkle_todo.iter()):
        m_seq.update_item(k, v)
    todo = list(d_bat.merkle_todo.iter())
    for i in range(0, len(todo), 64):
        m_bat.update_batch(todo[i:i + 64])
    assert len(d_bat.merkle_todo) == 0
    tree_seq = list(d_seq.merkle_tree.iter())
    tree_bat = list(d_bat.merkle_tree.iter())
    assert tree_seq == tree_bat
    assert any(tree_seq)  # non-degenerate
    for p in range(256):
        assert m_seq.root_hash(p) == m_bat.root_hash(p)


# ---- cluster tests -----------------------------------------------------


async def make_table_cluster(tmp_path, n=3, rf=3, fullcopy=False,
                             engine="memory"):
    net = LocalNetwork()
    systems, tables, dbs = [], [], []
    for i in range(n):
        app = NetApp(NETID)
        net.register(app)
        meta = str(tmp_path / f"node{i}")
        s = System(app, ReplicationMode.parse(rf), meta,
                   status_interval=0.2, ping_interval=0.2)
        systems.append(s)
    tasks = [asyncio.create_task(s.run()) for s in systems]
    for s in systems[1:]:
        await s.netapp.try_connect(systems[0].netapp.public_addr, systems[0].id)
        s.peering.add_peer(systems[0].netapp.public_addr, systems[0].id)
    deadline = asyncio.get_event_loop().time() + 15
    while asyncio.get_event_loop().time() < deadline:
        if all(len(s.netapp.conns) == n - 1 for s in systems):
            break
        await asyncio.sleep(0.05)
    # flat layout
    lm = systems[0].layout_manager
    for s in systems:
        lm.history.stage_role(s.id, NodeRole(zone="z1", capacity=1 << 30))
    lm.apply_staged(None)
    while asyncio.get_event_loop().time() < deadline:
        if all(s.layout_manager.history.current().version == 1 for s in systems):
            break
        await asyncio.sleep(0.05)
    for i, s in enumerate(systems):
        db = open_db(str(tmp_path / f"node{i}" / "db"), engine=engine)
        dbs.append(db)
        if fullcopy:
            repl = TableFullReplication(s)
        else:
            repl = TableShardedReplication(
                s, s.replication.read_quorum, s.replication.write_quorum
            )
        tables.append(Table(KvSchema(), repl, RpcHelper(s), db))
    return net, systems, tables, tasks


async def stop_all(systems, tasks):
    for s in systems:
        await s.stop()
    for t in tasks:
        t.cancel()


def test_quorum_insert_get(tmp_path, db_engine):
    async def main():
        net, systems, tables, tasks = await make_table_cluster(tmp_path, engine=db_engine)
        try:
            await tables[0].insert(KvEntry.new(b"bucket", b"obj1", "hello"))
            # visible via any node
            got = await tables[2].get(b"bucket", b"obj1")
            assert got is not None and got.value.value == "hello"
            # all three replicas hold it locally (rf=3, 3 nodes); the
            # insert acks at quorum 2/3 and the third write lands in
            # background, so await convergence
            def held():
                return sum(
                    1 for t in tables
                    if t.data.read_entry(b"bucket", b"obj1") is not None
                )

            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline and held() < 3:
                await asyncio.sleep(0.02)
            assert held() == 3
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_insert_tolerates_one_node_down(tmp_path, db_engine):
    async def main():
        net, systems, tables, tasks = await make_table_cluster(tmp_path, engine=db_engine)
        try:
            # kill node 2's transport
            await systems[2].netapp.shutdown()
            await tables[0].insert(KvEntry.new(b"b", b"k", "v"))
            got = await tables[1].get(b"b", b"k")
            assert got is not None and got.value.value == "v"
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_read_repair_heals_divergence(tmp_path, db_engine):
    async def main():
        net, systems, tables, tasks = await make_table_cluster(tmp_path, engine=db_engine)
        try:
            # write divergent values directly into local stores; the newer
            # value is on 2 of 3 replicas so every read quorum (R=2)
            # intersects it
            tables[0].data.update_entry_decoded(KvEntry.new(b"b", b"k", "old", ts=100))
            tables[1].data.update_entry_decoded(KvEntry.new(b"b", b"k", "new", ts=200))
            tables[2].data.update_entry_decoded(KvEntry.new(b"b", b"k", "new", ts=200))
            got = await tables[0].get(b"b", b"k")
            assert got.value.value == "new"
            # read repair runs in background: all nodes converge to "new"
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                vals = [
                    t.data.read_entry(b"b", b"k") for t in tables
                ]
                decoded = [
                    t.data.decode_stored(v).value.value
                    for t, v in zip(tables, vals) if v is not None
                ]
                if decoded.count("new") == 3:
                    break
                await asyncio.sleep(0.05)
            assert decoded.count("new") == 3
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_sync_heals_lagging_node(tmp_path, db_engine):
    async def main():
        net, systems, tables, tasks = await make_table_cluster(tmp_path, engine=db_engine)
        try:
            # node 2 misses 30 writes (applied only on 0 and 1 locally)
            for i in range(30):
                e = KvEntry.new(b"bkt", b"key%d" % i, i, ts=1000 + i)
                tables[0].data.update_entry_decoded(e)
                tables[1].data.update_entry_decoded(e)
            # drain merkle queues
            for t in tables:
                for k, v in list(t.data.merkle_todo.iter()):
                    t.merkle.update_item(k, v)
            from garage_tpu.table.sync import TableSyncer

            syncers = [TableSyncer(t, interval=1e9) for t in tables]
            await syncers[0].sync_all_partitions()
            assert len(tables[2].data.store) == 30
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_gc_three_phase(tmp_path, db_engine):
    async def main():
        net, systems, tables, tasks = await make_table_cluster(tmp_path, engine=db_engine)
        try:
            from garage_tpu.table.gc import TableGc, GcTodoEntry

            gcs = [TableGc(t) for t in tables]
            for t in tables:
                t.data.gc_delay = 0.0  # immediate GC eligibility
            await tables[0].insert(KvEntry.new(b"b", b"k", "v", ts=100))
            # tombstone it
            await tables[0].insert(KvEntry.new(b"b", b"k", None, ts=200))
            # leader enqueued gc todo
            leader_todo = [len(t.data.gc_todo) for t in tables]
            assert sum(leader_todo) >= 1
            for g in gcs:
                await g.work()
            for t in tables:
                assert t.data.read_entry(b"b", b"k") is None
                assert len(t.data.gc_todo) == 0
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_fullcopy_local_reads(tmp_path, db_engine):
    async def main():
        net, systems, tables, tasks = await make_table_cluster(
            tmp_path, fullcopy=True, engine=db_engine
        )
        try:
            await tables[0].insert(KvEntry.new(b"cfg", b"bucket1", {"a": 1}))
            # write quorum is n-1 (fullcopy.rs semantics): one replica may
            # still be in flight when insert() returns — wait for fan-out
            for t in tables:
                for _ in range(200):
                    if t.data.read_entry(b"cfg", b"bucket1") is not None:
                        break
                    await asyncio.sleep(0.02)
                assert t.data.read_entry(b"cfg", b"bucket1") is not None
            # reads are local: work even with the other two disconnected
            await systems[0].netapp.shutdown()
            got = await tables[1].get(b"cfg", b"bucket1")
            assert got is not None
        finally:
            await stop_all(systems, tasks)

    run(main())


def test_insert_queue_drains(tmp_path, db_engine):
    async def main():
        net, systems, tables, tasks = await make_table_cluster(tmp_path, engine=db_engine)
        try:
            from garage_tpu.table.queue import InsertQueueWorker

            # enqueue via a transaction, as triggers do
            t0 = tables[0]
            e = KvEntry.new(b"qq", b"x", "queued")
            t0.data.db.transaction(lambda tx: t0.data.queue_insert(tx, e))
            assert len(t0.data.insert_queue) == 1
            w = InsertQueueWorker(t0)
            await w.work()
            assert len(t0.data.insert_queue) == 0
            got = await tables[1].get(b"qq", b"x")
            assert got is not None and got.value.value == "queued"
        finally:
            await stop_all(systems, tasks)

    run(main())
