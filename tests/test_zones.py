"""Zones as a first-class subsystem (ISSUE 16).

Unit coverage for the zone layer — ZoneHealth rollup, zone-aware
request ordering, write zone-span verification, the per-request
DEGRADED consistency override, the per-zone cache-tier ring, the
partition_zone chaos fault — plus the acceptance drill: a 3-zone /
6-node cluster-in-a-box under Zipf load loses a whole zone and must
keep serving consistent quorums with zero failures, report the
partition via GET /v1/zones within about one peering interval, serve
DEGRADED-override reads from the surviving side of the cut, and keep
hot-block cache probes strictly intra-zone (counter-asserted).
"""

import asyncio
import json
import socket
import time
import urllib.request

import pytest

from garage_tpu.chaos import FaultSpec, arm, disarm
from garage_tpu.chaos.injector import ChaosController
from garage_tpu.rpc import ReplicationMode, RequestStrategy, RpcHelper
from garage_tpu.rpc.layout import NodeRole
from garage_tpu.rpc.replication_mode import ConsistencyMode
from garage_tpu.utils.error import QuorumError, ZoneSpanError
from garage_tpu.utils.metrics import registry
from garage_tpu.zones import ZoneState
from garage_tpu.zones.health import SUSPECT_FAILED_PINGS

from clusterbox import ClusterBox, Workload
from test_rpc import _wait, make_cluster, stop_cluster


def run(coro, timeout=240.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


@pytest.fixture(autouse=True)
def _chaos_clean():
    disarm()
    yield
    disarm()


def apply_zoned_layout(systems, zones, rf=3, zone_redundancy=None):
    """Stage every system with a zone from `zones` (by index) and
    apply on node 0."""
    lm = systems[0].layout_manager
    for s, z in zip(systems, zones):
        lm.history.stage_role(s.id, NodeRole(zone=z, capacity=1 << 30))
    if zone_redundancy is not None:
        lm.history.stage_parameters(zone_redundancy)
    lm.apply_staged(None)


# ---- ZoneHealth ---------------------------------------------------------


def test_zone_health_rollup(tmp_path):
    """up -> degraded -> partitioned as a zone's nodes drop, from the
    surviving observer's point of view; the local zone never reports
    partitioned to itself."""

    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 4)
        try:
            apply_zoned_layout(systems, ["z1", "z1", "z2", "z2"])
            await _wait(lambda: all(
                s.layout_manager.history.current().version == 1
                for s in systems), 10)
            zh = systems[0].zone_health
            assert zh.local_zone() == "z1"
            assert set(zh.zone_nodes()) == {"z1", "z2"}
            await _wait(lambda: zh.zone_state("z1") == ZoneState.UP
                        and zh.zone_state("z2") == ZoneState.UP, 10)

            # half of z2 gone: degraded
            for other in systems[:3]:
                net.partition(other.id, systems[3].id)
            await _wait(lambda: zh.zone_state("z2") == ZoneState.DEGRADED,
                        15)
            # all of z2 gone: partitioned — and the snapshot agrees
            for other in systems[:2]:
                net.partition(other.id, systems[2].id)
            await _wait(
                lambda: zh.zone_state("z2") == ZoneState.PARTITIONED, 15)
            snap = zh.snapshot()
            assert snap["localZone"] == "z1"
            by_zone = {z["zone"]: z for z in snap["zones"]}
            assert by_zone["z2"]["state"] == "partitioned"
            assert by_zone["z2"]["nodesUp"] == 0
            assert len(by_zone["z2"]["downNodes"]) == 2
            # the observer's own zone stays up (self is always up)
            assert by_zone["z1"]["state"] == "up"
            assert zh.partitioned_zones() == {"z2"}
        finally:
            await stop_cluster(systems, tasks)

    run(main())


def test_zone_health_unknown_zone_and_suspect_pings(tmp_path):
    """A node with no layout role resolves to no zone (gateways are not
    zone members); consecutive failed pings alone mark a node down
    before the conn state machine gives up on the link."""

    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3, rf=2)
        try:
            # only two nodes get roles: the third is a gateway
            lm = systems[0].layout_manager
            for s in systems[:2]:
                lm.history.stage_role(
                    s.id, NodeRole(zone="z1", capacity=1 << 30))
            lm.apply_staged(None)
            await _wait(lambda: all(
                s.layout_manager.history.current().version == 1
                for s in systems), 10)
            zh = systems[0].zone_health
            assert zh.zone_of(systems[2].id) is None
            assert set(zh.zone_nodes()) == {"z1"}
            assert all(systems[2].id not in members
                       for members in zh.zone_nodes().values())
            # suspect-ping path: simulate the counter the ping loop
            # bumps — two misses is enough to call the node down even
            # while its conn still looks CONNECTED
            peer = systems[0].peering.peers[systems[1].id]
            assert not zh.node_down(systems[1].id)
            peer.failed_pings = SUSPECT_FAILED_PINGS
            assert zh.node_down(systems[1].id)
            assert zh.zone_state("z1") == ZoneState.DEGRADED
            peer.failed_pings = 0
        finally:
            await stop_cluster(systems, tasks)

    run(main())


# ---- zone-aware request order + degraded reads --------------------------


def test_request_order_shuns_partitioned_zone(tmp_path):
    """Local zone first; nodes whose whole zone is partitioned sort
    dead last even while their conn state flaps."""

    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 4)
        try:
            apply_zoned_layout(systems, ["z1", "z1", "z2", "z2"])
            await _wait(lambda: all(
                s.layout_manager.history.current().version == 1
                for s in systems), 10)
            rpc = RpcHelper(systems[0])
            ids = [s.id for s in systems]
            order = rpc.request_order(list(ids))
            # self first, then the same-zone peer, then z2
            assert order[0] == systems[0].id
            assert order[1] == systems[1].id
            # partition all of z2: its nodes must sort last regardless
            # of conn flaps — force the scenario via the health rollup
            for target in systems[2:]:
                for other in systems:
                    if other is not target:
                        net.partition(other.id, target.id)
            zh = systems[0].zone_health
            await _wait(
                lambda: zh.zone_state("z2") == ZoneState.PARTITIONED, 15)
            order = rpc.request_order(list(ids))
            assert set(order[2:]) == {systems[2].id, systems[3].id}
        finally:
            await stop_cluster(systems, tasks)

    run(main())


def test_degraded_override_reads_one_replica(tmp_path):
    """try_call_many with consistency=DEGRADED succeeds on a single
    reachable replica where the consistent quorum fails."""

    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_zoned_layout(systems, ["z1", "z2", "z3"],
                               zone_redundancy=2)
            await _wait(lambda: all(
                s.layout_manager.history.current().version == 1
                for s in systems), 10)
            async def h(frm, payload, stream):
                return {"ok": True}

            for s in systems:
                s.netapp.endpoint("test/zdeg").set_handler(h)
            ep = systems[0].netapp.endpoint("test/zdeg")
            rpc = RpcHelper(systems[0])
            ids = [s.id for s in systems]
            # sever both peers: consistent quorum 2 cannot be met
            net.partition(systems[0].id, systems[1].id)
            net.partition(systems[0].id, systems[2].id)
            await _wait(lambda: not systems[0].is_up(systems[1].id)
                        and not systems[0].is_up(systems[2].id), 15)
            with pytest.raises(QuorumError):
                await rpc.try_call_many(
                    ep, ids, {"op": "x"},
                    RequestStrategy(quorum=2, timeout=5.0))
            before = registry().totals("rpc_degraded_read")[0]
            resps = await rpc.try_call_many(
                ep, ids, {"op": "x"},
                RequestStrategy(quorum=2, timeout=5.0,
                                consistency=ConsistencyMode.DEGRADED))
            assert len(resps) >= 1 and resps[0]["ok"]
            assert registry().totals("rpc_degraded_read")[0] == before + 1
        finally:
            await stop_cluster(systems, tasks)

    run(main())


# ---- write zone-span verification ---------------------------------------


def test_write_zone_span_verification(tmp_path):
    """A write set confined to fewer zones than zone_redundancy raises
    the typed ZoneSpanError before any replica is written; spanning
    sets, unknown-zone sets, zone_span=0 and DEGRADED overrides pass."""

    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 3)
        try:
            apply_zoned_layout(systems, ["z1", "z1", "z2"],
                               zone_redundancy=2)
            await _wait(lambda: all(
                s.layout_manager.history.current().version == 1
                for s in systems), 10)
            rpc = RpcHelper(systems[0])
            ep = type("E", (), {"path": "test/span"})()
            a, b, c = [s.id for s in systems]
            node_of = lambda k: k[0] if isinstance(k, tuple) else k  # noqa: E731

            def verify(sets, **kw):
                rpc._verify_zone_span(ep, sets,
                                      RequestStrategy(quorum=2, **kw),
                                      node_of)

            verify([[a, b, c]])            # spans z1+z2: fine
            verify([[a, c]])               # spans both: fine
            with pytest.raises(ZoneSpanError) as ei:
                verify([[a, b]])           # z1 only
            assert ei.value.required_zones == 2
            assert ei.value.got_zones == 1
            assert isinstance(ei.value, QuorumError)  # typed subclass
            # erasure-style (node, shard) keys resolve through node_of
            with pytest.raises(ZoneSpanError):
                verify([[(a, 0), (b, 1)]])
            # explicit opt-outs and overrides
            verify([[a, b]], zone_span=0)
            verify([[a, b]],
                   consistency=ConsistencyMode.DEGRADED)
            with pytest.raises(ZoneSpanError):
                verify([[a, b, c]], zone_span=3)  # stricter than layout
            # a set containing an unknown node is skipped (conservative)
            verify([[a, b, b"\x00" * 32]])
            # end-to-end: try_write_many_sets rejects before writing
            wep = systems[0].netapp.endpoint("test/span_rpc")
            with pytest.raises(ZoneSpanError):
                await rpc.try_write_many_sets(
                    wep, [[a, b]], {"op": "w"},
                    RequestStrategy(quorum=2, timeout=5.0))
        finally:
            await stop_cluster(systems, tasks)

    run(main())


# ---- partition_zone chaos fault -----------------------------------------


def test_partition_zone_fault_matching():
    """The fault severs exactly the named zone's cross-zone links:
    intra-zone traffic (inside and outside the zone) and unresolvable
    endpoints pass untouched."""

    async def main():
        zones = {b"a" * 32: "z1", b"b" * 32: "z1",
                 b"c" * 32: "z2", b"d" * 32: None}
        c = ChaosController(seed=7)
        c.zone_resolver = zones.get
        c.add(FaultSpec(kind="partition_zone", zone="z2"))

        async def ok(local, peer):
            return await c.net_frame("send", local, peer, 100)

        assert await ok(b"a" * 32, b"b" * 32)     # intra z1
        assert await ok(b"c" * 32, b"c" * 32)     # intra z2
        assert await ok(b"a" * 32, b"d" * 32)     # unresolvable side
        assert await ok(b"", b"c" * 32)           # no local id: skipped
        with pytest.raises(ConnectionError):
            await ok(b"a" * 32, b"c" * 32)        # z1 -> z2 severed
        with pytest.raises(ConnectionError):
            await ok(b"c" * 32, b"b" * 32)        # z2 -> z1 severed
        assert c.total_fired == 2
        assert c.faults[0].to_dict()["zone"] == "z2"
        # a fault with no zone scope never matches anything
        c.clear()
        c.add(FaultSpec(kind="partition_zone"))
        assert await ok(b"a" * 32, b"c" * 32)
        # without a resolver the fault is inert, not an error
        c.clear()
        c.zone_resolver = None
        c.add(FaultSpec(kind="partition_zone", zone="z2"))
        assert await ok(b"a" * 32, b"c" * 32)

    run(main())


# ---- per-zone cache-tier ring -------------------------------------------


class _StubCache:
    max_bytes = 1 << 20

    def top_keys(self, n):
        return []

    def contains(self, h):
        return True  # ISSUE 18: hints for held blocks never prefetch


class _StubRpc:
    def health(self):
        return None


def _tier_on(system):
    from garage_tpu.block.cache_tier import ClusterCacheTier

    mgr = type("M", (), {})()
    mgr.system = system
    mgr.rpc = _StubRpc()
    mgr.cache = _StubCache()
    return ClusterCacheTier(mgr)


def test_cache_tier_ring_is_per_zone(tmp_path):
    """members() restricts to the local node's zone; hints from other
    zones are dropped on receipt; a zoneless node keeps the global
    ring (the pre-zone behavior)."""

    async def main():
        net, systems, tasks = await make_cluster(tmp_path, 4)
        try:
            apply_zoned_layout(systems, ["z1", "z1", "z2", "z2"])
            await _wait(lambda: all(
                s.layout_manager.history.current().version == 1
                for s in systems), 10)
            tier = _tier_on(systems[0])
            ids = [s.id for s in systems]
            assert set(tier.members()) == {ids[0], ids[1]}
            # every owned hash maps inside the zone
            for i in range(32):
                owner = tier.owner_of(bytes([i]) * 32)
                assert owner in (None, ids[1])
            # cross-zone hints are dropped, same-zone accepted
            h = b"\x07" * 32
            tier.note_hints(ids[2], [h])
            assert not tier.is_hot(h)
            assert tier.hints_dropped_cross_zone == 1
            tier.note_hints(ids[1], [h])
            assert tier.is_hot(h)
            assert tier.stats()["zone"] == "z1"

            # ISSUE 18 conformance: the prefetch trigger sits BEHIND
            # the same zone gate — a cross-zone hint must never queue
            # a speculative decode either
            triggered = []
            tier._maybe_prefetch = triggered.append
            h2 = b"\x08" * 32
            tier.note_hints(ids[2], [h2])  # cross-zone: dropped
            assert triggered == []
            tier.note_hints(ids[1], [h2])  # same-zone: considered
            assert triggered == [h2]

            # zoneless observer (a node with no layout role, e.g. a
            # gateway worker): the pre-zone global roster survives
            mgr = tier.manager
            mgr.system = type("S", (), {})()
            mgr.system.id = b"\xff" * 32  # not in the layout at all
            mgr.system.layout_helper = systems[0].layout_helper
            assert set(tier.members()) == set(ids)
        finally:
            await stop_cluster(systems, tasks)

    run(main())


# ---- the acceptance drill -----------------------------------------------


def test_zone_partition_drill(tmp_path):
    """3-zone / 6-node, rf=3, zone_redundancy=2, sustained Zipf load:
    partitioning ALL of z3 must cost zero failed quorum ops in
    consistent mode, GET /v1/zones flips to partitioned within about
    one peering-detection interval, DEGRADED-override reads serve from
    the surviving zones on BOTH sides of the cut, and hot-block cache
    probes never leave their zone (counter-asserted)."""

    async def main():
        from test_model import put_object_like_api

        box = await ClusterBox(
            tmp_path, n=6, rf=3,
            zones=["z1", "z1", "z2", "z2", "z3", "z3"],
            zone_redundancy=2).start()
        zone_of = {nd.id: box.zones[i] for i, nd in enumerate(box.nodes)}
        wl = None
        srv = None
        try:
            # placement precondition (the spread-maximizing solver):
            # every partition has one replica in EVERY zone — losing a
            # whole zone leaves 2/3 replicas, so R=2/W=2 quorums hold
            v = box.nodes[0].system.layout_manager.history.current()
            assert v.zone_redundancy == 2
            for p in range(256):
                assert len({zone_of[n] for n in v.nodes_of(p)}) == 3, \
                    f"partition {p} does not span all zones"

            g0 = box.nodes[0].garage
            wl = Workload(box, obj_kib=16, period=0.02, zipf=4.0).start()
            await wl.wait_ops(puts=6, gets=6, timeout=90)
            # a pinned object for the cross-cut DEGRADED read
            pin = b"zone-drill-pinned " * 100
            await put_object_like_api(g0, wl.bucket_id, "drill-pin", pin)

            # hot-set reads through the cache tier (the workload's own
            # gets bypass it with cacheable=False): warms the per-zone
            # lane and feeds the probe counters
            hot = [h for h, _ in wl.stored[:4]]
            for h in hot:
                assert (await box.nodes[0].manager.rpc_get_block(h)) \
                    is not None

            cross0 = registry().totals("block_cross_zone_read_bytes")[1]
            total0 = registry().totals("block_remote_read_bytes")[1]

            # ---- sever z3 ------------------------------------------
            c = arm(seed=1606)
            c.zone_resolver = zone_of.get
            c.add(FaultSpec(kind="partition_zone", zone="z3"))
            t_armed = time.monotonic()
            zh0 = box.nodes[0].system.zone_health
            await box.wait(
                lambda: zh0.zone_state("z3") == ZoneState.PARTITIONED,
                20, "z3 partitioned in node0's zone health")
            detect_s = time.monotonic() - t_armed
            # detection = SUSPECT_FAILED_PINGS missed pings at the
            # box's 0.3 s cadence (+ jitter) — "within one peering
            # interval" with CI headroom
            assert detect_s < 5.0, f"zone partition took {detect_s:.1f}s"

            # admin surface: GET /v1/zones serves the same rollup
            from garage_tpu.admin.http import AdminHttpServer

            g0.config.admin_token = "zones-drill-token"
            srv = AdminHttpServer(g0)
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
            await srv.start("127.0.0.1", port)
            loop = asyncio.get_running_loop()

            def fetch_zones():
                r = urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/zones",
                    headers={"authorization":
                             "Bearer zones-drill-token"})
                with urllib.request.urlopen(r, timeout=10) as resp:
                    return json.loads(resp.read().decode())

            snap = await loop.run_in_executor(None, fetch_zones)
            assert snap["localZone"] == "z1"
            states = {z["zone"]: z["state"] for z in snap["zones"]}
            assert states["z3"] == "partitioned"
            assert states["z1"] == "up"

            # ---- sustained consistent load through the partition ----
            puts0, gets0 = len(wl.put_lat), len(wl.get_lat)
            await wl.wait_ops(puts=puts0 + 10, gets=gets0 + 10,
                              timeout=120)
            # hot-set reads keep landing through the per-zone cache lane
            for h in hot:
                assert (await box.nodes[0].manager.rpc_get_block(h)) \
                    is not None

            # DEGRADED-override read from the SURVIVING side
            obj = await g0.object_table.get(
                wl.bucket_id, b"drill-pin",
                consistency=ConsistencyMode.DEGRADED)
            assert obj is not None
            # ...and from the SEVERED side, where the consistent quorum
            # is genuinely unreachable
            g4 = box.nodes[4].garage
            zh4 = box.nodes[4].system.zone_health
            await box.wait(
                lambda: zh4.partitioned_zones() == {"z1", "z2"},
                20, "node4 sees the rest of the world partitioned")
            with pytest.raises(QuorumError):
                await g4.object_table.get(wl.bucket_id, b"drill-pin")
            obj4 = await g4.object_table.get(
                wl.bucket_id, b"drill-pin",
                consistency=ConsistencyMode.DEGRADED)
            assert obj4 is not None
            assert obj4.bucket_id == obj.bucket_id

            stats = await wl.stop()
            wl = None
            assert stats["failures"] == [], \
                f"quorum ops failed during zone partition: {stats}"
            assert stats["corrupt"] == 0

            # cache probes never crossed a zone, on any node
            for nd in box.live():
                tier = nd.manager.cache_tier
                if tier is not None:
                    assert tier.cross_zone_probes == 0, \
                        f"node{nd.index} probed across zones"
            assert registry().totals("cache_tier_cross_zone_probe")[0] \
                == 0
            # cross-zone read fraction on the remote-read byte stream
            # stays bounded: local-zone-first ordering means z1 serves
            # z1 (hedges may occasionally spill over)
            cross = registry().totals(
                "block_cross_zone_read_bytes")[1] - cross0
            total = registry().totals(
                "block_remote_read_bytes")[1] - total0
            if total > 0:
                assert cross / total <= 0.5, \
                    f"cross-zone read fraction {cross}/{total}"
        finally:
            disarm()
            if wl is not None:
                await wl.stop()
            if srv is not None:
                await srv.stop()
            await box.stop()

    run(main())
