"""External discovery: Consul + Kubernetes providers against in-process
fake HTTP servers, and the System discovery loop converging a cluster
with NO bootstrap peers (ref: rpc/consul.rs, rpc/kubernetes.rs).
"""

import asyncio
import json

from garage_tpu.rpc.discovery import (ConsulDiscovery, KubernetesDiscovery,
                                      providers_from_config)
from garage_tpu.utils.config import config_from_dict

from test_block import NETID, run  # noqa: F401


class FakeConsul:
    """Minimal /v1/agent/service/register + /v1/catalog/service/<name>."""

    def __init__(self):
        self.services: dict[str, dict] = {}
        self.server = None
        self.port = None

    async def start(self):
        self.server = await asyncio.start_server(self._conn, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]

    async def stop(self):
        self.server.close()
        await self.server.wait_closed()

    async def _conn(self, reader, writer):
        try:
            req = await reader.readline()
            method, path, _ = req.decode().split(" ", 2)
            length = 0
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b""):
                    break
                if line.lower().startswith(b"content-length:"):
                    length = int(line.split(b":")[1])
            body = await reader.readexactly(length) if length else b""
            status, resp = self._route(method, path, body)
            payload = json.dumps(resp).encode()
            writer.write(
                f"HTTP/1.1 {status} X\r\ncontent-type: application/json"
                f"\r\ncontent-length: {len(payload)}\r\n\r\n".encode()
                + payload)
            await writer.drain()
        finally:
            writer.close()

    def _route(self, method, path, body):
        if method == "PUT" and path == "/v1/agent/service/register":
            svc = json.loads(body.decode())
            self.services[svc["ID"]] = svc
            return 200, {}
        if method == "GET" and path.startswith("/v1/catalog/service/"):
            name = path.rsplit("/", 1)[1]
            return 200, [
                {"ServiceAddress": s["Address"], "ServicePort": s["Port"],
                 "ServiceMeta": s.get("Meta", {})}
                for s in self.services.values() if s["Name"] == name
            ]
        return 404, {"error": "not found"}


def test_consul_register_and_discover():
    async def main():
        consul = FakeConsul()
        await consul.start()
        try:
            prov = ConsulDiscovery(f"127.0.0.1:{consul.port}", "garage")
            nid_a, nid_b = b"\x01" * 32, b"\x02" * 32
            await prov.register(nid_a, ("10.0.0.1", 3901))
            await prov.register(nid_b, ("10.0.0.2", 3901))
            peers = sorted(await prov.get_peers())
            assert peers == [(("10.0.0.1", 3901), nid_a),
                             (("10.0.0.2", 3901), nid_b)]
        finally:
            await consul.stop()

    run(main())


def test_kubernetes_crd_provider():
    """The k8s provider drives the same fake-HTTP pattern: upsert a CR,
    then list; the fake speaks just enough of the CRD REST surface."""

    class FakeK8s(FakeConsul):
        def __init__(self):
            super().__init__()
            self.crs: dict[str, dict] = {}

        def _route(self, method, path, body):
            base = "/apis/deuxfleurs.fr/v1/namespaces/ns1/garagenodes"
            if path == base and method == "GET":
                return 200, {"items": list(self.crs.values())}
            if path == base and method == "POST":
                cr = json.loads(body.decode())
                self.crs[cr["metadata"]["name"]] = cr
                return 201, cr
            if path.startswith(base + "/") and method == "PUT":
                name = path.rsplit("/", 1)[1]
                if name not in self.crs:
                    return 404, {}
                cr = json.loads(body.decode())
                self.crs[name] = cr
                return 200, cr
            return 404, {}

    async def main():
        k8s = FakeK8s()
        await k8s.start()
        try:
            prov = KubernetesDiscovery(
                "ns1", "garage",
                api_server=f"http://127.0.0.1:{k8s.port}", token="t")
            nid = b"\x07" * 32
            await prov.register(nid, ("10.1.0.1", 3901))
            await prov.register(nid, ("10.1.0.1", 3902))  # update via PUT
            peers = await prov.get_peers()
            assert peers == [(("10.1.0.1", 3902), nid)]
        finally:
            await k8s.stop()

    run(main())


def test_system_discovery_loop_connects_cluster(tmp_path):
    """Two real nodes with NO bootstrap peers find each other purely
    through the (fake) Consul catalog."""
    from garage_tpu.net import LocalNetwork, NetApp
    from garage_tpu.rpc import ReplicationMode, System

    async def main():
        consul = FakeConsul()
        await consul.start()
        net = LocalNetwork()
        systems, tasks = [], []
        try:
            for i in range(2):
                app = NetApp(NETID)
                net.register(app)
                prov = ConsulDiscovery(f"127.0.0.1:{consul.port}",
                                       "garage")
                s = System(app, ReplicationMode.parse(1),
                           str(tmp_path / f"n{i}"),
                           status_interval=5.0, ping_interval=5.0,
                           discovery=[prov], discovery_interval=0.1)
                systems.append(s)
            tasks = [asyncio.create_task(s.run()) for s in systems]
            deadline = asyncio.get_event_loop().time() + 15
            while asyncio.get_event_loop().time() < deadline:
                if all(len(s.netapp.conns) == 1 for s in systems):
                    break
                await asyncio.sleep(0.05)
            assert all(len(s.netapp.conns) == 1 for s in systems)
        finally:
            for s in systems:
                await s.stop()
            for t in tasks:
                t.cancel()
            await consul.stop()

    run(main())


def test_providers_from_config():
    cfg = config_from_dict({
        "metadata_dir": "/tmp/x",
        "consul_discovery": {"consul_http_addr": "127.0.0.1:8500",
                             "service_name": "garage-test"},
        "kubernetes_discovery": {"namespace": "prod",
                                 "service_name": "garage"},
    })
    provs = providers_from_config(cfg)
    assert len(provs) == 2
    assert isinstance(provs[0], ConsulDiscovery)
    assert provs[0].service_name == "garage-test"
    assert isinstance(provs[1], KubernetesDiscovery)
    assert provs[1].namespace == "prod"
