"""Independent S3 SigV4 client for conformance tests.

Deliberately does NOT reuse garage_tpu.api.signature — this is a
from-scratch signer over http.client so server-side verification is
exercised against a second implementation (the reference does the same
with aws-sdk-s3 + a hand-rolled custom_requester, ref:
src/garage/tests/common/custom_requester.rs).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Optional

ALGORITHM = "AWS4-HMAC-SHA256"
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _sha256(b: bytes) -> str:
    return hashlib.sha256(b).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def uri_encode(s: str, encode_slash: bool = True) -> str:
    return urllib.parse.quote(s, safe="-_.~" if encode_slash else "-_.~/")


class S3Client:
    def __init__(self, host: str, port: int, key_id: str, secret: str,
                 region: str = "garage"):
        self.host = host
        self.port = port
        self.key_id = key_id
        self.secret = secret
        self.region = region

    # ---- signing -------------------------------------------------------

    def _scope(self, date: str) -> str:
        return f"{date}/{self.region}/s3/aws4_request"

    def signing_key(self, date: str) -> bytes:
        k = _hmac(b"AWS4" + self.secret.encode(), date)
        k = _hmac(k, self.region)
        k = _hmac(k, "s3")
        return _hmac(k, "aws4_request")

    def _canonical_query(self, query: list[tuple[str, str]]) -> str:
        pairs = sorted((uri_encode(k), uri_encode(v)) for k, v in query)
        return "&".join(f"{k}={v}" for k, v in pairs)

    def sign(self, method: str, path: str, query: list[tuple[str, str]],
             headers: dict[str, str], payload_hash: str,
             now: Optional[datetime.datetime] = None) -> dict[str, str]:
        """-> headers + Authorization. `headers` must already contain
        host; x-amz-date/x-amz-content-sha256 are added here."""
        now = now or datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date = now.strftime("%Y%m%d")
        headers = dict(headers)
        headers["x-amz-date"] = amz_date
        headers["x-amz-content-sha256"] = payload_hash
        signed = sorted(h.lower() for h in headers)
        canonical_headers = "".join(
            f"{h}:{' '.join(str(headers[next(k for k in headers if k.lower() == h)]).split())}\n"
            for h in signed)
        creq = "\n".join([
            method,
            # S3 convention: the request path is single-encoded by
            # the caller and used VERBATIM as the canonical URI (no
            # re-encoding - %20 must not become %2520)
            path or "/",
            self._canonical_query(query),
            canonical_headers,
            ";".join(signed),
            payload_hash,
        ])
        sts = "\n".join([ALGORITHM, amz_date, self._scope(date),
                         _sha256(creq.encode())])
        sig = hmac.new(self.signing_key(date), sts.encode(),
                       hashlib.sha256).hexdigest()
        headers["authorization"] = (
            f"{ALGORITHM} Credential={self.key_id}/{self._scope(date)},"
            f"SignedHeaders={';'.join(signed)},Signature={sig}")
        return headers

    # ---- plain requests ------------------------------------------------

    def request(self, method: str, path: str,
                query: Optional[list[tuple[str, str]]] = None,
                headers: Optional[dict[str, str]] = None,
                body: bytes = b"", unsigned_payload: bool = False,
                anonymous: bool = False, timeout: float = 30.0):
        """-> (status, headers dict, body bytes)."""
        query = query or []
        headers = {k.lower(): v for k, v in (headers or {}).items()}
        headers.setdefault("host", f"{self.host}:{self.port}")
        if not anonymous:
            payload_hash = ("UNSIGNED-PAYLOAD" if unsigned_payload
                            else _sha256(body))
            headers = self.sign(method, path, query, headers, payload_hash)
        qs = "&".join(f"{uri_encode(k)}={uri_encode(v)}" for k, v in query)
        url = path + ("?" + qs if qs else "")
        conn = http.client.HTTPConnection(self.host, self.port, timeout=timeout)
        try:
            conn.request(method, url, body=body, headers=headers)
            r = conn.getresponse()
            rbody = r.read()
            rhdrs = {k.lower(): v for k, v in r.getheaders()}
            return r.status, rhdrs, rbody
        finally:
            conn.close()

    # ---- aws-chunked streaming bodies ----------------------------------

    def chunked_signed_body(self, chunks: list[bytes], amz_date: str,
                            seed_signature: str,
                            trailer: Optional[tuple[str, str]] = None,
                            sign_trailer_label: str = "AWS4-HMAC-SHA256-TRAILER",
                            ) -> bytes:
        """Build a STREAMING-AWS4-HMAC-SHA256-PAYLOAD[-TRAILER] body."""
        date = amz_date[:8]
        sk = self.signing_key(date)
        prev = seed_signature
        out = bytearray()
        for data in list(chunks) + [b""]:
            sts = "\n".join(["AWS4-HMAC-SHA256-PAYLOAD", amz_date,
                             self._scope(date), prev, EMPTY_SHA256,
                             _sha256(data)])
            sig = hmac.new(sk, sts.encode(), hashlib.sha256).hexdigest()
            out += f"{len(data):x};chunk-signature={sig}\r\n".encode()
            if data:
                out += data + b"\r\n"
            prev = sig
        if trailer is None:
            out += b"\r\n"
        else:
            name, value = trailer
            out += f"{name}:{value}\r\n".encode()
            sts = "\n".join([sign_trailer_label, amz_date, self._scope(date),
                             prev, _sha256(f"{name}:{value}\n".encode())])
            sig = hmac.new(sk, sts.encode(), hashlib.sha256).hexdigest()
            out += f"x-amz-trailer-signature:{sig}\r\n".encode()
            out += b"\r\n"
        return bytes(out)

    def put_chunked(self, path: str, chunks: list[bytes],
                    trailer: Optional[tuple[str, str]] = None,
                    corrupt_chunk_sig: bool = False,
                    extra_headers: Optional[dict[str, str]] = None,
                    query: Optional[list[tuple[str, str]]] = None):
        """PUT with aws-chunked signed framing (+ optional signed
        trailer)."""
        mode = ("STREAMING-AWS4-HMAC-SHA256-PAYLOAD-TRAILER" if trailer
                else "STREAMING-AWS4-HMAC-SHA256-PAYLOAD")
        decoded_len = sum(len(c) for c in chunks)
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        headers = {"host": f"{self.host}:{self.port}",
                   "content-encoding": "aws-chunked",
                   "x-amz-decoded-content-length": str(decoded_len)}
        if trailer:
            headers["x-amz-trailer"] = trailer[0]
        if extra_headers:
            headers.update(extra_headers)
        headers = self.sign("PUT", path, query or [], headers, mode,
                            now=now)
        seed = headers["authorization"].rsplit("Signature=", 1)[1]
        body = self.chunked_signed_body(chunks, amz_date, seed,
                                        trailer=trailer)
        if corrupt_chunk_sig:
            i = body.index(b"chunk-signature=") + len(b"chunk-signature=")
            body = (body[:i]
                    + (b"0" if body[i:i + 1] != b"0" else b"1")
                    + body[i + 1:])
        url = path
        if query:
            url += "?" + "&".join(
                f"{uri_encode(k)}={uri_encode(v)}" for k, v in query)
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        try:
            conn.request("PUT", url, body=body, headers=headers)
            r = conn.getresponse()
            rbody = r.read()
            return r.status, {k.lower(): v for k, v in r.getheaders()}, rbody
        finally:
            conn.close()

    def put_unsigned_trailer(self, path: str, chunks: list[bytes],
                             trailer: tuple[str, str]):
        """PUT with STREAMING-UNSIGNED-PAYLOAD-TRAILER framing."""
        decoded_len = sum(len(c) for c in chunks)
        headers = {"host": f"{self.host}:{self.port}",
                   "content-encoding": "aws-chunked",
                   "x-amz-trailer": trailer[0],
                   "x-amz-decoded-content-length": str(decoded_len)}
        headers = self.sign("PUT", path, [], headers,
                            "STREAMING-UNSIGNED-PAYLOAD-TRAILER")
        out = bytearray()
        for data in list(chunks) + [b""]:
            out += f"{len(data):x}\r\n".encode()
            if data:
                out += data + b"\r\n"
        out += f"{trailer[0]}:{trailer[1]}\r\n".encode()
        out += b"\r\n"
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        try:
            conn.request("PUT", path, body=bytes(out), headers=headers)
            r = conn.getresponse()
            rbody = r.read()
            return r.status, {k.lower(): v for k, v in r.getheaders()}, rbody
        finally:
            conn.close()

    # ---- presigned -----------------------------------------------------

    def presign(self, method: str, path: str, expires: int = 300,
                query: Optional[list[tuple[str, str]]] = None) -> str:
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date = now.strftime("%Y%m%d")
        q = list(query or []) + [
            ("X-Amz-Algorithm", ALGORITHM),
            ("X-Amz-Credential", f"{self.key_id}/{self._scope(date)}"),
            ("X-Amz-Date", amz_date),
            ("X-Amz-Expires", str(expires)),
            ("X-Amz-SignedHeaders", "host"),
        ]
        creq = "\n".join([
            method,
            # S3 convention: the request path is single-encoded by
            # the caller and used VERBATIM as the canonical URI (no
            # re-encoding - %20 must not become %2520)
            path or "/",
            self._canonical_query(q),
            f"host:{self.host}:{self.port}\n",
            "host",
            "UNSIGNED-PAYLOAD",
        ])
        sts = "\n".join([ALGORITHM, amz_date, self._scope(date),
                         _sha256(creq.encode())])
        sig = hmac.new(self.signing_key(date), sts.encode(),
                       hashlib.sha256).hexdigest()
        q.append(("X-Amz-Signature", sig))
        qs = "&".join(f"{uri_encode(k)}={uri_encode(v)}" for k, v in q)
        return f"{path}?{qs}"

    def raw(self, method: str, url: str, headers: Optional[dict] = None,
            body: bytes = b""):
        """Unsigned raw request (for presigned URLs / anonymous)."""
        headers = headers or {}
        headers.setdefault("host", f"{self.host}:{self.port}")
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        try:
            conn.request(method, url, body=body, headers=headers)
            r = conn.getresponse()
            rbody = r.read()
            return r.status, {k.lower(): v for k, v in r.getheaders()}, rbody
        finally:
            conn.close()


def xml_find(body: bytes, tag: str) -> list[str]:
    """All text values of elements whose tag ends with `tag`."""
    root = ET.fromstring(body)
    out = []
    for el in root.iter():
        if el.tag.split("}")[-1] == tag:
            out.append(el.text or "")
    return out


def xml_error_code(body: bytes) -> str:
    try:
        return xml_find(body, "Code")[0]
    except (ET.ParseError, IndexError):
        return ""
