"""GF(2^8) + Reed-Solomon codec tests (ops/gf256.py, ops/rs.py)."""

import itertools

import numpy as np
import pytest

from garage_tpu.ops import gf256, rs


class TestGF256:
    def test_tables_consistent(self):
        # exp/log are inverse bijections on the nonzero elements
        for a in range(1, 256):
            assert gf256.GF_EXP[gf256.GF_LOG[a]] == a

    def test_mul_against_schoolbook(self):
        def slow_mul(a, b):
            p = 0
            for _ in range(8):
                if b & 1:
                    p ^= a
                b >>= 1
                a <<= 1
                if a & 0x100:
                    a ^= gf256.GF_POLY
            return p

        rng = np.random.default_rng(0)
        for a, b in rng.integers(0, 256, size=(200, 2)):
            assert int(gf256.gf_mul(a, b)) == slow_mul(int(a), int(b))

    def test_field_axioms_sampled(self):
        rng = np.random.default_rng(1)
        a, b, c = rng.integers(0, 256, size=(3, 64), dtype=np.uint8)
        assert np.array_equal(gf256.gf_mul(a, b), gf256.gf_mul(b, a))
        assert np.array_equal(
            gf256.gf_mul(a, gf256.gf_mul(b, c)), gf256.gf_mul(gf256.gf_mul(a, b), c)
        )
        # distributivity over XOR (field addition)
        assert np.array_equal(
            gf256.gf_mul(a, b ^ c), gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)
        )

    def test_inverse(self):
        a = np.arange(1, 256, dtype=np.uint8)
        assert np.all(gf256.gf_mul(a, gf256.gf_inv(a)) == 1)

    def test_matrix_inverse(self):
        rng = np.random.default_rng(2)
        for n in (1, 3, 8):
            while True:
                a = rng.integers(0, 256, size=(n, n), dtype=np.uint8)
                try:
                    ainv = gf256.gf_inv_matrix(a)
                    break
                except np.linalg.LinAlgError:
                    continue
            assert np.array_equal(gf256.gf_matmul(a, ainv), np.eye(n, dtype=np.uint8))

    def test_singular_raises(self):
        a = np.array([[1, 2], [1, 2]], dtype=np.uint8)
        with pytest.raises(np.linalg.LinAlgError):
            gf256.gf_inv_matrix(a)

    def test_bitmatrix_matches_field_mul(self):
        rng = np.random.default_rng(3)
        a = rng.integers(0, 256, size=(3, 5), dtype=np.uint8)
        x = rng.integers(0, 256, size=(5, 17), dtype=np.uint8)
        want = gf256.gf_matmul(a, x)
        got = np.asarray(gf256.bit_matmul_apply(gf256.bitmat_t_for(a), x))
        assert np.array_equal(got, want)

    def test_bitmatrix_batched(self):
        rng = np.random.default_rng(4)
        a = rng.integers(0, 256, size=(4, 10), dtype=np.uint8)
        x = rng.integers(0, 256, size=(2, 3, 10, 33), dtype=np.uint8)
        got = np.asarray(gf256.bit_matmul_apply(gf256.bitmat_t_for(a), x))
        assert got.shape == (2, 3, 4, 33)
        for i in range(2):
            for j in range(3):
                assert np.array_equal(got[i, j], gf256.gf_matmul(a, x[i, j]))


class TestRS:
    def test_generator_systematic_and_mds(self):
        k, m = 4, 3
        g = rs.generator_matrix(k, m)
        assert np.array_equal(g[:k], np.eye(k, dtype=np.uint8))
        # MDS: every k-subset of rows is invertible
        for rows in itertools.combinations(range(k + m), k):
            gf256.gf_inv_matrix(g[list(rows)])  # raises if singular

    @pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (10, 4)])
    def test_encode_device_matches_numpy(self, k, m):
        rng = np.random.default_rng(5)
        data = rng.integers(0, 256, size=(k, 101), dtype=np.uint8)
        assert np.array_equal(np.asarray(rs.encode(k, m, data)), rs.encode_np(k, m, data))

    def test_roundtrip_all_erasure_patterns(self):
        k, m = 4, 2
        rng = np.random.default_rng(6)
        data = rng.integers(0, 256, size=(k, 64), dtype=np.uint8)
        parity = np.asarray(rs.encode(k, m, data))
        stripe = np.concatenate([data, parity], axis=0)
        for present in itertools.combinations(range(k + m), k):
            got = np.asarray(rs.decode(k, m, present, stripe[list(present)]))
            assert np.array_equal(got, data), f"pattern {present}"

    def test_repair_rebuilds_missing_shards(self):
        k, m = 10, 4
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, size=(k, 128), dtype=np.uint8)
        stripe = np.concatenate([data, np.asarray(rs.encode(k, m, data))], axis=0)
        missing = (1, 7, 11, 13)
        present = tuple(i for i in range(k + m) if i not in missing)[:k]
        got = np.asarray(rs.repair(k, m, present, missing, stripe[list(present)]))
        assert np.array_equal(got, stripe[list(missing)])

    def test_batched_stripes(self):
        k, m = 4, 2
        rng = np.random.default_rng(8)
        data = rng.integers(0, 256, size=(5, k, 32), dtype=np.uint8)
        parity = np.asarray(rs.encode(k, m, data))
        assert parity.shape == (5, m, 32)
        for b in range(5):
            assert np.array_equal(parity[b], rs.encode_np(k, m, data[b]))

    def test_stripe_split_join(self):
        blob = bytes(range(250))
        shards = rs.split_stripe(blob, 4)
        assert shards.shape == (4, 63)
        assert rs.join_stripe(shards, len(blob)) == blob

    def test_m_zero_is_noop_parity(self):
        data = np.zeros((3, 8), dtype=np.uint8)
        assert rs.encode_np(3, 0, data).shape == (0, 8)


def test_pallas_kernel_matches_numpy_interpret():
    """The fused Pallas GF kernel (interpreter mode on CPU) must agree
    with the numpy reference for encode, decode and repair matrices."""
    import numpy as np

    from garage_tpu.ops import gf256, pallas_gf, rs

    rng = np.random.default_rng(7)
    k, m = 4, 2
    data = rng.integers(0, 256, (3, k, 1024), dtype=np.uint8)
    out = np.asarray(pallas_gf.encode(k, m, data, interpret=True))
    want = np.stack([rs.encode_np(k, m, data[i]) for i in range(3)])
    assert np.array_equal(out, want)
    # decode matrix through the same kernel
    present = (0, 2, 4, 5)
    full = np.concatenate([data, out], axis=1)
    surv = full[:, list(present), :]
    dec = np.asarray(pallas_gf.gf_apply(
        rs.decode_matrix(k, m, present), surv, interpret=True))
    assert np.array_equal(dec, data)
    # odd-but-tileable lane counts pick a smaller tile
    data2 = rng.integers(0, 256, (1, k, 1280), dtype=np.uint8)
    out2 = np.asarray(pallas_gf.encode(k, m, data2, interpret=True))
    assert np.array_equal(out2[0], rs.encode_np(k, m, data2[0]))


def test_parity_check_detects_any_single_corruption():
    """Property: for RS(k,m), flipping ANY single byte of ANY shard
    (data or parity) makes parity_check report the stripe inconsistent,
    and only that stripe — the linear code guarantees every non-zero
    error in one row perturbs at least one parity row."""
    k, m, S, B = 4, 2, 1024, 6
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, (B, k, S), dtype=np.uint8)
    parity = np.asarray(rs.encode(k, m, data))
    clean = np.concatenate([data, parity], axis=1)
    assert np.asarray(rs.parity_check(k, m, clean)).tolist() == [True] * B

    for _ in range(24):
        b = int(rng.integers(B))
        row = int(rng.integers(k + m))
        col = int(rng.integers(S))
        bad = clean.copy()
        bad[b, row, col] ^= int(rng.integers(1, 256))
        verdict = np.asarray(rs.parity_check(k, m, bad)).tolist()
        want = [i != b for i in range(B)]
        assert verdict == want, (b, row, col)
