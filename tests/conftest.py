"""Force JAX onto a virtual 8-device CPU mesh for all tests.

Multi-chip hardware is not available in CI; sharding tests run against
xla_force_host_platform_device_count=8. Must run before jax is imported.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
