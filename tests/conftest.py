"""Force JAX onto a virtual 8-device CPU mesh for all tests.

Multi-chip hardware is not available in CI; sharding tests run against
xla_force_host_platform_device_count=8. The axon sitecustomize in this
image force-registers a remote-TPU backend and overrides JAX_PLATFORMS,
so an explicit config.update is required — env vars are not enough.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# feeders in tests must never probe the real accelerator: the probe
# subprocess would see the axon tunnel (which ignores JAX_PLATFORMS) and
# start calibration threads whose C++ state aborts interpreter teardown
os.environ["GARAGE_TPU_DEVICE"] = "off"
# enforce the metric naming contract at registration time (the runtime
# half of the static GL07 rule; utils/metrics.py)
os.environ.setdefault("GARAGE_METRICS_STRICT", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from garage_tpu.utils import sanitizer  # noqa: E402

if sanitizer.armed():
    # runtime asyncio sanitizer (ISSUE 14): loop-stall detector +
    # teardown leak/conservation checks. CI exports GARAGE_SANITIZE=1
    # for tier-1 and the nightly soak.
    sanitizer.install()


@pytest.fixture(autouse=True)
def _sanitizer_reports():
    """Fail the test that stalled the loop / leaked a task or lock /
    broke budget conservation — the report names the culprit frame."""
    if sanitizer.armed():
        sanitizer.drain_reports()  # a prior test's tail must not bleed
    yield
    if not sanitizer.armed():
        return
    reports = sanitizer.drain_reports()
    if reports:
        detail = "\n".join(f"[{r['kind']}] {r['detail']}"
                           for r in reports)
        pytest.fail(f"sanitizer reports (GARAGE_SANITIZE=1):\n{detail}",
                    pytrace=False)


@pytest.fixture(params=["memory", "sqlite", "lsm"])
def db_engine(request) -> str:
    """The engine axis: every db/table test that takes this fixture runs
    once per KV engine, so a new engine (lsm) inherits the whole
    existing suite for free (ISSUE 7 satellite; mirrors src/db/test.rs
    running one suite over every adapter)."""
    return request.param
