"""Headline benchmark: RS(10,4) erasure encode throughput, GB/s per chip.

Prints exactly one JSON line. Baseline: 4.0 GB/s/chip (BASELINE.md,
driver target for the north-star metric "RS(10,4) encode MB/s").
Runs on whatever accelerator JAX finds; if the TPU backend is
unavailable it falls back to CPU with a smaller problem so the bench
always reports (the unit field says which backend measured).
"""

from __future__ import annotations

import json
import time

import numpy as np


PROBE_TIMEOUT = 180.0  # first TPU init can be slow; a dead tunnel hangs


def _probe_accelerator() -> bool:
    """Check in a subprocess whether the default backend comes up — a
    broken TPU tunnel can hang init indefinitely, which a timeout on a
    child process converts into a clean CPU fallback."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=PROBE_TIMEOUT,
            capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _get_backend():
    if not _probe_accelerator():
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        return jax, "cpu"
    import jax

    return jax, jax.devices()[0].platform


def main() -> None:
    jax, platform = _get_backend()

    from garage_tpu.ops import rs

    k, m = 10, 4
    if platform == "cpu":
        shard_len, batch, iters = 1 << 16, 4, 2  # keep CPU fallback quick
    else:
        shard_len, batch, iters = 1 << 20, 8, 5  # 10 MiB stripes, 80 MiB/iter
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(batch, k, shard_len), dtype=np.uint8)
    data = jax.device_put(data)

    parity = rs.encode(k, m, data)  # compile + warm
    jax.block_until_ready(parity)

    t0 = time.perf_counter()
    for _ in range(iters):
        parity = rs.encode(k, m, data)
    jax.block_until_ready(parity)
    dt = time.perf_counter() - t0

    gbps = batch * k * shard_len * iters / dt / 1e9
    print(
        json.dumps(
            {
                "metric": "rs_10_4_encode",
                "value": round(gbps, 3),
                "unit": f"GB/s/chip[{platform}]",
                "vs_baseline": round(gbps / 4.0, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
