"""Headline benchmark for the TPU-native block data path.

Prints exactly one JSON line. Headline metric: RS(10,4) erasure encode
GB/s per chip (BASELINE.md driver target: 4.0 GB/s/chip). The same line
carries the system-level numbers the north star asks for ("S3 PutObject
GB/s/chip; RS encode MB/s; scrub blocks/s"):

  put_gbps           block throughput measured THROUGH
                     BlockManager.rpc_put_block on an in-process 6-node
                     erasure(4,2) loopback cluster (device feeder
                     batches encode onto the TPU; quorum-acked writes)
  scrub_blocks_per_s ScrubWorker.scrub_batch over stored 1 MiB blocks,
                     content-hash verified in batched device passes
  blake3_gbps        batched BLAKE3 content hashing on device

A broken accelerator tunnel can hang JAX init forever, so the default
backend is probed in a subprocess with a timeout (block/feeder.py); on
failure everything falls back to CPU with smaller problem sizes and the
probe error is carried in the output so the fallback is never silent.
"""

from __future__ import annotations

import asyncio
import json
import os
import shutil
import tempfile
import time

import numpy as np


def bench_rs_encode(jax, platform: str) -> float:
    """Sustained RS(10,4) encode GB/s, measured with a DEPENDENCY CHAIN:
    each iteration's input folds in the previous parity, so iterations
    cannot overlap and a single end-of-chain sync gives wall-clock for
    exactly `iters` sequential encodes (per-call dispatch overhead
    amortized — the number a busy PUT pipeline sustains)."""
    import jax.numpy as jnp

    from garage_tpu.ops import rs

    k, m = 10, 4
    if platform == "cpu":
        shard_len, batch, iters = 1 << 16, 4, 3  # keep CPU fallback quick
    else:
        shard_len, batch, iters = 1 << 20, 8, 20  # 80 MiB per step
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(batch, k, shard_len), dtype=np.uint8)
    data = jax.device_put(data)

    @jax.jit
    def step(x):
        # the PRODUCTION encode entry point (rs.encode selects the XLA
        # bit-matmul or, with GARAGE_TPU_PALLAS, the fused Pallas
        # kernel); the xor/concat fold adds a little extra work, making
        # the figure slightly conservative
        p = rs.encode(k, m, x)
        pad = jnp.zeros((batch, k - 2 * m, shard_len), jnp.uint8)
        return x ^ jnp.concatenate([p, p, pad], axis=1)

    x = step(data)  # compile + warm
    _ = np.asarray(x[0, 0, :8])
    t0 = time.perf_counter()
    x = data
    for _ in range(iters):
        x = step(x)
    _ = np.asarray(x[0, 0, :8])  # one tiny d2h: full-chain completion
    dt = time.perf_counter() - t0
    return batch * k * shard_len * iters / dt / 1e9


def bench_blake3(jax, platform: str) -> float:
    from garage_tpu.ops import treehash

    if platform == "cpu":
        batch, iters = 4, 2
    else:
        batch, iters = 32, 5
    rng = np.random.default_rng(1)
    msgs = rng.integers(0, 256, size=(batch, 1 << 20), dtype=np.uint8)
    lengths = np.full(batch, 1 << 20, dtype=np.int32)
    treehash.hash_batch_jax(msgs, lengths)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        treehash.hash_batch_jax(msgs, lengths)
    dt = time.perf_counter() - t0
    return batch * (1 << 20) * iters / dt / 1e9


async def _put_cluster_bench(tmp: str, platform: str) -> dict:
    """6-node in-process loopback cluster, erasure(4,2): pump 1 MiB
    blocks through BlockManager.rpc_put_block — the real quorum write
    path (feeder batches the RS math; shard files land on tmpfs)."""
    from garage_tpu.block import BlockManager, DataLayout
    from garage_tpu.block.block import DataBlock
    from garage_tpu.block.repair import ScrubWorker
    from garage_tpu.db import open_db
    from garage_tpu.net import LocalNetwork, NetApp
    from garage_tpu.rpc import ReplicationMode, System
    from garage_tpu.rpc.layout import NodeRole
    from garage_tpu.utils.data import blake3sum

    n, k, m = 6, 4, 2
    nblocks = 16 if platform == "cpu" else 128
    block_len = 1 << 20
    net = LocalNetwork()
    systems, managers = [], []
    rm = ReplicationMode.parse(3, erasure=f"{k},{m}")
    for i in range(n):
        app = NetApp(b"bench-net")
        net.register(app)
        meta = os.path.join(tmp, f"node{i}")
        os.makedirs(meta, exist_ok=True)
        s = System(app, rm, meta, status_interval=0.2, ping_interval=5.0)
        systems.append(s)
    tasks = [asyncio.create_task(s.run()) for s in systems]
    for s in systems[1:]:
        await s.netapp.try_connect(systems[0].netapp.public_addr,
                                   systems[0].id)
        s.peering.add_peer(systems[0].netapp.public_addr, systems[0].id)
    deadline = asyncio.get_event_loop().time() + 15
    while asyncio.get_event_loop().time() < deadline:
        if all(len(s.netapp.conns) == n - 1 for s in systems):
            break
        await asyncio.sleep(0.05)
    lm = systems[0].layout_manager
    for s in systems:
        lm.history.stage_role(s.id, NodeRole(zone="z1", capacity=1 << 30))
    lm.apply_staged(None)
    while asyncio.get_event_loop().time() < deadline:
        if all(s.layout_manager.history.current().version == 1
               for s in systems):
            break
        await asyncio.sleep(0.05)
    for i, s in enumerate(systems):
        db = open_db(os.path.join(tmp, f"node{i}", "db"), engine="memory")
        lay = DataLayout.single(os.path.join(tmp, f"node{i}", "data"))
        managers.append(BlockManager(s, db, lay, compression=False))

    rng = np.random.default_rng(2)
    blocks = [rng.integers(0, 256, block_len, dtype=np.uint8).tobytes()
              for _ in range(nblocks)]
    hashes = [blake3sum(b) for b in blocks]

    for i in range(2):  # warm/compile the device encode path
        await managers[0].rpc_put_block(hashes[i], blocks[i])

    t0 = time.perf_counter()
    conc = 16
    idx, pending = 2, set()
    while idx < nblocks or pending:
        while idx < nblocks and len(pending) < conc:
            pending.add(asyncio.create_task(
                managers[0].rpc_put_block(hashes[idx], blocks[idx])))
            idx += 1
        done, pending = await asyncio.wait(
            pending, return_when=asyncio.FIRST_COMPLETED)
        for t in done:
            t.result()
    dt = time.perf_counter() - t0
    put_gbps = (nblocks - 2) * block_len / dt / 1e9

    # ---- scrub: replicate-mode batched device verify -------------------
    app = NetApp(b"bench-net")
    net.register(app)
    sm = os.path.join(tmp, "scrubnode")
    os.makedirs(sm, exist_ok=True)
    s1 = System(app, ReplicationMode.parse(1), sm,
                status_interval=3600.0, ping_interval=3600.0)
    db1 = open_db(os.path.join(sm, "db"), engine="memory")
    mgr1 = BlockManager(s1, db1, DataLayout.single(os.path.join(sm, "data")),
                        compression=False)
    for h, b in zip(hashes, blocks):
        mgr1.write_local(h, DataBlock.plain(b).pack())
    scrubber = ScrubWorker(mgr1)
    await scrubber.scrub_batch(hashes[:4])  # warm/compile
    t0 = time.perf_counter()
    bad = 0
    for i in range(0, nblocks, 32):
        bad += await scrubber.scrub_batch(hashes[i:i + 32])
    scrub_bps = nblocks / (time.perf_counter() - t0)

    feeder_stats = dict(managers[0].feeder.stats)
    feeder_perf = {**managers[0].feeder.perf_summary(),
                   **{f"scrub_{k2}": v for k2, v in
                      mgr1.feeder.perf_summary().items()}}
    for s in systems + [s1]:
        await s.stop()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)
    return {
        "put_gbps": round(put_gbps, 3),
        "scrub_blocks_per_s": round(scrub_bps, 1),
        "scrub_corrupt": bad,
        "feeder_device_items": feeder_stats["device_items"],
        "feeder_max_batch": feeder_stats["max_batch"],
        "feeder_mbps": feeder_perf,
    }


def main() -> None:
    from garage_tpu.block.feeder import probe_device

    probe = probe_device(timeout=180.0)
    if not probe["ok"]:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if not probe["ok"]:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform

    extra: dict = {"platform": platform}
    if probe.get("error"):
        extra["probe_error"] = probe["error"]

    gbps = bench_rs_encode(jax, platform)
    extra["blake3_gbps"] = round(bench_blake3(jax, platform), 3)

    tmp = tempfile.mkdtemp(
        prefix="gt_bench_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    try:
        extra.update(asyncio.run(
            asyncio.wait_for(_put_cluster_bench(tmp, platform), 600)))
    except Exception as e:  # system bench must never kill the headline
        extra["put_error"] = f"{type(e).__name__}: {e}"[:300]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    print(json.dumps({
        "metric": "rs_10_4_encode",
        "value": round(gbps, 3),
        "unit": f"GB/s/chip[{platform}]",
        "vs_baseline": round(gbps / 4.0, 3),
        **extra,
    }))


if __name__ == "__main__":
    main()
