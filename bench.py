"""Headline benchmark: RS(10,4) erasure encode throughput, GB/s per chip.

Prints exactly one JSON line. Baseline: 4.0 GB/s/chip (BASELINE.md,
driver target for the north-star metric "RS(10,4) encode MB/s").
Runs on whatever backend JAX finds (real TPU under the driver).
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    from garage_tpu.ops import rs

    k, m = 10, 4
    shard_len = 1 << 20  # 1 MiB shards -> 10 MiB stripes (16 MiB-part regime)
    batch = 8
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(batch, k, shard_len), dtype=np.uint8)
    data = jax.device_put(data)

    parity = rs.encode(k, m, data)  # compile + warm
    jax.block_until_ready(parity)

    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        parity = rs.encode(k, m, data)
    jax.block_until_ready(parity)
    dt = time.perf_counter() - t0

    gbps = batch * k * shard_len * iters / dt / 1e9
    print(
        json.dumps(
            {
                "metric": "rs_10_4_encode",
                "value": round(gbps, 3),
                "unit": "GB/s/chip",
                "vs_baseline": round(gbps / 4.0, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
