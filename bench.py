"""Headline benchmark for the TPU-native block data path.

Prints exactly one JSON line. Headline metric: RS(10,4) erasure encode
GB/s per chip (BASELINE.md driver target: 4.0 GB/s/chip). The same line
carries the system-level numbers the north star asks for ("S3 PutObject
GB/s/chip; RS encode MB/s; scrub blocks/s"):

  put_gbps             block throughput measured THROUGH
                       BlockManager.rpc_put_block on an in-process
                       6-node erasure(4,2) loopback cluster (quorum-
                       acked writes; host/native or device per feeder
                       calibration)
  device_put_gbps      same path with DeviceFeeder(mode="require"):
                       every encode batch forced onto the accelerator —
                       proves the device data path end to end
                       (feeder_device_items > 0)
  cpu_put_gbps         CPU BASELINE (BASELINE.md row 1): same cluster
                       shape, replicate-3 whole-block writes, feeder
                       mode="off" — the reference's replication
                       strategy on the host path
  scrub_blocks_per_s   ScrubWorker.scrub_batch over stored 1 MiB
                       blocks, content-hash verified in batched passes
  cpu_scrub_blocks_per_s  scrub with feeder mode="off" (baseline row 5)
  blake3_gbps          batched BLAKE3 content hashing on device

A broken accelerator tunnel can hang JAX init forever, so the default
backend is probed in a subprocess with a timeout (block/feeder.py).
The probe RETRIES with short timeouts spread over time (r4's capture
lost its TPU numbers to one unlucky 180 s wait), and a CPU-fallback
run keeps re-probing between segments: if the tunnel comes alive the
bench re-execs itself once so a fresh interpreter captures the full
device segment set. Landed probes are disk-cached (TTL 10 min). On
final failure everything falls back to CPU with smaller problem sizes
and the probe error is carried in the output so the fallback is never
silent.

Exit is via os._exit(0) after the JSON line: the axon PJRT plugin can
SIGABRT/SIGSEGV in its C++ teardown when a tunneled device was touched
(observed r3: rc=134 after a correct JSON line). All real cleanup
(cluster stop, feeder stop, tmpdir removal) happens before that; the
hard-exit only skips interpreter/XLA destructor roulette.
"""

from __future__ import annotations

import asyncio
import json
import os
import shutil
import tempfile
import time

import numpy as np


def _best_of_reps(run_chain, amount: float, unit_div: float,
                  slow_below: float, platform: str, reps: int = 4) -> float:
    """Best-of-N timing SPREAD OVER TIME: the dev tunnel is co-tenant
    noisy on the scale of minutes, so back-to-back reps all land in the
    same congestion window; sleeping between slow reps samples several
    windows. run_chain() executes one full dependency chain including
    its end-of-chain sync; the rate is amount/unit_div per second."""
    best = 0.0
    for rep in range(reps):
        t0 = time.perf_counter()
        run_chain()
        dt = time.perf_counter() - t0
        best = max(best, amount / unit_div / dt)
        if platform != "cpu" and rep < reps - 1 and best < slow_below:
            time.sleep(8.0)
    return best


def bench_rs_encode(jax, platform: str) -> float:
    """Sustained RS(10,4) encode GB/s, measured with a DEPENDENCY CHAIN:
    each iteration's input folds in the previous parity, so iterations
    cannot overlap and a single end-of-chain sync gives wall-clock for
    exactly `iters` sequential encodes (per-call dispatch overhead
    amortized — the number a busy PUT pipeline sustains)."""
    import jax.numpy as jnp

    from garage_tpu.ops import rs

    k, m = 10, 4
    if platform == "cpu":
        shard_len, batch, iters = 1 << 16, 4, 3  # keep CPU fallback quick
    else:
        shard_len, batch, iters = 1 << 20, 8, 20  # 80 MiB per step
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(batch, k, shard_len), dtype=np.uint8)
    data = jax.device_put(data)

    @jax.jit
    def step(x):
        # the PRODUCTION encode entry point (rs.encode selects the XLA
        # bit-matmul or, with GARAGE_TPU_PALLAS, the fused Pallas
        # kernel); the xor/concat fold adds a little extra work, making
        # the figure slightly conservative
        p = rs.encode(k, m, x)
        pad = jnp.zeros((batch, k - 2 * m, shard_len), jnp.uint8)
        return x ^ jnp.concatenate([p, p, pad], axis=1)

    x = step(data)  # compile + warm
    _ = np.asarray(x[0, 0, :8])

    def chain():
        x = data
        for _ in range(iters):
            x = step(x)
        _ = np.asarray(x[0, 0, :8])  # one tiny d2h: full-chain completion

    return _best_of_reps(chain, batch * k * shard_len * iters, 1e9, 8.0,
                         platform)


def bench_blake3(jax, platform: str) -> tuple[float, float]:
    """-> (end_to_end_gbps, device_resident_gbps).

    end_to_end includes the host->device transfer each call (what a
    host-resident data path pays); device_resident chains iterations on
    device data with a digest fold (no overlap possible) — the kernel's
    own rate, which is what the PUT pipeline gets when blocks are
    already device-resident after the RS encode (DEVICE_PATH.md)."""
    import jax.numpy as jnp

    from garage_tpu.ops import treehash

    if platform == "cpu":
        batch, iters = 4, 2
    else:
        batch, iters = 32, 8
    rng = np.random.default_rng(1)
    msgs = rng.integers(0, 256, size=(batch, 1 << 20), dtype=np.uint8)
    lengths = np.full(batch, 1 << 20, dtype=np.int32)
    treehash.hash_batch_jax(msgs, lengths)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(max(iters // 2, 2)):
        treehash.hash_batch_jax(msgs, lengths)
    dt = time.perf_counter() - t0
    e2e = batch * (1 << 20) * max(iters // 2, 2) / dt / 1e9

    n_chunks = (1 << 20) // treehash.CHUNK_LEN
    rows = jnp.asarray(msgs)
    lengths_d = jax.device_put(lengths)

    @jax.jit
    def step(x):
        cv = treehash.hash_rows(x, lengths_d, n_chunks)  # (B, 8) u32
        fold = jnp.broadcast_to(cv.astype(jnp.uint8)[:, :1], x.shape)
        return x ^ fold

    x = step(rows)
    x.block_until_ready()

    def chain():
        nonlocal x
        for _ in range(iters):
            x = step(x)
        x.block_until_ready()

    best = _best_of_reps(chain, batch * (1 << 20) * iters, 1e9, 1.5,
                         platform)
    return e2e, best


def bench_scrub_kernel(jax, platform: str) -> float:
    """Device-resident parity-check scrub DETECT rate, in logical
    1 MiB blocks/s (VERDICT r4 next-round #2: a driver-captured number
    behind the "scrub ≥10×" kernel claim, not just DEVICE_PATH.md's
    writeup).

    This is the PRODUCT deep-scrub detect kernel
    (ScrubWorker._deep_scrub -> feeder.parity_check ->
    ops/rs.parity_check): re-derive the m parity shards from the k
    stored data shards (GF(2^8) bit-matmul — the same kernel as the
    encode headline) and compare with the stored parity; any
    single-shard corruption flips every parity row, so a clean compare
    certifies the stripe without per-shard hashing. Localization +
    repair (decode + content-hash, ScrubWorker._repair_stripe) run
    host-side only on flagged stripes. Chained like bench_rs_encode:
    each iteration's data folds in the previous verdict, so iterations
    cannot overlap and one end-of-chain sync times `iters` sequential
    passes. blocks/s counts logical pre-encode bytes (k·S) in MiB."""
    import jax.numpy as jnp

    from garage_tpu.ops import gf256, rs

    k, m = 10, 4
    if platform == "cpu":
        shard_len, batch, iters = 1 << 16, 4, 3
    else:
        shard_len, batch, iters = 1 << 20, 8, 20  # 80 MiB data per step
    parity_bits = gf256.bitmat_t_for(rs.parity_matrix(k, m))
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, size=(batch, k, shard_len), dtype=np.uint8)
    parity = rs.encode(k, m, data)
    shards = jnp.concatenate([jnp.asarray(data), parity], axis=1)

    @jax.jit
    def step(x):
        d = x[:, :k, :]
        p2 = gf256.bit_matmul_apply(parity_bits, d)
        bad = jnp.any(p2 != x[:, k:, :], axis=(1, 2))  # (B,) detect verdict
        # fold the verdict into the data so the next iteration depends
        # on this one (same discipline as bench_rs_encode); stored
        # parity becomes p2 so the compare work never degenerates
        fold = bad.astype(jnp.uint8)[:, None, None]
        return jnp.concatenate([d ^ fold, p2], axis=1)

    x = step(shards)  # compile + warm
    _ = np.asarray(x[0, 0, :8])

    def chain():
        x = shards
        for _ in range(iters):
            x = step(x)
        _ = np.asarray(x[0, 0, :8])

    return _best_of_reps(chain, batch * k * shard_len * iters, 1 << 20,
                         4000, platform)


async def _build_cluster(tmp: str, n: int, rm, device_mode: str,
                         compression: bool = False,
                         ping_interval: float = 10.0):
    """In-process loopback cluster: n Systems + BlockManagers."""
    from garage_tpu.block import BlockManager, DataLayout
    from garage_tpu.db import open_db
    from garage_tpu.net import LocalNetwork, NetApp
    from garage_tpu.rpc import System
    from garage_tpu.rpc.layout import NodeRole

    net = LocalNetwork()
    systems, managers = [], []
    for i in range(n):
        app = NetApp(b"bench-net")
        net.register(app)
        meta = os.path.join(tmp, f"node{i}")
        os.makedirs(meta, exist_ok=True)
        s = System(app, rm, meta, status_interval=0.5,
                   ping_interval=ping_interval)
        systems.append(s)
    tasks = [asyncio.create_task(s.run()) for s in systems]
    for s in systems[1:]:
        await s.netapp.try_connect(systems[0].netapp.public_addr,
                                   systems[0].id)
        s.peering.add_peer(systems[0].netapp.public_addr, systems[0].id)
    deadline = asyncio.get_event_loop().time() + 15
    while asyncio.get_event_loop().time() < deadline:
        if all(len(s.netapp.conns) == n - 1 for s in systems):
            break
        await asyncio.sleep(0.05)
    lm = systems[0].layout_manager
    for s in systems:
        lm.history.stage_role(s.id, NodeRole(zone="z1", capacity=1 << 30))
    lm.apply_staged(None)
    while asyncio.get_event_loop().time() < deadline:
        if all(s.layout_manager.history.current().version == 1
               for s in systems):
            break
        await asyncio.sleep(0.05)
    for i, s in enumerate(systems):
        db = open_db(os.path.join(tmp, f"node{i}", "db"), engine="memory")
        lay = DataLayout.single(os.path.join(tmp, f"node{i}", "data"))
        managers.append(BlockManager(s, db, lay, compression=compression,
                                     device_mode=device_mode))
    return systems, managers, tasks


async def _teardown(systems, managers, tasks) -> None:
    for mg in managers:
        await mg.stop()
    for s in systems:
        await s.stop()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)


async def _settle_feeder(feeder, timeout: float = 150.0) -> None:
    """Wait for the one-time device probe + calibration to finish so the
    timed window measures steady state, not jax-import/XLA-compile
    startup cost (a server pays that once at boot, off the request
    path). No-op when the feeder is pinned host/device."""
    if feeder.mode != "auto":
        return
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if feeder._device_ok is not None and not feeder._calibrating \
                and not feeder._probing:
            return
        await asyncio.sleep(0.25)


async def _pump_blocks(manager, hashes, blocks, start: int,
                       conc: int = 8) -> float:
    """Drive rpc_put_block with a fixed worker pool (no O(n^2)
    asyncio.wait churn); returns wall seconds."""
    counter = iter(range(start, len(blocks)))
    t0 = time.perf_counter()

    async def worker():
        for j in counter:
            await manager.rpc_put_block(hashes[j], blocks[j])

    await asyncio.gather(*[worker() for _ in range(conc)])
    return time.perf_counter() - t0


async def _put_cluster_bench(tmp: str, platform: str, nblocks: int,
                             device_mode: str, erasure: bool) -> dict:
    """Cluster bench: pump 1 MiB blocks through BlockManager.rpc_put_block
    — the real quorum write path — then scrub what landed."""
    from garage_tpu.block.block import DataBlock
    from garage_tpu.block.repair import ScrubWorker
    from garage_tpu.db import open_db
    from garage_tpu.net import NetApp
    from garage_tpu.rpc import ReplicationMode, System
    from garage_tpu.utils.data import blake3sum

    n, k, m = 6, 4, 2
    block_len = 1 << 20
    rm = (ReplicationMode.parse(3, erasure=f"{k},{m}") if erasure
          else ReplicationMode.parse(3))
    systems, managers, tasks = await _build_cluster(tmp, n, rm, device_mode)

    rng = np.random.default_rng(2)
    blocks = [rng.integers(0, 256, block_len, dtype=np.uint8).tobytes()
              for _ in range(nblocks)]
    hashes = [blake3sum(b) for b in blocks]

    for i in range(2):  # warm/compile the encode path
        await managers[0].rpc_put_block(hashes[i], blocks[i])
    await _settle_feeder(managers[0].feeder)
    dt = await _pump_blocks(managers[0], hashes, blocks, 2)
    dt = min(dt, await _pump_blocks(managers[0], hashes, blocks, 2))
    put_gbps = (nblocks - 2) * block_len / dt / 1e9

    # ---- scrub: batched verify over locally stored whole blocks --------
    from garage_tpu.block import BlockManager, DataLayout
    from garage_tpu.net import LocalNetwork

    net1 = LocalNetwork()
    app = NetApp(b"bench-net")
    net1.register(app)
    sm = os.path.join(tmp, "scrubnode")
    os.makedirs(sm, exist_ok=True)
    s1 = System(app, ReplicationMode.parse(1), sm,
                status_interval=3600.0, ping_interval=3600.0)
    db1 = open_db(os.path.join(sm, "db"), engine="memory")
    mgr1 = BlockManager(s1, db1, DataLayout.single(os.path.join(sm, "data")),
                        compression=False, device_mode=device_mode)
    for h, b in zip(hashes, blocks):
        mgr1.write_local(h, DataBlock.plain(b).pack())
    scrubber = ScrubWorker(mgr1)
    await scrubber.scrub_batch(hashes[:4])  # warm/compile
    await _settle_feeder(mgr1.feeder)
    scrub_bps, bad = 0.0, 0
    for _rep in range(2):  # best-of-2 against co-tenant noise
        t0 = time.perf_counter()
        bad = 0
        for i in range(0, nblocks, 32):
            bad += await scrubber.scrub_batch(hashes[i:i + 32])
        scrub_bps = max(scrub_bps, nblocks / (time.perf_counter() - t0))

    feeder_stats = dict(managers[0].feeder.stats)
    feeder_pipe = managers[0].feeder.pipeline_stats()
    feeder_perf = {**managers[0].feeder.perf_summary(),
                   **{f"scrub_{k2}": v for k2, v in
                      mgr1.feeder.perf_summary().items()}}
    # wire+disk bytes per 1 MiB block: the erasure path's structural
    # advantage (k+m shards of 1/k each vs `factor` whole copies) that
    # an in-process loopback bench cannot price — on a real network and
    # disks, replicate-3 moves 2x the bytes RS(4,2) does
    if erasure:
        wire = (k + m) * ((block_len + k - 1) // k + 16) / (1 << 20)
    else:
        wire = 3.0
    await _teardown(systems + [s1], managers + [mgr1], tasks)
    return {
        "put_gbps": round(put_gbps, 3),
        "put_wire_mib_per_block": round(wire, 2),
        "scrub_blocks_per_s": round(scrub_bps, 1),
        "scrub_corrupt": bad,
        # repairs that localized from the packed cache tier (ISSUE 18)
        # instead of gathering the stripe; 0.0 on this single-node
        # whole-block lane — bench_cache_tier prices the cluster case
        "scrub_cache_hit_rate": round(
            scrubber.scrub_cache_hits
            / max(scrubber.scrub_cache_lookups, 1), 3),
        "feeder_device_items": feeder_stats["device_items"],
        "feeder_max_batch": feeder_stats["max_batch"],
        "feeder_mbps": feeder_perf,
        # staged-pipeline engagement: device-busy/wall (> 1.0 means
        # transfer really overlapped compute), the padding tax of
        # fixed-shape launches, and how many XLA programs were built —
        # so the next BENCH_r*.json distinguishes "tunnel down" from
        # "pipeline not overlapping"
        "feeder_overlap_efficiency": feeder_pipe["overlap_efficiency"],
        "feeder_pad_waste_pct": round(
            100.0 * feeder_stats["pad_waste_bytes"]
            / max(feeder_stats["pad_waste_bytes"]
                  + feeder_stats["device_bytes"], 1), 2),
        "feeder_recompiles": feeder_stats["recompiles"],
        "feeder_mesh_batches": feeder_stats["mesh_batches"],
    }


def bench_s3_put(nobj: int, obj_mib: int = 4, device: bool = False) -> dict:
    """The north-star metric measured at its real boundary: S3 PutObject
    through a forked single-node server — HTTP parse, SigV4, chunker,
    MD5+BLAKE3, block store — then GetObject readback. Uses the test
    harness's server fork + independent signer; UNSIGNED-PAYLOAD (the
    common SDK choice for HTTPS) so the signature pass is one HMAC, not
    a full-body SHA256.

    device=True forks the server with the TPU feeder REQUIRED on the
    live PUT path (no JAX_PLATFORMS=cpu pin) and scrapes its /metrics
    for feeder_device_items — the end-to-end proof that live S3 PUTs
    batch through the accelerator (VERDICT r4 weak #2)."""
    import concurrent.futures
    import shutil
    import subprocess
    import sys
    import tempfile
    import urllib.request

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "tests"))
    from s3util import S3Client
    from test_s3_api import REPO, Server

    tmp = tempfile.mkdtemp(
        prefix="gt_s3bench_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)

    class DeviceServer(Server):
        """Forked server allowed to open the real accelerator: the
        conformance harness pins its servers to cpu + feeder off; the
        device segment needs the opposite."""

        def start(self) -> None:
            import select

            # PREPEND the repo to PYTHONPATH — replacing it would drop
            # the accelerator plugin's site dir (e.g. /root/.axon_site)
            # and the child would silently lose the device: unpinned
            # discovery falls back to cpu, and that negative verdict
            # poisons the probe cache. This cost the first r5 capture.
            pp = REPO + ((os.pathsep + os.environ["PYTHONPATH"])
                         if os.environ.get("PYTHONPATH") else "")
            env = dict(os.environ, PYTHONPATH=pp, PYTHONUNBUFFERED="1",
                       GARAGE_TPU_DEVICE="require")
            # Drop the platform pin ONLY if it pins cpu (the test
            # conftest's pin). A real-accelerator pin (e.g. axon) must
            # survive: unpinned discovery silently falls back to cpu
            # when plugin init fails under co-tenant load, and the
            # resulting NEGATIVE probe verdict lands in a different
            # cache namespace where it poisons later probes — the
            # exact failure that cost the first r5 live-path capture.
            if env.get("JAX_PLATFORMS", "").strip().lower() in ("", "cpu"):
                env.pop("JAX_PLATFORMS", None)
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "garage_tpu.cli.server",
                 "--config", self.config_path, "--log-level", "warning"],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            # select-with-deadline, NOT bare readline(): a device server
            # hung in JAX init (the documented tunnel failure mode)
            # would block readline forever and wedge the whole bench
            deadline = time.monotonic() + 120
            buf = ""
            while time.monotonic() < deadline:
                r, _, _ = select.select([self.proc.stdout], [], [], 5.0)
                if r:
                    line = self.proc.stdout.readline()
                    buf += line
                    if "ready" in line:
                        return
                if self.proc.poll() is not None:
                    raise RuntimeError("server died: " + buf)
            self.proc.kill()
            raise RuntimeError("device server did not come up in 120s")

    srv = (DeviceServer if device else Server)(tmp)
    # the conformance harness uses tiny 64 KiB blocks; the throughput
    # bench wants the production default
    with open(srv.config_path) as f:
        cfg = f.read()
    assert "block_size = 65536" in cfg, "test harness config drifted"
    with open(srv.config_path, "w") as f:
        f.write(cfg.replace("block_size = 65536", "block_size = 1048576"))
    if not device:
        os.environ.setdefault("GARAGE_TPU_DEVICE", "off")
    try:
        srv.start()
        srv.setup_layout_and_key()
        cli = S3Client("127.0.0.1", srv.s3_port, srv.key_id, srv.secret)
        st, _, body = cli.request("PUT", "/bench")
        assert st == 200, body

        import json as _json

        def admin_tuning(spec: dict | None = None) -> dict:
            """POST (spec given) or GET the live /v1/s3/tuning knobs."""
            rq = urllib.request.Request(
                f"http://127.0.0.1:{srv.admin_port}/v1/s3/tuning",
                data=(_json.dumps(spec).encode()
                      if spec is not None else None),
                method="POST" if spec is not None else "GET",
                headers={"authorization": "Bearer test-admin-token"})
            with urllib.request.urlopen(rq, timeout=10) as r:
                return _json.loads(r.read().decode())

        # cache OFF for every cold segment: s3_put/get/range/readahead
        # numbers must keep measuring the store path (and stay
        # comparable with pre-cache rounds); the hot-cache segment
        # below re-enables it explicitly
        admin_tuning({"read_cache_max_bytes": 0})
        size = obj_mib << 20
        data = np.random.default_rng(7).integers(
            0, 256, size, dtype=np.uint8).tobytes()

        # device mode proves the live path, not throughput: a crawling
        # tunnel moves single-digit MB/s, so give those requests a
        # timeout that survives it
        rq_timeout = 240.0 if device else 30.0

        def put(i):
            st, _, b = cli.request("PUT", f"/bench/o{i}", body=data,
                                   unsigned_payload=True,
                                   timeout=rq_timeout)
            assert st == 200, b[:200]

        def get(i):
            st, _, b = cli.request("GET", f"/bench/o{i}",
                                   timeout=rq_timeout)
            assert st == 200 and len(b) == size
        # warm (device mode: triggers jax import + compile in the
        # server; the feeder settles off the timed window). Device
        # mode retries transport-level failures: rq_timeout covers the
        # common cold-probe wait, but connection resets and the
        # worst-case negative-then-forced probe chain can still
        # exhaust a single attempt.
        warm_attempts = 5 if device else 1
        for _w in range(warm_attempts):
            try:
                put(0)
                break
            except AssertionError:
                # the server ANSWERED with an error — deterministic
                # (e.g. probe verdict: tunnel dead); retrying the same
                # server just burns the 240 s timeout repeatedly
                raise
            except Exception:
                if _w == warm_attempts - 1:
                    raise
                time.sleep(5.0)
        if device:
            time.sleep(5.0)
            put(0)
        best_put = best_get = 0.0
        with concurrent.futures.ThreadPoolExecutor(4) as pool:
            for _rep in range(2 if device else 3):  # best-of across
                # co-tenant windows (device mode stays short)
                t0 = time.perf_counter()
                list(pool.map(put, range(nobj)))
                dt = time.perf_counter() - t0
                best_put = max(best_put, nobj * size / dt / 1e9)
                t0 = time.perf_counter()
                list(pool.map(get, range(nobj)))
                dt = time.perf_counter() - t0
                best_get = max(best_get, nobj * size / dt / 1e9)
        out = {"s3_put_gbps": round(best_put, 3),
               "s3_get_gbps": round(best_get, 3)}
        if not device:
            # ---- range reads + readahead sweep (ISSUE 2) -------------
            lo, hi = size // 4, size // 4 + size // 2  # mid-object,
            # starts mid-block: exercises the partial-block slice path

            def get_range(i):
                st, _, b = cli.request(
                    "GET", f"/bench/o{i}",
                    headers={"range": f"bytes={lo}-{hi - 1}"},
                    timeout=rq_timeout)
                assert st == 206 and len(b) == hi - lo

            best_range = 0.0
            with concurrent.futures.ThreadPoolExecutor(4) as pool:
                for _rep in range(3):
                    t0 = time.perf_counter()
                    list(pool.map(get_range, range(nobj)))
                    dt = time.perf_counter() - t0
                    best_range = max(best_range,
                                     nobj * (hi - lo) / dt / 1e9)
            out["s3_get_range_gbps"] = round(best_range, 3)

            # GET throughput vs readahead depth (0 = the pre-pipeline
            # sequential behavior, the fallback switch) — flipped at
            # runtime through the admin API, no server restarts
            sweep = {}
            try:
                with concurrent.futures.ThreadPoolExecutor(4) as pool:
                    for ra in (0, 1, 3, 6):
                        admin_tuning({"get_readahead_blocks": ra})
                        best = 0.0
                        for _rep in range(2):
                            t0 = time.perf_counter()
                            list(pool.map(get, range(nobj)))
                            dt = time.perf_counter() - t0
                            best = max(best, nobj * size / dt / 1e9)
                        sweep[str(ra)] = round(best, 3)
                out["s3_get_readahead_sweep"] = sweep
                if sweep.get("0"):
                    out["s3_get_readahead_speedup"] = round(
                        max(sweep.values()) / sweep["0"], 2)
            finally:
                admin_tuning({"get_readahead_blocks": 3})

            # ---- hot-block read cache (ISSUE 3) ----------------------
            # cache on/off sweep under the SAME harness: 8 client
            # threads (the 4-thread s3_get leg above can bottleneck on
            # the Python client; hot-vs-cold is about the SERVER's
            # per-GET work, so drive it harder), cache sized to hold
            # the working set twice over, one warming pass to fill
            # probation, timed re-reads promote + hit; then the
            # identical loop with the cache off for the cold leg.
            def timed_get_pass(reps=3):
                best = 0.0
                with concurrent.futures.ThreadPoolExecutor(8) as p:
                    for _rep in range(reps):
                        t0 = time.perf_counter()
                        list(p.map(get, range(nobj)))
                        dt = time.perf_counter() - t0
                        best = max(best, nobj * size / dt / 1e9)
                return best

            try:
                admin_tuning({"read_cache_max_bytes": 2 * nobj * size})
                with concurrent.futures.ThreadPoolExecutor(8) as p:
                    list(p.map(get, range(nobj)))  # warm: miss-fill
                s0 = admin_tuning()["read_cache"]
                best_hot = timed_get_pass()
                s1 = admin_tuning()["read_cache"]
                admin_tuning({"read_cache_max_bytes": 0})  # sweep: off
                best_cold = timed_get_pass()
                dh = s1["hits"] - s0["hits"]
                dm = s1["misses"] - s0["misses"]
                out["s3_get_hot_gbps"] = round(best_hot, 3)
                out["s3_get_cold_gbps"] = round(best_cold, 3)
                out["cache_hit_rate"] = round(dh / max(dh + dm, 1), 3)
                if best_cold:
                    out["s3_get_hot_vs_cold"] = round(
                        best_hot / best_cold, 2)
            finally:
                # leave it off for the multipart leg (stays store-path)
                admin_tuning({"read_cache_max_bytes": 0})
        if not device:
            # multipart leg (BASELINE rows 3/4: big-part uploads):
            # 4 concurrent 8 MiB UploadParts + Complete, best of 2
            import xml.etree.ElementTree as ET

            part_mib, nparts = 8, 4
            pdata = np.random.default_rng(9).integers(
                0, 256, part_mib << 20, dtype=np.uint8).tobytes()
            best_mpu = 0.0
            for rep in range(2):
                st, _, b = cli.request("POST", f"/bench/mpu{rep}",
                                       query=[("uploads", "")])
                assert st == 200, b[:200]
                upload_id = ET.fromstring(b).findtext(
                    "{*}UploadId") or ET.fromstring(b).findtext("UploadId")

                def put_part(pn):
                    st, hdrs, b2 = cli.request(
                        "PUT", f"/bench/mpu{rep}",
                        query=[("partNumber", str(pn)),
                               ("uploadId", upload_id)],
                        body=pdata, unsigned_payload=True)
                    assert st == 200, b2[:200]
                    return pn, dict(hdrs)["etag"].strip('"')

                t0 = time.perf_counter()
                with concurrent.futures.ThreadPoolExecutor(4) as pool:
                    etags = dict(pool.map(put_part, range(1, nparts + 1)))
                xml_parts = "".join(
                    f"<Part><PartNumber>{pn}</PartNumber>"
                    f"<ETag>\"{etags[pn]}\"</ETag></Part>"
                    for pn in sorted(etags))
                st, _, b = cli.request(
                    "POST", f"/bench/mpu{rep}",
                    query=[("uploadId", upload_id)],
                    body=(f"<CompleteMultipartUpload>{xml_parts}"
                          f"</CompleteMultipartUpload>").encode())
                assert st == 200, b[:300]
                dt = time.perf_counter() - t0
                best_mpu = max(best_mpu,
                               nparts * (part_mib << 20) / dt / 1e9)
            out["s3_multipart_put_gbps"] = round(best_mpu, 3)
        if device:
            # scrape the LIVE server's feeder counters before stopping
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.admin_port}/metrics",
                    timeout=10) as r:
                metrics = r.read().decode()
            scr: dict[str, float] = {}
            for line in metrics.splitlines():
                if not line.startswith("feeder_"):
                    continue
                name = line.split()[0].split("{")[0]
                # labeled series (pipeline busy per stage) sum up
                scr[name] = scr.get(name, 0.0) + float(line.split()[-1])
            waste = scr.get("feeder_pad_waste_bytes", 0.0)
            devbytes = scr.get("feeder_device_bytes", 0.0)
            out = {"s3_device_put_gbps": out["s3_put_gbps"],
                   "s3_device_get_gbps": out["s3_get_gbps"],
                   "s3_feeder_device_items":
                       int(scr.get("feeder_device_items", 0)),
                   "s3_feeder_device_batches":
                       int(scr.get("feeder_device_batches", 0)),
                   # pipeline engagement next to the proof counter:
                   # "tunnel down" reads as device_items == 0, while
                   # "engaged but serial" reads as items > 0 with
                   # overlap_efficiency <= 1.0
                   "s3_feeder_overlap_efficiency":
                       scr.get("feeder_overlap_efficiency", 0.0),
                   "s3_feeder_pipeline_busy_s": round(
                       scr.get("feeder_pipeline_busy_seconds", 0.0), 3),
                   "s3_feeder_pipeline_wall_s": round(
                       scr.get("feeder_pipeline_wall_seconds", 0.0), 3),
                   "s3_feeder_pad_waste_pct": round(
                       100.0 * waste / max(waste + devbytes, 1.0), 2),
                   "s3_feeder_recompiles":
                       int(scr.get("feeder_recompiles", 0)),
                   "s3_feeder_mesh_batches":
                       int(scr.get("feeder_mesh_batches", 0))}
        return out
    finally:
        srv.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_put_path(nobj: int = 8, obj_mib: int = 6,
                   stub_gbps: str = "0.02,0.08,0.04",
                   ingest_pool: bool = True) -> dict:
    """Stage-level proof of the wire->device PUT path (ISSUE 17): live
    S3 PUTs into an in-process erasure(4,2) cluster with the STUB
    device backend required and its stage rates pinned LOW, so the
    deterministic modelled sleeps dominate the real CPU work and the
    number that comes out measures how well the FRONTEND feeds the
    device, not the host's kernels.

    Arithmetic of the gate: every body byte rides the feeder twice
    (hash_md5 + encode_put), so per body byte the modelled h2d and
    compute stages each move 2 bytes and d2h moves (k+m)/k (the shard
    payloads). The pipelined ceiling is 1/max(stage multiples/rate);
    a path that serializes the stages gets 1/sum(...) — ~0.6 of the
    ceiling at the default rates. frontend_efficiency = achieved /
    ceiling; >= 0.8 is the CI gate (device_smoke.py).

    Also reported: the copy audit (s3_put_copy_bytes by path vs body
    bytes — the tentpole's "copy-count-one" claim, <= ~1.1x with the
    pinned ingest pool vs >= 3x for the classic path), ingest-pool
    occupancy, and a signed aws-chunked leg that proves the SigV4
    chunk-sha256 lane batches through the same device pipeline."""
    import concurrent.futures
    import pathlib
    import shutil
    import socket as _socket
    import sys
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    for p in (here, os.path.join(here, "tests")):
        if p not in sys.path:
            sys.path.insert(0, p)
    from s3util import S3Client
    from test_model import make_garage_cluster, stop_all

    from garage_tpu.api.s3.api_server import S3ApiServer
    from garage_tpu.model.helper import GarageHelper, allow_all
    from garage_tpu.utils.metrics import registry

    rates = [float(x) for x in stub_gbps.split(",")]
    env_keys = ("GARAGE_TPU_DEVICE", "GARAGE_TPU_DEVICE_BACKEND",
                "GARAGE_TPU_STUB_GBPS", "JAX_PLATFORMS")
    saved = {k: os.environ.get(k) for k in env_keys}
    os.environ.update({"GARAGE_TPU_DEVICE": "require",
                       "GARAGE_TPU_DEVICE_BACKEND": "stub",
                       "GARAGE_TPU_STUB_GBPS": stub_gbps,
                       # the stub needs no accelerator; pinning cpu
                       # keeps plugin discovery out of the measurement
                       "JAX_PLATFORMS": "cpu"})
    tmp = tempfile.mkdtemp(
        prefix="gt_putpath_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    pool = concurrent.futures.ThreadPoolExecutor(max(8, nobj))

    def copy_snapshot() -> dict[str, float]:
        return {labels.get("path", "?"): total
                for labels, _cnt, total, _mx
                in registry().series("s3_put_copy_bytes")}

    async def scenario() -> dict:
        net, garages, tasks = await make_garage_cluster(
            pathlib.Path(tmp), n=6, rf=3, erasure=(4, 2))
        g = garages[0]
        # the pool must cover every stream's in-flight window (1 block
        # being hashed + up to put_parallelism encodes) or lease
        # exhaustion stalls the chunker and the device goes idle —
        # exactly the sizing guidance in DEVICE_PATH.md.
        # ingest_pool=False (--no-ingest-pool) is the A/B control: the
        # classic copy path under identical modelled rates.
        g.config.s3_ingest_buffers = (4 * max(8, nobj)
                                      if ingest_pool else 0)
        helper = GarageHelper(g)
        key = await helper.create_key("putpath-bench")
        bucket = await helper.create_bucket("putpath")
        await helper.set_bucket_key_permissions(bucket.id, key.key_id,
                                                allow_all())
        srv = S3ApiServer(g)
        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        await srv.start("127.0.0.1", port)
        cli = S3Client("127.0.0.1", port, key.key_id,
                       key.params.secret_key, region=g.config.s3_region)
        loop = asyncio.get_running_loop()
        size = obj_mib << 20
        data = np.random.default_rng(17).integers(
            0, 256, size, dtype=np.uint8).tobytes()
        feeder = g.block_manager.feeder
        k, m = g.block_manager.codec.k, g.block_manager.codec.m

        def put(i):
            st, _, b = cli.request("PUT", f"/putpath/o{i}", body=data,
                                   unsigned_payload=True, timeout=120.0)
            assert st == 200, b[:200]

        try:
            # warm: probe verdict, pool allocation, first stub batch
            await loop.run_in_executor(pool, put, 0)
            copy0 = copy_snapshot()
            items0 = feeder.stats["device_items"]
            t0 = time.perf_counter()
            await asyncio.gather(*[loop.run_in_executor(pool, put, i)
                                   for i in range(nobj)])
            dt = time.perf_counter() - t0
            put_gbps = nobj * size / dt / 1e9
            put_items = feeder.stats["device_items"] - items0

            copy1 = copy_snapshot()
            copy_by_path = {p: copy1.get(p, 0.0) - copy0.get(p, 0.0)
                            for p in copy1
                            if copy1.get(p, 0.0) > copy0.get(p, 0.0)}
            body_bytes = float(nobj * size)

            # modelled ceiling at the pinned rates (see docstring)
            mults = (2.0, 2.0, (k + m) / k)
            ceiling = 1.0 / max(mu / r for mu, r in zip(mults, rates))
            serial = 1.0 / sum(mu / r for mu, r in zip(mults, rates))

            pl = feeder.pipeline_stats()
            ipool = getattr(g.block_manager, "_ingest_pool", None)

            # signed aws-chunked leg: per-chunk sha256 through the
            # feeder lane (1 MiB client chunks, concurrent streams)
            sha_items0 = feeder.stats["device_items"]
            chunks = [data[o:o + (1 << 20)]
                      for o in range(0, size, 1 << 20)]

            def put_signed(i):
                st, _, b = cli.put_chunked(f"/putpath/s{i}", chunks)
                assert st == 200, b[:200]

            nsig = min(nobj, 4)
            t0 = time.perf_counter()
            await asyncio.gather(*[
                loop.run_in_executor(pool, put_signed, i)
                for i in range(nsig)])
            sig_dt = time.perf_counter() - t0

            return {
                "put_path_gbps": round(put_gbps, 4),
                "put_path_modeled_ceiling_gbps": round(ceiling, 4),
                "put_path_modeled_serial_gbps": round(serial, 4),
                "frontend_efficiency": round(put_gbps / ceiling, 3),
                "put_copy_bytes_by_path": {
                    p: int(v) for p, v in sorted(copy_by_path.items())},
                "put_copy_ratio": round(
                    sum(copy_by_path.values()) / body_bytes, 3),
                "put_feeder_device_items": put_items,
                "put_pipeline_overlap": pl.get("overlap_efficiency", 0.0),
                "put_ingest_pool": (ipool.stats()
                                    if ipool is not None else None),
                "put_signed_chunked_gbps": round(
                    nsig * size / sig_dt / 1e9, 4),
                "put_sha256_device_items":
                    feeder.stats["device_items"] - sha_items0,
                "put_stub_gbps": stub_gbps,
                # per-lane calibration ledger ([MB, s] per op/backend,
                # exponentially forgotten) and the per-stage busy split
                # — the two readings the TPU recapture runbook
                # (DEVICE_PATH.md) interprets
                "put_lane_perf": {f"{o}/{be}": [round(bb / 1e6, 1),
                                                round(tt, 3)]
                                  for (o, be), (bb, tt)
                                  in feeder._perf.items()},
                "put_stage_busy": pl,
            }
        finally:
            await srv.stop()
            await stop_all(garages, tasks)

    try:
        return asyncio.run(asyncio.wait_for(scenario(), 300))
    finally:
        pool.shutdown(wait=False)
        for kk, v in saved.items():
            if v is None:
                os.environ.pop(kk, None)
            else:
                os.environ[kk] = v
        shutil.rmtree(tmp, ignore_errors=True)


def bench_qos(duration: float = 6.0, nthreads: int = 8,
              obj_mib: int = 1) -> dict:
    """QoS admission control under pressure: sustained S3 PUTs against
    an in-process erasure(4,2) cluster WHILE deep scrub re-walks the
    store, with a deliberately tight bytes/s budget. Reports admitted
    vs offered throughput, the shed rate (503 SlowDown), and what the
    feedback governor did to scrub tranquility while users were
    waiting — the traffic-control plane the qos/ subsystem exists for."""
    import concurrent.futures
    import pathlib
    import shutil
    import sys
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    for p in (here, os.path.join(here, "tests")):
        if p not in sys.path:
            sys.path.insert(0, p)
    from s3util import S3Client
    from test_model import make_garage_cluster, stop_all

    from garage_tpu.api.s3.api_server import S3ApiServer
    from garage_tpu.model.helper import GarageHelper, allow_all
    from garage_tpu.qos.limiter import QosLimits

    tmp = tempfile.mkdtemp(
        prefix="gt_qosbench_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    pool = concurrent.futures.ThreadPoolExecutor(nthreads)

    async def scenario() -> dict:
        import socket as _socket

        net, garages, tasks = await make_garage_cluster(
            pathlib.Path(tmp), n=6, rf=3, erasure=(4, 2))
        g = garages[0]
        helper = GarageHelper(g)
        key = await helper.create_key("qos-bench")
        bucket = await helper.create_bucket("qos-bench")
        await helper.set_bucket_key_permissions(bucket.id, key.key_id,
                                                allow_all())
        srv = S3ApiServer(g)
        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        await srv.start("127.0.0.1", port)
        cli = S3Client("127.0.0.1", port, key.key_id,
                       key.params.secret_key, region=g.config.s3_region)
        loop = asyncio.get_running_loop()
        size = obj_mib << 20
        data = np.random.default_rng(11).integers(
            0, 256, size, dtype=np.uint8).tobytes()

        def put(name):
            st, hdrs, _ = cli.request("PUT", f"/qos-bench/{name}",
                                      body=data, unsigned_payload=True,
                                      timeout=60.0)
            return st

        try:
            # prefill (unlimited) so scrub has stripes to walk — and to
            # measure what this box can actually push, so the budget
            # below meaningfully overloads fast and slow machines alike
            t0 = time.monotonic()
            for st in await asyncio.gather(*[
                    loop.run_in_executor(pool, put, f"seed{i}")
                    for i in range(16)]):
                assert st == 200, st
            prefill_bps = 16 * size / (time.monotonic() - t0)

            # tight budget: ~1/3 of measured capacity, 1 s burst,
            # near-zero waiting room -> sustained overload MUST shed
            limit_bps = max(1 << 20, int(prefill_bps / 3))
            g.qos.set_limits(QosLimits(global_bytes_per_s=limit_bps,
                                       global_bytes_burst=limit_bps,
                                       max_wait_s=0.05))
            if g.qos_governor is not None:
                g.qos_governor.interval = 0.5  # sample fast in a short run

            # deep scrub runs CONCURRENTLY on every node, restarted
            # whenever a pass drains, throttled only by its (governed)
            # tranquility
            stop_scrub = asyncio.Event()

            async def keep_scrubbing():
                while not stop_scrub.is_set():
                    for g2 in garages:
                        sw = g2.block_manager.scrub_worker
                        if sw is not None and sw.state.cursor == b"" \
                                and not sw._due():
                            sw.command("start")
                    await asyncio.sleep(0.5)

            scrub_task = asyncio.create_task(keep_scrubbing())

            counts = {"ok": 0, "shed": 0, "other": 0}
            t_end = time.monotonic() + duration

            def hammer(i):
                n = 0
                while time.monotonic() < t_end:
                    st = put(f"w{i}-{n}")
                    n += 1
                    if st == 200:
                        counts["ok"] += 1
                    elif st == 503:
                        counts["shed"] += 1
                    else:
                        counts["other"] += 1

            t0 = time.monotonic()
            await asyncio.gather(*[loop.run_in_executor(pool, hammer, i)
                                   for i in range(nthreads)])
            dt = time.monotonic() - t0
            stop_scrub.set()
            await scrub_task

            total = counts["ok"] + counts["shed"] + counts["other"]
            deep_checked = sum(
                g2.block_manager.scrub_worker.deep_checked
                for g2 in garages
                if g2.block_manager.scrub_worker is not None)
            gov = g.qos_governor
            sw0 = g.block_manager.scrub_worker
            return {
                "qos_put_admitted_mbps": round(
                    counts["ok"] * size / dt / 1e6, 1),
                "qos_put_offered_mbps": round(
                    total * size / dt / 1e6, 1),
                "qos_limit_mbps": round(limit_bps / 1e6, 1),
                "qos_shed_rate": round(counts["shed"] / max(total, 1), 3),
                "qos_admitted": counts["ok"],
                "qos_sheds": counts["shed"],
                "qos_errors": counts["other"],
                "qos_deep_stripes_checked": deep_checked,
                "qos_governor_pressure": (round(gov.pressure, 3)
                                          if gov is not None else None),
                "qos_scrub_tranquility": (round(sw0.state.tranquility, 2)
                                          if sw0 is not None else None),
            }
        finally:
            await srv.stop()
            await stop_all(garages, tasks)

    try:
        return asyncio.run(asyncio.wait_for(scenario(), 300))
    finally:
        pool.shutdown(wait=False)
        shutil.rmtree(tmp, ignore_errors=True)


def bench_degraded(nhashes: int = 24, block_kib: int = 256) -> dict:
    """Tail latency of quorum GETs with ONE PEER HUNG, hedging on vs
    off — the number the self-healing rpc layer (PR 4) exists to move.

    An in-process 4-node replicate-3 cluster stores blocks whose read
    sets exclude node 0 (so every GET is a real remote read), then a
    chaos `rpc_hang` fault hangs every block RPC to one victim peer.
    The same GET set runs with hedging off and on; per-GET latencies
    give p50/p99. Off: a victim-first GET waits out the (adaptive)
    timeout. On: it costs one hedge delay. Both legs keep adaptive
    timeouts, so the off leg is already the IMPROVED baseline — the
    reported win is hedging's alone, on top of it."""
    import shutil
    import tempfile

    from garage_tpu.chaos import FaultSpec, arm, disarm
    from garage_tpu.rpc import ReplicationMode
    from garage_tpu.utils.data import blake3sum

    tmp = tempfile.mkdtemp(
        prefix="gt_degraded_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)

    def pctl(xs, q):
        s = sorted(xs)
        return s[min(len(s) - 1, int(q * len(s)))]

    async def scenario() -> dict:
        rm = ReplicationMode.parse(3)
        systems, managers, tasks = await _build_cluster(tmp, 4, rm, "off")
        try:
            for m in managers:
                m.cache.configure(max_bytes=0)  # measure the rpc path
            me = systems[0].id
            peers = [s.id for s in systems[1:]]
            # blocks whose read set excludes node 0: with n=4 and rf=3
            # the read set is then exactly the other three nodes, so
            # every GET leaves the node and every peer is a candidate
            rng = np.random.default_rng(21)
            helper = systems[0].layout_helper
            hashes, salt = [], 0
            while len(hashes) < nhashes and salt < 50000:
                salt += 1
                data = rng.integers(0, 256, block_kib << 10,
                                    dtype=np.uint8).tobytes()
                h = blake3sum(data)
                if me not in helper.block_read_nodes_of(h):
                    await managers[0].rpc_put_block(h, data,
                                                    compress=False)
                    hashes.append(h)
            health = systems[0].peering.health

            async def timed_leg(hedge_on: bool):
                disarm()
                health.reset()
                # warm per-peer latency samples so adaptive timeouts
                # and hedge delays engage (the first-ranked peer — the
                # upcoming victim — serves every warm GET)
                for _ in range(3):
                    for h in hashes:
                        await managers[0].rpc_get_block(h,
                                                        cacheable=False)
                # hang whoever currently ranks FIRST, so the fault sits
                # squarely on the hot path of every GET. count=3: below
                # the breaker threshold, so the off leg measures pure
                # timeout cost (1 s, then backed-off) and stays bounded
                # — the breaker's own win is covered by tests, not here
                victim = managers[0].rpc.request_order(list(peers))[0]
                c = arm(seed=77)
                c.add(FaultSpec(kind="rpc_hang",
                                peer=victim.hex()[:8],
                                endpoint="garage_tpu/block",
                                count=3))
                health.hedging_enabled = hedge_on
                lats = []
                for h in hashes:
                    t0 = time.perf_counter()
                    got = await managers[0].rpc_get_block(
                        h, cacheable=False)
                    lats.append(time.perf_counter() - t0)
                    assert got is not None
                fired = c.total_fired
                disarm()
                return lats, fired

            # a ping-driven reorder can shuffle the victim off the hot
            # path between arming and the GETs — a leg where the hang
            # never FIRED measured nothing, so retry until both legs
            # actually injected (same rule as the tests: silent
            # non-injection proves nothing)
            for _attempt in range(3):
                off, f_off = await timed_leg(False)
                hedges0 = health.hedges_launched
                on, f_on = await timed_leg(True)
                hedges = health.hedges_launched - hedges0
                if f_off > 0 and f_on > 0:
                    break
            health.hedging_enabled = True
            out = {
                "degraded_get_p50_off_ms": round(pctl(off, 0.5) * 1e3, 1),
                "degraded_get_p99_off_ms": round(pctl(off, 0.99) * 1e3, 1),
                "degraded_get_p50_on_ms": round(pctl(on, 0.5) * 1e3, 1),
                "degraded_get_p99_on_ms": round(pctl(on, 0.99) * 1e3, 1),
                "degraded_hedges_launched": hedges,
                "degraded_faults_fired_off_on": [f_off, f_on],
            }
            if pctl(on, 0.99) > 0:
                out["degraded_p99_tail_win"] = round(
                    pctl(off, 0.99) / pctl(on, 0.99), 2)
            return out
        finally:
            disarm()
            await _teardown(systems, managers, tasks)

    try:
        return asyncio.run(asyncio.wait_for(scenario(), 300))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_decode(nblocks: int = 24, block_kib: int = 1024,
                 device_mode: str = "off") -> dict:
    """Degraded-GET + scrub-rebuild lane (ISSUE 13) — the read-side
    twin of the encode lane. An in-process 6-node erasure(4,2) cluster
    stores `nblocks`; block i's systematic shard (i % k) is then
    deleted cluster-wide, so every GET is a real degraded decode and
    the run mixes k distinct erasure patterns (the pattern-as-data
    production shape: recompiles must not scale with patterns).

      decode_get_gbps             concurrent degraded GETs end to end
                                  (gather + feeder decode + verify)
      decode_blocks_per_s/_gbps   feeder-routed decode of the gathered
                                  stripes (batched; host or device per
                                  routing/mode)
      decode_direct_blocks_per_s  pre-ISSUE-13 baseline: one serial
                                  numpy decode per stripe on the caller
      rebuild_blocks_per_s        feeder-batched shard rebuild wave
                                  (the resync/scrub repair path) vs
      rebuild_direct_blocks_per_s codec.repair_parts per stripe, serial
      decode_feeder_device_items  read-path device engagement (the
                                  degraded-GET twin of
                                  feeder_device_items)
      decode_recompiles           XLA programs built across the mixed-
                                  pattern decode/rebuild lanes (flat =
                                  the pattern-as-data proof)
    """
    import shutil
    import tempfile

    from garage_tpu.block.codec import shard_nodes_of
    from garage_tpu.ops import rs
    from garage_tpu.rpc import ReplicationMode
    from garage_tpu.utils.data import blake3sum

    k, m = 4, 2
    block_len = block_kib << 10
    tmp = tempfile.mkdtemp(
        prefix="gt_decode_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)

    async def scenario() -> dict:
        rm = ReplicationMode.parse(3, erasure=f"{k},{m}")
        systems, managers, tasks = await _build_cluster(tmp, 6, rm,
                                                        device_mode)
        try:
            for mg in managers:
                mg.cache.configure(max_bytes=0)  # measure the decode path
            rng = np.random.default_rng(5)
            blocks = [rng.integers(0, 256, block_len,
                                   dtype=np.uint8).tobytes()
                      for _ in range(nblocks)]
            hashes = [blake3sum(b) for b in blocks]
            for h, b in zip(hashes, blocks):
                await managers[0].rpc_put_block(h, b, compress=False)
            by_id = {s.id: mg for s, mg in zip(systems, managers)}
            v = systems[0].layout_helper.current()
            # delete block i's systematic shard i%k everywhere it
            # landed: every GET degrades, patterns rotate across k
            missing = []
            for i, h in enumerate(hashes):
                placement = shard_nodes_of(v, h, k + m)
                want = i % k
                mgr = by_id[placement[want]]
                for _ in range(200):  # quorum acks at 5/6; wait for it
                    p = mgr._find(h, [f".s{want}"])
                    if p is not None:
                        break
                    await asyncio.sleep(0.01)
                if p is not None:
                    os.remove(p)
                missing.append(want)
            feeder = managers[0].feeder
            got = await managers[0].rpc_get_block(hashes[0],
                                                  cacheable=False)
            assert got == blocks[0]  # warm/compile the degraded path
            await _settle_feeder(feeder)

            async def pump_gets() -> float:
                counter = iter(range(nblocks))

                async def w():
                    for j in counter:
                        out = await managers[0].rpc_get_block(
                            hashes[j], cacheable=False)
                        assert out == blocks[j]

                t0 = time.perf_counter()
                await asyncio.gather(*[w() for _ in range(8)])
                return time.perf_counter() - t0

            get_dt = await pump_gets()
            get_dt = min(get_dt, await pump_gets())

            # gather each stripe once so the math-only lanes time the
            # decode/rebuild, not the shard fetches
            sets = []
            for h in hashes:
                placement = shard_nodes_of(v, h, k + m)
                g = await managers[0]._gather_parts(h, placement, k)
                parts, cands, _lens = g
                present = tuple(sorted(parts.keys())[:k])
                sets.append((present, [parts[i] for i in present],
                             cands[0]))
            rc0 = feeder.stats["recompiles"]

            async def feeder_decode_lane() -> float:
                t0 = time.perf_counter()
                outs = await asyncio.gather(*[
                    feeder.decode(p, s, plen) for p, s, plen in sets])
                for o, b in zip(outs, blocks):
                    assert len(o) >= len(b)
                return time.perf_counter() - t0

            fdt = await feeder_decode_lane()
            fdt = min(fdt, await feeder_decode_lane())

            def direct_decode() -> float:
                # the pre-batching shape: one numpy matmul per stripe,
                # serial on the caller thread
                t0 = time.perf_counter()
                for present, shards, plen in sets:
                    st = np.stack([np.frombuffer(s, dtype=np.uint8)
                                   for s in shards])
                    rs.join_stripe(rs.decode_np(k, m, present, st), plen)
                return time.perf_counter() - t0

            ddt = await asyncio.to_thread(direct_decode)
            ddt = min(ddt, await asyncio.to_thread(direct_decode))

            async def rebuild_lane() -> float:
                t0 = time.perf_counter()
                outs = await asyncio.gather(*[
                    feeder.repair(p, (miss,), s)
                    for (p, s, _plen), miss in zip(sets, missing)])
                assert all(missing[j] in outs[j]
                           for j in range(nblocks))
                return time.perf_counter() - t0

            rdt = await rebuild_lane()
            rdt = min(rdt, await rebuild_lane())

            codec = managers[0].codec

            def direct_rebuild() -> float:
                t0 = time.perf_counter()
                for (present, shards, _plen), miss in zip(sets, missing):
                    codec.repair_parts(dict(zip(present, shards)),
                                       (miss,))
                return time.perf_counter() - t0

            rddt = await asyncio.to_thread(direct_rebuild)
            rddt = min(rddt, await asyncio.to_thread(direct_rebuild))

            fs = dict(feeder.stats)
            waste = fs["pad_waste_bytes"]
            out = {
                "decode_get_gbps": round(
                    nblocks * block_len / get_dt / 1e9, 3),
                "decode_blocks_per_s": round(nblocks / fdt, 1),
                "decode_gbps": round(nblocks * block_len / fdt / 1e9, 3),
                "decode_direct_blocks_per_s": round(nblocks / ddt, 1),
                "decode_vs_direct": round(ddt / fdt, 2),
                "rebuild_blocks_per_s": round(nblocks / rdt, 1),
                "rebuild_direct_blocks_per_s": round(nblocks / rddt, 1),
                "rebuild_vs_direct": round(rddt / rdt, 2),
                "decode_feeder_items": fs["decode_items"],
                "decode_feeder_device_items": fs["decode_device_items"],
                "decode_recompiles": fs["recompiles"] - rc0,
                "decode_patterns_mixed": len(set(missing)),
                "decode_pad_waste_pct": round(
                    100.0 * waste
                    / max(waste + fs["decode_device_bytes"], 1), 2),
                "decode_feeder_mbps": {
                    op: v for op, v in feeder.perf_summary().items()
                    if op.startswith("decode")},
            }
            return out
        finally:
            await _teardown(systems, managers, tasks)

    try:
        return asyncio.run(asyncio.wait_for(scenario(), 300))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_cache_tier(nblocks: int = 12, block_kib: int = 512,
                     rounds: int = 4, nodes: int = 6) -> dict:
    """Cluster cache tier economics (ISSUE 15). A 6-node erasure(4,2)
    cluster serves a hot working set from EVERY node, tier off vs on:

      cache_tier_hot_get_gbps        cluster hot-GET throughput, tier on
      cache_tier_hot_get_base_gbps   node-local baseline (tier off;
                                     each node keeps its own copy)
      cache_tier_decodes             cluster-wide store decodes for the
                                     hot set with the tier on — the
                                     "~1 per block, not N" proof — vs
      cache_tier_decodes_base        N per block without it
      cache_tier_remote_hit_ms       mean GET served by a remote probe
                                     hit vs
      cache_tier_cold_decode_ms      the cold gather+decode it replaces
      cache_tier_hint_convergence_s  hot-hash hint gossip: heat node0,
                                     time until every peer knows
      cache_tier_flash_decode_amp    (ISSUE 18) cold Zipf flash crowd:
                                     cluster decodes per distinct hot
                                     block with probe leases on, vs
      ..._flash_decode_amp_nolease   the same herd with the lease
                                     wait-mode off (wait_ms=0)
      cache_tier_flash_p99_ms        herd GET p99, leases on/off —
                                     prices the park-and-wake tradeoff
      cache_tier_scrub_cache_hit_rate  stripe repairs localizing from
                                     the packed tier instead of a
                                     cluster gather
      shm_forward_*_us               shm publish+map vs loopback-socket
                                     copy per forward, by payload size
    """
    import shutil
    import socket as socketmod
    import tempfile

    from garage_tpu.rpc import ReplicationMode
    from garage_tpu.utils.data import blake3sum

    block_len = block_kib << 10
    tmp = tempfile.mkdtemp(
        prefix="gt_tier_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)

    async def scenario() -> dict:
        rm = ReplicationMode.parse(3, erasure="4,2")
        systems, managers, tasks = await _build_cluster(
            tmp, nodes, rm, "off", ping_interval=0.3)
        try:
            rng = np.random.default_rng(15)
            blocks = [rng.integers(0, 256, block_len,
                                   dtype=np.uint8).tobytes()
                      for _ in range(nblocks)]
            hashes = [blake3sum(b) for b in blocks]
            for h, b in zip(hashes, blocks):
                await managers[0].rpc_put_block(h, b, compress=False,
                                                cacheable=False)

            def decodes() -> int:
                return sum(m.metrics["store_reads"] for m in managers)

            async def hot_sweep() -> float:
                t0 = time.perf_counter()
                for _ in range(rounds):
                    await asyncio.gather(*[
                        _read_all(m) for m in managers])
                return time.perf_counter() - t0

            async def _read_all(m) -> None:
                for h, b in zip(hashes, blocks):
                    got = await m.rpc_get_block(h)
                    assert len(got) == len(b)

            def reset_caches() -> None:
                for m in managers:
                    m.cache.clear()

            async def warm_once() -> None:
                # ONE node touches the set first (the production shape:
                # some reader is always first; the herd arrives after).
                # With the tier on this seeds the owners via the
                # write-through pushes; wait for them to land.
                await _read_all(managers[0])
                if managers[0].cache_tier.enabled:
                    deadline = time.perf_counter() + 10.0
                    by_node = {s.id: m for s, m in zip(systems,
                                                       managers)}
                    for h in hashes:
                        o = managers[0].cache_tier.owner_of(h)
                        om = by_node[o] if o is not None \
                            else managers[0]
                        while om.cache.get(h) is None \
                                and time.perf_counter() < deadline:
                            await asyncio.sleep(0.01)

            # ---- node-local baseline: tier off ------------------------
            for m in managers:
                m.cache_tier.enabled = False
            d0 = decodes()
            await warm_once()
            base_dt = await hot_sweep()
            base_decodes = decodes() - d0

            # ---- tier on ----------------------------------------------
            reset_caches()
            for m in managers:
                m.cache_tier.enabled = True
            d0 = decodes()
            await warm_once()
            tier_dt = await hot_sweep()
            tier_decodes = decodes() - d0

            # ---- latency lanes ----------------------------------------
            # remote probe-hit GETs: non-owner reads of owner-warm keys
            lat_hit, lat_cold = [], []
            for h, b in zip(hashes, blocks):
                reader = next((m for m in managers
                               if m.cache_tier.owner_of(h) is not None),
                              None)
                if reader is None:
                    continue
                t0 = time.perf_counter()
                got = await reader.rpc_get_block(h)
                dt = time.perf_counter() - t0
                if reader.cache.get(h) is None:  # really remote-served
                    lat_hit.append(dt)
                t0 = time.perf_counter()
                await reader.rpc_get_block(h, cacheable=False)
                lat_cold.append(time.perf_counter() - t0)

            # ---- hint gossip convergence ------------------------------
            # a FRESH hash (never read in the sweeps, so no earlier
            # ping can have carried it): heat it, clock the spread
            fresh = os.urandom(1 << 10)
            hot_h = blake3sum(fresh)
            m0 = managers[0]
            m0.cache.insert(hot_h, fresh)
            m0.cache.get(hot_h)  # a hit makes it gossip-worthy
            t0 = time.perf_counter()
            conv = None
            while time.perf_counter() - t0 < 20.0:
                if all(m.cache_tier.is_hot(hot_h)
                       for m in managers[1:]):
                    conv = time.perf_counter() - t0
                    break
                await asyncio.sleep(0.02)

            # ---- flash crowd: cold-herd decode amplification ----------
            # (ISSUE 18) every node hammers a Zipf-weighted sequence
            # over a fully COLD set, probe leases on vs off. The
            # prefetch lane is parked for the drill: the sweeps above
            # left 120 s-TTL hints everywhere, and owners acting on
            # them mid-herd would decode behind the count.
            from garage_tpu.block.cache_tier import (
                LEASE_WAIT_MS_DEFAULT, PREFETCH_INFLIGHT_DEFAULT)

            zipf_w = 1.0 / np.arange(1, nblocks + 1)
            zipf_w = zipf_w / zipf_w.sum()
            flash_rng = np.random.default_rng(18)
            seqs = [flash_rng.choice(nblocks, size=nblocks * 2,
                                     p=zipf_w) for _ in managers]
            distinct = len({int(i) for seq in seqs for i in seq})

            async def flash(lease_on: bool) -> tuple[float, float]:
                for m in managers:
                    m.cache.clear()
                    m.packed_cache.clear()
                    m.cache_tier.lease_wait_ms = (
                        LEASE_WAIT_MS_DEFAULT if lease_on else 0.0)
                    m.cache_tier.prefetch_inflight = 0
                d0 = decodes()
                lats: list = []

                async def hammer(m, seq):
                    for i in seq:
                        t0 = time.perf_counter()
                        await m.rpc_get_block(hashes[int(i)])
                        lats.append(time.perf_counter() - t0)

                await asyncio.gather(*[hammer(m, seq)
                                       for m, seq in zip(managers,
                                                         seqs)])
                amp = (decodes() - d0) / max(distinct, 1)
                lats.sort()
                p99 = lats[min(len(lats) - 1,
                               int(0.99 * len(lats)))] * 1e3
                return round(amp, 2), round(p99, 3)

            amp_off, p99_off = await flash(lease_on=False)
            amp_on, p99_on = await flash(lease_on=True)
            for m in managers:  # restore the knobs for the next lanes
                m.cache_tier.lease_wait_ms = LEASE_WAIT_MS_DEFAULT
                m.cache_tier.prefetch_inflight = \
                    PREFETCH_INFLIGHT_DEFAULT

            # ---- scrub repair rides the packed tier -------------------
            # forge one shard on a handful of stripes whose scrub
            # leader holds the packed bytes warm: repair localizes from
            # the cache instead of gathering the stripe
            from garage_tpu.block import ScrubWorker
            from garage_tpu.block.codec import shard_nodes_of
            from garage_tpu.block.manager import (pack_shard,
                                                  unpack_shard)

            layout = systems[0].layout_helper.current()
            by_node = {s.id: m for s, m in zip(systems, managers)}
            width = managers[0].codec.width
            sc_hits = sc_lookups = repaired = 0
            for h in hashes[:6]:
                placement = shard_nodes_of(layout, h, width)
                leader = by_node[placement[0]]
                if leader.packed_cache.get(h) is None:
                    # decode once ON the leader (tier lane parked so
                    # the probe can't shortcut it): warms its packed
                    # segment the way a foreground herd would
                    leader.cache.discard(h)
                    tier_was = leader.cache_tier.enabled
                    leader.cache_tier.enabled = False
                    await leader.rpc_get_block(h)
                    leader.cache_tier.enabled = tier_was
                victim = by_node[placement[1]]
                raw = victim.read_local_shard(h, 1)
                payload, packed_len = unpack_shard(raw)
                forged = (bytes(b ^ 0xFF for b in payload[:64])
                          + payload[64:])
                victim.write_local_shard(h, 1,
                                         pack_shard(forged, packed_len))
                sw = ScrubWorker(leader)
                repaired += await sw.scrub_batch([h])
                sc_hits += sw.scrub_cache_hits
                sc_lookups += sw.scrub_cache_lookups

            total = nodes * rounds * nblocks * block_len
            out = {
                "cache_tier_hot_get_gbps": round(total / tier_dt / 1e9,
                                                 3),
                "cache_tier_hot_get_base_gbps": round(
                    total / base_dt / 1e9, 3),
                "cache_tier_decodes": tier_decodes,
                "cache_tier_decodes_base": base_decodes,
                "cache_tier_decodes_per_block": round(
                    tier_decodes / nblocks, 2),
                "cache_tier_remote_hit_ms": round(
                    1e3 * sum(lat_hit) / max(len(lat_hit), 1), 3),
                "cache_tier_cold_decode_ms": round(
                    1e3 * sum(lat_cold) / max(len(lat_cold), 1), 3),
                "cache_tier_hint_convergence_s": (
                    round(conv, 3) if conv is not None else None),
                "cache_tier_probe_hits": sum(
                    m.cache_tier.probe_hits for m in managers),
                # ISSUE 18: cold-herd economics + packed-tier scrub
                "cache_tier_flash_decode_amp": amp_on,
                "cache_tier_flash_decode_amp_nolease": amp_off,
                "cache_tier_flash_p99_ms": p99_on,
                "cache_tier_flash_p99_ms_nolease": p99_off,
                "cache_tier_scrub_repaired": repaired,
                "cache_tier_scrub_cache_hit_rate": round(
                    sc_hits / max(sc_lookups, 1), 3),
            }
            return out
        finally:
            await _teardown(systems, managers, tasks)

    def shm_vs_socket() -> dict:
        """Micro lane: one FORWARD's payload transfer. The shm shape is
        the production one — the owner publishes a hot block once per
        lease and every subsequent forward is a reference + mmap view
        (zero payload copies); the socket shape pays the full payload
        copy through the kernel per forward. shm_publish_*_us prices
        the cold first-publish separately."""
        from garage_tpu.gateway.shm import ShmReader, ShmRing, ring_path

        out = {}
        ring = ShmRing(ring_path(tmp, 99), 64 << 20, lease_s=30.0)
        reader = ShmReader()
        for kib in (64, 256, 1024, 4096):
            payload = os.urandom(kib << 10)
            n_iter = max(8, (16 << 20) // (kib << 10))
            # cold publish: a fresh hash each time = one real write
            t0 = time.perf_counter()
            for i in range(8):
                h = (kib * 1000 + i).to_bytes(32, "big")
                ref = ring.publish(h, payload)
                assert ref is not None
            publish_dt = (time.perf_counter() - t0) / 8
            # hot forward: same block served over and over — publish
            # degrades to a slot-reuse lookup, get maps the view
            h = (kib * 1000).to_bytes(32, "big")
            t0 = time.perf_counter()
            for _ in range(n_iter):
                ref = ring.publish(h, payload)
                mv = reader.get(ref, h)
                assert mv is not None and mv.nbytes == len(payload)
            shm_dt = (time.perf_counter() - t0) / n_iter
            out[f"shm_publish_{kib}k_us"] = round(publish_dt * 1e6, 1)
            # socket: the payload crosses a loopback socketpair
            a, b = socketmod.socketpair()
            try:
                a.setsockopt(socketmod.SOL_SOCKET,
                             socketmod.SO_SNDBUF, 4 << 20)
                b.setsockopt(socketmod.SOL_SOCKET,
                             socketmod.SO_RCVBUF, 4 << 20)
                buf = bytearray(len(payload))

                def pump_one():
                    view = memoryview(buf)
                    got = 0
                    while got < len(payload):
                        got += b.recv_into(view[got:], len(payload) - got)

                import concurrent.futures as cf

                with cf.ThreadPoolExecutor(1) as pool:
                    t0 = time.perf_counter()
                    for _ in range(n_iter):
                        fut = pool.submit(pump_one)
                        a.sendall(payload)
                        fut.result()
                    sock_dt = (time.perf_counter() - t0) / n_iter
            finally:
                a.close()
                b.close()
            out[f"shm_forward_{kib}k_us"] = round(shm_dt * 1e6, 1)
            out[f"shm_socket_{kib}k_us"] = round(sock_dt * 1e6, 1)
            out[f"shm_vs_socket_{kib}k"] = round(
                sock_dt / max(shm_dt, 1e-9), 2)
        ring.close()
        return out

    try:
        res = asyncio.run(asyncio.wait_for(scenario(), 300))
        res.update(shm_vs_socket())
        return res
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_resize(n_nodes: int = 16, nobj: int = 48, obj_kib: int = 256,
                 leg_s: float = 5.0) -> dict:
    """Zero-downtime cluster resize economics (ISSUE 6): foreground
    PUT/GET p50/p99 while a layout transition (add-node, then
    drain-node) rebalances data across a 16-node cluster-in-a-box,
    vs the same workload with no resize — with the qos governor and
    breaker-aware resync placement active, rebalance must yield to
    foreground tails. Also reports the rebalance throughput itself
    (resync bytes moved / transition wall time)."""
    import pathlib
    import shutil
    import sys
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    for p in (here, os.path.join(here, "tests")):
        if p not in sys.path:
            sys.path.insert(0, p)
    from clusterbox import ClusterBox, Workload
    from test_model import put_object_like_api

    from garage_tpu.utils.data import gen_uuid

    tmp = tempfile.mkdtemp(
        prefix="gt_resize_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)

    async def scenario() -> dict:
        # gossip cadence scaled for a 16-node single-core sim: the
        # test default (status every 0.1 s) is thousands of status
        # RPCs/s at this fan-out and would drown the workload in
        # control-plane noise
        box = await ClusterBox(pathlib.Path(tmp), n=n_nodes, rf=3,
                               governor=True, status_interval=0.5,
                               ping_interval=2.0).start()
        try:
            # seed data so the rebalance has bytes to move
            g0 = box.nodes[0].garage
            bucket = gen_uuid()
            rng = np.random.default_rng(31)
            sem = asyncio.Semaphore(8)

            async def seed(i):
                data = rng.integers(0, 256, obj_kib << 10,
                                    dtype=np.uint8).tobytes()
                async with sem:
                    await put_object_like_api(g0, bucket, f"s{i}", data)

            await asyncio.gather(*(seed(i) for i in range(nobj)))
            await asyncio.sleep(4.0)  # let seeding's table queues drain

            # baseline leg: steady-state foreground, no resize
            wb = Workload(box, obj_kib=obj_kib, period=0.02)
            wb.start()
            await asyncio.sleep(leg_s)
            base = await wb.stop()

            # resize leg: the same workload while an add-node and then
            # a drain-node transition rebalance the cluster
            moved0 = sum(nd.manager.metrics["resync_bytes"]
                         for nd in box.live())
            wr = Workload(box, obj_kib=obj_kib, period=0.02)
            wr.start()
            t0 = time.monotonic()
            newbie = await box.add_node()
            orch = box.orchestrator()
            orch.stage_add(newbie.id, "z1", 1 << 30)
            rep_add = await orch.run(timeout=240.0)
            orch.stage_remove(box.nodes[1].id)
            rep_drain = await orch.run(timeout=240.0)
            try:
                await box.wait(lambda: box.resync_backlog() == 0, 90,
                               "rebalance backlog")
            except AssertionError:
                pass  # report what moved either way
            dt = time.monotonic() - t0
            res = await wr.stop()
            moved = sum(nd.manager.metrics["resync_bytes"]
                        for nd in box.live()) - moved0
            out = {
                "resize_nodes": n_nodes,
                "resize_add_transition_s": round(
                    rep_add.total_seconds, 2),
                "resize_drain_transition_s": round(
                    rep_drain.total_seconds, 2),
                "resize_rebalance_mb": round(moved / 1e6, 1),
                "resize_rebalance_mbps": round(
                    moved / max(dt, 1e-9) / 1e6, 2),
                "resize_ops_failed": len(res["failures"]),
                "resize_backlog_left": box.resync_backlog(),
                "resize_get_p50_ms": res["get_p50_ms"],
                "resize_get_p99_ms": res["get_p99_ms"],
                "resize_put_p50_ms": res["put_p50_ms"],
                "resize_put_p99_ms": res["put_p99_ms"],
                "resize_base_get_p99_ms": base["get_p99_ms"],
                "resize_base_put_p99_ms": base["put_p99_ms"],
            }
            if base["get_p99_ms"] and res["get_p99_ms"]:
                out["resize_get_p99_vs_baseline"] = round(
                    res["get_p99_ms"] / base["get_p99_ms"], 2)
            if base["put_p99_ms"] and res["put_p99_ms"]:
                out["resize_put_p99_vs_baseline"] = round(
                    res["put_p99_ms"] / base["put_p99_ms"], 2)
            return out
        finally:
            await box.stop()

    try:
        return asyncio.run(asyncio.wait_for(scenario(), 600))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_zone(nblocks: int = 12, block_kib: int = 256,
               rounds: int = 3, wan_ms: float = 20.0) -> dict:
    """Zone-aware read economics (ISSUE 16). A 3-zone / 6-node
    cluster-in-a-box with a chaos-injected WAN delay on every
    cross-zone link out of the reading node, reading blocks the reader
    does NOT hold locally (the remote-read shape):

      zone_local_get_p50_ms /      local-zone-first ordering serves the
      zone_local_get_p99_ms        same-zone replica: one LAN hop, the
                                   WAN delay never paid
      zone_cross_get_p50_ms /      the same reads with the same-zone
      zone_cross_get_p99_ms        replica's link severed — forced
                                   cross-zone, each GET pays the WAN
      zone_local_cross_mb /        block_cross_zone_read_bytes delta per
      zone_cross_cross_mb          leg: ~0 for the local leg is the
                                   routing claim as a byte counter
    """
    import pathlib
    import shutil
    import sys
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    for p in (here, os.path.join(here, "tests")):
        if p not in sys.path:
            sys.path.insert(0, p)
    from clusterbox import ClusterBox

    from garage_tpu.chaos import FaultSpec, arm, disarm
    from garage_tpu.utils.data import blake3sum
    from garage_tpu.utils.metrics import registry

    block_len = block_kib << 10
    tmp = tempfile.mkdtemp(
        prefix="gt_zone_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)

    async def scenario() -> dict:
        box = await ClusterBox(
            pathlib.Path(tmp), n=6, rf=3,
            zones=["z1", "z1", "z2", "z2", "z3", "z3"],
            zone_redundancy=2).start()
        try:
            m0 = box.nodes[0].manager
            layout = box.nodes[0].system.layout_helper.current()
            rng = np.random.default_rng(16)
            # blocks the reader does NOT hold: every read is remote,
            # and the spread-maximizing layout guarantees the one z1
            # replica is node 1 — the same-zone lane we then sever
            hashes = []
            while len(hashes) < nblocks:
                b = rng.integers(0, 256, block_len,
                                 dtype=np.uint8).tobytes()
                h = blake3sum(b)
                if box.nodes[0].id in layout.nodes_of_hash(h):
                    continue
                await m0.rpc_put_block(h, b, compress=False,
                                       cacheable=False)
                hashes.append(h)

            n0 = box.nodes[0].id.hex()[:8]
            n1 = box.nodes[1].id.hex()[:8]

            def wan_faults(c):
                # WAN model: every frame node0 sends across a zone
                # boundary pays wan_ms (pings included — they survive)
                for nd, zone in zip(box.nodes, box.zones):
                    if zone != "z1":
                        c.add(FaultSpec(kind="net_delay", node=n0,
                                        peer=nd.id.hex()[:8],
                                        delay_s=wan_ms / 1e3))

            async def sweep() -> list:
                lat = []
                for _ in range(rounds):
                    for h in hashes:
                        t0 = time.perf_counter()
                        got = await m0.rpc_get_block(h, cacheable=False)
                        lat.append(time.perf_counter() - t0)
                        assert len(got) == block_len
                return lat

            def pctl(xs, q):
                s = sorted(xs)
                return round(
                    s[min(len(s) - 1, int(q * len(s)))] * 1e3, 2)

            def cross_mb() -> float:
                return registry().totals(
                    "block_cross_zone_read_bytes")[1] / 1e6

            # ---- local leg: same-zone replica reachable ---------------
            c = arm(seed=16)
            wan_faults(c)
            x0 = cross_mb()
            local = await sweep()
            local_cross = cross_mb() - x0

            # ---- cross leg: sever node0 <-> node1, pay the WAN --------
            c.add(FaultSpec(kind="net_disconnect", node=n0, peer=n1))
            c.add(FaultSpec(kind="net_disconnect", node=n1, peer=n0))
            x0 = cross_mb()
            cross = await sweep()
            cross_bytes = cross_mb() - x0
            disarm()

            return {
                "zone_local_get_p50_ms": pctl(local, 0.5),
                "zone_local_get_p99_ms": pctl(local, 0.99),
                "zone_cross_get_p50_ms": pctl(cross, 0.5),
                "zone_cross_get_p99_ms": pctl(cross, 0.99),
                "zone_local_cross_mb": round(local_cross, 2),
                "zone_cross_cross_mb": round(cross_bytes, 2),
            }
        finally:
            disarm()
            await box.stop()

    try:
        return asyncio.run(asyncio.wait_for(scenario(), 300))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_metadata(keys: int = 150_000, engines=("sqlite", "lsm"),
                   delim_prefixes: int = 256, list_reps: int = 24,
                   sync_missing: int = 1_000) -> dict:
    """Metadata at millions of objects (ISSUE 7): the many-small-keys
    workload every earlier bench skipped. Per engine (sqlite vs lsm),
    on one `keys`-row table shaped like a real bucket
    (`d00042/o00001234` — `delim_prefixes` distinct top-level
    prefixes):

      insert/s      bulk load through the REAL table write path
                    (TableData.update_many: CRDT merge + store write +
                    merkle todo per row)
      merkle        convergence rate draining the todo backlog through
                    MerkleUpdater.update_batch (one walk per subtree)
      list p50/p99  _collect_objects — the actual S3 lister — paged
                    from random continuation points (plain) and folding
                    the bucket into common prefixes (delimiter);
                    delimiter fetches-per-page is reported so the
                    O(distinct prefixes) skip-scan claim is a number
      sync round    a REAL TableSyncer anti-entropy round between two
                    loopback nodes: divergent (peer missing
                    `sync_missing` rows -> trie descent + push) and
                    converged (root-checksum confirmation) legs

    Keys default small enough for the main bench line; the nightly
    smoke runs --keys 1000000 and the slow tier 10M."""
    import pathlib  # noqa: F401  (parity with sibling benches)
    import random
    import shutil
    import tempfile

    from garage_tpu.api.s3 import list as s3list
    from garage_tpu.db import open_db
    from garage_tpu.table.data import TableData
    from garage_tpu.table.merkle import MerkleUpdater
    from garage_tpu.table.schema import Entry, TableSchema, tree_key

    class MetaEntry(Entry):
        VERSION_MARKER = b"BMta1"

        def __init__(self, pk, sk, value):
            self.pk, self.sk, self.value = pk, sk, value

        def partition_key(self):
            return self.pk

        def sort_key(self):
            return self.sk

        def merge(self, other):
            return other if other.value >= self.value else self

        def pack(self):
            return [self.pk, self.sk, self.value]

        @classmethod
        def unpack(cls, raw):
            return cls(raw[0], raw[1], raw[2])

        # duck-typed for the S3 list collector (_collect_objects reads
        # .key and .last_data() only)
        @property
        def key(self):
            return self.sk.decode()

        def last_data(self):
            return self

    class MetaSchema(TableSchema):
        TABLE_NAME = "benchmeta"
        ENTRY = MetaEntry

    class _Repl:  # standalone build: same partition math as the ring
        def partition_of(self, h):
            return h[0]

        def storage_nodes(self, h):
            return [b"me"]

    bucket = b"bench-bucket"
    per_prefix = max(1, keys // delim_prefixes)
    val = b"m" * 96  # ~ an object row's metadata payload

    def key_of(i: int) -> bytes:
        return b"d%05d/o%08d" % (i // per_prefix, i)

    def pctl(samples, q):
        return round(float(np.percentile(np.array(samples), q)) * 1000, 3)

    def build_and_measure(engine: str, tmp: str) -> dict:
        r: dict = {}
        db = open_db(os.path.join(tmp, "a"), engine=engine)
        schema = MetaSchema()
        data = TableData(db, schema, _Repl(), b"me")

        # 1. bulk insert through the real local write path
        insert_dt = 0.0
        for lo in range(0, keys, 10_000):
            raws = [schema.encode_entry(MetaEntry(bucket, key_of(i), val))
                    for i in range(lo, min(lo + 10_000, keys))]
            t0 = time.perf_counter()
            data.update_many(raws)
            insert_dt += time.perf_counter() - t0
        r["insert_per_s"] = round(keys / insert_dt, 1)

        # 2. merkle convergence: drain the whole todo backlog batched
        # (1024-row transactions: bulk-load drain, amortizing the upper
        # trie levels harder than the worker's foreground-friendly 256)
        m = MerkleUpdater(data)
        t0 = time.perf_counter()
        while True:
            todo = list(data.merkle_todo.iter(limit=4096))
            if not todo:
                break
            for i in range(0, len(todo), 1024):
                m.update_batch(todo[i:i + 1024])
        r["merkle_items_per_s"] = round(
            keys / (time.perf_counter() - t0), 1)

        if engine == "lsm":
            # read-optimized steady state for the list legs (the
            # maintenance worker reaches it on an idle node)
            db._engine.compact_full()
            es = db.engine_stats()
            r["segments"] = es["segments"]
            r["flushes"] = es["flushes"]
            r["compactions"] = es["compactions"]

        # 3. list latencies through the real S3 collector
        class _Ctx:
            bucket_id = bucket
            fetches = 0

            def __init__(self):
                self.garage = self
                self.object_table = self

            async def get_range(self, pk, start_sk=None, flt=None,
                                limit=1000, prefix_sk=None, **kw):
                self.fetches += 1
                raws = data.read_range(pk, start_sk, None, limit,
                                       prefix_sk=prefix_sk)
                return [schema.decode_entry(x) for x in raws]

        rng = random.Random(7)

        async def list_legs():
            ctx = _Ctx()
            plain, delim = [], []
            # warm-up: one page of each shape untimed, so the p99
            # measures the steady state, not first-touch cache fills
            await s3list._collect_objects(ctx, "", None, "", 1000)
            await s3list._collect_objects(ctx, "", None, "/", 1000)
            for _ in range(list_reps):
                resume = ("k", key_of(rng.randrange(keys)).decode())
                t0 = time.perf_counter()
                await s3list._collect_objects(ctx, "", resume, "", 1000)
                plain.append(time.perf_counter() - t0)
            ctx.fetches = 0
            t0 = time.perf_counter()
            _, cps, _, _ = await s3list._collect_objects(
                ctx, "", None, "/", 1000)
            first_dt = time.perf_counter() - t0
            fetches = ctx.fetches
            delim.append(first_dt)
            for _ in range(list_reps - 1):
                t0 = time.perf_counter()
                await s3list._collect_objects(ctx, "", None, "/", 1000)
                delim.append(time.perf_counter() - t0)
            return plain, delim, len(cps), fetches

        plain, delim, n_prefixes, delim_fetches = asyncio.run(list_legs())
        r["list_p50_ms"] = pctl(plain, 50)
        r["list_p99_ms"] = pctl(plain, 99)
        r["delim_list_p50_ms"] = pctl(delim, 50)
        r["delim_list_p99_ms"] = pctl(delim, 99)
        r["delim_prefixes"] = n_prefixes
        # the skip-scan claim as a number: range reads per delimiter
        # page ~ distinct prefixes, independent of keys under them
        r["delim_fetches_per_page"] = delim_fetches

        # 4. real anti-entropy round between two loopback nodes; peer B
        # starts as a snapshot of A missing `sync_missing` rows
        db.snapshot(os.path.join(tmp, "b"))
        db_b = open_db(os.path.join(tmp, "b"), engine=engine)
        data_b = TableData(db_b, MetaSchema(), _Repl(), b"me")
        missing = rng.sample(range(keys), min(sync_missing, keys))

        def drop(tx):
            for i in missing:
                k = tree_key(bucket, key_of(i))
                tx.remove(data_b.store, k)
                tx.insert(data_b.merkle_todo, k, b"")

        db_b.transaction(drop)
        mb = MerkleUpdater(data_b)
        while True:
            todo = list(data_b.merkle_todo.iter(limit=4096))
            if not todo:
                break
            for i in range(0, len(todo), MerkleUpdater.TX_STEP):
                mb.update_batch(todo[i:i + MerkleUpdater.TX_STEP])

        from garage_tpu.net import LocalNetwork, NetApp
        from garage_tpu.rpc import ReplicationMode, RpcHelper, System
        from garage_tpu.rpc.layout import NodeRole
        from garage_tpu.table import Table, TableShardedReplication
        from garage_tpu.table.sync import TableSyncer

        async def sync_round():
            net = LocalNetwork()
            systems = []
            for i in range(2):
                app = NetApp(b"bench-meta")
                net.register(app)
                s = System(app, ReplicationMode.parse(2),
                           os.path.join(tmp, f"node{i}"),
                           status_interval=0.2, ping_interval=0.2)
                systems.append(s)
            tasks = [asyncio.create_task(s.run()) for s in systems]
            try:
                await systems[1].netapp.try_connect(
                    systems[0].netapp.public_addr, systems[0].id)
                systems[1].peering.add_peer(
                    systems[0].netapp.public_addr, systems[0].id)
                deadline = time.monotonic() + 15
                while time.monotonic() < deadline:
                    if all(len(s.netapp.conns) == 1 for s in systems):
                        break
                    await asyncio.sleep(0.05)
                lm = systems[0].layout_manager
                for s in systems:
                    lm.history.stage_role(
                        s.id, NodeRole(zone="z1", capacity=1 << 30))
                lm.apply_staged(None)
                while time.monotonic() < deadline:
                    if all(s.layout_manager.history.current().version == 1
                           for s in systems):
                        break
                    await asyncio.sleep(0.05)
                tabs = []
                for s, d in zip(systems, (db, db_b)):
                    repl = TableShardedReplication(
                        s, s.replication.read_quorum,
                        s.replication.write_quorum)
                    tabs.append(Table(MetaSchema(), repl,
                                      RpcHelper(s), d))
                syncers = [TableSyncer(t, interval=1e9) for t in tabs]
                t0 = time.perf_counter()
                ok = await syncers[0].sync_all_partitions()
                div_s = time.perf_counter() - t0
                healed = len(tabs[1].data.store) == keys
                t0 = time.perf_counter()
                await syncers[0].sync_all_partitions()
                conv_s = time.perf_counter() - t0
                return div_s, conv_s, ok and healed
            finally:
                for s in systems:
                    await s.stop()
                for t in tasks:
                    t.cancel()

        div_s, conv_s, sync_ok = asyncio.run(
            asyncio.wait_for(sync_round(), 300))
        r["sync_round_divergent_s"] = round(div_s, 3)
        r["sync_round_converged_s"] = round(conv_s, 3)
        r["sync_healed"] = sync_ok
        db.close()
        db_b.close()
        return r

    out: dict = {"meta_keys": keys}
    base = "/dev/shm" if os.path.isdir("/dev/shm") else None
    for engine in engines:
        tmp = tempfile.mkdtemp(prefix=f"gt_meta_{engine}_", dir=base)
        try:
            for k, v in build_and_measure(engine, tmp).items():
                out[f"meta_{engine}_{k}"] = v
        except Exception as e:  # one engine must never kill the line
            out[f"meta_{engine}_error"] = f"{type(e).__name__}: {e}"[:300]
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    if out.get("meta_lsm_insert_per_s") and out.get(
            "meta_sqlite_insert_per_s"):
        out["meta_insert_lsm_vs_sqlite"] = round(
            out["meta_lsm_insert_per_s"]
            / out["meta_sqlite_insert_per_s"], 2)
    if out.get("meta_lsm_delim_list_p99_ms") and out.get(
            "meta_sqlite_delim_list_p99_ms"):
        out["meta_delim_p99_lsm_vs_sqlite"] = round(
            out["meta_sqlite_delim_list_p99_ms"]
            / out["meta_lsm_delim_list_p99_ms"], 2)
    return out


def bench_gateway(nobj: int = 16, obj_mib: int = 2,
                  workers_list=None) -> dict:
    """Multi-process gateway scaling (ISSUE 8): s3_put/s3_get GB/s
    through a forked store + N SO_REUSEPORT workers, swept over
    `workers ∈ {1, 2, 4, cpu_count}`. `gateway_scaling_put` =
    gbps(best N) / gbps(1) — the "frontend scales with cores" number —
    plus the lease-rebalance convergence time measured against the
    real BudgetLeaseBroker under a deterministic 10:1 demand skew.

    workers=1 runs the single-process in-process frontend (the exact
    pre-gateway path), so the baseline is honest."""
    import concurrent.futures
    import json as _json
    import shutil
    import sys
    import tempfile
    import urllib.request

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "tests"))
    from s3util import S3Client
    from test_s3_api import Server

    cpus = os.cpu_count() or 1
    if workers_list is None:
        workers_list = sorted({w for w in (1, 2, 4, cpus)
                               if w <= max(cpus, 2)})
    out: dict = {"gateway_cpus": cpus,
                 "gateway_workers_swept": list(workers_list)}
    size = obj_mib << 20
    data = np.random.default_rng(11).integers(
        0, 256, size, dtype=np.uint8).tobytes()
    base_dir = "/dev/shm" if os.path.isdir("/dev/shm") else None
    per: dict[int, tuple[float, float]] = {}
    for n in workers_list:
        tmp = tempfile.mkdtemp(prefix=f"gt_gw{n}_", dir=base_dir)
        srv = Server(tmp)
        with open(srv.config_path) as f:
            cfg = f.read()
        cfg = cfg.replace("block_size = 65536",
                          "block_size = 1048576")
        cfg += f"\n[gateway]\nworkers = {n}\nlease_interval_s = 0.5\n"
        with open(srv.config_path, "w") as f:
            f.write(cfg)
        os.environ.setdefault("GARAGE_TPU_DEVICE", "off")
        try:
            srv.start()
            srv.setup_layout_and_key()
            cli = S3Client("127.0.0.1", srv.s3_port, srv.key_id,
                           srv.secret)
            st, _, body = cli.request("PUT", "/gwbench")
            assert st == 200, body[:200]
            # cache OFF: this sweep measures the frontend + store
            # path, and the tuning POST fans out to every worker
            rq = urllib.request.Request(
                f"http://127.0.0.1:{srv.admin_port}/v1/s3/tuning",
                data=_json.dumps(
                    {"read_cache_max_bytes": 0}).encode(),
                method="POST",
                headers={"authorization": "Bearer test-admin-token"})
            urllib.request.urlopen(rq, timeout=10).read()

            def put(i):
                st, _, b = cli.request(
                    "PUT", f"/gwbench/o{i}", body=data,
                    unsigned_payload=True, timeout=60.0)
                assert st == 200, b[:200]

            def get(i):
                st, _, b = cli.request("GET", f"/gwbench/o{i}",
                                       timeout=60.0)
                assert st == 200 and len(b) == size

            put(0)  # warm
            best_put = best_get = 0.0
            threads = max(4, 2 * n)
            with concurrent.futures.ThreadPoolExecutor(threads) as pool:
                for _rep in range(2):
                    t0 = time.perf_counter()
                    list(pool.map(put, range(nobj)))
                    dt = time.perf_counter() - t0
                    best_put = max(best_put, nobj * size / dt / 1e9)
                    t0 = time.perf_counter()
                    list(pool.map(get, range(nobj)))
                    dt = time.perf_counter() - t0
                    best_get = max(best_get, nobj * size / dt / 1e9)
            per[n] = (best_put, best_get)
            out[f"s3_put_gbps_w{n}"] = round(best_put, 3)
            out[f"s3_get_gbps_w{n}"] = round(best_get, 3)
        except Exception as e:  # one worker count never kills the line
            out[f"gateway_w{n}_error"] = f"{type(e).__name__}: {e}"[:300]
        finally:
            srv.stop()
            shutil.rmtree(tmp, ignore_errors=True)
    if 1 in per and len(per) > 1:
        base_put, base_get = per[1]
        best_n = max(per, key=lambda k: per[k][0])
        out["gateway_best_workers"] = best_n
        out["gateway_scaling_put"] = round(
            per[best_n][0] / max(base_put, 1e-9), 2)
        out["gateway_scaling_get"] = round(
            max(g for _, g in per.values()) / max(base_get, 1e-9), 2)

    # lease-rebalance convergence: the broker under a deterministic
    # 10:1:1:1 demand skew (simulated renews at the production
    # interval) — rounds until the hot worker holds >= 90% of its
    # demand-proportional share
    from garage_tpu.gateway.lease import BudgetLeaseBroker

    t = [1000.0]
    broker = BudgetLeaseBroker(1000.0, min_share=0.05, ttl_s=3.0,
                               expected_workers=4,
                               clock=lambda: t[0])
    interval = 1.0
    names = [f"w{i}" for i in range(4)]
    for _ in range(5):  # settle at equal demand
        t[0] += interval
        for w in names:
            broker.renew(w, demand_rps=100.0)
    demands = {w: (1000.0 if w == "w0" else 100.0) for w in names}
    target = None
    rounds = 0
    for rounds in range(1, 31):
        t[0] += interval
        for w in names:
            broker.renew(w, demand_rps=demands[w])
        assert broker.conservation_ok
        hot = broker.granted("w0")[0] or 0.0
        # demand-proportional share (floor-adjusted) of the budget
        if target is None:
            floor = 0.05 * 250.0
            target = floor + (1000.0 - 4 * floor) * (1000.0 / 1300.0)
        if hot >= 0.9 * target:
            break
    out["lease_rebalance_convergence_s"] = round(rounds * interval, 2)
    return out


def bench_native_blake3() -> float:
    """The native host BLAKE3 kernel (b3gf.c, AVX2 8-way) — what the
    product actually hashes with on the host path."""
    from garage_tpu.native import blake3_many

    rng = np.random.default_rng(3)
    blobs = [rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
             for _ in range(8)]
    blake3_many(blobs)  # warm
    best = 0.0
    for _rep in range(3):
        t0 = time.perf_counter()
        for _ in range(4):
            blake3_many(blobs)
        dt = time.perf_counter() - t0
        best = max(best, 8 * (1 << 20) * 4 / dt / 1e9)
    return best


def bench_native_parity() -> float:
    """The HOST route of the deep-scrub detect pass
    (feeder._do_parity_check backend=host: native GF matmul + compare)
    in logical 1 MiB blocks/s — what the product's deep scrub sustains
    when calibration keeps it host-side."""
    from garage_tpu.block.codec import ErasureCodec
    from garage_tpu.block.feeder import DeviceFeeder

    from garage_tpu import native

    if not native.available():
        # the numpy fallback must not masquerade under a native label
        # (same honesty rule as the blake3/jax-on-host relabeling)
        raise RuntimeError("native kernels unavailable")
    codec = ErasureCodec(10, 4, use_jax=False)
    f = DeviceFeeder(codec=codec, mode="off")
    rng = np.random.default_rng(4)
    stripes = [codec.encode(
        rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes())
        for _ in range(8)]
    f._do_parity_check(stripes, "host")  # warm
    best = 0.0
    for _rep in range(3):
        t0 = time.perf_counter()
        for _ in range(3):
            verdicts = f._do_parity_check(stripes, "host")
            if not all(verdicts):
                raise RuntimeError(f"healthy stripes flagged: {verdicts}")
        dt = time.perf_counter() - t0
        best = max(best, 8 * 3 / dt)
    return best


def probe_with_retries() -> tuple[dict, int]:
    """r4's capture fell to CPU because the ONE 180 s probe timed out on
    a congested tunnel. Short timeouts, several attempts, sleeps in
    between: a flaky tunnel usually answers one of several probes spread
    across congestion windows (VERDICT r5 #1). A landed probe is cached
    on disk (TTL 10 min), so later stages and a re-exec reuse it."""
    from garage_tpu.block.feeder import probe_device

    timeouts = (60.0, 45.0, 45.0, 45.0, 45.0)
    for i, t in enumerate(timeouts):
        probe = probe_device(timeout=t, force=i > 0)
        if probe["ok"]:
            return probe, i + 1
        if i + 1 < len(timeouts):
            time.sleep(10.0)
    return probe, len(timeouts)


def maybe_reexec_on_device() -> None:
    """Mid-run re-probe for CPU-fallback runs: if the tunnel has come
    alive since the startup probes, re-exec the bench so a fresh
    interpreter (jax cannot switch backends post-import) captures the
    full device segment set. One re-exec max."""
    if os.environ.get("GARAGE_TPU_BENCH_NO_REEXEC"):
        return
    from garage_tpu.block.feeder import probe_device

    probe = probe_device(timeout=45.0, force=True)
    if probe["ok"]:
        os.environ["GARAGE_TPU_BENCH_NO_REEXEC"] = "1"
        os.environ.pop("JAX_PLATFORMS", None)
        # bench_s3_put's host segment setdefault()s this to "off"; the
        # re-exec'd run must start with the feeder free to use the
        # device or its "auto" segments capture nothing
        os.environ.pop("GARAGE_TPU_DEVICE", None)
        import sys

        os.execv(sys.executable,
                 [sys.executable, os.path.abspath(__file__)])


def main() -> None:
    from garage_tpu.utils.runtime import tune

    tune()
    probe, attempts = probe_with_retries()
    if not probe["ok"]:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if not probe["ok"]:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform

    extra: dict = {"platform": platform, "probe_attempts": attempts}
    if probe.get("error"):
        extra["probe_error"] = probe["error"]

    gbps = bench_rs_encode(jax, platform)
    b3_e2e, b3_dev = bench_blake3(jax, platform)
    try:
        native_b3 = round(bench_native_blake3(), 3)
    except Exception:
        native_b3 = None
    if platform == "cpu":
        # the jax treehash numbers on a CPU fallback are the TPU kernel
        # running on the host backend — label them so they can't be read
        # as the product's CPU hashing speed (VERDICT r4 weak #5); the
        # native kernel IS the host hashing speed
        extra["blake3_jax_on_host_gbps"] = round(b3_dev, 3)
        if native_b3 is not None:
            extra["blake3_gbps"] = native_b3
    else:
        extra["blake3_gbps"] = round(b3_e2e, 3)
        extra["blake3_device_gbps"] = round(b3_dev, 3)
    if native_b3 is not None:
        extra["blake3_native_host_gbps"] = native_b3
    try:
        sk = round(bench_scrub_kernel(jax, platform), 1)
        if platform == "cpu":
            # TPU kernel on the host jax backend — label it so the
            # number can't be read as a device rate (same rule as the
            # blake3 relabeling above)
            extra["scrub_kernel_jax_on_host_blocks_per_s"] = sk
        else:
            extra["scrub_kernel_blocks_per_s"] = sk
    except Exception as e:
        extra["scrub_kernel_error"] = f"{type(e).__name__}: {e}"[:300]
    try:
        extra["scrub_parity_native_host_blocks_per_s"] = round(
            bench_native_parity(), 1)
    except Exception as e:
        extra["scrub_parity_error"] = f"{type(e).__name__}: {e}"[:300]
    if platform == "cpu":
        maybe_reexec_on_device()

    # cpu fallback: enough blocks that the scrub segment measures
    # hundreds of ms, not page-cache noise (r5: 16-block scrub numbers
    # swung 4× between runs)
    nblocks = 48 if platform == "cpu" else 128
    base = "/dev/shm" if os.path.isdir("/dev/shm") else None

    def run_segment(tag, device_mode, erasure, nb):
        tmp = tempfile.mkdtemp(prefix=f"gt_bench_{tag}_", dir=base)
        try:
            return asyncio.run(asyncio.wait_for(
                _put_cluster_bench(tmp, platform, nb, device_mode, erasure),
                600))
        except Exception as e:  # one segment must never kill the line
            return {"error": f"{type(e).__name__}: {e}"[:300]}
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    # main segment: erasure(4,2), feeder auto-calibrated. Run TWICE,
    # interleaved with the cpu-baseline segment below, and keep each
    # segment's best: identical back-to-back runs on this co-tenant
    # box have measured 40 vs 530 scrub blocks/s, so single samples
    # (and especially single-sample RATIOS) are meaningless.
    def best_of(a: dict, b: dict) -> dict:
        if "error" in a:
            return b
        if "error" in b:
            return a
        out = dict(a)
        for k, v in b.items():
            if isinstance(v, (int, float)) and isinstance(a.get(k), (int, float)):
                out[k] = max(a[k], v)
        return out

    seg = run_segment("main", "auto" if platform != "cpu" else "off",
                      True, nblocks)
    cpu_seg = run_segment("cpu", "off", False, nblocks)
    seg = best_of(seg, run_segment(
        "main2", "auto" if platform != "cpu" else "off", True, nblocks))
    extra.update({k: v for k, v in seg.items() if k != "error"})
    if "error" in seg:
        extra["put_error"] = seg["error"]
    if platform == "cpu":
        maybe_reexec_on_device()  # re-probe between segments

    # device-required segment: every encode batch forced onto the
    # accelerator — proves the device path end to end (VERDICT r3 #3)
    if platform != "cpu":
        # 16 blocks: proves the forced end-to-end device path while
        # staying inside the batch timeout even at ~2 MB/s tunnel rates
        seg = run_segment("dev", "require", True, min(nblocks, 16))
        if "error" in seg:
            extra["device_put_error"] = seg["error"]
        else:
            extra["device_put_gbps"] = seg["put_gbps"]
            extra["feeder_device_items"] = max(
                extra.get("feeder_device_items", 0),
                seg["feeder_device_items"])
            extra["device_feeder_mbps"] = seg["feeder_mbps"]

    # north-star boundary: S3 PutObject/GetObject through a real forked
    # server (HTTP + SigV4 + chunker + MD5/BLAKE3 + store)
    try:
        extra.update(bench_s3_put(8 if platform == "cpu" else 16))
    except Exception as e:
        extra["s3_put_error"] = f"{type(e).__name__}: {e}"[:300]
    # the gap this PR tracks: how much of the internal block path's
    # throughput the HTTP/signature frontend actually delivers
    if extra.get("s3_put_gbps") and extra.get("put_gbps"):
        extra["frontend_efficiency"] = round(
            extra["s3_put_gbps"] / extra["put_gbps"], 3)

    # qos admission control: sustained PUTs + concurrent deep scrub
    # against a tight byte budget — admitted vs shed + governor action
    try:
        extra.update(bench_qos())
    except Exception as e:
        extra["qos_error"] = f"{type(e).__name__}: {e}"[:300]

    # degraded-mode tail latency: one peer hung (chaos rpc_hang),
    # hedged reads on vs off — the p99 win is the PR 4 headline
    try:
        extra.update(bench_degraded())
    except Exception as e:
        extra["degraded_error"] = f"{type(e).__name__}: {e}"[:300]

    # read-side device lane (ISSUE 13): degraded-GET decode +
    # scrub-rebuild through the feeder's pattern-as-data route, vs the
    # serial host baseline — the decode twin of the encode segments
    try:
        extra.update(bench_decode(
            device_mode="auto" if platform != "cpu" else "off"))
    except Exception as e:
        extra["decode_error"] = f"{type(e).__name__}: {e}"[:300]
    if platform != "cpu":
        # forced-device edition: every decode batch on the accelerator
        # (small, to stay inside the watchdog on a crawling tunnel)
        try:
            dev = bench_decode(nblocks=8, device_mode="require")
            extra["device_decode_gbps"] = dev["decode_gbps"]
            extra["decode_feeder_device_items"] = max(
                extra.get("decode_feeder_device_items", 0),
                dev["decode_feeder_device_items"])
            extra["device_decode_recompiles"] = dev["decode_recompiles"]
        except Exception as e:
            extra["device_decode_error"] = f"{type(e).__name__}: {e}"[:300]

    # zero-downtime resize: rebalance throughput vs foreground p99
    # during an add-node + drain-node transition on a 16-node
    # cluster-in-a-box (ISSUE 6)
    try:
        extra.update(bench_resize())
    except Exception as e:
        extra["resize_error"] = f"{type(e).__name__}: {e}"[:300]

    # metadata at scale (ISSUE 7): insert/list/sync on a many-small-keys
    # table, sqlite vs lsm. Modest key count here; the nightly soak runs
    # `bench.py bench_metadata --keys 1000000` for the full-scale line.
    try:
        extra.update(bench_metadata())
    except Exception as e:
        extra["metadata_error"] = f"{type(e).__name__}: {e}"[:300]

    # multi-core gateway (ISSUE 8): s3_put/get swept over worker
    # counts; gateway_scaling_put is the per-core frontend claim
    try:
        extra.update(bench_gateway())
    except Exception as e:
        extra["gateway_error"] = f"{type(e).__name__}: {e}"[:300]

    # cluster cache tier (ISSUE 15): cluster hot-GET throughput and
    # decode dedup (tier on vs node-local baseline), remote-hit vs
    # cold-decode latency, hint-gossip convergence, shm-vs-socket
    # forward latency
    try:
        extra.update(bench_cache_tier())
    except Exception as e:
        extra["cache_tier_error"] = f"{type(e).__name__}: {e}"[:300]

    # zone-aware reads (ISSUE 16): local-zone-first vs forced
    # cross-zone GET latency under an injected WAN delay, with the
    # cross-zone byte counter as the routing proof
    try:
        extra.update(bench_zone())
    except Exception as e:
        extra["zone_error"] = f"{type(e).__name__}: {e}"[:300]
    if platform == "cpu":
        maybe_reexec_on_device()

    # LIVE-path device proof: a forked server with the feeder required,
    # live S3 PUTs batching through the accelerator, feeder counters
    # scraped from its /metrics (VERDICT r4 weak #2 / r5 #1)
    if platform != "cpu":
        for _attempt in range(2):  # one retry: the forked server's
            # probe can lose a co-tenant congestion window the parent's
            # own probe survived. Small objects (1 MiB): the segment
            # exists to prove feeder_device_items>0 on the live path,
            # and a crawling tunnel (~2 MB/s observed) must not push
            # the whole segment past its timeouts.
            try:
                extra.update(bench_s3_put(2, obj_mib=1, device=True))
                extra.pop("s3_device_error", None)
                break
            except Exception as e:
                extra["s3_device_error"] = f"{type(e).__name__}: {e}"[:300]

    # CPU baseline segment: replicate-3 whole blocks, host only
    # (BASELINE.md rows 1/5: the reference's strategy on the host
    # path). Second leg of the interleave; best of both.
    cpu_seg = best_of(cpu_seg, run_segment("cpu2", "off", False, nblocks))
    seg = cpu_seg
    if "error" in seg:
        extra["cpu_put_error"] = seg["error"]
    else:
        extra["cpu_put_gbps"] = seg["put_gbps"]
        extra["cpu_put_wire_mib_per_block"] = seg.get(
            "put_wire_mib_per_block")
        extra["cpu_scrub_blocks_per_s"] = seg["scrub_blocks_per_s"]
        if extra.get("put_gbps"):
            extra["put_vs_cpu_baseline"] = round(
                extra["put_gbps"] / max(seg["put_gbps"], 1e-9), 2)
        if extra.get("scrub_blocks_per_s"):
            extra["scrub_vs_cpu_baseline"] = round(
                extra["scrub_blocks_per_s"]
                / max(seg["scrub_blocks_per_s"], 1e-9), 2)
        if extra.get("scrub_kernel_blocks_per_s") and platform != "cpu":
            # the driver-captured form of the "scrub ≥10×" claim:
            # device-resident detect kernel vs the measured host
            # replicate-3 hash-scrub baseline in the SAME run
            extra["scrub_kernel_vs_cpu_baseline"] = round(
                extra["scrub_kernel_blocks_per_s"]
                / max(seg["scrub_blocks_per_s"], 1e-9), 2)

    print(json.dumps({
        "metric": "rs_10_4_encode",
        "value": round(gbps, 3),
        "unit": f"GB/s/chip[{platform}]",
        "vs_baseline": round(gbps / 4.0, 3),
        **extra,
    }), flush=True)
    # skip interpreter teardown: the axon PJRT plugin's C++ destructors
    # can abort after a tunneled device was used (r3: rc=134); all real
    # cleanup already ran above
    os._exit(0)


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "bench_metadata":
        # standalone scenario (nightly soak smoke / operator runs):
        # python bench.py bench_metadata --keys 1000000
        import argparse

        ap = argparse.ArgumentParser()
        ap.add_argument("cmd")
        ap.add_argument("--keys", type=int, default=1_000_000)
        ap.add_argument("--engines", default="sqlite,lsm")
        a = ap.parse_args()
        print(json.dumps({
            "metric": "bench_metadata",
            **bench_metadata(keys=a.keys,
                             engines=tuple(a.engines.split(","))),
        }), flush=True)
        os._exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "bench_cache_tier":
        # standalone scenario (nightly soak / operator runs):
        # python bench.py bench_cache_tier --nblocks 24 --nodes 6
        import argparse

        ap = argparse.ArgumentParser()
        ap.add_argument("cmd")
        ap.add_argument("--nblocks", type=int, default=12)
        ap.add_argument("--block-kib", type=int, default=512)
        ap.add_argument("--rounds", type=int, default=4)
        ap.add_argument("--nodes", type=int, default=6)
        a = ap.parse_args()
        print(json.dumps({
            "metric": "bench_cache_tier",
            **bench_cache_tier(nblocks=a.nblocks,
                               block_kib=a.block_kib,
                               rounds=a.rounds, nodes=a.nodes),
        }), flush=True)
        os._exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "bench_put_path":
        # standalone scenario (CI gate / nightly soak):
        # python bench.py bench_put_path --nobj 8 --obj-mib 4
        import argparse

        ap = argparse.ArgumentParser()
        ap.add_argument("cmd")
        ap.add_argument("--nobj", type=int, default=8)
        ap.add_argument("--obj-mib", type=int, default=6)
        ap.add_argument("--stub-gbps", default="0.02,0.08,0.04")
        ap.add_argument("--no-ingest-pool", action="store_true",
                        help="A/B control: classic copy path under "
                             "identical modelled rates")
        a = ap.parse_args()
        print(json.dumps({
            "metric": "bench_put_path",
            **bench_put_path(nobj=a.nobj, obj_mib=a.obj_mib,
                             stub_gbps=a.stub_gbps,
                             ingest_pool=not a.no_ingest_pool),
        }), flush=True)
        os._exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "bench_zone":
        # standalone scenario (nightly soak / operator runs):
        # python bench.py bench_zone --wan-ms 40
        import argparse

        ap = argparse.ArgumentParser()
        ap.add_argument("cmd")
        ap.add_argument("--nblocks", type=int, default=12)
        ap.add_argument("--block-kib", type=int, default=256)
        ap.add_argument("--rounds", type=int, default=3)
        ap.add_argument("--wan-ms", type=float, default=20.0)
        a = ap.parse_args()
        print(json.dumps({
            "metric": "bench_zone",
            **bench_zone(nblocks=a.nblocks, block_kib=a.block_kib,
                         rounds=a.rounds, wan_ms=a.wan_ms),
        }), flush=True)
        os._exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "bench_gateway":
        # standalone scenario (CI smoke / operator runs):
        # python bench.py bench_gateway --workers 1,2,4 --nobj 16
        import argparse

        ap = argparse.ArgumentParser()
        ap.add_argument("cmd")
        ap.add_argument("--workers", default="")
        ap.add_argument("--nobj", type=int, default=16)
        ap.add_argument("--obj-mib", type=int, default=2)
        a = ap.parse_args()
        wl = ([int(w) for w in a.workers.split(",") if w]
              or None)
        print(json.dumps({
            "metric": "bench_gateway",
            **bench_gateway(nobj=a.nobj, obj_mib=a.obj_mib,
                            workers_list=wl),
        }), flush=True)
        os._exit(0)
    main()
