#!/usr/bin/env bash
# End-to-end smoke for the flagship erasure(4,2) mode against a REAL
# 6-process cluster: S3 PUT/GET via presigned curl (blocks striped as
# RS(4,2) shards across all six nodes), then a DOUBLE node kill — the
# full loss tolerance of the code — with a degraded read that must
# still return byte-identical data from any 4 surviving shards.
# Companion to script/smoke.sh (replicate-3); same driving style.
set -euo pipefail
cd "$(dirname "$0")/.."
REPO=$PWD
PY=${PYTHON:-python}
export PYTHONPATH="$REPO:$REPO/tests"
export JAX_PLATFORMS=cpu GARAGE_TPU_DEVICE=off PYTHONUNBUFFERED=1

N=6
TMP=$(mktemp -d "${TMPDIR:-/tmp}/gt_esmoke.XXXXXX")
PIDS=()
cleanup() {
    for p in "${PIDS[@]:-}"; do kill "$p" 2>/dev/null || true; done
    wait 2>/dev/null || true
    rm -rf "$TMP"
}
trap cleanup EXIT

say() { printf '\033[1;34m== %s\033[0m\n' "$*"; }
die() { printf '\033[1;31mFAIL: %s\033[0m\n' "$*" >&2; exit 1; }

free_port() { "$PY" -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()'; }

say "generating configs for $N erasure(4,2) nodes"
for i in $(seq 1 $N); do
    mkdir -p "$TMP/node$i"
    eval "RPC$i=$(free_port) S3_$i=$(free_port) ADM$i=$(free_port)"
done
for i in $(seq 1 $N); do
    rpc_var="RPC$i"; s3_var="S3_$i"; adm_var="ADM$i"
    cat > "$TMP/node$i/garage.toml" <<EOF
metadata_dir = "$TMP/node$i/meta"
data_dir = "$TMP/node$i/data"
replication_factor = 3
# the double-kill below removes BOTH metadata replicas of any
# partition whose 3-node set contains both victims (ring-dependent);
# degraded mode (read quorum 1, the reference's knob for exactly this)
# keeps metadata readable whenever ANY replica survives, so the smoke
# exercises the block layer's full m=2 loss tolerance deterministically
consistency_mode = "degraded"
erasure_coding = "4,2"
db_engine = "sqlite"
block_size = 65536
rpc_bind_addr = "127.0.0.1:${!rpc_var}"
rpc_public_addr = "127.0.0.1:${!rpc_var}"
rpc_secret = "00112233445566778899aabbccddeeff00112233445566778899aabbccddeeff"

[s3_api]
api_bind_addr = "127.0.0.1:${!s3_var}"
s3_region = "garage"
root_domain = ".s3.garage.test"

[admin]
api_bind_addr = "127.0.0.1:${!adm_var}"
admin_token = "smoke-admin-token"
EOF
done

say "starting $N server processes"
for i in $(seq 1 $N); do
    "$PY" -m garage_tpu.cli.server --config "$TMP/node$i/garage.toml" \
        --log-level warning > "$TMP/node$i/log" 2>&1 &
    PIDS+=($!)
done
probe() {
    [ "$(curl -s -o /dev/null -w '%{http_code}' "http://127.0.0.1:$1/health")" != "000" ]
}
for i in $(seq 1 $N); do
    adm_var="ADM$i"
    for _ in $(seq 1 100); do
        probe "${!adm_var}" && break
        sleep 0.2
    done
    probe "${!adm_var}" \
        || die "node $i did not come up ($(tail -3 "$TMP/node$i/log"))"
done

cli() { "$PY" -m garage_tpu.cli.main --config "$TMP/node1/garage.toml" "$@"; }
cli2() { "$PY" -m garage_tpu.cli.main --config "$TMP/node$1/garage.toml" "${@:2}"; }

say "connecting nodes + applying a $N-node layout"
NODE1_ID=$(cli status | awk '/^node id:/{print $3}')
for i in $(seq 2 $N); do
    cli2 "$i" connect "$NODE1_ID@127.0.0.1:$RPC1" >/dev/null
done
sleep 1
for i in $(seq 1 $N); do
    NID=$(cli2 "$i" status | awk '/^node id:/{print $3}')
    cli layout assign "$NID" -z "dc$(( (i - 1) % 3 + 1 ))" -c 1G >/dev/null
done
cli layout apply >/dev/null
STATUS=$(cli status)
echo "$STATUS" | grep -q "layout:   v1" \
    || { echo "$STATUS"; die "layout not applied"; }

say "creating key + bucket"
KEYOUT=$(cli key new --name esmoke)
KEY_ID=$(echo "$KEYOUT" | awk '/^Key ID:/{print $3}')
SECRET=$(echo "$KEYOUT" | awk '/^Secret key:/{print $3}')
cli bucket create esmoke >/dev/null
cli bucket allow esmoke --key "$KEY_ID" --read --write --owner >/dev/null

presign() {
    "$PY" - "$@" <<EOF
import sys
from s3util import S3Client
method, path, *rest = sys.argv[1:]
q = [tuple(a.split("=", 1)) for a in rest]
c = S3Client("127.0.0.1", $S3_1, "$KEY_ID", "$SECRET", "garage")
print(f"http://127.0.0.1:$S3_1" + c.presign(method, path, query=q or None))
EOF
}

say "S3: 1 MiB object striped as RS(4,2) across $N nodes"
head -c 1048576 /dev/urandom > "$TMP/obj"
curl -sf -X PUT --data-binary "@$TMP/obj" "$(presign PUT /esmoke/obj)" >/dev/null \
    || die "presigned PUT failed"
curl -sf "$(presign GET /esmoke/obj)" -o "$TMP/obj.back"
cmp "$TMP/obj" "$TMP/obj.back" || die "GET returned different bytes"
# shards really are spread: every node's data dir holds .sN files
for i in $(seq 1 $N); do
    find "$TMP/node$i/data" -name '*.s*' | grep -q . \
        || die "node $i holds no shards"
done

say "S3: multipart upload over erasure shards"
head -c 300000 /dev/urandom > "$TMP/part1"
head -c 300000 /dev/urandom > "$TMP/part2"
INIT=$(curl -sf -X POST "$(presign POST /esmoke/mpobj uploads=)")
UPLOAD_ID=$(echo "$INIT" | sed -n 's/.*<UploadId>\(.*\)<\/UploadId>.*/\1/p')
[ -n "$UPLOAD_ID" ] || die "no UploadId in $INIT"
ETAG1=$(curl -sfi -X PUT --data-binary "@$TMP/part1" \
    "$(presign PUT /esmoke/mpobj partNumber=1 "uploadId=$UPLOAD_ID")" \
    | tr -d '\r' | awk -F'"' 'tolower($0) ~ /^etag:/{print $2}')
ETAG2=$(curl -sfi -X PUT --data-binary "@$TMP/part2" \
    "$(presign PUT /esmoke/mpobj partNumber=2 "uploadId=$UPLOAD_ID")" \
    | tr -d '\r' | awk -F'"' 'tolower($0) ~ /^etag:/{print $2}')
cat > "$TMP/complete.xml" <<EOF
<CompleteMultipartUpload>
<Part><PartNumber>1</PartNumber><ETag>"$ETAG1"</ETag></Part>
<Part><PartNumber>2</PartNumber><ETag>"$ETAG2"</ETag></Part>
</CompleteMultipartUpload>
EOF
COMPLETE=$(curl -sf -X POST --data-binary "@$TMP/complete.xml" \
    "$(presign POST /esmoke/mpobj "uploadId=$UPLOAD_ID")") \
    && echo "$COMPLETE" | grep -q ETag \
    || die "complete-multipart failed: ${COMPLETE:-curl error}"
cat "$TMP/part1" "$TMP/part2" > "$TMP/mp.expect"
curl -sf "$(presign GET /esmoke/mpobj)" -o "$TMP/mp.back"
cmp "$TMP/mp.expect" "$TMP/mp.back" || die "multipart GET mismatch"

say "S3: degraded read with TWO nodes down (full m=2 loss tolerance)"
kill "${PIDS[4]}" "${PIDS[5]}" 2>/dev/null
wait "${PIDS[4]}" "${PIDS[5]}" 2>/dev/null || true
# with both parity nodes gone every remaining shard is load-bearing:
# the first read can race the dead-connection detector while stale
# conns to the killed nodes drain, so allow a few retries
degraded_get() { # path outfile
    for _ in $(seq 1 15); do
        if curl -sf "$(presign GET "$1")" -o "$2"; then return 0; fi
        sleep 1
    done
    return 1
}
degraded_get /esmoke/obj "$TMP/obj.back2" || die "degraded GET failed"
cmp "$TMP/obj" "$TMP/obj.back2" || die "degraded GET mismatch (2 nodes down)"
degraded_get /esmoke/mpobj "$TMP/mp.back2" || die "degraded multipart GET failed"
cmp "$TMP/mp.expect" "$TMP/mp.back2" || die "degraded multipart GET mismatch"

say "nodes restart and rejoin"
for i in 5 6; do
    "$PY" -m garage_tpu.cli.server --config "$TMP/node$i/garage.toml" \
        --log-level warning >> "$TMP/node$i/log" 2>&1 &
    PIDS[$((i - 1))]=$!
done
for _ in $(seq 1 60); do
    UP=$(curl -s -H "Authorization: Bearer smoke-admin-token" \
        "http://127.0.0.1:$ADM1/v1/health" \
        | "$PY" -c 'import json,sys; print(json.load(sys.stdin)["connectedNodes"])' \
        2>/dev/null || echo 0)
    [ "$UP" = "$N" ] && break
    sleep 0.5
done
[ "$UP" = "$N" ] || die "cluster did not re-converge ($UP/$N nodes)"

say "ALL ERASURE SMOKE TESTS PASSED"
