#!/usr/bin/env bash
# Nightly chaos soak (ROADMAP "Chaos in CI nightly"): the deterministic
# chaos suite first, then N randomized-seed soak iterations against a
# real in-process cluster. Every iteration logs its seed ON ENTRY, so
# any failure replays deterministically:
#
#     CHAOS_SOAK_SEED=<seed> pytest tests/test_chaos.py -k soak -s
#
# Usage: script/chaos_soak.sh [iterations]    (default 10)
set -euo pipefail
cd "$(dirname "$0")/.."
PY=${PYTHON:-python}
# GARAGE_SANITIZE=1 (ISSUE 14): the runtime asyncio sanitizer arms for
# the whole soak — loop-stall/leak/conservation reports fail the
# owning test via conftest AND are grepped out of the log below so a
# stall in any forked child process also fails the job. Threshold 2 s:
# calibrated on the 2-core box (tier-1 + soak run clean at 1 s; 2 s
# leaves headroom for CI-runner noise under chaos load).
export JAX_PLATFORMS=cpu GARAGE_TPU_DEVICE=off GARAGE_METRICS_STRICT=1 \
       PYTHONUNBUFFERED=1 GARAGE_SANITIZE=1 \
       GARAGE_SANITIZE_STALL_S=${GARAGE_SANITIZE_STALL_S:-2.0}
ITERS=${1:-10}
SOAK_LOG=$(mktemp /tmp/chaos_soak.XXXXXX.log)

say() { printf '\033[1;34m== %s\033[0m\n' "$*"; }

# mirror everything into the soak log so sanitizer reports from forked
# child processes (gateway workers, lsm crash drills) land in the
# artifacts and are asserted on at the end
exec > >(tee "$SOAK_LOG") 2>&1
say "soak log: $SOAK_LOG (sanitizer armed, stall threshold ${GARAGE_SANITIZE_STALL_S}s)"

say "chaos suite (deterministic seeds)"
"$PY" -m pytest tests/test_chaos.py -q -m 'not slow' -p no:cacheprovider

say "randomized soak: $ITERS iterations"
for i in $(seq 1 "$ITERS"); do
    SEED=$(( (RANDOM << 15) ^ RANDOM ^ $$ + i ))
    say "soak $i/$ITERS seed=$SEED (replay: CHAOS_SOAK_SEED=$SEED pytest tests/test_chaos.py -k soak -s)"
    CHAOS_SOAK_SEED=$SEED "$PY" -m pytest tests/test_chaos.py \
        -k test_randomized_soak -q -s -p no:cacheprovider
done

# resize soak (ISSUE 6): layout churn — add-node, drain-node,
# kill-and-restart — under randomized budgeted chaos with a live
# workload; static-membership faults alone don't exercise the
# transition machinery. Fewer iterations: each one drives three full
# transitions on a 5-node cluster-in-a-box.
RESIZE_ITERS=$(( (ITERS + 4) / 5 ))
say "resize soak: $RESIZE_ITERS iterations (layout churn + chaos)"
for i in $(seq 1 "$RESIZE_ITERS"); do
    SEED=$(( (RANDOM << 15) ^ RANDOM ^ $$ + 1000 + i ))
    say "resize soak $i/$RESIZE_ITERS seed=$SEED (replay: CHAOS_SOAK_SEED=$SEED pytest tests/test_resize.py -k resize_soak -s)"
    CHAOS_SOAK_SEED=$SEED "$PY" -m pytest tests/test_resize.py \
        -k test_resize_soak -q -s -p no:cacheprovider
done
# metadata-at-scale smoke (ISSUE 7): 1M-key bench_metadata (sqlite vs
# lsm — insert/s, list p50/p99 plain+delimiter, merkle convergence,
# table-sync round) so metadata perf regressions show up in the nightly
# trajectory like block-path ones do. The 10M tier lives behind the
# `slow` pytest marker (tests/test_metadata_scale.py).
META_KEYS="${META_KEYS:-1000000}"
say "metadata smoke: bench_metadata --keys $META_KEYS"
JAX_PLATFORMS=cpu GARAGE_TPU_DEVICE=off "$PY" bench.py bench_metadata \
    --keys "$META_KEYS"

# multi-process gateway smoke (ISSUE 8): the forked 2-worker
# integration drill (traffic through the shared SO_REUSEPORT port,
# worker kill + respawn, lease conservation) plus a 1-vs-2-worker
# bench_gateway sweep so frontend-scaling regressions land in the
# nightly trajectory. GATEWAY_WORKERS overridable for bigger boxes.
GATEWAY_WORKERS="${GATEWAY_WORKERS:-1,2}"
say "gateway smoke: 2-worker kill/respawn drill + bench_gateway --workers $GATEWAY_WORKERS"
"$PY" -m pytest tests/test_gateway.py -q -p no:cacheprovider \
    -k "end_to_end or kill_respawn"
"$PY" bench.py bench_gateway --workers "$GATEWAY_WORKERS" --nobj 8

# cluster cache tier smoke (ISSUE 15 + 18): the kill-the-owner drill
# (zero failed GETs, ring remap, bounded decodes) and the flash-crowd
# drills — the fast Zipf amplification bound plus the slow
# kill-the-lease-holder soak under randomized absorbable chaos (seeded
# for replay like the soak iterations above) — plus bench_cache_tier:
# cluster hot-GET GB/s, decode dedup vs the node-local baseline,
# flash-crowd decode amplification with leases on/off, the packed-tier
# scrub_cache_hit_rate, hint-gossip convergence and shm-vs-socket
# forward latency land in the nightly trajectory. TIER_BLOCKS
# overridable.
TIER_BLOCKS="${TIER_BLOCKS:-16}"
SEED=$(( (RANDOM << 15) ^ RANDOM ^ $$ + 2000 ))
say "cache tier smoke: kill-owner + flash-crowd drills seed=$SEED (replay: CHAOS_SOAK_SEED=$SEED pytest tests/test_cache_tier.py -k flash_crowd -s) + bench_cache_tier --nblocks $TIER_BLOCKS"
CHAOS_SOAK_SEED=$SEED JAX_PLATFORMS=cpu GARAGE_TPU_DEVICE=off "$PY" -m pytest \
    tests/test_cache_tier.py -q -p no:cacheprovider \
    -k "kill_owner or probe_hit or hints_gossip or flash_crowd"
JAX_PLATFORMS=cpu GARAGE_TPU_DEVICE=off "$PY" bench.py bench_cache_tier \
    --nblocks "$TIER_BLOCKS"

# zone subsystem smoke (ISSUE 16): the 3-zone partition drill — a
# whole zone severed under Zipf load with zero failed consistent
# quorum ops, DEGRADED-override reads from both sides of the cut, and
# counter-asserted intra-zone cache probes — plus bench_zone, whose
# local-vs-forced-cross-zone GET latency split and cross-zone byte
# counters land in the nightly trajectory. Runs under the sanitizer
# like everything above: a zone partition that wedges a loop fails.
say "zone smoke: partition-a-whole-zone drill + bench_zone"
JAX_PLATFORMS=cpu GARAGE_TPU_DEVICE=off "$PY" -m pytest \
    tests/test_zones.py -q -p no:cacheprovider \
    -k "drill or degraded_override or partition_zone_fault"
JAX_PLATFORMS=cpu GARAGE_TPU_DEVICE=off "$PY" bench.py bench_zone

# wire->device PUT-path smoke (ISSUE 17): bench_put_path pins the stub
# backend with modelled rates internally, so the frontend_efficiency /
# copy-ratio trajectory it emits is comparable night over night on any
# runner. JSON archived next to the soak log (the nightly trajectory
# artifact); the hard >= 0.8 / <= 1.1x gates live in device_smoke.py.
PUTPATH_JSON="${SOAK_LOG%.log}.putpath.json"
say "put-path smoke: bench_put_path (archiving $PUTPATH_JSON)"
JAX_PLATFORMS=cpu "$PY" bench.py bench_put_path \
    | tee "$PUTPATH_JSON"

# a stall/leak/conservation report anywhere in the soak — including
# inside a forked worker whose parent test still passed — fails the
# job; the report text names the pinned frame
sleep 1  # let tee flush
if grep -a -q "\[GARAGE_SANITIZE\]" "$SOAK_LOG"; then
    say "SANITIZER REPORTS DURING SOAK:"
    grep -a "\[GARAGE_SANITIZE\]" "$SOAK_LOG" | head -30
    exit 1
fi

say "chaos soak OK (sanitizer clean)"
