#!/usr/bin/env python3
"""Device-gated live-path smoke (nightly CI + TPU-box proof runs).

Probes the accelerator first and SKIPS CLEANLY (exit 0) when no device
answers — a deviceless runner must not fail the nightly. With a device
(or with GARAGE_TPU_DEVICE_BACKEND=stub, the CI rehearsal of the same
gate), it forks a real server under GARAGE_TPU_DEVICE=require, drives
live S3 PUTs through it, and asserts the engagement gate:
feeder_device_items > 0 on the live PUT path, with the pipeline's
overlap efficiency and pad-waste reported alongside.

Usage: python script/device_smoke.py [nobj] [obj_mib]
"""

import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def main() -> int:
    nobj = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    obj_mib = int(sys.argv[2]) if len(sys.argv) > 2 else 4

    stub = os.environ.get("GARAGE_TPU_DEVICE_BACKEND") == "stub"
    if not stub:
        from garage_tpu.block.feeder import probe_device

        res = probe_device(timeout=120.0)
        if not res["ok"]:
            print("SKIP: no device answered the probe "
                  f"({res['error'] or res['platform']})")
            return 0
        print(f"device probe ok: {res['platform']}")

    import bench

    out = bench.bench_s3_put(nobj, obj_mib, device=True)
    print(json.dumps(out, indent=2))
    if out.get("s3_feeder_device_items", 0) <= 0:
        print("FAIL: feeder_device_items == 0 — live S3 PUTs never "
              "reached the device path")
        return 1
    print("OK: live PUT path engaged the device "
          f"({out['s3_feeder_device_items']} items, overlap "
          f"{out.get('s3_feeder_overlap_efficiency', 0.0)})")

    # read-side gate (ISSUE 13): degraded GETs + rebuild waves must
    # engage the device decode route — stub and real device alike
    dec = bench.bench_decode(nblocks=4, block_kib=256,
                             device_mode="require")
    print(json.dumps(dec, indent=2))
    if dec.get("decode_feeder_device_items", 0) <= 0:
        print("FAIL: decode_feeder_device_items == 0 — degraded GETs "
              "never reached the device decode path")
        return 1
    # pattern-as-data flatness gate: under the stub nothing compiles
    # (0); on a real device only the first decode + rebuild SHAPES may
    # compile — recompiles scaling with the mixed pattern count means
    # the present-set leaked back into a jit key
    rc_ceiling = 0 if stub else 3
    if dec.get("decode_recompiles", 0) > rc_ceiling:
        print(f"FAIL: decode_recompiles = {dec['decode_recompiles']} "
              f"(> {rc_ceiling}) across "
              f"{dec['decode_patterns_mixed']} erasure patterns — "
              "decode is recompiling per pattern")
        return 1
    print("OK: degraded-GET/rebuild path engaged the device "
          f"({dec['decode_feeder_device_items']} decode items, "
          f"{dec['decode_recompiles']} recompiles across "
          f"{dec['decode_patterns_mixed']} erasure patterns)")

    # wire->device gate (ISSUE 17): bench_put_path pins the STUB
    # backend with modelled rates internally (the measurement isolates
    # the FRONTEND, so it runs identically on a deviceless CI runner
    # and a TPU box). The frontend must keep the modelled pipeline
    # >= 80% fed and land each body byte in host RAM ~once (<= 1.1x,
    # alignment slop). The per-stage breakdown prints for the TPU
    # recapture runbook (DEVICE_PATH.md).
    pp = bench.bench_put_path()
    print(json.dumps(pp, indent=2))
    if pp.get("put_feeder_device_items", 0) <= 0:
        print("FAIL: put_feeder_device_items == 0 — ingest-path PUTs "
              "never reached the device path")
        return 1
    if pp.get("put_sha256_device_items", 0) <= 0:
        print("FAIL: put_sha256_device_items == 0 — signed-chunk "
              "hashing never reached the batched sha256 lane")
        return 1
    eff = pp.get("frontend_efficiency", 0.0)
    if eff < 0.8:
        print(f"FAIL: frontend_efficiency = {eff:.3f} (< 0.8) — "
              "the frontend starves the modelled device pipeline "
              f"(ceiling {pp['put_path_modeled_ceiling_gbps']} GB/s, "
              f"measured {pp['put_path_gbps']} GB/s)")
        return 1
    ratio = pp.get("put_copy_ratio", 99.0)
    if ratio > 1.1:
        print(f"FAIL: put_copy_ratio = {ratio:.2f} (> 1.1) — PUT "
              "bodies are being re-materialized between socket and "
              f"device: {pp['put_copy_bytes_by_path']}")
        return 1
    print(f"OK: wire->device gap closed (efficiency {eff:.3f}, "
          f"copy ratio {ratio:.2f}, "
          f"{pp['put_feeder_device_items']} device items)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
