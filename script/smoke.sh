#!/usr/bin/env bash
# End-to-end smoke test against a REAL 3-process dev cluster, driven
# entirely from outside the framework: curl for S3/web/admin HTTP
# (presigned URLs, so curl carries no SDK), the operator CLI, and the
# k2v-cli binary. Mirrors the reference's script/test-smoke.sh +
# script/dev-cluster.sh (3 nodes, one machine, real TCP).
#
# Usage: script/smoke.sh        (exits 0 on success)
set -euo pipefail
cd "$(dirname "$0")/.."
REPO=$PWD
PY=${PYTHON:-python}
export PYTHONPATH="$REPO:$REPO/tests"
export JAX_PLATFORMS=cpu GARAGE_TPU_DEVICE=off PYTHONUNBUFFERED=1

TMP=$(mktemp -d "${TMPDIR:-/tmp}/gt_smoke.XXXXXX")
PIDS=()
cleanup() {
    for p in "${PIDS[@]:-}"; do kill "$p" 2>/dev/null || true; done
    wait 2>/dev/null || true
    rm -rf "$TMP"
}
trap cleanup EXIT

say() { printf '\033[1;34m== %s\033[0m\n' "$*"; }
die() { printf '\033[1;31mFAIL: %s\033[0m\n' "$*" >&2; exit 1; }

free_port() { "$PY" -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()'; }

say "generating configs for 3 nodes"
for i in 1 2 3; do
    mkdir -p "$TMP/node$i"
    eval "RPC$i=$(free_port) S3_$i=$(free_port) K2V$i=$(free_port) ADM$i=$(free_port) WEB$i=$(free_port)"
done
for i in 1 2 3; do
    rpc_var="RPC$i"; s3_var="S3_$i"; k2v_var="K2V$i"; adm_var="ADM$i"; web_var="WEB$i"
    cat > "$TMP/node$i/garage.toml" <<EOF
metadata_dir = "$TMP/node$i/meta"
data_dir = "$TMP/node$i/data"
replication_factor = 3
db_engine = "sqlite"
block_size = 65536
rpc_bind_addr = "127.0.0.1:${!rpc_var}"
rpc_public_addr = "127.0.0.1:${!rpc_var}"
rpc_secret = "00112233445566778899aabbccddeeff00112233445566778899aabbccddeeff"

[s3_api]
api_bind_addr = "127.0.0.1:${!s3_var}"
s3_region = "garage"
root_domain = ".s3.garage.test"

[k2v_api]
api_bind_addr = "127.0.0.1:${!k2v_var}"

[admin]
api_bind_addr = "127.0.0.1:${!adm_var}"
admin_token = "smoke-admin-token"

[web]
bind_addr = "127.0.0.1:${!web_var}"
root_domain = ".web.garage.test"
EOF
done

say "starting 3 server processes"
for i in 1 2 3; do
    "$PY" -m garage_tpu.cli.server --config "$TMP/node$i/garage.toml" \
        --log-level warning > "$TMP/node$i/log" 2>&1 &
    PIDS+=($!)
done
probe() { # any HTTP answer counts as up (pre-layout /health is 503)
    [ "$(curl -s -o /dev/null -w '%{http_code}' "http://127.0.0.1:$1/health")" != "000" ]
}
for i in 1 2 3; do
    adm_var="ADM$i"
    for _ in $(seq 1 100); do
        probe "${!adm_var}" && break
        sleep 0.2
    done
    probe "${!adm_var}" \
        || die "node $i did not come up ($(tail -3 "$TMP/node$i/log"))"
done

cli() { "$PY" -m garage_tpu.cli.main --config "$TMP/node1/garage.toml" "$@"; }
cli2() { "$PY" -m garage_tpu.cli.main --config "$TMP/node$1/garage.toml" "${@:2}"; }

say "connecting nodes + applying a 3-zone layout"
NODE1_ID=$(cli status | awk '/^node id:/{print $3}')
for i in 2 3; do
    cli2 "$i" connect "$NODE1_ID@127.0.0.1:$RPC1" >/dev/null
done
sleep 1
for i in 1 2 3; do
    NID=$(cli2 "$i" status | awk '/^node id:/{print $3}')
    cli layout assign "$NID" -z "dc$i" -c 1G >/dev/null
done
cli layout apply >/dev/null
# capture-then-grep: with pipefail, `cli | grep -q` is flaky — grep -q
# exits at the first match and the resulting SIGPIPE (141) fails the
# pipeline even though the match succeeded
STATUS=$(cli status)
echo "$STATUS" | grep -q "layout:   v1" \
    || { echo "$STATUS"; die "layout not applied"; }

say "creating key + bucket"
KEYOUT=$(cli key new --name smoke)
KEY_ID=$(echo "$KEYOUT" | awk '/^Key ID:/{print $3}')
SECRET=$(echo "$KEYOUT" | awk '/^Secret key:/{print $3}')
cli bucket create smoke >/dev/null
cli bucket allow smoke --key "$KEY_ID" --read --write --owner >/dev/null

presign() { # method path [extra query args as k=v ...]
    "$PY" - "$@" <<EOF
import sys
from s3util import S3Client
method, path, *rest = sys.argv[1:]
q = [tuple(a.split("=", 1)) for a in rest]
c = S3Client("127.0.0.1", $S3_1, "$KEY_ID", "$SECRET", "garage")
print(f"http://127.0.0.1:$S3_1" + c.presign(method, path, query=q or None))
EOF
}

say "S3: simple put/get via presigned curl"
head -c 100000 /dev/urandom > "$TMP/obj1"
curl -sf -X PUT --data-binary "@$TMP/obj1" "$(presign PUT /smoke/obj1)" >/dev/null \
    || die "presigned PUT failed"
curl -sf "$(presign GET /smoke/obj1)" -o "$TMP/obj1.back"
cmp "$TMP/obj1" "$TMP/obj1.back" || die "GET returned different bytes"

say "S3: multipart upload via presigned curl"
head -c 400000 /dev/urandom > "$TMP/part1"
head -c 400000 /dev/urandom > "$TMP/part2"
INIT=$(curl -sf -X POST "$(presign POST /smoke/mpobj uploads=)")
UPLOAD_ID=$(echo "$INIT" | sed -n 's/.*<UploadId>\(.*\)<\/UploadId>.*/\1/p')
[ -n "$UPLOAD_ID" ] || die "no UploadId in $INIT"
ETAG1=$(curl -sfi -X PUT --data-binary "@$TMP/part1" \
    "$(presign PUT /smoke/mpobj partNumber=1 "uploadId=$UPLOAD_ID")" \
    | tr -d '\r' | awk -F'"' 'tolower($0) ~ /^etag:/{print $2}')
ETAG2=$(curl -sfi -X PUT --data-binary "@$TMP/part2" \
    "$(presign PUT /smoke/mpobj partNumber=2 "uploadId=$UPLOAD_ID")" \
    | tr -d '\r' | awk -F'"' 'tolower($0) ~ /^etag:/{print $2}')
cat > "$TMP/complete.xml" <<EOF
<CompleteMultipartUpload>
<Part><PartNumber>1</PartNumber><ETag>"$ETAG1"</ETag></Part>
<Part><PartNumber>2</PartNumber><ETag>"$ETAG2"</ETag></Part>
</CompleteMultipartUpload>
EOF
COMPLETE=$(curl -sf -X POST --data-binary "@$TMP/complete.xml" \
    "$(presign POST /smoke/mpobj "uploadId=$UPLOAD_ID")") \
    && echo "$COMPLETE" | grep -q ETag \
    || die "complete-multipart failed: ${COMPLETE:-curl error}"
cat "$TMP/part1" "$TMP/part2" > "$TMP/mp.expect"
curl -sf "$(presign GET /smoke/mpobj)" -o "$TMP/mp.back"
cmp "$TMP/mp.expect" "$TMP/mp.back" || die "multipart GET mismatch"

say "S3: read quorum survives one node down"
kill "${PIDS[2]}" 2>/dev/null; wait "${PIDS[2]}" 2>/dev/null || true
curl -sf "$(presign GET /smoke/obj1)" -o "$TMP/obj1.back2"
cmp "$TMP/obj1" "$TMP/obj1.back2" || die "degraded GET mismatch"
"$PY" -m garage_tpu.cli.server --config "$TMP/node3/garage.toml" \
    --log-level warning >> "$TMP/node3/log" 2>&1 &
PIDS[2]=$!

say "website: vhost serving via curl Host header"
BUCKET_ID=$(curl -sf -H "Authorization: Bearer smoke-admin-token" \
    "http://127.0.0.1:$ADM1/v1/bucket?globalAlias=smoke" \
    | "$PY" -c 'import json,sys; print(json.load(sys.stdin)["id"])')
printf '<html>smoke-index</html>' > "$TMP/index.html"
curl -sf -X PUT --data-binary "@$TMP/index.html" \
    -H 'content-type: text/html' \
    "$(presign PUT /smoke/index.html)" >/dev/null
curl -sf -X PUT -H "Authorization: Bearer smoke-admin-token" \
    -d '{"websiteAccess":{"enabled":true,"indexDocument":"index.html"}}' \
    "http://127.0.0.1:$ADM1/v1/bucket?id=$BUCKET_ID" >/dev/null
WEBPAGE=$(curl -sf -H "Host: smoke.web.garage.test" \
    "http://127.0.0.1:$WEB1/") \
    && echo "$WEBPAGE" | grep -q smoke-index \
    || die "website index not served: ${WEBPAGE:-curl error}"

say "k2v: insert/read via k2v-cli"
# wait for the restarted node 3 to rejoin (k2v reads need quorum 2/3
# and inserts route to a specific storage node)
for _ in $(seq 1 50); do
    UP=$(curl -s -H "Authorization: Bearer smoke-admin-token" \
        "http://127.0.0.1:$ADM1/v1/health" \
        | "$PY" -c 'import json,sys; print(json.load(sys.stdin)["connectedNodes"])' \
        2>/dev/null || echo 0)
    [ "$UP" = "3" ] && break
    sleep 0.3
done
export AWS_ACCESS_KEY_ID="$KEY_ID" AWS_SECRET_ACCESS_KEY="$SECRET"
OUT=$("$PY" -m garage_tpu.cli.k2v --port "$K2V1" --bucket smoke \
    insert room1 msg1 "hello from smoke" 2>&1) \
    && echo "$OUT" | grep -q ok || die "k2v insert: $OUT"
OUT=$("$PY" -m garage_tpu.cli.k2v --port "$K2V1" --bucket smoke \
    read room1 msg1 2>&1) \
    && echo "$OUT" | grep -q "hello from smoke" || die "k2v read: $OUT"

say "admin: cluster healthy + metrics served"
retry() { # transient-proof: the admin API shares the node's event loop
    for _ in $(seq 1 10); do "$@" && return 0; sleep 0.5; done
    return 1
}
retry bash -c 'curl -sfm 20 -H "Authorization: Bearer smoke-admin-token" \
    "http://127.0.0.1:'"$ADM1"'/v1/health" | grep -qE "\"(healthy|degraded)\""' \
    || die "cluster not healthy"
retry bash -c 'curl -sfm 20 -H "Authorization: Bearer smoke-admin-token" \
    "http://127.0.0.1:'"$ADM1"'/metrics" | grep -q cluster_healthy' \
    || die "metrics missing"

say "admin: hot-block read cache counters exported"
CACHE_METRICS=$(curl -sfm 20 -H "Authorization: Bearer smoke-admin-token" \
    "http://127.0.0.1:$ADM1/metrics" | grep '^cache_' || true)
for counter in cache_hits cache_misses cache_evictions cache_bytes; do
    echo "$CACHE_METRICS" | grep -q "^$counter" \
        || die "cache counter $counter missing from /metrics"
done
# the GETs above ran against node 1's cache: the counters must be live
echo "$CACHE_METRICS" | grep -Eq '^cache_(hits|misses) [1-9]' \
    || die "cache counters never moved ($CACHE_METRICS)"

say "admin: cluster cache tier live on a 3-node cluster"
# the tier plane must exist (cache_tier_enabled 1 on a multi-node
# cluster with the cache on) and its probe/serve counters must be
# exported; the hint book fills as peering pings flow
for counter in cache_tier_enabled cache_tier_members cache_tier_probes \
               cache_tier_probe_hits cache_tier_hints_known; do
    echo "$CACHE_METRICS" | grep -q "^$counter" \
        || die "cache tier counter $counter missing from /metrics"
done
echo "$CACHE_METRICS" | grep -q '^cache_tier_enabled 1' \
    || die "cache tier not active ($CACHE_METRICS)"
echo "$CACHE_METRICS" | grep -Eq '^cache_tier_members [2-9]' \
    || die "cache tier ring has no members"

say "chaos: dead peer injected, writes+reads still reach quorum"
# from node 1's point of view, every RPC to node 3 now fails — the
# runtime equivalent of node 3 dropping dead mid-traffic
NODE3_ID=$(cli2 3 status | awk '/^node id:/{print $3}')
curl -sf -X POST -H "Authorization: Bearer smoke-admin-token" \
    -d "{\"seed\": 7, \"faults\": [{\"kind\": \"rpc_error\", \
\"peer\": \"${NODE3_ID:0:8}\", \"count\": 200}]}" \
    "http://127.0.0.1:$ADM1/v1/chaos" >/dev/null || die "chaos arm failed"
head -c 100000 /dev/urandom > "$TMP/objchaos"
curl -sf -X PUT --data-binary "@$TMP/objchaos" \
    "$(presign PUT /smoke/objchaos)" >/dev/null \
    || die "PUT with a dead peer failed (write quorum is 2/3)"
curl -sf "$(presign GET /smoke/objchaos)" -o "$TMP/objchaos.back" \
    || die "GET with a dead peer failed"
cmp "$TMP/objchaos" "$TMP/objchaos.back" \
    || die "GET under chaos returned different bytes"
# the faults must have actually fired (a chaos test that injects
# nothing proves nothing) ...
curl -sf -H "Authorization: Bearer smoke-admin-token" \
    "http://127.0.0.1:$ADM1/v1/chaos" \
    | "$PY" -c 'import json,sys; st=json.load(sys.stdin); \
assert st["enabled"] and st["total_fired"] >= 1, st' \
    || die "chaos faults never fired"
# ... and the chaos + self-healing rpc planes are in /metrics
CHAOS_METRICS=$(curl -sfm 20 -H "Authorization: Bearer smoke-admin-token" \
    "http://127.0.0.1:$ADM1/metrics")
echo "$CHAOS_METRICS" | grep -q '^chaos_enabled 1' \
    || die "chaos_enabled missing/wrong in /metrics"
echo "$CHAOS_METRICS" | grep -Eq '^chaos_fired_total [1-9]' \
    || die "chaos_fired_total never moved"
for m in rpc_hedge_launched_total rpc_hedge_wins_total \
         rpc_breaker_open_total rpc_hedging_enabled; do
    echo "$CHAOS_METRICS" | grep -q "^$m" \
        || die "self-healing metric $m missing from /metrics"
done
# disarm + clear: the node goes back to the no-op fast path
curl -sf -X POST -H "Authorization: Bearer smoke-admin-token" \
    -d '{"enabled": false, "clear": true}' \
    "http://127.0.0.1:$ADM1/v1/chaos" >/dev/null || die "chaos disarm failed"
curl -sf -H "Authorization: Bearer smoke-admin-token" \
    "http://127.0.0.1:$ADM1/metrics" | grep -q '^chaos_enabled 0' \
    || die "chaos did not disarm"

say "resize: kill-and-restart a node mid-workload (ISSUE 6)"
# sustained presigned PUT/GET against node 1 while node 2 is crashed
# (SIGKILL) and later restarted; every op must succeed byte-identical —
# quorum 2/3 covers the outage, the breaker covers the tail
FAILLOG="$TMP/krloop.fail"; : > "$FAILLOG"
(
    for i in $(seq 1 30); do
        head -c 60000 /dev/urandom > "$TMP/kr$i"
        curl -sf --max-time 30 -X PUT --data-binary "@$TMP/kr$i" \
            "$(presign PUT /smoke/kr$i)" >/dev/null \
            || { echo "PUT kr$i failed" >> "$FAILLOG"; continue; }
        curl -sf --max-time 30 "$(presign GET /smoke/kr$i)" \
            -o "$TMP/kr$i.back" \
            || { echo "GET kr$i failed" >> "$FAILLOG"; continue; }
        cmp -s "$TMP/kr$i" "$TMP/kr$i.back" \
            || echo "kr$i bytes differ" >> "$FAILLOG"
    done
) &
KRLOOP=$!
sleep 2
say "  crashing node 2 (SIGKILL)"
kill -9 "${PIDS[1]}" 2>/dev/null; wait "${PIDS[1]}" 2>/dev/null || true
# stay down long enough for node 1's breaker to open and pass its
# cooldown (open -> half-open needs >5 s down + traffic observing it)
sleep 8
say "  restarting node 2"
"$PY" -m garage_tpu.cli.server --config "$TMP/node2/garage.toml" \
    --log-level warning >> "$TMP/node2/log" 2>&1 &
PIDS[1]=$!
wait "$KRLOOP" || true
[ -s "$FAILLOG" ] && { cat "$FAILLOG"; die "ops failed during kill-and-restart"; }
# node 1 observed the whole breaker lifecycle: open (node 2 died),
# half-open (cooldown elapsed under traffic), closed (recovery)
KRM=$(curl -sfm 20 -H "Authorization: Bearer smoke-admin-token" \
    "http://127.0.0.1:$ADM1/metrics")
for label in open half_open closed; do
    echo "$KRM" | grep -q "rpc_breaker_transition_count{to=\"$label\"}" \
        || die "breaker never went $label during kill-and-restart"
done
# the restarted node rejoins and its resync backlog drains to zero
for _ in $(seq 1 60); do
    UP=$(curl -s -H "Authorization: Bearer smoke-admin-token" \
        "http://127.0.0.1:$ADM1/v1/health" \
        | "$PY" -c 'import json,sys; print(json.load(sys.stdin)["connectedNodes"])' \
        2>/dev/null || echo 0)
    [ "$UP" = "3" ] && break
    sleep 0.5
done
[ "$UP" = "3" ] || die "node 2 did not rejoin after restart"
for _ in $(seq 1 40); do
    BACKLOG=$(curl -sfm 20 -H "Authorization: Bearer smoke-admin-token" \
        "http://127.0.0.1:$ADM2/metrics" 2>/dev/null \
        | awk '/^resync_backlog /{print $2}' || true)
    [ "$BACKLOG" = "0" ] && break
    sleep 0.5
done
[ "$BACKLOG" = "0" ] || die "resync backlog did not drain after restart ($BACKLOG)"

say "gateway: 2 SO_REUSEPORT workers, kill one, zero failed retried ops (ISSUE 8)"
# a separate single-store node with [gateway] workers = 2: the main
# process is store + supervisor (admin only), two forked workers share
# the S3 port. Kill one worker mid-traffic: every op (with connection-
# error retries, as any S3 SDK does) must still succeed on the
# survivor, the dead worker's qos lease must drain back to the pool
# (conservation gauge stays 1), and the supervisor must respawn it.
GWDIR="$TMP/gw"; mkdir -p "$GWDIR"
GW_RPC=$(free_port); GW_S3=$(free_port); GW_ADM=$(free_port)
cat > "$GWDIR/garage.toml" <<EOF
metadata_dir = "$GWDIR/meta"
data_dir = "$GWDIR/data"
replication_factor = 1
db_engine = "sqlite"
block_size = 65536
rpc_bind_addr = "127.0.0.1:$GW_RPC"
rpc_public_addr = "127.0.0.1:$GW_RPC"

[s3_api]
api_bind_addr = "127.0.0.1:$GW_S3"
s3_region = "garage"
root_domain = ".s3.garage.test"

[admin]
api_bind_addr = "127.0.0.1:$GW_ADM"
admin_token = "smoke-admin-token"

[gateway]
workers = 2
lease_interval_s = 0.3
respawn_backoff_s = 0.5

[qos]
global_rps = 500
EOF
"$PY" -m garage_tpu.cli.server --config "$GWDIR/garage.toml" \
    --log-level warning > "$GWDIR/log" 2>&1 &
PIDS+=($!)
for _ in $(seq 1 120); do
    grep -q ready "$GWDIR/log" 2>/dev/null && break
    sleep 0.5
done
grep -q ready "$GWDIR/log" || { cat "$GWDIR/log"; die "gateway server did not come up"; }
GWNODE=$("$PY" -m garage_tpu.cli.main --config "$GWDIR/garage.toml" status \
    | awk '/^node id:/{print $NF}')
"$PY" -m garage_tpu.cli.main --config "$GWDIR/garage.toml" \
    layout assign "$GWNODE" -z dc1 -c 1G >/dev/null
"$PY" -m garage_tpu.cli.main --config "$GWDIR/garage.toml" \
    layout apply >/dev/null
GWKEYS=$("$PY" -m garage_tpu.cli.main --config "$GWDIR/garage.toml" \
    key new --name smoke-gw)
GW_KEY=$(echo "$GWKEYS" | awk '/^Key ID:/{print $NF}')
GW_SECRET=$(echo "$GWKEYS" | awk '/^Secret key:/{print $NF}')
"$PY" -m garage_tpu.cli.main --config "$GWDIR/garage.toml" \
    key allow "$GW_KEY" --create-bucket >/dev/null
# worker-labeled metrics prove the supervisor aggregates both workers
GWM=$(curl -sfm 20 -H "Authorization: Bearer smoke-admin-token" \
    "http://127.0.0.1:$GW_ADM/metrics")
echo "$GWM" | grep -q 'worker="0"' || die "no worker=0 series in gateway /metrics"
echo "$GWM" | grep -q 'worker="1"' || die "no worker=1 series in gateway /metrics"
echo "$GWM" | grep -q '^gateway_lease_conservation_ok 1' \
    || die "lease conservation not asserted before kill"
# drive PUT/GET with retries while a worker is SIGKILLed mid-loop
WORKER_PID=$(curl -sf -H "Authorization: Bearer smoke-admin-token" \
    "http://127.0.0.1:$GW_ADM/v1/gateway" \
    | "$PY" -c 'import json,sys; print(json.load(sys.stdin)["workers"][0]["pid"])')
GWFAIL=$("$PY" - "$GW_S3" "$GW_KEY" "$GW_SECRET" "$WORKER_PID" <<'PYEOF'
import os, signal, sys, time
sys.path.insert(0, "tests")
from s3util import S3Client
port, key, secret, wpid = int(sys.argv[1]), sys.argv[2], sys.argv[3], int(sys.argv[4])
c = S3Client("127.0.0.1", port, key, secret)
assert c.request("PUT", "/gwsmoke")[0] == 200
data = os.urandom(100_000)
failed = 0
for i in range(40):
    if i == 10:
        os.kill(wpid, signal.SIGKILL)  # mid-loop worker kill
    for attempt in range(4):
        try:
            st, _, _ = c.request("PUT", f"/gwsmoke/o{i}", body=data,
                                 unsigned_payload=True)
            assert st == 200
            st, _, got = c.request("GET", f"/gwsmoke/o{i}")
            assert st == 200 and got == data
            break
        except Exception:
            if attempt == 3:
                failed += 1
            time.sleep(0.05)
print(failed)
PYEOF
)
[ "$GWFAIL" = "0" ] || die "$GWFAIL gateway ops failed after retries during worker kill"
# lease drained + conserved, and the worker respawned
for _ in $(seq 1 40); do
    GWALIVE=$(curl -s -H "Authorization: Bearer smoke-admin-token" \
        "http://127.0.0.1:$GW_ADM/v1/gateway" \
        | "$PY" -c 'import json,sys; d=json.load(sys.stdin); print(d["workers_alive"], 1 if d["broker"]["conservation_ok"] else 0)' \
        2>/dev/null || echo "0 0")
    [ "$GWALIVE" = "2 1" ] && break
    sleep 0.5
done
[ "$GWALIVE" = "2 1" ] || die "worker did not respawn with conserved leases ($GWALIVE)"
curl -sfm 20 -H "Authorization: Bearer smoke-admin-token" \
    "http://127.0.0.1:$GW_ADM/metrics" \
    | grep -Eq '^gateway_worker_restarts_total [1-9]' \
    || die "gateway respawn not counted"

say "ALL SMOKE TESTS PASSED"
