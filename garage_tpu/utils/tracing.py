"""Distributed tracing: contextvar trace ids, timed spans, JSONL export.

Ref parity: the reference wraps every RPC, table op, block IO and PUT
pipeline stage in OpenTelemetry spans and exports OTLP
(src/garage/tracing_setup.rs:13-37, src/rpc/rpc_helper.rs:172-190,
src/api/s3/put.rs:395,424,452). This build keeps the same span
topology with a dependency-free tracer:

- a contextvar carries (trace_id, span_id) across awaits, so every
  nested span knows its parent without explicit plumbing
- `span("name", **attrs)` works as a sync or async context manager;
  when tracing is disabled it costs one attribute read
- finished spans go to an in-memory ring (admin API /trace tail) and,
  when `GARAGE_TPU_TRACE=<path>` (or `enable(path)`) is set, to a
  JSON-lines file — one object per span with trace/span/parent ids,
  name, start (unix us), dur_us, and attrs
- the rpc layer propagates the trace id on the wire (conn.call header)
  so one S3 request's spans correlate across nodes
"""

from __future__ import annotations

import atexit
import contextvars
import json
import os
import secrets
import threading
import time
from collections import deque
from typing import Optional

_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "garage_tpu_trace", default=None)  # (trace_id: str, span_id: str)

RING_MAX = 2048


_FLUSH_EVERY = 128  # spans buffered before one batched write() syscall


class Tracer:
    def __init__(self):
        self.enabled = bool(os.environ.get("GARAGE_TPU_TRACE"))
        self._path = os.environ.get("GARAGE_TPU_TRACE") or None
        if self._path in ("1", "ring"):  # ring-only mode
            self._path = None
        self._file = None
        self._buf: list[str] = []
        self._lock = threading.Lock()
        self.ring: deque = deque(maxlen=RING_MAX)
        # extra span consumers (e.g. the OTLP exporter, utils/otlp.py);
        # each gets every finished span record and must not block
        self.sinks: list = []

    def enable(self, path: Optional[str] = None) -> None:
        self.enabled = True
        if path:
            self._close()
            self._path = path

    def disable(self) -> None:
        self.enabled = False
        self._close()

    def _close(self) -> None:
        with self._lock:
            self._flush_locked()
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buf or self._path is None:
            return
        if self._file is None:
            try:
                self._file = open(self._path, "a")
            except OSError:
                self._path = None
                self._buf.clear()
                return
        try:
            self._file.write("".join(self._buf))
            self._file.flush()
        except OSError:
            pass
        self._buf.clear()

    def emit(self, rec: dict) -> None:
        self.ring.append(rec)
        for sink in self.sinks:
            sink(rec)
        if self._path is None:
            return
        # buffer; one write() per _FLUSH_EVERY spans keeps the export
        # off the hot path (a 4 MiB PUT emits ~200 spans)
        with self._lock:
            self._buf.append(json.dumps(rec, separators=(",", ":")) + "\n")
            if len(self._buf) >= _FLUSH_EVERY:
                self._flush_locked()


tracer = Tracer()
atexit.register(tracer.flush)


def current_trace_id() -> Optional[str]:
    """Wire form "trace_id:span_id" — the caller's span id rides along
    so remote-side spans parent-link into the caller's tree."""
    cur = _ctx.get()
    return f"{cur[0]}:{cur[1]}" if cur else None


def set_remote_context(wire: Optional[str]) -> None:
    """Adopt a trace context that arrived over the wire (handler side)."""
    if wire and ":" in wire:
        trace_id, span_id = wire.split(":", 1)
        _ctx.set((trace_id, span_id))
    elif wire:
        _ctx.set((wire, "remote"))


class span:
    """with span("table.insert", table=name): ...  (sync or async)."""

    __slots__ = ("name", "attrs", "t0", "ids", "token")

    def __init__(self, name: str, **attrs):
        self.name = name
        self.attrs = attrs
        self.token = None

    def _enter(self):
        if not tracer.enabled:
            return self
        parent = _ctx.get()
        if parent is None:
            trace_id = secrets.token_hex(8)
            parent_id = None
        else:
            trace_id, parent_id = parent
        span_id = secrets.token_hex(4)
        self.ids = (trace_id, span_id, parent_id)
        self.token = _ctx.set((trace_id, span_id))
        self.t0 = time.perf_counter()
        return self

    def _exit(self, exc_type):
        if self.token is None:
            return False
        dur_us = int((time.perf_counter() - self.t0) * 1e6)
        trace_id, span_id, parent_id = self.ids
        rec = {
            "trace": trace_id,
            "span": span_id,
            "parent": parent_id,
            "name": self.name,
            "start_us": int(time.time() * 1e6) - dur_us,
            "dur_us": dur_us,
        }
        if self.attrs:
            rec["attrs"] = {k: (v.hex()[:16] if isinstance(v, bytes) else v)
                            for k, v in self.attrs.items()}
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        tracer.emit(rec)
        _ctx.reset(self.token)
        self.token = None
        return False

    def __enter__(self):
        return self._enter()

    def __exit__(self, exc_type, exc, tb):
        return self._exit(exc_type)

    async def __aenter__(self):
        return self._enter()

    async def __aexit__(self, exc_type, exc, tb):
        # lint: ignore[GL10] emit buffers; the open+write is one amortized page-cache append per _FLUSH_EVERY spans on an already-open file
        return self._exit(exc_type)
