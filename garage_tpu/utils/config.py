"""TOML configuration. Ref parity: src/util/config.rs:13-263.

Field names mirror the reference's garage.toml so operators can port configs
nearly verbatim; TPU-specific knobs live under [tpu].
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any, Optional

try:  # stdlib on 3.11+; bare 3.10 images have neither tomllib nor tomli
    import tomllib
except ModuleNotFoundError:
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ModuleNotFoundError:
        tomllib = None


@dataclass
class DataDir:
    path: str
    capacity: Optional[int] = None  # bytes; None => read-only dir
    read_only: bool = False


@dataclass
class TpuConfig:
    """TPU data-plane knobs (no reference analogue; README "The TPU
    data plane"). The feeder routing/trial knobs were hard-coded module
    constants before the staged pipeline landed; a None leaves the
    feeder's built-in default in force. inflight_batches /
    device_min_bytes / device_min_items are also runtime-tunable via
    admin GET/POST /v1/s3/tuning."""

    enable: bool = True
    # max blocks shipped to the device in one encode/hash call (the
    # feeder's greedy-drain cap; 256 matches the previously hard-coded
    # value)
    batch_blocks: int = 256
    # platform override for tests ("cpu" forces the jnp fallback path)
    platform: Optional[str] = None
    # staged-pipeline depth: device batches concurrently in flight
    # through the h2d/compute/d2h stages (3 = one per stage; 2 = double
    # buffering, cheaper on device RAM but leaves the transfer engine
    # idle while the batch ahead computes + reads back)
    inflight_batches: int = 3
    # calibration routing floors: batches below BOTH never leave the
    # host (a device round trip costs more than it saves there)
    device_min_bytes: Optional[int] = None  # default 4 MiB
    device_min_items: Optional[int] = None  # default 4
    # read-side floors (decode/repair, ISSUE 13): a lone degraded GET
    # decodes host-inline for latency; only coalesced bursts
    # (concurrent degraded GETs, scrub/resync rebuild waves) pay a
    # device trip. Runtime-tunable via admin /v1/s3/tuning.
    device_min_decode_bytes: Optional[int] = None  # default 4 MiB
    device_min_decode_items: Optional[int] = None  # default 4
    # exploration-trial caps: items/bytes sacrificed to re-time the
    # currently-losing backend (block/feeder.py _trial_cut)
    trial_max_items: Optional[int] = None   # default 2
    trial_items_cap: Optional[int] = None   # default 8
    trial_max_bytes: Optional[int] = None   # default 4 MiB
    # fixed-shape launch buckets: item counts pad up to the next value
    # here so XLA compiles a handful of programs instead of one per
    # batch shape (feeder_pad_waste_bytes / feeder_recompiles track
    # the trade); shard lengths round to the next power of two
    pad_buckets: list = field(
        default_factory=lambda: [1, 2, 4, 8, 16, 32, 64, 128, 256])
    # batches of at least this many items shard across every visible
    # chip through parallel/mesh.py's (dp, tp) data-plane mesh
    mesh_min_items: int = 8
    # "jax" = real accelerator; "stub" = deterministic latency
    # emulator (CI / deviceless boxes; GARAGE_TPU_DEVICE_BACKEND
    # env var overrides)
    device_backend: str = "jax"
    # per-batch watchdog budget, seconds (covers every pipeline stage)
    batch_timeout_s: Optional[float] = None  # default 300
    # batch-formation linger, milliseconds (ISSUE 17): how long the
    # dispatcher holds a hash/encode batch open waiting for sibling
    # PUT streams' submissions to line up. Under light load a trickle
    # of PUTs used to ride size-1 host fallbacks because the greedy
    # drain found an empty queue; the linger (still gated on >1 active
    # stream) lets them coalesce into one device launch. 0 disables.
    batch_linger_ms: Optional[float] = None  # default 6.0


@dataclass
class QosConfig:
    """[qos] admission control + background-work governor (no reference
    analogue; see garage_tpu/qos/). A None limit disables that limiter
    entirely — an absent [qos] section costs nothing on the request
    path. The governor IS on by default (background repair yields to
    foreground latency, sprints when idle); `governor = false` keeps
    the static tranquilities, and an explicit `worker set
    *-tranquility` always outranks it (persisted for scrub)."""

    global_rps: Optional[float] = None
    global_burst: Optional[float] = None
    global_bytes_per_s: Optional[float] = None
    global_bytes_burst: Optional[float] = None
    per_key_rps: Optional[float] = None
    per_bucket_rps: Optional[float] = None
    max_concurrent: Optional[int] = None
    max_queue: int = 64
    max_wait_s: float = 0.5
    # deficit round-robin across per-key queues INSIDE the global bytes
    # bucket (qos/limiter.py DeficitRoundRobin): under byte-budget
    # contention every active key gets an equal share of the drain
    # instead of first-come-first-served, so one hot key cannot
    # monopolize a worker's lease before per-key limits bite
    fair_keys: bool = True
    governor: bool = True
    governor_interval: float = 2.0
    governor_target_latency: float = 0.05  # seconds
    scrub_tranquility_min: float = 1.0
    scrub_tranquility_max: float = 30.0
    resync_tranquility_min: float = 0.0
    resync_tranquility_max: float = 2.0
    # resync/rebalance backlog depth at which the governor's backlog
    # signal saturates (rebalance yields to foreground p99 during a
    # cluster resize; README "Cluster resize")
    resync_backlog_ref: float = 256.0


@dataclass
class GatewayConfig:
    """[gateway] multi-process S3/K2V/web frontend (garage_tpu/gateway/;
    no reference analogue; README "Multi-process gateway"). `workers`
    selects how many API worker processes share the frontend ports via
    SO_REUSEPORT: 1 (default) keeps today's in-process frontends —
    byte-compatible with every prior release — and 0 means
    auto(cpu_count). With N > 1 the main process becomes the store node
    + supervisor (no S3 frontend of its own): it forks N API-only
    worker nodes, rents each a lease on the node's qos budgets
    (rebalanced by observed demand every `lease_interval_s`, reclaimed
    `lease_ttl_s` after a worker goes silent), respawns crashed workers
    no faster than `respawn_backoff_s`, and aggregates their /metrics
    under a `worker` label. `cache_shard` routes cacheable block reads
    to a consistent-hash owner worker so the node holds ONE decoded
    copy of a hot block instead of N. `min_share` is the fraction of a
    worker's fair share it always keeps leased even when idle (the
    demand-discovery floor)."""

    workers: int = 1
    lease_interval_s: float = 1.0
    lease_ttl_s: float = 3.0
    min_share: float = 0.05
    respawn_backoff_s: float = 2.0
    cache_shard: bool = True
    # zero-copy intra-node cache forwards (ISSUE 15, gateway/shm.py):
    # the owner worker publishes the decoded payload once into a
    # shared-memory ring and the forwarding worker serves it via
    # memoryview — no payload bytes cross the loopback socket. false =
    # kill switch, every forward carries bytes over the socket again.
    shm_forwards: bool = True
    # ring capacity per worker and the reuse lease: a published slot
    # is never overwritten before its lease expires, which bounds how
    # long a forwarding worker may keep serving the mapped bytes
    shm_ring_bytes: int = 64 * 1024 * 1024
    shm_lease_s: float = 60.0


@dataclass
class ChaosConfig:
    """[chaos] deterministic fault injection (garage_tpu/chaos/; no
    reference analogue). Disabled by default — the seams are single
    pointer-compare no-ops until armed. `faults` is a list of inline
    tables matching chaos.FaultSpec fields, e.g.

        [chaos]
        enable = true
        seed = 42
        faults = [ {kind = "rpc_error", peer = "ab12", prob = 0.1} ]

    Runtime arm/disarm/inspect via admin `GET/POST /v1/chaos`."""

    enable: bool = False
    seed: int = 0
    faults: list = field(default_factory=list)


@dataclass
class Config:
    # ref: util/config.rs:13-258
    metadata_dir: str = ""
    data_dir: list[DataDir] = field(default_factory=list)
    metadata_fsync: bool = False
    data_fsync: bool = False
    block_size: int = 1024 * 1024  # ref default 1 MiB (util/config.rs:269-271)
    block_ram_buffer_max: int = 256 * 1024 * 1024
    # [block] read_cache_max_bytes: budget of the node-local hot-block
    # read cache (block/cache.py). None = default to
    # block_ram_buffer_max // 4; 0 disables. Runtime-tunable via admin
    # POST /v1/s3/tuning (README "Hot-block read cache").
    block_read_cache_max_bytes: Optional[int] = None
    # [block] resync_breaker_aware: rebalance/resync pushes skip peers
    # whose circuit breaker is open and spread across healthy holders
    # (README "Cluster resize"); off restores blind placement
    block_resync_breaker_aware: bool = True
    # [block] cache_tier: CLUSTER-wide read cache tier (ISSUE 15,
    # block/cache_tier.py; README "Cluster cache tier"). Non-owner
    # reads probe the block's rendezvous-hash owner node in one hop
    # and warm it on miss, so the cluster pays ~1 decode per hot block
    # instead of one per node. false = every read serves node-locally
    # (the pre-tier behavior); the node-local cache itself is governed
    # by read_cache_max_bytes as before.
    block_cache_tier: bool = True
    # [block] cache_tier_hint_top_n: hottest cache keys gossiped per
    # peering ping (hot-hash hints; background resync reads probe the
    # tier only for hinted-hot blocks)
    block_cache_tier_hint_top_n: int = 16
    # [block] cache_lease_wait_ms: probe singleflight lease wait
    # (ISSUE 18, README "Cluster cache tier"). A probe that misses at
    # the owner behind a live lease parks up to this long — budgeted
    # INSIDE the flat probe timeout — for the lease holder's decode to
    # land; default ≈ the observed p95 of a 1 MiB erasure gather+decode.
    # 0 disables leases entirely (probes answer flat misses, the
    # pre-lease race returns).
    block_cache_lease_wait_ms: float = 250.0
    # [block] cache_prefetch_inflight: concurrent hint-driven prefetch
    # decodes at a cache owner (bounded queue, qos-governor-paced);
    # 0 disables prefetch
    block_cache_prefetch_inflight: int = 2
    # [block] cache_packed_max_bytes: byte budget of the packed-bytes
    # tier segment (exact on-disk packed block images; shard rebuilds
    # and scrub stripe repairs re-encode from it with zero gather
    # RPCs). None = block_ram_buffer_max // 8; 0 disables. Erasure
    # mode only — replicate stores hold no stripes to rebuild.
    block_cache_packed_max_bytes: Optional[int] = None
    compression_level: Optional[int] = 1  # zstd level; None disables
    replication_factor: int = 1
    consistency_mode: str = "consistent"  # consistent|degraded|dangerous
    # erasure coding mode (north star; not in reference): e.g. "4,2" => k=4,m=2
    erasure_coding: Optional[str] = None
    # block content hash: "blake3" (TPU-batchable tree hash, default) or
    # "blake2" (the reference's sequential hash, for migrated stores)
    block_hash_algo: str = "blake3"

    rpc_secret: Optional[str] = None
    rpc_secret_file: Optional[str] = None
    rpc_bind_addr: str = "127.0.0.1:3901"
    rpc_public_addr: Optional[str] = None
    # [rpc] self-healing knobs (rpc/rpc_helper.py + net/peering.py
    # PeerHealthTracker; README "Fault injection & self-healing RPC"):
    # hedged reads on/off, the cluster-wide hedge rate cap (token
    # bucket, hedges/s), and p99-derived adaptive per-call timeouts
    rpc_hedging: bool = True
    rpc_hedge_rate: float = 8.0
    # [rpc] hedge_writes: backup pushes for IDEMPOTENT writes that
    # opted in per-call (erasure shard puts; README "Cluster resize").
    # Off = writes never hedge, regardless of per-call opt-ins.
    rpc_hedge_writes: bool = True
    rpc_adaptive_timeout: bool = True
    # [rpc] layout_debounce_ms: coalescing window for layout gossip
    # broadcasts (rpc/layout/manager.py). Every tracker tick during a
    # resize fires a change; broadcasting each one is an O(N^2) gossip
    # storm, so back-to-back changes ride one wave per window. Raise on
    # big clusters, lower for snappier test convergence.
    rpc_layout_debounce_ms: float = 100.0
    bootstrap_peers: list[str] = field(default_factory=list)
    # external discovery (ref: rpc/consul.rs, rpc/kubernetes.rs);
    # TOML sections [consul_discovery] / [kubernetes_discovery]
    consul_http_addr: Optional[str] = None
    consul_service_name: Optional[str] = None
    kubernetes_namespace: Optional[str] = None
    kubernetes_service_name: Optional[str] = None

    # [metadata] db_engine: sqlite (durable default) | memory (tests) |
    # lsm (log-structured merge engine for metadata at millions of
    # keys; README "Metadata at scale"). Top-level `db_engine = ...`
    # also accepted, like the reference garage.toml.
    db_engine: str = "sqlite"

    s3_api_bind_addr: Optional[str] = None
    s3_region: str = "garage"
    root_domain: str = ".s3.garage"
    # [s3_api] data-plane tuning (no reference analogue; see README
    # "S3 data-plane tuning"). get_readahead_blocks: how many blocks the
    # GET path prefetches beyond the one currently streaming to the
    # client (0 = strictly sequential, the pre-readahead behavior).
    # put_blocks_max_parallel: concurrent block writes in the PUT
    # pipeline (ref: put.rs:42 used a hard-coded 3). Both are runtime
    # read/writable via admin `GET/POST /v1/s3/tuning` for bench sweeps.
    s3_get_readahead_blocks: int = 3
    s3_put_blocks_max_parallel: int = 3
    # ingest_buffers: pinned host buffers for the zero-copy PUT path
    # (ISSUE 17, block/hostbuf.py) — each holds one block in stripe
    # layout, so the pool pins ~N * block_size RAM; exhaustion
    # backpressures PUTs instead of allocating. 0 disables the
    # zero-copy path entirely (every PUT takes the classic copy path).
    s3_ingest_buffers: int = 16
    k2v_api_bind_addr: Optional[str] = None
    admin_api_bind_addr: Optional[str] = None
    admin_token: Optional[str] = None
    # lint: ignore[GL08] read via getattr in fill_secrets
    admin_token_file: Optional[str] = None
    metrics_token: Optional[str] = None
    # lint: ignore[GL08] read via getattr in fill_secrets
    metrics_token_file: Optional[str] = None
    # [admin] trace_sink: OTLP/HTTP collector base URL (ref:
    # config.rs admin.trace_sink + garage/tracing_setup.rs)
    admin_trace_sink: Optional[str] = None
    web_bind_addr: Optional[str] = None
    web_root_domain: str = ".web.garage"

    # [table] sync_tranquility_max: per-partition sleep (seconds) the
    # qos governor applies to table anti-entropy rounds at full
    # pressure (qos/governor.py; was the hard-coded
    # TABLE_SYNC_TRANQ_MAX). 0 disables governor pacing of table sync.
    table_sync_tranquility_max: float = 0.05

    metadata_auto_snapshot_interval: Optional[float] = None  # seconds
    metadata_snapshots_dir: Optional[str] = None  # default {meta}/snapshots

    tpu: TpuConfig = field(default_factory=TpuConfig)
    qos: QosConfig = field(default_factory=QosConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    gateway: GatewayConfig = field(default_factory=GatewayConfig)

    @property
    def data_dirs(self) -> list[DataDir]:
        return self.data_dir

    @property
    def erasure_params(self) -> Optional[tuple[int, int]]:
        if not self.erasure_coding:
            return None
        k, m = self.erasure_coding.split(",")
        return int(k), int(m)


def _parse_data_dir(v: Any) -> list[DataDir]:
    # Accept a single path string or a list of {path, capacity, read_only}
    # tables (multi-HDD mode, ref: util/config.rs DataDirEnum).
    if isinstance(v, str):
        return [DataDir(path=v)]
    out = []
    for d in v:
        if isinstance(d, str):
            out.append(DataDir(path=d))
        else:
            cap = d.get("capacity")
            if isinstance(cap, str):
                cap = parse_capacity(cap)
            out.append(DataDir(path=d["path"], capacity=cap,
                               read_only=bool(d.get("read_only", False))))
    return out


def parse_capacity(s: str) -> int:
    """'1G', '100M', '2T' → bytes (decimal units like the reference)."""
    s = s.strip()
    units = {"k": 10**3, "m": 10**6, "g": 10**9, "t": 10**12}
    if s and s[-1].lower() in units:
        return int(float(s[:-1]) * units[s[-1].lower()])
    return int(s)


def _toml_scalar(s: str):
    s = s.strip()
    if len(s) >= 2 and s[0] == s[-1] and s[0] in "\"'":
        return s[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    if s == "true":
        return True
    if s == "false":
        return False
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        return s  # bare value; garage.toml doesn't use these


def _split_toml_array(s: str) -> list[str]:
    out, depth, cur, quote = [], 0, "", None
    for ch in s:
        if quote:
            cur += ch
            if ch == quote and not cur.endswith("\\" + quote):
                quote = None
            continue
        if ch in "\"'":
            quote = ch
            cur += ch
        elif ch in "[{":
            depth += 1
            cur += ch
        elif ch in "]}":
            depth -= 1
            cur += ch
        elif ch == "," and depth == 0:
            out.append(cur)
            cur = ""
        else:
            cur += ch
    if cur.strip():
        out.append(cur)
    return out


def _toml_value(s: str):
    s = s.strip()
    if s.startswith("[") and s.endswith("]"):
        return [_toml_value(p) for p in _split_toml_array(s[1:-1])]
    if s.startswith("{") and s.endswith("}"):
        d = {}
        for pair in _split_toml_array(s[1:-1]):
            k, _, v = pair.partition("=")
            d[k.strip().strip('"')] = _toml_value(v)
        return d
    return _toml_scalar(s)


def parse_toml_minimal(text: str) -> dict:
    """Fallback TOML-subset parser for images without tomllib/tomli
    (Python <= 3.10): sections, key = scalar/array/inline-table,
    comments. Covers the full garage.toml surface this build reads;
    NOT a general TOML implementation (no multi-line values, no
    [[array-of-tables]], no date types)."""
    root: dict = {}
    cur = root
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            cur = root
            for part in line[1:-1].split("."):
                cur = cur.setdefault(part.strip().strip('"'), {})
            continue
        key, sep, val = line.partition("=")
        if not sep:
            raise ValueError(f"unparseable config line: {line!r}")
        # cut at the first '#' that is outside any quoted string
        quote = None
        for i, ch in enumerate(val):
            if quote:
                if ch == quote:
                    quote = None
            elif ch in "\"'":
                quote = ch
            elif ch == "#":
                val = val[:i]
                break
        cur[key.strip().strip('"')] = _toml_value(val)
    return root


def read_config(path: str) -> Config:
    """ref: util/config.rs:259 read_config. Env var GARAGE_RPC_SECRET etc.
    override file values (subset of the reference's layered secrets)."""
    with open(path, "rb") as f:
        data = f.read()
    if tomllib is not None:
        raw = tomllib.loads(data.decode())
    else:
        raw = parse_toml_minimal(data.decode())
    return config_from_dict(raw)


def config_from_dict(raw: dict) -> Config:
    cfg = Config()
    simple_fields = {f.name for f in dataclasses.fields(Config)} \
        - {"data_dir", "tpu", "qos", "chaos", "gateway"}
    for key, val in raw.items():
        if key == "data_dir":
            cfg.data_dir = _parse_data_dir(val)
        elif key == "tpu" and isinstance(val, dict):
            cfg.tpu = TpuConfig(**val)
        elif key == "qos" and isinstance(val, dict):
            cfg.qos = QosConfig(**val)
        elif key == "chaos" and isinstance(val, dict):
            cfg.chaos = ChaosConfig(**val)
        elif key == "gateway" and isinstance(val, dict):
            cfg.gateway = GatewayConfig(**val)
        elif key in ("s3_api", "k2v_api", "admin", "web", "block", "rpc",
                     "table", "metadata",
                     "consul_discovery", "kubernetes_discovery"):
            # nested sections like the reference layout; [metadata]
            # db_engine / fsync map onto the top-level fields so the
            # engine selection reads like the docs ([metadata]
            # db_engine = "lsm")
            prefix = {"s3_api": "s3_", "k2v_api": "k2v_",
                      "admin": "admin_", "web": "web_", "block": "block_",
                      "rpc": "rpc_", "table": "table_",
                      "metadata": "metadata_",
                      "consul_discovery": "consul_",
                      "kubernetes_discovery": "kubernetes_"}[key]
            for k2, v2 in val.items():
                attr = k2 if k2.startswith(prefix) else None
                # prefixed name first: [web] root_domain must map to
                # web_root_domain, not the top-level (S3) root_domain
                for cand in (prefix + k2, k2, {
                    "api_bind_addr": prefix + "api_bind_addr",
                }.get(k2, "")):
                    if cand in simple_fields:
                        attr = cand
                        break
                if attr:
                    if attr in ("block_size", "block_ram_buffer_max",
                                "block_read_cache_max_bytes",
                                "block_cache_packed_max_bytes") \
                            and isinstance(v2, str):
                        v2 = parse_capacity(v2)
                    setattr(cfg, attr, v2)
        elif key in simple_fields:
            if key in ("block_size", "block_ram_buffer_max",
                       "block_read_cache_max_bytes",
                       "block_cache_packed_max_bytes") \
                    and isinstance(val, str):
                val = parse_capacity(val)
            setattr(cfg, key, val)
        # unknown keys ignored (forward compat)
    fill_secrets(cfg)
    if not cfg.metadata_dir:
        raise ValueError("metadata_dir is required")
    return cfg


def _read_secret_file(path: str) -> str:
    """Read a one-line secret file with a permission check: refuse
    group/world-readable files unless GARAGE_ALLOW_WORLD_READABLE_SECRETS
    is set (ref: src/garage/secrets.rs:54-120)."""
    if not os.environ.get("GARAGE_ALLOW_WORLD_READABLE_SECRETS"):
        mode = os.stat(path).st_mode
        if mode & 0o077:
            raise ValueError(
                f"secret file {path} is readable by other users "
                f"(mode {mode & 0o777:03o}); chmod 600 it or set "
                "GARAGE_ALLOW_WORLD_READABLE_SECRETS=1")
    with open(path) as f:
        return f.read().strip()


def fill_secrets(cfg: "Config") -> None:
    """Layered secret resolution, per secret: env var > env _FILE var >
    config *_file > config inline (ref: src/garage/secrets.rs
    fill_secrets — same precedence, CLI flags excepted). An env value
    OVERRIDES config-file sources (that is the point of the layering —
    rotation without editing the TOML); only the two env forms
    conflicting is an error."""
    for attr, env in (("rpc_secret", "GARAGE_RPC_SECRET"),
                      ("admin_token", "GARAGE_ADMIN_TOKEN"),
                      ("metrics_token", "GARAGE_METRICS_TOKEN")):
        file_attr = f"{attr}_file"
        env_val = os.environ.get(env)
        env_file = os.environ.get(f"{env}_FILE")
        if env_val and env_file:
            raise ValueError(f"both {env} and {env}_FILE are set; "
                             "pick one")
        if env_val:
            setattr(cfg, attr, env_val)
            continue
        if env_file:
            setattr(cfg, attr, _read_secret_file(env_file))
            continue
        cfg_file = getattr(cfg, file_attr, None)
        if cfg_file:
            if getattr(cfg, attr, None):
                raise ValueError(
                    f"both {attr} and {file_attr} are set in the "
                    "config; pick one")
            setattr(cfg, attr, _read_secret_file(cfg_file))
