"""TOML configuration. Ref parity: src/util/config.rs:13-263.

Field names mirror the reference's garage.toml so operators can port configs
nearly verbatim; TPU-specific knobs live under [tpu].
"""

from __future__ import annotations

import dataclasses
import os
import tomllib
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class DataDir:
    path: str
    capacity: Optional[int] = None  # bytes; None => read-only dir
    read_only: bool = False


@dataclass
class TpuConfig:
    """TPU data-plane knobs (no reference analogue)."""

    enable: bool = True
    # batch of blocks shipped to the device in one encode/hash call
    batch_blocks: int = 16
    # platform override for tests ("cpu" forces the jnp fallback path)
    platform: Optional[str] = None


@dataclass
class Config:
    # ref: util/config.rs:13-258
    metadata_dir: str = ""
    data_dir: list[DataDir] = field(default_factory=list)
    metadata_fsync: bool = False
    data_fsync: bool = False
    block_size: int = 1024 * 1024  # ref default 1 MiB (util/config.rs:269-271)
    block_ram_buffer_max: int = 256 * 1024 * 1024
    compression_level: Optional[int] = 1  # zstd level; None disables
    replication_factor: int = 1
    consistency_mode: str = "consistent"  # consistent|degraded|dangerous
    # erasure coding mode (north star; not in reference): e.g. "4,2" => k=4,m=2
    erasure_coding: Optional[str] = None
    # block content hash: "blake3" (TPU-batchable tree hash, default) or
    # "blake2" (the reference's sequential hash, for migrated stores)
    block_hash_algo: str = "blake3"

    rpc_secret: Optional[str] = None
    rpc_secret_file: Optional[str] = None
    rpc_bind_addr: str = "127.0.0.1:3901"
    rpc_public_addr: Optional[str] = None
    bootstrap_peers: list[str] = field(default_factory=list)
    # external discovery (ref: rpc/consul.rs, rpc/kubernetes.rs);
    # TOML sections [consul_discovery] / [kubernetes_discovery]
    consul_http_addr: Optional[str] = None
    consul_service_name: Optional[str] = None
    kubernetes_namespace: Optional[str] = None
    kubernetes_service_name: Optional[str] = None

    db_engine: str = "sqlite"  # sqlite|memory (lmdb not in this image)

    s3_api_bind_addr: Optional[str] = None
    s3_region: str = "garage"
    root_domain: str = ".s3.garage"
    k2v_api_bind_addr: Optional[str] = None
    admin_api_bind_addr: Optional[str] = None
    admin_token: Optional[str] = None
    admin_token_file: Optional[str] = None
    metrics_token: Optional[str] = None
    metrics_token_file: Optional[str] = None
    # [admin] trace_sink: OTLP/HTTP collector base URL (ref:
    # config.rs admin.trace_sink + garage/tracing_setup.rs)
    admin_trace_sink: Optional[str] = None
    web_bind_addr: Optional[str] = None
    web_root_domain: str = ".web.garage"

    metadata_auto_snapshot_interval: Optional[float] = None  # seconds
    metadata_snapshots_dir: Optional[str] = None  # default {meta}/snapshots

    tpu: TpuConfig = field(default_factory=TpuConfig)

    @property
    def data_dirs(self) -> list[DataDir]:
        return self.data_dir

    @property
    def erasure_params(self) -> Optional[tuple[int, int]]:
        if not self.erasure_coding:
            return None
        k, m = self.erasure_coding.split(",")
        return int(k), int(m)


def _parse_data_dir(v: Any) -> list[DataDir]:
    # Accept a single path string or a list of {path, capacity, read_only}
    # tables (multi-HDD mode, ref: util/config.rs DataDirEnum).
    if isinstance(v, str):
        return [DataDir(path=v)]
    out = []
    for d in v:
        if isinstance(d, str):
            out.append(DataDir(path=d))
        else:
            cap = d.get("capacity")
            if isinstance(cap, str):
                cap = parse_capacity(cap)
            out.append(DataDir(path=d["path"], capacity=cap,
                               read_only=bool(d.get("read_only", False))))
    return out


def parse_capacity(s: str) -> int:
    """'1G', '100M', '2T' → bytes (decimal units like the reference)."""
    s = s.strip()
    units = {"k": 10**3, "m": 10**6, "g": 10**9, "t": 10**12}
    if s and s[-1].lower() in units:
        return int(float(s[:-1]) * units[s[-1].lower()])
    return int(s)


def read_config(path: str) -> Config:
    """ref: util/config.rs:259 read_config. Env var GARAGE_RPC_SECRET etc.
    override file values (subset of the reference's layered secrets)."""
    with open(path, "rb") as f:
        raw = tomllib.load(f)
    return config_from_dict(raw)


def config_from_dict(raw: dict) -> Config:
    cfg = Config()
    simple_fields = {f.name for f in dataclasses.fields(Config)} - {"data_dir", "tpu"}
    for key, val in raw.items():
        if key == "data_dir":
            cfg.data_dir = _parse_data_dir(val)
        elif key == "tpu" and isinstance(val, dict):
            cfg.tpu = TpuConfig(**val)
        elif key in ("s3_api", "k2v_api", "admin", "web",
                     "consul_discovery", "kubernetes_discovery"):
            # nested sections like the reference layout
            prefix = {"s3_api": "s3_", "k2v_api": "k2v_",
                      "admin": "admin_", "web": "web_",
                      "consul_discovery": "consul_",
                      "kubernetes_discovery": "kubernetes_"}[key]
            for k2, v2 in val.items():
                attr = k2 if k2.startswith(prefix) else None
                # prefixed name first: [web] root_domain must map to
                # web_root_domain, not the top-level (S3) root_domain
                for cand in (prefix + k2, k2, {
                    "api_bind_addr": prefix + "api_bind_addr",
                }.get(k2, "")):
                    if cand in simple_fields:
                        attr = cand
                        break
                if attr:
                    setattr(cfg, attr, v2)
        elif key in simple_fields:
            if key == "block_size" and isinstance(val, str):
                val = parse_capacity(val)
            setattr(cfg, key, val)
        # unknown keys ignored (forward compat)
    fill_secrets(cfg)
    if not cfg.metadata_dir:
        raise ValueError("metadata_dir is required")
    return cfg


def _read_secret_file(path: str) -> str:
    """Read a one-line secret file with a permission check: refuse
    group/world-readable files unless GARAGE_ALLOW_WORLD_READABLE_SECRETS
    is set (ref: src/garage/secrets.rs:54-120)."""
    if not os.environ.get("GARAGE_ALLOW_WORLD_READABLE_SECRETS"):
        mode = os.stat(path).st_mode
        if mode & 0o077:
            raise ValueError(
                f"secret file {path} is readable by other users "
                f"(mode {mode & 0o777:03o}); chmod 600 it or set "
                "GARAGE_ALLOW_WORLD_READABLE_SECRETS=1")
    with open(path) as f:
        return f.read().strip()


def fill_secrets(cfg: "Config") -> None:
    """Layered secret resolution, per secret: env var > env _FILE var >
    config *_file > config inline (ref: src/garage/secrets.rs
    fill_secrets — same precedence, CLI flags excepted). An env value
    OVERRIDES config-file sources (that is the point of the layering —
    rotation without editing the TOML); only the two env forms
    conflicting is an error."""
    for attr, env in (("rpc_secret", "GARAGE_RPC_SECRET"),
                      ("admin_token", "GARAGE_ADMIN_TOKEN"),
                      ("metrics_token", "GARAGE_METRICS_TOKEN")):
        file_attr = f"{attr}_file"
        env_val = os.environ.get(env)
        env_file = os.environ.get(f"{env}_FILE")
        if env_val and env_file:
            raise ValueError(f"both {env} and {env}_FILE are set; "
                             "pick one")
        if env_val:
            setattr(cfg, attr, env_val)
            continue
        if env_file:
            setattr(cfg, attr, _read_secret_file(env_file))
            continue
        cfg_file = getattr(cfg, file_attr, None)
        if cfg_file:
            if getattr(cfg, attr, None):
                raise ValueError(
                    f"both {attr} and {file_attr} are set in the "
                    "config; pick one")
            setattr(cfg, attr, _read_secret_file(cfg_file))
