"""Versioned on-disk encoding with forward migration.

Ref parity: src/util/migrate.rs:5-157. Values are encoded as msgpack with a
leading version marker. Decoding tries the current version first, then walks
back through `PREVIOUS` classes, decoding with the old schema and applying
`migrate()` forward — so any historical on-disk state loads after upgrades.

A Migratable class defines:
    VERSION_MARKER: bytes     # e.g. b"G010obj"
    PREVIOUS: type | None     # older Migratable class, or None
    def pack(self) -> object                  # msgpack-able plain structure
    @classmethod def unpack(cls, raw) -> cls
    def migrate(self) -> "next version instance"   # only on non-latest
"""

from __future__ import annotations

from typing import Optional, Type, TypeVar

import msgpack

M = TypeVar("M", bound="Migratable")


class Migratable:
    VERSION_MARKER: bytes = b""
    PREVIOUS: Optional[Type["Migratable"]] = None

    def pack(self):
        raise NotImplementedError

    @classmethod
    def unpack(cls, raw):
        raise NotImplementedError

    def migrate(self) -> "Migratable":
        raise NotImplementedError("not an old version")


def encode(value: Migratable) -> bytes:
    assert value.VERSION_MARKER, "VERSION_MARKER required"
    return value.VERSION_MARKER + msgpack.packb(value.pack(), use_bin_type=True)


def decode(cls: Type[M], data: bytes) -> M:
    """Decode `data` as `cls`, falling back through the PREVIOUS chain and
    migrating forward. ref: src/util/migrate.rs:19-55."""
    chain = []
    c: Optional[Type[Migratable]] = cls
    while c is not None:
        chain.append(c)
        c = c.PREVIOUS
    for depth, c in enumerate(chain):
        marker = c.VERSION_MARKER
        if data.startswith(marker):
            raw = msgpack.unpackb(data[len(marker):], raw=False)
            val = c.unpack(raw)
            for _ in range(depth):
                val = val.migrate()
            return val  # type: ignore[return-value]
    raise ValueError(
        f"cannot decode {cls.__name__}: no version marker matches "
        f"(head={data[:16]!r})"
    )
