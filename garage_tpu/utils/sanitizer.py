"""Runtime asyncio sanitizer (ISSUE 14): the dynamic half of the
GL10/GL12 static story, in the spirit of ThreadSanitizer's
static/dynamic pairing — every static claim about the event loop is
checked against the LIVE loop when `GARAGE_SANITIZE=1`.

Three checks, all report-don't-crash (a monitor must never alter the
behavior it observes; tests assert on the drained reports instead):

  * **loop-stall detector** — a heartbeat callback re-arms itself on
    every registered event loop; an own monitor THREAD samples the
    beats and, when one goes silent past the threshold, captures the
    loop thread's live stack via `sys._current_frames()` and reports
    the frames actually pinning the loop. Sharper than asyncio debug
    mode's slow-callback log: that one reports AFTER the callback
    returns, this one names the frame WHILE it blocks (a hang is
    reported before it resolves, not after).
  * **leak checks at loop teardown** — hooked into
    `asyncio.runners._cancel_all_tasks` (the `asyncio.run` exit path):
    before the runner cancels stragglers, any pending task that is not
    a deliberate background task (`utils.background.spawn` /
    `BackgroundRunner` mark theirs) is reported as leaked; after the
    cancellation settles, any asyncio.Lock still held by a task of
    this loop is reported (a lock that survives its holder serializes
    the next run forever).
  * **budget conservation** — objects exposing `conservation_ok`
    (BudgetLeaseBroker; qos TokenBucket via its clamp invariant)
    register themselves when armed and are re-checked at every loop
    teardown: a leaked lease/token is invisible until the budget runs
    dry, so the soak asserts Σ granted ≤ budget after every test.

Wired into tests/conftest.py: an autouse fixture drains reports after
each test and fails THAT test, so tier-1 and the nightly soak run
sanitized (CI exports GARAGE_SANITIZE=1). The stall threshold is
`GARAGE_SANITIZE_STALL_S` (default 1.0 s — calibrated on the 2-core CI
box where tier-1 runs clean; the seeded self-test uses 0.25 s).
"""

from __future__ import annotations

import asyncio
import os
import sys
import threading
import time
import traceback
import weakref
from typing import Optional

ENV_FLAG = "GARAGE_SANITIZE"
ENV_THRESHOLD = "GARAGE_SANITIZE_STALL_S"
DEFAULT_STALL_S = 1.0
# sample-cadence floor (ISSUE 15 satellite): the stall sampler used to
# run at threshold/5 only, so at the 1 s default the monitor woke every
# 200 ms and a sub-200 ms-threshold configuration could sandwich a
# whole stall between two samples. The period is now capped at 20 ms —
# we sample at LEAST every 20 ms — and the heartbeat itself reports a
# stall RETROACTIVELY when it fires late (see _beat), so a stall past
# the threshold is caught even when it resolves between monitor samples.
STALL_SAMPLE_FLOOR_S = 0.02

# attribute marking a task as deliberately detached/supervised
BACKGROUND_ATTR = "_garage_background"

_lock = threading.Lock()
_reports: list[dict] = []
_installed = False
# patches are irreversible, but REPORTING can be switched off: the
# self-tests install in unarmed pytest sessions and deactivate on the
# way out so later tests don't accumulate reports nobody drains
_active = False
_stall_threshold = DEFAULT_STALL_S

# live loops: id(loop) -> [thread_id, last_beat, reported, beat_token]
_loops: dict[int, list] = {}
_beat_seq = 0
_monitor: Optional[threading.Thread] = None
# held asyncio.Locks: id(lock) -> (loop_id, task_name, since)
_held_locks: dict[int, tuple] = {}
# objects with a `conservation_ok` property (weakrefs)
_conserved: list = []


def armed() -> bool:
    return os.environ.get(ENV_FLAG, "") == "1"


def stall_threshold() -> float:
    return _stall_threshold


def configure(stall_threshold_s: Optional[float] = None) -> None:
    global _stall_threshold
    if stall_threshold_s is not None:
        _stall_threshold = float(stall_threshold_s)


# ---- reporting ----------------------------------------------------------

def set_active(flag: bool) -> None:
    global _active
    _active = bool(flag)


def report(kind: str, detail: str) -> None:
    if not _active:
        return
    entry = {"kind": kind, "detail": detail, "time": time.time()}
    with _lock:
        _reports.append(entry)
    # stderr line so forked processes and the soak's log artifacts
    # carry the report even when no in-process assert sees it
    print(f"[GARAGE_SANITIZE] {kind}: {detail}", file=sys.stderr)


def reports() -> list[dict]:
    with _lock:
        return list(_reports)


def drain_reports() -> list[dict]:
    with _lock:
        out = list(_reports)
        _reports.clear()
    return out


# ---- conservation tracking ----------------------------------------------

def track_conservation(obj) -> None:
    """Register an object exposing `conservation_ok`; checked at every
    loop teardown while the object is alive. No-op when disarmed.
    Dead refs are pruned here too — long-lived armed processes churn
    per-key TokenBuckets, and teardown (the other pruning site) may
    not run until process exit."""
    if not armed():
        return
    with _lock:
        _conserved[:] = [r for r in _conserved if r() is not None]
        _conserved.append(weakref.ref(obj))


def _check_conservation() -> None:
    with _lock:
        refs = list(_conserved)
    live = []
    for r in refs:
        obj = r()
        if obj is None:
            continue
        live.append(r)
        try:
            ok = obj.conservation_ok
        except Exception:  # lint: ignore[GL05] a broken invariant property must not crash the monitor; the object is simply skipped
            continue
        if not ok:
            report("budget_conservation",
                   f"{type(obj).__name__} violates its conservation "
                   f"invariant at loop teardown: {obj!r}")
    with _lock:
        _conserved[:] = live


# ---- stall detector ------------------------------------------------------

def _sample_period() -> float:
    """Sampling/heartbeat period: threshold/5, floored at 10 ms and
    capped at STALL_SAMPLE_FLOOR_S (a minimum cadence — sub-200 ms
    thresholds stay observable)."""
    return max(0.01, min(_stall_threshold / 5.0, STALL_SAMPLE_FLOOR_S))


def _beat(loop, token: int) -> None:
    ent = _loops.get(id(loop))
    if ent is None or ent[3] != token or loop.is_closed():
        # stale chain: this loop re-entered run_forever (new token) or
        # stopped — without the token check every run_until_complete
        # on a persistent loop would add one more self-re-arming chain
        return
    now = time.monotonic()
    dt = now - ent[1]
    if dt > _stall_threshold and not ent[2]:
        # the beat itself arrived late past the threshold: the stall
        # already RESOLVED (we are running again), so the live stack is
        # gone, but the episode must still be reported — the monitor
        # thread can sandwich a short stall between two samples, this
        # check cannot
        report("loop_stall",
               f"event loop was silent for {dt:.2f}s (threshold "
               f"{_stall_threshold:.2f}s); stall resolved before a "
               "live stack could be captured")
    ent[1] = now
    ent[2] = False  # beat recovered: re-arm one report per episode
    try:
        loop.call_later(_sample_period(), _beat, loop, token)
    except RuntimeError:
        pass  # loop closing under us


def _loop_stack(thread_id: int) -> str:
    frame = sys._current_frames().get(thread_id)
    if frame is None:
        return "<no frame>"
    return "".join(traceback.format_stack(frame, limit=12))


def _monitor_main() -> None:
    while True:
        time.sleep(_sample_period())
        now = time.monotonic()
        for ent in list(_loops.values()):
            tid, last, reported = ent[0], ent[1], ent[2]
            dt = now - last
            if dt > _stall_threshold and not reported:
                ent[2] = True
                report(
                    "loop_stall",
                    f"event loop silent for {dt:.2f}s "
                    f"(threshold {_stall_threshold:.2f}s); loop-thread "
                    f"stack:\n{_loop_stack(tid)}")


def _ensure_monitor() -> None:
    global _monitor
    if _monitor is None or not _monitor.is_alive():
        _monitor = threading.Thread(target=_monitor_main,
                                    name="garage-sanitizer",
                                    daemon=True)
        _monitor.start()


# ---- teardown checks -----------------------------------------------------

def _pending_leaks(loop) -> list[str]:
    out = []
    try:
        tasks = asyncio.all_tasks(loop)
    except RuntimeError:
        return out
    for t in tasks:
        if t.done() or getattr(t, BACKGROUND_ATTR, False):
            continue
        coro = t.get_coro()
        where = ""
        frame = getattr(coro, "cr_frame", None)
        if frame is not None:
            where = (f" at {frame.f_code.co_filename}:"
                     f"{frame.f_lineno}")
        out.append(f"{t.get_name()} ({coro!r}{where})")
    return out


def _held_locks_of(loop) -> list[str]:
    """Report AND purge this loop's held-lock entries: the loop is
    closing, so a leaked lock can never be released — leaving the
    entry would re-attribute it to a future loop allocated at the
    same address (id() reuse) and fail an innocent test."""
    with _lock:
        mine = {k: v for k, v in _held_locks.items()
                if v[0] == id(loop)}
        for k in mine:
            del _held_locks[k]
    return [f"Lock held by task {name!r} for {time.monotonic() - t0:.1f}s"
            for _lid, name, t0 in mine.values()]


def _check_teardown(loop) -> None:
    for leak in _pending_leaks(loop):
        report("task_leak",
               f"pending non-background task at loop teardown: {leak}")


def _check_post_cancel(loop) -> None:
    for h in _held_locks_of(loop):
        report("lock_leak", f"asyncio.Lock still held at loop close: {h}")
    _check_conservation()


# ---- installation --------------------------------------------------------

def install(stall_threshold_s: Optional[float] = None) -> None:
    """Idempotent. Patches the asyncio seams the sanitizer observes;
    safe to call at import time from conftest when armed."""
    global _installed
    configure(stall_threshold_s if stall_threshold_s is not None
              else float(os.environ.get(ENV_THRESHOLD, DEFAULT_STALL_S)))
    set_active(True)
    if _installed:
        return
    _installed = True

    # (1) heartbeat on every loop that runs
    base = asyncio.base_events.BaseEventLoop
    orig_run_forever = base.run_forever

    def run_forever(self):
        global _beat_seq
        _beat_seq += 1
        token = _beat_seq
        _loops[id(self)] = [threading.get_ident(), time.monotonic(),
                            False, token]
        _ensure_monitor()
        self.call_soon(_beat, self, token)
        try:
            return orig_run_forever(self)
        finally:
            _loops.pop(id(self), None)

    base.run_forever = run_forever

    # background-ness is INHERITED: a task created from inside a
    # supervised background task (gather fan-outs in service loops,
    # helpers they spawn) is itself supervised by the same chain — a
    # teardown that catches such a wave mid-flight is not a leak
    orig_create_task = base.create_task

    def create_task(self, coro, **kw):
        t = orig_create_task(self, coro, **kw)
        try:
            parent = asyncio.current_task()
        except RuntimeError:
            parent = None
        if parent is not None and getattr(parent, BACKGROUND_ATTR,
                                          False):
            setattr(t, BACKGROUND_ATTR, True)
        return t

    base.create_task = create_task

    # (2) teardown checks on the asyncio.run exit path
    runners = asyncio.runners
    orig_cancel_all = runners._cancel_all_tasks

    def _cancel_all_tasks(loop):
        _check_teardown(loop)
        try:
            return orig_cancel_all(loop)
        finally:
            _check_post_cancel(loop)

    runners._cancel_all_tasks = _cancel_all_tasks

    # (3) asyncio.Lock hold tracking
    orig_acquire = asyncio.Lock.acquire
    orig_release = asyncio.Lock.release

    async def acquire(self):
        r = await orig_acquire(self)
        task = asyncio.current_task()
        name = task.get_name() if task is not None else "?"
        try:
            loop_id = id(asyncio.get_running_loop())
        except RuntimeError:
            loop_id = 0
        with _lock:
            _held_locks[id(self)] = (loop_id, name, time.monotonic())
        return r

    def release(self):
        orig_release(self)
        with _lock:
            _held_locks.pop(id(self), None)

    asyncio.Lock.acquire = acquire
    asyncio.Lock.release = release
