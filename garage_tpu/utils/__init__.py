"""Foundation utilities (ref: src/util)."""
