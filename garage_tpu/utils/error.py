"""Unified error types. Ref parity: src/util/error.rs."""

from __future__ import annotations


class GarageError(Exception):
    """Base error."""


class TimeoutError_(GarageError):
    pass


class QuorumError(GarageError):
    """Could not reach quorum. ref: util/error.rs Error::Quorum(q, sets, ok, total, errs)."""

    def __init__(self, quorum: int, sets: int | None, ok: int, total: int, errors: list):
        self.quorum, self.sets, self.ok, self.total, self.errors = quorum, sets, ok, total, errors
        where = f" in {sets} sets" if sets is not None else ""
        super().__init__(
            f"could not reach quorum {quorum}{where}: {ok}/{total} ok; "
            f"errors: {[str(e) for e in errors[:4]]}"
        )


class ZoneSpanError(QuorumError):
    """A write quorum set cannot span the required number of zones
    (ISSUE 16 zone-aware quorums). Subclasses QuorumError so callers
    that already treat quorum failures as retryable/unavailable degrade
    gracefully; the distinct type lets operators tell "placement can't
    satisfy zone_redundancy" apart from "nodes were down"."""

    def __init__(self, required: int, got: int, zones: list[str], total: int):
        self.required_zones, self.got_zones, self.zone_list = required, got, zones
        super(QuorumError, self).__init__(
            f"write set spans {got} zone(s) {zones} < required zone span "
            f"{required} across {total} node(s)"
        )
        # QuorumError field shape, for handlers that introspect it
        self.quorum, self.sets, self.ok, self.total, self.errors = (
            required, None, got, total, [])


class CorruptData(GarageError):
    def __init__(self, hash_: bytes):
        self.hash = hash_
        super().__init__(f"corrupt data for block {hash_.hex()[:16]}")


class MissingBlock(GarageError):
    def __init__(self, hash_: bytes):
        self.hash = hash_
        super().__init__(f"missing block {hash_.hex()[:16]}")


class RpcError(GarageError):
    """An error returned by a remote node."""


class NoSuchBucket(GarageError):
    pass


class NoSuchKey(GarageError):
    pass


class BadRequest(GarageError):
    pass
