"""Background worker runtime (asyncio).

Ref parity: src/util/background/ — BackgroundRunner (mod.rs:16-75), Worker
loop with Busy/Idle/Throttled/Done states and exponential error backoff
(worker.rs:19-232), BgVars runtime-tunable variables (vars.rs).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger("garage.background")

# fire-and-forget tasks retained here until done: asyncio keeps only a
# weak reference to running tasks, so an un-retained task can be
# garbage-collected mid-flight and its exception is never observed
# (GL04 orphan-task — the static rule and this helper are two halves
# of the same invariant)
_detached: set[asyncio.Task] = set()


def spawn(coro, name: str = "") -> asyncio.Task:
    """Deliberately-detached task with lifecycle hygiene: retained
    until done, exception observed and logged instead of surfacing as
    'Task exception was never retrieved' at interpreter exit."""
    t = asyncio.ensure_future(coro)
    if name:
        try:
            t.set_name(name)
        except AttributeError:
            pass
    # deliberate background work: the runtime sanitizer's task-leak
    # check at loop teardown skips marked tasks (utils/sanitizer.py)
    t._garage_background = True
    _detached.add(t)
    t.add_done_callback(_spawn_done)
    return t


def _spawn_done(t: asyncio.Task) -> None:
    _detached.discard(t)
    if t.cancelled():
        return
    e = t.exception()
    if e is not None:
        # warning, not debug: before spawn() existed these surfaced as
        # asyncio's ERROR-level "Task exception was never retrieved",
        # and a detached task dying is never expected (expected
        # failures are caught inside the task)
        logger.warning("detached task %s failed: %s",
                       t.get_name(), e, exc_info=e)


class WState(Enum):
    BUSY = "busy"
    IDLE = "idle"
    DONE = "done"


@dataclass
class Throttled:
    delay: float


WorkerState = Any  # WState | Throttled


@dataclass
class WorkerInfo:
    name: str
    state: str = "idle"
    errors: int = 0
    consecutive_errors: int = 0
    last_error: Optional[str] = None
    last_error_time: Optional[float] = None
    tranquility: Optional[int] = None
    progress: Optional[str] = None
    queue_length: Optional[int] = None
    persistent_errors: Optional[int] = None


class Worker:
    """Subclass and implement work(); optionally wait_for_work().

    work() returns WState.BUSY (more work immediately), WState.IDLE (call
    wait_for_work), Throttled(delay), or WState.DONE (exit loop).
    ref: src/util/background/worker.rs:41-59.
    """

    name: str = "worker"

    def info(self) -> WorkerInfo:
        return WorkerInfo(name=self.name)

    async def work(self) -> WorkerState:
        return WState.DONE

    async def wait_for_work(self) -> None:
        await asyncio.sleep(10)


class BackgroundRunner:
    """Spawns workers as asyncio tasks; tracks status; graceful shutdown with
    an 8 s deadline. ref: src/util/background/mod.rs:42-75, worker.rs:189-232.
    """

    EXIT_DEADLINE = 8.0

    def __init__(self):
        self._tasks: Dict[str, asyncio.Task] = {}
        self._workers: Dict[str, Worker] = {}
        self._infos: Dict[str, WorkerInfo] = {}
        self._stopping = asyncio.Event()
        self._seq = 0

    def spawn_worker(self, worker: Worker) -> None:
        self._seq += 1
        wid = f"{self._seq}:{worker.name}"
        self._workers[wid] = worker
        self._infos[wid] = worker.info()
        t = asyncio.create_task(self._run_worker(wid, worker), name=wid)
        # supervised by shutdown(); not a leak at loop teardown
        t._garage_background = True
        self._tasks[wid] = t

    def worker_info(self) -> Dict[str, WorkerInfo]:
        for wid, w in self._workers.items():
            base = w.info()
            prev = self._infos.get(wid)
            if prev:
                base.errors = prev.errors
                base.consecutive_errors = prev.consecutive_errors
                base.last_error = prev.last_error
                base.last_error_time = prev.last_error_time
                base.state = prev.state
            self._infos[wid] = base
        return dict(self._infos)

    async def _run_worker(self, wid: str, worker: Worker) -> None:
        info = self._infos[wid]
        while not self._stopping.is_set():
            try:
                info.state = "busy"
                state = await worker.work()
                info.consecutive_errors = 0
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — worker errors backoff+retry
                info.errors += 1
                info.consecutive_errors += 1
                info.last_error = f"{type(e).__name__}: {e}"
                info.last_error_time = time.time()
                logger.warning("worker %s error: %s", wid, e, exc_info=True)
                # exponential backoff 1s → ~60s, ref worker.rs:206-215
                delay = min(60.0, 1.0 * (2 ** min(info.consecutive_errors - 1, 6)))
                state = Throttled(delay)
            if state is WState.DONE:
                break
            if isinstance(state, Throttled):
                info.state = "throttled"
                try:
                    await asyncio.wait_for(self._stopping.wait(), state.delay)
                    break
                except asyncio.TimeoutError:
                    continue
            if state is WState.IDLE:
                info.state = "idle"
                wait = asyncio.create_task(worker.wait_for_work())
                stop = asyncio.create_task(self._stopping.wait())
                done, pending = await asyncio.wait(
                    {wait, stop}, return_when=asyncio.FIRST_COMPLETED
                )
                for p in pending:
                    p.cancel()
                if stop in done:
                    break
        info.state = "done"

    async def shutdown(self) -> None:
        self._stopping.set()
        if not self._tasks:
            return
        _, pending = await asyncio.wait(
            set(self._tasks.values()), timeout=self.EXIT_DEADLINE
        )
        for p in pending:
            logger.warning("worker %s did not exit in time; cancelling", p.get_name())
            p.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)


class BgVars:
    """Named runtime-tunable variables exposed via CLI `worker get/set`.
    ref: src/util/background/vars.rs."""

    def __init__(self):
        self._vars: Dict[str, tuple[Callable[[], str], Callable[[str], None]]] = {}

    def register_rw(self, name: str, getter: Callable[[], Any],
                    setter: Callable[[str], None]) -> None:
        self._vars[name] = (lambda: str(getter()), setter)

    def get(self, name: str) -> str:
        return self._vars[name][0]()

    def set(self, name: str, value: str) -> None:
        self._vars[name][1](value)

    def all(self) -> Dict[str, str]:
        return {k: g() for k, (g, _) in self._vars.items()}
