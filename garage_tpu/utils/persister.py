"""Atomic save/load of a Migratable value to a file (tmp+rename).

Ref parity: src/util/persister.rs:10-120 (Persister, PersisterShared).
"""

from __future__ import annotations

import os
import threading
from typing import Generic, Optional, Type, TypeVar

from . import migrate

M = TypeVar("M", bound=migrate.Migratable)


class Persister(Generic[M]):
    def __init__(self, directory: str, name: str, cls: Type[M]):
        self.path = os.path.join(directory, name)
        self.cls = cls

    def load(self) -> Optional[M]:
        try:
            with open(self.path, "rb") as f:
                return migrate.decode(self.cls, f.read())
        except FileNotFoundError:
            return None

    def save(self, value: M) -> None:
        tmp = self.path + ".tmp"
        data = migrate.encode(value)
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)


class PersisterShared(Generic[M]):
    """Persister + in-memory cached value behind a lock.
    ref: src/util/persister.rs:89."""

    def __init__(self, directory: str, name: str, cls: Type[M], default: M):
        self._p = Persister(directory, name, cls)
        self._lock = threading.Lock()
        loaded = self._p.load()
        self._value = loaded if loaded is not None else default
        if loaded is None:
            self._p.save(self._value)

    def get(self) -> M:
        with self._lock:
            return self._value

    def set(self, value: M) -> None:
        with self._lock:
            self._value = value
            self._p.save(value)

    def update(self, fn) -> M:
        with self._lock:
            self._value = fn(self._value)
            self._p.save(self._value)
            return self._value
