"""Process-level runtime tuning for data-plane processes.

The CPython default GIL switch interval is 5 ms. On the block data path
the event loop and the native-kernel worker threads trade the GIL
thousands of times per second; at 5 ms a thread that finishes a
GIL-released C call can wait out most of a switch interval before the
loop runs again. Measured on the r4 loopback PUT bench this single
setting was worth >2x end-to-end throughput (0.115 -> 0.251 GB/s).

Called from server startup (cli/server.py) and bench entry points; not
from library import (a library must not mutate interpreter-global state
on import).
"""

from __future__ import annotations

import sys

SWITCH_INTERVAL = 0.0002


def tune() -> None:
    sys.setswitchinterval(SWITCH_INTERVAL)
